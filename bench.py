"""Flagship benchmark: train-step token throughput per chip.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Baseline anchor (BASELINE.md): the reference's Llama-3-8B torch-XLA FSDP
recipe reaches 0.476 samples/s at seq 8192 on a tpu-v6e-8, i.e.
0.476 * 8192 / 8 = 487.4 train tokens/s/chip. We measure our JAX trainer's
tokens/s on one chip (model size auto-scaled to fit a single chip's HBM) and
report vs_baseline = ours / 487.4. Extra context (model, MFU, hardware) goes
to stderr so stdout stays a single JSON line.
"""
from __future__ import annotations

import json
import os
import sys
import time

BASELINE_TOK_PER_S_PER_CHIP = 0.476 * 8192 / 8  # 487.4


def main():
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models.llama import PRESETS, LlamaModel
    from skypilot_tpu.train import Trainer

    backend = jax.default_backend()
    on_tpu = backend in ('tpu', 'axon')
    if on_tpu:
        preset, batch, seq, steps = 'llama-1b', 4, 2048, 8
    else:  # CPU fallback so the bench always emits a record
        preset, batch, seq, steps = 'test-tiny', 4, 256, 4

    config = PRESETS[preset]
    n_chips = jax.device_count()
    mesh = None
    if n_chips > 1:
        # Use every local chip (FSDP over all); batch scales with chips so
        # per-chip work is constant and the per-chip division is honest.
        from skypilot_tpu.parallel import MeshSpec, make_mesh
        mesh = make_mesh(MeshSpec(fsdp=n_chips))
        batch *= n_chips
    model = LlamaModel(config, mesh=mesh)
    trainer = Trainer(model)
    print(f'bench: backend={backend} preset={preset} chips={n_chips} '
          f'params={config.num_params/1e9:.2f}B batch={batch} seq={seq}',
          file=sys.stderr)

    state = trainer.init_fn()(jax.random.key(0))
    jax.block_until_ready(state.params)
    step = trainer.step_fn()
    tokens = jax.random.randint(jax.random.key(1), (batch, seq), 0,
                                config.vocab_size)
    batch_data = trainer.shard_batch(
        {'tokens': tokens, 'targets': jnp.roll(tokens, -1, axis=1)})

    # Warmup (compile) then timed steps. The loss is fetched to host each
    # step: on the tunneled dev backend block_until_ready alone does not
    # guarantee the remote step ran, and one scalar D2H per step is noise
    # relative to a 0.1s+ train step.
    for _ in range(2):
        state, metrics = step(state, batch_data)
    float(metrics['loss'])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, metrics = step(state, batch_data)
        last_loss = float(metrics['loss'])
    dt = time.perf_counter() - t0

    tok_per_s_per_chip = batch * seq * steps / dt / n_chips
    model_tflops = 6 * config.num_params * batch * seq / 1e12
    tflops_per_s = model_tflops * steps / dt / n_chips
    print(f'bench: {tok_per_s_per_chip:,.0f} tok/s/chip, '
          f'~{tflops_per_s:.1f} model TFLOP/s/chip, '
          f'loss={last_loss:.3f}', file=sys.stderr)

    print(json.dumps({
        'metric': 'train_tokens_per_sec_per_chip',
        'value': round(tok_per_s_per_chip, 2),
        'unit': 'tokens/s/chip',
        'vs_baseline': round(tok_per_s_per_chip / BASELINE_TOK_PER_S_PER_CHIP,
                             3),
    }))


if __name__ == '__main__':
    main()
