"""Flagship benchmark: train-step token throughput per chip, with MFU.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", ...extras}.

Baseline anchor (BASELINE.md): the reference's Llama-3-8B torch-XLA FSDP
recipe reaches 0.476 samples/s at seq 8192 on a tpu-v6e-8 host =
0.476 * 8192 / 8 = 487.4 train tokens/s/chip AT 8B.

Honest comparison (VERDICT r1): tokens/s scales ~1/params at fixed
hardware FLOP/s, so raw tokens/s of a smaller model must not be compared
to the 8B anchor. This bench runs the largest config that fits the chip's
HBM (8B needs ~80GB of train state; a v5e chip has 16GB), reports the
measured tokens/s for THAT model as "value", and computes
``vs_baseline`` from the **8B-equivalent** rate:
    tok8b = tok/s * N_params / 8.03e9
Extra context (model size, MFU vs the detected chip's bf16 peak, hardware)
rides in the same JSON object and on stderr.
"""
from __future__ import annotations

import json
import sys
import time

BASELINE_8B_TOK_PER_S_PER_CHIP = 0.476 * 8192 / 8  # 487.4
LLAMA3_8B_PARAMS = 8.03e9

# bf16 peak TFLOP/s per chip by device kind (public specs).
_PEAKS = {
    'TPU v2': 46, 'TPU v3': 123, 'TPU v4': 275,
    'TPU v5 lite': 197, 'TPU v5e': 197, 'TPU v5p': 459, 'TPU v5': 459,
    'TPU v6 lite': 918, 'TPU v6e': 918,
}


def chip_peak_tflops(device) -> float:
    kind = getattr(device, 'device_kind', '') or ''
    for name, peak in _PEAKS.items():
        if kind.startswith(name):
            return float(peak)
    return 197.0  # conservative default: v5e


def _probe_backend() -> tuple:
    """(jax.default_backend(), device_count) probed in a SUBPROCESS: the
    parent must not initialize jax (and thereby hold the chip) before the
    launched-path phase — its job needs the chip first."""
    import subprocess
    try:
        out = subprocess.run(
            [sys.executable, '-c',
             'import jax; print(jax.default_backend(), '
             'jax.device_count())'],
            capture_output=True, text=True, timeout=300)
        if out.returncode == 0:
            backend, count = out.stdout.strip().splitlines()[-1].split()
            return backend, int(count)
    except (subprocess.TimeoutExpired, OSError, ValueError):
        pass
    return 'cpu', 1


def _workload():
    """One workload definition shared by the launched and in-process
    phases, so their rates are directly comparable."""
    import dataclasses

    from skypilot_tpu.models.llama import PRESETS

    backend, n_devices = _probe_backend()
    on_tpu = backend in ('tpu', 'axon')
    if on_tpu:
        # Largest preset whose ~10N-byte train state + activations fit one
        # chip's HBM (v5e: 16GB). 'names_qkv' remat (selective: keep
        # attention context + SwiGLU product + post-rotary Q/K/V) +
        # Pallas flash fwd/bwd; measured best of {dots, names, names_qkv,
        # names_offload} x {batch 1, 2} at seq 8192 on v5e (names_qkv is
        # +3.2% over names in interleaved A/B; offload loses 33%; the
        # flash kernels run at 41% fwd / 65% bwd of bf16 peak, so the
        # 6N-only MFU gap to the with-attention figure is accounting,
        # not kernel inefficiency).
        preset, batch, seq, steps = 'llama-1b', 1, 8192, 8
        config = dataclasses.replace(PRESETS[preset],
                                     remat_policy='names_qkv')
    else:  # CPU fallback so the bench always emits a record
        preset, batch, seq, steps = 'test-tiny', 4, 256, 4
        config = PRESETS[preset]
    return backend, n_devices, preset, batch, seq, steps, config


def _overhead_breakdown(summary: dict, t_submit: float,
                        prefix: str = '') -> dict:
    """Split submit->first-step into phases from the callback's marks:
    control plane (provision/ship/queue), runtime startup (python+jax/PJRT
    incl. tunnel), param init, first-step compile."""
    marks = summary.get('marks') or {}
    ps = marks.get('proc_start')
    jr = marks.get('jax_ready')
    idn = marks.get('init_done')
    fse = summary.get('first_step_end_ts')
    out = {}
    if ps:
        out[f'{prefix}control_plane_s'] = round(ps - t_submit, 2)
    if ps and jr:
        out[f'{prefix}runtime_startup_s'] = round(jr - ps, 2)
    if jr and idn:
        out[f'{prefix}param_init_s'] = round(idn - jr, 2)
    if idn and fse:
        out[f'{prefix}first_step_s'] = round(fse - idn, 2)
    return out


def run_launched(preset: str, batch: int, seq: int, steps: int,
                 config, n_devices: int = 1) -> dict:
    """Benchmark THROUGH the product's own control plane (VERDICT r2 weak
    #3): `launch` the training task on the local backend (the emulated
    host is this machine, so the job sees the same chip), measure
    submit -> first-step latency and steady-state tok/s via callbacks/.

    Runs BEFORE the in-process phase: the launched job is a separate
    process and the chip can only be held by one at a time.
    """
    import os
    import tempfile
    import time as time_lib

    import skypilot_tpu as sky
    from skypilot_tpu import core, execution
    from skypilot_tpu.callbacks import SUMMARY_FILE
    from skypilot_tpu.runtime import job_lib

    os.environ.setdefault('SKYTPU_STATE_DIR',
                          tempfile.mkdtemp(prefix='skytpu-bench-state-'))
    remat = getattr(config, 'remat_policy', 'full')
    # Global batch scales with chips (train.run shards over fsdp=auto),
    # mirroring the in-process phase's scaling so the per-chip rates are
    # directly comparable.
    global_batch = batch * n_devices

    from skypilot_tpu import exceptions as skytpu_exceptions

    def one_launch(fast: bool) -> tuple:
        """Launch the training task; returns (status, summary|None,
        t_submit)."""
        log_dir = tempfile.mkdtemp(prefix='skytpu-bench-cb-')
        task = sky.Task(
            run=(f'python3 -m skypilot_tpu.train.run --preset {preset} '
                 f'--batch {global_batch} --seq {seq} --steps {steps + 2} '
                 f'--remat {remat} --log-every {steps + 2}'),
            envs={'SKYTPU_BENCHMARK_LOG_DIR': log_dir})
        task.set_resources([sky.Resources(cloud='local')])
        t_submit = time_lib.time()
        job_id, _ = execution.launch(task, cluster_name='bench-launched',
                                     detach_run=True, stream_logs=False,
                                     fast=fast)
        # Worst healthy case is ~2 min of compile + seconds of steps; a
        # 15-min ceiling keeps a wedged chip/tunnel from eating the whole
        # bench window (the record then carries the non-terminal status).
        deadline = time_lib.time() + 900
        status = None
        while time_lib.time() < deadline:
            try:
                status = core.job_status('bench-launched', job_id)
            except skytpu_exceptions.SkyTpuError:
                status = None  # transient (agent heartbeat lag): keep going
            if status and job_lib.JobStatus(status).is_terminal():
                break
            time_lib.sleep(1.0)
        try:
            with open(os.path.join(log_dir, SUMMARY_FILE)) as f:
                summary = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            summary = None
        return status, summary, t_submit

    out = {}
    try:
        # Cold: fresh cluster, empty compilation cache.
        status, summary, t_submit = one_launch(fast=False)
        out['launched_job_status'] = status
        if summary is None or not summary.get('first_step_end_ts'):
            out['launched_error'] = 'no benchmark summary from cold launch'
            return out
        out['launch_overhead_s'] = round(
            summary['first_step_end_ts'] - t_submit, 2)
        out.update(_overhead_breakdown(summary, t_submit))
        if summary.get('seconds_per_step'):
            tok = (global_batch * seq / summary['seconds_per_step']
                   / n_devices)
            out['launched_tokens_per_sec_per_chip'] = round(tok, 2)
        # Warm: same cluster, --fast (skip setup/mounts), persistent XLA
        # compilation cache already populated by the cold run.
        status_w, summary_w, t_submit_w = one_launch(fast=True)
        out['warm_launched_job_status'] = status_w
        if summary_w and summary_w.get('first_step_end_ts'):
            out['warm_launch_overhead_s'] = round(
                summary_w['first_step_end_ts'] - t_submit_w, 2)
            out.update(_overhead_breakdown(summary_w, t_submit_w,
                                           prefix='warm_'))
    except Exception as e:  # noqa: BLE001 — phases below must survive
        out['launched_error'] = f'{type(e).__name__}: {e}'
    finally:
        try:
            core.down('bench-launched')
        except Exception:  # noqa: BLE001 — bench must not die on cleanup
            pass
    return out


def run_decode(config, params) -> dict:
    """Serving-side numbers from the in-tree continuous-batching engine
    (BASELINE.md serving anchors are Llama-2-7B on EIGHT v6e chips — not
    reproducible on one v5e — so these ride as context, not vs_baseline):
    steady-state decode tok/s with full slots, and prefill TTFT.
    """
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models.decode import DecodeEngine, prefill_bucket

    slots, max_len, prompt_len = 16, 1024, 128
    engine = DecodeEngine(config, batch_slots=slots, max_len=max_len)
    state = engine.init_state()
    prompt = jax.random.randint(jax.random.key(7), (prompt_len,), 0,
                                config.vocab_size)
    bucket = prefill_bucket(prompt_len, engine.max_len)
    padded = jnp.pad(prompt, (0, bucket - prompt_len))
    k, v, logits = engine.prefill(params, padded, prompt_len)
    first = int(jnp.argmax(logits))  # compile + sync
    ttfts = []
    for _ in range(3):
        t0 = time.perf_counter()
        k, v, logits = engine.prefill(params, padded, prompt_len)
        first = int(jnp.argmax(logits))
        ttfts.append(time.perf_counter() - t0)
    for s in range(slots):
        state = engine.insert(state, k, v, prompt_len, first, s)
    rng = jax.random.key(11)
    for i in range(4):  # warmup (compile)
        state, sampled, rng = engine.step(params, state, rng)
    int(sampled[0])
    n = 64
    t0 = time.perf_counter()
    for i in range(n):
        state, sampled, rng = engine.step(params, state, rng)
    int(sampled[0])  # sync
    dt = time.perf_counter() - t0
    return {
        'decode_tokens_per_sec_per_chip': round(slots * n / dt, 1),
        'decode_batch_slots': slots,
        'decode_ttft_ms': round(sorted(ttfts)[1] * 1e3, 1),
        'decode_prompt_len': prompt_len,
    }


def run_serve(on_tpu: bool) -> dict:
    """Serve-path phase (BASELINE north-star: SkyServe req/s + TTFT +
    TPOT): full serve stack on the local cloud — controller + LB +
    generation replica subprocess (which owns the chip) — driven with the
    anchor workload shape (~2500 input / ~150 output tokens). Runs before
    the in-process phase for the same chip-ownership reason as
    run_launched."""
    from skypilot_tpu.benchmark import serve_bench
    if on_tpu:
        return serve_bench.run(
            preset='llama-1b', batch_slots=32, max_len=4096,
            prompt_len=2500, output_len=150, concurrencies=(24, 48),
            window_s=75.0, warmup_requests=2)
    return serve_bench.run(
        preset='test-tiny', batch_slots=2, max_len=128, prompt_len=24,
        output_len=8, concurrencies=(2,), window_s=6.0,
        warmup_requests=1, ready_timeout_s=240)


def main():
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models.llama import LlamaModel
    from skypilot_tpu.train import Trainer

    backend, n_devices, preset, batch, seq, steps, config = _workload()

    # Phase 1: through the control plane (separate process; runs first so
    # the chip is free for the in-process phase afterwards).
    try:
        launched = run_launched(preset, batch, seq, steps, config,
                                n_devices=n_devices)
    except Exception as e:  # noqa: BLE001 — the in-process number must
        launched = {'launched_error': f'{type(e).__name__}: {e}'}  # survive
    print(f'bench launched-path: {launched}', file=sys.stderr)

    # Phase 1.5: serve path (LB -> replica), also subprocess-based.
    try:
        serve = run_serve(on_tpu=backend in ('tpu', 'axon'))
    except Exception as e:  # noqa: BLE001
        serve = {'serve_error': f'{type(e).__name__}: {e}'}
    print(f'bench serve-path: {serve}', file=sys.stderr)

    n_chips = jax.device_count()
    mesh = None
    if n_chips > 1:
        # Use every local chip (FSDP over all); batch scales with chips so
        # per-chip work is constant and the per-chip division is honest.
        from skypilot_tpu.parallel import MeshSpec, make_mesh
        mesh = make_mesh(MeshSpec(fsdp=n_chips))
        batch *= n_chips
    model = LlamaModel(config, mesh=mesh)
    trainer = Trainer(model)
    device = jax.devices()[0]
    peak = chip_peak_tflops(device)
    print(f'bench: backend={backend} device={device.device_kind!r} '
          f'preset={preset} chips={n_chips} '
          f'params={config.num_params/1e9:.2f}B batch={batch} seq={seq} '
          f'remat={config.remat_policy}', file=sys.stderr)

    state = trainer.init_fn()(jax.random.key(0))
    step = trainer.step_fn()
    # Distinct batch per step: the dev tunnel backend memoizes identical
    # executions, which would make repeated-batch timings fictitious.
    batches = []
    for i in range(steps + 2):
        tokens = jax.random.randint(jax.random.key(i), (batch, seq), 0,
                                    config.vocab_size)
        batches.append(trainer.shard_batch(
            {'tokens': tokens, 'targets': jnp.roll(tokens, -1, axis=1)}))

    # Warmup (compile); the scalar fetch is the only reliable sync on the
    # tunneled backend (block_until_ready does not wait there).
    for i in range(2):
        state, metrics = step(state, batches[i])
    float(metrics['loss'])
    t0 = time.perf_counter()
    for i in range(steps):
        state, metrics = step(state, batches[2 + i])
    last_loss = float(metrics['loss'])
    dt = time.perf_counter() - t0

    tok_per_s_per_chip = batch * seq * steps / dt / n_chips
    # MFU with the standard (PaLM appendix B) accounting: 6N plus the
    # causal self-attention matmuls — at seq 8192 attention is real MXU
    # work (~23% of this model's FLOPs), not a rounding term. The pure-6N
    # figure is kept alongside for comparability with 6N-only reports.
    flops_per_token = config.train_flops_per_token(seq)
    tflops_per_s = flops_per_token * tok_per_s_per_chip / 1e12
    mfu = tflops_per_s / peak
    mfu_6n = 6 * config.num_params * tok_per_s_per_chip / 1e12 / peak
    tok8b_equiv = tok_per_s_per_chip * config.num_params / LLAMA3_8B_PARAMS
    vs_baseline = tok8b_equiv / BASELINE_8B_TOK_PER_S_PER_CHIP

    print(f'bench: {tok_per_s_per_chip:,.0f} tok/s/chip @ '
          f'{config.num_params/1e9:.2f}B, {tflops_per_s:.1f} model TFLOP/s '
          f'(MFU {mfu*100:.1f}% of {peak:.0f} peak; '
          f'{mfu_6n*100:.1f}% counting 6N only), '
          f'8B-equivalent {tok8b_equiv:,.0f} tok/s/chip, '
          f'loss={last_loss:.3f}', file=sys.stderr)

    record = {
        'metric': 'train_tokens_per_sec_per_chip',
        'value': round(tok_per_s_per_chip, 2),
        'unit': f'tokens/s/chip @ {config.num_params/1e9:.2f}B seq {seq}',
        'vs_baseline': round(vs_baseline, 3),
        'equivalent_8b_tokens_per_sec_per_chip': round(tok8b_equiv, 2),
        'model_params_b': round(config.num_params / 1e9, 3),
        'mfu_pct': round(mfu * 100, 1),
        'mfu_6n_pct': round(mfu_6n * 100, 1),
        'chip': device.device_kind,
        'seq_len': seq,
    }
    record.update(launched)
    if launched.get('launched_tokens_per_sec_per_chip'):
        record['launched_vs_inprocess'] = round(
            launched['launched_tokens_per_sec_per_chip']
            / tok_per_s_per_chip, 3)
    record.update(serve)
    if serve.get('serve_req_per_s'):
        from skypilot_tpu.benchmark import serve_bench as serve_bench_lib
        record.update(serve_bench_lib.equivalence_estimate(
            serve['serve_req_per_s'],
            model_params=serve['serve_model_params'],
            chip_kind=device.device_kind))
    # Phase 3: serving-side decode throughput (free the optimizer state
    # first — train state + KV cache together would not fit HBM).
    try:
        params = state.params
        del state, step, batches
        decode = run_decode(config, params)
    except Exception as e:  # noqa: BLE001 — context, not the metric
        decode = {'decode_error': f'{type(e).__name__}: {e}'}
    print(f'bench decode: {decode}', file=sys.stderr)
    record.update(decode)
    print(json.dumps(record))


if __name__ == '__main__':
    main()
