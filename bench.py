"""Flagship benchmark: train-step token throughput per chip, with MFU.

Prints the result as a JSON line; later phases re-print the record with
their fields merged in, so the LAST stdout line is always the most
complete record — and an earlier line is still a complete, parseable
record if a later phase is killed. Required fields ride on every line:
{"metric", "value", "unit", "vs_baseline", ...extras}.

Wedge-proofing (VERDICT r4 #1): every phase runs in its OWN subprocess
with a hard wall-clock budget; the orchestrator never initializes JAX.
On budget overrun the phase's process group is SIGKILLed, any orphaned
local-cluster processes (agents, job leaders, serve replicas) are killed
via their pidfiles, the chip is re-probed, and remaining chip phases are
skipped to CPU fallback with an explicit ``chip_wedged: true`` — a
wedged device tunnel can cost one phase's budget, never the record.

Baseline anchor (BASELINE.md): the reference's Llama-3-8B torch-XLA FSDP
recipe reaches 0.476 samples/s at seq 8192 on a tpu-v6e-8 host =
0.476 * 8192 / 8 = 487.4 train tokens/s/chip AT 8B.

Honest comparison (VERDICT r1): tokens/s scales ~1/params at fixed
hardware FLOP/s, so raw tokens/s of a smaller model must not be compared
to the 8B anchor. This bench runs the largest config that fits the chip's
HBM (8B needs ~80GB of train state; a v5e chip has 16GB), reports the
measured tokens/s for THAT model as "value", and computes
``vs_baseline`` from the **8B-equivalent** rate:
    tok8b = tok/s * N_params / 8.03e9
Extra context (model size, MFU vs the detected chip's bf16 peak, hardware)
rides in the same JSON object and on stderr.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import subprocess
import sys
import tempfile
import time

BASELINE_8B_TOK_PER_S_PER_CHIP = 0.476 * 8192 / 8  # 487.4
LLAMA3_8B_PARAMS = 8.03e9

# bf16 peak TFLOP/s per chip by device kind (public specs).
_PEAKS = {
    'TPU v2': 46, 'TPU v3': 123, 'TPU v4': 275,
    'TPU v5 lite': 197, 'TPU v5e': 197, 'TPU v5p': 459, 'TPU v5': 459,
    'TPU v6 lite': 918, 'TPU v6e': 918,
}

# Test seams: scale every phase budget, or pin one phase's budget in
# seconds (SKYTPU_BENCH_BUDGET_TRAIN=8), so a wedged phase times out in
# seconds, not minutes.
_SCALE = float(os.environ.get('SKYTPU_BENCH_TIMEOUT_SCALE', '1.0'))


def _phase_budget(phase: str, default_s: float) -> float:
    override = os.environ.get(f'SKYTPU_BENCH_BUDGET_{phase.upper()}')
    return float(override) if override else default_s * _SCALE


def chip_peak_tflops_by_kind(kind: str) -> float:
    for name, peak in _PEAKS.items():
        if kind.startswith(name):
            return float(peak)
    return 197.0  # conservative default: v5e


# ---- orchestrator: chip probe + phase subprocesses -------------------------
_PROBE_SRC = (
    "import jax, jax.numpy as jnp\n"
    "x = jax.jit(lambda a: a + 1)(jnp.ones((4,)))\n"
    "d = jax.devices()[0]\n"
    "print('PROBE', jax.default_backend(), jax.device_count(),\n"
    "      getattr(d, 'device_kind', 'unknown').replace(' ', '_'),\n"
    "      float(x.sum()), flush=True)\n")


def _kill_group(proc: subprocess.Popen) -> None:
    try:
        os.killpg(proc.pid, signal.SIGKILL)
    except (ProcessLookupError, PermissionError):
        pass
    try:
        proc.wait(timeout=10)
    except subprocess.TimeoutExpired:
        pass


def probe_chip(timeout: float) -> dict | None:
    """Run a tiny jit in a throwaway subprocess (the wedged tunnel HANGS
    rather than erroring, so this must be killable; and the orchestrator
    must never hold the chip itself)."""
    forced = os.environ.get('SKYTPU_BENCH_FORCE_PROBE')
    if forced:  # test seam: 'backend,count,device kind' or 'none'
        if forced == 'none':
            return None
        try:
            backend, count, kind = forced.split(',', 2)
            return {'backend': backend, 'n_devices': int(count),
                    'device_kind': kind}
        except ValueError:
            # A malformed seam value must not break the every-run-emits-
            # a-record contract; treat it as a failed probe.
            print(f'bench: bad SKYTPU_BENCH_FORCE_PROBE {forced!r} '
                  '(want backend,count,kind) -> treating as wedged',
                  file=sys.stderr)
            return None
    try:
        proc = subprocess.Popen(
            [sys.executable, '-c', _PROBE_SRC],
            stdout=subprocess.PIPE, stderr=subprocess.DEVNULL, text=True,
            start_new_session=True)
        out, _ = proc.communicate(timeout=timeout)
        for line in (out or '').splitlines():
            if line.startswith('PROBE '):
                _, backend, count, kind, _ = line.split()
                return {'backend': backend, 'n_devices': int(count),
                        'device_kind': kind.replace('_', ' ')}
    except subprocess.TimeoutExpired:
        _kill_group(proc)
    except (OSError, ValueError):
        pass
    return None


# True when the orchestrator created SKYTPU_STATE_DIR itself (every
# cluster in it is bench-owned). With a user-provided state dir, cleanup
# only touches bench-prefixed clusters — never a dev's live agents/jobs.
_owns_state_dir = False


def _cleanup_orphans() -> None:
    """Kill agents/job leaders/replicas left by a SIGKILLed phase, via the
    local backend's own pidfile teardown (TERM then KILL on the pgid)."""
    from skypilot_tpu.provision import local_impl
    root = local_impl._clusters_root()
    try:
        names = os.listdir(root)
    except FileNotFoundError:
        return
    for name in names:
        if not _owns_state_dir and not name.startswith('bench-'):
            continue
        try:
            local_impl._kill_host_processes(name)
        except Exception as e:  # noqa: BLE001 — cleanup is best-effort
            print(f'bench: orphan cleanup {name}: {e}', file=sys.stderr)


def run_phase(phase: str, timeout: float, *, force_cpu: bool,
              extra_args: list | None = None) -> dict:
    """Run one bench phase in its own process group with a hard budget."""
    out_path = tempfile.mktemp(prefix=f'skytpu-bench-{phase}-',
                               suffix='.json')
    env = dict(os.environ)
    if force_cpu:
        # Blank (not unset) PALLAS_AXON_POOL_IPS skips the tunnel backend
        # registration entirely; then JAX_PLATFORMS=cpu is honored.
        env['PALLAS_AXON_POOL_IPS'] = ''
        env['JAX_PLATFORMS'] = 'cpu'
    cmd = [sys.executable, os.path.abspath(__file__), '--phase', phase,
           '--out', out_path] + (extra_args or [])
    t0 = time.time()
    proc = subprocess.Popen(cmd, stdout=sys.stderr, stderr=sys.stderr,
                            env=env, start_new_session=True)
    timed_out = False
    try:
        proc.wait(timeout=timeout)
    except subprocess.TimeoutExpired:
        timed_out = True
        _kill_group(proc)
        _cleanup_orphans()
    result: dict = {}
    try:
        # Phases write the record incrementally, so a phase killed mid-way
        # still contributes what it measured before wedging.
        with open(out_path) as f:
            result = json.load(f)
        os.remove(out_path)
    except (FileNotFoundError, json.JSONDecodeError):
        if not timed_out:
            result = {f'{phase}_error':
                      f'phase exited rc={proc.returncode} without a record'}
    if timed_out:
        result[f'{phase}_timeout'] = True
        result[f'{phase}_budget_s'] = round(timeout, 1)
    result[f'{phase}_phase_s'] = round(time.time() - t0, 1)
    return result


def _wedge_hook(phase: str) -> None:
    """Test seam: SKYTPU_BENCH_WEDGE_PHASE=<phase>[,<phase>...] makes
    those phases hang (simulating a wedged chip).
    SKYTPU_BENCH_WEDGE_ONCE=<marker-path> wedges only the first attempt,
    so retry paths are testable."""
    wedged = os.environ.get('SKYTPU_BENCH_WEDGE_PHASE', '')
    if phase not in [w.strip() for w in wedged.split(',')]:
        return
    marker = os.environ.get('SKYTPU_BENCH_WEDGE_ONCE')
    if marker:
        if os.path.exists(marker):
            return
        with open(marker, 'w'):
            pass
    time.sleep(10 ** 6)


def _workload(on_tpu: bool):
    """One workload definition shared by every phase, so rates are
    directly comparable."""
    import dataclasses

    from skypilot_tpu.models.llama import PRESETS

    if on_tpu:
        # Largest preset whose ~10N-byte train state + activations fit one
        # chip's HBM (v5e: 16GB). 'names_qkv' remat (selective: keep
        # attention context + SwiGLU product + post-rotary Q/K/V) +
        # Pallas flash fwd/bwd; measured best of {dots, names, names_qkv,
        # names_offload} x {batch 1, 2} at seq 8192 on v5e (names_qkv is
        # +3.2% over names in interleaved A/B; offload loses 33%; the
        # flash kernels run at 41% fwd / 65% bwd of bf16 peak, so the
        # 6N-only MFU gap to the with-attention figure is accounting,
        # not kernel inefficiency).
        preset, batch, seq, steps = 'llama-1b', 1, 8192, 8
        config = dataclasses.replace(PRESETS[preset],
                                     remat_policy='names_qkv')
    else:  # CPU fallback so the bench always emits a record
        preset, batch, seq, steps = 'test-tiny', 4, 256, 4
        config = PRESETS[preset]
    return preset, batch, seq, steps, config


# ---- phase: train (in-process step throughput; THE headline) ---------------
def phase_train(out_path: str) -> None:
    _wedge_hook('train')
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models.llama import LlamaModel
    from skypilot_tpu.train import Trainer

    backend = jax.default_backend()
    on_tpu = backend in ('tpu', 'axon')
    preset, batch, seq, steps, config = _workload(on_tpu)

    n_chips = jax.device_count()
    mesh = None
    if n_chips > 1:
        # Use every local chip (FSDP over all); batch scales with chips so
        # per-chip work is constant and the per-chip division is honest.
        from skypilot_tpu.parallel import MeshSpec, make_mesh
        mesh = make_mesh(MeshSpec(fsdp=n_chips))
        batch *= n_chips
    model = LlamaModel(config, mesh=mesh)
    trainer = Trainer(model)
    device = jax.devices()[0]
    kind = getattr(device, 'device_kind', 'unknown')
    peak = chip_peak_tflops_by_kind(kind)
    print(f'bench train: backend={backend} device={kind!r} '
          f'preset={preset} chips={n_chips} '
          f'params={config.num_params/1e9:.2f}B batch={batch} seq={seq} '
          f'remat={config.remat_policy}', file=sys.stderr)

    state = trainer.init_fn()(jax.random.key(0))
    step = trainer.step_fn()
    # Distinct batch per step: the dev tunnel backend memoizes identical
    # executions, which would make repeated-batch timings fictitious.
    batches = []
    for i in range(steps + 2):
        tokens = jax.random.randint(jax.random.key(i), (batch, seq), 0,
                                    config.vocab_size)
        batches.append(trainer.shard_batch(
            {'tokens': tokens, 'targets': jnp.roll(tokens, -1, axis=1)}))

    # Warmup (compile); the scalar fetch is the only reliable sync on the
    # tunneled backend (block_until_ready does not wait there).
    for i in range(2):
        state, metrics = step(state, batches[i])
    float(metrics['loss'])
    t0 = time.perf_counter()
    for i in range(steps):
        state, metrics = step(state, batches[2 + i])
    last_loss = float(metrics['loss'])
    dt = time.perf_counter() - t0

    tok_per_s_per_chip = batch * seq * steps / dt / n_chips
    # MFU with the standard (PaLM appendix B) accounting: 6N plus the
    # causal self-attention matmuls — at seq 8192 attention is real MXU
    # work (~23% of this model's FLOPs), not a rounding term. The pure-6N
    # figure is kept alongside for comparability with 6N-only reports.
    flops_per_token = config.train_flops_per_token(seq)
    tflops_per_s = flops_per_token * tok_per_s_per_chip / 1e12
    mfu = tflops_per_s / peak
    mfu_6n = 6 * config.num_params * tok_per_s_per_chip / 1e12 / peak
    tok8b_equiv = tok_per_s_per_chip * config.num_params / LLAMA3_8B_PARAMS
    vs_baseline = tok8b_equiv / BASELINE_8B_TOK_PER_S_PER_CHIP

    print(f'bench train: {tok_per_s_per_chip:,.0f} tok/s/chip @ '
          f'{config.num_params/1e9:.2f}B, {tflops_per_s:.1f} model TFLOP/s '
          f'(MFU {mfu*100:.1f}% of {peak:.0f} peak; '
          f'{mfu_6n*100:.1f}% counting 6N only), '
          f'8B-equivalent {tok8b_equiv:,.0f} tok/s/chip, '
          f'loss={last_loss:.3f}', file=sys.stderr)

    _write_record(out_path, {
        'metric': 'train_tokens_per_sec_per_chip',
        'value': round(tok_per_s_per_chip, 2),
        'unit': f'tokens/s/chip @ {config.num_params/1e9:.2f}B seq {seq}',
        'vs_baseline': round(vs_baseline, 3),
        'equivalent_8b_tokens_per_sec_per_chip': round(tok8b_equiv, 2),
        'model_params_b': round(config.num_params / 1e9, 3),
        'mfu_pct': round(mfu * 100, 1),
        'mfu_6n_pct': round(mfu_6n * 100, 1),
        'chip': kind,
        'seq_len': seq,
    })


# ---- phase: launched (through the product control plane) -------------------
def _overhead_breakdown(summary: dict, t_submit: float,
                        prefix: str = '') -> dict:
    """Split submit->first-step into phases from the callback's marks:
    control plane (provision/ship/queue), runtime startup (python+jax/PJRT
    incl. tunnel), param init, first-step compile."""
    marks = summary.get('marks') or {}
    ps = marks.get('proc_start')
    jr = marks.get('jax_ready')
    idn = marks.get('init_done')
    fse = summary.get('first_step_end_ts')
    out = {}
    if ps:
        out[f'{prefix}control_plane_s'] = round(ps - t_submit, 2)
    if ps and jr:
        out[f'{prefix}runtime_startup_s'] = round(jr - ps, 2)
    if jr and idn:
        out[f'{prefix}param_init_s'] = round(idn - jr, 2)
    if idn and fse:
        out[f'{prefix}first_step_s'] = round(fse - idn, 2)
    return out


def phase_launched(out_path: str, on_tpu: bool, n_devices: int) -> None:
    """Benchmark THROUGH the product's own control plane (VERDICT r2 weak
    #3): `launch` the training task on the local backend (the emulated
    host is this machine, so the job sees the same chip), measure
    submit -> first-step latency and steady-state tok/s via callbacks/.
    """
    _wedge_hook('launched')
    import skypilot_tpu as sky
    from skypilot_tpu import core, execution
    from skypilot_tpu import exceptions as skytpu_exceptions
    from skypilot_tpu.callbacks import SUMMARY_FILE
    from skypilot_tpu.runtime import job_lib

    preset, batch, seq, steps, config = _workload(on_tpu)
    remat = getattr(config, 'remat_policy', 'full')
    # Global batch scales with chips (train.run shards over fsdp=auto),
    # mirroring the in-process phase's scaling so the per-chip rates are
    # directly comparable.
    global_batch = batch * n_devices
    # Per-launch wall-clock caps INSIDE the phase budget: the cold launch
    # wedging must still leave time for the record write (the phase-level
    # SIGKILL is the backstop, not the plan).
    cold_cap = (300 if on_tpu else 180) * _SCALE
    warm_cap = (150 if on_tpu else 120) * _SCALE

    def one_launch(fast: bool, cap: float) -> tuple:
        """Launch the training task; returns (status, summary|None,
        t_submit)."""
        log_dir = tempfile.mkdtemp(prefix='skytpu-bench-cb-')
        task = sky.Task(
            run=(f'python3 -m skypilot_tpu.train.run --preset {preset} '
                 f'--batch {global_batch} --seq {seq} --steps {steps + 2} '
                 f'--remat {remat} --log-every {steps + 2}'),
            envs={'SKYTPU_BENCHMARK_LOG_DIR': log_dir})
        task.set_resources([sky.Resources(cloud='local')])
        t_submit = time.time()
        job_id, _ = execution.launch(task, cluster_name='bench-launched',
                                     detach_run=True, stream_logs=False,
                                     fast=fast)
        deadline = time.time() + cap
        status = None
        while time.time() < deadline:
            try:
                status = core.job_status('bench-launched', job_id)
            except skytpu_exceptions.SkyTpuError:
                status = None  # transient (agent heartbeat lag): keep going
            if status and job_lib.JobStatus(status).is_terminal():
                break
            time.sleep(1.0)
        else:
            # Timed out: SIGKILL the job's process group directly (a job
            # wedged in a blocked tunnel RPC never handles SIGTERM) so the
            # chip is free for whatever runs next.
            from skypilot_tpu.provision import local_impl
            local_impl._kill_host_processes('bench-launched')
        try:
            with open(os.path.join(log_dir, SUMMARY_FILE)) as f:
                summary = json.load(f)
        except (FileNotFoundError, json.JSONDecodeError):
            summary = None
        return status, summary, t_submit

    out: dict = {}
    try:
        # Cold: fresh cluster, empty compilation cache.
        status, summary, t_submit = one_launch(fast=False, cap=cold_cap)
        out['launched_job_status'] = status
        if summary is None or not summary.get('first_step_end_ts'):
            out['launched_error'] = 'no benchmark summary from cold launch'
            _write_record(out_path, out)
            return
        out['launch_overhead_s'] = round(
            summary['first_step_end_ts'] - t_submit, 2)
        out.update(_overhead_breakdown(summary, t_submit))
        if summary.get('seconds_per_step'):
            tok = (global_batch * seq / summary['seconds_per_step']
                   / n_devices)
            out['launched_tokens_per_sec_per_chip'] = round(tok, 2)
        _write_record(out_path, out)  # cold results survive a warm wedge
        # Warm: same cluster, --fast (skip setup/mounts), persistent XLA
        # compilation cache already populated by the cold run.
        status_w, summary_w, t_submit_w = one_launch(fast=True,
                                                     cap=warm_cap)
        out['warm_launched_job_status'] = status_w
        if summary_w and summary_w.get('first_step_end_ts'):
            out['warm_launch_overhead_s'] = round(
                summary_w['first_step_end_ts'] - t_submit_w, 2)
            out.update(_overhead_breakdown(summary_w, t_submit_w,
                                           prefix='warm_'))
    except Exception as e:  # noqa: BLE001 — phases below must survive
        out['launched_error'] = f'{type(e).__name__}: {e}'
    finally:
        try:
            core.down('bench-launched')
        except Exception:  # noqa: BLE001 — bench must not die on cleanup
            pass
    _write_record(out_path, out)


# ---- phase: serve (controller + LB + replica) ------------------------------
def phase_serve(out_path: str, on_tpu: bool, chip_kind: str) -> None:
    """Serve-path phase (BASELINE north-star: SkyServe req/s + TTFT +
    TPOT): full serve stack on the local cloud — controller + LB +
    generation replica subprocess (which owns the chip) — driven with the
    anchor workload shape (~2500 input / ~150 output tokens)."""
    _wedge_hook('serve')
    from skypilot_tpu.benchmark import serve_bench

    def progress(partial: dict) -> None:
        _write_record(out_path, partial)  # survive a mid-sweep SIGKILL

    # Inner deadlines (per service: ready + warmup + burn-in + sweep
    # windows + teardown) sum to ~450s TPU / ~190s CPU per A/B arm, x2
    # arms — INSIDE the phase budget (1000/450), so a slow-but-healthy
    # run finishes rather than getting SIGKILLed.
    try:
        if on_tpu:
            # 4-point sweep bracketing the r5 saturation knee (TTFT p99
            # exploded between c24 and c48), A/B chunked-prefill +
            # admission-control vs the monolithic control. SLO = the
            # BASELINE anchor's P99 TTFT (4.5s).
            # batch_slots doubled vs the contiguous r5 run (32 -> 64)
            # while kv_blocks pins the pool to the SAME HBM budget the
            # 32-slot contiguous cache used (32 x 4096 / 64 rows): the
            # paged engine's acceptance claim — admitted concurrency
            # bounded by actual sequence lengths, not slot regions —
            # measured under an unchanged memory budget. The shared-
            # prefix arm (2048-token common prefix, the r05 system-
            # prompt shape) records serve_prefix_hit_rate.
            out = serve_bench.run(
                preset='llama-1b', batch_slots=64, max_len=4096,
                prompt_len=2500, output_len=150,
                concurrencies=(12, 24, 36, 48),
                window_s=45.0, warmup_requests=2,
                ready_timeout_s=150 * _SCALE, warmup_deadline_s=90 * _SCALE,
                prefill_chunk=256, ttft_slo_ms=4500.0, ab_monolithic=True,
                prefix_share_len=2048, kv_block=64, kv_blocks=2049,
                spec_tokens=4, progress=progress)
        else:
            out = serve_bench.run(
                preset='test-tiny', batch_slots=2, max_len=128,
                prompt_len=24, output_len=8, concurrencies=(1, 2, 3, 4),
                window_s=4.0, warmup_requests=1,
                ready_timeout_s=120 * _SCALE, warmup_deadline_s=60 * _SCALE,
                prefill_chunk=8, ttft_slo_ms=2000.0, ab_monolithic=True,
                prefix_share_len=16, kv_block=8,
                spec_tokens=4, progress=progress)
    except Exception as e:  # noqa: BLE001 — a failed serve phase must
        # still contribute an explanatory record, not just rc!=0
        _write_record(out_path,
                      {'serve_error': f'{type(e).__name__}: {e}'})
        return
    # int8 quantized-KV arm at DOUBLED kv_blocks: int8 codes + f32
    # scales roughly halve KV bytes/block, so twice the blocks fit the
    # SAME HBM budget the bf16 arm ran under — the record captures
    # bf16-vs-int8 in one sweep (the r06 acceptance claim: admitted
    # concurrency >= 2x the bf16 block budget, tpot_p50 no worse at
    # matched concurrency). Failure here must not void the headline
    # arm's record.
    try:
        if on_tpu:
            i8 = serve_bench.run(
                preset='llama-1b', batch_slots=64, max_len=4096,
                prompt_len=2500, output_len=150,
                concurrencies=(24, 48, 72, 96),
                window_s=45.0, warmup_requests=2,
                ready_timeout_s=150 * _SCALE,
                warmup_deadline_s=90 * _SCALE,
                prefill_chunk=256, ttft_slo_ms=4500.0,
                prefix_share_len=2048, kv_block=64, kv_blocks=4097,
                spec_tokens=4, kv_dtype='int8',
                service_name='bench-serve-int8')
        else:
            i8 = serve_bench.run(
                preset='test-tiny', batch_slots=2, max_len=128,
                prompt_len=24, output_len=8, concurrencies=(2,),
                window_s=4.0, warmup_requests=1,
                ready_timeout_s=120 * _SCALE,
                warmup_deadline_s=60 * _SCALE,
                prefill_chunk=8, kv_block=8,
                spec_tokens=4, kv_dtype='int8',
                service_name='bench-serve-int8')
        out['serve_sweep_int8'] = i8.get('serve_sweep')
        for fld in ('serve_kv_dtype', 'serve_kv_blocks',
                    'serve_req_per_s', 'serve_ttft_p99_ms',
                    'serve_tpot_p50_ms'):
            if fld in i8:
                out['serve_int8_' + fld[len('serve_'):]] = i8[fld]
    except Exception as e:  # noqa: BLE001
        out['serve_int8_error'] = f'{type(e).__name__}: {e}'
    if out.get('serve_req_per_s'):
        out.update(serve_bench.equivalence_estimate(
            out['serve_req_per_s'],
            model_params=out['serve_model_params'],
            chip_kind=chip_kind))
    _write_record(out_path, out)


# ---- phase: decode (standalone engine throughput, fresh process) -----------
def phase_decode(out_path: str) -> None:
    """Serving-side numbers from the in-tree continuous-batching engine
    (BASELINE.md serving anchors are Llama-2-7B on EIGHT v6e chips — not
    reproducible on one v5e — so these ride as context, not vs_baseline):
    steady-state decode tok/s with full slots, and prefill TTFT. Runs in
    a FRESH process so the number is independent of what earlier phases
    did to the chip (VERDICT r4 #2)."""
    _wedge_hook('decode')
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models.decode import DecodeEngine, prefill_bucket
    from skypilot_tpu.models.llama import LlamaModel

    on_tpu = jax.default_backend() in ('tpu', 'axon')
    _, _, _, _, config = _workload(on_tpu)
    model = LlamaModel(config)
    params = jax.jit(model.init)(jax.random.key(0))
    # Decode in the compute dtype, like the serve replica does.
    params = jax.tree.map(
        lambda a: a.astype(config.dtype)
        if hasattr(a, 'dtype') and a.dtype == jnp.float32 else a, params)

    slots, max_len, prompt_len = (16, 1024, 128) if on_tpu else (4, 128, 24)
    engine = DecodeEngine(config, batch_slots=slots, max_len=max_len)
    state = engine.init_state()
    prompt = jax.random.randint(jax.random.key(7), (prompt_len,), 0,
                                config.vocab_size)
    bucket = prefill_bucket(prompt_len, engine.max_len)
    padded = jnp.pad(prompt, (0, bucket - prompt_len))
    k, v, logits = engine.prefill(params, padded, prompt_len)
    first = int(jnp.argmax(logits))  # compile + sync
    ttfts = []
    for _ in range(3):
        t0 = time.perf_counter()
        k, v, logits = engine.prefill(params, padded, prompt_len)
        first = int(jnp.argmax(logits))
        ttfts.append(time.perf_counter() - t0)
    for s in range(slots):
        state = engine.insert(state, k, v, prompt_len, first, s)
    rng = jax.random.key(11)
    for i in range(4):  # warmup (compile)
        state, sampled, rng = engine.step(params, state, rng)
    int(sampled[0])
    n = 64
    t0 = time.perf_counter()
    for i in range(n):
        state, sampled, rng = engine.step(params, state, rng)
    int(sampled[0])  # sync
    dt = time.perf_counter() - t0
    _write_record(out_path, {
        'decode_tokens_per_sec_per_chip': round(slots * n / dt, 1),
        'decode_batch_slots': slots,
        'decode_ttft_ms': round(sorted(ttfts)[1] * 1e3, 1),
        'decode_prompt_len': prompt_len,
    })


# ---- record plumbing -------------------------------------------------------
def _write_record(out_path: str, record: dict) -> None:
    tmp = out_path + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(record, f)
    os.replace(tmp, out_path)


def _emit(record: dict) -> None:
    print(json.dumps(record), flush=True)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument('--phase', choices=['train', 'launched', 'serve',
                                            'decode'])
    parser.add_argument('--out')
    parser.add_argument('--on-tpu', action='store_true')
    parser.add_argument('--n-devices', type=int, default=1)
    parser.add_argument('--chip-kind', default='cpu')
    args = parser.parse_args()

    if args.phase:
        {'train': lambda: phase_train(args.out),
         'launched': lambda: phase_launched(args.out, args.on_tpu,
                                            args.n_devices),
         'serve': lambda: phase_serve(args.out, args.on_tpu,
                                      args.chip_kind),
         'decode': lambda: phase_decode(args.out)}[args.phase]()
        return

    # ---- orchestrator ----
    t_start = time.time()
    # One shared state dir for every phase's local clusters, so orphan
    # cleanup after a SIGKILLed phase can find their pidfiles.
    global _owns_state_dir
    if not os.environ.get('SKYTPU_STATE_DIR'):
        os.environ['SKYTPU_STATE_DIR'] = tempfile.mkdtemp(
            prefix='skytpu-bench-state-')
        _owns_state_dir = True
    record: dict = {}

    probe = probe_chip(timeout=_phase_budget('probe', 150))
    on_tpu = bool(probe) and probe['backend'] in ('tpu', 'axon')
    if probe is None:
        record['chip_wedged'] = True
        record['chip_wedged_at'] = 'initial_probe'
    chip_kind = probe['device_kind'] if probe else 'cpu'
    n_devices = probe['n_devices'] if probe else 1
    print(f'bench: probe={probe} on_tpu={on_tpu}', file=sys.stderr)

    def reprobe(stage: str) -> bool:
        """Re-probe between phases; on failure flip to CPU + flag."""
        nonlocal on_tpu
        if not on_tpu:
            return False
        if probe_chip(timeout=_phase_budget('reprobe', 90)) is None:
            record['chip_wedged'] = True
            record['chip_wedged_at'] = stage
            on_tpu = False
            _cleanup_orphans()
        return on_tpu

    # Phase 1 — train. THE metric: runs first, emitted immediately, so no
    # later phase can erase it.
    train = run_phase('train',
                      _phase_budget('train', 600 if on_tpu else 300),
                      force_cpu=not on_tpu)
    if on_tpu and ('train_timeout' in train or 'train_error' in train):
        record['chip_wedged'] = True
        record['chip_wedged_at'] = 'train'
        record['train_tpu_failure'] = train
        on_tpu = False
        _cleanup_orphans()
        train = run_phase('train', _phase_budget('train_retry', 300),
                          force_cpu=True)
    record.update(train)
    if 'value' not in record:  # CPU fallback also failed: emit SOMETHING
        record.setdefault('metric', 'train_tokens_per_sec_per_chip')
        record.setdefault('value', 0.0)
        record.setdefault('unit', 'tokens/s/chip (train phase failed)')
        record.setdefault('vs_baseline', 0.0)
    _emit(record)

    # Phase 2 — launched (through the control plane).
    reprobe('before_launched')
    record.update(run_phase(
        'launched', _phase_budget('launched', 480 if on_tpu else 360),
        force_cpu=not on_tpu,
        extra_args=(['--on-tpu'] if on_tpu else [])
        + ['--n-devices', str(n_devices if on_tpu else 1)]))
    if record.get('launched_tokens_per_sec_per_chip') and record.get(
            'value'):
        record['launched_vs_inprocess'] = round(
            record['launched_tokens_per_sec_per_chip'] / record['value'], 3)
    _emit(record)

    # Phase 3 — serve (controller + LB + replica; the budget covers BOTH
    # A/B arms — monolithic control + chunked headline).
    reprobe('before_serve')
    record.update(run_phase(
        'serve', _phase_budget('serve', 1000 if on_tpu else 450),
        force_cpu=not on_tpu,
        extra_args=(['--on-tpu'] if on_tpu else [])
        + ['--chip-kind', chip_kind if on_tpu else 'cpu']))
    _emit(record)

    # Phase 4 — decode (fresh-process engine throughput).
    reprobe('before_decode')
    record.update(run_phase('decode',
                            _phase_budget('decode', 300 if on_tpu else 240),
                            force_cpu=not on_tpu))
    record['bench_elapsed_s'] = round(time.time() - t_start, 1)
    _emit(record)


if __name__ == '__main__':
    main()
