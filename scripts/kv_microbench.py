#!/usr/bin/env python3
"""Paged-vs-contiguous decode microbench: step time + KV HBM footprint.

A full serve sweep takes minutes of wall clock and a whole serve stack;
this is the 30-second regression probe for the paged-KV engine. It runs
the SAME fixed-batch decode loop twice — contiguous per-slot KV
(``kv_block=0``) and paged (``--kv-block``) — on one process's device
and reports per-step wall time plus the exact KV state bytes, so a
paged-path regression (gather/scatter overhead creeping up, pool
mis-sizing) shows up in CI-adjacent tooling without a serve run::

    python scripts/kv_microbench.py                      # CPU tiny
    python scripts/kv_microbench.py --preset llama-1b \
        --slots 16 --max-len 1024 --kv-block 64          # on-chip

Output is one JSON line (machine-diffable in BENCH-style tooling).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)


def _state_kv_bytes(state) -> int:
    return int(state.k.nbytes) + int(state.v.nbytes)


def bench_engine(config, params, *, slots: int, max_len: int,
                 prompt_len: int, steps: int, kv_block: int,
                 kv_blocks=None) -> dict:
    """Decode-step timing at full occupancy for one engine mode."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models.decode import DecodeEngine, prefill_bucket

    engine = DecodeEngine(config, batch_slots=slots, max_len=max_len,
                          kv_block=kv_block, kv_blocks=kv_blocks)
    state = engine.init_state()
    prompt = jax.random.randint(jax.random.key(7), (prompt_len,), 0,
                                config.vocab_size)
    bucket = prefill_bucket(prompt_len, engine.max_len)
    padded = jnp.pad(prompt, (0, bucket - prompt_len))
    rng = jax.random.key(11)
    for s in range(slots):
        state, _, rng = engine.admit(params, state, padded, prompt_len,
                                     s, rng)
    for _ in range(4):  # compile + warm
        state, sampled, rng = engine.step(params, state, rng)
    int(sampled[0])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, sampled, rng = engine.step(params, state, rng)
    int(sampled[0])  # sync
    dt = time.perf_counter() - t0
    return {
        'mode': 'paged' if kv_block > 0 else 'contiguous',
        'kv_block': kv_block,
        'kv_blocks': engine.kv_blocks,
        'step_ms': round(dt / steps * 1e3, 3),
        'decode_tokens_per_s': round(slots * steps / dt, 1),
        'kv_state_bytes': _state_kv_bytes(state),
        'kv_state_mib': round(_state_kv_bytes(state) / 2**20, 2),
    }


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    parser.add_argument('--preset', default='test-tiny')
    parser.add_argument('--slots', type=int, default=4)
    parser.add_argument('--max-len', type=int, default=128)
    parser.add_argument('--prompt-len', type=int, default=24)
    parser.add_argument('--steps', type=int, default=32)
    parser.add_argument('--kv-block', type=int, default=64,
                        help='block rows for the paged arm')
    parser.add_argument('--kv-blocks', type=int, default=None,
                        help='paged pool size (default: contiguous HBM '
                             'budget at --slots)')
    args = parser.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models.llama import PRESETS, LlamaModel

    config = PRESETS[args.preset]
    model = LlamaModel(config)
    params = jax.jit(model.init)(jax.random.key(0))
    params = jax.tree.map(
        lambda a: a.astype(config.dtype)
        if hasattr(a, 'dtype') and a.dtype == jnp.float32 else a, params)

    common = dict(slots=args.slots, max_len=args.max_len,
                  prompt_len=min(args.prompt_len, args.max_len - 1),
                  steps=args.steps)
    contiguous = bench_engine(config, params, kv_block=0, **common)
    paged = bench_engine(config, params, kv_block=args.kv_block,
                         kv_blocks=args.kv_blocks, **common)
    record = {
        'preset': args.preset,
        'batch_slots': args.slots,
        'max_len': args.max_len,
        'prompt_len': common['prompt_len'],
        'backend': jax.default_backend(),
        'contiguous': contiguous,
        'paged': paged,
        'paged_step_overhead_pct': round(
            (paged['step_ms'] / contiguous['step_ms'] - 1) * 100, 1)
        if contiguous['step_ms'] else None,
    }
    print(json.dumps(record))
    return 0


if __name__ == '__main__':
    sys.exit(main())
