#!/usr/bin/env python3
"""Paged-vs-contiguous decode microbench: step time + KV HBM footprint.

A full serve sweep takes minutes of wall clock and a whole serve stack;
this is the 30-second regression probe for the paged-KV engine. It runs
the SAME fixed-batch decode loop twice — contiguous per-slot KV
(``kv_block=0``) and paged (``--kv-block``) — on one process's device
and reports per-step wall time plus the exact KV state bytes, so a
paged-path regression (gather/scatter overhead creeping up, pool
mis-sizing) shows up in CI-adjacent tooling without a serve run.
Three further arms ride along: sync-vs-async dispatch
(``--async-depths``), speculative decode (``--spec-ks``:
accepted-tokens-per-step + effective tok/s per draft length on a
repetitive prompt), quantized KV (``--quant-ks``: int8-vs-bf16
bytes/token, step-time ratio, round-trip error, and greedy-stream
agreement with spec decode off and on), span tracing
(``--trace-overhead``: traced-vs-plain step time for the request-
lifecycle tracer's hot-path recording; pinned < 5% in tier-1) and
roofline attribution (``--roofline``: per-variant FLOPs/bytes/
arithmetic-intensity/MFU from cost_analysis, estimator fallback on
CPU)::

    python scripts/kv_microbench.py                      # CPU tiny
    python scripts/kv_microbench.py --preset llama-1b \
        --slots 16 --max-len 1024 --kv-block 64          # on-chip

Output is one JSON line (machine-diffable in BENCH-style tooling).
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)


def _state_kv_bytes(state) -> int:
    """KV state footprint: pool halves plus (int8 mode) their scales."""
    return (int(state.k.nbytes) + int(state.v.nbytes)
            + int(state.k_scale.nbytes) + int(state.v_scale.nbytes))


def bench_engine(config, params, *, slots: int, max_len: int,
                 prompt_len: int, steps: int, kv_block: int,
                 kv_blocks=None, kv_dtype=None) -> dict:
    """Decode-step timing at full occupancy for one engine mode."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models.decode import DecodeEngine, prefill_bucket

    engine = DecodeEngine(config, batch_slots=slots, max_len=max_len,
                          kv_block=kv_block, kv_blocks=kv_blocks,
                          kv_dtype=kv_dtype)
    state = engine.init_state()
    prompt = jax.random.randint(jax.random.key(7), (prompt_len,), 0,
                                config.vocab_size)
    bucket = prefill_bucket(prompt_len, engine.max_len)
    padded = jnp.pad(prompt, (0, bucket - prompt_len))
    rng = jax.random.key(11)
    for s in range(slots):
        state, _, rng = engine.admit(params, state, padded, prompt_len,
                                     s, rng)
    for _ in range(4):  # compile + warm
        state, sampled, rng = engine.step(params, state, rng)
    int(sampled[0])
    t0 = time.perf_counter()
    for _ in range(steps):
        state, sampled, rng = engine.step(params, state, rng)
    int(sampled[0])  # sync
    dt = time.perf_counter() - t0
    return {
        'mode': 'paged' if kv_block > 0 else 'contiguous',
        'kv_block': kv_block,
        'kv_blocks': engine.kv_blocks,
        'kv_dtype': engine.kv_dtype,
        'kv_bytes_per_token': engine.kv_bytes_per_token(),
        'step_ms': round(dt / steps * 1e3, 3),
        'decode_tokens_per_s': round(slots * steps / dt, 1),
        'kv_state_bytes': _state_kv_bytes(state),
        'kv_state_mib': round(_state_kv_bytes(state) / 2**20, 2),
    }


def bench_async(config, params, *, slots: int, max_len: int,
                prompt_len: int, steps: int, kv_block: int,
                kv_blocks=None, depth: int = 2,
                host_work_ms: float = 1.0) -> dict:
    """Sync-vs-async dispatch arm: the serving scheduler's dispatch
    pattern at a given in-flight depth. Each scheduling round
    dispatches a burst of ``depth`` steps back-to-back, then runs one
    completion pass — a single batched D2H fetch plus ``host_work_ms``
    of emulated per-round host work (the admission / release
    bookkeeping / detokenization stand-in).

    depth=1 reproduces the synchronous schedule — every step pays the
    full host pass before the next dispatch, so every recorded gap
    eats it whole. depth>=2 amortizes the pass across the burst: only
    the round boundary pays it, the intra-burst gaps collapse to the
    loop's own dispatch overhead, and the reported step-gap p50 drops
    to sub-host-work territory while effective tok/s rises.
    """
    import time as _time
    from collections import deque

    import jax
    import jax.numpy as jnp
    import numpy as np

    from skypilot_tpu.models.decode import DecodeEngine, prefill_bucket

    engine = DecodeEngine(config, batch_slots=slots, max_len=max_len,
                          kv_block=kv_block, kv_blocks=kv_blocks)
    state = engine.init_state()
    prompt = jax.random.randint(jax.random.key(7), (prompt_len,), 0,
                                config.vocab_size)
    bucket = prefill_bucket(prompt_len, engine.max_len)
    padded = jnp.pad(prompt, (0, bucket - prompt_len))
    rng = jax.random.key(11)
    for s in range(slots):
        state, _, rng = engine.admit(params, state, padded, prompt_len,
                                     s, rng)
    for _ in range(4):  # compile + warm
        state, sampled, rng = engine.step(params, state, rng)
    int(sampled[0])
    if depth > 1:  # warm the batched-fetch concatenate variant too
        np.asarray(jnp.concatenate([sampled.reshape(-1)] * depth))

    inflight: 'deque' = deque()
    gaps_ms = []
    last_end = None
    done = 0
    t0 = _time.perf_counter()
    while done < steps:
        burst = min(depth, steps - done)
        for _ in range(burst):
            t_start = _time.perf_counter()
            if last_end is not None:
                gaps_ms.append((t_start - last_end) * 1e3)
            state, sampled, rng = engine.step(params, state, rng)
            last_end = _time.perf_counter()
            inflight.append(sampled)
            done += 1
        # Completion pass for the round: ONE batched device-to-host
        # fetch for every queued step, then the emulated host work.
        arrs = [inflight.popleft().reshape(-1) for _ in range(len(inflight))]
        np.asarray(jnp.concatenate(arrs) if len(arrs) > 1 else arrs[0])
        if host_work_ms > 0:
            _time.sleep(host_work_ms / 1e3)
    dt = _time.perf_counter() - t0
    gaps_ms.sort()
    return {
        'depth': depth,
        'host_work_ms': host_work_ms,
        'step_gap_p50_ms': round(gaps_ms[len(gaps_ms) // 2], 3)
        if gaps_ms else None,
        'step_gap_max_ms': round(gaps_ms[-1], 3) if gaps_ms else None,
        'effective_tokens_per_s': round(slots * steps / dt, 1),
    }


def bench_spec(config, params, *, max_len: int, prompt_len: int,
               k: int, ngram: int = 3, out_tokens: int = 160,
               kv_block: int = 64) -> dict:
    """Spec-decode arm: accepted-tokens-per-step and effective tok/s
    for one draft length ``k`` on a repetitive prompt (the traffic
    shape prompt-lookup drafting targets). One slot is driven
    end-to-end exactly like the scheduler drives it — host-side
    ``draft_tokens`` over the request's own emitted history, one
    ``step_verify`` per round, 1..k+1 tokens banked per round — so the
    reported tok/s includes the drafter's host cost, not just device
    time. ``k=0`` runs the plain one-token step loop as the baseline
    (accepted_per_step is 1.0 by construction there)."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models.decode import (DecodeEngine, draft_tokens,
                                            prefill_bucket)

    slots = 2  # slot 1 stays inactive: exercises the masked-slot path
    engine = DecodeEngine(config, batch_slots=slots, max_len=max_len,
                          kv_block=kv_block, spec_tokens=k)
    pattern = (5, 9, 2, 7, 11, 3)
    prompt = [pattern[i % len(pattern)] % config.vocab_size
              for i in range(prompt_len)]
    bucket = prefill_bucket(prompt_len, engine.max_len)
    padded = jnp.asarray(prompt + [0] * (bucket - prompt_len),
                         jnp.int32)

    def run():
        state = engine.init_state()
        rng = jax.random.key(11)
        state, first, rng = engine.admit(params, state, padded,
                                         prompt_len, 0, rng)
        hist = prompt + [int(first)]
        emitted, steps = 1, 0
        while emitted < out_tokens:
            if k > 0:
                draft = jnp.asarray(
                    [draft_tokens(hist, k, ngram), [0] * k], jnp.int32)
                state, out, acc, rng = engine.step_verify(
                    params, state, rng, draft)
                take = int(acc[0]) + 1
                hist.extend(int(t) for t in out[0][:take])
            else:
                state, sampled, rng = engine.step(params, state, rng)
                take = 1
                hist.append(int(sampled[0]))
            emitted += take
            steps += 1
        return emitted, steps

    run()  # compile + warm every variant the timed run hits
    t0 = time.perf_counter()
    emitted, steps = run()
    dt = time.perf_counter() - t0
    return {
        'k': k,
        'tokens': emitted,
        'decode_steps': steps,
        'accepted_per_step': round(emitted / steps, 2),
        'effective_tokens_per_s': round(emitted / dt, 1),
    }


def _greedy_stream(config, params, engine, prompt, n_tokens: int,
                   k: int, ngram: int = 3) -> list:
    """One slot's greedy stream under a given spec draft length (k=0 =
    plain steps) — the int8-vs-bf16 agreement probe's driver."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models.decode import draft_tokens, prefill_bucket

    state = engine.init_state()
    rng = jax.random.key(11)
    bucket = prefill_bucket(len(prompt), engine.max_len)
    padded = jnp.asarray(list(prompt) + [0] * (bucket - len(prompt)),
                         jnp.int32)
    state, first, rng = engine.admit(params, state, padded, len(prompt),
                                     0, rng)
    hist = list(prompt) + [int(first)]
    out = [int(first)]
    while len(out) < n_tokens:
        if k > 0:
            draft = jnp.asarray([draft_tokens(hist, k, ngram), [0] * k],
                                jnp.int32)
            state, toks, acc, rng = engine.step_verify(params, state,
                                                       rng, draft)
            take = [int(t) for t in toks[0][:int(acc[0]) + 1]]
        else:
            state, sampled, rng = engine.step(params, state, rng)
            take = [int(sampled[0])]
        out.extend(take)
        hist.extend(take)
    return out[:n_tokens]


def bench_quant(config, params, *, slots: int, max_len: int,
                prompt_len: int, steps: int, kv_block: int,
                kv_blocks=None, spec_ks=(0, 4), agree_tokens: int = 128
                ) -> dict:
    """Quantized-KV arm: int8-vs-bf16 bytes/token and step time on the
    SAME paged workload, greedy-stream agreement per spec draft length,
    and the raw quantize->dequantize round-trip error. The headline
    claims: bytes reduction >= 1.9x, step time <= 1.1x bf16."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models.decode import (DecodeEngine,
                                            dequantize_kv_rows,
                                            quantize_kv_rows)

    common = dict(slots=slots, max_len=max_len, prompt_len=prompt_len,
                  steps=steps, kv_block=kv_block, kv_blocks=kv_blocks)
    bf = bench_engine(config, params, **common)
    i8 = bench_engine(config, params, kv_dtype='int8', **common)

    # Round-trip error on KV-shaped data: per-row absmax symmetric
    # quantization bounds the error at scale/2 = absmax/254 per element.
    x = jax.random.normal(jax.random.key(3),
                          (config.num_kv_heads, kv_block,
                           config.head_dim), jnp.float32)
    q, s = quantize_kv_rows(x)
    err = jnp.abs(dequantize_kv_rows(q, s) - x)
    rel = float(jnp.max(err) / jnp.max(jnp.abs(x)))

    agree_len = min(agree_tokens, max_len - prompt_len - 8)
    agreement = {}
    pattern = (5, 9, 2, 7, 11, 3, 13, 4)
    prompt = [pattern[i % len(pattern)] % config.vocab_size
              for i in range(prompt_len)]
    for k in spec_ks:
        e_bf = DecodeEngine(config, batch_slots=2, max_len=max_len,
                            kv_block=kv_block, spec_tokens=k)
        e_i8 = DecodeEngine(config, batch_slots=2, max_len=max_len,
                            kv_block=kv_block, spec_tokens=k,
                            kv_dtype='int8')
        s_bf = _greedy_stream(config, params, e_bf, prompt, agree_len, k)
        s_i8 = _greedy_stream(config, params, e_i8, prompt, agree_len, k)
        agreement[f'k{k}'] = round(
            sum(a == b for a, b in zip(s_bf, s_i8)) / agree_len, 4)

    return {
        'bf16': bf,
        'int8': i8,
        'kv_bytes_reduction': round(
            bf['kv_bytes_per_token'] / i8['kv_bytes_per_token'], 2),
        'step_time_ratio': round(i8['step_ms'] / bf['step_ms'], 3)
        if bf['step_ms'] else None,
        'roundtrip_rel_err': round(rel, 5),
        'greedy_agreement': agreement,
        'agree_tokens': agree_len,
    }


def bench_trace_overhead(config, params, *, slots: int, max_len: int,
                         prompt_len: int, steps: int, kv_block: int,
                         kv_blocks=None, rounds: int = 3) -> dict:
    """Span-tracing overhead arm: the SAME paged decode loop with and
    without the scheduler's per-step trace-ring recording (one decode
    span per slot per step, a verify point, an exemplar'd histogram
    observe — the instrumentation the request-lifecycle tracer adds to
    the hot path). Interleaved A/B rounds with min-per-arm timing keep
    thermal/GC drift out of the ratio; the tier-1 pin asserts the
    traced arm stays within 5% of plain."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models.decode import DecodeEngine, prefill_bucket
    from skypilot_tpu.utils import metrics as metrics_lib
    from skypilot_tpu.utils import timeline

    engine = DecodeEngine(config, batch_slots=slots, max_len=max_len,
                          kv_block=kv_block, kv_blocks=kv_blocks)
    state = engine.init_state()
    prompt = jax.random.randint(jax.random.key(7), (prompt_len,), 0,
                                config.vocab_size)
    bucket = prefill_bucket(prompt_len, engine.max_len)
    padded = jnp.pad(prompt, (0, bucket - prompt_len))
    rng = jax.random.key(11)
    for s in range(slots):
        state, _, rng = engine.admit(params, state, padded, prompt_len,
                                     s, rng)
    for _ in range(4):  # compile + warm
        state, sampled, rng = engine.step(params, state, rng)
    int(sampled[0])
    hist = metrics_lib.histogram('skytpu_bench_trace_overhead_ms',
                                 'trace-overhead arm probe histogram')

    def run(traced: bool, tag: int) -> float:
        nonlocal state, rng
        rids = [f'bench-{tag}-{s}' for s in range(slots)]
        t0 = time.perf_counter()
        for i in range(steps):
            t_step = time.perf_counter()
            state, sampled, rng = engine.step(params, state, rng)
            if traced:
                end = time.time()
                dur = time.perf_counter() - t_step
                for rid in rids:
                    timeline.trace_span(rid, 'decode', end - dur, end,
                                        steps=1, spec=False)
                timeline.trace_point(rids[i % slots], 'verify', end,
                                     k=0, accepted=1)
                hist.observe(dur * 1e3, exemplar=rids[i % slots])
            if (i + 1) % 16 == 0:  # exercise ring sealing too
                if traced:
                    for rid in rids:
                        timeline.trace_finish(rid, status='ok')
                    rids = [f'bench-{tag}-{s}-{i}' for s in range(slots)]
        int(sampled[0])  # sync
        dt = time.perf_counter() - t0
        if traced:
            for rid in rids:
                timeline.trace_finish(rid, status='ok')
        return dt

    run(True, -1)  # warm the trace path (ring allocation, atexit hook)
    best = {False: float('inf'), True: float('inf')}
    for r in range(rounds):
        for traced in (False, True) if r % 2 == 0 else (True, False):
            best[traced] = min(best[traced], run(traced, r))
    plain_ms = best[False] / steps * 1e3
    traced_ms = best[True] / steps * 1e3
    return {
        'step_ms_plain': round(plain_ms, 4),
        'step_ms_traced': round(traced_ms, 4),
        'overhead_pct': round((traced_ms / plain_ms - 1) * 100, 2)
        if plain_ms else None,
        'spans_per_step': slots + 1,
        'rounds': rounds,
    }


def bench_roofline(config, params, *, slots: int, max_len: int,
                   prompt_len: int, steps: int, kv_block: int,
                   kv_blocks=None) -> dict:
    """Roofline arm: run the paged decode loop long enough for the
    profiler to see real step times, then attribute cost_analysis
    FLOPs/bytes (analytic estimator on backends without it) to each
    compiled variant. Prints variant -> (FLOPs, bytes, AI, MFU) — the
    same numbers the ``skytpu_engine_step_{flops,bytes,ai,mfu}`` gauges
    export on a serving replica."""
    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models import decode
    from skypilot_tpu.models.decode import DecodeEngine, prefill_bucket

    engine = DecodeEngine(config, batch_slots=slots, max_len=max_len,
                          kv_block=kv_block, kv_blocks=kv_blocks)
    state = engine.init_state()
    prompt = jax.random.randint(jax.random.key(7), (prompt_len,), 0,
                                config.vocab_size)
    bucket = prefill_bucket(prompt_len, engine.max_len)
    padded = jnp.pad(prompt, (0, bucket - prompt_len))
    rng = jax.random.key(11)
    for s in range(slots):
        state, _, rng = engine.admit(params, state, padded, prompt_len,
                                     s, rng)
    for _ in range(4):  # compile + warm
        state, sampled, rng = engine.step(params, state, rng)
    int(sampled[0])
    for _ in range(steps):  # measured: feeds the per-variant step EWMA
        t0 = time.perf_counter()
        state, sampled, rng = engine.step(params, state, rng)
        int(sampled[0])
        engine.profiler.note_step(time.perf_counter() - t0)
    engine.profiler.note_roofline(engine.roofline_costs(params, state))
    snap = engine.profiler.roofline_snapshot(decode.peak_flops())
    for variant in sorted(snap):
        row = snap[variant]
        print(f'# roofline {variant}: flops={row["flops"]:.3e} '
              f'bytes={row["bytes"]:.3e} ai={row["ai"]:.2f} '
              f'mfu={row["mfu"]:.4f}', file=sys.stderr)
    return snap


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    parser.add_argument('--preset', default='test-tiny')
    parser.add_argument('--slots', type=int, default=4)
    parser.add_argument('--max-len', type=int, default=128)
    parser.add_argument('--prompt-len', type=int, default=24)
    parser.add_argument('--steps', type=int, default=32)
    parser.add_argument('--kv-block', type=int, default=64,
                        help='block rows for the paged arm')
    parser.add_argument('--kv-blocks', type=int, default=None,
                        help='paged pool size (default: contiguous HBM '
                             'budget at --slots)')
    parser.add_argument('--async-depths', type=int, nargs='*',
                        default=(1, 2, 4),
                        help='in-flight depths for the sync-vs-async '
                             'arm (empty = skip)')
    parser.add_argument('--host-work-ms', type=float, default=1.0,
                        help='emulated per-step host latency in the '
                             'async arm')
    parser.add_argument('--spec-ks', type=int, nargs='*',
                        default=(0, 2, 4, 8),
                        help='draft lengths for the spec-decode arm '
                             '(0 = plain-step baseline; empty = skip)')
    parser.add_argument('--spec-ngram', type=int, default=3,
                        help='drafter n-gram length in the spec arm')
    parser.add_argument('--quant-ks', type=int, nargs='*',
                        default=(0, 4),
                        help='spec draft lengths for the int8-vs-bf16 '
                             'agreement probe in the quant arm '
                             '(empty = skip the quant arm)')
    parser.add_argument('--trace-overhead', action='store_true',
                        help='add the span-tracing overhead arm '
                             '(traced-vs-plain decode step time)')
    parser.add_argument('--roofline', action='store_true',
                        help='add the roofline-attribution arm '
                             '(variant -> FLOPs/bytes/AI/MFU)')
    args = parser.parse_args(argv)

    import jax
    import jax.numpy as jnp

    from skypilot_tpu.models.llama import PRESETS, LlamaModel

    config = PRESETS[args.preset]
    model = LlamaModel(config)
    params = jax.jit(model.init)(jax.random.key(0))
    params = jax.tree.map(
        lambda a: a.astype(config.dtype)
        if hasattr(a, 'dtype') and a.dtype == jnp.float32 else a, params)

    common = dict(slots=args.slots, max_len=args.max_len,
                  prompt_len=min(args.prompt_len, args.max_len - 1),
                  steps=args.steps)
    contiguous = bench_engine(config, params, kv_block=0, **common)
    paged = bench_engine(config, params, kv_block=args.kv_block,
                         kv_blocks=args.kv_blocks, **common)
    record = {
        'preset': args.preset,
        'batch_slots': args.slots,
        'max_len': args.max_len,
        'prompt_len': common['prompt_len'],
        'backend': jax.default_backend(),
        'contiguous': contiguous,
        'paged': paged,
        'paged_step_overhead_pct': round(
            (paged['step_ms'] / contiguous['step_ms'] - 1) * 100, 1)
        if contiguous['step_ms'] else None,
        # Sync-vs-async dispatch: step gap + effective tok/s per depth
        # (depth 1 = the synchronous oracle), paged engine.
        'async': [bench_async(config, params, kv_block=args.kv_block,
                              kv_blocks=args.kv_blocks, depth=d,
                              host_work_ms=args.host_work_ms, **common)
                  for d in (args.async_depths or ())],
    }
    if args.quant_ks:
        # Quant arm needs room for the agreement stream; reuse the spec
        # arm's length floor.
        quant_max_len = max(args.max_len, 256)
        record['quant'] = bench_quant(
            config, params, slots=args.slots, max_len=quant_max_len,
            prompt_len=common['prompt_len'], steps=args.steps,
            kv_block=args.kv_block, spec_ks=tuple(args.quant_ks))
    if args.spec_ks:
        # Own max_len: the stream needs room to settle into a cycle the
        # drafter can lock onto before the length budget runs out. Pool
        # size is left derived (kv_blocks=None) so a --kv-blocks tuned
        # for --max-len never undersizes this arm.
        spec_max_len = max(args.max_len, 256)
        spec_out = min(200,
                       spec_max_len - common['prompt_len'] - 16)
        record['spec'] = [
            bench_spec(config, params, max_len=spec_max_len,
                       prompt_len=common['prompt_len'], k=k,
                       ngram=args.spec_ngram, out_tokens=spec_out,
                       kv_block=args.kv_block)
            for k in args.spec_ks]
    if args.trace_overhead:
        record['trace_overhead'] = bench_trace_overhead(
            config, params, kv_block=args.kv_block,
            kv_blocks=args.kv_blocks, **common)
    if args.roofline:
        record['roofline'] = bench_roofline(
            config, params, kv_block=args.kv_block,
            kv_blocks=args.kv_blocks, **common)
    print(json.dumps(record))
    return 0


if __name__ == '__main__':
    sys.exit(main())
