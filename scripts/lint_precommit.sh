#!/usr/bin/env sh
# Pre-commit skylint: lint the git-changed files plus their
# reverse-dependency closure, against the committed baseline, and leave
# a machine-readable report behind for CI archiving.
#
#   scripts/lint_precommit.sh                 # report to /tmp
#   SKYLINT_REPORT=out.json scripts/lint_precommit.sh
#   scripts/lint_precommit.sh --check shapecheck   # extra args pass through
#
# Exit codes follow scripts/skylint.py: 0 clean, 1 findings, 2 usage.
set -e
cd "$(dirname "$0")/.."
exec python scripts/skylint.py --changed \
    --baseline skylint-baseline.json \
    --json-out "${SKYLINT_REPORT:-/tmp/skylint_precommit.json}" "$@"
