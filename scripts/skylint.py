#!/usr/bin/env python3
"""skylint driver: AST static analysis over the skypilot_tpu tree.

Usage::

    python scripts/skylint.py                  # whole package, all checks
    python scripts/skylint.py path [path ...]  # narrower roots
    python scripts/skylint.py --check lock-order --json
    python scripts/skylint.py --changed        # git-diff + rev-dep closure
    python scripts/skylint.py --json-out /tmp/skylint.json
    python scripts/skylint.py --baseline skylint-baseline.json
    python scripts/skylint.py --list-checks

Exit 0 = no un-suppressed findings (after baseline waivers); 1 =
findings (listed on stderr in human mode, on stdout as JSON with
--json — bench.py archives the JSON per round); 2 = usage error.
``--json-out`` writes the same JSON report to a file regardless of the
console mode — the CI artifact. ``--changed`` still parses and indexes
the whole tree (cross-module closures need it) but reports only
findings in files named by ``git diff`` plus their reverse-dependency
closure. ``--baseline`` waives findings matching a frozen
``{path, check}`` list (``--write-baseline`` regenerates it); an empty
baseline — the preferred state — waives nothing. Baseline entries whose
{path, check} no longer match any finding are reported as STALE on
stderr (and under ``baseline_stale`` in the JSON report) — the fix
landed, so the waiver only masks future regressions; regenerating with
``--write-baseline`` prunes them. Aggregate contracts
(dead env-var entries, docs table, metric-family coverage) only run
over the full default tree; explicit roots get per-file checks only.
See docs/static_analysis.md.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import subprocess
import sys
from typing import List, Set, Tuple

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from skypilot_tpu.lint import core  # noqa: E402


def _git_changed_files() -> List[str]:
    """Repo-relative .py paths touched per git: unstaged + staged +
    untracked. Any git failure is fatal — silently linting nothing
    would report a false-clean tree."""
    out: Set[str] = set()
    for args in (['git', 'diff', '--name-only'],
                 ['git', 'diff', '--name-only', '--cached'],
                 ['git', 'ls-files', '--others', '--exclude-standard']):
        proc = subprocess.run(args, cwd=_REPO_ROOT, capture_output=True,
                              text=True, check=True)
        out.update(line.strip() for line in proc.stdout.splitlines()
                   if line.strip().endswith('.py'))
    return sorted(out)


def _apply_baseline(run: 'core.LintRun', baseline_path: str
                    ) -> Tuple[List[dict], List[dict]]:
    """Waive findings matching baseline {path, check} entries (each
    entry waives any number of findings at that path+check — a frozen
    known-findings list for fixes that must be deferred). Returns
    (waived findings, stale entries): an entry whose path the run
    examined with that check armed — on a FULL-TREE run, since narrowed
    roots skip the aggregate contracts — but that matches no finding is
    stale; so is any entry whose path no longer exists on disk — the deferred fix
    landed (or the file moved) and the waiver now only masks future
    regressions at that path+check. Entries outside the reported scope
    (a ``--changed`` closure, an explicit narrower root) are never
    judged: staleness can only be decided by a run that actually
    looked. Stale entries are reported on stderr and pruned by a
    standalone ``--write-baseline`` run."""
    with open(baseline_path, encoding='utf-8') as f:
        entries = json.load(f).get('findings', [])
    keys = {(e['path'], e['check']) for e in entries}
    waived = [f for f in run.findings if (f.path, f.check) in keys]
    live = {(f.path, f.check) for f in waived}
    examined = {c.relpath for c in run.contexts}
    if run.report_paths is not None:
        examined &= run.report_paths
    ran = {c.name for c in run.checkers}
    if not run.full_tree:
        # Narrowed roots skip the aggregate contracts (dead env
        # entries, metric-family coverage), so "no finding" proves
        # nothing there — only a full-tree run may judge staleness.
        examined = set()
    # A path that no longer exists on disk is stale regardless of
    # scope: the file was deleted or renamed, and the waiver would
    # silently re-arm if the old path ever reappeared.
    missing = {p for p, _ in keys
               if not os.path.exists(os.path.join(_REPO_ROOT, p))}
    stale = [{'path': p, 'check': c}
             for p, c in sorted(keys - live)
             if (p in examined and c in ran) or p in missing]
    run.findings = [f for f in run.findings
                    if (f.path, f.check) not in keys]
    return [dataclasses.asdict(f) for f in waived], stale


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('roots', nargs='*',
                        help='files/dirs to lint (default: skypilot_tpu/)')
    parser.add_argument('--check', action='append', dest='checks',
                        help='run only this check (repeatable)')
    parser.add_argument('--json', action='store_true',
                        help='machine-readable output on stdout')
    parser.add_argument('--json-out', metavar='FILE',
                        help='also write the JSON report to FILE '
                             '(CI artifact)')
    parser.add_argument('--changed', action='store_true',
                        help='report only findings in git-changed files '
                             'plus their reverse-dependency closure')
    parser.add_argument('--no-cross-module', action='store_true',
                        help='pre-v2 same-file semantics (regression '
                             'pinning; not for CI)')
    parser.add_argument('--baseline', metavar='FILE',
                        help='waive findings matching this frozen '
                             '{path, check} list')
    parser.add_argument('--write-baseline', metavar='FILE',
                        help='write current findings as a baseline '
                             'and exit 0')
    parser.add_argument('--list-checks', action='store_true')
    args = parser.parse_args(argv)

    if args.list_checks:
        for cls in core.all_checkers():
            print(f'{cls.name}: {cls.description}')
        return 0

    if args.write_baseline and (args.baseline or args.changed):
        # Composing would regenerate from an already-waived /
        # closure-filtered finding set and silently drop every live
        # waiver outside it — the opposite of "prune stale entries".
        print('skylint: --write-baseline regenerates from a full '
              'un-waived run; drop --baseline/--changed',
              file=sys.stderr)
        return 2

    report_paths = None
    if args.changed:
        if args.roots:
            print('skylint: --changed implies the default full-tree '
                  'root', file=sys.stderr)
            return 2
        if args.no_cross_module:
            # The closure needs the project index; silently reporting
            # the full tree instead would be a scope lie.
            print('skylint: --changed requires cross-module analysis '
                  '(drop --no-cross-module)', file=sys.stderr)
            return 2
        try:
            changed = _git_changed_files()
        except (subprocess.CalledProcessError, OSError) as e:
            print(f'skylint: --changed requires git: {e}',
                  file=sys.stderr)
            return 2
        report_paths = changed  # closure expanded below, post-index

    try:
        run = core.run_skylint(roots=args.roots or None,
                               checks=args.checks,
                               cross_module=not args.no_cross_module)
    except ValueError as e:  # unknown --check name
        print(f'skylint: {e}', file=sys.stderr)
        return 2
    if report_paths is not None and run.project is not None:
        # Union with the raw changed set: a changed file that failed to
        # parse never entered the index, and dropping its parse-error
        # finding here would report a false-clean tree.
        closure = run.project.reverse_closure(report_paths) \
            | set(report_paths)
        run.report_paths = closure
        run.findings = [f for f in run.findings if f.path in closure]

    waived: List[dict] = []
    stale: List[dict] = []
    if args.baseline:
        try:
            waived, stale = _apply_baseline(run, args.baseline)
        except (OSError, ValueError, KeyError, TypeError,
                AttributeError) as e:
            # Shape errors too (a top-level list, a string entry):
            # anything malformed must be the friendly exit-2 message,
            # not a traceback.
            print(f'skylint: bad baseline {args.baseline}: '
                  f'{type(e).__name__}: {e}', file=sys.stderr)
            return 2
        for entry in stale:
            print(f'skylint: stale baseline entry {entry["path"]} '
                  f'({entry["check"]}): no matching finding — the '
                  f'waiver now only masks future regressions; a '
                  f'standalone --write-baseline run prunes it',
                  file=sys.stderr)

    report = run.to_json()
    if args.baseline:
        # Always present under --baseline (even when empty): the report
        # schema is a contract CI consumers key on.
        payload = json.loads(report)
        payload['baseline_waived'] = waived
        payload['baseline_stale'] = stale
        report = json.dumps(payload, indent=2)
    if args.json_out:
        with open(args.json_out, 'w', encoding='utf-8') as f:
            f.write(report + '\n')

    if args.write_baseline:
        uniq = [{'path': p, 'check': c} for p, c in
                sorted({(f.path, f.check) for f in run.findings})]
        payload = {'findings': uniq}
        with open(args.write_baseline, 'w', encoding='utf-8') as f:
            json.dump(payload, f, indent=2)
            f.write('\n')
        print(f'skylint: wrote baseline with {len(uniq)} entries to '
              f'{args.write_baseline}')
        return 0

    if args.json:
        print(report)
    else:
        stream = sys.stderr if run.findings else sys.stdout
        print(run.render_human(), file=stream)
    return 1 if run.findings else 0


if __name__ == '__main__':
    sys.exit(main())
