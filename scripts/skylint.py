#!/usr/bin/env python3
"""skylint driver: AST static analysis over the skypilot_tpu tree.

Usage::

    python scripts/skylint.py                  # whole package, all checks
    python scripts/skylint.py path [path ...]  # narrower roots
    python scripts/skylint.py --check lock-discipline --json
    python scripts/skylint.py --list-checks

Exit 0 = no un-suppressed findings; 1 = findings (listed on stderr in
human mode, on stdout as JSON with --json — bench.py archives the JSON
per round). Aggregate contracts (dead env-var entries, docs table,
metric-family coverage) only run over the full default tree; explicit
roots get per-file checks only. See docs/static_analysis.md.
"""
from __future__ import annotations

import argparse
import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from skypilot_tpu.lint import core  # noqa: E402


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument('roots', nargs='*',
                        help='files/dirs to lint (default: skypilot_tpu/)')
    parser.add_argument('--check', action='append', dest='checks',
                        help='run only this check (repeatable)')
    parser.add_argument('--json', action='store_true',
                        help='machine-readable output on stdout')
    parser.add_argument('--list-checks', action='store_true')
    args = parser.parse_args(argv)

    if args.list_checks:
        for cls in core.all_checkers():
            print(f'{cls.name}: {cls.description}')
        return 0

    try:
        run = core.run_skylint(roots=args.roots or None,
                               checks=args.checks)
    except ValueError as e:  # unknown --check name
        print(f'skylint: {e}', file=sys.stderr)
        return 2
    if args.json:
        print(run.to_json())
    else:
        stream = sys.stderr if run.findings else sys.stdout
        print(run.render_human(), file=stream)
    return 1 if run.findings else 0


if __name__ == '__main__':
    sys.exit(main())
