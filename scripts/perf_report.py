#!/usr/bin/env python3
"""Compare BENCH records: per-metric deltas with a regression gate.

Two modes over the repo's ``BENCH_r*.json`` perf records (each one run
of bench.py: ``{"n": .., "cmd", "rc", "tail", "parsed": {metrics}}``):

    python scripts/perf_report.py BENCH_r04.json BENCH_r05.json
    python scripts/perf_report.py --dir . --threshold 10

The two-file form prints per-metric old/new/delta and exits non-zero
when any metric regresses by more than ``--threshold`` percent — the
CI-adjacent "did this PR cost us throughput" gate. The ``--dir`` form
prints each metric's trajectory across every record (sorted by run
number) so a slow leak that no single adjacent pair trips on is still
visible.

Direction is inferred from the metric name: ``*_ms``/``*_s``/latency/
overhead metrics regress when they go UP, everything else (tok/s,
req/s, MFU) regresses when it goes DOWN. Only flat numeric metrics are
compared; nested sweeps, strings and config echoes (``*_len``,
``*_slots`` ...) are skipped.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
from typing import Dict, List, Tuple

# Config echoes recorded alongside results: identical-or-intentional
# between runs, never a perf signal.
_SKIP_SUFFIXES = ('_len', '_slots', '_params', '_params_b', '_concurrency',
                  'seq_len', '_status', '_note')
# Lower-is-better names: latency/duration suffixes plus overhead and
# error counts; everything else numeric is a rate or utilisation where
# higher wins. '_per_s' rates also end in '_s', so the rate check runs
# first; suffix-only matching keeps 'tokens_per_sec_per_chip' a rate.
_LOWER_BETTER_SUFFIXES = ('_ms', '_s')
_LOWER_BETTER_FRAGMENTS = ('overhead', 'errors')


def lower_is_better(name: str) -> bool:
    if name.endswith('_per_s'):
        return False  # a rate that happens to end in '_s'
    return (any(name.endswith(s) for s in _LOWER_BETTER_SUFFIXES)
            or any(f in name for f in _LOWER_BETTER_FRAGMENTS))


def load_record(path: str) -> dict:
    with open(path) as f:
        record = json.load(f)
    if 'parsed' not in record:
        raise ValueError(f'{path}: not a BENCH record (no "parsed" key)')
    return record


def numeric_metrics(parsed: dict) -> Dict[str, float]:
    out: Dict[str, float] = {}
    # A failed bench run records "parsed": null — contributes nothing.
    for name, value in (parsed or {}).items():
        if isinstance(value, bool) or not isinstance(value, (int, float)):
            continue
        if any(name.endswith(s) for s in _SKIP_SUFFIXES):
            continue
        out[name] = float(value)
    return out


def compare(old: dict, new: dict,
            threshold_pct: float) -> Tuple[List[list], List[str]]:
    """Rows of [metric, old, new, delta_pct, verdict] plus the names
    that regressed past the threshold."""
    old_m = numeric_metrics(old['parsed'])
    new_m = numeric_metrics(new['parsed'])
    rows: List[list] = []
    regressions: List[str] = []
    for name in sorted(set(old_m) & set(new_m)):
        a, b = old_m[name], new_m[name]
        if a == 0:
            delta = 0.0 if b == 0 else float('inf')
        else:
            delta = (b - a) / abs(a) * 100.0
        worse = -delta if lower_is_better(name) else delta
        if worse < -threshold_pct:
            verdict = 'REGRESSED'
            regressions.append(name)
        elif worse > threshold_pct:
            verdict = 'improved'
        else:
            verdict = 'ok'
        rows.append([name, a, b, delta, verdict])
    return rows, regressions


def find_records(directory: str) -> List[str]:
    paths = glob.glob(os.path.join(directory, 'BENCH_r*.json'))

    def run_number(path: str) -> int:
        try:
            return int(load_record(path).get('n', 0))
        except Exception:  # noqa: BLE001 — unreadable file sorts first
            return 0
    return sorted(paths, key=run_number)


def trajectory(paths: List[str]) -> List[list]:
    """[metric, v_r1, v_r2, ...] across the records, '-' where a run
    predates the metric."""
    records = [load_record(p) for p in paths]
    metrics = [numeric_metrics(r['parsed']) for r in records]
    names = sorted(set().union(*metrics)) if metrics else []
    return [[name] + [m.get(name, '-') for m in metrics]
            for name in names]


def _fmt(v) -> str:
    if isinstance(v, float):
        return f'{v:+.1f}%' if abs(v) < 1e7 else 'inf'
    return str(v)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__.split('\n')[0])
    parser.add_argument('records', nargs='*',
                        help='two BENCH_r*.json files: old new')
    parser.add_argument('--dir', default=None,
                        help='print the metric trajectory across every '
                             'BENCH_r*.json in this directory instead')
    parser.add_argument('--threshold', type=float, default=5.0,
                        help='regression gate in percent (two-file mode)')
    args = parser.parse_args(argv)

    if args.dir is not None:
        paths = find_records(args.dir)
        if len(paths) < 2:
            print(f'need >=2 BENCH_r*.json under {args.dir}',
                  file=sys.stderr)
            return 2
        labels = [f'r{load_record(p).get("n", "?")}' for p in paths]
        print('\t'.join(['metric'] + labels))
        for row in trajectory(paths):
            print('\t'.join(str(c) for c in row))
        return 0

    if len(args.records) != 2:
        parser.error('expected exactly two records (old new) or --dir')
    old, new = (load_record(p) for p in args.records)
    rows, regressions = compare(old, new, args.threshold)
    print(f'# {args.records[0]} (n={old.get("n")}) -> '
          f'{args.records[1]} (n={new.get("n")}), '
          f'threshold {args.threshold:.1f}%')
    print('\t'.join(['metric', 'old', 'new', 'delta', 'verdict']))
    for name, a, b, delta, verdict in rows:
        print(f'{name}\t{a}\t{b}\t{_fmt(delta)}\t{verdict}')
    if regressions:
        print(f'REGRESSIONS ({len(regressions)}): '
              + ', '.join(regressions), file=sys.stderr)
        return 1
    return 0


if __name__ == '__main__':
    sys.exit(main())
