#!/bin/bash
# Chip-gated round-5 measurements (VERDICT r4 #2/#3/#7), runnable the
# moment a TPU is reachable. The dev tunnel was down for the entire
# round-5 session, so these numbers could not be refreshed; the CPU-side
# fixes they validate are in-tree and unit-pinned:
#   #2 decode: scalar-sampling cache (models/decode.py) — expect the
#      standalone fresh-process decode back at >= 2300 tok/s/chip
#      @ 16 slots (r3 level) vs r4's 523.
#   #7 warm init: A/B restore-vs-reinit; enable $SKYTPU_WARM_INIT_CACHE
#      for launched jobs if restore wins on this link.
#   #3 serve: full bench serve phase — TTFT p50 target < 3 s at c24,
#      0 errors, equivalence estimate in the record.
set -x
cd "$(dirname "$0")/.."

# 1. Chip probe (a wedged tunnel HANGS; keep it killable).
timeout 120 python -c "
import jax, jax.numpy as jnp
x = jax.jit(lambda a: a + 1)(jnp.ones((4,)))
print('PROBE OK', jax.default_backend(), float(x.sum()))" \
    || { echo 'PROBE FAILED — chip unreachable'; exit 1; }

# 2. Standalone decode, fresh process.
O=$(mktemp)
timeout 600 python bench.py --phase decode --out "$O" && cat "$O" && echo

# 3. Warm-init A/B (run twice: first saves, second restores).
AB=$(mktemp -d)
for attempt in save restore; do
  timeout 900 python - "$AB" << 'PYEOF'
import dataclasses, sys, time
import jax
from skypilot_tpu.models.llama import PRESETS, LlamaModel
from skypilot_tpu.train import Trainer
config = dataclasses.replace(PRESETS['llama-1b'],
                             remat_policy='names_qkv')
trainer = Trainer(LlamaModel(config))
t0 = time.time()
state, src = trainer.init_with_warm_cache(sys.argv[1], jax.random.key(0))
int(jax.device_get(state.step))
print(f'init_with_warm_cache: {src} in {time.time() - t0:.1f}s')
PYEOF
done

# 4. Full bench (train first, per-phase budgets, wedge-proof).
timeout 2400 python bench.py 2>/tmp/tpu_bench.err | tail -1
