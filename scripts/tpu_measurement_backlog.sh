#!/bin/bash
# Chip-gated measurements (originally VERDICT r4 #2/#3/#7). MEASURED on
# 2026-07-31 when the tunnel came back — all targets met:
#   #2 decode: 2427.5 tok/s/chip @ 16 slots standalone (target >= 2300;
#      the r4 regression to 523 is fixed), TTFT 108.6 ms; full-bench
#      decode phase 2364.1.
#   #7 warm launch: 13.19 s total overhead (target < 15; r4 was 25),
#      decomposed: control plane 3.1, param init 4.56 (warm-init
#      snapshot restore), first step 5.52.
#   #3 serve: full sweep inside budget with the equivalence estimate in
#      the record (13.48 est. 7B-v6e8-equiv req/s vs the 11.42 anchor);
#      c24 = 0 errors / TTFT p50 2.81 s after the streaming-warmup +
#      burn-in fix; repeat runs ranged 2.2-2.8 s p50 (tunnel variance
#      ~±20-35% run-to-run — prefer the driver's official record).
#   Full wedge-proof bench: train 16,392.9 tok/s/chip @ 0.89B (57.6%
#      MFU, 3.711x baseline), all phases emitted, 535 s total.
# The script remains runnable for future refresh.
set -x
cd "$(dirname "$0")/.."

# 1. Chip probe (a wedged tunnel HANGS; keep it killable).
timeout 120 python -c "
import jax, jax.numpy as jnp
x = jax.jit(lambda a: a + 1)(jnp.ones((4,)))
print('PROBE OK', jax.default_backend(), float(x.sum()))" \
    || { echo 'PROBE FAILED — chip unreachable'; exit 1; }

# 2. Standalone decode, fresh process.
O=$(mktemp)
timeout 600 python bench.py --phase decode --out "$O" && cat "$O" && echo

# 3. Warm-init A/B (run twice: first saves, second restores).
AB=$(mktemp -d)
for attempt in save restore; do
  timeout 900 python - "$AB" << 'PYEOF'
import dataclasses, sys, time
import jax
from skypilot_tpu.models.llama import PRESETS, LlamaModel
from skypilot_tpu.train import Trainer
config = dataclasses.replace(PRESETS['llama-1b'],
                             remat_policy='names_qkv')
trainer = Trainer(LlamaModel(config))
t0 = time.time()
state, src = trainer.init_with_warm_cache(sys.argv[1], jax.random.key(0))
int(jax.device_get(state.step))
print(f'init_with_warm_cache: {src} in {time.time() - t0:.1f}s')
PYEOF
done

# 4. Full bench (train first, per-phase budgets, wedge-proof).
timeout 2400 python bench.py 2>/tmp/tpu_bench.err | tail -1
