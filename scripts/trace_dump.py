#!/usr/bin/env python3
"""Convert a request-lifecycle trace to a Perfetto/Chrome trace file.

The serving plane records a structured span tree per request
(utils/timeline.py trace ring) and serves it as JSON at
``/trace/<request-id>`` on both the replica and the load balancer (the
LB merges its own ``lb.proxy`` span with the replica tree). This tool
turns that JSON into Chrome trace-event format, loadable in Perfetto
(https://ui.perfetto.dev) or ``chrome://tracing``::

    python scripts/trace_dump.py http://127.0.0.1:PORT/trace/REQ_ID
    python scripts/trace_dump.py trace.json -o req.trace.json

The source is a URL (fetched) or a local file holding the ``/trace``
payload. Spans become complete ('X') events on one row per span name;
zero-duration spans (verify, first_token) become instant ('i') events
so they stay visible at any zoom. Span attrs ride along as event args.
Output defaults to ``<request_id>.trace.json`` in the cwd.
"""
from __future__ import annotations

import argparse
import json
import sys
import urllib.request
from typing import Any, Dict, List


def load_trace(source: str) -> Dict[str, Any]:
    if source.startswith(('http://', 'https://')):
        with urllib.request.urlopen(source, timeout=5.0) as resp:
            return json.loads(resp.read())
    with open(source, encoding='utf-8') as f:
        return json.load(f)


def to_chrome_events(trace: Dict[str, Any]) -> List[Dict[str, Any]]:
    """Chrome trace events for one /trace payload. One pid for the
    whole request; tid = span name so each lifecycle stage gets its own
    swim lane and repeated spans (prefill chunks, decode bursts) line
    up on one row."""
    rid = trace.get('request_id', '?')
    pid = trace.get('pid', 0)
    lanes: Dict[str, int] = {}
    events: List[Dict[str, Any]] = [{
        'name': 'process_name', 'ph': 'M', 'pid': pid,
        'args': {'name': f'request {rid}'},
    }]
    for span in trace.get('spans', ()):
        name = span.get('name', '?')
        tid = lanes.setdefault(name, len(lanes) + 1)
        start = span.get('start_us', 0)
        dur = max(0, span.get('end_us', start) - start)
        evt: Dict[str, Any] = {
            'name': name, 'pid': pid, 'tid': tid, 'ts': start,
            'cat': 'request',
        }
        if dur == 0:
            evt.update(ph='i', s='t')  # thread-scoped instant
        else:
            evt.update(ph='X', dur=dur)
        attrs = span.get('attrs')
        if attrs:
            evt['args'] = attrs
        events.append(evt)
    for name, tid in lanes.items():
        events.append({'name': 'thread_name', 'ph': 'M', 'pid': pid,
                       'tid': tid, 'args': {'name': name}})
    return events


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description='request trace JSON -> Perfetto/Chrome trace file')
    parser.add_argument('source',
                        help='/trace/<request-id> URL or a local JSON '
                        'file holding its payload')
    parser.add_argument('-o', '--output', default=None,
                        help='output path (default '
                        '<request_id>.trace.json)')
    args = parser.parse_args(argv)

    try:
        trace = load_trace(args.source)
    except (OSError, ValueError) as e:
        print(f'error: cannot load trace from {args.source!r}: {e}',
              file=sys.stderr)
        return 1
    if not isinstance(trace, dict) or 'spans' not in trace:
        print(f'error: {args.source!r} is not a /trace payload '
              "(missing 'spans')", file=sys.stderr)
        return 1

    events = to_chrome_events(trace)
    out = args.output or f"{trace.get('request_id', 'trace')}.trace.json"
    with open(out, 'w', encoding='utf-8') as f:
        json.dump({'traceEvents': events, 'displayTimeUnit': 'ms'}, f)
    n_spans = len(trace.get('spans', ()))
    state = 'complete' if trace.get('complete') else 'in-flight'
    print(f'{out}: {n_spans} spans ({state}, '
          f"dropped={trace.get('dropped_spans', 0)}) — open in "
          'https://ui.perfetto.dev')
    return 0


if __name__ == '__main__':
    sys.exit(main())
