#!/usr/bin/env python3
"""Lint: every metric registered in the tree follows the naming
convention ``skytpu_<subsystem>_<name>_<unit>``.

Thin shim over the skylint framework's ``metric-name`` checker
(skypilot_tpu/lint/checkers/metric_names.py) — the check moved there
when the repo grew a full static-analysis suite; this entry point keeps
the historical CLI contract (root argument, exit 0 clean / 1 with
violations listed on stderr). Run standalone::

    python scripts/check_metric_names.py [root]

or via the tier-1 tests (tests/test_metrics.py, tests/test_skylint.py).
Family coverage (EXPECTED_FAMILIES in the checker module) is only
enforced over the full default tree: a narrower root legitimately lacks
most families and must not fail on their absence.
"""
from __future__ import annotations

import os
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from skypilot_tpu.lint import core  # noqa: E402
from skypilot_tpu.lint.checkers.metric_names import (  # noqa: E402,F401
    EXPECTED_FAMILIES)


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    run = core.run_skylint(roots=args or None, checks=['metric-name'])
    if run.findings:
        print('metric naming violations '
              '(convention: skytpu_<subsystem>_<name>_<unit>):',
              file=sys.stderr)
        for f in run.findings:
            print(f'  {f.path}:{f.line}: {f.message}', file=sys.stderr)
        return 1
    print(f'check_metric_names: {len(run.contexts)} files clean')
    return 0


if __name__ == '__main__':
    sys.exit(main())
