#!/usr/bin/env python3
"""Lint: every metric registered in the tree follows the naming
convention ``skytpu_<subsystem>_<name>_<unit>``.

Two enforcement layers share one rule (``utils.metrics.validate_name``):
the registry raises at registration time (catches dynamic names), and
this script statically scans every ``counter(``/``gauge(``/
``histogram(`` call whose first argument is a string literal (catches
names on code paths tests never execute). Run standalone::

    python scripts/check_metric_names.py [root]

or via the tier-1 test (tests/test_metrics.py). Exit 0 = clean,
1 = violations (listed on stderr).
"""
from __future__ import annotations

import os
import re
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from skypilot_tpu.utils.metrics import validate_name  # noqa: E402

# First string-literal argument of a metric constructor call. DOTALL so
# calls wrapped onto the next line still match.
_CALL_RE = re.compile(
    r'\b(?:counter|gauge|histogram)\(\s*[\'"]([A-Za-z0-9_]+)[\'"]',
    re.DOTALL)


def scan_file(path: str) -> list:
    """[(line_number, name, error)] for convention violations."""
    with open(path, encoding='utf-8') as f:
        src = f.read()
    out = []
    for m in _CALL_RE.finditer(src):
        name = m.group(1)
        err = validate_name(name)
        if err:
            line = src.count('\n', 0, m.start()) + 1
            out.append((line, name, err))
    return out


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    root = args[0] if args else os.path.join(_REPO_ROOT, 'skypilot_tpu')
    violations = []
    n_files = 0
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != '__pycache__']
        for fn in filenames:
            if not fn.endswith('.py'):
                continue
            path = os.path.join(dirpath, fn)
            n_files += 1
            for line, name, err in scan_file(path):
                violations.append(
                    f'{os.path.relpath(path, _REPO_ROOT)}:{line}: {err}')
    if violations:
        print('metric naming violations '
              '(convention: skytpu_<subsystem>_<name>_<unit>):',
              file=sys.stderr)
        for v in violations:
            print(f'  {v}', file=sys.stderr)
        return 1
    print(f'check_metric_names: {n_files} files clean')
    return 0


if __name__ == '__main__':
    sys.exit(main())
