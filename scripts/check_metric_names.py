#!/usr/bin/env python3
"""Lint: every metric registered in the tree follows the naming
convention ``skytpu_<subsystem>_<name>_<unit>``.

Two enforcement layers share one rule (``utils.metrics.validate_name``):
the registry raises at registration time (catches dynamic names), and
this script statically scans every ``counter(``/``gauge(``/
``histogram(`` call whose first argument is a string literal (catches
names on code paths tests never execute). Run standalone::

    python scripts/check_metric_names.py [root]

or via the tier-1 test (tests/test_metrics.py). Exit 0 = clean,
1 = violations (listed on stderr).
"""
from __future__ import annotations

import os
import re
import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO_ROOT)

from skypilot_tpu.utils.metrics import validate_name  # noqa: E402

# First string-literal argument of a metric constructor call. DOTALL so
# calls wrapped onto the next line still match.
_CALL_RE = re.compile(
    r'\b(?:counter|gauge|histogram)\(\s*[\'"]([A-Za-z0-9_]+)[\'"]',
    re.DOTALL)

# Registration coverage: these metric FAMILIES are load-bearing (bench
# records, dashboards, docs tables reference them by prefix) — a
# refactor that renames them away silently breaks every consumer. The
# scan must find at least one registration per family or the lint
# fails, so "the family exists in the tree" is a tier-1 guarantee.
EXPECTED_FAMILIES = (
    'skytpu_serve_',      # scheduler/admission plane
    'skytpu_engine_',     # decode engine step profiling
    'skytpu_engine_kv_',  # paged-KV pool + prefix cache
    'skytpu_lb_',         # load balancer proxy series
)


def scan_file(path: str) -> tuple:
    """([(line_number, name, error)], [names]) for one file."""
    with open(path, encoding='utf-8') as f:
        src = f.read()
    out = []
    names = []
    for m in _CALL_RE.finditer(src):
        name = m.group(1)
        names.append(name)
        err = validate_name(name)
        if err:
            line = src.count('\n', 0, m.start()) + 1
            out.append((line, name, err))
    return out, names


def main(argv=None) -> int:
    args = argv if argv is not None else sys.argv[1:]
    # Family coverage is only meaningful over the full tree: a narrower
    # root (e.g. `... skypilot_tpu/utils`) legitimately lacks most
    # families and must not fail on their absence.
    check_families = not args
    root = args[0] if args else os.path.join(_REPO_ROOT, 'skypilot_tpu')
    violations = []
    n_files = 0
    all_names = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != '__pycache__']
        for fn in filenames:
            if not fn.endswith('.py'):
                continue
            path = os.path.join(dirpath, fn)
            n_files += 1
            file_violations, names = scan_file(path)
            all_names.extend(names)
            for line, name, err in file_violations:
                violations.append(
                    f'{os.path.relpath(path, _REPO_ROOT)}:{line}: {err}')
    if check_families:
        for family in EXPECTED_FAMILIES:
            if not any(n.startswith(family) for n in all_names):
                violations.append(
                    f'expected metric family {family}* has no '
                    f'registration under {root} (renamed away? update '
                    'EXPECTED_FAMILIES and every consumer)')
    if violations:
        print('metric naming violations '
              '(convention: skytpu_<subsystem>_<name>_<unit>):',
              file=sys.stderr)
        for v in violations:
            print(f'  {v}', file=sys.stderr)
        return 1
    print(f'check_metric_names: {n_files} files clean')
    return 0


if __name__ == '__main__':
    sys.exit(main())
