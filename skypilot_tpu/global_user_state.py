"""Persistent user state: clusters, history, enabled clouds.

Counterpart of reference ``sky/global_user_state.py`` (sqlite `clusters` /
`cluster_history` / kv tables, pickled handles; :40-111,548-606). The state
dir is ``$SKYTPU_STATE_DIR`` (default ``~/.skytpu``) so tests fully isolate.
"""
from __future__ import annotations

import enum
import json
import os
import pickle
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu.utils import common_utils

_DB_LOCK = threading.Lock()
_LOCAL = threading.local()
_INITIALIZED_PATHS: set = set()


def get_state_dir() -> str:
    d = os.environ.get('SKYTPU_STATE_DIR', '~/.skytpu')
    d = os.path.expanduser(d)
    os.makedirs(d, exist_ok=True)
    return d


def _db() -> sqlite3.Connection:
    """Thread-local connection: sharing one connection across threads lets
    execute/commit pairs interleave (commit A's half-done transaction from
    B). WAL + busy timeout make cross-connection writers serialize safely."""
    path = os.path.join(get_state_dir(), 'state.db')
    conns = getattr(_LOCAL, 'conns', None)
    if conns is None:
        conns = _LOCAL.conns = {}
    conn = conns.get(path)
    if conn is None:
        conn = sqlite3.connect(path, timeout=10.0)
        conn.execute('PRAGMA journal_mode=WAL')
        with _DB_LOCK:
            if path not in _INITIALIZED_PATHS:
                _create_tables(conn)
                _INITIALIZED_PATHS.add(path)
        conns[path] = conn
    return conn


def _create_tables(conn: sqlite3.Connection) -> None:
    conn.execute("""
        CREATE TABLE IF NOT EXISTS clusters (
            name TEXT PRIMARY KEY,
            launched_at INTEGER,
            handle BLOB,
            last_use TEXT,
            status TEXT,
            autostop_idle_minutes INTEGER DEFAULT -1,
            autostop_down INTEGER DEFAULT 0,
            owner TEXT,
            config_hash TEXT,
            metadata TEXT DEFAULT '{}'
        )""")
    conn.execute("""
        CREATE TABLE IF NOT EXISTS cluster_history (
            cluster_hash TEXT,
            name TEXT,
            num_hosts INTEGER,
            resources BLOB,
            launched_at INTEGER,
            duration_s INTEGER,
            usage_intervals BLOB,
            PRIMARY KEY (cluster_hash, launched_at)
        )""")
    conn.execute("""
        CREATE TABLE IF NOT EXISTS storage (
            name TEXT PRIMARY KEY,
            url TEXT,
            mode TEXT,
            launched_at INTEGER,
            last_use TEXT
        )""")
    conn.execute("""
        CREATE TABLE IF NOT EXISTS kv (
            key TEXT PRIMARY KEY,
            value TEXT
        )""")
    conn.commit()


class ClusterStatus(enum.Enum):
    """Reconciled cluster lifecycle state (reference sky/status_lib)."""
    INIT = 'INIT'          # provisioning or unknown/dirty
    UP = 'UP'              # provisioned + runtime healthy
    STOPPED = 'STOPPED'    # hosts stopped, disk kept

    def colored(self) -> str:
        color = {'INIT': '\x1b[33m', 'UP': '\x1b[32m',
                 'STOPPED': '\x1b[90m'}[self.value]
        return f'{color}{self.value}\x1b[0m'


# ---- clusters --------------------------------------------------------------
def add_or_update_cluster(cluster_name: str, handle: Any,
                          requested_resources: Optional[Any] = None,
                          ready: bool = False,
                          config_hash: Optional[str] = None) -> None:
    status = ClusterStatus.UP if ready else ClusterStatus.INIT
    now = int(time.time())
    db = _db()
    existing = get_cluster_from_name(cluster_name)
    launched_at = existing['launched_at'] if existing else now
    db.execute(
        """INSERT INTO clusters
           (name, launched_at, handle, last_use, status,
            autostop_idle_minutes, autostop_down, owner, config_hash, metadata)
           VALUES (?,?,?,?,?,
                   COALESCE((SELECT autostop_idle_minutes FROM clusters
                             WHERE name=?), -1),
                   COALESCE((SELECT autostop_down FROM clusters
                             WHERE name=?), 0),
                   ?,?,COALESCE((SELECT metadata FROM clusters
                                 WHERE name=?), '{}'))
           ON CONFLICT(name) DO UPDATE SET
               launched_at=excluded.launched_at, handle=excluded.handle,
               last_use=excluded.last_use, status=excluded.status,
               config_hash=COALESCE(excluded.config_hash, config_hash)
        """,
        (cluster_name, launched_at, pickle.dumps(handle),
         common_utils.get_user_name(), status.value,
         cluster_name, cluster_name,
         common_utils.get_user_hash(), config_hash, cluster_name))
    db.commit()
    if requested_resources is not None:
        _record_history(cluster_name, handle, requested_resources, launched_at)


def _record_history(cluster_name: str, handle: Any, resources: Any,
                    launched_at: int) -> None:
    db = _db()
    cluster_hash = f'{cluster_name}-{launched_at}'
    num_hosts = getattr(resources, 'num_hosts', 1)
    db.execute(
        """INSERT OR REPLACE INTO cluster_history
           (cluster_hash, name, num_hosts, resources, launched_at,
            duration_s, usage_intervals)
           VALUES (?,?,?,?,?,NULL,?)""",
        (cluster_hash, cluster_name, num_hosts, pickle.dumps(resources),
         launched_at, pickle.dumps([(launched_at, None)])))
    db.commit()


def update_cluster_status(cluster_name: str, status: ClusterStatus) -> None:
    db = _db()
    db.execute('UPDATE clusters SET status=? WHERE name=?',
               (status.value, cluster_name))
    db.commit()


def update_last_use(cluster_name: str) -> None:
    db = _db()
    db.execute('UPDATE clusters SET last_use=? WHERE name=?',
               (f'{common_utils.get_user_name()}@{int(time.time())}',
                cluster_name))
    db.commit()


def set_cluster_autostop(cluster_name: str, idle_minutes: int,
                         down: bool) -> None:
    db = _db()
    db.execute(
        'UPDATE clusters SET autostop_idle_minutes=?, autostop_down=? '
        'WHERE name=?', (idle_minutes, int(down), cluster_name))
    db.commit()


def remove_cluster(cluster_name: str, terminate: bool) -> None:
    db = _db()
    if terminate:
        # Close the usage interval in history.
        row = db.execute(
            'SELECT launched_at FROM clusters WHERE name=?',
            (cluster_name,)).fetchone()
        if row:
            db.execute(
                'UPDATE cluster_history SET duration_s=? '
                'WHERE cluster_hash=?',
                (int(time.time()) - row[0], f'{cluster_name}-{row[0]}'))
        db.execute('DELETE FROM clusters WHERE name=?', (cluster_name,))
    else:
        db.execute(
            'UPDATE clusters SET status=? WHERE name=?',
            (ClusterStatus.STOPPED.value, cluster_name))
    db.commit()


def _row_to_record(row) -> Dict[str, Any]:
    (name, launched_at, handle, last_use, status, idle, down, owner,
     config_hash, metadata) = row
    return {
        'name': name,
        'launched_at': launched_at,
        'handle': pickle.loads(handle) if handle else None,
        'last_use': last_use,
        'status': ClusterStatus(status),
        'autostop': idle,
        'to_down': bool(down),
        'owner': owner,
        'config_hash': config_hash,
        'metadata': json.loads(metadata or '{}'),
    }


def get_cluster_from_name(
        cluster_name: Optional[str]) -> Optional[Dict[str, Any]]:
    if cluster_name is None:
        return None
    row = _db().execute('SELECT * FROM clusters WHERE name=?',
                        (cluster_name,)).fetchone()
    return _row_to_record(row) if row else None


def get_clusters() -> List[Dict[str, Any]]:
    rows = _db().execute(
        'SELECT * FROM clusters ORDER BY launched_at DESC').fetchall()
    return [_row_to_record(r) for r in rows]


def get_cluster_history() -> List[Dict[str, Any]]:
    rows = _db().execute(
        'SELECT cluster_hash, name, num_hosts, resources, launched_at, '
        'duration_s FROM cluster_history ORDER BY launched_at DESC').fetchall()
    out = []
    for (cluster_hash, name, num_hosts, resources, launched_at,
         duration_s) in rows:
        out.append({
            'cluster_hash': cluster_hash,
            'name': name,
            'num_hosts': num_hosts,
            'resources': pickle.loads(resources) if resources else None,
            'launched_at': launched_at,
            'duration_s': duration_s,
        })
    return out


# ---- storage ---------------------------------------------------------------
def add_or_update_storage(name: str, url: str, mode: str) -> None:
    """Record a bucket a task has synced/mounted (reference
    global_user_state storage table :57-111)."""
    import time as time_lib
    db = _db()
    db.execute(
        'INSERT INTO storage (name, url, mode, launched_at, last_use) '
        'VALUES (?,?,?,?,?) ON CONFLICT(name) DO UPDATE SET '
        'url=excluded.url, mode=excluded.mode, last_use=excluded.last_use',
        (name, url, mode, int(time_lib.time()),
         common_utils_last_command()))
    db.commit()


def get_storages() -> List[Dict[str, Any]]:
    rows = _db().execute('SELECT name, url, mode, launched_at, last_use '
                         'FROM storage ORDER BY launched_at').fetchall()
    return [{'name': n, 'url': u, 'mode': m, 'launched_at': t,
             'last_use': lu} for n, u, m, t, lu in rows]


def remove_storage(name: str) -> None:
    db = _db()
    db.execute('DELETE FROM storage WHERE name=?', (name,))
    db.commit()


def common_utils_last_command() -> str:
    import sys
    return ' '.join(sys.argv[:4])


# ---- kv --------------------------------------------------------------------
def set_kv(key: str, value: str) -> None:
    db = _db()
    db.execute('INSERT OR REPLACE INTO kv (key, value) VALUES (?,?)',
               (key, value))
    db.commit()


def get_kv(key: str) -> Optional[str]:
    row = _db().execute('SELECT value FROM kv WHERE key=?', (key,)).fetchone()
    return row[0] if row else None


def set_enabled_clouds(clouds: List[str]) -> None:
    set_kv('enabled_clouds', json.dumps(sorted(clouds)))


def get_enabled_clouds() -> Optional[List[str]]:
    v = get_kv('enabled_clouds')
    return json.loads(v) if v is not None else None
