"""Task DAGs (chains, for pipelines of tasks).

Counterpart of reference ``sky/dag.py`` (networkx-backed Dag + ``with
sky.Dag():`` context). Kept dependency-light: adjacency dicts instead of
networkx — the optimizer only needs topological order and chain detection.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Set

from skypilot_tpu import task as task_lib


class Dag:
    """A DAG of Tasks. Supports `with Dag() as dag: Task(...)` registration."""

    def __init__(self, name: Optional[str] = None):
        self.name = name
        self.tasks: List[task_lib.Task] = []
        self._edges: Dict[task_lib.Task, Set[task_lib.Task]] = {}
        self._redges: Dict[task_lib.Task, Set[task_lib.Task]] = {}
        # Managed-jobs metadata:
        self.policy_applied: bool = False

    def add(self, task: task_lib.Task) -> None:
        if task in self.tasks:
            return
        self.tasks.append(task)
        self._edges.setdefault(task, set())
        self._redges.setdefault(task, set())

    def remove(self, task: task_lib.Task) -> None:
        self.tasks.remove(task)
        for nbrs in self._edges.values():
            nbrs.discard(task)
        for nbrs in self._redges.values():
            nbrs.discard(task)
        self._edges.pop(task, None)
        self._redges.pop(task, None)

    def add_edge(self, op1: task_lib.Task, op2: task_lib.Task) -> None:
        self.add(op1)
        self.add(op2)
        self._edges[op1].add(op2)
        self._redges[op2].add(op1)

    def __len__(self) -> int:
        return len(self.tasks)

    def __enter__(self) -> 'Dag':
        push_dag(self)
        return self

    def __exit__(self, *args) -> None:
        pop_dag()

    def topological_order(self) -> List[task_lib.Task]:
        indeg = {t: len(self._redges[t]) for t in self.tasks}
        # Stable order: seed queue in insertion order.
        queue = [t for t in self.tasks if indeg[t] == 0]
        out: List[task_lib.Task] = []
        while queue:
            t = queue.pop(0)
            out.append(t)
            for nbr in sorted(self._edges[t], key=self.tasks.index):
                indeg[nbr] -= 1
                if indeg[nbr] == 0:
                    queue.append(nbr)
        if len(out) != len(self.tasks):
            raise ValueError('DAG has a cycle')
        return out

    def is_chain(self) -> bool:
        if len(self.tasks) <= 1:
            return True
        nonzero_out = [t for t in self.tasks if self._edges[t]]
        return all(len(self._edges[t]) <= 1 for t in self.tasks) and all(
            len(self._redges[t]) <= 1 for t in self.tasks) and (
                len(nonzero_out) == len(self.tasks) - 1)

    def __repr__(self) -> str:
        return f'Dag({self.name!r}, tasks={[t.name for t in self.tasks]})'


_dag_stack = threading.local()


def push_dag(dag: Dag) -> None:
    stack = getattr(_dag_stack, 'stack', None)
    if stack is None:
        stack = []
        _dag_stack.stack = stack
    stack.append(dag)


def pop_dag() -> Optional[Dag]:
    stack = getattr(_dag_stack, 'stack', None)
    if stack:
        return stack.pop()
    return None


def get_current_dag() -> Optional[Dag]:
    stack = getattr(_dag_stack, 'stack', None)
    if stack:
        return stack[-1]
    return None
