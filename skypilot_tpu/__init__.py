"""skypilot_tpu: a TPU-native orchestration + training/serving framework.

A from-scratch rebuild of the capabilities of SkyPilot (reference layer map in
SURVEY.md §1) designed TPU-first: TPU pod slices are first-class schedulable
units, the on-cluster runtime is Ray-free (per-host agents + jax.distributed
rendezvous over ICI/DCN), and the compute path (models/, parallel/, ops/) is
idiomatic JAX/XLA/Pallas.

Public API mirrors the reference's surface (sky/__init__.py:83-222):
``launch/exec/status/start/stop/down/autostop/queue/cancel/tail_logs/optimize``
plus the ``Task``/``Resources``/``Dag`` object layer.
"""
from skypilot_tpu.accelerators import TpuSlice
from skypilot_tpu.dag import Dag
from skypilot_tpu.resources import Resources
from skypilot_tpu.task import Task

__version__ = '0.1.0'

__all__ = [
    'Dag',
    'Resources',
    'Task',
    'TpuSlice',
    '__version__',
]


def __getattr__(name):  # lazy: heavy modules only on use
    _lazy = {
        'launch': ('skypilot_tpu.execution', 'launch'),
        'exec': ('skypilot_tpu.execution', 'exec_'),
        'optimize': ('skypilot_tpu.optimizer', 'optimize'),
        'status': ('skypilot_tpu.core', 'status'),
        'start': ('skypilot_tpu.core', 'start'),
        'stop': ('skypilot_tpu.core', 'stop'),
        'down': ('skypilot_tpu.core', 'down'),
        'autostop': ('skypilot_tpu.core', 'autostop'),
        'queue': ('skypilot_tpu.core', 'queue'),
        'cancel': ('skypilot_tpu.core', 'cancel'),
        'tail_logs': ('skypilot_tpu.core', 'tail_logs'),
        'job_status': ('skypilot_tpu.core', 'job_status'),
        'serve_up': ('skypilot_tpu.serve.core', 'up'),
        'serve_status': ('skypilot_tpu.serve.core', 'status'),
        'serve_down': ('skypilot_tpu.serve.core', 'down'),
        'ServiceSpec': ('skypilot_tpu.serve.service_spec', 'ServiceSpec'),
    }
    if name in _lazy:
        import importlib
        module, attr = _lazy[name]
        return getattr(importlib.import_module(module), attr)
    raise AttributeError(f'module {__name__!r} has no attribute {name!r}')
