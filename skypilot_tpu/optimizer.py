"""Optimizer: choose (cloud, region, slice/instance) per task.

Counterpart of reference ``sky/optimizer.py`` (Optimizer.optimize:107, DP on
chains:410, candidate fill-in with blocked-resource filtering:1142-1309).
TPU-native changes:

- Objectives: COST ($/h), TIME (estimated runtime via a roofline-ish model on
  slice FLOPs), and PERF_PER_DOLLAR (bf16 TFLOPs per $/h) — the last is the
  natural TPU ranking because slice generations differ 4-9x in per-chip
  throughput at different prices.
- Every task gets an *ordered candidate list* (region-level, cheapest/best
  first, blocklist-filtered); the failover provisioner walks it without
  re-running the optimizer from scratch (the reference re-optimizes per retry,
  cloud_vm_ray_backend.py:2163).
- Chain DAGs use DP with inter-region egress cost on edges (ILP is not needed
  until non-chain DAGs exist; reference gates the same way, optimizer.py:410).
"""
from __future__ import annotations

import enum
from typing import Any, Dict, Iterable, List, Optional, Tuple

from skypilot_tpu import check as check_lib
from skypilot_tpu import clouds as clouds_lib
from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import task as task_lib
from skypilot_tpu.utils import timeline


class OptimizeTarget(enum.Enum):
    COST = 'cost'
    TIME = 'time'
    PERF_PER_DOLLAR = 'perf_per_dollar'


# Assumed cross-cloud/cross-region transfer bandwidth for the TIME
# objective's egress edge weights (conservative DCN-ish figure).
_EGRESS_BANDWIDTH_GBPS = 8.0


class Candidate:
    """A concrete launchable choice with its score breakdown."""

    def __init__(self, resources: resources_lib.Resources, cost_per_hour: float,
                 est_time_s: Optional[float], perf_per_dollar: float):
        self.resources = resources
        self.cost_per_hour = cost_per_hour
        self.est_time_s = est_time_s
        self.perf_per_dollar = perf_per_dollar

    def sort_key(self, target: OptimizeTarget) -> Tuple:
        if target == OptimizeTarget.COST:
            return (self.cost_per_hour,)
        if target == OptimizeTarget.TIME:
            return (self.est_time_s if self.est_time_s is not None else
                    float('inf'), self.cost_per_hour)
        return (-self.perf_per_dollar, self.cost_per_hour)

    def __repr__(self) -> str:
        return (f'Candidate({self.resources}, ${self.cost_per_hour:.2f}/h, '
                f'{self.perf_per_dollar:.0f} TFLOPs/$)')


def _estimate_time_s(resources: resources_lib.Resources,
                     task: task_lib.Task) -> Optional[float]:
    """Runtime estimate: user-provided FLOPs over slice peak (w/ 40% MFU)."""
    total_flops = getattr(task, 'estimated_total_flops', None)
    if total_flops is None or resources.tpu is None:
        return None
    peak = resources.tpu.total_bf16_tflops * 1e12
    return float(total_flops) / (peak * 0.4)


def _enumerate_candidates(
    task: task_lib.Task,
    resources: resources_lib.Resources,
    enabled_clouds: List[str],
    blocked_resources: Iterable[resources_lib.Resources],
) -> Tuple[List[Candidate], List[str]]:
    """Expand one Resources filter into priced region-level candidates."""
    hints: List[str] = []
    clouds_to_try = ([resources.cloud] if resources.cloud is not None
                     else enabled_clouds)
    out: List[Candidate] = []
    for cloud_name in clouds_to_try:
        if cloud_name not in enabled_clouds:
            hints.append(f'{cloud_name}: not enabled (run `skytpu check`)')
            continue
        cloud = clouds_lib.get_cloud(cloud_name)
        feasible = cloud.get_feasible_resources(resources)
        if not feasible.resources:
            if feasible.hint:
                hints.append(f'{cloud_name}: {feasible.hint}')
            continue
        for launchable in feasible.resources:
            for region in cloud.regions_for(launchable):
                candidate_res = launchable.copy(
                    region=region, zone=launchable.zone)
                if any(candidate_res.should_be_blocked_by(b)
                       for b in blocked_resources):
                    continue
                try:
                    cost = cloud.hourly_cost(candidate_res, region=region)
                except exceptions.ResourcesUnavailableError as e:
                    hints.append(str(e))
                    continue
                tpu = candidate_res.tpu
                ppd = (tpu.total_bf16_tflops / cost
                       if tpu is not None and cost > 0 else 0.0)
                out.append(
                    Candidate(candidate_res, cost,
                              _estimate_time_s(candidate_res, task), ppd))
    return out, hints


def _print_candidate_table(task: task_lib.Task, candidates: List[Candidate],
                           target: OptimizeTarget) -> None:
    import tabulate  # local import: CLI-path dependency only
    rows = []
    for i, c in enumerate(candidates[:8]):
        r = c.resources
        tpu = r.tpu
        rows.append([
            '*' if i == 0 else '',
            r.cloud,
            tpu.name if tpu else r.instance_type,
            f'{tpu.num_hosts}' if tpu else '1',
            tpu.topology_str if tpu else '-',
            r.region,
            '[Spot]' if r.use_spot else '',
            f'$ {c.cost_per_hour:.2f}',
            f'{c.perf_per_dollar:,.0f}' if c.perf_per_dollar else '-',
        ])
    name = task.name or '<unnamed>'
    print(f'Optimizer: task {name!r} candidates '
          f'(objective: {target.value}):')
    print(tabulate.tabulate(
        rows, headers=['', 'CLOUD', 'TARGET', 'HOSTS', 'ICI', 'REGION', '',
                       '$/HR', 'TFLOPS/$']))


@timeline.event
def optimize(
    dag_or_task,
    minimize: OptimizeTarget = OptimizeTarget.COST,
    blocked_resources: Optional[Iterable[resources_lib.Resources]] = None,
    quiet: bool = False,
    raise_error: bool = True,
) -> 'dag_lib.Dag':
    """Assign best_resources (+ ordered candidates) to every task."""
    if isinstance(dag_or_task, task_lib.Task):
        dag = dag_lib.Dag()
        dag.add(dag_or_task)
    else:
        dag = dag_or_task
    blocked = list(blocked_resources or [])
    enabled_clouds = check_lib.get_cached_enabled_clouds_or_refresh()

    per_task: Dict[task_lib.Task, List[Candidate]] = {}
    for task in dag.topological_order():
        all_cands: List[Candidate] = []
        all_hints: List[str] = []
        for resources in task.resources:
            cands, hints = _enumerate_candidates(
                task, resources, enabled_clouds, blocked)
            all_hints.extend(hints)
            if task.resources_ordered and cands:
                # First satisfiable filter wins outright.
                cands.sort(key=lambda c: c.sort_key(minimize))
                all_cands = cands
                break
            all_cands.extend(cands)
        if not task.resources_ordered:
            all_cands.sort(key=lambda c: c.sort_key(minimize))
        if not all_cands:
            msg = (f'No launchable resources for task {task.name!r}. '
                   + ('; '.join(all_hints) if all_hints else
                      'All candidates were filtered out.'))
            if raise_error:
                raise exceptions.ResourcesUnavailableError(msg)
            per_task[task] = []
            continue
        per_task[task] = all_cands

    if len(dag.tasks) > 1 and dag.is_chain():
        _assign_chain_dp(dag, per_task, minimize)
    elif len(dag.tasks) > 1:
        _assign_general_bnb(dag, per_task, minimize)
    else:
        for task, cands in per_task.items():
            if cands:
                task.best_resources = cands[0].resources
                task.estimated_cost_per_hour = cands[0].cost_per_hour

    for task, cands in per_task.items():
        task.candidate_resources = [c.resources for c in cands]
        if not quiet and cands:
            _print_candidate_table(task, cands, minimize)
    return dag


def _use_total_cost(order: List[task_lib.Task],
                    per_task: Dict[task_lib.Task, List[Candidate]],
                    target: OptimizeTarget) -> bool:
    """COST node weights can be total dollars (est_hours * $/h) only when
    EVERY candidate has a time estimate — mixing total-$ and $/h weights
    in one min() would favor whichever is numerically smaller, not
    cheaper. Shared by the chain DP and the general-DAG solver."""
    return (target == OptimizeTarget.COST and all(
        c.est_time_s is not None for t in order for c in per_task[t]))


def _greedy_assign(order: List[task_lib.Task],
                   per_task: Dict[task_lib.Task, List[Candidate]]) -> None:
    """Per-task first-candidate assignment (the fallback when joint
    optimization is impossible or not worth it)."""
    for task in order:
        cands = per_task[task]
        if cands:
            task.best_resources = cands[0].resources
            task.estimated_cost_per_hour = cands[0].cost_per_hour


def _assign_chain_dp(dag: 'dag_lib.Dag',
                     per_task: Dict[task_lib.Task, List[Candidate]],
                     target: OptimizeTarget) -> None:
    """DP over a chain: per-node objective + inter-region egress on edges.

    Mirrors reference _optimize_by_dp (sky/optimizer.py:410); egress model is
    $/GB between (cloud, region) pairs with task.estimated_output_gb.
    """
    order = dag.topological_order()
    use_total_cost = _use_total_cost(order, per_task, target)
    if any(not per_task[t] for t in order):
        # raise_error=False path: a task with zero candidates makes the chain
        # unassignable — fall back to greedy per-task assignment for the
        # tasks that do have candidates instead of crashing.
        _greedy_assign(order, per_task)
        return
    # dp[i][j] = (score, parent_index) for candidate j of task i.
    dp: List[List[Tuple[float, Optional[int]]]] = []
    for i, task in enumerate(order):
        cands = per_task[task]
        row: List[Tuple[float, Optional[int]]] = []
        for j, cand in enumerate(cands):
            # Node weight, in the objective's own unit. For COST a one-shot
            # egress fee ($) is only comparable to a *total* run cost, so
            # when the task has a time estimate the node weight becomes
            # est_hours * $/h (total $); otherwise egress edges are left
            # unweighted rather than summing $/h with $.
            own = cand.sort_key(target)[0]
            if use_total_cost:
                own = cand.cost_per_hour * cand.est_time_s / 3600.0
            if i == 0:
                row.append((own, None))
                continue
            prev_task = order[i - 1]
            best: Tuple[float, Optional[int]] = (float('inf'), None)
            for pj, prev_cand in enumerate(per_task[prev_task]):
                # Edge weight in the objective's unit: total dollars for
                # COST (only when node weights are total dollars too),
                # transfer seconds for TIME. PERF_PER_DOLLAR (an hourly
                # ratio) admits no coherent one-shot conversion, so its
                # edges stay unweighted. (_edge_weight is shared with the
                # general-DAG solver.)
                egress = _edge_weight(prev_task, prev_cand, cand, target,
                                      use_total_cost)
                total = dp[i - 1][pj][0] + own + egress
                if total < best[0]:
                    best = (total, pj)
            row.append(best)
        dp.append(row)
    # Backtrack.
    last = min(range(len(dp[-1])), key=lambda j: dp[-1][j][0])
    choice = last
    for i in range(len(order) - 1, -1, -1):
        task = order[i]
        cand = per_task[task][choice]
        task.best_resources = cand.resources
        task.estimated_cost_per_hour = cand.cost_per_hour
        parent = dp[i][choice][1]
        if parent is not None:
            choice = parent


def _edge_weight(prev_task: task_lib.Task, prev_cand: Candidate,
                 cand: Candidate, target: OptimizeTarget,
                 use_total_cost: bool) -> float:
    """Egress weight of one DAG edge, in the objective's unit (same
    semantics as the chain DP's inline computation)."""
    out_gb = getattr(prev_task, 'estimated_output_gb', 0.0) or 0.0
    if not out_gb:
        return 0.0
    src = prev_cand.resources
    dst = cand.resources
    cloud = clouds_lib.get_cloud(src.cloud)
    egress_usd = out_gb * cloud.egress_cost_per_gb(
        dst.cloud, dst.region or '', src.region)
    if use_total_cost:
        return egress_usd
    if target == OptimizeTarget.TIME and egress_usd > 0:
        return out_gb * 8 / _EGRESS_BANDWIDTH_GBPS
    return 0.0


def _assign_general_bnb(dag: 'dag_lib.Dag',
                        per_task: Dict[task_lib.Task, List[Candidate]],
                        target: OptimizeTarget) -> None:
    """Exact assignment for general (non-chain) DAGs.

    Where the reference reaches for a PuLP ILP (sky/optimizer.py:471),
    this uses dependency-free branch-and-bound over the topological order:
    node weights + egress edge weights decompose per choice, and the
    admissible bound (sum of per-task minima for unassigned tasks) prunes
    aggressively. Real task DAGs are small (<=10 tasks, tens of
    candidates), so this is exact; pathological sizes fall back to greedy.
    """
    order = dag.topological_order()
    if any(not per_task[t] for t in order):
        _greedy_assign(order, per_task)
        return
    idx = {t: i for i, t in enumerate(order)}
    parents = [[p for p in order if t in dag._edges[p]] for t in order]
    size_product = 1.0
    for t in order:
        size_product *= max(1, len(per_task[t]))
    edge_entries = sum(
        len(per_task[p]) * len(per_task[t])
        for i, t in enumerate(order) for p in parents[i])
    if size_product > 5e7 or edge_entries > 5e6:
        # Genuinely huge: greedy beats an exact stall (and a multi-GB
        # edge-weight table).
        _greedy_assign(order, per_task)
        return

    use_total_cost = _use_total_cost(order, per_task, target)

    # Precompute every weight once: the DFS revisits (task, candidate)
    # and (parent-cand, cand) pairs many times, and each edge weight does
    # catalog lookups — recomputing inside the search turns a weakly
    # pruned instance into an optimizer stall.
    node_w: List[List[float]] = []
    for t in order:
        row = []
        for c in per_task[t]:
            own = c.sort_key(target)[0]
            if use_total_cost:
                own = c.cost_per_hour * c.est_time_s / 3600.0
            row.append(own)
        node_w.append(row)
    # edge_w[i][p_local][pj][j]: parent p (local index among parents[i]),
    # parent candidate pj, own candidate j.
    edge_w: List[List[List[List[float]]]] = []
    for i, t in enumerate(order):
        per_parent = []
        for p in parents[i]:
            per_parent.append([
                [_edge_weight(p, pc, c, target, use_total_cost)
                 for c in per_task[t]]
                for pc in per_task[p]
            ])
        edge_w.append(per_parent)

    # Admissible remainder bound: best node weight per remaining task
    # (edges are nonnegative).
    suffix_min = [0.0] * (len(order) + 1)
    for i in range(len(order) - 1, -1, -1):
        suffix_min[i] = suffix_min[i + 1] + min(node_w[i])

    # Seed with the greedy assignment: guarantees a valid answer even when
    # every weight is inf (e.g. TIME objective with missing estimates —
    # the bound would otherwise prune the entire search).
    best_choice: List[int] = [0] * len(order)
    best_cost = 0.0
    for i in range(len(order)):
        best_cost += node_w[i][0] + sum(
            edge_w[i][pl][0][0] for pl in range(len(parents[i])))
    choice: List[int] = []

    def dfs(i: int, acc: float) -> None:
        nonlocal best_cost, best_choice
        if acc + suffix_min[i] >= best_cost:
            return
        if i == len(order):
            best_cost = acc
            best_choice = list(choice)
            return
        parent_choices = [choice[idx[p]] for p in parents[i]]
        scored = []
        for j in range(len(per_task[order[i]])):
            w = node_w[i][j]
            for pl, pj in enumerate(parent_choices):
                w += edge_w[i][pl][pj][j]
            scored.append((w, j))
        scored.sort()  # try promising branches first for tight bounds
        for w, j in scored:
            choice.append(j)
            dfs(i + 1, acc + w)
            choice.pop()

    dfs(0, 0.0)
    for i, task in enumerate(order):
        cand = per_task[task][best_choice[i]]
        task.best_resources = cand.resources
        task.estimated_cost_per_hour = cand.cost_per_hour
