"""Async request SDK over the API server.

Counterpart of reference ``sky/client/sdk.py`` (every call POSTs a payload
and returns a request id :300; ``get``/``stream_and_get`` fetch results
:1456-1512; local server autostart :1676-1786). stdlib http.client only.
"""
from __future__ import annotations

import http.client
import json
import os
import subprocess
import sys
import time
from typing import Any, Dict, List, Optional
from urllib.parse import urlparse

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state

DEFAULT_SERVER_URL = 'http://127.0.0.1:46580'


def server_url() -> str:
    return os.environ.get('SKYTPU_API_SERVER_URL', DEFAULT_SERVER_URL)


def is_remote_server() -> bool:
    """A server NOT on this machine: workdirs must be uploaded, not
    referenced by local path."""
    host = urlparse(server_url()).hostname or ''
    return host not in ('127.0.0.1', 'localhost', '::1')


def _headers() -> Dict[str, str]:
    headers: Dict[str, str] = {}
    token = os.environ.get('SKYTPU_API_TOKEN')
    if token:
        headers['Authorization'] = f'Bearer {token}'
    user = os.environ.get('SKYTPU_USER')
    if not user:
        import getpass
        try:
            user = getpass.getuser()
        except (KeyError, OSError):
            user = 'anonymous'
    headers['X-Skytpu-User'] = user
    return headers


def _conn() -> http.client.HTTPConnection:
    parsed = urlparse(server_url())
    return http.client.HTTPConnection(parsed.hostname,
                                      parsed.port or 80, timeout=3700)


def _call(method: str, path: str,
          body: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
    conn = _conn()
    try:
        payload = json.dumps(body).encode() if body is not None else None
        headers = dict(_headers())
        if payload:
            headers['Content-Type'] = 'application/json'
        conn.request(method, path, body=payload, headers=headers)
        resp = conn.getresponse()
        data = json.loads(resp.read() or b'{}')
        if resp.status >= 400:
            raise exceptions.ApiServerConnectionError(
                f'{method} {path}: {resp.status} {data.get("error")}')
        return data
    except (ConnectionRefusedError, OSError) as e:
        raise exceptions.ApiServerConnectionError(
            f'Cannot reach API server at {server_url()}: {e}. '
            'Run `skytpu api start` (or python -m '
            'skypilot_tpu.server.server).') from e
    finally:
        conn.close()


# ---- async request API -----------------------------------------------------
def submit(op: str, payload: Dict[str, Any]) -> str:
    return _call('POST', f'/api/v1/{op}', payload)['request_id']


def get(request_id: str, timeout_s: float = 3600) -> Any:
    out = _call('GET',
                f'/api/v1/get?request_id={request_id}'
                f'&timeout_s={timeout_s}')
    if out['status'] == 'FAILED':
        raise exceptions.SkyTpuError(
            f'Request {request_id} failed: {out.get("error")}')
    if out['status'] == 'CANCELLED':
        raise exceptions.RequestCancelled(f'Request {request_id} cancelled')
    if out.get('error') == 'timeout':
        raise TimeoutError(f'Request {request_id} still '
                           f'{out["status"]} after {timeout_s}s')
    return out['result']


def stream(request_id: str, out=None) -> None:
    """Stream the request's log to ``out`` until it finishes."""
    out = out or sys.stdout
    conn = _conn()
    try:
        conn.request('GET', f'/api/v1/stream?request_id={request_id}',
                     headers=_headers())
        resp = conn.getresponse()
        if resp.status >= 400:
            data = resp.read().decode(errors='replace')
            raise exceptions.ApiServerConnectionError(
                f'stream {request_id}: {resp.status} {data[:300]}')
        while True:
            data = resp.read(4096)
            if not data:
                break
            out.write(data.decode(errors='replace'))
            out.flush()
    finally:
        conn.close()


def stream_and_get(request_id: str, out=None) -> Any:
    stream(request_id, out)
    return get(request_id)


def api_cancel(request_id: str) -> bool:
    return _call('POST', '/api/v1/requests/cancel',
                 {'request_id': request_id})['cancelled']


def api_requests() -> List[Dict[str, Any]]:
    return _call('GET', '/api/v1/requests')['requests']


def upload_workdir(workdir: str) -> str:
    """Zip + upload a local workdir; returns the SERVER-side path
    (reference workdir zip upload, sky/server/server.py:313-425)."""
    import io
    import zipfile
    workdir = os.path.expanduser(workdir)
    buf = io.BytesIO()
    with zipfile.ZipFile(buf, 'w', zipfile.ZIP_DEFLATED) as zf:
        for root, dirs, files in os.walk(workdir):
            # Exactly '.git' (not .github/ etc., which tasks may need).
            dirs[:] = [d for d in dirs if d not in ('__pycache__', '.git')]
            for fname in files:
                full = os.path.join(root, fname)
                zf.write(full, os.path.relpath(full, workdir))
    conn = _conn()
    try:
        headers = dict(_headers())
        headers['Content-Type'] = 'application/zip'
        conn.request('POST', '/api/v1/upload', body=buf.getvalue(),
                     headers=headers)
        resp = conn.getresponse()
        data = json.loads(resp.read() or b'{}')
        if resp.status >= 400:
            raise exceptions.ApiServerConnectionError(
                f'upload: {resp.status} {data.get("error")}')
        return data['workdir']
    finally:
        conn.close()


def _task_payload(task) -> Dict[str, Any]:
    """Task config for the wire; local workdirs upload to remote servers
    (a client path means nothing on the server's filesystem)."""
    cfg = task.to_yaml_config()
    if cfg.get('workdir') and is_remote_server():
        cfg = dict(cfg, workdir=upload_workdir(cfg['workdir']))
    return cfg


# ---- op wrappers (async: return request ids) -------------------------------
def launch(task, cluster_name: str, **kwargs) -> str:
    payload = {'task': _task_payload(task), 'cluster_name': cluster_name}
    payload.update(kwargs)
    return submit('launch', payload)


def exec_(task, cluster_name: str, **kwargs) -> str:
    payload = {'task': _task_payload(task), 'cluster_name': cluster_name}
    payload.update(kwargs)
    return submit('exec', payload)


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = True) -> str:
    return submit('status', {'cluster_names': cluster_names,
                             'refresh': refresh})


def start(cluster_name: str) -> str:
    return submit('start', {'cluster_name': cluster_name})


def stop(cluster_name: str) -> str:
    return submit('stop', {'cluster_name': cluster_name})


def down(cluster_name: str) -> str:
    return submit('down', {'cluster_name': cluster_name})


def autostop(cluster_name: str, idle_minutes: int,
             down_on_idle: bool = False) -> str:
    return submit('autostop', {'cluster_name': cluster_name,
                               'idle_minutes': idle_minutes,
                               'down_on_idle': down_on_idle})


def queue(cluster_name: str) -> str:
    return submit('queue', {'cluster_name': cluster_name})


def cancel(cluster_name: str, job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> str:
    return submit('cancel', {'cluster_name': cluster_name,
                             'job_ids': job_ids, 'all_jobs': all_jobs})


def tail_logs(cluster_name: str, job_id: Optional[int] = None,
              follow: bool = True) -> str:
    return submit('tail_logs', {'cluster_name': cluster_name,
                                'job_id': job_id, 'follow': follow})


def serve_up(task, service_name: str) -> str:
    return submit('serve_up', {'task': _task_payload(task),
                               'service_name': service_name})


def serve_status(service_names: Optional[List[str]] = None) -> str:
    return submit('serve_status', {'service_names': service_names})


def serve_down(service_name: str) -> str:
    return submit('serve_down', {'service_name': service_name})


def serve_update(task, service_name: str) -> str:
    return submit('serve_update', {'task': _task_payload(task),
                                   'service_name': service_name})


def shell(cluster_name: str, command: str, out=None,
          timeout_s: float = 3600) -> int:
    """Stream a command on a cluster's head host through the API server
    (the exec path for k8s pods / remote servers; reference websocket
    ssh proxy, sky/server/server.py:1016). Returns the exit code.

    ``timeout_s`` is enforced server-side; the client socket caps out at
    ~3700s regardless (the _conn timeout), so longer-running commands
    should go through the job queue (`exec`) instead."""
    import re
    out = out or sys.stdout
    conn = _conn()
    try:
        payload = json.dumps({'cluster_name': cluster_name,
                              'command': command,
                              'timeout_s': timeout_s}).encode()
        headers = dict(_headers())
        headers['Content-Type'] = 'application/json'
        conn.request('POST', '/api/v1/shell', body=payload,
                     headers=headers)
        resp = conn.getresponse()
        if resp.status >= 400:
            data = resp.read().decode(errors='replace')
            raise exceptions.ApiServerConnectionError(
                f'shell {cluster_name}: {resp.status} {data[:300]}')
        tail = ''
        read1 = getattr(resp, 'read1', None)
        while True:
            chunk = read1(16384) if read1 is not None else resp.read(16384)
            if not chunk:
                break
            text = chunk.decode(errors='replace')
            tail = (tail + text)[-64:]
            out.write(text)
            out.flush()
        # LAST marker wins: command output could itself end with a
        # marker-shaped string (e.g. catting a captured shell log).
        marks = re.findall(r'\[skytpu exit (\d+)\]', tail)
        return int(marks[-1]) if marks else 255
    except (ConnectionRefusedError, OSError) as e:
        raise exceptions.ApiServerConnectionError(
            f'Cannot reach API server at {server_url()}: {e}. '
            'Run `skytpu api start` (or set SKYTPU_API_SERVER_URL).') \
            from e
    finally:
        conn.close()


def check() -> str:
    return submit('check', {})


def cost_report() -> str:
    return submit('cost_report', {})


# ---- local server management ----------------------------------------------
def _server_pid_file() -> str:
    return os.path.join(global_user_state.get_state_dir(), 'server',
                        'server.pid')


def api_status() -> Optional[Dict[str, Any]]:
    try:
        return _call('GET', '/healthz')
    except exceptions.ApiServerConnectionError:
        return None


def api_start(port: Optional[int] = None, wait: float = 10.0) -> None:
    """Start a local API server in the background if not already up.

    A non-default ``port`` retargets this process' server_url() too (via
    SKYTPU_API_SERVER_URL) so the health check and subsequent SDK calls hit
    the server actually started.
    """
    if port is not None:
        os.environ['SKYTPU_API_SERVER_URL'] = f'http://127.0.0.1:{port}'
    if api_status() is not None:
        return
    if port is None:
        port = urlparse(server_url()).port or 46580
    log_dir = os.path.join(global_user_state.get_state_dir(), 'server')
    os.makedirs(log_dir, exist_ok=True)
    from skypilot_tpu.runtime import constants as rt_constants
    with open(os.path.join(log_dir, 'server.log'), 'ab') as log:
        proc = subprocess.Popen(
            [sys.executable, '-m', 'skypilot_tpu.server.server',
             '--port', str(port)],
            stdout=log, stderr=log, start_new_session=True,
            env={**os.environ, **rt_constants.control_plane_env()})
    os.makedirs(os.path.dirname(_server_pid_file()), exist_ok=True)
    with open(_server_pid_file(), 'w') as f:
        f.write(str(proc.pid))
    deadline = time.time() + wait
    while time.time() < deadline:
        if api_status() is not None:
            return
        time.sleep(0.2)
    raise exceptions.ApiServerConnectionError(
        f'API server did not come up on port {port} within {wait}s')


def api_stop() -> bool:
    try:
        with open(_server_pid_file()) as f:
            pid = int(f.read().strip())
    except (FileNotFoundError, ValueError):
        return False
    import signal
    try:
        os.killpg(os.getpgid(pid), signal.SIGTERM)
    except ProcessLookupError:
        return False
    os.remove(_server_pid_file())
    return True
