"""Client SDK for the skytpu API server (see server/ package docstring)."""
