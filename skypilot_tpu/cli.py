"""skytpu CLI (click).

Counterpart of reference ``sky/cli.py`` (groups at :1041; 5,856 LoC there —
ours stays lean by delegating everything to core/execution). Entry point:
``python -m skypilot_tpu.cli`` or the ``skytpu`` console script.
"""
from __future__ import annotations

import json
import sys
from typing import List, Optional

import click

from skypilot_tpu.utils import common_utils


def _parse_env_overrides(env) -> 'dict | None':
    """--env KEY=VALUE tuples -> dict (None when empty), with a usage
    error (not a ValueError traceback) on malformed entries."""
    if not env:
        return None
    out = {}
    for kv in env:
        key, sep, value = kv.partition('=')
        if not sep or not key:
            raise click.BadParameter(
                f'--env expects KEY=VALUE, got {kv!r}')
        out[key] = value
    return out


def _task_from_args(entrypoint, name, workdir, cloud, accelerators,
                    num_nodes, env, cmd):
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib

    env_overrides = _parse_env_overrides(env)
    if entrypoint and entrypoint.endswith(('.yaml', '.yml')):
        # --env must apply at PARSE time: $VAR substitution into run:/
        # file_mounts happens on load, so a post-hoc update_envs would
        # leave the rendered command on the YAML defaults.
        task = task_lib.Task.from_yaml(entrypoint,
                                       env_overrides=env_overrides)
    else:
        run_cmd = cmd or entrypoint
        task = task_lib.Task(run=run_cmd)
        if env_overrides:
            task.update_envs(env_overrides)
    if name:
        task.name = name
    if workdir:
        task.workdir = workdir
    if num_nodes:
        task.num_nodes = num_nodes
    overrides = {}
    if cloud:
        overrides['cloud'] = cloud
    if accelerators:
        overrides['accelerators'] = accelerators
    if overrides:
        base = task.resources[0] if task.resources else \
            resources_lib.Resources()
        task.set_resources([base.copy(**overrides)])
    return task


@click.group()
@click.version_option(message='%(version)s')
def cli():
    """skytpu: TPU-native multi-cloud orchestration."""


@cli.command()
@click.argument('entrypoint', required=False)
@click.option('--cluster', '-c', default=None, help='Cluster name.')
@click.option('--name', '-n', default=None)
@click.option('--workdir', default=None)
@click.option('--cloud', default=None)
@click.option('--gpus', '--tpus', 'accelerators', default=None,
              help='Accelerator spec, e.g. tpu-v5e-8.')
@click.option('--num-nodes', type=int, default=None)
@click.option('--env', multiple=True, help='KEY=VALUE (repeatable).')
@click.option('--cmd', default=None, help='Inline run command.')
@click.option('--detach-run', '-d', is_flag=True)
@click.option('--retry-until-up', is_flag=True)
@click.option('--idle-minutes-to-autostop', '-i', type=int, default=None)
@click.option('--down', is_flag=True,
              help='Autostop tears down instead of stopping.')
@click.option('--dryrun', is_flag=True)
@click.option('--fast', is_flag=True,
              help='Skip file mounts + setup when the cluster is UP and '
                   'the setup config is unchanged.')
@click.option('--clone-disk-from', default=None,
              help='Image a STOPPED cluster\'s disk and start the new '
                   'cluster from it.')
def launch(entrypoint, cluster, name, workdir, cloud, accelerators,
           num_nodes, env, cmd, detach_run, retry_until_up,
           idle_minutes_to_autostop, down, dryrun, fast,
           clone_disk_from):
    """Launch a task (YAML file or inline command) on a new/existing
    cluster."""
    from skypilot_tpu import execution
    task = _task_from_args(entrypoint, name, workdir, cloud, accelerators,
                           num_nodes, env, cmd)
    cluster = cluster or f'skytpu-{common_utils.get_user_name()}'
    job_id, _ = execution.launch(
        task, cluster_name=cluster, retry_until_up=retry_until_up,
        idle_minutes_to_autostop=idle_minutes_to_autostop, down=down,
        detach_run=detach_run, dryrun=dryrun, fast=fast,
        clone_disk_from=clone_disk_from)
    if dryrun:
        click.echo('Dry run complete (optimizer table above).')
    elif job_id is not None and detach_run:
        click.echo(f'Job {job_id} submitted on cluster {cluster!r}. '
                   f'Logs: skytpu logs {cluster} {job_id}')


@cli.command('exec')
@click.argument('cluster')
@click.argument('entrypoint', required=False)
@click.option('--cmd', default=None)
@click.option('--detach-run', '-d', is_flag=True)
@click.option('--env', multiple=True)
def exec_cmd(cluster, entrypoint, cmd, detach_run, env):
    """Run a task on an existing cluster (skips provision/setup)."""
    from skypilot_tpu import execution
    task = _task_from_args(entrypoint, None, None, None, None, None, env,
                           cmd)
    job_id, _ = execution.exec_(task, cluster_name=cluster,
                                detach_run=detach_run)
    if job_id is not None and detach_run:
        click.echo(f'Job {job_id} submitted on cluster {cluster!r}.')


@cli.command()
@click.argument('clusters', nargs=-1)
@click.option('--refresh/--no-refresh', default=True)
def status(clusters, refresh):
    """Show clusters (reconciled against cloud state)."""
    from skypilot_tpu import core
    records = core.status(list(clusters) or None, refresh=refresh)
    if not records:
        click.echo('No existing clusters.')
        return
    fmt = '{:<20} {:<10} {:<24} {:<8} {:<12}'
    click.echo(fmt.format('NAME', 'STATUS', 'RESOURCES', 'HOSTS',
                          'AUTOSTOP'))
    for r in records:
        handle = r['handle']
        res = (str(handle.launched_resources) if handle else '-')
        hosts = handle.num_hosts if handle else '-'
        autostop = f"{r['autostop']}m" if r['autostop'] >= 0 else '-'
        if r['to_down'] and r['autostop'] >= 0:
            autostop += ' (down)'
        from skypilot_tpu.utils import ux_utils
        status_col = ux_utils.colorize_status(f"{r['status'].value:<10}")
        click.echo(fmt.format(r['name'], status_col,
                              res[:24], str(hosts), autostop))


@cli.command()
@click.argument('cluster')
def start(cluster):
    """Restart a stopped cluster."""
    from skypilot_tpu import core
    core.start(cluster)
    click.echo(f'Cluster {cluster!r} started.')


@cli.command()
@click.argument('clusters', nargs=-1, required=True)
def stop(clusters):
    """Stop cluster(s), keeping disks."""
    from skypilot_tpu import core
    for c in clusters:
        core.stop(c)
        click.echo(f'Cluster {c!r} stopped.')


@cli.command()
@click.argument('clusters', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True)
def down(clusters, yes):
    """Tear down cluster(s)."""
    from skypilot_tpu import core
    if not yes:
        click.confirm(f'Tear down {", ".join(clusters)}?', abort=True)
    for c in clusters:
        core.down(c)
        click.echo(f'Cluster {c!r} terminated.')


@cli.command()
@click.argument('cluster')
@click.option('--idle-minutes', '-i', type=int, required=True,
              help='-1 cancels autostop.')
@click.option('--down', is_flag=True)
def autostop(cluster, idle_minutes, down):
    """Schedule autostop/autodown after idleness."""
    from skypilot_tpu import core
    core.autostop(cluster, idle_minutes, down)
    if idle_minutes < 0:
        click.echo(f'Autostop cancelled on {cluster!r}.')
    else:
        click.echo(f'Cluster {cluster!r} will '
                   f'{"autodown" if down else "autostop"} after '
                   f'{idle_minutes}m idle.')


@cli.command()
@click.argument('cluster')
def queue(cluster):
    """Show the cluster's job queue."""
    from skypilot_tpu import core
    jobs = core.queue(cluster)
    if not jobs:
        click.echo('No jobs.')
        return
    fmt = '{:<6} {:<16} {:<12} {:<12}'
    click.echo(fmt.format('ID', 'NAME', 'STATUS', 'SUBMITTED'))
    for j in jobs:
        submitted = common_utils.readable_time_duration(j['submitted_at'])
        click.echo(fmt.format(j['job_id'], (j['name'] or '-')[:16],
                              j['status'], submitted))


@cli.command()
@click.argument('cluster')
@click.argument('job_id', required=False, type=int)
@click.option('--no-follow', is_flag=True)
def logs(cluster, job_id, no_follow):
    """Tail a job's logs."""
    from skypilot_tpu import core
    rc = core.tail_logs(cluster, job_id, follow=not no_follow)
    sys.exit(rc)


@cli.command(context_settings=dict(ignore_unknown_options=True))
@click.argument('cluster')
@click.argument('command', nargs=-1, type=click.UNPROCESSED)
def ssh(cluster, command):
    """SSH into a cluster's head host (``skytpu ssh mycluster [cmd]``).

    Uses the per-cluster Host block written at provision time (reference
    SSHConfigHelper, sky/utils/cluster_utils.py:38); plain
    ``ssh <cluster>`` works too once a cluster is UP.
    """
    import subprocess

    from skypilot_tpu.utils import cluster_utils
    argv = cluster_utils.head_ssh_args(cluster)
    if argv is None:
        raise click.ClickException(
            f'No ssh config for cluster {cluster!r} — is it UP on an '
            'SSH-reachable cloud? (local/kubernetes clusters have no '
            'direct ssh)')
    sys.exit(subprocess.call(argv + list(command)))


@cli.command(context_settings=dict(ignore_unknown_options=True))
@click.argument('cluster')
@click.argument('command', nargs=-1, required=True,
                type=click.UNPROCESSED)
def shell(cluster, command):
    """Run a command on a cluster head THROUGH the API server.

    The exec path for clusters you can't ssh to directly — Kubernetes
    pods, or any cluster managed by a shared remote API server
    (reference websocket ssh proxy, sky/server/server.py:1016). For VM
    clouds with direct reachability, `skytpu ssh` is interactive.
    """
    from skypilot_tpu import exceptions
    from skypilot_tpu.client import sdk
    try:
        sys.exit(sdk.shell(cluster, ' '.join(command)))
    except exceptions.ApiServerConnectionError as e:
        raise click.ClickException(str(e))


@cli.command()
@click.argument('cluster')
@click.argument('job_ids', nargs=-1, type=int)
@click.option('--all', 'all_jobs', is_flag=True)
def cancel(cluster, job_ids, all_jobs):
    """Cancel job(s)."""
    from skypilot_tpu import core
    cancelled = core.cancel(cluster, list(job_ids) or None,
                            all_jobs=all_jobs)
    click.echo(f'Cancelled jobs: {cancelled}')


@cli.command()
def check():
    """Probe cloud credentials and show enabled clouds."""
    from skypilot_tpu import check as check_lib
    results = check_lib.check_capabilities(quiet=True)
    for cloud_name, (ok, reason) in results.items():
        mark = '\x1b[32m✓\x1b[0m' if ok else '\x1b[31m✗\x1b[0m'
        click.echo(f'  {mark} {cloud_name}'
                   + (f': {reason}' if reason and not ok else ''))


@cli.command('show-tpus')
@click.option('--generation', default=None, help='e.g. v5e, v6e.')
@click.option('--refresh', is_flag=True,
              help='Re-fetch prices from the Cloud Billing API '
                   '(falls back to the built-in tables offline).')
def show_tpus(generation, refresh):
    """List TPU slice offerings with price and perf/$. (analog of
    reference `sky show-gpus`)."""
    from skypilot_tpu import accelerators as accel_lib
    from skypilot_tpu import catalog
    if refresh:
        source = catalog.refresh(online=True)
        click.echo(f'Catalog refreshed ({source}).')
    df = catalog.list_tpu_slices(generation=generation)
    # Cheapest region per slice type.
    df = df.loc[df.groupby('slice')['price'].idxmin()]
    fmt = '{:<16} {:<6} {:<6} {:<10} {:<8} {:<10} {:<10} {:<16}'
    click.echo(fmt.format('SLICE', 'CHIPS', 'HOSTS', 'TFLOPS', 'HBM_GB',
                          '$/HR', 'SPOT$/HR', 'TFLOPS_PER_$HR'))
    for _, r in df.sort_values(['generation', 'chips']).iterrows():
        s = accel_lib.TpuSlice.from_name(r['slice'])
        click.echo(fmt.format(
            r['slice'], r['chips'], r['num_hosts'],
            f'{s.total_bf16_tflops:,.0f}', f'{s.total_hbm_gb:,.0f}',
            f"{r['price']:,.2f}", f"{r['spot_price']:,.2f}",
            f"{s.total_bf16_tflops / r['price']:,.0f}"))


@cli.command('cost-report')
def cost_report():
    """Show per-cluster accumulated cost."""
    from skypilot_tpu import core
    rows = core.cost_report()
    if not rows:
        click.echo('No cluster history.')
        return
    fmt = '{:<20} {:<10} {:<10} {:<10}'
    click.echo(fmt.format('NAME', 'HOSTS', 'DURATION', 'COST($)'))
    for r in rows:
        click.echo(fmt.format(
            r['name'], r['num_hosts'],
            common_utils.readable_time_duration(0, r['duration_s'],
                                                absolute=True),
            f"{r['cost']:,.2f}"))


@cli.command()
@click.argument('entrypoint')
@click.option('--minimize', type=click.Choice(['cost', 'time',
                                               'perf_per_dollar']),
              default='cost')
def optimize(entrypoint, minimize):
    """Show the optimizer's candidate table for a task YAML."""
    from skypilot_tpu import optimizer as optimizer_lib
    from skypilot_tpu import task as task_lib
    task = task_lib.Task.from_yaml(entrypoint)
    optimizer_lib.optimize(
        task, minimize=optimizer_lib.OptimizeTarget(minimize))


@cli.group()
def storage():
    """Bucket storage attached to tasks (reference sky/cli.py:3773)."""


@storage.command('ls')
def storage_ls():
    """List storages recorded by task launches."""
    import datetime

    from skypilot_tpu import global_user_state
    rows = global_user_state.get_storages()
    if not rows:
        click.echo('No storages.')
        return
    fmt = '{:<24} {:<40} {:<7} {:<19}'
    click.echo(fmt.format('NAME', 'URL', 'MODE', 'LAUNCHED'))
    for r in rows:
        ts = datetime.datetime.fromtimestamp(
            r['launched_at']).strftime('%Y-%m-%d %H:%M:%S')
        click.echo(fmt.format(r['name'][:24], r['url'][:40], r['mode'], ts))


@storage.command('transfer')
@click.argument('src_url')
@click.argument('dst_url')
def storage_transfer(src_url, dst_url):
    """Copy a bucket tree between stores (e.g. s3://data gs://data).

    S3->GCS rides a provider-side path (no client transit); other pairs
    relay through this machine.
    """
    from skypilot_tpu.data import data_transfer
    data_transfer.transfer_url(src_url, dst_url)
    click.echo(f'Transferred {src_url} -> {dst_url}.')


@storage.command('delete')
@click.argument('names', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True)
def storage_delete(names, yes):
    """Forget storage records (bucket contents are not touched)."""
    from skypilot_tpu import global_user_state
    for name in names:
        if not yes:
            click.confirm(f'Delete storage record {name!r}?', abort=True)
        global_user_state.remove_storage(name)
        click.echo(f'Storage {name!r} removed from state.')


@cli.group()
def jobs():
    """Managed jobs with auto-recovery."""


@jobs.command('launch')
@click.argument('entrypoint', required=False)
@click.option('--name', '-n', default=None)
@click.option('--cloud', default=None)
@click.option('--gpus', '--tpus', 'accelerators', default=None)
@click.option('--cmd', default=None)
@click.option('--env', multiple=True)
@click.option('--detach-run', '-d', is_flag=True)
def jobs_launch(entrypoint, name, cloud, accelerators, cmd, env,
                detach_run):
    """Submit a managed job (controller recovers it on preemption).

    A multi-document YAML entrypoint is a PIPELINE: its tasks run
    sequentially on their own clusters, each with preemption recovery.
    """
    from skypilot_tpu import jobs as jobs_lib
    if entrypoint and entrypoint.endswith(('.yaml', '.yml')):
        from skypilot_tpu.utils import common_utils as cu
        from skypilot_tpu.utils import dag_utils
        configs = [c for c in cu.read_yaml_all(entrypoint) if c]
        if len(configs) > 1:
            if cloud or accelerators or cmd:
                # Per-task resource flags are ambiguous across a
                # pipeline's tasks; set them in each YAML document.
                raise click.UsageError(
                    '--cloud/--tpus/--cmd do not apply to multi-document '
                    'pipeline YAMLs; set resources per task in the YAML.')
            dag = dag_utils.load_chain_dag_from_yaml_configs(
                configs, env_overrides=_parse_env_overrides(env),
                source=entrypoint)
            job_id = jobs_lib.launch(dag, name=name)
            click.echo(f'Managed pipeline job {job_id} submitted '
                       f'({len(dag.tasks)} tasks).'
                       f' Logs: skytpu jobs logs {job_id}')
            if not detach_run:
                sys.exit(jobs_lib.tail_logs(job_id, follow=True))
            return
    task = _task_from_args(entrypoint, name, None, cloud, accelerators,
                           None, env, cmd)
    job_id = jobs_lib.launch(task, name=name)
    click.echo(f'Managed job {job_id} submitted.'
               f' Logs: skytpu jobs logs {job_id}')
    if not detach_run:
        sys.exit(jobs_lib.tail_logs(job_id, follow=True))


@jobs.command('queue')
@click.option('--verbose', '-v', is_flag=True,
              help='Show per-task rows for pipelines.')
def jobs_queue(verbose):
    """List managed jobs."""
    from skypilot_tpu import jobs as jobs_lib
    rows = jobs_lib.queue()
    if not rows:
        click.echo('No managed jobs.')
        return
    fmt = '{:<5} {:<16} {:<18} {:<6} {:<10} {:<20}'
    click.echo(fmt.format('ID', 'NAME', 'STATUS', 'TASK', 'RECOVERIES',
                          'CLUSTER'))
    for r in rows:
        n_tasks = r.get('num_tasks', 1) or 1
        task_col = (f"{(r.get('current_task_id') or 0) + 1}/{n_tasks}"
                    if n_tasks > 1 else '-')
        click.echo(fmt.format(r['job_id'], (r['name'] or '-')[:16],
                              r['status'].value, task_col,
                              r['recovery_count'],
                              (r['cluster_name'] or '-')[:20]))
        if verbose:
            for t in r.get('tasks', []):
                click.echo(fmt.format(
                    f"  {r['job_id']}.{t['task_id']}",
                    ('  ' + (t['name'] or '-'))[:16],
                    t['status'].value, '-', t['recovery_count'], '-'))


@jobs.command('cancel')
@click.argument('job_ids', nargs=-1, type=int)
@click.option('--all', 'all_jobs', is_flag=True)
def jobs_cancel(job_ids, all_jobs):
    """Cancel managed job(s)."""
    if not job_ids and not all_jobs:
        raise click.UsageError('Specify job ids or --all.')
    from skypilot_tpu import jobs as jobs_lib
    cancelled = jobs_lib.cancel(list(job_ids) or None, all_jobs=all_jobs)
    click.echo(f'Cancelling managed jobs: {cancelled}')


@jobs.command('logs')
@click.argument('job_id', type=int)
@click.option('--no-follow', is_flag=True)
@click.option('--controller', is_flag=True,
              help='Show the controller process log instead.')
@click.option('--task', 'task_id', type=int, default=None,
              help='Replay one pipeline task\'s log (archived after the '
                   'task finishes).')
def jobs_logs(job_id, no_follow, controller, task_id):
    """Stream a managed job's logs."""
    from skypilot_tpu.jobs import core as jobs_core
    if controller:
        click.echo(jobs_core.controller_logs(job_id))
        return
    sys.exit(jobs_core.tail_logs(job_id, follow=not no_follow,
                                 task_id=task_id))


@cli.group()
def serve():
    """Autoscaled, load-balanced services (SkyServe analog)."""


@serve.command('up')
@click.argument('entrypoint')
@click.option('--service-name', '-n', required=True)
@click.option('--env', multiple=True, help='KEY=VALUE (repeatable).')
def serve_up(entrypoint, service_name, env):
    """Start a service from a task YAML with a `service:` section."""
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.serve import core as serve_core
    task = task_lib.Task.from_yaml(
        entrypoint, env_overrides=_parse_env_overrides(env))
    result = serve_core.up(task, service_name)
    click.echo(f'Service {result["name"]!r} starting. '
               f'Endpoint: {result["endpoint"]}')
    click.echo(f'Watch: skytpu serve status {service_name}')


@serve.command('update')
@click.argument('service_name')
@click.argument('entrypoint')
@click.option('--env', multiple=True, help='KEY=VALUE (repeatable).')
def serve_update(service_name, entrypoint, env):
    """Rolling-update a running service to a new task YAML (zero
    downtime: old replicas drain only as new ones turn READY)."""
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.serve import core as serve_core
    task = task_lib.Task.from_yaml(
        entrypoint, env_overrides=_parse_env_overrides(env))
    result = serve_core.update(task, service_name)
    click.echo(f'Service {result["name"]!r} rolling to '
               f'version {result["version"]}.')
    click.echo(f'Watch: skytpu serve status {service_name}')


@serve.command('status')
@click.argument('service_names', nargs=-1)
def serve_status(service_names):
    """Show services and their replicas."""
    from skypilot_tpu.serve import core as serve_core
    rows = serve_core.status(list(service_names) or None)
    if not rows:
        click.echo('No services.')
        return
    fmt = '{:<20} {:<16} {:<28} {:<8} {:<4}'
    click.echo(fmt.format('NAME', 'STATUS', 'ENDPOINT', 'REPLICAS', 'VER'))
    for r in rows:
        n_ready = sum(1 for rep in r['replicas']
                      if rep['status'].value == 'READY')
        n_live = sum(1 for rep in r['replicas'] if rep['status'].is_live())
        click.echo(fmt.format(r['name'], r['status'].value,
                              r['endpoint'] or '-', f'{n_ready}/{n_live}',
                              f'v{r.get("version", 1)}'))
        for rep in r['replicas']:
            spot = '' if rep.get('spot', True) else ' [on-demand]'
            click.echo(f'  rep{rep["replica_id"]:<4} '
                       f'{rep["status"].value:<22} '
                       f'v{rep.get("version", 1)} '
                       f'{rep["url"] or "-"}{spot}')


@serve.command('down')
@click.argument('service_names', nargs=-1, required=True)
@click.option('--yes', '-y', is_flag=True)
def serve_down(service_names, yes):
    """Tear down service(s) and their replicas."""
    from skypilot_tpu.serve import core as serve_core
    if not yes:
        click.confirm(f'Tear down service(s) {", ".join(service_names)}?',
                      abort=True)
    for name in service_names:
        serve_core.down(name)
        click.echo(f'Service {name!r} torn down.')


@serve.command('logs')
@click.argument('service_name')
def serve_logs(service_name):
    """Show a service's controller log."""
    from skypilot_tpu.serve import core as serve_core
    click.echo(serve_core.controller_logs(service_name))


@cli.group()
def bench():
    """Benchmark a task across candidate TPU configs (reference `sky
    bench`, sky/benchmark/benchmark_utils.py)."""


@bench.command('launch')
@click.argument('entrypoint')
@click.option('--benchmark', '-b', required=True, help='Benchmark name.')
@click.option('--candidates', required=True,
              help='Comma-separated accelerators, e.g. tpu-v5e-8,tpu-v6e-8 '
                   '(or "local" entries for testing).')
def bench_launch(entrypoint, benchmark, candidates):
    """Launch ENTRYPOINT once per candidate config."""
    from skypilot_tpu import resources as resources_lib
    from skypilot_tpu import task as task_lib
    from skypilot_tpu.benchmark import utils as bench_utils
    task = task_lib.Task.from_yaml(entrypoint)
    cands = []
    for item in candidates.split(','):
        item = item.strip()
        if item == 'local':
            cands.append(resources_lib.Resources(cloud='local'))
        else:
            cands.append(resources_lib.Resources(accelerators=item))
    results = bench_utils.launch(task, benchmark, cands)
    for r in results:
        mark = f"job {r['job_id']}" if 'job_id' in r else \
            f"FAILED: {r.get('error', '?')[:60]}"
        click.echo(f"  {r['cluster']}: {mark}")
    click.echo(f"Results: skytpu bench show {benchmark}")


@bench.command('ls')
def bench_ls():
    """List benchmarks."""
    from skypilot_tpu.benchmark import state as bench_state
    rows = bench_state.list_benchmarks()
    if not rows:
        click.echo('No benchmarks.')
        return
    for r in rows:
        click.echo(f"{r['benchmark']:<24} {r['task_name'] or '-'}")


@bench.command('show')
@click.argument('benchmark')
def bench_show(benchmark):
    """Per-candidate sec/step and $/step."""
    from skypilot_tpu.benchmark import utils as bench_utils
    report = bench_utils.get_report(benchmark)
    if not report:
        click.echo(f'No results for benchmark {benchmark!r}.')
        return
    fmt = '{:<28} {:<26} {:<8} {:<8} {:<12} {:<12}'
    click.echo(fmt.format('CLUSTER', 'RESOURCES', '$/HR', 'STEPS',
                          'SEC/STEP', '$/STEP'))
    for r in report:
        click.echo(fmt.format(
            r['cluster'][:28], r['resources'][:26],
            f"{r['hourly_cost']:.2f}",
            str(r['num_steps'] or '-'),
            (f"{r['seconds_per_step']:.4f}"
             if r['seconds_per_step'] else '-'),
            (f"{r['cost_per_step']:.6f}"
             if r['cost_per_step'] is not None else '-')))


@bench.command('down')
@click.argument('benchmark')
def bench_down(benchmark):
    """Terminate a benchmark's candidate clusters."""
    from skypilot_tpu.benchmark import utils as bench_utils
    for name in bench_utils.down(benchmark):
        click.echo(f'Terminated {name}.')


@bench.command('delete')
@click.argument('benchmark')
def bench_delete(benchmark):
    """Delete a benchmark's records (clusters must be downed first)."""
    from skypilot_tpu.benchmark import utils as bench_utils
    bench_utils.delete(benchmark)
    click.echo(f'Deleted benchmark {benchmark}.')


@cli.group()
def api():
    """Manage the local API server."""


@api.command('start')
@click.option('--port', type=int, default=None)
def api_start(port):
    from skypilot_tpu.client import sdk
    sdk.api_start(port=port)
    click.echo(f'API server healthy at {sdk.server_url()}')


@api.command('stop')
def api_stop():
    from skypilot_tpu.client import sdk
    stopped = sdk.api_stop()
    click.echo('API server stopped.' if stopped
               else 'No API server pid file found.')


@api.command('status')
def api_status():
    from skypilot_tpu.client import sdk
    info = sdk.api_status()
    if info is None:
        click.echo(f'API server at {sdk.server_url()} is NOT reachable.')
        sys.exit(1)
    click.echo(f'API server at {sdk.server_url()}: {info["status"]}')


@api.command('logs')
@click.argument('request_id')
def api_logs(request_id):
    from skypilot_tpu.client import sdk
    sdk.stream(request_id)


@cli.group()
def local():
    """Manage a local Kubernetes cloud (kind)."""


@local.command('up')
@click.option('--name', default=None,
              help='kind cluster name (default: skytpu-local).')
def local_up(name):
    """Bootstrap a kind cluster as a local Kubernetes cloud
    (reference `sky local up`)."""
    from skypilot_tpu.utils import kind_utils
    kwargs = {'name': name} if name else {}
    path, created = kind_utils.local_up(**kwargs)
    verb = 'created' if created else 'already running; kubeconfig refreshed'
    click.echo(f'Local Kubernetes cluster {verb}.\n'
               f'  kubeconfig: {path}\n'
               f'Use it with:\n'
               f'  export KUBECONFIG={path}\n'
               f'  skytpu launch --cloud kubernetes -- echo hi')


@local.command('down')
@click.option('--name', default=None)
def local_down(name):
    """Tear down the kind-backed local Kubernetes cloud."""
    from skypilot_tpu.utils import kind_utils
    kwargs = {'name': name} if name else {}
    if kind_utils.local_down(**kwargs):
        click.echo('Local Kubernetes cluster deleted.')
    else:
        click.echo('No local Kubernetes cluster found.')


def main():
    cli()


if __name__ == '__main__':
    main()
