"""Inter-cloud bucket transfer (S3 -> GCS and friends).

Counterpart of reference ``sky/data/data_transfer.py`` (GCS Storage
Transfer Service for s3->gcs). The realistic TPU migration story is
one-directional — datasets produced on AWS move to GCS where the TPU
slices are — so that path gets a *direct* cloud-side command (``gsutil``
reads s3:// natively via its boto layer: the data moves provider-to-
provider, never through the client). Every other store pair falls back to
a generic relay through a client temp dir using the stores' client-side
download/upload ops — slower, but universal (and hermetically testable
with file:// stores).
"""
from __future__ import annotations

import shutil
import subprocess
import tempfile
from typing import List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.data import storage as storage_lib


def _direct_command(src: storage_lib.AbstractStore,
                    dst: storage_lib.AbstractStore) -> Optional[List[str]]:
    """A provider-side command for this pair, or None for the relay."""
    pair = (src.SCHEME, dst.SCHEME)
    if pair in (('s3', 'gs'), ('gs', 'gs')):
        # Both tools speak both schemes (storage_lib.gcs_cli picks).
        return storage_lib.gcs_cli(
            ['rsync', '-r', src.url, dst.url],
            ['-m', 'rsync', '-r', src.url, dst.url])
    if pair == ('s3', 's3'):
        return ['aws', 's3', 'sync', src.url, dst.url]
    return None


def transfer(src: storage_lib.AbstractStore,
             dst: storage_lib.AbstractStore) -> None:
    """Copy the full tree under ``src`` into ``dst``."""
    if not src.exists():
        raise exceptions.StorageError(
            f'transfer source {src.url} does not exist')
    cmd = _direct_command(src, dst)
    if cmd is not None and shutil.which(cmd[0]):
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise exceptions.StorageError(
                f'transfer {src.url} -> {dst.url} failed: '
                f'{proc.stderr[-800:]}')
        return
    # Generic relay: materialize locally, then upload. Universal, but the
    # data transits the client — only for pairs without a direct path.
    try:
        with tempfile.TemporaryDirectory(prefix='skytpu-transfer-') as tmp:
            src.download_local(tmp)
            dst.upload_local(tmp)
    except FileNotFoundError as e:
        raise exceptions.StorageError(
            f'transfer {src.url} -> {dst.url} needs the cloud CLI for '
            f'both stores on this machine: {e}') from None


def transfer_url(src_url: str, dst_url: str) -> None:
    transfer(storage_lib.parse_store_url(src_url),
             storage_lib.parse_store_url(dst_url))
