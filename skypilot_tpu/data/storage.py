"""Storage: named buckets attachable to tasks as COPY or MOUNT file mounts.

Counterpart of reference ``sky/data/storage.py`` (Storage :519, StorageMode
:265, AbstractStore :118). Differences for the TPU-native rebuild:

- Store operations are expressed as *remote shell commands* (download /
  upload / mount) executed on cluster hosts through the CommandRunner —
  there is no Ray task plumbing.
- GCS is the first-class store (TPU slices live in GCP; intra-region
  traffic is free and rides Google's backbone). S3 and others can register
  via ``register_store``.
- A hermetic ``file://`` store (bucket = directory) backs tests end-to-end,
  the same design stance as the emulated local cloud (SURVEY.md §4: the
  reference can only smoke-test storage against real clouds).
"""
from __future__ import annotations

import enum
import os
import shlex
import shutil
import subprocess
from typing import Any, Dict, Optional, Tuple, Type

from skypilot_tpu import exceptions


class StorageMode(enum.Enum):
    COPY = 'COPY'    # materialize bucket contents onto host disk
    MOUNT = 'MOUNT'  # FUSE-mount the bucket at the mount point
    # FUSE-mount with a local read cache + async write-back (reference
    # sky/data/storage.py:265-273): writes land locally and flush to the
    # bucket in the background, so training-step latency is decoupled
    # from object-store latency. Best for checkpoint/output dirs.
    MOUNT_CACHED = 'MOUNT_CACHED'


class AbstractStore:
    """One bucket (+ optional subpath) in one object-store provider.

    Subclasses provide shell-command *generators* (run on cluster hosts)
    plus client-side upload/exists used by ``Storage.sync_local_source``.
    """

    SCHEME = ''

    def __init__(self, bucket: str, sub_path: str = ''):
        self.bucket = bucket
        self.sub_path = sub_path.strip('/')

    @property
    def url(self) -> str:
        suffix = f'/{self.sub_path}' if self.sub_path else ''
        return f'{self.SCHEME}://{self.bucket}{suffix}'

    def __repr__(self) -> str:
        return f'{type(self).__name__}({self.url!r})'

    # -- remote-side command generation (run via CommandRunner) -------------
    def download_command(self, dst: str) -> str:
        """Shell that materializes the bucket path into ``dst`` (COPY)."""
        raise NotImplementedError

    def upload_command(self, src: str) -> str:
        """Shell that syncs host path ``src`` up into the bucket."""
        raise NotImplementedError

    def mount_command(self, mount_point: str) -> str:
        """Shell that FUSE-mounts the bucket at ``mount_point`` (MOUNT,
        read-write)."""
        raise NotImplementedError

    def mount_cached_command(self, mount_point: str) -> str:
        """Shell for the MOUNT_CACHED flavor: local read cache + async
        write-back (rclone vfs-cache full)."""
        raise NotImplementedError

    # -- client-side ops ----------------------------------------------------
    def upload_local(self, local_path: str) -> None:
        """Upload a local file/dir tree into the bucket (client machine)."""
        raise NotImplementedError

    def download_local(self, local_path: str) -> None:
        """Materialize the bucket path into a local dir (client machine);
        the generic inter-cloud transfer relay uses this."""
        raise NotImplementedError

    def exists(self) -> bool:
        raise NotImplementedError

    def bucket_exists(self) -> bool:
        """Does the BUCKET exist (ignoring sub_path)? Validation uses
        this: an empty/not-yet-written prefix of a real bucket is fine
        (output/checkpoint mounts create their path on first write)."""
        return type(self)(self.bucket).exists()


def gcs_cli(gcloud_args: list, gsutil_args: list) -> list:
    """One place to pick the GCS client CLI: prefer modern ``gcloud
    storage`` (newer google-cloud-cli installs drop standalone gsutil),
    fall back to ``gsutil``."""
    if shutil.which('gcloud'):
        return ['gcloud', 'storage'] + gcloud_args
    return ['gsutil'] + gsutil_args


class GcsStore(AbstractStore):
    """Google Cloud Storage via the gcloud CLI (remote hosts have it: they
    are GCP VMs/TPU-VMs) and gcsfuse for MOUNT.

    Reference counterpart: sky/data/storage.py GcsStore + gcsfuse branch of
    sky/data/mounting_utils.py:41-120.
    """

    SCHEME = 'gs'

    def download_command(self, dst: str) -> str:
        q = shlex.quote
        return (f'mkdir -p {q(dst)} && '
                f'(command -v gcloud >/dev/null && '
                f'gcloud storage rsync -r {q(self.url)} {q(dst)} || '
                f'gsutil -m rsync -r {q(self.url)} {q(dst)})')

    def upload_command(self, src: str) -> str:
        q = shlex.quote
        return (f'(command -v gcloud >/dev/null && '
                f'gcloud storage rsync -r {q(src)} {q(self.url)} || '
                f'gsutil -m rsync -r {q(src)} {q(self.url)})')

    def mount_command(self, mount_point: str) -> str:
        from skypilot_tpu.data import mounting_utils
        return mounting_utils.gcsfuse_mount_command(
            self.bucket, mount_point, sub_path=self.sub_path)

    def mount_cached_command(self, mount_point: str) -> str:
        # gcsfuse has no write-back cache mode; MOUNT_CACHED rides the
        # same rclone vfs machinery as the other object stores.
        from skypilot_tpu.data import mounting_utils
        return mounting_utils.rclone_gcs_mount_command(
            self.bucket, mount_point, self.sub_path, cached=True)

    def upload_local(self, local_path: str) -> None:
        local_path = os.path.expanduser(local_path)
        cmd = gcs_cli(['rsync', '-r', local_path, self.url],
                      ['-m', 'rsync', '-r', local_path, self.url])
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise exceptions.StorageError(
                f'upload to {self.url} failed: {proc.stderr[-500:]}')

    def download_local(self, local_path: str) -> None:
        os.makedirs(local_path, exist_ok=True)
        cmd = gcs_cli(['rsync', '-r', self.url, local_path],
                      ['-m', 'rsync', '-r', self.url, local_path])
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise exceptions.StorageError(
                f'download from {self.url} failed: {proc.stderr[-500:]}')

    def exists(self) -> bool:
        cmd = gcs_cli(['ls', self.url], ['ls', self.url])
        return subprocess.run(cmd, capture_output=True).returncode == 0


class S3Store(AbstractStore):
    """Amazon S3 via the aws CLI.

    Reference counterpart: sky/data/storage.py S3Store (:118-211 family).
    COPY materializes onto hosts; MOUNT is a writable rclone FUSE mount
    (write-on-close buffering); MOUNT_CACHED adds a local read cache +
    async write-back for checkpoint/output dirs.
    """

    SCHEME = 's3'

    # GCP TPU-VM images ship gcloud but not the aws CLI: bootstrap it on
    # first use (reference installs cloud CLIs in its setup commands,
    # sky/setup_files). ~/.local/bin covers pip --user installs.
    _ENSURE_AWS = ('export PATH=$PATH:$HOME/.local/bin; '
                   'command -v aws >/dev/null || '
                   'python3 -m pip install --user --quiet awscli; ')

    def _endpoint(self) -> str:
        """S3-compatible providers (R2, ...) override with their
        endpoint URL; empty = real AWS S3."""
        return ''

    @property
    def _s3_url(self) -> str:
        """The s3:// form the aws CLI needs (R2 urls are r2:// for the
        user but s3:// + --endpoint-url on the wire)."""
        path = f's3://{self.bucket}'
        return f'{path}/{self.sub_path}' if self.sub_path else path

    def _endpoint_flag(self) -> str:
        ep = self._endpoint()
        return f'--endpoint-url {shlex.quote(ep)} ' if ep else ''

    def download_command(self, dst: str) -> str:
        q = shlex.quote
        return (f'mkdir -p {q(dst)} && '
                f'{self._ENSURE_AWS}'
                f'aws s3 sync {self._endpoint_flag()}'
                f'{q(self._s3_url)} {q(dst)}')

    def upload_command(self, src: str) -> str:
        q = shlex.quote
        return (f'{self._ENSURE_AWS}aws s3 sync {self._endpoint_flag()}'
                f'{q(src)} {q(self._s3_url)}')

    def mount_command(self, mount_point: str) -> str:
        """rclone FUSE mount, read-write (reference mounts S3 via
        goofys/rclone, sky/data/mounting_utils.py:41-367): writes buffer
        locally and upload on close, so checkpoint-to-bucket works on
        AWS clusters."""
        from skypilot_tpu.data import mounting_utils
        return mounting_utils.rclone_s3_mount_command(
            self.bucket, mount_point, self.sub_path, read_only=False,
            endpoint=self._endpoint())

    def mount_cached_command(self, mount_point: str) -> str:
        from skypilot_tpu.data import mounting_utils
        return mounting_utils.rclone_s3_mount_command(
            self.bucket, mount_point, self.sub_path,
            endpoint=self._endpoint(), cached=True)

    def _aws(self, *args: str):
        ep = self._endpoint()
        argv = ['aws', 's3', *args]
        if ep:
            argv += ['--endpoint-url', ep]
        return subprocess.run(argv, capture_output=True, text=True)

    def upload_local(self, local_path: str) -> None:
        proc = self._aws('sync', os.path.expanduser(local_path),
                         self._s3_url)
        if proc.returncode != 0:
            raise exceptions.StorageError(
                f'upload to {self.url} failed: {proc.stderr[-500:]}')

    def download_local(self, local_path: str) -> None:
        os.makedirs(local_path, exist_ok=True)
        proc = self._aws('sync', self._s3_url, local_path)
        if proc.returncode != 0:
            raise exceptions.StorageError(
                f'download from {self.url} failed: {proc.stderr[-500:]}')

    def exists(self) -> bool:
        return self._aws('ls', self._s3_url).returncode == 0


class LocalStore(AbstractStore):
    """Hermetic test store: the 'bucket' is a directory path.

    ``file:///abs/dir`` URLs exercise every Storage/mount code path with no
    cloud. MOUNT is a symlink (a faithful stand-in for a FUSE mount from
    the task's point of view: same path indirection, shared backing files).
    """

    SCHEME = 'file'

    @property
    def root(self) -> str:
        path = f'/{self.bucket}'
        return os.path.join(path, self.sub_path) if self.sub_path else path

    @property
    def url(self) -> str:
        return f'file://{self.root}'

    def download_command(self, dst: str) -> str:
        q = shlex.quote
        return (f'mkdir -p {q(dst)} && '
                f'cp -a {q(self.root)}/. {q(dst)}/')

    def upload_command(self, src: str) -> str:
        q = shlex.quote
        return (f'mkdir -p {q(self.root)} && '
                f'cp -a {q(src)}/. {q(self.root)}/')

    def mount_command(self, mount_point: str) -> str:
        q = shlex.quote
        return (f'mkdir -p {q(self.root)} && '
                f'mkdir -p $(dirname {q(mount_point)}) && '
                f'rm -rf {q(mount_point)} && '
                f'ln -sfn {q(self.root)} {q(mount_point)}')

    def mount_cached_command(self, mount_point: str) -> str:
        # Local disk IS the cache; the symlink mount is already both.
        return self.mount_command(mount_point)

    def upload_local(self, local_path: str) -> None:
        local_path = os.path.expanduser(local_path)
        os.makedirs(self.root, exist_ok=True)
        if os.path.isdir(local_path):
            shutil.copytree(local_path, self.root, dirs_exist_ok=True)
        else:
            shutil.copy2(local_path, self.root)

    def download_local(self, local_path: str) -> None:
        if not os.path.isdir(self.root):
            raise exceptions.StorageError(f'{self.url} does not exist')
        shutil.copytree(self.root, local_path, dirs_exist_ok=True)

    def exists(self) -> bool:
        return os.path.isdir(self.root)


class R2Store(S3Store):
    """Cloudflare R2 via its S3-compatible endpoint.

    Reference counterpart: sky/data/storage.py R2Store (:519 family —
    there cloudflare adaptors build a boto3 session against the account
    endpoint). Here the S3Store machinery runs unchanged with
    ``--endpoint-url https://<account>.r2.cloudflarestorage.com``; the
    account id comes from ``$R2_ACCOUNT_ID`` or ``r2.account_id`` in
    ~/.skytpu/config.yaml, credentials from the standard AWS_* env that
    R2 tokens emulate.
    """

    SCHEME = 'r2'

    def _endpoint(self) -> str:
        from skypilot_tpu import config as config_lib
        account = (os.environ.get('R2_ACCOUNT_ID')
                   or config_lib.get_nested(('r2', 'account_id'), None))
        if not account:
            raise exceptions.StorageError(
                'R2 stores need an account id: set $R2_ACCOUNT_ID or '
                'r2.account_id in ~/.skytpu/config.yaml.')
        return f'https://{account}.r2.cloudflarestorage.com'


class IbmCosStore(S3Store):
    """IBM Cloud Object Storage via its S3-compatible endpoint.

    Reference counterpart: sky/data/storage.py IBMCosStore (:3752 family
    — there the ibm SDK builds clients). COS speaks the S3 API at
    regional endpoints, so the S3Store machinery runs unchanged with
    ``--endpoint-url https://s3.<region>.cloud-object-storage.appdomain.
    cloud``; region from ``$IBM_COS_REGION`` or ``ibm.cos_region`` in
    ~/.skytpu/config.yaml, HMAC credentials via the standard AWS_* env.
    """

    SCHEME = 'cos'

    def _endpoint(self) -> str:
        from skypilot_tpu import config as config_lib
        region = (os.environ.get('IBM_COS_REGION')
                  or config_lib.get_nested(('ibm', 'cos_region'), None))
        if not region:
            raise exceptions.StorageError(
                'IBM COS stores need a region: set $IBM_COS_REGION or '
                'ibm.cos_region in ~/.skytpu/config.yaml.')
        return (f'https://s3.{region}.cloud-object-storage'
                '.appdomain.cloud')


class OciStore(S3Store):
    """OCI Object Storage via its S3-compatible endpoint.

    Reference counterpart: sky/data/storage.py OciStore (:4216 family).
    OCI's S3 compatibility API lives at
    ``https://<namespace>.compat.objectstorage.<region>.oraclecloud.com``;
    namespace+region from ``$OCI_NAMESPACE``/``$OCI_REGION`` or
    ``oci.namespace``/``oci.region`` config, customer secret keys via
    the standard AWS_* env.
    """

    SCHEME = 'oci'

    def _endpoint(self) -> str:
        from skypilot_tpu import config as config_lib
        namespace = (os.environ.get('OCI_NAMESPACE')
                     or config_lib.get_nested(('oci', 'namespace'), None))
        region = (os.environ.get('OCI_REGION')
                  or config_lib.get_nested(('oci', 'region'), None))
        if not namespace or not region:
            raise exceptions.StorageError(
                'OCI stores need a namespace and region: set '
                '$OCI_NAMESPACE/$OCI_REGION or oci.namespace/oci.region '
                'in ~/.skytpu/config.yaml.')
        return (f'https://{namespace}.compat.objectstorage.{region}'
                '.oraclecloud.com')


class AzureBlobStore(AbstractStore):
    """Azure Blob Storage via rclone (sync + FUSE mount).

    Reference counterpart: sky/data/storage.py AzureBlobStore (:2413
    family — there the azure SDK builds container clients; here the
    rclone machinery used for S3/R2 runs with the ``azureblob`` remote
    type, so remote hosts need no azure SDK). The bucket is the
    CONTAINER; the storage account comes from ``$AZURE_STORAGE_ACCOUNT``
    or ``azure.storage_account`` in ~/.skytpu/config.yaml (same pattern
    as R2's account id), credentials from the standard
    AZURE_STORAGE_KEY / AZURE_STORAGE_SAS_TOKEN env.
    """

    SCHEME = 'az'

    def _account(self) -> str:
        from skypilot_tpu import config as config_lib
        account = (os.environ.get('AZURE_STORAGE_ACCOUNT')
                   or config_lib.get_nested(('azure', 'storage_account'),
                                            None))
        if not account:
            raise exceptions.StorageError(
                'Azure blob stores need a storage account: set '
                '$AZURE_STORAGE_ACCOUNT or azure.storage_account in '
                '~/.skytpu/config.yaml.')
        return account

    def _env_prefix(self) -> str:
        from skypilot_tpu.data import mounting_utils
        return mounting_utils.azureblob_rclone_env_prefix(self._account())

    @property
    def _remote_path(self) -> str:
        path = f'skytpu-az:{self.bucket}'
        return f'{path}/{self.sub_path}' if self.sub_path else path

    def download_command(self, dst: str) -> str:
        from skypilot_tpu.data import mounting_utils
        q = shlex.quote
        return (f'mkdir -p {q(dst)} && '
                f'{mounting_utils._INSTALL_RCLONE} && '  # pylint: disable=protected-access
                f'{self._env_prefix()}'
                f'rclone sync {q(self._remote_path)} {q(dst)}')

    def upload_command(self, src: str) -> str:
        from skypilot_tpu.data import mounting_utils
        q = shlex.quote
        return (f'{mounting_utils._INSTALL_RCLONE} && '  # pylint: disable=protected-access
                f'{self._env_prefix()}'
                f'rclone sync {q(src)} {q(self._remote_path)}')

    def mount_command(self, mount_point: str) -> str:
        from skypilot_tpu.data import mounting_utils
        return mounting_utils.rclone_azureblob_mount_command(
            self.bucket, mount_point, self.sub_path,
            account=self._account(), read_only=False)

    def mount_cached_command(self, mount_point: str) -> str:
        from skypilot_tpu.data import mounting_utils
        return mounting_utils.rclone_azureblob_mount_command(
            self.bucket, mount_point, self.sub_path,
            account=self._account(), cached=True)

    def _rclone(self, *args: str):
        from skypilot_tpu.data import mounting_utils
        env = dict(os.environ,
                   **mounting_utils.azureblob_rclone_env(self._account()))
        return subprocess.run(['rclone', *args], capture_output=True,
                              text=True, env=env)

    def upload_local(self, local_path: str) -> None:
        proc = self._rclone('sync', os.path.expanduser(local_path),
                            self._remote_path)
        if proc.returncode != 0:
            raise exceptions.StorageError(
                f'upload to {self.url} failed: {proc.stderr[-500:]}')

    def download_local(self, local_path: str) -> None:
        os.makedirs(local_path, exist_ok=True)
        proc = self._rclone('sync', self._remote_path, local_path)
        if proc.returncode != 0:
            raise exceptions.StorageError(
                f'download from {self.url} failed: {proc.stderr[-500:]}')

    def exists(self) -> bool:
        return self._rclone('lsd', self._remote_path).returncode == 0


_STORES: Dict[str, Type[AbstractStore]] = {}


def register_store(cls: Type[AbstractStore]) -> Type[AbstractStore]:
    _STORES[cls.SCHEME] = cls
    return cls


register_store(GcsStore)
register_store(S3Store)
register_store(R2Store)
register_store(IbmCosStore)
register_store(OciStore)
register_store(AzureBlobStore)
register_store(LocalStore)


def is_store_url(value: str) -> bool:
    scheme = value.split('://', 1)[0] if '://' in value else ''
    return scheme in _STORES


def parse_store_url(url: str) -> AbstractStore:
    if '://' not in url:
        raise exceptions.StorageError(f'not a store URL: {url!r}')
    scheme, rest = url.split('://', 1)
    if scheme not in _STORES:
        raise exceptions.StorageError(
            f'unsupported store scheme {scheme!r} (have: '
            f'{sorted(_STORES)})')
    rest = rest.rstrip('/')
    if scheme == 'file':
        # file:///abs/dir -> bucket is the abs path minus leading slash.
        bucket, sub = rest.lstrip('/'), ''
    else:
        bucket, _, sub = rest.partition('/')
    if not bucket:
        raise exceptions.StorageError(f'empty bucket in {url!r}')
    return _STORES[scheme](bucket, sub)


class Storage:
    """A named bucket a task mounts (MOUNT) or materializes (COPY).

    Reference: sky/data/storage.py:519 Storage. YAML forms accepted in
    ``file_mounts`` (same surface as the reference):

        /data: gs://bucket/path              # implicit COPY storage
        /ckpt:
          name: my-bucket                    # or source: gs://...
          store: gcs
          mode: MOUNT
        /out:
          source: ./local_dir                # uploaded, then mounted
          store: gcs
          mode: COPY
    """

    def __init__(self, name: Optional[str] = None,
                 source: Optional[str] = None,
                 mode: StorageMode = StorageMode.COPY,
                 store: Optional[str] = None):
        if name is None and source is None:
            raise exceptions.StorageError(
                'Storage needs a name or a source')
        self.mode = mode
        self.local_source: Optional[str] = None
        self._from_url = False

        if source is not None and is_store_url(source):
            self.store: AbstractStore = parse_store_url(source)
            self._from_url = True
        elif source is not None:
            # Local path to be uploaded into a named bucket.
            expanded = os.path.expanduser(source)
            if not os.path.exists(expanded):
                raise exceptions.StorageError(
                    f'storage source {source!r} does not exist locally')
            if name is None:
                raise exceptions.StorageError(
                    f'storage with local source {source!r} needs a bucket '
                    'name')
            self.local_source = expanded
            scheme = store or 'gs'
            self.store = _STORES[_normalize_scheme(scheme)](name)
        else:
            scheme = store or 'gs'
            self.store = _STORES[_normalize_scheme(scheme)](name)

    @property
    def url(self) -> str:
        return self.store.url

    def validate(self) -> None:
        """Early existence check at task submission (reference
        sky/data/storage.py source-bucket validation): a task pointing at
        a nonexistent source bucket must fail NOW with a clear error, not
        minutes later on a provisioned (billing) cluster.

        Bucket-level only (an empty prefix the task will write into is
        legitimate), and advisory when the cloud CLI is absent on the
        client — the hosts surface the error at COPY/MOUNT time then.
        """
        if not self._from_url:
            return
        try:
            ok = self.store.bucket_exists()
        except FileNotFoundError:
            return  # no cloud CLI on this client: cannot check here
        if not ok:
            raise exceptions.StorageError(
                f'storage source {self.url} does not exist or is not '
                'accessible with the current credentials')

    def sync_local_source(self) -> None:
        """Upload the local source into the bucket (no-op otherwise)."""
        if self.local_source is not None:
            self.store.upload_local(self.local_source)

    # -- YAML ---------------------------------------------------------------
    @classmethod
    def from_yaml_config(cls, config: Any) -> 'Storage':
        if isinstance(config, str):
            return cls(source=config)
        if not isinstance(config, dict):
            raise exceptions.StorageError(
                f'bad storage config: {config!r}')
        mode = StorageMode(str(config.get('mode', 'COPY')).upper())
        return cls(name=config.get('name'), source=config.get('source'),
                   mode=mode, store=config.get('store'))

    def to_yaml_config(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {'mode': self.mode.value}
        if self.local_source is not None:
            out['source'] = self.local_source
            out['name'] = self.store.bucket
            out['store'] = self.store.SCHEME
        else:
            out['source'] = self.store.url
        return out

    def __repr__(self) -> str:
        return f'Storage({self.store.url!r}, mode={self.mode.value})'


def _normalize_scheme(store: str) -> str:
    aliases = {'gcs': 'gs', 'gs': 'gs', 's3': 's3', 'aws': 's3',
               'r2': 'r2', 'az': 'az', 'azure': 'az',
               'cos': 'cos', 'ibm': 'cos', 'oci': 'oci',
               'file': 'file', 'local': 'file'}
    try:
        return aliases[store.lower()]
    except KeyError:
        raise exceptions.StorageError(
            f'unknown store {store!r} (have: {sorted(set(aliases))})'
        ) from None
