"""Data plane: bucket storage (COPY/MOUNT) for task file mounts.

Counterpart of reference ``sky/data`` (Storage/AbstractStore with COPY and
MOUNT modes, sky/data/storage.py:118,265,279,519; FUSE mount script
generation, sky/data/mounting_utils.py:41-464). GCS-first; a hermetic
``file://`` store backs unit/e2e tests the way the local cloud backs the
provisioner tests.
"""
from skypilot_tpu.data.storage import (AbstractStore, GcsStore, LocalStore,
                                       Storage, StorageMode, parse_store_url)

__all__ = [
    'AbstractStore',
    'GcsStore',
    'LocalStore',
    'Storage',
    'StorageMode',
    'parse_store_url',
]
