"""FUSE mount script generation (gcsfuse-first).

Counterpart of reference ``sky/data/mounting_utils.py:41-464`` (per-tool
install + mount command builders wrapped in a guard script). Only the GCS
path is generated here; the hermetic LocalStore "mounts" via symlink (see
data/storage.py) so tests never need FUSE.
"""
from __future__ import annotations

import shlex

GCSFUSE_VERSION = '2.4.0'

_INSTALL_GCSFUSE = (
    'command -v gcsfuse >/dev/null || { '
    'ARCH=$(uname -m | grep -q aarch64 && echo arm64 || echo amd64); '
    'curl -fsSL -o /tmp/gcsfuse.deb '
    'https://github.com/GoogleCloudPlatform/gcsfuse/releases/download/'
    f'v{GCSFUSE_VERSION}/gcsfuse_{GCSFUSE_VERSION}_$ARCH.deb && '
    'sudo dpkg -i /tmp/gcsfuse.deb || sudo apt-get install -f -y; }')


def gcsfuse_mount_command(bucket: str, mount_point: str,
                          sub_path: str = '') -> str:
    """Idempotent install + mount script for one bucket.

    ``--implicit-dirs`` so object prefixes act as directories; the
    ``only_dir`` flag scopes a bucket subpath (reference mounting_utils
    gcsfuse branch).
    """
    q = shlex.quote
    only_dir = f'--only-dir {q(sub_path)} ' if sub_path else ''
    return (
        f'{_INSTALL_GCSFUSE} && '
        f'sudo mkdir -p {q(mount_point)} && '
        f'sudo chown $(id -u):$(id -g) {q(mount_point)} && '
        f'(mountpoint -q {q(mount_point)} || '
        f'gcsfuse --implicit-dirs {only_dir}{q(bucket)} {q(mount_point)})')


def unmount_command(mount_point: str) -> str:
    q = shlex.quote
    return (f'mountpoint -q {q(mount_point)} && '
            f'(fusermount -u {q(mount_point)} || '
            f'sudo umount {q(mount_point)}) || true')
