"""FUSE mount script generation (gcsfuse for GCS, rclone for S3).

Counterpart of reference ``sky/data/mounting_utils.py:41-367`` (per-tool
install + mount command builders wrapped in a guard script; the reference
mounts S3 via goofys or rclone — rclone here: still maintained, single
static binary, no per-host config file needed thanks to env-based remote
config). The hermetic LocalStore "mounts" via symlink (see
data/storage.py) so tests never need FUSE.
"""
from __future__ import annotations

import shlex

GCSFUSE_VERSION = '2.4.0'
RCLONE_VERSION = '1.67.0'


def _deb_install(tool: str, url_template: str) -> str:
    """Idempotent guard that downloads + installs a .deb for the host
    arch. ``{arch}`` in the template expands to the shell's $ARCH.
    Grouping matters: `apt-get install -f` only repairs a FAILED dpkg —
    it must not mask a failed download (a bare `a && b || c` would run c,
    exit 0, and defer the real error to a confusing 'command not
    found' at mount time)."""
    url = url_template.format(arch='$ARCH')
    return (f'command -v {tool} >/dev/null || {{ '
            'ARCH=$(uname -m | grep -q aarch64 && echo arm64 '
            '|| echo amd64); '
            f'curl -fsSL -o /tmp/{tool}.deb {url} && '
            f'{{ sudo dpkg -i /tmp/{tool}.deb '
            '|| sudo apt-get install -f -y; }; }')


_INSTALL_GCSFUSE = _deb_install(
    'gcsfuse',
    'https://github.com/GoogleCloudPlatform/gcsfuse/releases/download/'
    f'v{GCSFUSE_VERSION}/gcsfuse_{GCSFUSE_VERSION}_{{arch}}.deb')


def gcsfuse_mount_command(bucket: str, mount_point: str,
                          sub_path: str = '') -> str:
    """Idempotent install + mount script for one bucket.

    ``--implicit-dirs`` so object prefixes act as directories; the
    ``only_dir`` flag scopes a bucket subpath (reference mounting_utils
    gcsfuse branch).
    """
    q = shlex.quote
    only_dir = f'--only-dir {q(sub_path)} ' if sub_path else ''
    return (
        f'{_INSTALL_GCSFUSE} && '
        f'sudo mkdir -p {q(mount_point)} && '
        f'sudo chown $(id -u):$(id -g) {q(mount_point)} && '
        f'(mountpoint -q {q(mount_point)} || '
        f'gcsfuse --implicit-dirs {only_dir}{q(bucket)} {q(mount_point)})')


_INSTALL_RCLONE = _deb_install(
    'rclone',
    'https://github.com/rclone/rclone/releases/download/'
    f'v{RCLONE_VERSION}/rclone-v{RCLONE_VERSION}-linux-{{arch}}.deb')


def _rclone_mount(src: str, mount_point: str, env_prefix: str,
                  cached: bool, read_only: bool) -> str:
    """The one rclone mount shape, shared by every remote type.

    Flavors (reference sky/data/mounting_utils.py:302-314, the rclone
    vfs-cache writeback branch):
    - read_only:  ``--read-only`` dataset-source mount;
    - writable:   ``--vfs-cache-mode writes`` — writes buffer locally
      and upload on close (checkpoint-to-bucket works; partial-write
      visibility is at file granularity, like the reference's mounts);
    - cached (MOUNT_CACHED): ``--vfs-cache-mode full`` with async
      write-back — reads cache locally too and writes flush in the
      background, decoupling training-step latency from object-store
      latency.
    """
    q = shlex.quote
    if cached:
        flavor = ('--vfs-cache-mode full --vfs-write-back 1s '
                  '--vfs-cache-max-size 10G --dir-cache-time 5s ')
    elif read_only:
        flavor = '--read-only --dir-cache-time 30s '
    else:
        flavor = '--vfs-cache-mode writes --dir-cache-time 30s '
    return (
        f'{_INSTALL_RCLONE} && '
        f'sudo mkdir -p {q(mount_point)} && '
        f'sudo chown $(id -u):$(id -g) {q(mount_point)} && '
        f'(mountpoint -q {q(mount_point)} || '
        f'{env_prefix}'
        f'rclone mount {q(src)} {q(mount_point)} '
        f'--daemon --allow-non-empty {flavor}'
        '--vfs-read-chunk-size 64M)')


def s3_rclone_env_prefix(endpoint: str = '') -> str:
    """The one definition of the rclone S3 remote, as a shell env
    prefix: ``env_auth`` picks up the instance role / AWS_* credentials
    — no config file to ship. ``endpoint`` targets S3-compatible
    providers (Cloudflare R2 etc.)."""
    q = shlex.quote
    provider = ('RCLONE_CONFIG_SKYTPU_S3_PROVIDER=Other '
                f'RCLONE_CONFIG_SKYTPU_S3_ENDPOINT={q(endpoint)} '
                if endpoint else 'RCLONE_CONFIG_SKYTPU_S3_PROVIDER=AWS ')
    return ('RCLONE_CONFIG_SKYTPU_S3_TYPE=s3 '
            f'{provider}'
            'RCLONE_CONFIG_SKYTPU_S3_ENV_AUTH=true ')


def rclone_s3_mount_command(bucket: str, mount_point: str,
                            sub_path: str = '',
                            read_only: bool = False,
                            endpoint: str = '',
                            cached: bool = False) -> str:
    """Idempotent install + rclone FUSE mount of an S3(-compatible)
    bucket. Writable by default (checkpoint-to-bucket on AWS clusters
    needs a mount path); ``cached`` selects the MOUNT_CACHED write-back
    flavor. Reference counterpart: sky/data/mounting_utils.py:41-367."""
    src = f'skytpu-s3:{bucket}'
    if sub_path:
        src += f'/{sub_path}'
    return _rclone_mount(src, mount_point,
                         s3_rclone_env_prefix(endpoint),
                         cached=cached, read_only=read_only)


def rclone_gcs_mount_command(bucket: str, mount_point: str,
                             sub_path: str = '',
                             cached: bool = True) -> str:
    """rclone mount of a GCS bucket — used for MOUNT_CACHED (plain MOUNT
    uses gcsfuse, which has no write-back cache mode). ``env_auth``
    rides the VM/TPU-VM service account."""
    src = f'skytpu-gcs:{bucket}'
    if sub_path:
        src += f'/{sub_path}'
    env = ("RCLONE_CONFIG_SKYTPU_GCS_TYPE='google cloud storage' "
           'RCLONE_CONFIG_SKYTPU_GCS_ENV_AUTH=true '
           'RCLONE_CONFIG_SKYTPU_GCS_BUCKET_POLICY_ONLY=true ')
    return _rclone_mount(src, mount_point, env, cached=cached,
                         read_only=False)


def unmount_command(mount_point: str) -> str:
    q = shlex.quote
    return (f'mountpoint -q {q(mount_point)} && '
            f'(fusermount -u {q(mount_point)} || '
            f'sudo umount {q(mount_point)}) || true')


def azureblob_rclone_env(account: str) -> 'dict[str, str]':
    """The one definition of the rclone azureblob remote: account from
    config, key/SAS from the standard AZURE_STORAGE_KEY /
    AZURE_STORAGE_SAS_TOKEN env (or MSI on Azure VMs via env_auth).
    Shared by blob-store sync commands and the FUSE mount."""
    return {
        'RCLONE_CONFIG_SKYTPU_AZ_TYPE': 'azureblob',
        'RCLONE_CONFIG_SKYTPU_AZ_ACCOUNT': account,
        'RCLONE_CONFIG_SKYTPU_AZ_ENV_AUTH': 'true',
    }


def azureblob_rclone_env_prefix(account: str) -> str:
    """azureblob_rclone_env as a shell `K=V K=V ` command prefix."""
    return ' '.join(f'{k}={shlex.quote(v)}' for k, v in
                    azureblob_rclone_env(account).items()) + ' '


def rclone_azureblob_mount_command(container: str, mount_point: str,
                                   sub_path: str = '',
                                   account: str = '',
                                   read_only: bool = False,
                                   cached: bool = False) -> str:
    """Idempotent install + rclone FUSE mount of an Azure blob container.

    Same rclone machinery as the S3 mount, with the ``azureblob`` remote
    type. Reference counterpart: the blobfuse2 branch of
    sky/data/mounting_utils.py.
    """
    src = f'skytpu-az:{container}'
    if sub_path:
        src += f'/{sub_path}'
    return _rclone_mount(src, mount_point,
                         azureblob_rclone_env_prefix(account),
                         cached=cached, read_only=read_only)
