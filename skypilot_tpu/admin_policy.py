"""Pluggable admin policy: organization-wide request mutation/validation.

Counterpart of reference ``sky/admin_policy.py`` (AdminPolicy/UserRequest/
MutatedUserRequest) + its application point (sky/execution.py:180-187).
Deployments point ``admin_policy: mypkg.MyPolicy`` in config at a class:

    class MyPolicy(skypilot_tpu.admin_policy.AdminPolicy):
        @classmethod
        def validate_and_mutate(cls, user_request):
            task = user_request.task
            for r in task.resources:
                if not r.use_spot and r.accelerators \
                        and r.accelerators.chips > 64:
                    raise ValueError('big slices must use spot')
            return skypilot_tpu.admin_policy.MutatedUserRequest(task=task)

Policies run in-process, CLIENT-side, on every launch/exec/jobs_launch/
serve_up — before any cloud call, and before a managed-job task is shipped
to a (possibly remote) controller cluster that does not carry the client's
config. Controller-cluster bring-up itself arrives with
``operation='controller_launch'`` so infrastructure can be exempted from
workload rules.
"""
from __future__ import annotations

import abc
import dataclasses
import importlib
from typing import Any, Optional

from skypilot_tpu import config as config_lib
from skypilot_tpu import exceptions


@dataclasses.dataclass
class RequestOptions:
    """Context about the request (reference RequestOptions)."""
    cluster_name: Optional[str] = None
    operation: str = 'launch'      # launch | exec | jobs_launch | serve_up
    dryrun: bool = False


@dataclasses.dataclass
class UserRequest:
    task: Any                      # task_lib.Task
    request_options: RequestOptions


@dataclasses.dataclass
class MutatedUserRequest:
    task: Any


class AdminPolicy(abc.ABC):
    """Subclass + config `admin_policy: module.Class` to enforce."""

    @classmethod
    @abc.abstractmethod
    def validate_and_mutate(cls, user_request: UserRequest
                            ) -> MutatedUserRequest:
        """Raise to reject; return a (possibly mutated) request to allow."""


def _load_policy_class() -> Optional[type]:
    path = config_lib.get_nested(('admin_policy',), None)
    if not path:
        return None
    module_name, _, class_name = str(path).rpartition('.')
    if not module_name:
        raise exceptions.InvalidConfigError(
            f'admin_policy must be a full import path, got {path!r}')
    try:
        module = importlib.import_module(module_name)
        policy = getattr(module, class_name)
    except (ImportError, AttributeError) as e:
        raise exceptions.InvalidConfigError(
            f'Cannot import admin policy {path!r}: {e}') from e
    if not (isinstance(policy, type) and issubclass(policy, AdminPolicy)):
        raise exceptions.InvalidConfigError(
            f'{path!r} is not an AdminPolicy subclass')
    return policy


def apply(task: Any, cluster_name: Optional[str] = None,
          operation: str = 'launch', dryrun: bool = False) -> Any:
    """Run the configured policy over a task; returns the task to use."""
    policy = _load_policy_class()
    if policy is None:
        return task
    request = UserRequest(task=task, request_options=RequestOptions(
        cluster_name=cluster_name, operation=operation, dryrun=dryrun))
    try:
        mutated = policy.validate_and_mutate(request)
    except exceptions.SkyTpuError:
        raise
    except Exception as e:  # policy rejection
        raise exceptions.AdminPolicyRejected(
            f'Admin policy {policy.__name__} rejected the request: '
            f'{e}') from e
    return mutated.task
