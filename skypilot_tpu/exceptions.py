"""Typed error taxonomy for skypilot_tpu.

Mirrors the role of the reference's error taxonomy (``sky/exceptions.py``):
a small set of exception types that carry enough structure for the failover
engine (blocked resources, failover history) and for the CLI/SDK to render
actionable messages.
"""
from __future__ import annotations

import enum
from typing import Any, Dict, List, Optional


class SkyTpuError(Exception):
    """Base class for all framework errors."""


class InvalidTaskError(SkyTpuError):
    """Task YAML / Task object is malformed."""


class InvalidResourcesError(SkyTpuError):
    """Resources spec is malformed or internally inconsistent."""


class InvalidSliceError(InvalidResourcesError):
    """Unknown TPU slice type / topology."""


class InvalidYamlError(InvalidTaskError):
    """YAML failed schema validation."""


class ClusterNotUpError(SkyTpuError):
    """Operation requires an UP cluster (e.g. exec / queue / logs)."""

    def __init__(self, message: str, cluster_status: Optional[Any] = None):
        super().__init__(message)
        self.cluster_status = cluster_status


class ClusterDoesNotExist(SkyTpuError):
    """Named cluster has no record."""


class ClusterOwnerIdentityMismatchError(SkyTpuError):
    """Cluster was created under a different cloud identity."""


class NotSupportedError(SkyTpuError):
    """Feature unsupported by the requested cloud/backend."""


class CloudUserIdentityError(SkyTpuError):
    """Could not determine the active cloud identity."""


class CloudError(SkyTpuError):
    """An error returned by a cloud API call."""

    def __init__(self, message: str, *, code: Optional[int] = None,
                 reason: Optional[str] = None):
        super().__init__(message)
        self.code = code
        self.reason = reason


class ProvisionError(SkyTpuError):
    """Provisioning a cluster failed (possibly after retries)."""


class ClusterError(SkyTpuError):
    """A cluster-level operation failed (bad state, missing cluster)."""


class InsufficientCapacityError(CloudError):
    """The cloud has no capacity for the request in this zone/region.

    The failover engine treats this as 'blocklist the zone and move on'
    (reference GCP handler for TPU capacity errors,
    sky/backends/cloud_vm_ray_backend.py:1019-1050).
    """


class ResourcesUnavailableError(SkyTpuError):
    """No feasible resources (capacity/quota/feasibility).

    Carries ``failover_history`` so callers (managed jobs, CLI) can show why
    each candidate was rejected — same contract as the reference's
    ``ResourcesUnavailableError`` (sky/exceptions.py).
    """

    def __init__(self, message: str,
                 failover_history: Optional[List[Exception]] = None,
                 no_failover: bool = False):
        super().__init__(message)
        self.failover_history: List[Exception] = failover_history or []
        self.no_failover = no_failover

    def with_failover_history(
            self, history: List[Exception]) -> 'ResourcesUnavailableError':
        self.failover_history = history
        return self


class ResourcesMismatchError(SkyTpuError):
    """Requested resources do not match the target cluster's resources."""


class CommandError(SkyTpuError):
    """A remote/local command returned non-zero."""

    def __init__(self, returncode: int, command: str, error_msg: str = '',
                 detailed_reason: Optional[str] = None):
        self.returncode = returncode
        self.command = command
        self.error_msg = error_msg
        self.detailed_reason = detailed_reason
        cmd = command if len(command) < 100 else command[:100] + '...'
        super().__init__(
            f'Command {cmd} failed with return code {returncode}.\n'
            f'{error_msg}')


class JobNotFoundError(SkyTpuError):
    """Job id not present in the cluster job queue."""


class JobExitCode(enum.IntEnum):
    """Exit codes for job-related CLI commands (mirrors reference mapping)."""
    SUCCEEDED = 0
    FAILED = 100
    NOT_FINISHED = 101
    NOT_FOUND = 102


class StorageError(SkyTpuError):
    """Storage (bucket) creation/sync/mount errors."""


class StorageBucketCreateError(StorageError):
    pass


class StorageBucketGetError(StorageError):
    pass


class StorageUploadError(StorageError):
    pass


class ServeUserTerminatedError(SkyTpuError):
    """Service was terminated by the user mid-operation."""


class RequestCancelled(SkyTpuError):
    """An API-server request was cancelled."""


class RequestNotFoundError(SkyTpuError):
    """Unknown API-server request id."""


class ApiServerConnectionError(SkyTpuError):
    """Could not reach the API server."""

    def __init__(self, server_url: str):
        super().__init__(
            f'Could not connect to API server at {server_url}. '
            f'Start one with `skytpu api start`.')
        self.server_url = server_url


class ManagedJobReachedMaxRetriesError(SkyTpuError):
    """Managed job exhausted launch retries during recovery."""


class ManagedJobStatusError(SkyTpuError):
    """Managed job is in an unexpected state."""


class NoClusterLaunchedError(SkyTpuError):
    """Failover ran out of candidates before launching anything."""


class InvalidConfigError(SkyTpuError):
    """Malformed ~/.skytpu/config.yaml content (e.g. bad admin_policy)."""


class AdminPolicyRejected(SkyTpuError):
    """The configured admin policy refused the request."""


def serialize_exception(e: Exception) -> Dict[str, Any]:
    """JSON-serializable form for shipping errors across the API server."""
    return {
        'type': type(e).__name__,
        'message': str(e),
        'attrs': {
            k: v for k, v in getattr(e, '__dict__', {}).items()
            if isinstance(v, (str, int, float, bool, type(None)))
        },
    }


def deserialize_exception(d: Dict[str, Any]) -> Exception:
    cls = globals().get(d.get('type', ''), SkyTpuError)
    if not (isinstance(cls, type) and issubclass(cls, Exception)):
        cls = SkyTpuError
    # Bypass subclass __init__ (signatures vary and some rebuild the
    # message); restore type, message, and flat attrs directly.
    e = cls.__new__(cls)
    Exception.__init__(e, d.get('message', ''))
    for k, v in d.get('attrs', {}).items():
        try:
            setattr(e, k, v)
        except (AttributeError, TypeError):
            # Read-only properties / __slots__ mismatches on exception
            # subclasses: keep the attrs that do restore.
            pass
    return e
