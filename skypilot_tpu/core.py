"""Server-side core ops: status/start/stop/down/autostop/queue/cancel/logs.

Counterpart of reference ``sky/core.py`` (status:92, start:399, down:471,
stop:506, autostop:566, queue:670, cancel:733, tail_logs:828). The status
refresh reconciles the sqlite record against cloud truth
(reference backend_utils._update_cluster_status:1769 — "the subtlest code
in the reference", SURVEY.md §7; ours is simpler because host groups are
atomic: a TPU slice is all-up or not).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

import filelock

from skypilot_tpu import backends
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import provision as provision_lib
from skypilot_tpu.utils import locks

ClusterStatus = global_user_state.ClusterStatus


def _refresh_record(record: Dict[str, Any]) -> Optional[Dict[str, Any]]:
    """Reconcile one cluster record against the cloud; returns the updated
    record, or None if the cluster no longer exists on the cloud.

    Takes the per-cluster lock *non-blocking*: if a lifecycle op (provision/
    start/stop/down) holds it, the cached record is returned unmodified
    rather than racing the mutation (reference refresh_cluster_record
    acquires with a short timeout and falls back to the cached row).
    """
    handle = record['handle']
    name = record['name']
    if handle is None:
        return record  # mid-provision INIT record; leave as-is
    try:
        with locks.cluster_lock(name).acquire(timeout=0):
            return _refresh_record_locked(record)
    except filelock.Timeout:
        return record  # lifecycle op in flight: keep the cached record


_AGENT_STALE_S = 60.0       # heartbeat older than this => runtime down
_AGENT_PROBE_TTL_S = 15.0   # probe at most this often per cluster


def _agent_healthy(handle: Any) -> bool:
    """Is the head agent alive? (reference health-checks `ray status` on
    refresh, backend_utils.py:912; here the agent heartbeat file is the
    runtime's pulse). Probes are TTL-cached: job-status pollers call
    refresh frequently and must not pay an SSH round-trip each time."""
    import time as time_lib

    from skypilot_tpu.runtime import constants as rt_constants
    cache_key = f'agent_probe:{handle.cluster_name}'
    cached = global_user_state.get_kv(cache_key)
    now = time_lib.time()
    if cached:
        ts, _, verdict = cached.partition(':')
        if now - float(ts) < _AGENT_PROBE_TTL_S:
            return verdict == 'ok'
    try:
        info = provision_lib.get_cluster_info(handle.cloud,
                                              handle.cluster_name,
                                              handle.region)
        head = provision_lib.get_command_runners(handle.cloud, info)[0]
        hb = (f'{rt_constants.RUNTIME_DIR}/'
              f'{rt_constants.HEARTBEAT_FILE}')
        # Age computed host-side: heartbeats carry the HOST's clock.
        res = head.run(
            f't=$(cat {hb} 2>/dev/null); [ -n "$t" ] || exit 9; '
            'echo $(( $(date +%s) - ${t%.*} ))', timeout=30)
        ok = (res.returncode == 0
              and res.stdout.strip().lstrip('-').isdigit()
              and int(res.stdout.strip()) < _AGENT_STALE_S)
    except Exception:  # noqa: BLE001 — unreachable host = unhealthy
        ok = False
    global_user_state.set_kv(cache_key,
                             f'{now}:{"ok" if ok else "down"}')
    return ok


def _refresh_record_locked(record: Dict[str, Any]
                           ) -> Optional[Dict[str, Any]]:
    """The reconciliation machine (reference _update_cluster_status:1769):

    cloud says                                  -> record becomes
    ------------------------------------------------------------------
    nothing (terminated / autostop --down)      -> record removed
    any 'preempted'/'terminated' host           -> slice cleaned up,
                                                   record removed (spot
                                                   slices die whole;
                                                   reference gcp.py:981)
    all 'running' + agent heartbeat fresh       -> UP
    all 'running' + agent dead/stale            -> INIT (hosts up,
                                                   runtime down)
    all 'stopped'                               -> STOPPED (+ autostop
                                                   disarmed: a stopped
                                                   cluster can't idle)
    anything else (pending/stopping/mixed)      -> INIT (transitional)
    """
    handle = record['handle']
    name = record['name']
    try:
        states = provision_lib.query_instances(handle.cloud, name,
                                               handle.region)
    except exceptions.SkyTpuError:
        return record  # cloud unreachable: keep stale record
    if not states:
        # Terminated externally (or autostop --down): drop the record.
        global_user_state.remove_cluster(name, terminate=True)
        return None
    values = set(states.values())
    if 'preempted' in values or 'terminated' in values:
        # A spot TPU slice lost capacity: the carcass still holds the
        # name/quota — delete it, then drop the record so managed jobs
        # see a clean "cluster gone" preemption signal.
        try:
            provision_lib.terminate_instances(handle.cloud, name,
                                              handle.region)
        except exceptions.SkyTpuError:
            pass
        global_user_state.remove_cluster(name, terminate=True)
        return None
    if values == {'running'}:
        new_status = (ClusterStatus.UP if _agent_healthy(handle)
                      else ClusterStatus.INIT)
    elif values == {'stopped'}:
        new_status = ClusterStatus.STOPPED
        if record.get('autostop', -1) is not None and \
                record.get('autostop', -1) >= 0:
            global_user_state.set_cluster_autostop(name, -1, False)
            record = dict(record, autostop=-1, to_down=False)
    else:
        new_status = ClusterStatus.INIT  # partial/transitional
    if new_status != record['status']:
        global_user_state.update_cluster_status(name, new_status)
        record = dict(record, status=new_status)
    return record


def status(cluster_names: Optional[List[str]] = None,
           refresh: bool = True) -> List[Dict[str, Any]]:
    records = global_user_state.get_clusters()
    if cluster_names:
        records = [r for r in records if r['name'] in cluster_names]
    if not refresh:
        return records
    if len(records) > 1:
        # Each refresh is an independent cloud query + possible SSH probe
        # behind its own per-cluster lock: run them concurrently so
        # `status --refresh` over N clusters is O(slowest), not O(sum)
        # (reference batches refresh with a process pool,
        # sky/backends/backend_utils.py:2084).
        from concurrent.futures import ThreadPoolExecutor
        with ThreadPoolExecutor(
                max_workers=min(16, len(records))) as pool:
            refreshed_all = list(pool.map(_refresh_record, records))
    else:
        refreshed_all = [_refresh_record(r) for r in records]
    return [r for r in refreshed_all if r is not None]


def _get_handle(cluster_name: str, need_up: bool = False
                ) -> backends.ResourceHandle:
    record = global_user_state.get_cluster_from_name(cluster_name)
    if record is None or record['handle'] is None:
        raise exceptions.ClusterDoesNotExist(
            f'Cluster {cluster_name!r} does not exist.')
    if need_up:
        record = _refresh_record(record)
        if record is None:
            raise exceptions.ClusterDoesNotExist(
                f'Cluster {cluster_name!r} no longer exists on the cloud.')
        if record['status'] != ClusterStatus.UP:
            raise exceptions.ClusterNotUpError(
                f'Cluster {cluster_name!r} is {record["status"].value}.',
                cluster_status=record['status'])
    return record['handle']


def start(cluster_name: str) -> None:
    with locks.cluster_lock(cluster_name):
        handle = _get_handle(cluster_name)
        backends.SliceBackend().restart(handle)


def stop(cluster_name: str) -> None:
    with locks.cluster_lock(cluster_name):
        handle = _get_handle(cluster_name)
        backends.SliceBackend().teardown(handle, terminate=False)


def down(cluster_name: str) -> None:
    with locks.cluster_lock(cluster_name):
        handle = _get_handle(cluster_name)
        backends.SliceBackend().teardown(handle, terminate=True)


def autostop(cluster_name: str, idle_minutes: int,
             down_on_idle: bool = False) -> None:
    with locks.cluster_lock(cluster_name):
        handle = _get_handle(cluster_name, need_up=True)
        backends.SliceBackend().set_autostop(handle, idle_minutes,
                                             down_on_idle)


def queue(cluster_name: str) -> List[Dict[str, Any]]:
    handle = _get_handle(cluster_name, need_up=True)
    return backends.SliceBackend().queue(handle)


def cancel(cluster_name: str, job_ids: Optional[List[int]] = None,
           all_jobs: bool = False) -> List[int]:
    handle = _get_handle(cluster_name, need_up=True)
    return backends.SliceBackend().cancel_jobs(handle, job_ids=job_ids,
                                               all_jobs=all_jobs)


def tail_logs(cluster_name: str, job_id: Optional[int] = None,
              follow: bool = True) -> int:
    handle = _get_handle(cluster_name, need_up=True)
    return backends.SliceBackend().tail_logs(handle, job_id, follow=follow)


def job_status(cluster_name: str, job_id: int) -> Optional[str]:
    handle = _get_handle(cluster_name, need_up=True)
    return backends.SliceBackend().job_status(handle, job_id)


def cost_report() -> List[Dict[str, Any]]:
    """Per-cluster cost history (reference core.py cost_report)."""
    import time as time_lib
    out = []
    for row in global_user_state.get_cluster_history():
        resources = row.get('resources')
        if isinstance(resources, (tuple, list)):
            resources = resources[0] if resources else None
        cost_per_hour = 0.0
        try:
            from skypilot_tpu import clouds as clouds_lib
            if resources is not None and resources.cloud:
                cloud = clouds_lib.get_cloud(resources.cloud)
                cost_per_hour = cloud.hourly_cost(resources,
                                                  resources.region,
                                                  resources.zone)
        except (exceptions.SkyTpuError, AssertionError, KeyError,
                ValueError, NotImplementedError):
            # Historical rows can name clouds/shapes no longer in the
            # catalog; the report shows cost 0.0 for them rather than
            # dying — but programming errors must still surface.
            pass
        duration = row.get('duration_s')
        if duration is None:
            duration = int(time_lib.time()) - row['launched_at']
        out.append({
            'name': row['name'],
            'launched_at': row['launched_at'],
            'duration_s': duration,
            'num_hosts': row['num_hosts'],
            'cost': cost_per_hour * duration / 3600.0,
        })
    return out
