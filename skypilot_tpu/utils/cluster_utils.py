"""Per-cluster SSH config: ``ssh <cluster>`` just works after launch.

Counterpart of reference ``sky/utils/cluster_utils.py:38``
(``SSHConfigHelper`` writes Host blocks into the user's ssh config on
provision, removes them on down). Layout here: one file per cluster under
``<state_dir>/ssh/<cluster>.conf`` plus a single ``Include`` directive
prepended to the user ssh config (Include must appear before any Host
block to apply globally). ``$SKYTPU_SSH_CONFIG`` overrides the user
config path (tests point it into a temp dir).

Host aliases: ``<cluster>`` = head (rank 0), ``<cluster>-<rank>`` for
every host of a multi-host slice.
"""
from __future__ import annotations

import os
from typing import List, Optional

from skypilot_tpu import global_user_state

_MARKER = '# Added by skytpu (cluster ssh config)'


def _user_config_path() -> str:
    return os.path.expanduser(
        os.environ.get('SKYTPU_SSH_CONFIG', '~/.ssh/config'))


def _cluster_dir() -> str:
    return os.path.join(global_user_state.get_state_dir(), 'ssh')


def cluster_config_path(cluster_name: str) -> str:
    return os.path.join(_cluster_dir(), f'{cluster_name}.conf')


def _ensure_include() -> None:
    """Prepend ``Include <state>/ssh/*.conf`` to the user ssh config
    (idempotent). Prepended, not appended: ssh applies Include inside the
    scope of a preceding Host block, so it must come first."""
    path = _user_config_path()
    # Quoted: an unquoted path with spaces parses as two include patterns.
    include_line = f'Include "{_cluster_dir()}/*.conf"'
    content = ''
    if os.path.exists(path):
        with open(path) as f:
            content = f.read()
    if include_line in content:
        return
    os.makedirs(os.path.dirname(path) or '.', exist_ok=True)
    block = f'{_MARKER}\n{include_line}\n\n'
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        f.write(block + content)
    os.replace(tmp, path)
    os.chmod(path, 0o600)


def add_cluster(cluster_name: str, ips: List[str], user: str,
                key_path: str, ssh_port: int = 22) -> str:
    """Write Host blocks for a provisioned cluster; returns the file."""
    os.makedirs(_cluster_dir(), exist_ok=True)
    lines = [f'{_MARKER}: {cluster_name}']
    for rank, ip in enumerate(ips):
        aliases = f'{cluster_name}-{rank}'
        if rank == 0:
            aliases = f'{cluster_name} {aliases}'
        lines += [
            f'Host {aliases}',
            f'  HostName {ip}',
            f'  User {user}',
            f'  IdentityFile "{key_path}"',
            f'  Port {ssh_port}',
            '  IdentitiesOnly yes',
            '  StrictHostKeyChecking no',
            '  UserKnownHostsFile /dev/null',
            '  LogLevel ERROR',
            '',
        ]
    path = cluster_config_path(cluster_name)
    tmp = path + '.tmp'
    with open(tmp, 'w') as f:
        f.write('\n'.join(lines))
    os.replace(tmp, path)
    os.chmod(path, 0o600)
    _ensure_include()
    return path


def remove_cluster(cluster_name: str) -> None:
    try:
        os.remove(cluster_config_path(cluster_name))
    except FileNotFoundError:
        pass


def head_ssh_args(cluster_name: str) -> Optional[List[str]]:
    """argv for ``ssh`` to the cluster head using the written config
    (None if no config exists — cluster not up or a local cluster)."""
    path = cluster_config_path(cluster_name)
    if not os.path.exists(path):
        return None
    return ['ssh', '-F', path, cluster_name]
