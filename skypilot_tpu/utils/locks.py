"""Cross-process cluster locks.

The API server executes operations in forked worker processes and multiple
CLIs can run concurrently, so thread locks cannot serialize cluster
lifecycle ops — two simultaneous ``launch -c same-name`` must not both pass
the existence check and double-provision. Per-cluster ``filelock`` files
under the state dir give process-level mutual exclusion, mirroring the
reference's per-cluster locking (reference sky/execution.py:510-523,
sky/backends/backend_utils.py cluster_status_lock).

Locks are cached per (state_dir, name) so every caller in a process shares
one ``FileLock`` instance: acquisition is reentrant within a thread and
mutually exclusive across threads and processes.
"""
from __future__ import annotations

import os
import threading
from typing import Dict

import filelock

# Reference uses 20s for cluster-status locks; lifecycle ops here can
# legitimately hold the lock for a whole provision, so wait generously.
CLUSTER_LOCK_TIMEOUT_S = float(
    os.environ.get('SKYTPU_CLUSTER_LOCK_TIMEOUT', 600))

_locks: Dict[str, filelock.FileLock] = {}
_guard = threading.Lock()


class ClusterLockTimeout(Exception):
    """Another process held the cluster lock past the timeout."""


def _lock_path(name: str) -> str:
    from skypilot_tpu import global_user_state
    lock_dir = os.path.join(global_user_state.get_state_dir(), 'locks')
    os.makedirs(lock_dir, exist_ok=True)
    return os.path.join(lock_dir, f'cluster.{name}.lock')


def cluster_lock(cluster_name: str,
                 timeout: float = None) -> filelock.FileLock:
    """Process-wide shared FileLock for a cluster (use as context manager)."""
    path = _lock_path(cluster_name)
    with _guard:
        lock = _locks.get(path)
        if lock is None:
            lock = filelock.FileLock(
                path, timeout=CLUSTER_LOCK_TIMEOUT_S
                if timeout is None else timeout)
            _locks[path] = lock
    return lock
