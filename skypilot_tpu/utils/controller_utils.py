"""Controller-cluster specs + bring-up.

Counterpart of reference ``sky/utils/controller_utils.py`` (Controllers enum
with per-controller spec :62-171). Managed-jobs (and, later, serve)
controllers run on a dedicated *controller cluster* — not on the client
machine — so they survive the client's laptop closing (VERDICT r1 §missing
5). The default controller cloud is ``local`` (works out of the box,
hermetic in tests); deployments point it at a GCE CPU VM via:

    # ~/.skytpu/config.yaml
    jobs:
      controller:
        resources: {cloud: gcp, region: us-central1}
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional

from skypilot_tpu import config as config_lib


@dataclasses.dataclass(frozen=True)
class ControllerSpec:
    name: str                  # human name for messages
    cluster_name: str          # fixed controller cluster name
    config_key: str            # config section ('jobs' / 'serve')
    idle_minutes_to_autostop: Optional[int]  # non-local clouds only


JOBS_CONTROLLER = ControllerSpec(
    name='managed-jobs controller',
    cluster_name='skytpu-jobs-controller',
    config_key='jobs',
    idle_minutes_to_autostop=10,
)

SERVE_CONTROLLER = ControllerSpec(
    name='serve controller',
    cluster_name='skytpu-serve-controller',
    config_key='serve',
    idle_minutes_to_autostop=None,  # serves stay up with their services
)


def controller_resources(spec: ControllerSpec) -> 'Any':
    """The Resources for the controller cluster (config-overridable)."""
    from skypilot_tpu import resources as resources_lib
    overrides: Dict[str, Any] = config_lib.get_nested(
        (spec.config_key, 'controller', 'resources'), None) or {}
    overrides.setdefault('cloud', 'local')
    return resources_lib.Resources.from_yaml_config(overrides)


def ensure_controller_cluster(spec: ControllerSpec) -> 'Any':
    """Get-or-launch the controller cluster; returns its ResourceHandle.

    Idempotent: ``execution.launch`` reuses an UP cluster under the
    per-cluster file lock, so concurrent submissions race safely.
    """
    from skypilot_tpu import execution
    from skypilot_tpu import task as task_lib
    resources = controller_resources(spec)
    task = task_lib.Task(name=spec.name.replace(' ', '-'), run=None)
    task.set_resources([resources])
    autostop = (spec.idle_minutes_to_autostop
                if resources.cloud != 'local' else None)
    _, handle = execution.launch(
        task, cluster_name=spec.cluster_name, detach_run=True,
        idle_minutes_to_autostop=autostop, stream_logs=False,
        policy_operation='controller_launch')
    assert handle is not None, f'{spec.name} cluster failed to come up'
    return handle


def get_controller_handle(spec: ControllerSpec) -> Optional['Any']:
    """The controller cluster's handle if it exists and is UP, else None."""
    from skypilot_tpu import global_user_state
    record = global_user_state.get_cluster_from_name(spec.cluster_name)
    if record is None or record['handle'] is None:
        return None
    if record['status'] != global_user_state.ClusterStatus.UP:
        return None
    return record['handle']


def controller_rpc(spec: ControllerSpec, module: str, args_str: str,
                   stream_to: Any = None,
                   timeout: Optional[float] = 120,
                   launch_if_missing: bool = True
                   ) -> tuple:
    """Run a protocol module (jobs.jobcli / serve.servecli) on the
    controller cluster's head. Returns (result, handle); both None when
    the controller cluster does not exist and launch_if_missing is False.

    The single client implementation of the controller protocol — jobs
    and serve both speak through here so transport behavior can't
    diverge.
    """
    from skypilot_tpu import backends
    handle = get_controller_handle(spec)
    if handle is None:
        if not launch_if_missing:
            return None, None
        handle = ensure_controller_cluster(spec)
    backend = backends.SliceBackend()
    res = backend.run_module(handle, module, args_str,
                             stream_to=stream_to, timeout=timeout)
    return res, handle


def parse_rpc_json(res: Any, op: str) -> Dict[str, Any]:
    """Last-stdout-line JSON payload of a controller RPC; raises the
    typed error carried in an ``error`` payload or a CommandError on a
    nonzero exit."""
    import json

    from skypilot_tpu import exceptions
    if res is None or res.returncode != 0:
        raise exceptions.CommandError(
            getattr(res, 'returncode', 1), f'controller rpc {op}',
            getattr(res, 'stderr', '') or getattr(res, 'stdout', ''))
    payload = json.loads(res.stdout.strip().splitlines()[-1])
    if 'error' in payload:
        raise exceptions.deserialize_exception(payload['error'])
    return payload
