"""Chrome-trace-event recorder for control-plane latency analysis.

Counterpart of reference ``sky/utils/timeline.py`` (:22-60 — Event context
manager + @event decorator, atexit JSON dump viewable in
chrome://tracing / Perfetto). Recording is off unless ``SKYTPU_TIMELINE``
is set (to a path, or ``1`` for the default under the state dir) — tracing
must cost nothing on the hot path when disabled.
"""
from __future__ import annotations

import atexit
import functools
import json
import os
import threading
import time
from typing import Any, Callable, List, Optional


_events: List[dict] = []
_lock = threading.Lock()
_registered = False


def enabled() -> bool:
    return bool(os.environ.get('SKYTPU_TIMELINE'))


def _dump_path() -> str:
    raw = os.environ.get('SKYTPU_TIMELINE', '')
    if raw and raw != '1':
        return os.path.expanduser(raw)
    from skypilot_tpu import global_user_state
    d = os.path.join(global_user_state.get_state_dir(), 'timeline')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f'trace-{os.getpid()}.json')


def save(path: Optional[str] = None) -> Optional[str]:
    """Write accumulated events in Chrome trace-event format."""
    with _lock:
        if not _events:
            return None
        events = list(_events)
    path = path or _dump_path()
    with open(path, 'w') as f:
        json.dump({'traceEvents': events, 'displayTimeUnit': 'ms'}, f)
    return path


def _record(name: str, ph: str, ts_us: float, **extra: Any) -> None:
    global _registered
    evt = {'name': name, 'ph': ph, 'ts': ts_us, 'pid': os.getpid(),
           'tid': threading.get_ident() % 2**31, **extra}
    with _lock:
        _events.append(evt)
        if not _registered:
            _registered = True
            atexit.register(save)


class Event:
    """Context manager emitting a begin/end pair."""

    def __init__(self, name: str):
        self._name = name

    def __enter__(self):
        if enabled():
            _record(self._name, 'B', time.time() * 1e6)
        return self

    def __exit__(self, *exc):
        if enabled():
            _record(self._name, 'E', time.time() * 1e6)
        return False


def event(name_or_fn: Any = None) -> Callable:
    """Decorator: wrap a function in an Event named after it."""
    def wrap(fn: Callable, name: str) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not enabled():
                return fn(*args, **kwargs)
            with Event(name):
                return fn(*args, **kwargs)
        return wrapper

    if callable(name_or_fn):
        return wrap(name_or_fn,
                    f'{name_or_fn.__module__}.{name_or_fn.__qualname__}')
    return lambda fn: wrap(fn, name_or_fn
                           or f'{fn.__module__}.{fn.__qualname__}')
