"""Chrome-trace-event recorder for control-plane latency analysis.

Counterpart of reference ``sky/utils/timeline.py`` (:22-60 — Event context
manager + @event decorator, atexit JSON dump viewable in
chrome://tracing / Perfetto). Recording is off unless ``SKYTPU_TIMELINE``
is set (to a path, or ``1`` for the default under the state dir) — tracing
must cost nothing on the hot path when disabled.

Beyond the reference's begin/end pairs this recorder supports the serve
request-tracing plane:

- **flow events** (``ph`` s/t/f) bound by a request id, so one request's
  spans connect across the load balancer and replica processes in the
  Perfetto view;
- **instant** (``ph`` i) and **complete** (``ph`` X, explicit duration)
  events, for cross-thread spans whose begin and end are observed by
  different threads (queue wait, prefill chunks);
- a **bounded ring buffer**: ``_events`` is a deque capped at
  ``$SKYTPU_TIMELINE_EVENTS`` (default 100k) events, so a long-running
  replica with tracing on keeps the most recent window instead of
  growing without bound. ``save()`` semantics are unchanged — it dumps
  whatever the buffer currently holds.
"""
from __future__ import annotations

import atexit
import collections
import functools
import json
import os
import threading
import time
from typing import Any, Callable, Deque, Optional

DEFAULT_CAPACITY = 100_000

# Cross-process trace-correlation header: assigned by the serve load
# balancer, adopted by the generation replica, echoed to the client.
# ONE definition — the name is a wire contract between processes.
REQUEST_ID_HEADER = 'X-Skytpu-Request-Id'


def _capacity_from_env() -> int:
    raw = os.environ.get('SKYTPU_TIMELINE_EVENTS', '')
    try:
        cap = int(raw) if raw else DEFAULT_CAPACITY
    except ValueError:
        cap = DEFAULT_CAPACITY
    return max(1, cap)


_events: Deque[dict] = collections.deque(maxlen=_capacity_from_env())
_lock = threading.Lock()
_registered = False


def configure(capacity: Optional[int] = None) -> None:
    """Re-create the ring buffer (drops recorded events). Tests and
    long-lived processes that change $SKYTPU_TIMELINE_EVENTS at runtime
    call this; normal startup reads the env var at import."""
    global _events
    with _lock:
        _events = collections.deque(
            maxlen=max(1, capacity) if capacity is not None
            else _capacity_from_env())


def enabled() -> bool:
    return bool(os.environ.get('SKYTPU_TIMELINE'))


def _dump_path() -> str:
    raw = os.environ.get('SKYTPU_TIMELINE', '')
    if raw and raw != '1':
        return os.path.expanduser(raw)
    from skypilot_tpu import global_user_state
    d = os.path.join(global_user_state.get_state_dir(), 'timeline')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f'trace-{os.getpid()}.json')


def save(path: Optional[str] = None) -> Optional[str]:
    """Write accumulated events in Chrome trace-event format."""
    with _lock:
        if not _events:
            return None
        events = list(_events)
    path = path or _dump_path()
    with open(path, 'w') as f:
        json.dump({'traceEvents': events, 'displayTimeUnit': 'ms'}, f)
    return path


def _record(name: str, ph: str, ts_us: float, **extra: Any) -> None:
    global _registered
    evt = {'name': name, 'ph': ph, 'ts': ts_us, 'pid': os.getpid(),
           'tid': threading.get_ident() % 2**31, **extra}
    with _lock:
        _events.append(evt)
        if not _registered:
            _registered = True
            atexit.register(save)


class Event:
    """Context manager emitting a begin/end pair."""

    def __init__(self, name: str):
        self._name = name

    def __enter__(self):
        if enabled():
            _record(self._name, 'B', time.time() * 1e6)
        return self

    def __exit__(self, *exc):
        if enabled():
            _record(self._name, 'E', time.time() * 1e6)
        return False


def event(name_or_fn: Any = None) -> Callable:
    """Decorator: wrap a function in an Event named after it."""
    def wrap(fn: Callable, name: str) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not enabled():
                return fn(*args, **kwargs)
            with Event(name):
                return fn(*args, **kwargs)
        return wrapper

    if callable(name_or_fn):
        return wrap(name_or_fn,
                    f'{name_or_fn.__module__}.{name_or_fn.__qualname__}')
    return lambda fn: wrap(fn, name_or_fn
                           or f'{fn.__module__}.{fn.__qualname__}')


# ---- serve request-tracing events ------------------------------------------
# All emitters check enabled() internally, but hot callers should still
# guard with ``if timeline.enabled():`` so argument construction is also
# skipped — the disabled path must stay one branch.

def instant(name: str, **args: Any) -> None:
    """Thread-scoped instant event (ph 'i') with optional args."""
    if not enabled():
        return
    extra = {'s': 't'}
    if args:
        extra['args'] = args
    _record(name, 'i', time.time() * 1e6, **extra)


def complete(name: str, duration_s: float, end_wall_s: Optional[float]
             = None, **args: Any) -> None:
    """Complete event (ph 'X'): a span whose begin/end were observed by
    different threads (or measured with perf_counter). ``duration_s`` is
    the span length; the start timestamp is reconstructed from the end
    wall clock (``end_wall_s`` or now) minus the duration."""
    if not enabled():
        return
    end = end_wall_s if end_wall_s is not None else time.time()
    extra: dict = {'dur': max(0.0, duration_s) * 1e6}
    if args:
        extra['args'] = args
    _record(name, 'X', (end - max(0.0, duration_s)) * 1e6, **extra)


def _flow(ph: str, name: str, flow_id: str,
          ts_s: Optional[float] = None, **args: Any) -> None:
    if not enabled():
        return
    extra: dict = {'cat': 'request', 'id': str(flow_id)}
    if ph == 'f':
        extra['bp'] = 'e'  # bind to the enclosing slice's end
    if args:
        extra['args'] = args
    _record(name, ph, (time.time() if ts_s is None else ts_s) * 1e6,
            **extra)


def flow_start(name: str, flow_id: str, ts_s: Optional[float] = None,
               **args: Any) -> None:
    """Begin a flow (ph 's'): the LB emits this when it assigns a
    request id; matching flow_step/flow_end events in other processes
    draw connecting arrows in Perfetto. Flow events only render when
    they fall INSIDE a duration slice on their thread — emitters pass
    ``ts_s`` to pin the event within a ``complete`` span."""
    _flow('s', name, flow_id, ts_s, **args)


def flow_step(name: str, flow_id: str, ts_s: Optional[float] = None,
              **args: Any) -> None:
    """Intermediate flow point (ph 't') — e.g. replica-side TTFT."""
    _flow('t', name, flow_id, ts_s, **args)


def flow_end(name: str, flow_id: str, ts_s: Optional[float] = None,
             **args: Any) -> None:
    """Terminate a flow (ph 'f') — e.g. LB finished streaming."""
    _flow('f', name, flow_id, ts_s, **args)
