"""Chrome-trace-event recorder for control-plane latency analysis.

Counterpart of reference ``sky/utils/timeline.py`` (:22-60 — Event context
manager + @event decorator, atexit JSON dump viewable in
chrome://tracing / Perfetto). Recording is off unless ``SKYTPU_TIMELINE``
is set (to a path, or ``1`` for the default under the state dir) — tracing
must cost nothing on the hot path when disabled.

Beyond the reference's begin/end pairs this recorder supports the serve
request-tracing plane:

- **flow events** (``ph`` s/t/f) bound by a request id, so one request's
  spans connect across the load balancer and replica processes in the
  Perfetto view;
- **instant** (``ph`` i) and **complete** (``ph`` X, explicit duration)
  events, for cross-thread spans whose begin and end are observed by
  different threads (queue wait, prefill chunks);
- a **bounded ring buffer**: ``_events`` is a deque capped at
  ``$SKYTPU_TIMELINE_EVENTS`` (default 100k) events, so a long-running
  replica with tracing on keeps the most recent window instead of
  growing without bound. ``save()`` semantics are unchanged — it dumps
  whatever the buffer currently holds.
"""
from __future__ import annotations

import atexit
import collections
import functools
import json
import os
import threading
import time
from typing import Any, Callable, Deque, List, Optional

DEFAULT_CAPACITY = 100_000

# Cross-process trace-correlation header: assigned by the serve load
# balancer, adopted by the generation replica, echoed to the client.
# ONE definition — the name is a wire contract between processes.
REQUEST_ID_HEADER = 'X-Skytpu-Request-Id'


def _capacity_from_env() -> int:
    raw = os.environ.get('SKYTPU_TIMELINE_EVENTS', '')
    try:
        cap = int(raw) if raw else DEFAULT_CAPACITY
    except ValueError:
        cap = DEFAULT_CAPACITY
    return max(1, cap)


_events: Deque[dict] = collections.deque(maxlen=_capacity_from_env())
_lock = threading.Lock()
_registered = False


def configure(capacity: Optional[int] = None) -> None:
    """Re-create the ring buffer (drops recorded events). Tests and
    long-lived processes that change $SKYTPU_TIMELINE_EVENTS at runtime
    call this; normal startup reads the env var at import."""
    global _events
    with _lock:
        _events = collections.deque(
            maxlen=max(1, capacity) if capacity is not None
            else _capacity_from_env())


def enabled() -> bool:
    return bool(os.environ.get('SKYTPU_TIMELINE'))


def _dump_path() -> str:
    raw = os.environ.get('SKYTPU_TIMELINE', '')
    if raw and raw != '1':
        return os.path.expanduser(raw)
    from skypilot_tpu import global_user_state
    d = os.path.join(global_user_state.get_state_dir(), 'timeline')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, f'trace-{os.getpid()}.json')


def save(path: Optional[str] = None) -> Optional[str]:
    """Write accumulated events in Chrome trace-event format."""
    with _lock:
        if not _events:
            return None
        events = list(_events)
    path = path or _dump_path()
    with open(path, 'w') as f:
        json.dump({'traceEvents': events, 'displayTimeUnit': 'ms'}, f)
    return path


def _record(name: str, ph: str, ts_us: float, **extra: Any) -> None:
    global _registered
    evt = {'name': name, 'ph': ph, 'ts': ts_us, 'pid': os.getpid(),
           'tid': threading.get_ident() % 2**31, **extra}
    with _lock:
        _events.append(evt)
        if not _registered:
            _registered = True
            atexit.register(save)


class Event:
    """Context manager emitting a begin/end pair."""

    def __init__(self, name: str):
        self._name = name

    def __enter__(self):
        if enabled():
            _record(self._name, 'B', time.time() * 1e6)
        return self

    def __exit__(self, *exc):
        if enabled():
            _record(self._name, 'E', time.time() * 1e6)
        return False


def event(name_or_fn: Any = None) -> Callable:
    """Decorator: wrap a function in an Event named after it."""
    def wrap(fn: Callable, name: str) -> Callable:
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            if not enabled():
                return fn(*args, **kwargs)
            with Event(name):
                return fn(*args, **kwargs)
        return wrapper

    if callable(name_or_fn):
        return wrap(name_or_fn,
                    f'{name_or_fn.__module__}.{name_or_fn.__qualname__}')
    return lambda fn: wrap(fn, name_or_fn
                           or f'{fn.__module__}.{fn.__qualname__}')


# ---- serve request-tracing events ------------------------------------------
# All emitters check enabled() internally, but hot callers should still
# guard with ``if timeline.enabled():`` so argument construction is also
# skipped — the disabled path must stay one branch.

def instant(name: str, **args: Any) -> None:
    """Thread-scoped instant event (ph 'i') with optional args."""
    if not enabled():
        return
    extra = {'s': 't'}
    if args:
        extra['args'] = args
    _record(name, 'i', time.time() * 1e6, **extra)


def complete(name: str, duration_s: float, end_wall_s: Optional[float]
             = None, **args: Any) -> None:
    """Complete event (ph 'X'): a span whose begin/end were observed by
    different threads (or measured with perf_counter). ``duration_s`` is
    the span length; the start timestamp is reconstructed from the end
    wall clock (``end_wall_s`` or now) minus the duration."""
    if not enabled():
        return
    end = end_wall_s if end_wall_s is not None else time.time()
    extra: dict = {'dur': max(0.0, duration_s) * 1e6}
    if args:
        extra['args'] = args
    _record(name, 'X', (end - max(0.0, duration_s)) * 1e6, **extra)


def _flow(ph: str, name: str, flow_id: str,
          ts_s: Optional[float] = None, **args: Any) -> None:
    if not enabled():
        return
    extra: dict = {'cat': 'request', 'id': str(flow_id)}
    if ph == 'f':
        extra['bp'] = 'e'  # bind to the enclosing slice's end
    if args:
        extra['args'] = args
    _record(name, ph, (time.time() if ts_s is None else ts_s) * 1e6,
            **extra)


def flow_start(name: str, flow_id: str, ts_s: Optional[float] = None,
               **args: Any) -> None:
    """Begin a flow (ph 's'): the LB emits this when it assigns a
    request id; matching flow_step/flow_end events in other processes
    draw connecting arrows in Perfetto. Flow events only render when
    they fall INSIDE a duration slice on their thread — emitters pass
    ``ts_s`` to pin the event within a ``complete`` span."""
    _flow('s', name, flow_id, ts_s, **args)


def flow_step(name: str, flow_id: str, ts_s: Optional[float] = None,
              **args: Any) -> None:
    """Intermediate flow point (ph 't') — e.g. replica-side TTFT."""
    _flow('t', name, flow_id, ts_s, **args)


def flow_end(name: str, flow_id: str, ts_s: Optional[float] = None,
             **args: Any) -> None:
    """Terminate a flow (ph 'f') — e.g. LB finished streaming."""
    _flow('f', name, flow_id, ts_s, **args)


# ---- structured request-trace ring ------------------------------------------
# A second, independent store: where the Chrome-event ring above is a
# flat window of EVERY event (for Perfetto), this one keys COMPLETED
# span trees by request id so `/trace/<request-id>` can answer "where
# did this request's latency go" long after the flat ring wrapped.
# It is part of the metrics plane, not the timeline plane: callers gate
# recording on their metrics containers (None under SKYTPU_METRICS=0),
# so the disabled path stays one branch and the ring works without
# SKYTPU_TIMELINE set. Appends take one short lock — nothing here
# blocks, so hot-path (`# skylint: hot-path`) callers may record.

TRACE_RING_DEFAULT = 256
# Spans kept per trace; a pathological 100k-token generation must not
# grow one trace without bound. Further spans count as dropped.
TRACE_SPANS_MAX = 512


def _trace_capacity_from_env() -> int:
    raw = os.environ.get('SKYTPU_TRACE_RING', '')
    try:
        cap = int(raw) if raw else TRACE_RING_DEFAULT
    except ValueError:
        cap = TRACE_RING_DEFAULT
    return max(1, cap)


_trace_lock = threading.Lock()
_trace_capacity = _trace_capacity_from_env()
# Completed traces, oldest-first (plain dict = insertion-ordered ring).
_traces: dict = {}
# In-flight traces: spans accumulate here until trace_finish moves the
# tree into the completed ring.
_open_traces: dict = {}


def configure_traces(capacity: Optional[int] = None) -> None:
    """Re-create the completed-trace ring (drops recorded traces)."""
    global _trace_capacity, _traces, _open_traces
    with _trace_lock:
        _trace_capacity = (max(1, capacity) if capacity is not None
                           else _trace_capacity_from_env())
        _traces = {}
        _open_traces = {}


def trace_span(request_id: str, name: str, start_s: float,
               end_s: float, **attrs: Any) -> None:
    """Append one completed span to ``request_id``'s (open) trace."""
    span: dict = {'name': name,
                  'start_us': int(start_s * 1e6),
                  'end_us': int(end_s * 1e6)}
    if attrs:
        span['attrs'] = attrs
    with _trace_lock:
        tr = _open_traces.get(request_id)
        if tr is None:
            # Bound the open table too: a request that never finishes
            # (client gone, crash path missed) must not leak forever.
            while len(_open_traces) >= 2 * _trace_capacity:
                _open_traces.pop(next(iter(_open_traces)))
            tr = {'request_id': request_id, 'pid': os.getpid(),
                  'spans': [], 'dropped_spans': 0}
            _open_traces[request_id] = tr
        if len(tr['spans']) >= TRACE_SPANS_MAX:
            tr['dropped_spans'] += 1
        else:
            tr['spans'].append(span)


def trace_point(request_id: str, name: str,
                ts_s: Optional[float] = None, **attrs: Any) -> None:
    """Zero-duration span (a point event in the tree)."""
    ts = time.time() if ts_s is None else ts_s
    trace_span(request_id, name, ts, ts, **attrs)


def trace_finish(request_id: str, **attrs: Any) -> None:
    """Seal ``request_id``'s trace into the completed ring (evicting
    the oldest completed trace past capacity). No-op for ids that never
    recorded a span."""
    with _trace_lock:
        tr = _open_traces.pop(request_id, None)
        if tr is None:
            return
        if attrs:
            tr.setdefault('attrs', {}).update(attrs)
        tr['finished_at_us'] = int(time.time() * 1e6)
        # Re-finish merges into the already-sealed tree (and moves it to
        # the ring's newest end): an LB and a replica sharing one
        # process (tests, single-process local serving) each seal their
        # own spans for the same request id, and neither may clobber
        # the other's half of the tree.
        prev = _traces.pop(request_id, None)
        if prev is not None:
            tr['spans'] = list(prev['spans']) + tr['spans']
            tr['dropped_spans'] += prev.get('dropped_spans', 0)
            if 'attrs' in prev:
                merged = dict(prev['attrs'])
                merged.update(tr.get('attrs', {}))
                tr['attrs'] = merged
        tr['spans'].sort(key=lambda s: (s['start_us'], s['end_us']))
        _traces[request_id] = tr
        while len(_traces) > _trace_capacity:
            _traces.pop(next(iter(_traces)))


def get_trace(request_id: str) -> Optional[dict]:
    """Completed trace for ``request_id`` (or the in-flight tree, with
    ``complete: false``, for a request still streaming). None if the
    id never traced or its trace aged out of the ring."""
    with _trace_lock:
        tr = _traces.get(request_id)
        if tr is not None:
            return {**tr, 'complete': True}
        tr = _open_traces.get(request_id)
        if tr is not None:
            snap = {**tr, 'spans': sorted(
                tr['spans'], key=lambda s: (s['start_us'], s['end_us']))}
            snap['complete'] = False
            return snap
    return None


def trace_stats() -> dict:
    """Ring occupancy for the trace-ring gauges."""
    with _trace_lock:
        return {'completed': len(_traces), 'open': len(_open_traces),
                'capacity': _trace_capacity}


def recent_traces(limit: int = 16) -> List[dict]:
    """The ``limit`` most recently completed traces, newest last —
    what the flight recorder folds into a postmortem artifact so the
    sealed window carries the actual request trees, not just rates.
    The ring dict is insertion-ordered (completion order), so the tail
    IS recency."""
    with _trace_lock:
        tail = list(_traces.values())[-max(0, int(limit)):]
        return [{**tr, 'complete': True} for tr in tail]
