"""Dependency-free metrics plane: counters, gauges, fixed-bucket
histograms with Prometheus text exposition.

The serve path computes TTFT estimates, queue depths, and per-step
timings and (before this module) threw them away as ad-hoc dict
counters; here they become scrapeable series so batching/autoscaling
tuning happens against production signals, not only bench runs. No
prometheus_client dependency: the container bakes a fixed toolchain, so
the registry + text format live in-tree (~the same architecture as
vLLM's metrics.py, minus the client library).

Cost model: instruments are plain attribute updates under a per-metric
lock (uncontended ~100ns); nothing is allocated per observation and
nothing happens at all unless a scraper hits ``render()``. Call sites
that want a strictly-zero disabled path hold ``None`` instead of a
metric container when ``enabled()`` is false, so disabled
instrumentation is one branch.

Naming convention (enforced at registration AND by
``scripts/check_metric_names.py``): ``skytpu_<subsystem>_<name>_<unit>``
with the unit drawn from :data:`UNITS` (counters end ``_total`` per
Prometheus convention).
"""
from __future__ import annotations

import bisect
import os
import re
import threading
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

# Latency buckets in MILLISECONDS: spans sub-ms decode steps through
# multi-second queue waits (TTFT p99 ~10s at the r05 knee).
DEFAULT_BUCKETS_MS: Tuple[float, ...] = (
    0.5, 1, 2.5, 5, 10, 25, 50, 100, 250, 500,
    1000, 2500, 5000, 10000, 30000)

# Allowed trailing unit tokens for skytpu_* metric names. 'total' is the
# Prometheus counter suffix; 'info' is the Prometheus info-metric idiom
# (constant 1 with identifying labels); 'token' denotes a per-token
# denominator (e.g. bytes_per_token); the rest are the units this
# codebase actually measures in.
UNITS = ('total', 'ms', 'seconds', 'tokens', 'requests', 'slots',
         'bytes', 'ratio', 'count', 'rps', 'info', 'token', 'flops')

_NAME_RE = re.compile(r'^skytpu_[a-z0-9]+(_[a-z0-9]+)+$')


def enabled() -> bool:
    """Metrics default ON (the plane exists to be on in production);
    $SKYTPU_METRICS=0 disables collection for overhead-sensitive runs."""
    return os.environ.get('SKYTPU_METRICS', '1').lower() not in (
        '0', 'false', 'off')


def validate_name(name: str) -> Optional[str]:
    """Return an error string when ``name`` violates the
    ``skytpu_<subsystem>_<name>_<unit>`` convention, else None. Shared
    with scripts/check_metric_names.py so the lint and the registry
    enforce one rule."""
    if not _NAME_RE.match(name):
        return (f'{name!r}: must match skytpu_<subsystem>_<name>_<unit> '
                '(lowercase, underscores)')
    parts = name.split('_')
    if len(parts) < 4:
        return (f'{name!r}: needs at least skytpu_<subsystem>_<name>_'
                f'<unit> (4 segments, got {len(parts)})')
    if parts[-1] not in UNITS:
        return (f'{name!r}: unit suffix {parts[-1]!r} not in '
                f'{sorted(UNITS)}')
    return None


def _fmt(v: float) -> str:
    """Prometheus float formatting: integers render without the '.0'."""
    return str(int(v)) if float(v).is_integer() else repr(float(v))


def _fmt_labels(labels: Tuple[Tuple[str, str], ...]) -> str:
    if not labels:
        return ''
    inner = ','.join(f'{k}="{v}"' for k, v in labels)
    return '{' + inner + '}'


def _fmt_exemplar(exemplar_id: str, value: float) -> str:
    """OpenMetrics exemplar suffix for a bucket sample line:
    ``... # {request_id="<id>"} <observed value>``. Our parser strips
    it; Prometheus pre-OpenMetrics scrapers skip unknown suffixes."""
    return f' # {{request_id="{exemplar_id}"}} {_fmt(value)}'


class Counter:
    """Monotonic counter."""

    kind = 'counter'

    def __init__(self, name: str, help_text: str,
                 labels: Tuple[Tuple[str, str], ...] = ()):
        self.name = name
        self.help = help_text
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def samples(self) -> List[Tuple[str, Tuple[Tuple[str, str], ...],
                                    float]]:
        with self._lock:
            return [(self.name, self.labels, self._value)]


class Gauge:
    """Instantaneous value."""

    kind = 'gauge'

    def __init__(self, name: str, help_text: str,
                 labels: Tuple[Tuple[str, str], ...] = ()):
        self.name = name
        self.help = help_text
        self.labels = labels
        self._lock = threading.Lock()
        self._value = 0.0

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        with self._lock:
            return self._value

    def samples(self) -> List[Tuple[str, Tuple[Tuple[str, str], ...],
                                    float]]:
        with self._lock:
            return [(self.name, self.labels, self._value)]


class Histogram:
    """Fixed-bucket histogram (cumulative buckets + sum + count).

    Buckets are chosen at registration; ``observe`` is a bisect + two
    adds under the lock — no per-observation allocation.
    """

    kind = 'histogram'

    def __init__(self, name: str, help_text: str,
                 buckets: Sequence[float] = DEFAULT_BUCKETS_MS,
                 labels: Tuple[Tuple[str, str], ...] = ()):
        self.name = name
        self.help = help_text
        self.labels = labels
        self.buckets = tuple(sorted(float(b) for b in buckets))
        if not self.buckets:
            raise ValueError(f'{name}: histogram needs >= 1 bucket')
        self._lock = threading.Lock()
        # Non-cumulative per-bucket counts; +Inf is the final slot.
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._count = 0
        # Last exemplar per bucket: (id, observed value) or None. An
        # exemplar names the request that landed in the bucket, so a
        # tail-quantile cell can link straight to that request's trace.
        self._exemplars: List[Optional[Tuple[str, float]]] = (
            [None] * (len(self.buckets) + 1))

    def observe(self, value: float,
                exemplar: Optional[str] = None) -> None:
        i = bisect.bisect_left(self.buckets, value)
        with self._lock:
            self._counts[i] += 1
            self._sum += value
            self._count += 1
            if exemplar is not None:
                self._exemplars[i] = (str(exemplar), float(value))

    def exemplars(self) -> Dict[str, Tuple[str, float]]:
        """{le-label: (exemplar_id, observed value)} for buckets that
        hold one. Keys use the same le formatting as samples() ('+Inf'
        for the final bucket), so exposition and lookup agree."""
        with self._lock:
            snap = list(self._exemplars)
        out: Dict[str, Tuple[str, float]] = {}
        for le, ex in zip(self.buckets, snap):
            if ex is not None:
                out[_fmt(le)] = ex
        if snap[-1] is not None:
            out['+Inf'] = snap[-1]
        return out

    @property
    def count(self) -> int:
        with self._lock:
            return self._count

    @property
    def sum(self) -> float:
        with self._lock:
            return self._sum

    def _snapshot(self) -> Tuple[List[int], float, int]:
        """Consistent (counts, sum, count) under one lock hold, so a
        scrape mid-observe can never show count != the +Inf bucket."""
        with self._lock:
            return list(self._counts), self._sum, self._count

    def cumulative(self) -> List[Tuple[float, int]]:
        """[(le, cumulative_count)] including the +Inf bucket."""
        counts, _, _ = self._snapshot()
        out: List[Tuple[float, int]] = []
        running = 0
        for le, c in zip(self.buckets, counts):
            running += c
            out.append((le, running))
        out.append((float('inf'), running + counts[-1]))
        return out

    def quantile(self, q: float) -> Optional[float]:
        """Estimated q-quantile (0..1) by linear interpolation inside
        the containing bucket — the scraper-side promql
        histogram_quantile, usable locally (dashboard, bench summary).
        None when empty; the top bucket clamps to its lower edge."""
        return histogram_quantile(self.cumulative(), q)

    def samples(self) -> List[Tuple[str, Tuple[Tuple[str, str], ...],
                                    float]]:
        counts, total, n = self._snapshot()
        out = []
        running = 0
        for le, c in zip(self.buckets, counts):
            running += c
            out.append((f'{self.name}_bucket',
                        self.labels + (('le', _fmt(le)),),
                        float(running)))
        out.append((f'{self.name}_bucket',
                    self.labels + (('le', '+Inf'),), float(n)))
        out.append((f'{self.name}_sum', self.labels, total))
        out.append((f'{self.name}_count', self.labels, float(n)))
        return out


def histogram_quantile(cumulative: Sequence[Tuple[float, float]],
                       q: float) -> Optional[float]:
    """Quantile estimate from [(le, cumulative_count)] pairs (the last
    pair being +Inf). Mirrors PromQL histogram_quantile: linear
    interpolation within the bucket, top (+Inf) bucket clamped to the
    highest finite edge.

    Degenerate inputs are deterministic, never arithmetic errors: an
    empty list or zero total observations -> None; a single-bucket
    histogram (only +Inf, no finite edge to interpolate toward) ->
    0.0; q outside [0, 1] is clamped so a caller typo can never walk
    off the bucket list and return +Inf."""
    if not cumulative:
        return None
    total = cumulative[-1][1]
    if total <= 0:
        return None
    rank = min(1.0, max(0.0, q)) * total
    prev_le, prev_cum = 0.0, 0.0
    for le, cum in cumulative:
        if cum >= rank:
            if le == float('inf'):
                return prev_le  # clamp: no upper edge to interpolate to
            if cum == prev_cum:
                return le
            frac = (rank - prev_cum) / (cum - prev_cum)
            return prev_le + (le - prev_le) * frac
        prev_le, prev_cum = le, cum
    # Unreachable with monotone cumulative input (the +Inf pair holds
    # the total); a non-monotone scrape still gets a finite answer.
    return 0.0 if prev_le == float('inf') else prev_le


# Rendering an EMPTY registry must not allocate: the no-metrics case is
# every non-serving process that still mounts /metrics.
_EMPTY = ''


class Registry:
    """Ordered collection of metrics with idempotent registration.

    Re-registering a name returns the existing metric (multiple
    scheduler instances in one process — tests — share series); a kind
    mismatch is a programming error and raises.
    """

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                            Any] = {}

    def _register(self, cls, name: str, help_text: str,
                  labels: Optional[Dict[str, str]] = None,
                  **kwargs) -> Any:
        err = validate_name(name)
        if err:
            raise ValueError(f'bad metric name {err}')
        lbl = tuple(sorted((labels or {}).items()))
        key = (name, lbl)
        with self._lock:
            existing = self._metrics.get(key)
            if existing is not None:
                if not isinstance(existing, cls):
                    raise ValueError(
                        f'{name} already registered as '
                        f'{existing.kind}, not {cls.kind}')
                return existing
            metric = cls(name, help_text, labels=lbl, **kwargs)
            self._metrics[key] = metric
            return metric

    def counter(self, name: str, help_text: str = '',
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._register(Counter, name, help_text, labels)

    def gauge(self, name: str, help_text: str = '',
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._register(Gauge, name, help_text, labels)

    def histogram(self, name: str, help_text: str = '',
                  buckets: Sequence[float] = DEFAULT_BUCKETS_MS,
                  labels: Optional[Dict[str, str]] = None) -> Histogram:
        return self._register(Histogram, name, help_text, labels,
                              buckets=buckets)

    def get(self, name: str,
            labels: Optional[Dict[str, str]] = None) -> Optional[Any]:
        with self._lock:
            return self._metrics.get(
                (name, tuple(sorted((labels or {}).items()))))

    def __len__(self) -> int:
        with self._lock:
            return len(self._metrics)

    def render(self) -> str:
        """Prometheus text exposition (format version 0.0.4)."""
        with self._lock:
            metrics = list(self._metrics.values())
        if not metrics:
            return _EMPTY
        lines: List[str] = []
        seen_headers = set()
        for m in metrics:
            if m.name not in seen_headers:
                seen_headers.add(m.name)
                if m.help:
                    lines.append(f'# HELP {m.name} {m.help}')
                lines.append(f'# TYPE {m.name} {m.kind}')
            exemplars = (m.exemplars()
                         if isinstance(m, Histogram) else {})
            for sample_name, labels, value in m.samples():
                line = f'{sample_name}{_fmt_labels(labels)} {_fmt(value)}'
                if exemplars and sample_name.endswith('_bucket'):
                    ex = exemplars.get(dict(labels).get('le', ''))
                    if ex is not None:
                        line += _fmt_exemplar(*ex)
                lines.append(line)
        return '\n'.join(lines) + '\n'


# Default process-wide registry; serving subsystems register here so one
# /metrics endpoint exposes scheduler + engine series together.
REGISTRY = Registry()

CONTENT_TYPE = 'text/plain; version=0.0.4; charset=utf-8'


def counter(name: str, help_text: str = '',
            labels: Optional[Dict[str, str]] = None) -> Counter:
    return REGISTRY.counter(name, help_text, labels)


def gauge(name: str, help_text: str = '',
          labels: Optional[Dict[str, str]] = None) -> Gauge:
    return REGISTRY.gauge(name, help_text, labels)


def histogram(name: str, help_text: str = '',
              buckets: Sequence[float] = DEFAULT_BUCKETS_MS,
              labels: Optional[Dict[str, str]] = None) -> Histogram:
    return REGISTRY.histogram(name, help_text, buckets, labels)


# ---- scrape-side helpers ----------------------------------------------------
_SAMPLE_RE = re.compile(
    r'^([a-zA-Z_:][a-zA-Z0-9_:]*)(?:\{(.*)\})?\s+(\S+)\s*$')
_LABEL_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')
_EXEMPLAR_RE = re.compile(r'^\{request_id="([^"]*)"\}\s+(\S+)$')

Sample = Tuple[str, Tuple[Tuple[str, str], ...], float]


def parse_text(text: str) -> List[Sample]:
    """Parse Prometheus text exposition into (name, labels, value)
    samples. Tolerant: comment/blank/malformed lines are skipped — a
    scrape of an arbitrary replica must never crash the scraper."""
    out: List[Sample] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith('#'):
            continue
        if ' # ' in line:  # OpenMetrics exemplar suffix on a sample
            line = line.split(' # ', 1)[0].rstrip()
        m = _SAMPLE_RE.match(line)
        if not m:
            continue
        name, raw_labels, raw_value = m.groups()
        try:
            value = float(raw_value)
        except ValueError:
            continue
        labels = tuple((k, v) for k, v in
                       _LABEL_RE.findall(raw_labels or ''))
        out.append((name, labels, value))
    return out


def aggregate_samples(
        sample_lists: Iterable[Sequence[Sample]]) -> List[Sample]:
    """Sum samples with identical (name, labels) across already-parsed
    scrapes — the fleet-level rollup the controller exposes. Summing is
    correct for counters and histogram series by construction; for
    gauges it yields fleet totals (total queue depth, total pending
    prefill tokens), which is the signal autoscaling consumes."""
    acc: Dict[Tuple[str, Tuple[Tuple[str, str], ...]], float] = {}
    order: List[Tuple[str, Tuple[Tuple[str, str], ...]]] = []
    for samples in sample_lists:
        for name, labels, value in samples:
            key = (name, labels)
            if key not in acc:
                acc[key] = 0.0
                order.append(key)
            acc[key] += value
    return [(name, labels, acc[(name, labels)])
            for name, labels in order]


def aggregate(texts: Iterable[str]) -> List[Sample]:
    """aggregate_samples over raw exposition texts."""
    return aggregate_samples(parse_text(t) for t in texts)


Exemplar = Tuple[str, Tuple[Tuple[str, str], ...], str, float]


def parse_exemplars(text: str) -> List[Exemplar]:
    """Extract (sample_name, labels, exemplar_id, observed_value) from
    exemplar-suffixed bucket lines (the inverse of the render-side
    suffix). Tolerant like parse_text: malformed suffixes are skipped."""
    out: List[Exemplar] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith('#') or ' # ' not in line:
            continue
        sample_part, ex_part = line.split(' # ', 1)
        m = _SAMPLE_RE.match(sample_part.rstrip())
        em = _EXEMPLAR_RE.match(ex_part.strip())
        if not m or not em:
            continue
        name, raw_labels, _ = m.groups()
        labels = tuple((k, v) for k, v in
                       _LABEL_RE.findall(raw_labels or ''))
        try:
            value = float(em.group(2))
        except ValueError:
            continue
        out.append((name, labels, em.group(1), value))
    return out


def merge_exemplars(
        exemplar_lists: Iterable[Sequence[Exemplar]]
) -> List[Exemplar]:
    """Union exemplars across scrapes, last writer per (name, labels)
    wins — the fleet rollup keeps ONE representative request per
    bucket, which is all a trace link needs."""
    acc: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
              Tuple[str, float]] = {}
    for exemplars in exemplar_lists:
        for name, labels, ex_id, value in exemplars:
            acc[(name, labels)] = (ex_id, value)
    return [(name, labels, ex_id, value)
            for (name, labels), (ex_id, value) in acc.items()]


def render_samples(samples: Iterable[Sample],
                   exemplars: Optional[Sequence[Exemplar]] = None
                   ) -> str:
    """Render raw samples as (untyped) exposition lines — used for the
    controller's fleet aggregate, which re-exports scraped series
    without their original TYPE metadata. ``exemplars`` re-attaches
    scraped exemplar suffixes to their bucket lines so trace links
    survive the re-export."""
    by_key: Dict[Tuple[str, Tuple[Tuple[str, str], ...]],
                 Tuple[str, float]] = {}
    for name, labels, ex_id, value in (exemplars or ()):
        by_key[(name, labels)] = (ex_id, value)
    lines = []
    for name, labels, value in samples:
        line = f'{name}{_fmt_labels(labels)} {_fmt(value)}'
        ex = by_key.get((name, labels))
        if ex is not None:
            line += _fmt_exemplar(*ex)
        lines.append(line)
    return '\n'.join(lines) + ('\n' if lines else '')


def sample_value(samples: Sequence[Sample], name: str,
                 labels: Optional[Dict[str, str]] = None
                 ) -> Optional[float]:
    """First sample value for ``name`` (None if absent). Without
    ``labels`` the first sample of any labeling wins (the historical
    behavior); with ``labels`` the sample's labels must contain every
    given pair — how the dashboard picks one (slo, window) burn-rate
    series out of the labeled family."""
    want = tuple(sorted((labels or {}).items()))
    for n, lbl, v in samples:
        if n != name:
            continue
        if not want or set(want) <= set(lbl):
            return v
    return None


def histogram_cumulative(samples: Sequence[Sample],
                         name: str) -> List[Tuple[float, float]]:
    """Reconstruct [(le, cumulative)] for histogram ``name`` from parsed
    samples (scrape-side counterpart of Histogram.cumulative)."""
    out: List[Tuple[float, float]] = []
    for n, labels, v in samples:
        if n != f'{name}_bucket':
            continue
        le = dict(labels).get('le')
        if le is None:
            continue
        out.append((float('inf') if le == '+Inf' else float(le), v))
    out.sort(key=lambda p: p[0])
    return out
