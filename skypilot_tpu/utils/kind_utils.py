"""`skytpu local up/down`: a local Kubernetes cloud via kind.

Counterpart of reference ``sky/cli.py:5548-5644`` (`sky local up`
bootstraps a kind cluster so the Kubernetes code path runs on a laptop).
The created cluster's kubeconfig lands in the skytpu state dir and
becomes the default the k8s transport reads (merged into $KUBECONFIG for
the current invocation; the CLI prints the export line for shells).
"""
from __future__ import annotations

import os
import shutil
import subprocess
from typing import Optional, Tuple

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state

CLUSTER_NAME = 'skytpu-local'


def kubeconfig_path(name: str = CLUSTER_NAME) -> str:
    suffix = '' if name == CLUSTER_NAME else f'-{name}'
    return os.path.join(global_user_state.get_state_dir(),
                        f'kind-kubeconfig{suffix}')


def _check_tools() -> Optional[str]:
    missing = [t for t in ('kind', 'kubectl', 'docker')
               if shutil.which(t) is None]
    if missing:
        return ('local up needs ' + ', '.join(missing) + ' installed. '
                'Install kind: https://kind.sigs.k8s.io/docs/user/'
                'quick-start/#installation')
    return None


def local_up(name: str = CLUSTER_NAME,
             wait: str = '120s') -> Tuple[str, bool]:
    """Create (or reuse) the kind cluster; returns (kubeconfig_path,
    created). Raises CloudError with an actionable message on failure."""
    hint = _check_tools()
    if hint:
        raise exceptions.CloudError(hint)
    path = kubeconfig_path(name)
    existing = subprocess.run(['kind', 'get', 'clusters'],
                              capture_output=True, text=True, timeout=60)
    if name in (existing.stdout or '').split():
        # Reuse: refresh the kubeconfig (it may have rotated certs).
        export = subprocess.run(
            ['kind', 'export', 'kubeconfig', '--name', name,
             '--kubeconfig', path],
            capture_output=True, text=True, timeout=60)
        if export.returncode != 0:
            raise exceptions.CloudError(
                f'kind cluster {name!r} exists but exporting its '
                f'kubeconfig failed: {export.stderr[-300:]}')
        return path, False
    create = subprocess.run(
        ['kind', 'create', 'cluster', '--name', name,
         '--kubeconfig', path, '--wait', wait],
        capture_output=True, text=True, timeout=600)
    if create.returncode != 0:
        raise exceptions.CloudError(
            f'kind cluster creation failed: {create.stderr[-500:]}')
    nodes = subprocess.run(
        ['kubectl', '--kubeconfig', path, 'get', 'nodes', '-o', 'name'],
        capture_output=True, text=True, timeout=60)
    if nodes.returncode != 0 or not nodes.stdout.strip():
        raise exceptions.CloudError(
            f'kind cluster came up but kubectl cannot see nodes: '
            f'{nodes.stderr[-300:]}')
    return path, True


def local_down(name: str = CLUSTER_NAME) -> bool:
    """Delete the kind cluster; returns True if one was deleted."""
    hint = _check_tools()
    if hint:
        raise exceptions.CloudError(hint)
    existing = subprocess.run(['kind', 'get', 'clusters'],
                              capture_output=True, text=True, timeout=60)
    if name not in (existing.stdout or '').split():
        return False
    delete = subprocess.run(['kind', 'delete', 'cluster', '--name', name],
                            capture_output=True, text=True, timeout=300)
    if delete.returncode != 0:
        raise exceptions.CloudError(
            f'kind cluster deletion failed: {delete.stderr[-500:]}')
    try:
        os.remove(kubeconfig_path(name))
    except FileNotFoundError:
        pass
    return True
