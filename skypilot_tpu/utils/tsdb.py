"""Dependency-free fixed-interval ring TSDB for the serve controller.

The controller already *sees* everything — it scrapes every replica's
/metrics each tick and aggregates — but until now it kept only the
latest sample, so "what was the fleet doing 90 seconds before that p99
spike" needed an external Prometheus. This module is the retrospective
half of the observability plane:

- :class:`SeriesRing` / :class:`TimeSeriesStore`: per-series rings of
  ``(t, value)`` points at the controller tick cadence, with coarser
  downsampled tiers behind them (tier k+1 stores the mean of every
  ``$SKYTPU_TSDB_DOWNSAMPLE`` consecutive tier-k points), so recent
  history is full-resolution and old history degrades gracefully
  instead of vanishing. Capacity ``$SKYTPU_TSDB_POINTS`` per tier.
- :class:`RateDeriver`: turns successive CUMULATIVE scrape snapshots
  (parsed Prometheus samples) into per-second rates and windowed
  histogram quantiles — the delta of two cumulative bucket vectors is
  itself a histogram of exactly that window's observations. Counter
  resets (replica restart mid-window) are detected per series: a value
  that went *down* means the counter restarted from zero, so the delta
  since the reset is the current value itself.
- :class:`EwmaAnomalyDetector`: EWMA mean/variance z-score per series,
  feeding the dashboard alert column and the flight-recorder trigger.
- :class:`FlightRecorder`: seals the last ``$SKYTPU_TSDB_FLIGHT_WINDOW``
  seconds of every series plus caller-supplied context (trace-ring
  entries, scheduler /stats) into a JSON postmortem artifact when a
  replica fails/drains or a series goes anomalous — the black box an
  operator opens *after* the incident.

Everything here is plain stdlib + utils.metrics parsing helpers: the
controller must run on machines with nothing installed.
"""
from __future__ import annotations

import json
import math
import os
import threading
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from skypilot_tpu import env_vars
from skypilot_tpu.utils import metrics as metrics_lib

Point = Tuple[float, float]  # (unix seconds, value)


def _points_per_tier() -> int:
    return max(16, env_vars.get_int('SKYTPU_TSDB_POINTS') or 512)


def _downsample_factor() -> int:
    return max(2, env_vars.get_int('SKYTPU_TSDB_DOWNSAMPLE') or 8)


class SeriesRing:
    """Fixed-capacity ring of (t, v) points with downsampled tiers.

    Tier 0 holds raw appends; every ``factor`` tier-k points are folded
    (mean of t, mean of v) into one tier-(k+1) point, so with 3 tiers
    and 512 points each, a 20 s tick keeps ~2.8 h full-resolution plus
    ~23 h at 160 s and ~7.6 days at 21 min per point.
    """

    TIERS = 3

    def __init__(self, points: Optional[int] = None,
                 factor: Optional[int] = None):
        self.points = points or _points_per_tier()
        self.factor = factor or _downsample_factor()
        self._tiers: List[deque] = [deque(maxlen=self.points)
                                    for _ in range(self.TIERS)]
        self._folding: List[List[Point]] = [[] for _ in range(self.TIERS)]

    def append(self, t: float, v: float) -> None:
        self._append_tier(0, float(t), float(v))

    def _append_tier(self, k: int, t: float, v: float) -> None:
        self._tiers[k].append((t, v))
        if k + 1 >= self.TIERS:
            return
        buf = self._folding[k]
        buf.append((t, v))
        if len(buf) >= self.factor:
            n = len(buf)
            self._folding[k] = []
            self._append_tier(k + 1, sum(p[0] for p in buf) / n,
                              sum(p[1] for p in buf) / n)

    def query(self, since: float = 0.0) -> List[Point]:
        """Points with t >= ``since`` from the finest tier that still
        reaches back to ``since``; when none does (the raw ring already
        wrapped past it), the tier with the longest memory answers —
        coarser, never empty-handed."""
        populated = [t for t in self._tiers if t]
        if not populated:
            return []
        for tier in self._tiers:
            if tier and tier[0][0] <= since:
                return [p for p in tier if p[0] >= since]
        oldest = min(populated, key=lambda tier: tier[0][0])
        return [p for p in oldest if p[0] >= since]


class TimeSeriesStore:
    """Named series, created on first record. Thread-safe: the
    controller tick records while HTTP handler threads query."""

    def __init__(self, points: Optional[int] = None,
                 factor: Optional[int] = None):
        self._points = points
        self._factor = factor
        self._series: Dict[str, SeriesRing] = {}
        self._lock = threading.Lock()

    def record(self, now: float, values: Dict[str, float]) -> None:
        with self._lock:
            for name, value in values.items():
                v = float(value)
                if not math.isfinite(v):
                    continue
                ring = self._series.get(name)
                if ring is None:
                    ring = SeriesRing(self._points, self._factor)
                    self._series[name] = ring
                ring.append(now, v)

    def names(self) -> List[str]:
        with self._lock:
            return sorted(self._series)

    def query(self, names: Optional[Sequence[str]] = None,
              since: float = 0.0) -> Dict[str, List[Point]]:
        with self._lock:
            wanted = self._series if names is None else {
                n: self._series[n] for n in names if n in self._series}
            return {name: [list(p) for p in ring.query(since)]
                    for name, ring in wanted.items()}


class RateDeriver:
    """Successive cumulative scrape snapshots -> per-tick series.

    ``derive(now, samples)`` diffs the fleet aggregate against the
    previous call and returns {series_name: value} for this window:
    counters become per-second rates, histograms become windowed
    quantiles (delta of cumulative bucket vectors = the window's own
    histogram), and ``_sum``/``_count`` pairs become windowed means.
    The first call only primes state and returns {}.

    Counter reset: the fleet aggregate DROPS a restarted replica's old
    counters (the manager prunes dead scrapes), so a cumulative value
    can go down without any single counter resetting. Either way the
    honest window delta is ``max(cur - prev, 0)`` — except a full
    restart (prev >> cur ~ 0) where ``cur`` itself is the activity
    since the reset, which ``cur < prev`` selects.
    """

    # (metric family, series name) — cumulative counters -> rate/s.
    COUNTERS = (
        ('skytpu_serve_requests_total', 'req_rps'),
        ('skytpu_serve_tokens_out_total', 'tok_rps'),
        ('skytpu_serve_rejected_total', 'rejected_rps'),
    )
    # (histogram family, series prefix, quantiles) -> windowed p50/p99.
    HISTOGRAMS = (
        ('skytpu_serve_ttft_ms', 'ttft', (0.5, 0.99)),
        ('skytpu_serve_tpot_ms', 'tpot', (0.5, 0.99)),
        ('skytpu_engine_step_gap_ms', 'step_gap', (0.5,)),
    )
    # (histogram family, series name) -> windowed mean (sum/count).
    MEANS = (
        ('skytpu_engine_spec_accept_tokens', 'spec_accept_per_step'),
    )

    def __init__(self):
        self._prev_t: Optional[float] = None
        self._prev_counters: Dict[str, float] = {}
        self._prev_buckets: Dict[str, Dict[float, float]] = {}
        self._prev_sums: Dict[str, Tuple[float, float]] = {}

    @staticmethod
    def _delta(cur: float, prev: Optional[float]) -> float:
        if prev is None:
            return 0.0
        return cur if cur < prev else cur - prev

    def derive(self, now: float,
               samples: Sequence[metrics_lib.Sample]) -> Dict[str, float]:
        first = self._prev_t is None
        dt = 0.0 if first else max(1e-9, now - self._prev_t)
        out: Dict[str, float] = {}
        for family, series in self.COUNTERS:
            cur = metrics_lib.sample_value(samples, family)
            if cur is None:
                continue
            if not first:
                out[series] = self._delta(
                    cur, self._prev_counters.get(family)) / dt
            self._prev_counters[family] = cur

        for family, prefix, quantiles in self.HISTOGRAMS:
            cum = metrics_lib.histogram_cumulative(samples, family)
            if not cum:
                continue
            cur_b = dict(cum)
            prev_b = self._prev_buckets.get(family)
            if not first and prev_b is not None:
                reset = any(cur_b.get(le, 0.0) < prev
                            for le, prev in prev_b.items())
                window = [(le, c if reset
                           else c - prev_b.get(le, 0.0))
                          for le, c in sorted(cur_b.items())]
                if window and window[-1][1] > 0:
                    for q in quantiles:
                        val = metrics_lib.histogram_quantile(window, q)
                        if val is not None:
                            out[f'{prefix}_p{int(q * 100)}_ms'] = val
            self._prev_buckets[family] = cur_b

        for family, series in self.MEANS:
            total = metrics_lib.sample_value(samples, f'{family}_sum')
            count = metrics_lib.sample_value(samples, f'{family}_count')
            if total is None or count is None:
                continue
            if not first:
                prev = self._prev_sums.get(family)
                d_count = self._delta(count, prev and prev[1])
                d_sum = (total if prev is not None and count < prev[1]
                         else total - (prev[0] if prev else 0.0))
                if d_count > 0:
                    out[series] = d_sum / d_count
            self._prev_sums[family] = (total, count)

        self._prev_t = now
        return out


class EwmaAnomalyDetector:
    """Per-series EWMA mean/variance z-score.

    ``observe(name, value)`` scores *before* folding the value in, so a
    spike is judged against the pre-spike baseline. The first
    ``min_samples`` observations return 0.0 (warming); a zero-variance
    baseline (constant series) scores any departure at :data:`Z_CAP` —
    definitely anomalous, still JSON-serializable.
    """

    Z_CAP = 100.0

    def __init__(self, alpha: float = 0.3,
                 z_threshold: Optional[float] = None,
                 min_samples: int = 5):
        if z_threshold is None:
            z_threshold = float(
                env_vars.get('SKYTPU_TSDB_ANOMALY_Z') or 4.0)
        self.alpha = alpha
        self.z_threshold = z_threshold
        self.min_samples = min_samples
        # name -> (count, ewma_mean, ewma_var)
        self._state: Dict[str, Tuple[int, float, float]] = {}
        self._last_z: Dict[str, float] = {}

    def observe(self, name: str, value: float) -> float:
        if not math.isfinite(value):
            return self._last_z.get(name, 0.0)
        n, mean, var = self._state.get(name, (0, float(value), 0.0))
        z = 0.0
        if n >= self.min_samples:
            sd = math.sqrt(var)
            if sd > 0.0:
                z = min(abs(value - mean) / sd, self.Z_CAP)
            elif value != mean:
                z = self.Z_CAP
        diff = value - mean
        incr = self.alpha * diff
        self._state[name] = (n + 1, mean + incr,
                             (1.0 - self.alpha) * (var + diff * incr))
        self._last_z[name] = z
        return z

    def observe_all(self, values: Dict[str, float]) -> Dict[str, float]:
        return {name: self.observe(name, v) for name, v in values.items()}

    def latest(self) -> Dict[str, float]:
        return dict(self._last_z)

    def flagged(self, zscores: Dict[str, float]) -> List[str]:
        return sorted(n for n, z in zscores.items()
                      if z >= self.z_threshold)


class FlightRecorder:
    """Black-box postmortem writer over a :class:`TimeSeriesStore`.

    ``seal(reason, now, context)`` snapshots the last
    ``$SKYTPU_TSDB_FLIGHT_WINDOW`` seconds of EVERY series (no
    selection — dropping a series is exactly what you regret during the
    postmortem) plus the caller's context dict into one JSON artifact
    under ``out_dir``. Repeat triggers of the same (reason-class,
    subject) within one window are throttled to a single artifact: an
    incident storms its trigger every tick, and 60 identical
    postmortems bury the one that matters.
    """

    def __init__(self, store: TimeSeriesStore, out_dir: str,
                 window_s: Optional[float] = None):
        if window_s is None:
            window_s = float(
                env_vars.get('SKYTPU_TSDB_FLIGHT_WINDOW') or 120)
        self.store = store
        self.out_dir = out_dir
        self.window_s = window_s
        self.sealed: List[str] = []
        self._last_seal: Dict[str, float] = {}

    @staticmethod
    def _throttle_key(reason: str) -> str:
        return ':'.join(reason.split(':')[:2])

    def seal(self, reason: str, now: float,
             context: Optional[Dict] = None) -> Optional[str]:
        key = self._throttle_key(reason)
        last = self._last_seal.get(key)
        if last is not None and now - last < self.window_s:
            return None
        payload = {
            'reason': reason,
            'sealed_at': now,
            'window_seconds': self.window_s,
            'series': self.store.query(since=now - self.window_s),
            'context': context or {},
        }
        os.makedirs(self.out_dir, exist_ok=True)
        slug = ''.join(ch if ch.isalnum() else '-' for ch in key)
        path = os.path.join(
            self.out_dir, f'postmortem_{int(now * 1000)}_{slug}.json')
        tmp = path + '.tmp'
        with open(tmp, 'w', encoding='utf-8') as f:
            json.dump(payload, f, indent=1, default=str)
        os.replace(tmp, path)  # readers never see a half-written box
        self._last_seal[key] = now
        self.sealed.append(path)
        return path
