"""Command runners: uniform exec/rsync to cluster hosts.

Counterpart of reference ``sky/utils/command_runner.py`` (CommandRunner:167,
SSHCommandRunner:437, KubernetesCommandRunner:713). Three impls:

- ``SSHCommandRunner``: ControlMaster-pooled ssh + rsync (TPU VM hosts).
- ``LocalProcessRunner``: subprocess against a host *directory* (the local
  cloud's emulated hosts) — the permanent test backend, so every
  orchestration path exercises the same runner interface.
- ``KubernetesCommandRunner``: kubectl-exec against pod hosts.
"""
from __future__ import annotations

import dataclasses
import os
import shlex
import subprocess
import tempfile
from typing import Dict, List, Optional, Sequence, Tuple, Union

_SSH_OPTIONS = [
    '-o', 'StrictHostKeyChecking=no',
    '-o', 'UserKnownHostsFile=/dev/null',
    '-o', 'IdentitiesOnly=yes',
    '-o', 'ConnectTimeout=30',
    '-o', 'ServerAliveInterval=30',
    '-o', 'ServerAliveCountMax=3',
    '-o', 'LogLevel=ERROR',
]


def _control_path() -> str:
    d = os.path.join(tempfile.gettempdir(), 'skytpu_ssh_ctrl')
    os.makedirs(d, exist_ok=True)
    return os.path.join(d, '%C')


@dataclasses.dataclass
class CommandResult:
    returncode: int
    stdout: str
    stderr: str


def _tar_pipe_upload(remote_argv_fn, source: str, target: str,
                     transport_name: str) -> None:
    """Upload ``source`` by piping a local tar stream into a remote
    extract command. ``remote_argv_fn(remote_cmd)`` wraps the remote shell
    command into the transport's argv (ssh / kubectl exec)."""
    src = source.rstrip('/')
    src_dir = os.path.isdir(src)
    tar_src = f'-C {shlex.quote(src)} .' if src_dir else (
        f'-C {shlex.quote(os.path.dirname(src) or ".")} '
        f'{shlex.quote(os.path.basename(src))}')
    if src_dir and not source.endswith('/'):
        target = os.path.join(target, os.path.basename(src))
    remote_cmd = (f'mkdir -p {shlex.quote(target)} && '
                  f'tar -x -C {shlex.quote(target)}')
    argv = remote_argv_fn(remote_cmd)
    tar = subprocess.Popen(['bash', '-c', f'tar -c {tar_src}'],
                           stdout=subprocess.PIPE)
    res = subprocess.run(argv, stdin=tar.stdout, capture_output=True,
                         text=True)
    tar.wait()
    if res.returncode != 0 or tar.returncode != 0:
        raise RuntimeError(
            f'tar-over-{transport_name} failed: {res.stderr.strip()}')


class CommandRunner:
    """Interface: run a command on a host; rsync files to/from it.

    ``stream_to`` may be a filesystem path (appended to) or a writable
    file-like object (lines are pumped to it as they arrive — works for
    in-memory buffers without a real fd, e.g. under click's CliRunner).
    """

    def run(self,
            cmd: Union[str, Sequence[str]],
            env: Optional[Dict[str, str]] = None,
            timeout: Optional[float] = None,
            stream_to=None) -> CommandResult:
        raise NotImplementedError

    @staticmethod
    def _run_with_stream(argv: Sequence[str], stream_to, cwd=None,
                         env=None, timeout=None) -> CommandResult:
        if isinstance(stream_to, str):
            with open(stream_to, 'ab') as f:
                proc = subprocess.run(argv, cwd=cwd, env=env, stdout=f,
                                      stderr=subprocess.STDOUT,
                                      timeout=timeout)
            return CommandResult(proc.returncode, '', '')
        proc = subprocess.Popen(argv, cwd=cwd, env=env,
                                stdout=subprocess.PIPE,
                                stderr=subprocess.STDOUT, text=True,
                                errors='replace')
        assert proc.stdout is not None
        timer = None
        if timeout:
            # The line-pump below has no natural timeout hook; a timer
            # kill bounds it (otherwise `timeout` is silently ignored on
            # the streaming path and a hung remote command pins the
            # caller's thread forever).
            import threading as _threading
            timer = _threading.Timer(timeout, proc.kill)
            timer.start()
        try:
            for line in proc.stdout:
                stream_to.write(line)
                stream_to.flush()
        except BaseException:
            # Consumer went away (e.g. HTTP client disconnect): the child
            # must not be orphaned mid-run — it would block forever once
            # its 64KB pipe buffer fills.
            proc.kill()
            proc.wait()
            raise
        finally:
            if timer is not None:
                timer.cancel()
        return CommandResult(proc.wait(), '', '')

    def rsync(self, source: str, target: str, up: bool = True) -> None:
        raise NotImplementedError

    def check_connection(self) -> bool:
        res = self.run('true', timeout=30)
        return res.returncode == 0


class LocalProcessRunner(CommandRunner):
    """Runs commands as subprocesses with cwd = the emulated host's dir."""

    def __init__(self, host_dir: str, base_env: Optional[Dict[str, str]] = None):
        self.host_dir = host_dir
        self.base_env = dict(base_env or {})

    def run(self, cmd, env=None, timeout=None, stream_to=None):
        if not isinstance(cmd, str):
            cmd = ' '.join(shlex.quote(c) for c in cmd)
        full_env = dict(os.environ)
        full_env.update(self.base_env)
        if env:
            full_env.update(env)
        os.makedirs(self.host_dir, exist_ok=True)
        if stream_to is not None:
            return self._run_with_stream(['bash', '-c', cmd], stream_to,
                                         cwd=self.host_dir, env=full_env,
                                         timeout=timeout)
        proc = subprocess.run(['bash', '-c', cmd], cwd=self.host_dir,
                              env=full_env, capture_output=True, text=True,
                              timeout=timeout)
        return CommandResult(proc.returncode, proc.stdout, proc.stderr)

    def rsync(self, source: str, target: str, up: bool = True) -> None:
        """rsync-semantics copy (pure Python: host dirs share a filesystem
        and the image may lack an rsync binary)."""
        import shutil
        if up:
            dst = os.path.join(self.host_dir, target.lstrip('/'))
            src = source
        else:
            src = os.path.join(self.host_dir, source.lstrip('/'))
            dst = target
        src_slash = src.endswith('/')
        src, dst = src.rstrip('/'), dst.rstrip('/')
        if os.path.isdir(src):
            if not src_slash:  # rsync: no trailing slash copies the dir itself
                dst = os.path.join(dst, os.path.basename(src))
            os.makedirs(dst, exist_ok=True)
            shutil.copytree(src, dst, dirs_exist_ok=True, symlinks=True)
        else:
            os.makedirs(os.path.dirname(dst) or '.', exist_ok=True)
            if os.path.isdir(dst):
                dst = os.path.join(dst, os.path.basename(src))
            shutil.copy2(src, dst)


class SSHCommandRunner(CommandRunner):
    """ssh/rsync with ControlMaster connection pooling."""

    def __init__(self, ip: str, user: str, key_path: str, port: int = 22,
                 proxy_command: Optional[str] = None):
        self.ip = ip
        self.user = user
        self.key_path = os.path.expanduser(key_path)
        self.port = port
        self.proxy_command = proxy_command

    def _ssh_base(self) -> List[str]:
        opts = list(_SSH_OPTIONS)
        opts += ['-o', 'ControlMaster=auto',
                 '-o', f'ControlPath={_control_path()}',
                 '-o', 'ControlPersist=120s']
        if self.proxy_command:
            opts += ['-o', f'ProxyCommand={self.proxy_command}']
        return (['ssh'] + opts + ['-i', self.key_path, '-p', str(self.port),
                                  f'{self.user}@{self.ip}'])

    def run(self, cmd, env=None, timeout=None, stream_to=None):
        if not isinstance(cmd, str):
            cmd = ' '.join(shlex.quote(c) for c in cmd)
        if env:
            exports = ' '.join(f'export {k}={shlex.quote(v)};'
                               for k, v in env.items())
            cmd = exports + ' ' + cmd
        argv = self._ssh_base() + [f'bash -lc {shlex.quote(cmd)}']
        if stream_to is not None:
            return self._run_with_stream(argv, stream_to, timeout=timeout)
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=timeout)
        return CommandResult(proc.returncode, proc.stdout, proc.stderr)

    def rsync(self, source: str, target: str, up: bool = True) -> None:
        import shutil
        if shutil.which('rsync'):
            ssh_cmd = ' '.join(['ssh'] + _SSH_OPTIONS
                               + ['-i', self.key_path, '-p', str(self.port)])
            remote = f'{self.user}@{self.ip}:{target if up else source}'
            pair = [source, remote] if up else [remote, target]
            res = subprocess.run(
                ['rsync', '-a', '--delete', '-e', ssh_cmd] + pair,
                capture_output=True, text=True)
            if res.returncode != 0:
                raise RuntimeError(f'rsync failed: {res.stderr.strip()}')
            return
        # Fallback: tar over ssh (no rsync binary on the client).
        if not up:
            raise RuntimeError('rsync-down requires the rsync binary')
        _tar_pipe_upload(
            lambda rc: self._ssh_base() + [f'bash -lc {shlex.quote(rc)}'],
            source, target, 'ssh')


class KubernetesCommandRunner(CommandRunner):
    """kubectl-exec runner for pod hosts (reference
    sky/utils/command_runner.py:713 KubernetesCommandRunner).

    Shells out to kubectl (present wherever a kubeconfig is) instead of
    streaming exec over SPDY ourselves; rsync uses tar piped through
    `kubectl exec -i`.
    """

    def __init__(self, namespace: str, pod_name: str,
                 container: str = 'skytpu'):
        self.namespace = namespace
        self.pod_name = pod_name
        self.container = container

    def _base(self, interactive: bool = False) -> List[str]:
        argv = ['kubectl', 'exec']
        if interactive:
            argv.append('-i')
        argv += ['-n', self.namespace, self.pod_name,
                 '-c', self.container, '--']
        return argv

    def run(self, cmd, env=None, timeout=None, stream_to=None):
        if not isinstance(cmd, str):
            cmd = ' '.join(shlex.quote(c) for c in cmd)
        if env:
            exports = ' '.join(f'export {k}={shlex.quote(v)};'
                               for k, v in env.items())
            cmd = exports + ' ' + cmd
        argv = self._base() + ['bash', '-c', cmd]
        if stream_to is not None:
            return self._run_with_stream(argv, stream_to, timeout=timeout)
        proc = subprocess.run(argv, capture_output=True, text=True,
                              timeout=timeout)
        return CommandResult(proc.returncode, proc.stdout, proc.stderr)

    def rsync(self, source: str, target: str, up: bool = True) -> None:
        if not up:
            raise RuntimeError('kubectl runner supports upload only')
        _tar_pipe_upload(
            lambda rc: self._base(interactive=True) + ['bash', '-c', rc],
            source, target, 'kubectl')
