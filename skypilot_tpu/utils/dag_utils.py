"""Chain-DAG <-> YAML helpers for managed-job pipelines.

Counterpart of reference ``sky/utils/dag_utils.py``
(load_chain_dag_from_yaml :59, dump_chain_dag_to_yaml :119). A pipeline
YAML is a multi-document file: an optional first doc containing only
``name:`` titles the pipeline; each following doc is a task config, run
sequentially by the jobs controller.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import dag as dag_lib
from skypilot_tpu import exceptions
from skypilot_tpu import task as task_lib
from skypilot_tpu.utils import common_utils


def load_chain_dag_from_yaml(
        path: str,
        env_overrides: Optional[Dict[str, str]] = None) -> dag_lib.Dag:
    configs = [c for c in common_utils.read_yaml_all(path) if c]
    return load_chain_dag_from_yaml_configs(configs, env_overrides,
                                            source=path)


def load_chain_dag_from_yaml_configs(
        configs: List[Dict[str, Any]],
        env_overrides: Optional[Dict[str, str]] = None,
        source: str = '<configs>') -> dag_lib.Dag:
    dag_name = None
    if configs and set(configs[0].keys()) == {'name'}:
        # Header doc: names the pipeline, defines no task.
        dag_name = configs[0]['name']
        configs = configs[1:]
    if not configs:
        raise exceptions.InvalidTaskError(
            f'{source}: no task documents found')
    dag = dag_lib.Dag(name=dag_name)
    prev = None
    for cfg in configs:
        task = task_lib.Task.from_yaml_config(cfg, env_overrides,
                                              source=source)
        dag.add(task)
        if prev is not None:
            dag.add_edge(prev, task)
        prev = task
    return dag


def dag_to_yaml_configs(dag: dag_lib.Dag) -> List[Dict[str, Any]]:
    """Task configs in chain order (topological)."""
    return [t.to_yaml_config() for t in dag.topological_order()]
