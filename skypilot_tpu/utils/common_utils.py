"""Small shared helpers: ids, names, yaml, retries, parsing."""
from __future__ import annotations

import functools
import getpass
import hashlib
import os
import re
import socket
import time
import uuid
from typing import Any, Callable, Dict, List, Optional, Tuple, Union

import yaml

CLUSTER_NAME_VALID_REGEX = re.compile(r'^[a-zA-Z]([-_.a-zA-Z0-9]*[a-zA-Z0-9])?$')
USER_HASH_LENGTH = 8


def find_free_port(host: str = '127.0.0.1') -> int:
    """An OS-assigned free port. NOTE: bind-then-close is inherently racy —
    only use where the consumer binds immediately (e.g. picking distinct
    ports for local replicas); long-lived servers should bind port 0
    themselves and report the assigned port."""
    s = socket.socket()
    s.bind((host, 0))
    port = s.getsockname()[1]
    s.close()
    return port


def get_user_hash() -> str:
    """Stable per-user hash, used to namespace generated cloud resources."""
    env = os.environ.get('SKYTPU_USER_HASH')
    if env:
        return env[:USER_HASH_LENGTH]
    key = f'{getpass.getuser()}-{socket.gethostname()}'
    return hashlib.md5(key.encode()).hexdigest()[:USER_HASH_LENGTH]


def get_user_name() -> str:
    return os.environ.get('SKYTPU_USER', None) or getpass.getuser()


def generate_run_id() -> str:
    return f'skytpu-{time.strftime("%Y-%m-%d-%H-%M-%S")}-{uuid.uuid4().hex[:6]}'


def check_cluster_name_is_valid(name: str) -> None:
    if not CLUSTER_NAME_VALID_REGEX.match(name):
        raise ValueError(
            f'Cluster name {name!r} is invalid: must start with a letter and '
            'contain only letters, digits, "-", "_", ".".')


def make_cluster_name_on_cloud(display_name: str, max_length: int = 35) -> str:
    """Append user hash; truncate+hash if too long (cloud name limits)."""
    user_hash = get_user_hash()
    name = f'{display_name}-{user_hash}'
    name = name.replace('_', '-').replace('.', '-').lower()
    if len(name) > max_length:
        digest = hashlib.md5(name.encode()).hexdigest()[:6]
        keep = max_length - len(user_hash) - len(digest) - 2
        name = f'{name[:keep]}-{digest}-{user_hash}'
    return name


def read_yaml(path: str) -> Dict[str, Any]:
    with open(path, 'r', encoding='utf-8') as f:
        return yaml.safe_load(f) or {}


def read_yaml_all(path: str) -> List[Dict[str, Any]]:
    with open(path, 'r', encoding='utf-8') as f:
        return [c or {} for c in yaml.safe_load_all(f)]


def dump_yaml(path: str, config: Union[Dict[str, Any], List[Dict[str, Any]]]) -> None:
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    with open(path, 'w', encoding='utf-8') as f:
        f.write(dump_yaml_str(config))


def dump_yaml_str(config: Union[Dict[str, Any], List[Dict[str, Any]]]) -> str:

    class _Dumper(yaml.SafeDumper):
        pass

    _Dumper.add_representer(
        tuple, lambda d, t: d.represent_list(list(t)))
    if isinstance(config, list):
        return yaml.dump_all(config, Dumper=_Dumper, sort_keys=False,
                             default_flow_style=False)
    return yaml.dump(config, Dumper=_Dumper, sort_keys=False,
                     default_flow_style=False)


def parse_plus_number(value: Union[int, float, str, None],
                      field: str) -> Tuple[Optional[float], bool]:
    """Parse '8', 8, '8+' → (8.0, plus?). None → (None, False)."""
    if value is None:
        return None, False
    if isinstance(value, (int, float)):
        return float(value), False
    s = str(value).strip()
    plus = s.endswith('+')
    if plus:
        s = s[:-1]
    try:
        return float(s), plus
    except ValueError as e:
        raise ValueError(f'Invalid {field}: {value!r}. '
                         f"Expected a number or 'N+'.") from e


def parse_memory_gb(value: Union[int, float, str, None],
                    field: str = 'memory') -> Tuple[Optional[float], bool]:
    """Like parse_plus_number but strips an optional GB/GiB unit.

    Accepts '32', '32+', '32GB', '32GB+', '32+GB'.
    """
    if isinstance(value, str):
        s = value.strip()
        m = re.match(r'^([0-9.]+)\s*(\+)?\s*(gb|gib|g)?\s*(\+)?$', s,
                     flags=re.IGNORECASE)
        if m is None:
            raise ValueError(f'Invalid {field}: {value!r}. '
                             "Expected e.g. '32', '32+', '32GB', '32GB+'.")
        value = m.group(1) + ('+' if (m.group(2) or m.group(4)) else '')
    return parse_plus_number(value, field)


def format_float(x: Optional[float], precision: int = 2) -> str:
    if x is None:
        return '-'
    if abs(x - round(x)) < 1e-9:
        return str(int(round(x)))
    return f'{x:.{precision}f}'


def retry(max_retries: int = 3, initial_backoff: float = 1.0,
          exceptions_to_retry: Tuple = (Exception,)) -> Callable:
    """Exponential-backoff retry decorator for flaky IO."""

    def decorator(fn: Callable) -> Callable:

        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            backoff = initial_backoff
            for attempt in range(max_retries):
                try:
                    return fn(*args, **kwargs)
                except exceptions_to_retry:
                    if attempt == max_retries - 1:
                        raise
                    time.sleep(backoff)
                    backoff *= 2
            raise RuntimeError('unreachable')

        return wrapper

    return decorator


def readable_time_duration(start: Optional[float], end: Optional[float] = None,
                           absolute: bool = False) -> str:
    """'3m 12s' style durations for status tables."""
    if start is None:
        return '-'
    if end is None:
        end = time.time()
    seconds = int(end - start)
    if seconds < 0:
        seconds = 0
    units = [('d', 86400), ('h', 3600), ('m', 60), ('s', 1)]
    parts: List[str] = []
    for suffix, size in units:
        if seconds >= size or (suffix == 's' and not parts):
            n, seconds = divmod(seconds, size)
            parts.append(f'{n}{suffix}')
        if len(parts) == 2:
            break
    text = ' '.join(parts)
    if absolute:
        return text
    return f'{text} ago'


def truncate_long_string(s: str, max_length: int = 35) -> str:
    if len(s) <= max_length:
        return s
    return s[:max_length - 3] + '...'


class Backoff:
    """Capped exponential backoff with jitter-free determinism for tests."""

    def __init__(self, initial: float = 1.0, cap: float = 30.0,
                 factor: float = 2.0):
        self._next = initial
        self._cap = cap
        self._factor = factor

    def next_backoff(self) -> float:
        value = self._next
        self._next = min(self._next * self._factor, self._cap)
        return value
