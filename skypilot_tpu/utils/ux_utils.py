"""Console UX: spinner statuses, log-path hints, colored status names.

Role of reference ``sky/utils/rich_utils.py`` + ``ux_utils.py`` (safe
spinner statuses, 'To see detailed logs: ...' hints). Uses ``rich`` when
available and stdout is a TTY; otherwise degrades to plain line prints so
API-server logs and CI output stay clean.
"""
from __future__ import annotations

import contextlib
import sys
from typing import Iterator, Optional

_SPINNER = None  # single live spinner (rich refuses nested Live displays)


@contextlib.contextmanager
def status(message: str) -> Iterator[None]:
    """Spinner while a long operation runs; plain print off-TTY."""
    global _SPINNER
    use_rich = sys.stdout.isatty() and _SPINNER is None
    if use_rich:
        try:
            import rich.console
            console = rich.console.Console()
            with console.status(f'[bold cyan]{message}[/]') as live:
                _SPINNER = live
                try:
                    yield
                finally:
                    _SPINNER = None
            return
        except ImportError:
            pass
    print(message, flush=True)
    yield


def update_status(message: str) -> None:
    if _SPINNER is not None:
        _SPINNER.update(f'[bold cyan]{message}[/]')
    else:
        print(message, flush=True)


def log_path_hint(path: str, what: str = 'detailed logs') -> str:
    return f'To see {what}: tail -f {path}'


_STATUS_COLORS = {
    'UP': 'green', 'RUNNING': 'green', 'SUCCEEDED': 'green',
    'READY': 'green', 'ALIVE': 'green',
    'INIT': 'yellow', 'PENDING': 'yellow', 'STARTING': 'yellow',
    'PROVISIONING': 'yellow', 'RECOVERING': 'yellow', 'STOPPED': 'yellow',
    'FAILED': 'red', 'FAILED_SETUP': 'red', 'FAILED_NO_RESOURCE': 'red',
    'FAILED_CONTROLLER': 'red', 'CANCELLED': 'red', 'SHUTTING_DOWN': 'red',
}
_ANSI = {'green': '\033[32m', 'yellow': '\033[33m', 'red': '\033[31m'}


def colorize_status(name: str) -> str:
    """ANSI-color a status name on TTYs; pass through otherwise.

    Accepts pre-padded input (lookup strips whitespace) so fixed-width
    table columns survive the invisible escape codes.
    """
    if not sys.stdout.isatty():
        return name
    color = _STATUS_COLORS.get(name.strip())
    if color is None:
        return name
    return f'{_ANSI[color]}{name}\033[0m'
