"""Name → class registries (clouds, backends, recovery strategies, LB policies).

Same role as the reference's ``sky/utils/registry.py``: decorating a class
registers it under a canonical name; lookups are case-insensitive and support
aliases.
"""
from __future__ import annotations

from typing import Callable, Dict, Generic, List, Optional, Type, TypeVar

T = TypeVar('T')


class Registry(Generic[T]):

    def __init__(self, registry_name: str):
        self._registry_name = registry_name
        self._entries: Dict[str, T] = {}
        self._aliases: Dict[str, str] = {}

    def register(self, name: Optional[str] = None,
                 aliases: Optional[List[str]] = None) -> Callable[[Type], Type]:

        def decorator(cls: Type) -> Type:
            key = (name or cls.__name__).lower()
            if key in self._entries:
                raise ValueError(
                    f'{self._registry_name}: duplicate registration {key!r}')
            self._entries[key] = cls
            for alias in aliases or []:
                self._aliases[alias.lower()] = key
            return cls

        return decorator

    def canonical_name(self, name: str) -> str:
        key = name.lower()
        return self._aliases.get(key, key)

    def get(self, name: str) -> Optional[T]:
        return self._entries.get(self.canonical_name(name))

    def from_str(self, name: Optional[str]) -> Optional[T]:
        if name is None:
            return None
        entry = self.get(name)
        if entry is None:
            raise ValueError(
                f'{self._registry_name}: unknown name {name!r}. '
                f'Available: {sorted(self._entries)}')
        return entry

    def keys(self) -> List[str]:
        return sorted(self._entries)

    def values(self) -> List[T]:
        return [self._entries[k] for k in sorted(self._entries)]

    def __contains__(self, name: str) -> bool:
        return self.canonical_name(name) in self._entries
