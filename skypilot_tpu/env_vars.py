"""Central registry of every ``SKYTPU_*`` environment variable.

Every environment variable the package READS must be registered here —
``skylint``'s ``env-contract`` checker enforces it (an unregistered read
fails tier-1, and a registered entry nothing reads is flagged as dead).
The registry is also the source of the env-var table in
``docs/serving.md`` (``render_markdown_table``), so name, default, and
doc live in exactly one place.

Entries marked ``exported=True`` are part of the env contract this
framework EXPORTS to user job processes (the SKYTPU_* rank contract,
``runtime/constants.py``) or to replica tasks; the package sets them but
may never read them back, so the dead-entry check skips them.

Readers either go through the accessors here (``get`` applies the
registered default; hot subsystems — models/decode.py, serve/, jobs/ —
do) or read ``os.environ`` directly with the same literal name (leaf
utilities); both count as reads for the contract checker.

Import-light on purpose (os + dataclasses only): this module is imported
by utils/, serve/, models/ alike and must never create an import cycle.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Dict, Iterable, Optional


@dataclasses.dataclass(frozen=True)
class EnvVar:
    name: str
    default: Optional[str]      # None = unset means "feature off"/derived
    subsystem: str
    doc: str
    exported: bool = False      # set for subprocesses/user tasks, not read


REGISTRY: Dict[str, EnvVar] = {}


def _v(name: str, default: Optional[str], subsystem: str, doc: str,
       exported: bool = False) -> None:
    if name in REGISTRY:
        raise ValueError(f'duplicate env var registration: {name}')
    REGISTRY[name] = EnvVar(name, default, subsystem, doc, exported)


# -- runtime rank contract (exported to every job process) --------------------
_v('SKYTPU_NUM_HOSTS', None, 'runtime',
   'hosts in the gang (rank contract exported to job processes)',
   exported=True)
_v('SKYTPU_HOST_RANK', None, 'runtime',
   'this host\'s rank within the gang (slice-major)', exported=True)
_v('SKYTPU_HOST_IPS', None, 'runtime',
   'newline-separated host IPs in rank order', exported=True)
_v('SKYTPU_COORDINATOR_ADDR', None, 'runtime',
   'host0_ip:port for jax.distributed rendezvous')
_v('SKYTPU_NUM_PROCESSES', None, 'runtime',
   'process count for jax.distributed.initialize')
_v('SKYTPU_PROCESS_ID', None, 'runtime',
   'process id for jax.distributed.initialize')
_v('SKYTPU_JOB_ID', None, 'runtime',
   'job id the agent exports to every job process', exported=True)
_v('SKYTPU_CLUSTER_NAME', None, 'runtime',
   'cluster name the agent exports to every job process', exported=True)
_v('SKYTPU_NUM_SLICES', None, 'runtime',
   'ganged slice count (multi-slice DCN jobs)')
_v('SKYTPU_SLICE_ID', None, 'runtime',
   'this host\'s slice id (multi-slice DCN jobs)')
_v('SKYTPU_HOSTS_PER_SLICE', None, 'runtime',
   'hosts per slice (rank = slice_id * hosts_per_slice + worker)',
   exported=True)
_v('SKYTPU_AXON_STASH', None, 'runtime',
   'stashed PALLAS_AXON_POOL_IPS for control-plane spawns (restored '
   'for user job processes)')
_v('SKYTPU_MAX_CONCURRENT_JOBS', None, 'runtime',
   'override for per-host concurrent job slots (default: derived from '
   'host shape)')

# -- state / identity / config ------------------------------------------------
_v('SKYTPU_STATE_DIR', '~/.skytpu', 'state',
   'root of the sqlite state + logs tree')
_v('SKYTPU_CONFIG', None, 'state',
   'path of the user config YAML (default ~/.skytpu/config.yaml)')
_v('SKYTPU_USER_HASH', None, 'state',
   'override for the stable per-user hash')
_v('SKYTPU_USER', None, 'state',
   'override for the user name recorded on clusters/requests')
_v('SKYTPU_SSH_CONFIG', '~/.ssh/config', 'state',
   'ssh config file that cluster host aliases are written into')
_v('SKYTPU_CLUSTER_LOCK_TIMEOUT', '600', 'state',
   'seconds to wait for a per-cluster operation lock')

# -- API server / client ------------------------------------------------------
_v('SKYTPU_API_SERVER_URL', None, 'api',
   'remote API server endpoint (default: the local in-process server)')
_v('SKYTPU_API_TOKEN', None, 'api',
   'bearer token for the API server (client sends, server verifies)')
_v('SKYTPU_UPLOAD_MAX_BYTES', None, 'api',
   'server-side cap on workdir upload size (default in server/server.py)')

# -- serve plane --------------------------------------------------------------
_v('SKYTPU_SERVE_TICK', '20', 'serve',
   'controller loop tick in seconds (autoscale + reconcile + probe)')
_v('SKYTPU_SERVE_LB_SYNC', '5', 'serve',
   'LB replica-list sync interval in seconds (also sizes the rolling-'
   'update drain grace: 2x)')
_v('SKYTPU_SERVE_LB_PORT', '0', 'serve',
   'pinned LB listen port (0 = kernel-assigned)')
_v('SKYTPU_LB_METRICS_PATH', '/metrics', 'serve',
   "path the LB answers with its own metrics ('' = proxy through)")
_v('SKYTPU_SERVE_DEBUG', None, 'serve',
   'log per-replica liveness verdicts (preemption classification)')
_v('SKYTPU_SERVE_REPLICA_PORT', '8001', 'serve',
   'port the generation replica binds (assigned by the replica manager)')
_v('SKYTPU_SERVE_REPLICA_ID', None, 'serve',
   'replica id exported into each replica task', exported=True)
_v('SKYTPU_ADMIT_BATCH', '1', 'serve',
   'same-bucket admissions fused into one admit_many dispatch '
   '(1 = solo)')
_v('SKYTPU_PREFILL_CHUNK', '0', 'serve',
   'prefill chunk size in tokens (0 = monolithic prefill)')
_v('SKYTPU_PREFILL_BUDGET', '0', 'serve',
   'prefill tokens dispatched per scheduling round (0 = 2x chunk)')
_v('SKYTPU_TTFT_SLO_MS', '0', 'serve',
   'TTFT SLO for admission control; estimated-over-SLO requests get '
   '429 (0 = never reject)')
_v('SKYTPU_PREFILL_TOKENS_PER_S', '0', 'serve',
   'seed for the effective-prefill-rate EMA (0 = learn from traffic)')
_v('SKYTPU_INFLIGHT_STEPS', '2', 'serve',
   'decode steps dispatched back-to-back per scheduling round '
   '(1 = synchronous one-step-per-tick oracle)')

# -- decode engine ------------------------------------------------------------
_v('SKYTPU_KV_BLOCK', '64', 'engine',
   'KV cache block rows (0 = contiguous per-slot KV, the equivalence '
   'oracle)')
_v('SKYTPU_KV_BLOCKS', '0', 'engine',
   'KV pool size in blocks (0 = the contiguous layout\'s HBM budget)')
_v('SKYTPU_KV_DTYPE', 'bf16', 'engine',
   'paged-KV storage dtype: bf16 (bit-identity oracle) or int8 '
   '(absmax-quantized pool + f32 per-row scales; paged mode only)')
_v('SKYTPU_SPEC_TOKENS', '4', 'engine',
   'speculative draft tokens per decode step (0 = plain one-token '
   'steps, the bit-identity oracle)')
_v('SKYTPU_SPEC_NGRAM', '3', 'engine',
   'max n-gram length the prompt-lookup drafter matches against each '
   'request\'s own token history')

# -- observability ------------------------------------------------------------
_v('SKYTPU_METRICS', '1', 'observability',
   'metrics collection on/off (0/false/off disables)')
_v('SKYTPU_TIMELINE', None, 'observability',
   'trace output path; enables the Perfetto timeline when set')
_v('SKYTPU_TIMELINE_EVENTS', None, 'observability',
   'timeline ring-buffer capacity (default 100000)')
_v('SKYTPU_TRACE_RING', None, 'observability',
   'completed request-trace ring capacity served at /trace/<request-id> '
   '(default 256)')
_v('SKYTPU_SLO_TTFT_MS', None, 'observability',
   'TTFT threshold for the controller burn-rate engine (default: the '
   'admission SLO SKYTPU_TTFT_SLO_MS; 0/unset with no admission SLO '
   'disables the TTFT burn signal)')
_v('SKYTPU_SLO_TPOT_MS', '0', 'observability',
   'TPOT threshold in ms for the controller burn-rate engine '
   '(0 = TPOT burn signal off)')
_v('SKYTPU_SLO_TARGET', '0.99', 'observability',
   'SLO attainment target; the error budget is 1 - target and burn '
   'rate 1.0 drains it exactly at the refill rate')
_v('SKYTPU_TSDB_POINTS', '512', 'observability',
   'points per tier of the controller ring TSDB (3 tiers: raw tick '
   'cadence plus two downsampled)')
_v('SKYTPU_TSDB_DOWNSAMPLE', '8', 'observability',
   'TSDB downsample factor: each coarser tier stores the mean of this '
   'many finer-tier points')
_v('SKYTPU_TSDB_ANOMALY_Z', '4.0', 'observability',
   'EWMA z-score at/above which a fleet series is flagged anomalous '
   '(dashboard alert + flight-recorder trigger)')
_v('SKYTPU_TSDB_FLIGHT_WINDOW', '120', 'observability',
   'seconds of series history the flight recorder seals into each '
   'postmortem artifact (also the per-trigger seal throttle)')
_v('SKYTPU_PROFILE_DIR', None, 'observability',
   'directory for POST /profile device-profile artifacts (default: '
   '<state dir>/profiles)')
_v('SKYTPU_PEAK_TFLOPS', '0', 'observability',
   'accelerator peak TFLOP/s for the serving-MFU roofline gauges '
   '(0 = MFU gauges report 0; AI/FLOPs/bytes still export)')

# -- managed jobs -------------------------------------------------------------
_v('SKYTPU_JOBS_POLL_INTERVAL', '15', 'jobs',
   'jobs controller cluster-poll interval in seconds')
_v('SKYTPU_JOBS_MAX_PARALLEL_JOBS', None, 'jobs',
   'override for alive controller processes (default: derived from '
   'controller memory)')
_v('SKYTPU_JOBS_MAX_PARALLEL_LAUNCHES', None, 'jobs',
   'override for in-flight provisions (default: derived from CPUs)')

# -- train / bench ------------------------------------------------------------
_v('SKYTPU_WARM_INIT_CACHE', None, 'train',
   'persistent XLA compile-cache dir for warm-start A/B runs')
_v('SKYTPU_BENCHMARK_LOG_DIR', None, 'train',
   'arms the benchmark callbacks; summary JSON lands here')

# -- provisioning -------------------------------------------------------------
_v('SKYTPU_OCI_COMPARTMENT', None, 'provision',
   'OCI compartment OCID override')
_v('SKYTPU_OCI_SUBNET', None, 'provision',
   'OCI subnet OCID override')
for _cloud in ('AWS', 'GCP', 'AZURE', 'OCI', 'DO', 'LAMBDA', 'VAST',
               'RUNPOD', 'CUDO', 'HYPERSTACK', 'PAPERSPACE',
               'FLUIDSTACK'):
    _v(f'SKYTPU_FAKE_{_cloud}_CREDENTIALS', None, 'clouds-testing',
       f'fake-credential mode for {_cloud.title()} (tests/local runs '
       'without real cloud credentials)')


# -- accessors ----------------------------------------------------------------
def get(name: str) -> Optional[str]:
    """The variable's current value with its registered default applied
    when UNSET (an explicitly empty value passes through as '' — several
    knobs give '' a meaning distinct from their default, e.g.
    SKYTPU_KV_BLOCK). Unregistered names raise: the registry is the
    contract."""
    entry = REGISTRY.get(name)
    if entry is None:
        raise KeyError(f'{name} is not registered in '
                       'skypilot_tpu/env_vars.py — add it there (and to '
                       'the docs table) before reading it')
    return os.environ.get(name, entry.default)


def get_int(name: str) -> int:
    """``int(get(name))`` with empty-string treated as 0 (several knobs
    use '' to mean "feature off", e.g. SKYTPU_KV_BLOCK= selects the
    contiguous KV layout). Call sites whose empty-string semantics
    differ (e.g. "raise on empty") coerce ``get()`` themselves."""
    return int(get(name) or 0)


def render_markdown_table(
        subsystems: Optional[Iterable[str]] = None) -> str:
    """The docs env-var table (docs/serving.md embeds the full one).

    Regenerate with::

        python -c "from skypilot_tpu import env_vars; \
print(env_vars.render_markdown_table())"
    """
    wanted = set(subsystems) if subsystems is not None else None
    rows = ['| Variable | Default | Subsystem | Description |',
            '| --- | --- | --- | --- |']
    for entry in sorted(REGISTRY.values(), key=lambda e: (e.subsystem,
                                                          e.name)):
        if wanted is not None and entry.subsystem not in wanted:
            continue
        default = '(unset)' if entry.default is None else \
            f'`{entry.default}`'
        doc = entry.doc + (' *(exported, not read back)*'
                           if entry.exported else '')
        rows.append(f'| `{entry.name}` | {default} | {entry.subsystem} '
                    f'| {doc} |')
    return '\n'.join(rows)
