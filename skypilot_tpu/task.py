"""Task: the declarative unit of work.

Counterpart of reference ``sky/task.py`` (Task with name/setup/run/num_nodes/
envs/workdir/file mounts/resources/service; YAML round-trip at
sky/task.py:196-1333). Differences for the TPU-native design:

- ``num_nodes`` counts *slices* (almost always 1); the per-slice host count is
  derived from the TPU slice type (see resources.Resources.num_hosts). The
  runtime still exports per-host rank/count env vars for multi-host slices.
- The env contract exported to ``run:`` is JAX-native (SKYTPU_COORDINATOR_ADDR
  / SKYTPU_NUM_PROCESSES / SKYTPU_PROCESS_ID plus topology vars), with
  SKYPILOT_NODE_* compatibility aliases (see agent/constants.py).
"""
from __future__ import annotations

import os
import re
from typing import (Any, Callable, Dict, List, Optional, Set, Tuple, Union)

from skypilot_tpu import exceptions
from skypilot_tpu import resources as resources_lib
from skypilot_tpu import schemas
from skypilot_tpu.utils import common_utils

_VALID_NAME_REGEX = re.compile(r'^[a-zA-Z0-9]+(?:[._-]{1,2}[a-zA-Z0-9]+)*$')

RunFn = Callable[[int, List[str]], Optional[str]]


def _fill_in_env_vars(yaml_field: Any, env_vars: Dict[str, str]) -> Any:
    """Substitute ``$VAR``/``${VAR}`` in string fields (file_mounts etc.)."""
    if isinstance(yaml_field, str):
        # Word-boundary-aware so $FOO never corrupts $FOOD.
        def _sub(m: 're.Match') -> str:
            name = m.group(1) or m.group(2)
            return env_vars.get(name, m.group(0))

        return re.sub(r'\$\{(\w+)\}|\$(\w+)', _sub, yaml_field)
    if isinstance(yaml_field, dict):
        return {k: _fill_in_env_vars(v, env_vars) for k, v in yaml_field.items()}
    if isinstance(yaml_field, list):
        return [_fill_in_env_vars(v, env_vars) for v in yaml_field]
    return yaml_field


class Task:
    """A coarse-grained unit of execution: setup + run on some Resources."""

    def __init__(
        self,
        name: Optional[str] = None,
        *,
        setup: Optional[str] = None,
        run: Optional[Union[str, RunFn]] = None,
        envs: Optional[Dict[str, str]] = None,
        secrets: Optional[Dict[str, str]] = None,
        workdir: Optional[str] = None,
        num_nodes: Optional[int] = None,
        file_mounts: Optional[Dict[str, str]] = None,
    ):
        self.name = name
        self.setup = setup
        self.run = run
        self.workdir = workdir
        self._envs = dict(envs) if envs else {}
        self._secrets = dict(secrets) if secrets else {}
        self.num_nodes = num_nodes if num_nodes is not None else 1
        self.file_mounts: Optional[Dict[str, str]] = (
            dict(file_mounts) if file_mounts else None)
        # mount_path -> data.Storage (reference sky/task.py storage_mounts).
        self.storage_mounts: Dict[str, Any] = {}
        self._extract_storage_mounts()
        self._resources: Tuple[resources_lib.Resources, ...] = (
            resources_lib.Resources(),)
        self._resources_ordered = False
        self.service: Optional[Any] = None  # serve.ServiceSpec
        self.config_overrides: Optional[Dict[str, Any]] = None
        # Optimizer inputs (YAML `estimated:` section): the TIME objective
        # and DP egress edges are inert without them (reference relies on
        # time_estimator callbacks, sky/optimizer.py:237).
        self.estimated_total_flops: Optional[float] = None
        self.estimated_output_gb: Optional[float] = None
        # Set by the optimizer:
        self.best_resources: Optional[resources_lib.Resources] = None
        self.estimated_cost_per_hour: Optional[float] = None
        self._validate()

        from skypilot_tpu import dag as dag_lib  # avoid import cycle
        current = dag_lib.get_current_dag()
        if current is not None:
            current.add(self)

    def _extract_storage_mounts(self) -> None:
        """Split bucket-backed entries out of ``file_mounts``.

        ``/data: gs://bucket/path`` (implicit COPY) and dict-valued entries
        (full storage specs) become ``storage_mounts``; plain local-path
        entries stay in ``file_mounts`` (reference sky/task.py:1028
        sync_storage_mounts split).
        """
        if not self.file_mounts:
            return
        from skypilot_tpu.data import storage as storage_lib
        plain: Dict[str, str] = {}
        for dst, src in self.file_mounts.items():
            if isinstance(src, dict) or (
                    isinstance(src, str) and storage_lib.is_store_url(src)):
                self.storage_mounts[dst] = (
                    storage_lib.Storage.from_yaml_config(src))
            else:
                plain[dst] = src
        self.file_mounts = plain or None

    def set_storage_mounts(self, mounts: Optional[Dict[str, Any]]) -> 'Task':
        from skypilot_tpu.data import storage as storage_lib
        self.storage_mounts = {}
        for dst, spec in (mounts or {}).items():
            if isinstance(spec, storage_lib.Storage):
                self.storage_mounts[dst] = spec
            else:
                self.storage_mounts[dst] = (
                    storage_lib.Storage.from_yaml_config(spec))
        return self

    def sync_storage_mounts(self) -> None:
        """Client-side phase: validate buckets, upload local sources."""
        from skypilot_tpu import global_user_state
        from skypilot_tpu import exceptions
        for dst, storage in self.storage_mounts.items():
            try:
                storage.validate()
            except exceptions.StorageError as e:
                raise exceptions.StorageError(
                    f'file_mounts[{dst!r}]: {e}') from None
            storage.sync_local_source()
            global_user_state.add_or_update_storage(
                storage.store.bucket, storage.url, storage.mode.value)

    # ---- validation -------------------------------------------------------
    def _validate(self) -> None:
        if self.name is not None and not _VALID_NAME_REGEX.match(self.name):
            raise exceptions.InvalidTaskError(
                f'Invalid task name {self.name!r}')
        if self.num_nodes < 1:
            raise exceptions.InvalidTaskError(
                f'num_nodes must be >= 1, got {self.num_nodes}')
        if self.run is not None and not isinstance(self.run, str) and (
                not callable(self.run)):
            raise exceptions.InvalidTaskError(
                'run must be a shell-script string or a callable')
        if self.workdir is not None:
            expanded = os.path.expanduser(self.workdir)
            if not os.path.isdir(expanded):
                raise exceptions.InvalidTaskError(
                    f'workdir {self.workdir!r} is not an existing directory')

    # ---- resources --------------------------------------------------------
    @property
    def resources(self) -> Tuple[resources_lib.Resources, ...]:
        return self._resources

    @property
    def resources_ordered(self) -> bool:
        return self._resources_ordered

    def set_resources(
        self,
        resources: Union[resources_lib.Resources,
                         List[resources_lib.Resources],
                         Set[resources_lib.Resources]],
        ordered: bool = False,
    ) -> 'Task':
        if isinstance(resources, resources_lib.Resources):
            resources = [resources]
        resources = list(resources)
        if not resources:
            raise exceptions.InvalidTaskError('Empty resources')
        self._resources = tuple(resources)
        self._resources_ordered = ordered
        return self

    # ---- envs -------------------------------------------------------------
    @property
    def envs(self) -> Dict[str, str]:
        return dict(self._envs)

    @property
    def secrets(self) -> Dict[str, str]:
        return dict(self._secrets)

    @property
    def envs_and_secrets(self) -> Dict[str, str]:
        out = dict(self._envs)
        out.update(self._secrets)
        return out

    def update_envs(
            self, envs: Union[None, Dict[str, str],
                              List[Tuple[str, str]]]) -> 'Task':
        if envs is None:
            return self
        if isinstance(envs, (list, tuple)):
            envs = dict(envs)
        for k, v in envs.items():
            if not isinstance(k, str) or not k:
                raise exceptions.InvalidTaskError(f'Invalid env name: {k!r}')
            self._envs[k] = str(v)
        return self

    def update_secrets(self, secrets: Optional[Dict[str, str]]) -> 'Task':
        if secrets:
            for k, v in secrets.items():
                self._secrets[k] = str(v)
        return self

    # ---- service ----------------------------------------------------------
    def set_service(self, service: Optional[Any]) -> 'Task':
        self.service = service
        return self

    # ---- YAML round-trip ---------------------------------------------------
    @classmethod
    def from_yaml_config(cls, config: Dict[str, Any],
                         env_overrides: Optional[Dict[str, str]] = None,
                         source: Optional[str] = None) -> 'Task':
        schemas.validate_task_config(config, source=source)
        config = dict(config)

        # YAML null means "must be supplied"; explicit '' is a real value.
        envs: Dict[str, Any] = {
            str(k): None if v is None else str(v)
            for k, v in (config.get('envs') or {}).items()
        }
        if env_overrides:
            envs.update({k: str(v) for k, v in env_overrides.items()})
        missing = [k for k, v in envs.items() if v is None]
        if missing:
            raise exceptions.InvalidTaskError(
                f'Environment variable(s) {missing} have no value; pass '
                "them via --env or fill in the 'envs:' section.")
        # Env substitution applies to everything downstream of `envs:`.
        config = _fill_in_env_vars(config, envs)

        task = cls(
            name=config.get('name'),
            setup=config.get('setup'),
            run=config.get('run'),
            envs=envs,
            secrets={str(k): str(v)
                     for k, v in (config.get('secrets') or {}).items()},
            workdir=config.get('workdir'),
            num_nodes=config.get('num_nodes'),
            file_mounts=config.get('file_mounts'),
        )
        res = resources_lib.Resources.from_yaml_config(
            config.get('resources'))
        ordered = bool((config.get('resources') or {}).get('ordered'))
        task.set_resources(res if isinstance(res, list) else [res],
                           ordered=ordered)
        if config.get('storage_mounts'):
            task.set_storage_mounts(config['storage_mounts'])
        if config.get('service'):
            from skypilot_tpu.serve import service_spec  # lazy import
            task.set_service(
                service_spec.ServiceSpec.from_yaml_config(config['service']))
        task.config_overrides = config.get('config_overrides')
        est = config.get('estimated') or {}
        for field, attr in (('total_flops', 'estimated_total_flops'),
                            ('output_gb', 'estimated_output_gb')):
            if est.get(field) is not None:
                try:
                    setattr(task, attr, float(est[field]))
                except (TypeError, ValueError) as e:
                    raise exceptions.InvalidTaskError(
                        f'estimated.{field}: {est[field]!r} is not a '
                        'number') from e
        return task

    @classmethod
    def from_yaml(cls, yaml_path: str,
                  env_overrides: Optional[Dict[str, str]] = None) -> 'Task':
        configs = common_utils.read_yaml_all(os.path.expanduser(yaml_path))
        configs = [c for c in configs if c]
        if not configs:
            return cls()
        if len(configs) > 1:
            raise exceptions.InvalidTaskError(
                f'{yaml_path} contains multiple documents; use '
                'dag_utils.load_chain_dag_from_yaml for pipelines.')
        return cls.from_yaml_config(configs[0], env_overrides,
                                    source=yaml_path)

    def to_yaml_config(self) -> Dict[str, Any]:
        cfg: Dict[str, Any] = {}

        def add(key: str, value: Any) -> None:
            if value is not None and value != {} and value != []:
                cfg[key] = value

        add('name', self.name)
        if len(self._resources) == 1:
            add('resources', self._resources[0].to_yaml_config())
        else:
            key = 'ordered' if self._resources_ordered else 'any_of'
            cfg['resources'] = {
                key: [r.to_yaml_config() for r in self._resources]
            }
        if self.num_nodes != 1:
            cfg['num_nodes'] = self.num_nodes
        add('envs', self._envs or None)
        add('secrets', self._secrets or None)
        add('workdir', self.workdir)
        add('file_mounts', self.file_mounts)
        add('storage_mounts',
            {dst: s.to_yaml_config()
             for dst, s in self.storage_mounts.items()} or None)
        add('setup', self.setup)
        add('run', self.run if isinstance(self.run, str) else None)
        if self.service is not None:
            cfg['service'] = self.service.to_yaml_config()
        add('config_overrides', self.config_overrides)
        est = {}
        if self.estimated_total_flops is not None:
            est['total_flops'] = self.estimated_total_flops
        if self.estimated_output_gb is not None:
            est['output_gb'] = self.estimated_output_gb
        add('estimated', est or None)
        return cfg

    # ---- misc -------------------------------------------------------------
    @property
    def tpu(self) -> Optional[Any]:
        """The TPU slice if every resource option agrees on one."""
        slices = {r.tpu for r in self._resources}
        if len(slices) == 1:
            return next(iter(slices))
        return None

    def __repr__(self) -> str:
        name = self.name or '<unnamed>'
        res = ', '.join(str(r) for r in self._resources)
        return f'Task({name!r}, num_nodes={self.num_nodes}, resources=[{res}])'
