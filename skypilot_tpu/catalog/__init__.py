"""TPU/GCE service catalog: slice availability, pricing, perf facts.

Counterpart of the reference's ``sky/clouds/service_catalog`` (lazy pandas
CSVs with TTL refresh, sky/clouds/service_catalog/common.py:130-238; GCP TPU
pseudo-instance handling, gcp_catalog.py:232-254). TPU-native changes:

- The row unit is a *slice in a zone*, not an instance type: price, chips,
  hosts, and ICI topology are columns, so the optimizer can rank by
  **perf/$ per chip** directly (chips * gen TFLOPs / price).
- Catalogs are baked into the wheel (no hosted fetch in the offline build);
  ``fetchers/fetch_gcp.py`` regenerates them.
"""
from __future__ import annotations

import os
from typing import Dict, List, Optional, Tuple

import pandas as pd

from skypilot_tpu import accelerators as accel_lib
from skypilot_tpu import exceptions

_DATA_DIR = os.path.join(os.path.dirname(os.path.abspath(__file__)), 'data')


_read_cache: Dict[str, pd.DataFrame] = {}


def _read(name: str) -> pd.DataFrame:
    """Load a catalog CSV, caching only successful reads: the empty
    fallback for a missing file is NOT cached, so a catalog regenerated
    later in the same process (e.g. via a fetcher) is picked up."""
    cached = _read_cache.get(name)
    if cached is not None:
        return cached
    path = os.path.join(_DATA_DIR, name)
    if not os.path.exists(path) and name.startswith('gcp_'):
        # Regenerate on first use (e.g. fresh checkout). Only the GCP
        # fetcher exists; other catalogs ship as committed CSVs.
        from skypilot_tpu.catalog.fetchers import fetch_gcp
        fetch_gcp.refresh()
    if not os.path.exists(path):
        return pd.DataFrame(columns=[
            'instance_type', 'vcpus', 'memory_gb', 'region', 'price',
            'spot_price'])
    df = pd.read_csv(path)
    _read_cache[name] = df
    return df


_read.cache_clear = _read_cache.clear  # type: ignore[attr-defined]


def refresh(online: bool = True) -> str:
    """Re-fetch prices (Billing API when reachable) and reload the CSVs."""
    from skypilot_tpu.catalog.fetchers import fetch_gcp
    source = fetch_gcp.refresh(online=online)
    _read.cache_clear()
    return source


def _tpus() -> pd.DataFrame:
    return _read('gcp_tpus.csv')


def _vms(cloud: str = 'gcp') -> pd.DataFrame:
    return _read(f'{cloud}_vms.csv')


# ---- TPU slice queries -----------------------------------------------------
def get_slice_zones(slice_: accel_lib.TpuSlice,
                    region: Optional[str] = None) -> List[str]:
    df = _tpus()
    df = df[df['slice'] == slice_.name]
    if region is not None:
        df = df[df['region'] == region]
    return sorted(df['zone'].unique())


def get_slice_regions(slice_: accel_lib.TpuSlice) -> List[str]:
    df = _tpus()
    return sorted(df[df['slice'] == slice_.name]['region'].unique())


def get_slice_hourly_cost(slice_: accel_lib.TpuSlice, use_spot: bool,
                          region: Optional[str] = None,
                          zone: Optional[str] = None) -> float:
    df = _tpus()
    df = df[df['slice'] == slice_.name]
    if zone is not None:
        df = df[df['zone'] == zone]
    elif region is not None:
        df = df[df['region'] == region]
    if df.empty:
        where = zone or region or 'any region'
        raise exceptions.ResourcesUnavailableError(
            f'{slice_.name} is not available in {where}.')
    col = 'spot_price' if use_spot else 'price'
    return float(df[col].min())


def list_tpu_slices(
        generation: Optional[str] = None,
        region: Optional[str] = None) -> pd.DataFrame:
    """One row per (slice, zone): used by `skytpu show-tpus`."""
    df = _tpus()
    if generation is not None:
        df = df[df['generation'] == generation]
    if region is not None:
        df = df[df['region'] == region]
    return df.reset_index(drop=True)


def perf_per_dollar(slice_: accel_lib.TpuSlice, use_spot: bool,
                    region: Optional[str] = None) -> float:
    """bf16 TFLOPs per $/hour — the TPU-native ranking metric."""
    cost = get_slice_hourly_cost(slice_, use_spot, region=region)
    if cost <= 0:
        return float('inf')
    return slice_.total_bf16_tflops / cost


# ---- VM queries (per cloud: gcp_vms.csv / aws_vms.csv) ---------------------
def get_instance_hourly_cost(instance_type: str, use_spot: bool,
                             region: Optional[str] = None,
                             cloud: str = 'gcp') -> float:
    df = _vms(cloud)
    df = df[df['instance_type'] == instance_type]
    if region is not None:
        df = df[df['region'] == region]
    if df.empty:
        raise exceptions.ResourcesUnavailableError(
            f'Instance type {instance_type} not found'
            f'{" in " + region if region else ""}.')
    col = 'spot_price' if use_spot else 'price'
    return float(df[col].min())


def get_instance_info(instance_type: str,
                      cloud: str = 'gcp') -> Tuple[int, float]:
    """(vcpus, memory_gb) for an instance type."""
    df = _vms(cloud)
    df = df[df['instance_type'] == instance_type]
    if df.empty:
        raise exceptions.ResourcesUnavailableError(
            f'Unknown instance type {instance_type}.')
    row = df.iloc[0]
    return int(row['vcpus']), float(row['memory_gb'])


def get_default_instance_type(cpus: Optional[float] = None,
                              cpus_plus: bool = True,
                              memory: Optional[float] = None,
                              memory_plus: bool = True,
                              region: Optional[str] = None,
                              cloud: str = 'gcp') -> Optional[str]:
    """Cheapest instance satisfying the cpu/memory constraints."""
    df = _vms(cloud)
    if region is not None:
        df = df[df['region'] == region]
    if cpus is None and memory is None:
        cpus, cpus_plus = 4, True  # sensible default, ref uses 4 vCPU too
    if cpus is not None:
        df = df[df['vcpus'] >= cpus] if cpus_plus else df[df['vcpus'] == cpus]
    if memory is not None:
        df = (df[df['memory_gb'] >= memory]
              if memory_plus else df[df['memory_gb'] == memory])
    if df.empty:
        return None
    # Cheapest (then smallest) first.
    df = df.sort_values(['price', 'vcpus'])
    return str(df.iloc[0]['instance_type'])


def get_vm_regions(instance_type: str, cloud: str = 'gcp') -> List[str]:
    df = _vms(cloud)
    return sorted(df[df['instance_type'] == instance_type]['region'].unique())


def get_tpu_host_shape(generation: str) -> Tuple[int, float]:
    """(vcpus, memory_gb) on each TPU-VM host of a generation."""
    from skypilot_tpu.catalog.fetchers import fetch_gcp
    return fetch_gcp.TPU_HOST_SHAPES[generation]


def validate_region_zone(
        region: Optional[str],
        zone: Optional[str]) -> None:
    """Cheap sanity check that a region/zone exists in the catalog."""
    if region is None and zone is None:
        return
    tpus, vms = _tpus(), _vms()
    regions = set(tpus['region']).union(vms['region'])
    # Only AWS/Azure need their region sets later (zone-suffix rules);
    # every other cloud just contributes its regions to the known set.
    aws_regions = set(_vms('aws')['region'].unique())
    regions.update(aws_regions)
    azure_regions = set(_vms('azure')['region'].unique())
    regions.update(azure_regions)
    for cloud_name in ('lambda', 'do', 'fluidstack', 'vast', 'runpod',
                       'paperspace', 'hyperstack', 'oci', 'cudo'):
        regions.update(_vms(cloud_name)['region'].unique())
    zones = set(tpus['zone'])
    # AWS AZs: region + single-letter suffix; regions carry up to six
    # (us-east-1a..f), so accept any letter on a known region.
    zones.update(f'{r}{s}' for r in aws_regions for s in 'abcdef')
    # Azure AZs are bare digits within a region ('1'/'2'/'3').
    zones.update('123')
    # OCI availability domains: '{region}-AD-{n}'.
    oci_regions = set(_vms('oci')['region'].unique())
    zones.update(f'{r}-AD-{i}' for r in oci_regions for i in (1, 2, 3))
    if zone is not None and zone not in zones:
        # GCE zones are region+suffix; accept unknown-but-wellformed.
        if zone.rsplit('-', 1)[0] not in regions:
            raise exceptions.InvalidResourcesError(
                f'Unknown zone {zone!r} (known TPU zones: {sorted(zones)})')
    if region is not None:
        if region not in regions:
            raise exceptions.InvalidResourcesError(
                f'Unknown region {region!r} (known: {sorted(regions)})')
        if zone is not None and zone in ('1', '2', '3'):
            if region not in azure_regions:
                raise exceptions.InvalidResourcesError(
                    f'Zone {zone!r} is an Azure AZ digit but {region!r} '
                    'is not an Azure region')
        elif zone is not None and zone.rsplit('-', 1)[0] != region \
                and not (zone.startswith(region)
                         and len(zone) == len(region) + 1) \
                and not zone.startswith(f'{region}-AD-'):
            # GCP: region-suffix (us-central1-a); AWS: region+letter
            # (us-east-1a); OCI: region-AD-n.
            raise exceptions.InvalidResourcesError(
                f'Zone {zone!r} is not in region {region!r}')
