"""Generate the GCP TPU + GCE catalog CSVs.

Counterpart of the reference's catalog data fetchers
(sky/clouds/service_catalog/data_fetchers/fetch_gcp.py:34-66, which scrapes
the GCP pricing SKU API and gap-fills TPU zones by hand). In production this
module would hit ``cloudbilling.googleapis.com``; offline it regenerates the
baked-in CSVs from the static tables below, which mirror public on-demand
per-chip-hour pricing and published TPU zone availability.

Run:  python -m skypilot_tpu.catalog.fetchers.fetch_gcp
"""
from __future__ import annotations

import csv
import os
from typing import Dict, List, Tuple

from skypilot_tpu import accelerators as accel_lib

_HERE = os.path.dirname(os.path.abspath(__file__))
DATA_DIR = os.path.join(_HERE, '..', 'data')

# Public on-demand $/chip-hour in US regions; spot is the public preemptible
# discount (~0.35-0.45x depending on generation).
_TPU_PRICE_PER_CHIP_HOUR: Dict[str, Tuple[float, float]] = {
    'v2': (1.125, 0.45),
    'v3': (2.00, 0.80),
    'v4': (3.22, 1.13),
    'v5e': (1.20, 0.42),
    'v5p': (4.20, 1.47),
    'v6e': (2.70, 0.945),
}

# Zone availability per generation (published TPU region/zone matrix; the
# reference hand-maintains the same kind of table, fetch_gcp.py:34-66).
_TPU_ZONES: Dict[str, List[str]] = {
    'v2': ['us-central1-b', 'us-central1-c', 'europe-west4-a',
           'asia-east1-c'],
    'v3': ['europe-west4-a', 'us-central1-a'],
    'v4': ['us-central2-b'],
    'v5e': ['us-central1-a', 'us-west4-a', 'us-east1-c', 'us-east5-b',
            'europe-west4-b', 'asia-southeast1-b'],
    'v5p': ['us-east5-a', 'us-central2-b', 'europe-west4-b'],
    'v6e': ['us-east5-b', 'us-east1-d', 'us-central2-b', 'europe-west4-a',
            'asia-northeast1-b'],
}

# Regional price multiplier vs US.
_REGION_MULTIPLIER = [('europe-', 1.08), ('asia-', 1.10)]

# GCE shapes for CPU tasks and controllers: (vcpus, memory_gb, $/h US).
_GCE_INSTANCES: Dict[str, Tuple[int, float, float]] = {
    'n2-standard-2': (2, 8, 0.0971),
    'n2-standard-4': (4, 16, 0.1942),
    'n2-standard-8': (8, 32, 0.3885),
    'n2-standard-16': (16, 64, 0.7769),
    'n2-standard-32': (32, 128, 1.5539),
    'n2-highmem-8': (8, 64, 0.5241),
    'n2-highmem-16': (16, 128, 1.0482),
    'e2-standard-2': (2, 8, 0.0670),
    'e2-standard-4': (4, 16, 0.1341),
    'e2-standard-8': (8, 32, 0.2681),
}
_GCE_SPOT_FACTOR = 0.30

# TPU-VM host shapes: CPU/RAM available on each TPU host for the user's
# processes (reference forces the analogous shapes, sky/clouds/gcp.py:614-665).
TPU_HOST_SHAPES: Dict[str, Tuple[int, float]] = {
    'v2': (96, 334.0),
    'v3': (96, 334.0),
    'v4': (240, 400.0),
    'v5e': (112, 192.0),
    'v5p': (208, 448.0),
    'v6e': (180, 720.0),
}


def _region_of(zone: str) -> str:
    return zone.rsplit('-', 1)[0]


def _multiplier(region: str) -> float:
    for prefix, mult in _REGION_MULTIPLIER:
        if region.startswith(prefix):
            return mult
    return 1.0


def generate_tpu_rows() -> List[Dict[str, object]]:
    rows: List[Dict[str, object]] = []
    for name in accel_lib.list_slice_names():
        s = accel_lib.TpuSlice.from_name(name)
        base, base_spot = _TPU_PRICE_PER_CHIP_HOUR[s.generation]
        for zone in _TPU_ZONES[s.generation]:
            region = _region_of(zone)
            mult = _multiplier(region)
            rows.append({
                'slice': s.name,
                'generation': s.generation,
                'chips': s.chips,
                'num_hosts': s.num_hosts,
                'topology': s.topology_str,
                'region': region,
                'zone': zone,
                'price': round(base * s.chips * mult, 4),
                'spot_price': round(base_spot * s.chips * mult, 4),
            })
    return rows


def generate_vm_rows() -> List[Dict[str, object]]:
    regions = sorted({_region_of(z)
                      for zones in _TPU_ZONES.values()
                      for z in zones} | {'us-central1'})
    rows = []
    for itype, (vcpus, mem, price) in _GCE_INSTANCES.items():
        for region in regions:
            mult = _multiplier(region)
            rows.append({
                'instance_type': itype,
                'vcpus': vcpus,
                'memory_gb': mem,
                'region': region,
                'price': round(price * mult, 4),
                'spot_price': round(price * _GCE_SPOT_FACTOR * mult, 4),
            })
    return rows


def write_csv(path: str, rows: List[Dict[str, object]]) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', newline='', encoding='utf-8') as f:
        writer = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
        writer.writeheader()
        writer.writerows(rows)


def main() -> None:
    tpu_rows = generate_tpu_rows()
    vm_rows = generate_vm_rows()
    write_csv(os.path.join(DATA_DIR, 'gcp_tpus.csv'), tpu_rows)
    write_csv(os.path.join(DATA_DIR, 'gcp_vms.csv'), vm_rows)
    print(f'Wrote {len(tpu_rows)} TPU rows, {len(vm_rows)} VM rows '
          f'to {os.path.normpath(DATA_DIR)}')


if __name__ == '__main__':
    main()
