"""Generate the GCP TPU + GCE catalog CSVs.

Counterpart of the reference's catalog data fetchers
(sky/clouds/service_catalog/data_fetchers/fetch_gcp.py:34-66, which scrapes
the GCP pricing SKU API and gap-fills TPU zones by hand). Two price
sources, merged:

1. **Cloud Billing Catalog API** (``cloudbilling.googleapis.com/v1``):
   ``refresh(online=True)`` walks services -> Cloud TPU SKUs, parses
   per-chip-hour on-demand/preemptible unit prices per region from SKU
   descriptions, and overrides the static table wherever a live price was
   found. Reuses the TPU provisioner's retrying transport
   (provision/gcp_api.py), so tests fake the billing API the same way they
   fake the TPU API.
2. **Static tables** below (public on-demand per-chip-hour pricing and
   published TPU zone availability): the offline fallback — this build
   environment has zero egress, and the reference likewise hand-gap-fills
   zones its SKU scrape misses.

Run:  python -m skypilot_tpu.catalog.fetchers.fetch_gcp [--online]
      skytpu show-tpus --refresh
"""
from __future__ import annotations

import csv
import os
import re
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import accelerators as accel_lib

_HERE = os.path.dirname(os.path.abspath(__file__))
DATA_DIR = os.path.join(_HERE, '..', 'data')

BILLING_BASE = 'https://cloudbilling.googleapis.com/v1'

# Public on-demand $/chip-hour in US regions; spot is the public preemptible
# discount (~0.35-0.45x depending on generation).
_TPU_PRICE_PER_CHIP_HOUR: Dict[str, Tuple[float, float]] = {
    'v2': (1.125, 0.45),
    'v3': (2.00, 0.80),
    'v4': (3.22, 1.13),
    'v5e': (1.20, 0.42),
    'v5p': (4.20, 1.47),
    'v6e': (2.70, 0.945),
}

# Zone availability per generation (published TPU region/zone matrix; the
# reference hand-maintains the same kind of table, fetch_gcp.py:34-66).
_TPU_ZONES: Dict[str, List[str]] = {
    'v2': ['us-central1-b', 'us-central1-c', 'europe-west4-a',
           'asia-east1-c'],
    'v3': ['europe-west4-a', 'us-central1-a'],
    'v4': ['us-central2-b'],
    'v5e': ['us-central1-a', 'us-west4-a', 'us-east1-c', 'us-east5-b',
            'europe-west4-b', 'asia-southeast1-b'],
    'v5p': ['us-east5-a', 'us-central2-b', 'europe-west4-b'],
    'v6e': ['us-east5-b', 'us-east1-d', 'us-central2-b', 'europe-west4-a',
            'asia-northeast1-b'],
}

# Regional price multiplier vs US.
_REGION_MULTIPLIER = [('europe-', 1.08), ('asia-', 1.10)]

# GCE shapes for CPU tasks and controllers: (vcpus, memory_gb, $/h US).
_GCE_INSTANCES: Dict[str, Tuple[int, float, float]] = {
    'n2-standard-2': (2, 8, 0.0971),
    'n2-standard-4': (4, 16, 0.1942),
    'n2-standard-8': (8, 32, 0.3885),
    'n2-standard-16': (16, 64, 0.7769),
    'n2-standard-32': (32, 128, 1.5539),
    'n2-highmem-8': (8, 64, 0.5241),
    'n2-highmem-16': (16, 128, 1.0482),
    'e2-standard-2': (2, 8, 0.0670),
    'e2-standard-4': (4, 16, 0.1341),
    'e2-standard-8': (8, 32, 0.2681),
}
_GCE_SPOT_FACTOR = 0.30

# TPU-VM host shapes: CPU/RAM available on each TPU host for the user's
# processes (reference forces the analogous shapes, sky/clouds/gcp.py:614-665).
TPU_HOST_SHAPES: Dict[str, Tuple[int, float]] = {
    'v2': (96, 334.0),
    'v3': (96, 334.0),
    'v4': (240, 400.0),
    'v5e': (112, 192.0),
    'v5p': (208, 448.0),
    'v6e': (180, 720.0),
}


# ---- Cloud Billing Catalog API fetch ---------------------------------------
# SKU descriptions name generations inconsistently ("Tpu-v4", "Cloud TPU
# v5e", "TPU v5 Lite", "Trillium"); normalize to our generation keys.
_GEN_IN_DESCRIPTION = [
    (re.compile(r'v5\s*lite|v5e', re.I), 'v5e'),
    (re.compile(r'v5p', re.I), 'v5p'),
    (re.compile(r'v6e|trillium', re.I), 'v6e'),
    (re.compile(r'\bv2\b', re.I), 'v2'),
    (re.compile(r'\bv3\b', re.I), 'v3'),
    (re.compile(r'\bv4\b', re.I), 'v4'),
]


class BillingClient:
    """Paginated reader for the Cloud Billing Catalog API.

    Goes through ``provision.gcp_api``'s transport: retries/backoff for
    free, and the tests' fake-transport seam covers this client too.
    """

    def __init__(self, transport: Optional[Any] = None):
        if transport is None:
            from skypilot_tpu.provision import gcp_api
            transport = gcp_api.get_transport()
        self._transport = transport

    def _paginate(self, url: str, key: str,
                  params: Optional[Dict[str, Any]] = None
                  ) -> List[Dict[str, Any]]:
        items: List[Dict[str, Any]] = []
        params = dict(params or {})
        while True:
            resp = self._transport.request('GET', url, params=params)
            items.extend(resp.get(key, []))
            token = resp.get('nextPageToken')
            if not token:
                return items
            params['pageToken'] = token

    def find_service(self, display_name: str) -> Optional[str]:
        """'Cloud TPU' -> 'services/E000-...' (resource name)."""
        for svc in self._paginate(f'{BILLING_BASE}/services', 'services'):
            if svc.get('displayName') == display_name:
                return svc['name']
        return None

    def list_skus(self, service_name: str) -> List[Dict[str, Any]]:
        return self._paginate(f'{BILLING_BASE}/{service_name}/skus',
                              'skus', params={'currencyCode': 'USD'})


def _unit_price(sku: Dict[str, Any]) -> Optional[float]:
    """$/usage-unit from the SKU's first pricing tier (units + nanos)."""
    try:
        expr = sku['pricingInfo'][0]['pricingExpression']
        rate = expr['tieredRates'][-1]['unitPrice']
        return int(rate.get('units', 0) or 0) + rate.get('nanos', 0) / 1e9
    except (KeyError, IndexError, TypeError, ValueError):
        return None


def parse_tpu_sku_prices(skus: List[Dict[str, Any]]
                         ) -> Dict[Tuple[str, str], Dict[str, float]]:
    """SKUs -> {(generation, region): {'OnDemand': $, 'Preemptible': $}}.

    Only per-chip-hour compute SKUs count (usage unit hour); commitment /
    storage / network SKUs are skipped (the reference's scraper filters the
    same way, reference fetch_gcp.py TPU SKU handling).
    """
    prices: Dict[Tuple[str, str], Dict[str, float]] = {}
    for sku in skus:
        desc = sku.get('description', '')
        category = sku.get('category', {})
        usage_type = category.get('usageType', '')
        if usage_type not in ('OnDemand', 'Preemptible'):
            continue  # Commit1Yr etc.
        gen = None
        for pattern, g in _GEN_IN_DESCRIPTION:
            if pattern.search(desc):
                gen = g
                break
        if gen is None:
            continue
        try:
            unit = (sku['pricingInfo'][0]['pricingExpression']
                    .get('usageUnit', ''))
        except (KeyError, IndexError):
            continue
        if unit not in ('h', 'hr', 'hour'):
            continue
        price = _unit_price(sku)
        if price is None or price <= 0:
            continue
        for region in sku.get('serviceRegions', []):
            entry = prices.setdefault((gen, region), {})
            # Pods and single-host SKUs share per-chip pricing; keep the
            # cheapest seen (some regions list legacy higher-priced SKUs).
            if usage_type not in entry or price < entry[usage_type]:
                entry[usage_type] = price
    return prices


def fetch_tpu_prices(transport: Optional[Any] = None
                     ) -> Dict[Tuple[str, str], Dict[str, float]]:
    """Live per-chip-hour prices keyed by (generation, region)."""
    client = BillingClient(transport)
    service = client.find_service('Cloud TPU')
    if service is None:
        return {}
    return parse_tpu_sku_prices(client.list_skus(service))


def _region_of(zone: str) -> str:
    return zone.rsplit('-', 1)[0]


def _multiplier(region: str) -> float:
    for prefix, mult in _REGION_MULTIPLIER:
        if region.startswith(prefix):
            return mult
    return 1.0


def generate_tpu_rows(
    live_prices: Optional[Dict[Tuple[str, str], Dict[str, float]]] = None
) -> List[Dict[str, object]]:
    """One row per (slice, zone). ``live_prices`` (from the Billing API)
    overrides the static per-chip-hour table wherever present."""
    live_prices = live_prices or {}
    rows: List[Dict[str, object]] = []
    for name in accel_lib.list_slice_names():
        s = accel_lib.TpuSlice.from_name(name)
        base, base_spot = _TPU_PRICE_PER_CHIP_HOUR[s.generation]
        for zone in _TPU_ZONES[s.generation]:
            region = _region_of(zone)
            mult = _multiplier(region)
            per_chip = base * mult
            per_chip_spot = base_spot * mult
            live = live_prices.get((s.generation, region))
            if live:
                per_chip = live.get('OnDemand', per_chip)
                per_chip_spot = live.get('Preemptible', per_chip_spot)
            rows.append({
                'slice': s.name,
                'generation': s.generation,
                'chips': s.chips,
                'num_hosts': s.num_hosts,
                'topology': s.topology_str,
                'region': region,
                'zone': zone,
                'price': round(per_chip * s.chips, 4),
                'spot_price': round(per_chip_spot * s.chips, 4),
            })
    return rows


def generate_vm_rows() -> List[Dict[str, object]]:
    regions = sorted({_region_of(z)
                      for zones in _TPU_ZONES.values()
                      for z in zones} | {'us-central1'})
    rows = []
    for itype, (vcpus, mem, price) in _GCE_INSTANCES.items():
        for region in regions:
            mult = _multiplier(region)
            rows.append({
                'instance_type': itype,
                'vcpus': vcpus,
                'memory_gb': mem,
                'region': region,
                'price': round(price * mult, 4),
                'spot_price': round(price * _GCE_SPOT_FACTOR * mult, 4),
            })
    return rows


def write_csv(path: str, rows: List[Dict[str, object]]) -> None:
    os.makedirs(os.path.dirname(path), exist_ok=True)
    with open(path, 'w', newline='', encoding='utf-8') as f:
        writer = csv.DictWriter(f, fieldnames=list(rows[0].keys()))
        writer.writeheader()
        writer.writerows(rows)


def refresh(online: bool = False,
            transport: Optional[Any] = None) -> str:
    """Regenerate both CSVs; returns 'online' or 'offline' (what happened).

    ``online=True`` tries the Billing Catalog API first and silently falls
    back to the static tables when unreachable (no credentials, no egress)
    — cost optimization keeps working either way, the reference behaves the
    same when its hosted catalog is stale.
    """
    live_prices: Dict[Tuple[str, str], Dict[str, float]] = {}
    source = 'offline'
    if online:
        try:
            live_prices = fetch_tpu_prices(transport)
            if live_prices:
                source = 'online'
        except Exception as e:  # noqa: BLE001 — any failure means fallback
            print(f'billing API unavailable ({type(e).__name__}: {e}); '
                  'using static price tables')
    tpu_rows = generate_tpu_rows(live_prices)
    vm_rows = generate_vm_rows()
    try:
        write_csv(os.path.join(DATA_DIR, 'gcp_tpus.csv'), tpu_rows)
        write_csv(os.path.join(DATA_DIR, 'gcp_vms.csv'), vm_rows)
    except OSError as e:
        # Read-only install (e.g. root-owned site-packages): keep serving
        # the existing CSVs rather than crashing the CLI.
        print(f'catalog dir not writable ({e}); keeping existing CSVs')
        return 'stale'
    print(f'Wrote {len(tpu_rows)} TPU rows, {len(vm_rows)} VM rows '
          f'to {os.path.normpath(DATA_DIR)} '
          f'({source}; {len(live_prices)} live price points)')
    return source


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument('--online', action='store_true',
                        help='fetch live prices from the Billing API')
    args = parser.parse_args(argv)
    refresh(online=args.online)


if __name__ == '__main__':
    main()
