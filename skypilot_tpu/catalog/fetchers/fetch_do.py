"""Generate the DigitalOcean catalog CSV (do_vms.csv).

Counterpart of the reference's DO catalog (sky/catalog fetch for DO —
walks the authenticated ``/v2/sizes`` endpoint). Two sources, merged:

1. **DO sizes API** (``GET /v2/sizes`` — needs an API token):
   ``refresh(online=True)`` pulls live ``price_hourly`` + specs +
   per-size region availability. A ``sizes_fetcher`` seam lets tests
   fake the API without network.
2. **Static table** below (public pricing; DO has NO spot market, so
   ``spot_price`` mirrors ``price``): the offline fallback — this build
   environment has zero egress.

Run:  python -m skypilot_tpu.catalog.fetchers.fetch_do [--online]
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
DATA_DIR = os.path.join(_HERE, '..', 'data')

_REGIONS = ('nyc1', 'nyc3', 'sfo3', 'ams3', 'lon1', 'fra1', 'sgp1')

# (vcpus, memory_gb, $/h). Public DO pricing: s- basic, c- cpu-optimized,
# g- general purpose, m- memory-optimized.
_SIZES: Dict[str, Tuple[int, float, float]] = {
    's-1vcpu-2gb': (1, 2, 0.018),
    's-2vcpu-4gb': (2, 4, 0.036),
    's-4vcpu-8gb': (4, 8, 0.071),
    's-8vcpu-16gb': (8, 16, 0.143),
    'c-4': (4, 8, 0.125),
    'c-8': (8, 16, 0.25),
    'g-2vcpu-8gb': (2, 8, 0.094),
    'g-8vcpu-32gb': (8, 32, 0.376),
    'm-2vcpu-16gb': (2, 16, 0.125),
    'm-8vcpu-64gb': (8, 64, 0.499),
}


def fetch_sizes(
        sizes_fetcher: Optional[Callable[[], List[Dict[str, Any]]]] = None
) -> List[Dict[str, Any]]:
    """Live /v2/sizes payload: [{slug, vcpus, memory (MB), price_hourly,
    regions, available}]. ``sizes_fetcher`` is the test seam."""
    if sizes_fetcher is not None:
        return sizes_fetcher()
    from skypilot_tpu.provision import do_api
    client = do_api.get_client()
    return list(client._request('GET', '/sizes?per_page=200')  # pylint: disable=protected-access
                .get('sizes', []))


def generate_vm_rows(live: Optional[List[Dict[str, Any]]] = None
                     ) -> List[Dict[str, object]]:
    rows: List[Dict[str, object]] = []
    if live:
        for size in sorted(live, key=lambda s: s.get('slug', '')):
            slug = size.get('slug')
            if not slug or not size.get('available', True):
                continue
            price = float(size.get('price_hourly') or 0)
            for region in size.get('regions') or []:
                rows.append({
                    'instance_type': slug,
                    'vcpus': int(size.get('vcpus') or 0),
                    'memory_gb': float(size.get('memory') or 0) / 1024.0,
                    'region': region,
                    'price': round(price, 5),
                    'spot_price': round(price, 5),
                })
        if rows:
            return rows
    for slug, (vcpus, mem, price) in _SIZES.items():
        for region in _REGIONS:
            rows.append({
                'instance_type': slug,
                'vcpus': vcpus,
                'memory_gb': mem,
                'region': region,
                'price': price,
                'spot_price': price,
            })
    return rows


def refresh(online: bool = False,
            sizes_fetcher: Optional[Callable[[], List[Dict[str, Any]]]] = None
            ) -> str:
    """Regenerate do_vms.csv; returns 'online'/'offline'/'stale'."""
    live: List[Dict[str, Any]] = []
    source = 'offline'
    if online:
        try:
            live = fetch_sizes(sizes_fetcher)
            if live:
                source = 'online'
        except Exception as e:  # noqa: BLE001 — any failure = fallback
            print(f'sizes API unavailable ({type(e).__name__}: {e}); '
                  'using static price table')
    from skypilot_tpu.catalog.fetchers.fetch_gcp import write_csv
    rows = generate_vm_rows(live)
    try:
        write_csv(os.path.join(DATA_DIR, 'do_vms.csv'), rows)
    except OSError as e:
        print(f'catalog dir not writable ({e}); keeping existing CSV')
        return 'stale'
    print(f'Wrote {len(rows)} DO droplet rows to '
          f'{os.path.normpath(DATA_DIR)} ({source})')
    return source


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument('--online', action='store_true',
                        help='fetch live sizes/prices from /v2/sizes')
    args = parser.parse_args(argv)
    refresh(online=args.online)


if __name__ == '__main__':
    main()
