"""Generate the Hyperstack catalog CSV (hyperstack_vms.csv).

Static table of flavors (public pricing; no spot, so ``spot_price``
mirrors ``price``) with a ``flavors_fetcher`` seam for a live
``/core/flavors`` override.

Run:  python -m skypilot_tpu.catalog.fetchers.fetch_hyperstack [--online]
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
DATA_DIR = os.path.join(_HERE, '..', 'data')

_REGIONS = ('CANADA-1', 'NORWAY-1')

# flavor -> (vcpus, memory_gb, $/h)
_FLAVORS: Dict[str, Tuple[int, float, float]] = {
    'n3-RTX-A6000x1': (28, 58, 0.50),
    'n3-RTX-A6000x2': (56, 116, 1.00),
    'n3-A100x1': (28, 120, 1.35),
    'n3-A100x4': (112, 480, 5.40),
    'n3-A100x8': (224, 960, 10.80),
    'n3-H100x1': (28, 180, 1.90),
    'n3-H100x4': (112, 720, 7.60),
    'n3-H100x8': (224, 1440, 15.20),
}


def fetch_flavors(
        flavors_fetcher: Optional[Callable[[], List[Dict[str, Any]]]] = None
) -> List[Dict[str, Any]]:
    """Live flavors payload: [{name, cpu, ram, regions? , price?}].
    ``flavors_fetcher`` is the test seam."""
    if flavors_fetcher is not None:
        return flavors_fetcher()
    from skypilot_tpu.provision import hyperstack_api
    client = hyperstack_api.get_client()
    body = client._request('GET', '/core/flavors')  # pylint: disable=protected-access
    out: List[Dict[str, Any]] = []
    for group in body.get('data') or []:
        out.extend(group.get('flavors') or [])
    return out


def generate_vm_rows(live: Optional[List[Dict[str, Any]]] = None
                     ) -> List[Dict[str, object]]:
    rows: List[Dict[str, object]] = []
    if live:
        live = [f for f in live if f.get('name')]
        for f in sorted(live, key=lambda f: f['name']):
            price = float(f.get('price') or
                          (f.get('pricing') or {}).get('price') or 0)
            if price <= 0:
                # Keep the static price when the payload omits it.
                if f['name'] in _FLAVORS:
                    price = _FLAVORS[f['name']][2]
                else:
                    continue
            for region in f.get('regions') or _REGIONS:
                rows.append({
                    'instance_type': f['name'],
                    'vcpus': int(f.get('cpu') or 0),
                    'memory_gb': float(f.get('ram') or 0),
                    'region': region,
                    'price': round(price, 4),
                    'spot_price': round(price, 4),
                })
        if rows:
            return rows
    for name, (vcpus, mem, price) in _FLAVORS.items():
        for region in _REGIONS:
            rows.append({
                'instance_type': name,
                'vcpus': vcpus,
                'memory_gb': mem,
                'region': region,
                'price': price,
                'spot_price': price,
            })
    return rows


def refresh(online: bool = False,
            flavors_fetcher: Optional[Callable[[], List[Dict[str, Any]]]] = None
            ) -> str:
    """Regenerate hyperstack_vms.csv; returns 'online'/'offline'/'stale'."""
    live: List[Dict[str, Any]] = []
    source = 'offline'
    if online:
        try:
            live = fetch_flavors(flavors_fetcher)
            if live:
                source = 'online'
        except Exception as e:  # noqa: BLE001 — any failure = fallback
            print(f'flavors API unavailable ({type(e).__name__}: {e}); '
                  'using static price table')
    from skypilot_tpu.catalog.fetchers.fetch_gcp import write_csv
    rows = generate_vm_rows(live)
    try:
        write_csv(os.path.join(DATA_DIR, 'hyperstack_vms.csv'), rows)
    except OSError as e:
        print(f'catalog dir not writable ({e}); keeping existing CSV')
        return 'stale'
    print(f'Wrote {len(rows)} Hyperstack flavor rows to '
          f'{os.path.normpath(DATA_DIR)} ({source})')
    return source


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument('--online', action='store_true',
                        help='fetch live flavors from the API')
    args = parser.parse_args(argv)
    refresh(online=args.online)


if __name__ == '__main__':
    main()
