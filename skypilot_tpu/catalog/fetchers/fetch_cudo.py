"""Generate the Cudo Compute catalog CSV (cudo_vms.csv).

Static table of machine families at concrete sizing points (Cudo
prices per-vCPU/per-GB; each row is the priced point the provisioner
launches with) with a ``types_fetcher`` seam for a live override.

Run:  python -m skypilot_tpu.catalog.fetchers.fetch_cudo [--online]
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
DATA_DIR = os.path.join(_HERE, '..', 'data')

_REGIONS = ('gb-bournemouth', 'se-smedjebacken-1', 'us-santaclara-1')

# family -> (vcpus, memory_gb, $/h at that sizing point)
_FAMILIES: Dict[str, Tuple[int, float, float]] = {
    'epyc-milan': (4, 16, 0.042),
    'epyc-milan-8': (8, 32, 0.084),
    'epyc-milan-16': (16, 64, 0.168),
    'intel-broadwell': (4, 16, 0.036),
}


def fetch_machine_types(
        types_fetcher: Optional[Callable[[], List[Dict[str, Any]]]] = None
) -> List[Dict[str, Any]]:
    """Live machine-types payload; ``types_fetcher`` is the test seam."""
    if types_fetcher is not None:
        return types_fetcher()
    return []


def generate_vm_rows(live: Optional[List[Dict[str, Any]]] = None
                     ) -> List[Dict[str, object]]:
    rows: List[Dict[str, object]] = []
    if live:
        live = [t for t in live if t.get('machineType')]
        for t in sorted(live, key=lambda t: t['machineType']):
            price = float(t.get('price') or 0)
            if price <= 0:
                continue
            for region in t.get('dataCenters') or _REGIONS:
                rows.append({
                    'instance_type': t['machineType'],
                    'vcpus': int(t.get('vcpus') or 0),
                    'memory_gb': float(t.get('memory_gb') or 0),
                    'region': region,
                    'price': round(price, 4),
                    'spot_price': round(price, 4),
                })
        if rows:
            return rows
    for family, (vcpus, mem, price) in _FAMILIES.items():
        for region in _REGIONS:
            rows.append({
                'instance_type': family,
                'vcpus': vcpus,
                'memory_gb': mem,
                'region': region,
                'price': price,
                'spot_price': price,
            })
    return rows


def refresh(online: bool = False,
            types_fetcher: Optional[Callable[[], List[Dict[str, Any]]]] = None
            ) -> str:
    """Regenerate cudo_vms.csv; returns 'online'/'offline'/'stale'."""
    live: List[Dict[str, Any]] = []
    source = 'offline'
    if online:
        try:
            live = fetch_machine_types(types_fetcher)
            if live:
                source = 'online'
        except Exception as e:  # noqa: BLE001 — any failure = fallback
            print(f'machine-types source unavailable ({type(e).__name__}:'
                  f' {e}); using static price table')
    from skypilot_tpu.catalog.fetchers.fetch_gcp import write_csv
    rows = generate_vm_rows(live)
    if source == 'online' and rows == generate_vm_rows(None):
        # Every live row was discarded (no machineType / no price):
        # the CSV is the static fallback - do not label it online.
        source = 'offline'
    try:
        write_csv(os.path.join(DATA_DIR, 'cudo_vms.csv'), rows)
    except OSError as e:
        print(f'catalog dir not writable ({e}); keeping existing CSV')
        return 'stale'
    print(f'Wrote {len(rows)} Cudo machine rows to '
          f'{os.path.normpath(DATA_DIR)} ({source})')
    return source


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument('--online', action='store_true',
                        help='use a live machine-types source')
    args = parser.parse_args(argv)
    refresh(online=args.online)


if __name__ == '__main__':
    main()
