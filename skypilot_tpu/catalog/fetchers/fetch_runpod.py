"""Generate the RunPod catalog CSV (runpod_vms.csv).

Plans are ``{n}x_{GPU_ID}_{SECURE|COMMUNITY}`` (the reference invents
the same shape). Two sources, merged:

1. **GPU types GraphQL query** (``refresh(online=True)``): pulls live
   per-GPU secure/community prices + specs. A ``types_fetcher`` seam
   lets tests fake the API without network.
2. **Static table** below (public pricing; spot = typical interruptible
   rate ~50%): the offline fallback.

Run:  python -m skypilot_tpu.catalog.fetchers.fetch_runpod [--online]
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
DATA_DIR = os.path.join(_HERE, '..', 'data')

_REGIONS = ('US', 'CA', 'NL', 'SE', 'IS')

# gpu_id -> (vcpus/gpu, mem_gb/gpu, secure $/h/gpu, community $/h/gpu,
#            counts)
_GPUS: Dict[str, Tuple[int, float, float, float, Tuple[int, ...]]] = {
    'NVIDIA_RTX_4090': (8, 48, 0.69, 0.44, (1, 2, 4, 8)),
    'NVIDIA_RTX_A6000': (8, 50, 0.76, 0.49, (1, 2, 4)),
    'NVIDIA_A100_80GB_PCIe': (12, 96, 1.64, 1.19, (1, 2, 4, 8)),
    'NVIDIA_H100_80GB_HBM3': (16, 188, 2.99, 2.39, (1, 2, 4, 8)),
}

_SPOT_FRACTION = 0.5


def fetch_gpu_types(
        types_fetcher: Optional[Callable[[], List[Dict[str, Any]]]] = None
) -> List[Dict[str, Any]]:
    """Live gpuTypes payload: [{id, securePrice, communityPrice,
    memoryInGb, maxGpuCount}]. ``types_fetcher`` is the test seam."""
    if types_fetcher is not None:
        return types_fetcher()
    from skypilot_tpu.provision import runpod_api
    client = runpod_api.get_client()
    data = client._gql(  # pylint: disable=protected-access
        'query { gpuTypes { id securePrice communityPrice memoryInGb '
        'maxGpuCount } }')
    return list(data.get('gpuTypes') or [])


def generate_vm_rows(live: Optional[List[Dict[str, Any]]] = None
                     ) -> List[Dict[str, object]]:
    rows: List[Dict[str, object]] = []
    if live:
        for g in sorted(live, key=lambda g: g.get('id', '')):
            gid = (g.get('id') or '').replace(' ', '_')
            if not gid:
                continue
            mem = float(g.get('memoryInGb') or 48)
            # vcpus/GPU isn't in the gpuTypes payload: keep the static
            # table's per-GPU value for known ids so an online refresh
            # never rewrites cpu filters (H100 is 16/gpu, not 8).
            vcpus_per_gpu = _GPUS.get(gid, (8,))[0]
            counts = tuple(range(1, int(g.get('maxGpuCount') or 1) + 1))
            for cloud_type, price_key in (('SECURE', 'securePrice'),
                                          ('COMMUNITY', 'communityPrice')):
                price = float(g.get(price_key) or 0)
                if price <= 0:
                    continue
                for n in counts:
                    for region in _REGIONS:
                        rows.append({
                            'instance_type': f'{n}x_{gid}_{cloud_type}',
                            'vcpus': vcpus_per_gpu * n,
                            'memory_gb': mem * n,
                            'region': region,
                            'price': round(price * n, 4),
                            'spot_price': round(
                                price * n * _SPOT_FRACTION, 4),
                        })
        if rows:
            return rows
    for gid, (vcpus, mem, secure, community, counts) in _GPUS.items():
        for cloud_type, price in (('SECURE', secure),
                                  ('COMMUNITY', community)):
            for n in counts:
                for region in _REGIONS:
                    rows.append({
                        'instance_type': f'{n}x_{gid}_{cloud_type}',
                        'vcpus': vcpus * n,
                        'memory_gb': mem * n,
                        'region': region,
                        'price': round(price * n, 4),
                        'spot_price': round(price * n * _SPOT_FRACTION,
                                            4),
                    })
    return rows


def refresh(online: bool = False,
            types_fetcher: Optional[Callable[[], List[Dict[str, Any]]]] = None
            ) -> str:
    """Regenerate runpod_vms.csv; returns 'online'/'offline'/'stale'."""
    live: List[Dict[str, Any]] = []
    source = 'offline'
    if online:
        try:
            live = fetch_gpu_types(types_fetcher)
            if live:
                source = 'online'
        except Exception as e:  # noqa: BLE001 — any failure = fallback
            print(f'gpuTypes query unavailable ({type(e).__name__}: {e}); '
                  'using static price table')
    from skypilot_tpu.catalog.fetchers.fetch_gcp import write_csv
    rows = generate_vm_rows(live)
    try:
        write_csv(os.path.join(DATA_DIR, 'runpod_vms.csv'), rows)
    except OSError as e:
        print(f'catalog dir not writable ({e}); keeping existing CSV')
        return 'stale'
    print(f'Wrote {len(rows)} RunPod plan rows to '
          f'{os.path.normpath(DATA_DIR)} ({source})')
    return source


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument('--online', action='store_true',
                        help='fetch live prices via the gpuTypes query')
    args = parser.parse_args(argv)
    refresh(online=args.online)


if __name__ == '__main__':
    main()
