"""Generate the AWS EC2 catalog CSV (aws_vms.csv).

Counterpart of the reference's AWS data fetcher
(sky/clouds/service_catalog/data_fetchers/fetch_aws.py — boto3 walks the
EC2 + Pricing APIs per region). Two sources, merged:

1. **AWS Pricing API** (``pricing:GetProducts``, via boto3 when
   installed): ``refresh(online=True)`` queries on-demand Linux
   shared-tenancy prices per instance type/region and overrides the
   static table wherever a live price was found. A ``pricing_client``
   seam lets tests fake the API without boto3.
2. **Static table** below (public on-demand pricing; spot at the typical
   ~60% discount): the offline fallback — this build environment has
   zero egress.

Run:  python -m skypilot_tpu.catalog.fetchers.fetch_aws [--online]
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
DATA_DIR = os.path.join(_HERE, '..', 'data')

# (vcpus, memory_gb, on-demand $/h in us-east-1). Spot = 0.4x on-demand
# (the long-run average discount AWS publishes); other-region multipliers
# below match public price sheets.
_INSTANCE_TYPES: Dict[str, Tuple[int, float, float]] = {
    't3.medium': (2, 4, 0.0416),
    'm6i.large': (2, 8, 0.096),
    'm6i.xlarge': (4, 16, 0.192),
    'm6i.2xlarge': (8, 32, 0.384),
    'm6i.4xlarge': (16, 64, 0.768),
    'c6i.xlarge': (4, 8, 0.17),
    'c6i.4xlarge': (16, 32, 0.68),
    'r6i.xlarge': (4, 32, 0.252),
    'r6i.4xlarge': (16, 128, 1.008),
}

_REGION_MULTIPLIER: Dict[str, float] = {
    'us-east-1': 1.0,
    'us-west-2': 1.0,
    'eu-west-1': 1.1126,  # m6i sheet ratio, close enough fleet-wide
}

_SPOT_DISCOUNT = 0.4

# Pricing API location names (the API keys products by human-readable
# location, not region code).
_REGION_LOCATION = {
    'us-east-1': 'US East (N. Virginia)',
    'us-west-2': 'US West (Oregon)',
    'eu-west-1': 'EU (Ireland)',
}


def fetch_ec2_prices(pricing_client: Optional[Any] = None
                     ) -> Dict[Tuple[str, str], float]:
    """(instance_type, region) -> live on-demand $/h via the Pricing API.

    ``pricing_client`` is the test seam (an object with
    ``get_products(**kwargs) -> {'PriceList': [json_str, ...]}``);
    defaults to a real boto3 pricing client (us-east-1 hosts the API).
    """
    if pricing_client is None:
        import boto3  # type: ignore  # gated: not in this image
        pricing_client = boto3.client('pricing', region_name='us-east-1')
    out: Dict[Tuple[str, str], float] = {}
    for region, location in _REGION_LOCATION.items():
        # One filtered query per tracked instance type: the unfiltered
        # product list for a region is thousands of SKUs across many
        # pages, and a first-page-only read would silently keep stale
        # static prices for whatever didn't fit the page.
        for itype in _INSTANCE_TYPES:
            resp = pricing_client.get_products(
                ServiceCode='AmazonEC2',
                Filters=[
                    {'Type': 'TERM_MATCH', 'Field': 'instanceType',
                     'Value': itype},
                    {'Type': 'TERM_MATCH', 'Field': 'location',
                     'Value': location},
                    {'Type': 'TERM_MATCH', 'Field': 'operatingSystem',
                     'Value': 'Linux'},
                    {'Type': 'TERM_MATCH', 'Field': 'tenancy',
                     'Value': 'Shared'},
                    {'Type': 'TERM_MATCH', 'Field': 'preInstalledSw',
                     'Value': 'NA'},
                    {'Type': 'TERM_MATCH', 'Field': 'capacitystatus',
                     'Value': 'Used'},
                ])
            for raw in resp.get('PriceList', []):
                product = json.loads(raw) if isinstance(raw, str) else raw
                attrs = product.get('product', {}).get('attributes', {})
                if attrs.get('instanceType') != itype:
                    continue
                on_demand = product.get('terms', {}).get('OnDemand', {})
                for term in on_demand.values():
                    for dim in term.get('priceDimensions', {}).values():
                        usd = dim.get('pricePerUnit', {}).get('USD')
                        if usd and float(usd) > 0:
                            out[(itype, region)] = float(usd)
    return out


def generate_vm_rows(live: Optional[Dict[Tuple[str, str], float]] = None
                     ) -> List[Dict[str, object]]:
    live = live or {}
    rows: List[Dict[str, object]] = []
    for itype, (vcpus, mem, base) in _INSTANCE_TYPES.items():
        for region, mult in _REGION_MULTIPLIER.items():
            price = live.get((itype, region), round(base * mult, 4))
            rows.append({
                'instance_type': itype,
                'vcpus': vcpus,
                'memory_gb': mem,
                'region': region,
                'price': price,
                'spot_price': round(price * _SPOT_DISCOUNT, 4),
            })
    return rows


def refresh(online: bool = False,
            pricing_client: Optional[Any] = None) -> str:
    """Regenerate aws_vms.csv; returns 'online'/'offline'/'stale'."""
    live: Dict[Tuple[str, str], float] = {}
    source = 'offline'
    if online:
        try:
            live = fetch_ec2_prices(pricing_client)
            if live:
                source = 'online'
        except Exception as e:  # noqa: BLE001 — any failure = fallback
            print(f'pricing API unavailable ({type(e).__name__}: {e}); '
                  'using static price table')
    from skypilot_tpu.catalog.fetchers.fetch_gcp import write_csv
    rows = generate_vm_rows(live)
    try:
        write_csv(os.path.join(DATA_DIR, 'aws_vms.csv'), rows)
    except OSError as e:
        print(f'catalog dir not writable ({e}); keeping existing CSV')
        return 'stale'
    print(f'Wrote {len(rows)} EC2 rows to {os.path.normpath(DATA_DIR)} '
          f'({source}; {len(live)} live price points)')
    return source


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument('--online', action='store_true',
                        help='fetch live prices from the AWS Pricing API')
    args = parser.parse_args(argv)
    refresh(online=args.online)


if __name__ == '__main__':
    main()
