"""Generate the OCI catalog CSV (oci_vms.csv).

Static table of common shapes (public pay-as-you-go pricing; OCI
preemptible instances are billed at 50% of on-demand — a FIXED
discount, unlike market spot) with a ``shapes_fetcher`` seam for a live
override.

Flex shapes are priced per-OCPU+GB; the catalog rows carry a concrete
(vcpus, memory) point per shape so the optimizer compares like for
like (the provisioner derives shapeConfig from the same row).

Run:  python -m skypilot_tpu.catalog.fetchers.fetch_oci [--online]
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
DATA_DIR = os.path.join(_HERE, '..', 'data')

_REGIONS = ('us-ashburn-1', 'us-phoenix-1', 'eu-frankfurt-1',
            'uk-london-1', 'ap-tokyo-1')

# shape -> (vcpus, memory_gb, $/h). E4.Flex points: OCPU $0.025/h +
# $0.0015/GB/h (1 OCPU = 2 vcpus).
_SHAPES: Dict[str, Tuple[int, float, float]] = {
    'VM.Standard.E4.Flex': (4, 16, 0.074),       # 2 OCPU + 16 GB
    'VM.Standard.E4.Flex.8': (8, 32, 0.148),     # 4 OCPU + 32 GB
    'VM.Standard.E4.Flex.16': (16, 64, 0.296),   # 8 OCPU + 64 GB
    'VM.Standard3.Flex': (4, 16, 0.084),
    'VM.Standard.A1.Flex': (4, 24, 0.046),       # Ampere Arm
    'BM.Standard.E4.128': (256, 2048, 6.40),
}

_PREEMPTIBLE_DISCOUNT = 0.5  # fixed 50% for preemptible capacity


def fetch_shapes(
        shapes_fetcher: Optional[Callable[[], List[Dict[str, Any]]]] = None
) -> List[Dict[str, Any]]:
    """Live shapes payload: [{shape, vcpus, memory_gb, price, regions}].
    ``shapes_fetcher`` is the test seam (there is no public unauth
    pricing API; the real path would walk the signed ListShapes +
    published price list)."""
    if shapes_fetcher is not None:
        return shapes_fetcher()
    return []


def generate_vm_rows(live: Optional[List[Dict[str, Any]]] = None
                     ) -> List[Dict[str, object]]:
    rows: List[Dict[str, object]] = []
    if live:
        live = [s for s in live if s.get('shape')]
        for s in sorted(live, key=lambda s: s['shape']):
            price = float(s.get('price') or 0)
            if price <= 0:
                continue
            for region in s.get('regions') or _REGIONS:
                rows.append({
                    'instance_type': s['shape'],
                    'vcpus': int(s.get('vcpus') or 0),
                    'memory_gb': float(s.get('memory_gb') or 0),
                    'region': region,
                    'price': round(price, 4),
                    'spot_price': round(price * _PREEMPTIBLE_DISCOUNT,
                                        4),
                })
        if rows:
            return rows
    for shape, (vcpus, mem, price) in _SHAPES.items():
        for region in _REGIONS:
            rows.append({
                'instance_type': shape,
                'vcpus': vcpus,
                'memory_gb': mem,
                'region': region,
                'price': price,
                'spot_price': round(price * _PREEMPTIBLE_DISCOUNT, 4),
            })
    return rows


def refresh(online: bool = False,
            shapes_fetcher: Optional[Callable[[], List[Dict[str, Any]]]] = None
            ) -> str:
    """Regenerate oci_vms.csv; returns 'online'/'offline'/'stale'."""
    live: List[Dict[str, Any]] = []
    source = 'offline'
    if online:
        try:
            live = fetch_shapes(shapes_fetcher)
            if live:
                source = 'online'
        except Exception as e:  # noqa: BLE001 — any failure = fallback
            print(f'shapes source unavailable ({type(e).__name__}: {e}); '
                  'using static price table')
    from skypilot_tpu.catalog.fetchers.fetch_gcp import write_csv
    rows = generate_vm_rows(live)
    try:
        write_csv(os.path.join(DATA_DIR, 'oci_vms.csv'), rows)
    except OSError as e:
        print(f'catalog dir not writable ({e}); keeping existing CSV')
        return 'stale'
    print(f'Wrote {len(rows)} OCI shape rows to '
          f'{os.path.normpath(DATA_DIR)} ({source})')
    return source


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument('--online', action='store_true',
                        help='use a live shapes source when provided')
    args = parser.parse_args(argv)
    refresh(online=args.online)


if __name__ == '__main__':
    main()
