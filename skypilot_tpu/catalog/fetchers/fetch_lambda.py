"""Generate the Lambda Cloud catalog CSV (lambda_vms.csv).

Counterpart of the reference's Lambda data fetcher
(sky/clouds/service_catalog/data_fetchers/fetch_lambda_cloud.py — walks
the authenticated ``/instance-types`` endpoint). Two sources, merged:

1. **Lambda instance-types API** (``GET /api/v1/instance-types`` —
   needs an API key): ``refresh(online=True)`` pulls live
   ``price_cents_per_hour`` + specs + per-type region availability and
   overrides the static table. A ``types_fetcher`` seam lets tests fake
   the API without network.
2. **Static table** below (public on-demand pricing; Lambda has NO spot
   market, so ``spot_price`` mirrors ``price`` and the cloud class
   rejects ``use_spot`` before the column is ever read): the offline
   fallback — this build environment has zero egress.

Run:  python -m skypilot_tpu.catalog.fetchers.fetch_lambda [--online]
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
DATA_DIR = os.path.join(_HERE, '..', 'data')

# (vcpus, memory_gb, on-demand $/h, regions). Public Lambda pricing;
# regions are the typical availability set per type.
_US = ('us-east-1', 'us-west-1', 'us-midwest-1')
_GLOBAL = _US + ('europe-central-1', 'asia-northeast-1')
_INSTANCE_TYPES: Dict[str, Tuple[int, float, float, Tuple[str, ...]]] = {
    'gpu_1x_a10': (30, 200, 0.75, _GLOBAL),
    'gpu_1x_a100_sxm4': (30, 200, 1.29, _GLOBAL),
    'gpu_8x_a100_80gb_sxm4': (240, 1800, 14.32, _US),
    'gpu_1x_h100_pcie': (26, 200, 2.49, _GLOBAL),
    'gpu_8x_h100_sxm5': (208, 1800, 23.92, _US),
    'gpu_1x_gh200': (64, 432, 1.49, ('us-east-1', 'us-west-1')),
}


def fetch_instance_types(
        types_fetcher: Optional[Callable[[], Dict[str, Any]]] = None
) -> Dict[str, Any]:
    """Live /instance-types payload: name -> {instance_type:
    {price_cents_per_hour, specs{vcpus, memory_gib}},
    regions_with_capacity_available: [{name}]}. ``types_fetcher`` is the
    test seam; the default uses the authenticated REST client."""
    if types_fetcher is not None:
        return types_fetcher()
    from skypilot_tpu.provision import lambda_api
    return lambda_api.get_client().instance_types()


def generate_vm_rows(live: Optional[Dict[str, Any]] = None
                     ) -> List[Dict[str, object]]:
    rows: List[Dict[str, object]] = []
    if live:
        for name, entry in sorted(live.items()):
            spec = (entry.get('instance_type') or {})
            specs = spec.get('specs') or {}
            price = float(spec.get('price_cents_per_hour') or 0) / 100.0
            regions = [r.get('name') for r in
                       entry.get('regions_with_capacity_available') or []]
            # A type with no capacity anywhere still gets its static
            # regions: the catalog answers "where does Lambda OFFER
            # this", capacity errors are the provisioner's failover job.
            if not regions and name in _INSTANCE_TYPES:
                regions = list(_INSTANCE_TYPES[name][3])
            for region in regions:
                rows.append({
                    'instance_type': name,
                    'vcpus': int(specs.get('vcpus') or 0),
                    'memory_gb': float(specs.get('memory_gib') or 0),
                    'region': region,
                    'price': round(price, 4),
                    'spot_price': round(price, 4),
                })
        if rows:
            return rows
    for name, (vcpus, mem, price, regions) in _INSTANCE_TYPES.items():
        for region in regions:
            rows.append({
                'instance_type': name,
                'vcpus': vcpus,
                'memory_gb': mem,
                'region': region,
                'price': price,
                'spot_price': price,
            })
    return rows


def refresh(online: bool = False,
            types_fetcher: Optional[Callable[[], Dict[str, Any]]] = None
            ) -> str:
    """Regenerate lambda_vms.csv; returns 'online'/'offline'/'stale'."""
    live: Dict[str, Any] = {}
    source = 'offline'
    if online:
        try:
            live = fetch_instance_types(types_fetcher)
            if live:
                source = 'online'
        except Exception as e:  # noqa: BLE001 — any failure = fallback
            print(f'instance-types API unavailable ({type(e).__name__}: '
                  f'{e}); using static price table')
    from skypilot_tpu.catalog.fetchers.fetch_gcp import write_csv
    rows = generate_vm_rows(live)
    try:
        write_csv(os.path.join(DATA_DIR, 'lambda_vms.csv'), rows)
    except OSError as e:
        print(f'catalog dir not writable ({e}); keeping existing CSV')
        return 'stale'
    print(f'Wrote {len(rows)} Lambda VM rows to '
          f'{os.path.normpath(DATA_DIR)} ({source})')
    return source


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument('--online', action='store_true',
                        help='fetch live prices from /instance-types')
    args = parser.parse_args(argv)
    refresh(online=args.online)


if __name__ == '__main__':
    main()
