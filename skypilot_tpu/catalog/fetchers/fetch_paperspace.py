"""Generate the Paperspace catalog CSV (paperspace_vms.csv).

Static table of CORE machine types (public pricing; CPU 'C' tier +
GPU tiers; no spot market, so ``spot_price`` mirrors ``price``) with a
``types_fetcher`` seam for a live ``/machine-types`` override.

Run:  python -m skypilot_tpu.catalog.fetchers.fetch_paperspace [--online]
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
DATA_DIR = os.path.join(_HERE, '..', 'data')

_REGIONS = ('ny2', 'ca1', 'ams1')

# machine_type -> (vcpus, memory_gb, $/h)
_TYPES: Dict[str, Tuple[int, float, float]] = {
    'C4': (2, 4, 0.04),
    'C5': (4, 8, 0.08),
    'C6': (8, 16, 0.16),
    'C7': (12, 30, 0.30),
    'P4000': (8, 30, 0.51),
    'RTX4000': (8, 30, 0.56),
    'A4000': (8, 45, 0.76),
    'A100': (12, 90, 3.09),
    'A100-80G': (12, 90, 3.18),
}


def fetch_machine_types(
        types_fetcher: Optional[Callable[[], List[Dict[str, Any]]]] = None
) -> List[Dict[str, Any]]:
    """Live machine-types payload: [{label, cpus, ram (bytes or GB),
    price, regions}]. ``types_fetcher`` is the test seam."""
    if types_fetcher is not None:
        return types_fetcher()
    from skypilot_tpu.provision import paperspace_api
    client = paperspace_api.get_client()
    body = client._request('GET', '/machine-types')  # pylint: disable=protected-access
    return list(body.get('items') or body.get('data') or [])


def generate_vm_rows(live: Optional[List[Dict[str, Any]]] = None
                     ) -> List[Dict[str, object]]:
    rows: List[Dict[str, object]] = []
    if live:
        # Drop malformed entries BEFORE sorting (a None label would
        # TypeError inside sorted()).
        live = [t for t in live if t.get('label')]
        for t in sorted(live, key=lambda t: t['label']):
            label = t['label']
            price = float(t.get('price') or 0)
            if price <= 0:
                continue
            ram = float(t.get('ram') or 0)
            if ram > 1e6:  # bytes -> GB
                ram = ram / (1024 ** 3)
            for region in t.get('regions') or _REGIONS:
                rows.append({
                    'instance_type': label,
                    'vcpus': int(t.get('cpus') or 0),
                    'memory_gb': round(ram, 1),
                    'region': region,
                    'price': round(price, 4),
                    'spot_price': round(price, 4),
                })
        if rows:
            return rows
    for label, (vcpus, mem, price) in _TYPES.items():
        for region in _REGIONS:
            rows.append({
                'instance_type': label,
                'vcpus': vcpus,
                'memory_gb': mem,
                'region': region,
                'price': price,
                'spot_price': price,
            })
    return rows


def refresh(online: bool = False,
            types_fetcher: Optional[Callable[[], List[Dict[str, Any]]]] = None
            ) -> str:
    """Regenerate paperspace_vms.csv; returns 'online'/'offline'/'stale'."""
    live: List[Dict[str, Any]] = []
    source = 'offline'
    if online:
        try:
            live = fetch_machine_types(types_fetcher)
            if live:
                source = 'online'
        except Exception as e:  # noqa: BLE001 — any failure = fallback
            print(f'machine-types API unavailable ({type(e).__name__}: '
                  f'{e}); using static price table')
    from skypilot_tpu.catalog.fetchers.fetch_gcp import write_csv
    rows = generate_vm_rows(live)
    try:
        write_csv(os.path.join(DATA_DIR, 'paperspace_vms.csv'), rows)
    except OSError as e:
        print(f'catalog dir not writable ({e}); keeping existing CSV')
        return 'stale'
    print(f'Wrote {len(rows)} Paperspace machine rows to '
          f'{os.path.normpath(DATA_DIR)} ({source})')
    return source


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument('--online', action='store_true',
                        help='fetch live machine types from the API')
    args = parser.parse_args(argv)
    refresh(online=args.online)


if __name__ == '__main__':
    main()
