"""Generate the Azure VM catalog CSV (azure_vms.csv).

Counterpart of the reference's Azure data fetcher
(sky/clouds/service_catalog/data_fetchers/fetch_azure.py — walks the
azure SDK SKU list + the public Retail Prices REST API). Two sources,
merged:

1. **Azure Retail Prices API** (``https://prices.azure.com/api/retail/
   prices`` — public, unauthenticated): ``refresh(online=True)`` queries
   Linux consumption prices per VM size/region and overrides the static
   table wherever a live price was found. A ``price_fetcher`` seam lets
   tests fake the API without network.
2. **Static table** below (public pay-as-you-go pricing; spot at the
   typical ~70% discount Azure advertises): the offline fallback — this
   build environment has zero egress.

Run:  python -m skypilot_tpu.catalog.fetchers.fetch_azure [--online]
"""
from __future__ import annotations

import json
import os
import urllib.parse
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
DATA_DIR = os.path.join(_HERE, '..', 'data')

# (vcpus, memory_gb, pay-as-you-go $/h in eastus). Spot = 0.3x on-demand
# (Azure's advertised "up to 90%, typically ~70%" discount, taken
# conservatively); other-region multipliers below match public sheets.
_VM_SIZES: Dict[str, Tuple[int, float, float]] = {
    'Standard_B2s': (2, 4, 0.0416),
    'Standard_D2s_v5': (2, 8, 0.096),
    'Standard_D4s_v5': (4, 16, 0.192),
    'Standard_D8s_v5': (8, 32, 0.384),
    'Standard_D16s_v5': (16, 64, 0.768),
    'Standard_F4s_v2': (4, 8, 0.169),
    'Standard_F16s_v2': (16, 32, 0.677),
    'Standard_E4s_v5': (4, 32, 0.252),
    'Standard_E16s_v5': (16, 128, 1.008),
}

_REGION_MULTIPLIER: Dict[str, float] = {
    'eastus': 1.0,
    'westus2': 1.0,
    'westeurope': 1.1,
}

_SPOT_DISCOUNT = 0.3


def _default_price_fetcher(url: str) -> Dict[str, Any]:
    with urllib.request.urlopen(url, timeout=20) as resp:
        return json.loads(resp.read())


def fetch_retail_prices(
        price_fetcher: Optional[Callable[[str], Dict[str, Any]]] = None
) -> Dict[Tuple[str, str], float]:
    """(vm_size, region) -> live consumption $/h via the Retail Prices
    API. ``price_fetcher(url) -> response dict`` is the test seam."""
    fetcher = price_fetcher or _default_price_fetcher
    out: Dict[Tuple[str, str], float] = {}
    for region in _REGION_MULTIPLIER:
        # One filtered query per region; the API pages via NextPageLink.
        names = ','.join(f"'{s}'" for s in _VM_SIZES)
        filt = (f"serviceName eq 'Virtual Machines' and "
                f"armRegionName eq '{region}' and "
                f"priceType eq 'Consumption' and "
                f"armSkuName in ({names})")
        url = ('https://prices.azure.com/api/retail/prices?$filter='
               + urllib.parse.quote(filt))
        while url:
            resp = fetcher(url)
            for item in resp.get('Items', []):
                sku = item.get('armSkuName')
                if sku not in _VM_SIZES:
                    continue
                # Skip Windows/low-priority/spot meters: the plain Linux
                # consumption meter has no qualifier in its meter name.
                meter = item.get('meterName', '')
                product = item.get('productName', '')
                if 'Windows' in product or 'Spot' in meter \
                        or 'Low Priority' in meter:
                    continue
                price = float(item.get('retailPrice') or 0)
                if price > 0:
                    out[(sku, region)] = price
            url = resp.get('NextPageLink')
    return out


def generate_vm_rows(live: Optional[Dict[Tuple[str, str], float]] = None
                     ) -> List[Dict[str, object]]:
    live = live or {}
    rows: List[Dict[str, object]] = []
    for size, (vcpus, mem, base) in _VM_SIZES.items():
        for region, mult in _REGION_MULTIPLIER.items():
            price = live.get((size, region), round(base * mult, 4))
            rows.append({
                'instance_type': size,
                'vcpus': vcpus,
                'memory_gb': mem,
                'region': region,
                'price': price,
                'spot_price': round(price * _SPOT_DISCOUNT, 4),
            })
    return rows


def refresh(online: bool = False,
            price_fetcher: Optional[Callable[[str], Dict[str, Any]]] = None
            ) -> str:
    """Regenerate azure_vms.csv; returns 'online'/'offline'/'stale'."""
    live: Dict[Tuple[str, str], float] = {}
    source = 'offline'
    if online:
        try:
            live = fetch_retail_prices(price_fetcher)
            if live:
                source = 'online'
        except Exception as e:  # noqa: BLE001 — any failure = fallback
            print(f'retail prices API unavailable ({type(e).__name__}: '
                  f'{e}); using static price table')
    from skypilot_tpu.catalog.fetchers.fetch_gcp import write_csv
    rows = generate_vm_rows(live)
    try:
        write_csv(os.path.join(DATA_DIR, 'azure_vms.csv'), rows)
    except OSError as e:
        print(f'catalog dir not writable ({e}); keeping existing CSV')
        return 'stale'
    print(f'Wrote {len(rows)} Azure VM rows to '
          f'{os.path.normpath(DATA_DIR)} '
          f'({source}; {len(live)} live price points)')
    return source


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument('--online', action='store_true',
                        help='fetch live prices from the Retail Prices API')
    args = parser.parse_args(argv)
    refresh(online=args.online)


if __name__ == '__main__':
    main()
