"""Generate the Vast.ai catalog CSV (vast_vms.csv).

The marketplace has no price list — every host names its own rate — so
catalog rows are MEDIAN observed prices per synthetic plan
(``{n}x_{GPU_NAME}``, the same invention as the reference's
vast_catalog.py) and per country code. Two sources, merged:

1. **Offer search** (``refresh(online=True)``): samples live offers per
   plan via the REST client and writes median dph_total / min_bid. An
   ``offers_fetcher`` seam lets tests fake the API without network.
2. **Static table** below (typical marketplace rates): the offline
   fallback.

``spot_price`` is the typical winning interruptible bid (~40% of
on-demand — marketplace data, conservative).

Run:  python -m skypilot_tpu.catalog.fetchers.fetch_vast [--online]
"""
from __future__ import annotations

import os
import statistics
from typing import Any, Callable, Dict, List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
DATA_DIR = os.path.join(_HERE, '..', 'data')

_REGIONS = ('US', 'CA', 'DE', 'NL', 'SE')

# plan -> (vcpus, memory_gb, median $/h). Typical marketplace medians.
_PLANS: Dict[str, Tuple[int, float, float]] = {
    '1x_RTX_3090': (8, 32, 0.22),
    '1x_RTX_4090': (12, 64, 0.42),
    '4x_RTX_4090': (48, 256, 1.68),
    '1x_A100_SXM4': (16, 120, 0.95),
    '8x_A100_SXM4': (128, 960, 7.60),
    '8x_H100_SXM': (160, 1536, 18.40),
}

_SPOT_FRACTION = 0.4


def fetch_offer_medians(
        offers_fetcher: Optional[
            Callable[[str, int, str], List[Dict[str, Any]]]] = None
) -> Dict[Tuple[str, str], Tuple[float, float]]:
    """(plan, region) -> (median dph_total, median min_bid) from live
    offer samples. ``offers_fetcher(gpu_name, num_gpus, region)`` is the
    test seam; the default uses the REST client."""
    if offers_fetcher is None:
        from skypilot_tpu.provision import vast_api
        client = vast_api.get_client()

        def offers_fetcher(gpu_name, num_gpus, region):  # noqa: F811
            return client.search_offers(gpu_name=gpu_name,
                                        num_gpus=num_gpus,
                                        geolocation=region,
                                        min_disk_gb=50)
    from skypilot_tpu.provision import vast_impl
    out: Dict[Tuple[str, str], Tuple[float, float]] = {}
    for plan in _PLANS:
        num_gpus, gpu_name = vast_impl.split_plan(plan)
        for region in _REGIONS:
            offers = offers_fetcher(gpu_name, num_gpus, region)
            prices = [float(o['dph_total']) for o in offers
                      if o.get('dph_total')]
            bids = [float(o['min_bid']) for o in offers
                    if o.get('min_bid')]
            if prices:
                out[(plan, region)] = (
                    statistics.median(prices),
                    statistics.median(bids) if bids
                    else statistics.median(prices) * _SPOT_FRACTION)
    return out


def generate_vm_rows(
        live: Optional[Dict[Tuple[str, str], Tuple[float, float]]] = None
) -> List[Dict[str, object]]:
    live = live or {}
    rows: List[Dict[str, object]] = []
    for plan, (vcpus, mem, base) in _PLANS.items():
        for region in _REGIONS:
            price, bid = live.get(
                (plan, region), (base, round(base * _SPOT_FRACTION, 4)))
            rows.append({
                'instance_type': plan,
                'vcpus': vcpus,
                'memory_gb': mem,
                'region': region,
                'price': round(price, 4),
                'spot_price': round(bid, 4),
            })
    return rows


def refresh(online: bool = False,
            offers_fetcher: Optional[
                Callable[[str, int, str], List[Dict[str, Any]]]] = None
            ) -> str:
    """Regenerate vast_vms.csv; returns 'online'/'offline'/'stale'."""
    live: Dict[Tuple[str, str], Tuple[float, float]] = {}
    source = 'offline'
    if online:
        try:
            live = fetch_offer_medians(offers_fetcher)
            if live:
                source = 'online'
        except Exception as e:  # noqa: BLE001 — any failure = fallback
            print(f'offer search unavailable ({type(e).__name__}: {e}); '
                  'using static price table')
    from skypilot_tpu.catalog.fetchers.fetch_gcp import write_csv
    rows = generate_vm_rows(live)
    try:
        write_csv(os.path.join(DATA_DIR, 'vast_vms.csv'), rows)
    except OSError as e:
        print(f'catalog dir not writable ({e}); keeping existing CSV')
        return 'stale'
    print(f'Wrote {len(rows)} Vast plan rows to '
          f'{os.path.normpath(DATA_DIR)} ({source})')
    return source


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument('--online', action='store_true',
                        help='sample live offers for median prices')
    args = parser.parse_args(argv)
    refresh(online=args.online)


if __name__ == '__main__':
    main()
