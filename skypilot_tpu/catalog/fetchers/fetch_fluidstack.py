"""Generate the FluidStack catalog CSV (fluidstack_vms.csv).

Counterpart of the reference's FluidStack catalog fetcher (walks the
authenticated ``/list_available_configurations`` endpoint). Two sources,
merged:

1. **Plans API**: ``refresh(online=True)`` pulls live plans
   ({gpu_type, gpu_counts, price_per_gpu_hr, regions, cpu/memory per
   gpu}) via the REST client. A ``plans_fetcher`` seam lets tests fake
   the API without network.
2. **Static table** below (public pricing; no spot market, so
   ``spot_price`` mirrors ``price``): the offline fallback.

Instance types are ``{gpu_type}::{count}`` plans (the provisioner's
launch unit, reference fluidstack_utils.py:90).

Run:  python -m skypilot_tpu.catalog.fetchers.fetch_fluidstack [--online]
"""
from __future__ import annotations

import os
from typing import Any, Callable, Dict, List, Optional, Tuple

_HERE = os.path.dirname(os.path.abspath(__file__))
DATA_DIR = os.path.join(_HERE, '..', 'data')

# gpu_type -> (counts, $/gpu/h, vcpus-per-gpu, mem_gb-per-gpu, regions)
_PLANS: Dict[str, Tuple[Tuple[int, ...], float, int, float,
                        Tuple[str, ...]]] = {
    'RTX_A6000': ((1, 2, 4), 0.49, 8, 48, ('NORWAY_4', 'CANADA_1')),
    'A100_80G': ((1, 2, 4, 8), 1.49, 12, 120,
                 ('NORWAY_4', 'CANADA_1', 'ARIZONA_1')),
    'H100': ((8,), 2.89, 20, 192, ('NORWAY_4', 'ARIZONA_1')),
}


def fetch_plans(
        plans_fetcher: Optional[Callable[[], List[Dict[str, Any]]]] = None
) -> List[Dict[str, Any]]:
    """Live plans payload; ``plans_fetcher`` is the test seam."""
    if plans_fetcher is not None:
        return plans_fetcher()
    from skypilot_tpu.provision import fluidstack_api
    return fluidstack_api.get_client().list_plans()


def generate_vm_rows(live: Optional[List[Dict[str, Any]]] = None
                     ) -> List[Dict[str, object]]:
    rows: List[Dict[str, object]] = []
    if live:
        for plan in sorted(live, key=lambda p: p.get('gpu_type', '')):
            gpu_type = plan.get('gpu_type')
            if not gpu_type:
                continue
            price = float(plan.get('price_per_gpu_hr') or 0)
            vcpus = int(plan.get('cpus_per_gpu') or 8)
            mem = float(plan.get('memory_gb_per_gpu') or 64)
            for count in plan.get('gpu_counts') or [1]:
                for region in plan.get('regions') or []:
                    rows.append({
                        'instance_type': f'{gpu_type}::{count}',
                        'vcpus': vcpus * count,
                        'memory_gb': mem * count,
                        'region': region,
                        'price': round(price * count, 4),
                        'spot_price': round(price * count, 4),
                    })
        if rows:
            return rows
    for gpu_type, (counts, price, vcpus, mem, regions) in _PLANS.items():
        for count in counts:
            for region in regions:
                rows.append({
                    'instance_type': f'{gpu_type}::{count}',
                    'vcpus': vcpus * count,
                    'memory_gb': mem * count,
                    'region': region,
                    'price': round(price * count, 4),
                    'spot_price': round(price * count, 4),
                })
    return rows


def refresh(online: bool = False,
            plans_fetcher: Optional[Callable[[], List[Dict[str, Any]]]] = None
            ) -> str:
    """Regenerate fluidstack_vms.csv; returns 'online'/'offline'/'stale'."""
    live: List[Dict[str, Any]] = []
    source = 'offline'
    if online:
        try:
            live = fetch_plans(plans_fetcher)
            if live:
                source = 'online'
        except Exception as e:  # noqa: BLE001 — any failure = fallback
            print(f'plans API unavailable ({type(e).__name__}: {e}); '
                  'using static price table')
    from skypilot_tpu.catalog.fetchers.fetch_gcp import write_csv
    rows = generate_vm_rows(live)
    try:
        write_csv(os.path.join(DATA_DIR, 'fluidstack_vms.csv'), rows)
    except OSError as e:
        print(f'catalog dir not writable ({e}); keeping existing CSV')
        return 'stale'
    print(f'Wrote {len(rows)} FluidStack plan rows to '
          f'{os.path.normpath(DATA_DIR)} ({source})')
    return source


def main(argv: Optional[List[str]] = None) -> None:
    import argparse
    parser = argparse.ArgumentParser()
    parser.add_argument('--online', action='store_true',
                        help='fetch live plans from the API')
    args = parser.parse_args(argv)
    refresh(online=args.online)


if __name__ == '__main__':
    main()
