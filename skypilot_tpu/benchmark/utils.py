"""Benchmark harness: fan a task out over candidate TPU configs.

Counterpart of reference ``sky/benchmark/benchmark_utils.py`` (launches N
candidate resource configs, collects per-step timing via sky_callback,
reports $/step). Flow:

    bench launch  ->  one cluster per candidate, task runs with
                      SKYTPU_BENCHMARK_LOG_DIR armed (callbacks/ writes
                      benchmark_summary.json on the head host)
    bench show    ->  pulls summaries off each cluster, prints
                      sec/step, steps/$ and $/step per candidate
    bench down    ->  terminates the candidate clusters
"""
from __future__ import annotations

import copy
import json
import os
import threading
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import task as task_lib
from skypilot_tpu.benchmark import state
from skypilot_tpu.runtime import constants as rt_constants

# Remote dir (relative to the job's workdir) where the callback writes.
_REMOTE_LOG_DIR = 'skytpu_benchmark'


def _cluster_name(benchmark: str, idx: int) -> str:
    return f'skytpu-bench-{benchmark}-{idx}'


def _hourly_cost(resources: Any) -> float:
    try:
        from skypilot_tpu import clouds as clouds_lib
        cloud = clouds_lib.get_cloud(resources.cloud)
        return cloud.hourly_cost(resources, resources.region,
                                 resources.zone)
    except Exception:
        return 0.0


def launch(task: task_lib.Task, benchmark: str,
           candidates: List[Any]) -> List[Dict[str, Any]]:
    """Launch the task once per candidate Resources; returns per-candidate
    {cluster, job_id or error}. Launches run in parallel (one provision
    thread per candidate, the reference does the same)."""
    from skypilot_tpu import execution
    state.add_benchmark(benchmark, task.name)
    results: List[Dict[str, Any]] = [dict() for _ in candidates]

    def one(idx: int, resources: Any) -> None:
        cand_task = copy.deepcopy(task)
        cand_task.set_resources([resources])
        cand_task.update_envs(
            {'SKYTPU_BENCHMARK_LOG_DIR': _REMOTE_LOG_DIR})
        cluster = _cluster_name(benchmark, idx)
        try:
            job_id, handle = execution.launch(
                cand_task, cluster_name=cluster, detach_run=True,
                stream_logs=False)
            launched = (handle.launched_resources
                        if handle is not None else resources)
            state.add_result(benchmark, cluster, str(launched),
                             _hourly_cost(launched), job_id)
            results[idx] = {'cluster': cluster, 'job_id': job_id}
        except Exception as e:  # noqa: BLE001 — any failure is a
            # per-candidate result, never a dead thread + empty row
            state.add_result(benchmark, cluster, str(resources), 0.0, None)
            results[idx] = {'cluster': cluster, 'error': str(e)}

    threads = [threading.Thread(target=one, args=(i, r))
               for i, r in enumerate(candidates)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results


def update_summaries(benchmark: str) -> None:
    """Pull benchmark_summary.json off each candidate's head host."""
    from skypilot_tpu import backends
    backend = backends.SliceBackend()
    for row in state.get_results(benchmark):
        try:
            record = global_user_state.get_cluster_from_name(row['cluster'])
            if record is None or record['handle'] is None:
                continue
            path = (f'{rt_constants.WORKDIR}/{_REMOTE_LOG_DIR}/'
                    'benchmark_summary.json')
            head = backend._runners(record['handle'])[0]
            res = head.run(f'cat {path}', timeout=60)
            if res.returncode != 0:
                continue
            state.set_summary(benchmark, row['cluster'],
                              json.loads(res.stdout.strip()))
        except Exception:  # noqa: BLE001 — one hung/broken candidate
            # (SSH timeout, empty host list, bad JSON) must not take down
            # the whole report; its row just stays summary-less.
            continue


def get_report(benchmark: str, refresh: bool = True
               ) -> List[Dict[str, Any]]:
    """Per-candidate comparison rows with derived $/step."""
    if refresh:
        update_summaries(benchmark)
    report = []
    for row in state.get_results(benchmark):
        summary = row['summary'] or {}
        sec_per_step = summary.get('seconds_per_step')
        entry = {
            'cluster': row['cluster'],
            'resources': row['resources'],
            'hourly_cost': row['hourly_cost'],
            'num_steps': summary.get('num_steps'),
            'seconds_per_step': sec_per_step,
            'cost_per_step': (row['hourly_cost'] * sec_per_step / 3600
                              if sec_per_step else None),
        }
        report.append(entry)
    return report


def down(benchmark: str) -> List[str]:
    """Terminate all candidate clusters of a benchmark."""
    from skypilot_tpu import core
    downed = []
    for row in state.get_results(benchmark):
        try:
            core.down(row['cluster'])
            downed.append(row['cluster'])
        except exceptions.SkyTpuError:
            pass
    return downed


def delete(benchmark: str) -> None:
    state.delete_benchmark(benchmark)
