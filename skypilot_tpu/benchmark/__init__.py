"""Benchmark tool: run one task across candidate TPU configs, compare
seconds/step and $/step (reference ``sky bench``,
sky/benchmark/benchmark_utils.py + benchmark_state.py)."""
