"""Benchmark records: sqlite (reference sky/benchmark/benchmark_state.py)."""
from __future__ import annotations

import json
import os
import sqlite3
import threading
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import global_user_state

_LOCAL = threading.local()


def _db() -> sqlite3.Connection:
    path = os.path.join(global_user_state.get_state_dir(), 'benchmark.db')
    conns = getattr(_LOCAL, 'conns', None)
    if conns is None:
        conns = _LOCAL.conns = {}
    conn = conns.get(path)
    if conn is None:
        conn = sqlite3.connect(path, timeout=10.0)
        conn.execute('PRAGMA journal_mode=WAL')
        conn.execute("""
            CREATE TABLE IF NOT EXISTS benchmarks (
                benchmark TEXT PRIMARY KEY,
                task_name TEXT,
                launched_at REAL
            )""")
        conn.execute("""
            CREATE TABLE IF NOT EXISTS benchmark_results (
                benchmark TEXT,
                cluster TEXT,
                resources TEXT,
                hourly_cost REAL,
                job_id INTEGER,
                summary TEXT,
                PRIMARY KEY (benchmark, cluster)
            )""")
        conn.commit()
        conns[path] = conn
    return conn


def add_benchmark(benchmark: str, task_name: Optional[str]) -> None:
    conn = _db()
    conn.execute('INSERT OR REPLACE INTO benchmarks VALUES (?,?,?)',
                 (benchmark, task_name, time.time()))
    # Relaunch under an existing name starts fresh: stale candidate rows
    # from a previous run must not survive into the new report.
    conn.execute('DELETE FROM benchmark_results WHERE benchmark=?',
                 (benchmark,))
    conn.commit()


def add_result(benchmark: str, cluster: str, resources: str,
               hourly_cost: float, job_id: Optional[int]) -> None:
    conn = _db()
    conn.execute(
        'INSERT OR REPLACE INTO benchmark_results '
        '(benchmark, cluster, resources, hourly_cost, job_id, summary) '
        'VALUES (?,?,?,?,?,NULL)',
        (benchmark, cluster, resources, hourly_cost, job_id))
    conn.commit()


def set_summary(benchmark: str, cluster: str,
                summary: Dict[str, Any]) -> None:
    conn = _db()
    conn.execute('UPDATE benchmark_results SET summary=? '
                 'WHERE benchmark=? AND cluster=?',
                 (json.dumps(summary), benchmark, cluster))
    conn.commit()


def list_benchmarks() -> List[Dict[str, Any]]:
    return [{'benchmark': r[0], 'task_name': r[1], 'launched_at': r[2]}
            for r in _db().execute(
                'SELECT benchmark, task_name, launched_at FROM benchmarks '
                'ORDER BY launched_at DESC')]


def get_results(benchmark: str) -> List[Dict[str, Any]]:
    rows = []
    for r in _db().execute(
            'SELECT cluster, resources, hourly_cost, job_id, summary '
            'FROM benchmark_results WHERE benchmark=?', (benchmark,)):
        rows.append({
            'cluster': r[0], 'resources': r[1], 'hourly_cost': r[2],
            'job_id': r[3],
            'summary': json.loads(r[4]) if r[4] else None,
        })
    return rows


def delete_benchmark(benchmark: str) -> None:
    conn = _db()
    conn.execute('DELETE FROM benchmarks WHERE benchmark=?', (benchmark,))
    conn.execute('DELETE FROM benchmark_results WHERE benchmark=?',
                 (benchmark,))
    conn.commit()
