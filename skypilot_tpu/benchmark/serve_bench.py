"""Serve-path benchmark: closed-loop load through the LB to a replica.

Measures the BASELINE.md serving north-star metrics on THIS framework's
own serve path — serve controller + load balancer + in-tree
continuous-batching generation replica — not an in-process engine
microbenchmark. The reference's anchors are Llama-2-7B via JetStream on a
v6e-8 (reference examples/tpu/v6e/README.md serving section: 11.42 req/s,
TTFT median 1829 ms, TPOT median 18.88 ms, ~2500 input / ~150 output
tokens per request); this harness reproduces that workload shape against
the largest preset that fits the local chip and reports raw measured
numbers plus a clearly-labelled bandwidth-scaling equivalence estimate.

The service launches on the ``local`` cloud, so the replica subprocess
owns the real chip; the caller must not have initialized JAX (the serve
phase runs before any in-process device work, mirroring bench.py's
launched-train phase).
"""
from __future__ import annotations

import json
import random
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Sequence, Tuple


def summarize_metrics_text(text: str) -> Dict[str, Any]:
    """Server-side histogram/counter summaries from a replica's /metrics
    exposition — the production-signal counterpart of the client-side
    sweep numbers (client TTFT includes LB + network; these are the
    replica's own measurements, and the recompile counter is invisible
    to clients entirely)."""
    from skypilot_tpu.utils import metrics as metrics_lib
    samples = metrics_lib.parse_text(text)
    out: Dict[str, Any] = {}
    for name in ('skytpu_serve_ttft_ms', 'skytpu_serve_tpot_ms',
                 'skytpu_serve_queue_wait_ms',
                 'skytpu_serve_ttft_estimate_error_ms',
                 'skytpu_engine_step_ms',
                 # Spec-decode: accept histogram observes accept+1
                 # (tokens emitted per slot per verify step), so its
                 # mean is accepted_tokens_per_step directly.
                 'skytpu_engine_spec_accept_tokens',
                 'skytpu_engine_spec_verify_ms'):
        cum = metrics_lib.histogram_cumulative(samples, name)
        count = metrics_lib.sample_value(samples, f'{name}_count')
        total = metrics_lib.sample_value(samples, f'{name}_sum')
        if not cum or not count:
            continue
        p50 = metrics_lib.histogram_quantile(cum, 0.5)
        p99 = metrics_lib.histogram_quantile(cum, 0.99)
        out[name] = {
            'count': int(count),
            'mean': round(total / count, 3) if total is not None else None,
            'p50_est': round(p50, 2) if p50 is not None else None,
            'p99_est': round(p99, 2) if p99 is not None else None,
        }
    for name in ('skytpu_serve_requests_total',
                 'skytpu_serve_rejected_total',
                 'skytpu_serve_slo_violations_total',
                 'skytpu_engine_recompiles_total',
                 'skytpu_engine_prefill_tokens_total',
                 'skytpu_engine_decode_tokens_total',
                 'skytpu_engine_occupancy_ratio',
                 'skytpu_engine_kv_block_utilization_ratio',
                 'skytpu_engine_kv_prefix_hits_total',
                 'skytpu_engine_kv_prefix_hit_tokens_total',
                 'skytpu_engine_kv_prefix_lookup_tokens_total',
                 'skytpu_engine_kv_evictions_total',
                 'skytpu_engine_spec_draft_hits_total',
                 'skytpu_serve_slo_headroom_ms'):
        v = metrics_lib.sample_value(samples, name)
        if v is not None:
            out[name] = round(v, 3)
    return out


def _percentile(values: Sequence[float], pct: float) -> float:
    """Nearest-rank percentile (no interpolation; robust for small N)."""
    ordered = sorted(values)
    idx = min(len(ordered) - 1, max(0, int(round(pct / 100.0
                                                 * (len(ordered) - 1)))))
    return ordered[idx]


def make_prompt(rnd: random.Random, vocab_size: int, prompt_len: int,
                shared_prefix: Optional[Sequence[int]] = None
                ) -> List[int]:
    """One workload prompt: random ids, optionally behind a common
    prefix (the millions-of-users-one-system-prompt shape the paged-KV
    prefix cache serves). The prefix is truncated to leave >= 1 random
    suffix token so every request is a distinct sequence."""
    if not shared_prefix:
        return [rnd.randrange(vocab_size) for _ in range(prompt_len)]
    prefix = list(shared_prefix)[:max(0, prompt_len - 1)]
    return prefix + [rnd.randrange(vocab_size)
                     for _ in range(prompt_len - len(prefix))]


def _post_generate(endpoint: str, tokens: List[int], max_tokens: int,
                   stream: bool, timeout: float = 900.0):
    body = json.dumps({'tokens': tokens, 'max_tokens': max_tokens,
                       'stream': stream}).encode()
    req = urllib.request.Request(endpoint + '/generate', data=body,
                                 headers={'Content-Type':
                                          'application/json'})
    return urllib.request.urlopen(req, timeout=timeout)


def drive_load(endpoint: str, *, vocab_size: int, prompt_len: int,
               output_len: int, concurrency: int, window_s: float,
               seed: int = 0,
               shared_prefix: Optional[Sequence[int]] = None
               ) -> Dict[str, Any]:
    """Closed-loop load: ``concurrency`` clients, each streaming one
    request at a time, for ``window_s`` seconds. Only requests that
    complete inside the window count (their TTFT/TPOT are client-side
    wall-clock measurements, not server-reported). With
    ``shared_prefix``, every prompt starts with that common token run —
    the shared-system-prompt workload arm."""
    results: List[Tuple[float, float, int]] = []  # (ttft_s, total_s, n_out)
    errors = [0]
    rejected = [0]
    lock = threading.Lock()
    t_start = time.perf_counter()
    stop_at = t_start + window_s

    def client(tid: int) -> None:
        rnd = random.Random(seed * 1000 + tid)
        while time.perf_counter() < stop_at:
            tokens = make_prompt(rnd, vocab_size, prompt_len,
                                 shared_prefix)
            t0 = time.perf_counter()
            try:
                with _post_generate(endpoint, tokens, output_len,
                                    stream=True) as resp:
                    first: Optional[float] = None
                    n_out = 0
                    for line in resp:
                        if first is None:
                            first = time.perf_counter()
                        try:
                            obj = json.loads(line)
                        except ValueError:
                            continue
                        if 'token' in obj:
                            n_out += 1
                        if obj.get('done') or obj.get('error'):
                            break
                t1 = time.perf_counter()
                if first is not None and n_out >= 2 and t1 <= stop_at:
                    with lock:
                        results.append((first - t0, t1 - t0, n_out))
            except urllib.error.HTTPError as e:
                if e.code == 429:
                    # Admission early-reject: expected behavior past the
                    # saturation knee, counted separately from errors.
                    # Honor Retry-After (capped: a closed-loop client
                    # that sleeps out the window measures nothing).
                    with lock:
                        rejected[0] += 1
                    try:
                        delay = float(e.headers.get('Retry-After', '1'))
                    except (TypeError, ValueError):
                        delay = 1.0
                    time.sleep(min(max(delay, 0.1), 2.0))
                else:
                    with lock:
                        errors[0] += 1
                    time.sleep(0.5)
            except (urllib.error.URLError, OSError, ValueError):
                with lock:
                    errors[0] += 1
                time.sleep(0.5)

    threads = [threading.Thread(target=client, args=(i,), daemon=True)
               for i in range(concurrency)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=window_s + 900)

    if not results:
        return {'concurrency': concurrency, 'completed': 0,
                'errors': errors[0], 'rejected': rejected[0],
                'req_per_s': 0.0}
    ttfts = [r[0] * 1e3 for r in results]
    tpots = [(r[1] - r[0]) * 1e3 / (r[2] - 1) for r in results]
    total_out = sum(r[2] for r in results)
    return {
        'concurrency': concurrency,
        'completed': len(results),
        'errors': errors[0],
        'rejected': rejected[0],
        'req_per_s': round(len(results) / window_s, 3),
        'output_tokens_per_s': round(total_out / window_s, 1),
        'ttft_p50_ms': round(_percentile(ttfts, 50), 1),
        'ttft_p99_ms': round(_percentile(ttfts, 99), 1),
        'tpot_p50_ms': round(_percentile(tpots, 50), 2),
        'tpot_p99_ms': round(_percentile(tpots, 99), 2),
    }


def _fetch_stats(endpoint: str) -> Dict[str, Any]:
    try:
        with urllib.request.urlopen(endpoint + '/stats',
                                    timeout=30) as resp:
            return json.loads(resp.read())
    except (urllib.error.URLError, OSError, ValueError):
        return {}


def _prefix_arm(endpoint: str, *, vocab_size: int, prompt_len: int,
                prefix_len: int, output_len: int, concurrency: int,
                window_s: float) -> Dict[str, Any]:
    """Shared-system-prompt arm: N closed-loop clients whose prompts
    share a ``prefix_len`` common prefix. Records the replica's
    prefix-cache hit rate over the arm (delta of the cumulative /stats
    counters, so earlier random-prompt traffic doesn't dilute it) and
    the KV block utilization sampled mid-load (after the window every
    release has freed its blocks and the gauge reads 0)."""
    rnd = random.Random(4242)
    prefix = [rnd.randrange(vocab_size)
              for _ in range(min(prefix_len, prompt_len - 1))]
    before = _fetch_stats(endpoint)
    sweep_box: Dict[str, Any] = {}
    mid: Dict[str, Any] = {}

    def _drive() -> None:
        sweep_box.update(drive_load(
            endpoint, vocab_size=vocab_size, prompt_len=prompt_len,
            output_len=output_len, concurrency=concurrency,
            window_s=window_s, seed=77, shared_prefix=prefix))

    t = threading.Thread(target=_drive, daemon=True)
    t.start()
    time.sleep(window_s * 0.6)
    mid = _fetch_stats(endpoint)
    t.join(timeout=window_s + 900)
    after = _fetch_stats(endpoint)
    out: Dict[str, Any] = {'prefix_len': len(prefix),
                           'sweep': sweep_box}
    d_hit = (after.get('prefix_hit_tokens', 0)
             - before.get('prefix_hit_tokens', 0))
    d_lookup = (after.get('prefix_lookup_tokens', 0)
                - before.get('prefix_lookup_tokens', 0))
    d_admits = (after.get('prefix_lookups', 0)
                - before.get('prefix_lookups', 0))
    # Headline hit rate is over the SHAREABLE tokens (the block-aligned
    # common prefix) per admission: random suffixes can never hit, so a
    # whole-prompt denominator would cap the metric at
    # prefix/prompt_len (~0.82 at the 2048/2500 anchor shape) no matter
    # how well the cache works. Steady-state perfect sharing reads
    # ~1.0 here; the raw all-tokens ratio rides along for context.
    kv_block = int(after.get('kv_block', 0) or 0)
    shareable = (len(prefix) // kv_block) * kv_block if kv_block else 0
    if d_admits > 0 and shareable > 0:
        out['prefix_hit_rate'] = round(
            d_hit / (d_admits * shareable), 4)
    if d_lookup > 0:
        out['prefix_hit_rate_all_tokens'] = round(d_hit / d_lookup, 4)
    if 'kv_block_utilization' in mid:
        out['kv_block_utilization'] = mid['kv_block_utilization']
        out['kv_blocks_total'] = mid.get('kv_blocks_total')
    return out


def _bench_service(*, task, service_name: str, vocab_size: int,
                   prompt_len: int, output_len: int,
                   concurrencies: Sequence[int], window_s: float,
                   warmup_requests: int, ready_timeout_s: float,
                   warmup_deadline_s: float, prefix_share_len: int = 0,
                   progress=None) -> Dict[str, Any]:
    """Stand up ONE serve stack for ``task`` on the local cloud, warm the
    replica through the LB, sweep concurrency, fetch the replica's
    /stats, tear down. Returns {'sweep', 'warmup_failed', 'stats'};
    ``progress(sweep_so_far)`` persists partial results."""
    from skypilot_tpu.serve import core as serve_core
    from skypilot_tpu.serve import serve_state
    ReplicaStatus = serve_state.ReplicaStatus

    out: Dict[str, Any] = {'sweep': [], 'warmup_failed': False,
                           'stats': {}, 'metrics': {}, 'prefix': {}}
    result = serve_core.up(task, service_name)
    endpoint = result['endpoint']
    try:
        deadline = time.time() + ready_timeout_s
        while time.time() < deadline:
            ready = [r for r in serve_state.list_replicas(service_name)
                     if r['status'] == ReplicaStatus.READY]
            if ready:
                break
            time.sleep(2.0)
        else:
            raise TimeoutError(
                f'no READY replica within {ready_timeout_s}s')

        # Warmup THROUGH the LB: the first full-length request compiles the
        # big prefill bucket + insert (or the chunk variants); repeats hit
        # the LB sync + caches. Per-attempt timeout + overall deadline: a
        # READY-but-wedged chip (degraded tunnel) must fail the phase in
        # minutes, not hang the whole bench on 30 x 15-minute request
        # timeouts.
        if progress is not None:
            progress([])  # replica READY: persist the config fields
        rnd = random.Random(7)
        warm_deadline = time.time() + warmup_deadline_s
        warmed = False
        for i in range(max(1, warmup_requests)):
            tokens = [rnd.randrange(vocab_size)
                      for _ in range(prompt_len)]
            # Last warmup request goes through the STREAMING path — the
            # sweep measures streaming, so its first-hit costs (chunked
            # response plumbing, emitter flush cadence) must be paid
            # here, not inside the first measured window.
            stream = i == max(1, warmup_requests) - 1
            for attempt in range(30):
                if time.time() > warm_deadline:
                    raise TimeoutError('serve warmup never completed '
                                       '(chip wedged or replica hung)')
                try:
                    with _post_generate(endpoint, tokens,
                                        min(output_len, 16),
                                        stream=stream,
                                        timeout=180) as resp:
                        resp.read()
                    warmed = True
                    break
                except (urllib.error.URLError, OSError):
                    time.sleep(2.0)  # LB may not have synced the replica
        if not warmed:
            # Every attempt failed but the deadline never fired (e.g. fast
            # connection-refused loops): the sweep below would fold compile
            # time into TTFT/TPOT. Record it so the numbers are legible.
            out['warmup_failed'] = True
            print('serve bench WARNING: warmup exhausted all attempts '
                  'without a successful request; sweep numbers include '
                  'compile time', file=sys.stderr)

        if warmed and concurrencies:
            # Discarded burn-in at the first sweep's concurrency: the
            # r5 full run showed the FIRST measured window absorbing
            # one-time costs the single-request warmup can't reach
            # (per-client LB connections, admission queue filling to
            # steady state) — c24-first read TTFT p50 3.0s + 2 errors
            # while c48-second read 2.2s + 0. ~15s of load washes that
            # out of the measured numbers.
            burn = drive_load(endpoint, vocab_size=vocab_size,
                              prompt_len=prompt_len,
                              output_len=output_len,
                              concurrency=concurrencies[0],
                              window_s=15.0, seed=999)
            print(f'serve bench burn-in (discarded): {burn}',
                  file=sys.stderr)

        sweep: List[Dict[str, Any]] = []
        for conc in concurrencies:
            stats = drive_load(endpoint, vocab_size=vocab_size,
                               prompt_len=prompt_len,
                               output_len=output_len, concurrency=conc,
                               window_s=window_s, seed=conc)
            print(f'serve bench [{service_name}] @ concurrency {conc}: '
                  f'{stats}', file=sys.stderr)
            sweep.append(stats)
            if progress is not None:
                progress(sweep)
        out['sweep'] = sweep
        if warmed and prefix_share_len > 0 and concurrencies:
            # Shared-prefix workload arm at the sweep's top concurrency:
            # the prefix-cache acceptance measurement (hit rate > 0.9 at
            # the r05 shape) rides the same service instance.
            out['prefix'] = _prefix_arm(
                endpoint, vocab_size=vocab_size, prompt_len=prompt_len,
                prefix_len=prefix_share_len, output_len=output_len,
                concurrency=max(concurrencies), window_s=window_s)
            print(f'serve bench [{service_name}] shared-prefix arm: '
                  f"{out['prefix']}", file=sys.stderr)
        # Replica counters through the LB proxy: the rejected count is
        # the admission-control acceptance signal.
        try:
            with urllib.request.urlopen(endpoint + '/stats',
                                        timeout=30) as resp:
                out['stats'] = json.loads(resp.read())
        except (urllib.error.URLError, OSError, ValueError):
            pass
        # Replica /metrics scraped DIRECTLY (the LB answers /metrics
        # itself): server-side ttft/tpot/queue-wait histograms + the
        # recompile counter land in the BENCH record next to the
        # client-side sweep.
        try:
            replica_url = next(
                (r['url'] for r in serve_state.list_replicas(service_name)
                 if r['status'] == ReplicaStatus.READY and r['url']),
                None)
            if replica_url:
                with urllib.request.urlopen(
                        replica_url.rstrip('/') + '/metrics',
                        timeout=30) as resp:
                    out['metrics'] = summarize_metrics_text(
                        resp.read().decode('utf-8', 'replace'))
        except (urllib.error.URLError, OSError, ValueError):
            pass
    finally:
        try:
            serve_core.down(service_name)
        except Exception as e:  # noqa: BLE001 — bench must not die on teardown
            print(f'serve bench WARNING: teardown of {service_name} '
                  f'failed ({e}); replicas may still be running',
                  file=sys.stderr)
    return out


def run(*, preset: str = 'llama-1b', batch_slots: int = 16,
        max_len: int = 4096, prompt_len: int = 2500, output_len: int = 150,
        concurrencies: Sequence[int] = (8, 24), window_s: float = 75.0,
        warmup_requests: int = 2, ready_timeout_s: float = 900.0,
        warmup_deadline_s: Optional[float] = None,
        service_name: str = 'bench-serve',
        progress=None, prefill_chunk: int = 0, ttft_slo_ms: float = 0.0,
        ab_monolithic: bool = False, prefix_share_len: int = 0,
        kv_block: Optional[int] = None,
        kv_blocks: Optional[int] = None,
        spec_tokens: Optional[int] = None,
        kv_dtype: Optional[str] = None) -> Dict[str, Any]:
    """Serve-path sweep, optionally A/B'd chunked-vs-monolithic.

    The headline service runs with ``prefill_chunk``/``ttft_slo_ms``
    (env-configured on the replica: $SKYTPU_PREFILL_CHUNK +
    $SKYTPU_TTFT_SLO_MS). With ``ab_monolithic`` and a nonzero chunk, a
    monolithic-prefill control service runs the SAME sweep first and its
    points land in ``serve_sweep_monolithic`` + the per-concurrency
    ``serve_ttft_p99_ab`` table — the record carries the A/B, not just
    the winner. Returns the sweep plus the best-throughput point
    flattened into ``serve_*`` fields (the BENCH record contract).

    ``prefix_share_len`` > 0 appends a shared-system-prompt arm (all
    prompts behind one ``prefix_share_len``-token prefix) to the
    headline service and records ``serve_prefix_hit_rate`` +
    ``serve_kv_block_utilization``. ``kv_block``/``kv_blocks``
    (replica $SKYTPU_KV_BLOCK/$SKYTPU_KV_BLOCKS) pin the paged-KV pool
    geometry — size ``kv_blocks`` below slots x max_len to measure
    block-budget admission under a fixed HBM budget. ``spec_tokens``
    (replica $SKYTPU_SPEC_TOKENS) pins the speculative draft length;
    pass 0 for the plain-step oracle arm, and read the resulting
    accept yield from ``skytpu_engine_spec_accept_tokens`` (mean =
    accepted tokens per verify step) in the replica metrics summary.
    ``kv_dtype`` (replica $SKYTPU_KV_DTYPE) selects the KV storage
    dtype — run an ``int8`` arm at doubled ``kv_blocks`` to capture
    bf16-vs-int8 under the same HBM budget in one sweep."""
    import skypilot_tpu as sky
    from skypilot_tpu.models.llama import PRESETS
    from skypilot_tpu.serve import service_spec as spec_lib

    config = PRESETS[preset]
    if warmup_deadline_s is None:
        warmup_deadline_s = max(300.0, ready_timeout_s / 2)

    def make_task(chunk: int, slo_ms: float):
        # No --port: the replica reads $SKYTPU_SERVE_REPLICA_PORT
        # assigned by the replica manager (local replicas each get their
        # own free port).
        envs = {}
        if chunk:
            envs['SKYTPU_PREFILL_CHUNK'] = str(int(chunk))
        if slo_ms:
            envs['SKYTPU_TTFT_SLO_MS'] = str(float(slo_ms))
        if kv_block is not None:
            envs['SKYTPU_KV_BLOCK'] = str(int(kv_block))
        if kv_blocks is not None:
            envs['SKYTPU_KV_BLOCKS'] = str(int(kv_blocks))
        if spec_tokens is not None:
            envs['SKYTPU_SPEC_TOKENS'] = str(int(spec_tokens))
        if kv_dtype is not None:
            envs['SKYTPU_KV_DTYPE'] = str(kv_dtype)
        task = sky.Task(
            run=(f'{sys.executable} -m '
                 'skypilot_tpu.serve.generation_server '
                 f'--preset {preset} '
                 f'--batch-slots {batch_slots} --max-len {max_len}'),
            envs=envs or None)
        task.set_resources([sky.Resources(cloud='local')])
        task.set_service(spec_lib.ServiceSpec.from_yaml_config({
            'readiness_probe': {'path': '/health',
                                'initial_delay_seconds':
                                    int(ready_timeout_s),
                                'timeout_seconds': 5},
            'replica_policy': {'min_replicas': 1, 'max_replicas': 1},
        }))
        return task

    out: Dict[str, Any] = {
        'serve_model_params': int(config.num_params),
        'serve_model_params_b': round(config.num_params / 1e9, 3),
        'serve_prompt_len': prompt_len,
        'serve_output_len': output_len,
        'serve_batch_slots': batch_slots,
        'serve_prefill_chunk': prefill_chunk,
        'serve_ttft_slo_ms': ttft_slo_ms,
        'serve_prefix_share_len': prefix_share_len,
    }
    if kv_block is not None:
        out['serve_kv_block'] = kv_block
    if kv_blocks is not None:
        out['serve_kv_blocks'] = kv_blocks
    if spec_tokens is not None:
        out['serve_spec_tokens'] = spec_tokens
    if kv_dtype is not None:
        out['serve_kv_dtype'] = kv_dtype

    def sub_progress(field: str):
        if progress is None:
            return None

        def cb(sweep):
            progress({**out, field: sweep} if sweep else dict(out))
        return cb

    common = dict(vocab_size=config.vocab_size, prompt_len=prompt_len,
                  output_len=output_len, concurrencies=concurrencies,
                  window_s=window_s, warmup_requests=warmup_requests,
                  ready_timeout_s=ready_timeout_s,
                  warmup_deadline_s=warmup_deadline_s)
    if ab_monolithic and prefill_chunk:
        # The control arm is context, not the measurement: an infra
        # flake here (replica never READY, warmup timeout) must not
        # abort the headline chunked arm below.
        try:
            mono = _bench_service(task=make_task(0, 0.0),
                                  service_name=service_name + '-mono',
                                  progress=sub_progress(
                                      'serve_sweep_monolithic'),
                                  **common)
        except Exception as e:  # noqa: BLE001
            out['serve_mono_error'] = f'{type(e).__name__}: {e}'
            print(f'serve bench WARNING: monolithic control arm failed '
                  f'({e}); continuing to the chunked arm',
                  file=sys.stderr)
        else:
            out['serve_sweep_monolithic'] = mono['sweep']
            if mono['warmup_failed']:
                out['serve_mono_warmup_failed'] = True

    main = _bench_service(task=make_task(prefill_chunk, ttft_slo_ms),
                          service_name=service_name,
                          progress=sub_progress('serve_sweep'),
                          prefix_share_len=prefix_share_len, **common)
    sweep = main['sweep']
    out['serve_sweep'] = sweep
    if main['warmup_failed']:
        out['serve_warmup_failed'] = True
    if main.get('metrics'):
        out['serve_replica_metrics_summary'] = main['metrics']
    if main.get('prefix'):
        prefix = main['prefix']
        out['serve_prefix_sweep'] = prefix.get('sweep', {})
        if 'prefix_hit_rate' in prefix:
            out['serve_prefix_hit_rate'] = prefix['prefix_hit_rate']
        if 'kv_block_utilization' in prefix:
            out['serve_kv_block_utilization'] = (
                prefix['kv_block_utilization'])
    if main['stats']:
        out['serve_rejected'] = main['stats'].get('rejected', 0)
        out['serve_replica_stats'] = {
            k: main['stats'][k]
            for k in ('requests', 'rejected', 'queue_depth',
                      'prefill_chunk', 'ttft_slo_ms',
                      'prefill_tokens_per_s', 'kv_block',
                      'kv_blocks_total', 'prefix_hits',
                      'prefix_hit_rate', 'prefix_evictions')
            if k in main['stats']}
    if out.get('serve_sweep_monolithic'):
        # Per-concurrency TTFT p99 A/B: the acceptance signal that
        # chunked+admission never regresses past the monolithic control.
        mono_by_c = {s['concurrency']: s
                     for s in out['serve_sweep_monolithic']}
        out['serve_ttft_p99_ab'] = [
            {'concurrency': s['concurrency'],
             'monolithic_ms': mono_by_c.get(s['concurrency'],
                                            {}).get('ttft_p99_ms'),
             'chunked_ms': s.get('ttft_p99_ms')}
            for s in sweep]
    if sweep:
        best = max(sweep, key=lambda s: s.get('req_per_s', 0.0))
        if best.get('completed'):
            out.update({
                'serve_req_per_s': best['req_per_s'],
                'serve_output_tokens_per_s': best['output_tokens_per_s'],
                'serve_ttft_p50_ms': best['ttft_p50_ms'],
                'serve_ttft_p99_ms': best['ttft_p99_ms'],
                'serve_tpot_p50_ms': best['tpot_p50_ms'],
                'serve_tpot_p99_ms': best['tpot_p99_ms'],
                'serve_concurrency': best['concurrency'],
            })
    return out


def equivalence_estimate(measured_req_per_s: float, model_params: float,
                         chip_kind: str) -> Dict[str, Any]:
    """Bandwidth-scaling estimate of the measured rate at the reference
    anchor's scale (Llama-2-7B, 6.74e9 params, on 8x v6e).

    Decode on TPU is HBM-bandwidth-bound (weights + KV read per token), so
    req/s scales ~ (aggregate bandwidth) / (params). Prefill is
    compute-bound and scales faster on v6e, so this under-counts the
    anchor hardware's advantage — i.e. the estimate is conservative.
    Clearly an ESTIMATE: reported next to, never instead of, the raw
    measured numbers.
    """
    bw = {'TPU v5e': 819, 'TPU v5 lite': 819, 'TPU v5p': 2765,
          'TPU v6e': 1640, 'TPU v6 lite': 1640, 'TPU v4': 1228,
          'TPU v3': 900}
    chip_bw = next((v for k, v in bw.items() if chip_kind.startswith(k)),
                   819)
    anchor_bw = 8 * 1640.0  # v6e-8
    anchor_params = 6.74e9  # Llama-2-7B
    scale = (anchor_bw / chip_bw) * (model_params / anchor_params)
    return {
        'serve_7b_v6e8_equiv_req_per_s': round(
            measured_req_per_s * scale, 2),
        'serve_equiv_note': ('bandwidth-scaling estimate to the anchor '
                             'scale (7B on v6e-8); prefill compute not '
                             'scaled, so conservative'),
    }
