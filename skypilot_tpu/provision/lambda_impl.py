"""Lambda Cloud provisioner: GPU VM host groups (terminate-only lifecycle).

Counterpart of reference ``sky/provision/lambda_cloud/instance.py`` —
the fourth VM cloud exercising the functional provision API, and the
first with a *reduced* lifecycle: Lambda cannot stop instances
(terminate-only, reference instance.py:161-167 raises on stop), has no
zones, no spot, and account-global firewall rules rather than per-cluster
security groups (reference instance.py:330-351 skips cleanup for this
reason).

TPU-native deltas vs the reference module:
- rank discovery is stateless via instance names ``{name}-r{rank}``
  (Lambda has no tags; the reference keeps a local metadata file +
  '-head'/'-worker' suffixes — a name-encoded rank needs no local state
  and survives client-machine loss);
- capacity errors (``insufficient-capacity`` codes) are classified into
  ``InsufficientCapacityError`` so ``RetryingProvisioner`` region-failover
  drives Lambda exactly like GCP/AWS/Azure.

Cluster bookkeeping (region, name-on-cloud) lives in the client state
kv, mirroring ``provision/azure.py``.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import authentication
from skypilot_tpu import exceptions
from skypilot_tpu import provision as provision_lib
from skypilot_tpu.provision import lambda_api
from skypilot_tpu.provision import rest_cloud
from skypilot_tpu.utils import command_runner as runner_lib

SSH_USER = 'ubuntu'  # canonical Lambda login

# Lambda instance statuses -> the provision API's state words.
_STATE_MAP = {
    'booting': 'pending',
    'active': 'running',
    'unhealthy': 'pending',   # transient per API docs; wait_instances polls
    'terminating': 'terminating',
    'terminated': 'terminated',
}

# The firewall-rules API is not offered in this region (reference
# instance.py:270-276): opening ports there is a warning, not an error.
_NO_FIREWALL_REGIONS = ('us-south-1',)


# Cluster bookkeeping + rank decoding via the shared REST-cloud
# scaffolding (rest_cloud.py).
_records = rest_cloud.ClusterRecords('lambda_cluster')


def _live_instances(client, name: str,
                    region: Optional[str] = None
                    ) -> Dict[int, Dict[str, Any]]:
    """rank -> instance, excluding terminated/terminating. The API is
    ACCOUNT-global (not region-scoped like the AWS/Azure clients), so a
    region filter is required wherever a leaked instance from a
    failed-over region must not be adopted into the current gang."""
    out: Dict[int, Dict[str, Any]] = {}
    for inst in lambda_api.call(client, 'list_instances'):
        rank = rest_cloud.rank_of(inst.get('name') or '', name)
        if rank is None:
            continue
        if inst.get('status') in ('terminated', 'terminating'):
            continue
        if region is not None and (
                (inst.get('region') or {}).get('name') or region) != region:
            continue
        out[rank] = inst
    return out


def _ensure_ssh_key(client) -> str:
    """Register the local public key with Lambda if absent; returns the
    key name to launch with (reference lambda_utils.get_unique_ssh_key_name
    + register_ssh_key)."""
    _, pub_path = authentication.get_or_generate_keys()
    with open(pub_path, encoding='utf-8') as f:
        pub_key = f.read().strip()
    keys = lambda_api.call(client, 'list_ssh_keys')
    for key in keys:
        if (key.get('public_key') or '').strip() == pub_key:
            return key['name']
    taken = {key.get('name') for key in keys}
    key_name = 'skytpu'
    idx = 0
    while key_name in taken:
        idx += 1
        key_name = f'skytpu-{idx}'
    lambda_api.call(client, 'register_ssh_key', name=key_name,
                    public_key=pub_key)
    return key_name


# ---- provision API ---------------------------------------------------------
def run_instances(cluster_name: str, region: str, zone: Optional[str],
                  num_hosts: int, deploy_vars: Dict[str, Any]) -> None:
    del zone  # Lambda has no zones
    name = deploy_vars['cluster_name_on_cloud']
    record = {'region': region, 'zone': None, 'name_on_cloud': name,
              'num_hosts': num_hosts, 'deploy_vars': deploy_vars}
    # Record BEFORE creating (partial-failure resources must stay
    # reachable by terminate_instances; same contract as provision/gcp.py).
    _records.save(cluster_name, record)
    client = lambda_api.get_client()
    try:
        key_name = _ensure_ssh_key(client)
        existing = _live_instances(client, name, region)
        for rank in range(num_hosts):
            if rank in existing:
                continue  # idempotent relaunch
            lambda_api.call(
                client, 'launch',
                region=region,
                instance_type=deploy_vars.get('instance_type',
                                              'gpu_1x_a10'),
                name=f'{name}-r{rank}',
                ssh_key_names=[key_name],
                quantity=1)
    except exceptions.InsufficientCapacityError:
        # Clean up partial hosts, then drop the record so region failover
        # retries don't see a stale pointer. If cleanup itself failed,
        # KEEP the record: a later terminate_instances must still be able
        # to find and kill the leaked hosts.
        try:
            _terminate_all(client, name)
        except exceptions.CloudError:
            pass
        else:
            _records.delete(cluster_name)
        raise


def wait_instances(cluster_name: str, region: str, state: str = 'running',
                   timeout: float = 1800) -> None:
    if state != 'running':
        raise exceptions.NotSupportedError(
            'Lambda Cloud cannot stop instances (terminate-only).')
    rest_cloud.poll_for_state(
        cluster_name, lambda: query_instances(cluster_name, region),
        state, timeout)


def query_instances(cluster_name: str, region: str) -> Dict[str, str]:
    """Live host states. A PARTIALLY-dead cluster reports missing ranks
    as 'terminated'; a fully-dead cluster returns {} ("terminated
    cluster" contract in core.py)."""
    del region
    record = _records.load(cluster_name)
    if not record:
        return {}
    client = lambda_api.get_client()
    live = _live_instances(client, record['name_on_cloud'],
                           record.get('region'))
    if not live:
        return {}
    out: Dict[str, str] = {}
    for rank, inst in live.items():
        out[inst.get('name', f'r{rank}')] = _STATE_MAP.get(
            inst.get('status', ''), 'unknown')
    for rank in range(int(record.get('num_hosts') or 0)):
        if rank not in live:
            out[f'rank{rank}-missing'] = 'terminated'
    return out


def stop_instances(cluster_name: str, region: str) -> None:
    raise exceptions.NotSupportedError(
        'Lambda Cloud cannot stop instances (terminate-only); '
        'use `skytpu down` instead.')


def _terminate_all(client, name: str) -> None:
    ids = [inst['id'] for inst in _live_instances(client, name).values()
           if inst.get('id')]
    if ids:
        lambda_api.call(client, 'terminate', instance_ids=ids)


def terminate_instances(cluster_name: str, region: str) -> None:
    del region
    record = _records.load(cluster_name)
    if not record:
        return
    client = lambda_api.get_client()
    _terminate_all(client, record['name_on_cloud'])
    # Account-global firewall rules are left in place deliberately
    # (other clusters may use them; reference instance.py:330-351).
    _records.delete(cluster_name)


def get_cluster_info(cluster_name: str,
                     region: str) -> provision_lib.ClusterInfo:
    del region
    record = _records.require(cluster_name, 'Lambda')
    client = lambda_api.get_client()
    live = _live_instances(client, record['name_on_cloud'],
                           record.get('region'))
    hosts: List[provision_lib.HostInfo] = []
    # "Single host" is what was PROVISIONED, not what happens to be
    # alive: a half-dead gang must not get the loopback fallback.
    single = int(record.get('num_hosts') or 0) == 1
    for rank in sorted(live):
        inst = live[rank]
        # The API may omit private_ip (reference instance.py:56-68):
        # loopback is fine for a single host, fatal for a gang.
        internal = inst.get('private_ip')
        if internal is None:
            if not single:
                raise exceptions.ProvisionError(
                    f'No private IP for {inst.get("name")!r} — multi-host '
                    'rendezvous needs one.')
            internal = '127.0.0.1'
        hosts.append(provision_lib.HostInfo(
            host_id=inst.get('id', f'r{rank}'), rank=rank,
            internal_ip=internal,
            external_ip=inst.get('ip'),
            extra={}))
    return provision_lib.ClusterInfo(
        cluster_name=cluster_name, cloud='lambda',
        region=record['region'], zone=None, hosts=hosts,
        deploy_vars=record['deploy_vars'])


def open_ports(cluster_name: str, region: str, ports: List[str]) -> None:
    """Append tcp allow rules to the ACCOUNT-global firewall (PUT
    replaces the whole rule set, so existing rules are re-sent).
    Idempotent: already-open port ranges are skipped."""
    if not ports:
        return
    record = _records.require(cluster_name, 'Lambda')
    if record['region'] in _NO_FIREWALL_REGIONS:
        import logging
        logging.getLogger(__name__).warning(
            'Lambda region %s does not support firewall rules; ports %s '
            'not opened.', record['region'], ports)
        return
    client = lambda_api.get_client()
    existing = lambda_api.call(client, 'list_firewall_rules')
    rules = []
    have = set()
    for rule in existing:
        entry = {
            'protocol': rule.get('protocol', 'tcp'),
            'source_network': rule.get('source_network', '0.0.0.0/0'),
            'description': rule.get('description', ''),
        }
        pr = rule.get('port_range')
        if pr and rule.get('protocol') != 'icmp':
            entry['port_range'] = list(pr)
            have.add((entry['protocol'], tuple(pr)))
        rules.append(entry)
    changed = False
    for port in sorted(ports, key=str):
        if '-' in str(port):
            lo, hi = (int(p) for p in str(port).split('-', 1))
        else:
            lo = hi = int(port)
        if ('tcp', (lo, hi)) in have:
            continue
        rules.append({
            'protocol': 'tcp',
            'source_network': '0.0.0.0/0',
            'description': f'skytpu port {lo}-{hi}',
            'port_range': [lo, hi],
        })
        changed = True
    if changed:
        lambda_api.call(client, 'put_firewall_rules', rules=rules)


def get_command_runners(cluster_info: provision_lib.ClusterInfo,
                        ssh_credentials: Optional[Dict[str, str]] = None
                        ) -> List[runner_lib.CommandRunner]:
    return rest_cloud.ssh_runners(cluster_info, SSH_USER, ssh_credentials)
