"""OCI provisioner: Core compute instances (tag-scoped, spot via
preemptible config, NSG ports).

Counterpart of reference ``sky/provision/oci/`` (instance CRUD over the
oci SDK; VCN machinery in query_utils). Eleventh VM cloud — the fourth
enterprise cloud (after GCP/AWS/Azure) and the only one whose transport
carries real HTTP request signing in-tree (oci_api._Signer).

OCI-isms:
- instances are discovered by FREEFORM TAGS inside a compartment
  (``$SKYTPU_OCI_COMPARTMENT`` or config ``oci.compartment_ocid``,
  defaulting to the tenancy root);
- networking: OCI requires an existing subnet — configure
  ``oci.subnet_ocid`` (creating a VCN/IGW/route-table chain implicitly
  is a lot of invisible account mutation; the reference does it, we
  choose an explicit, documented prerequisite + an actionable error);
- ports are a per-cluster NSG attached at launch (rules added by
  open_ports — the NSG model also used on Azure, but attached to
  vnics, not subnets);
- ``use_spot`` sets preemptibleInstanceConfig (TERMINATE on
  preemption): a reclaimed instance disappears, surfacing as a rank
  hole exactly like RunPod spot;
- stop/start supported (standard shapes don't bill compute stopped);
- "Out of host capacity." classifies as capacity -> AD/region failover.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import authentication
from skypilot_tpu import exceptions
from skypilot_tpu import provision as provision_lib
from skypilot_tpu.provision import oci_api
from skypilot_tpu.provision import rest_cloud
from skypilot_tpu.utils import command_runner as runner_lib

SSH_USER = 'ubuntu'

# Canonical Ubuntu 22.04 platform image alias; a real deployment pins
# an image OCID via resources.image_id.
DEFAULT_IMAGE = 'ubuntu-22.04'

_TAG_CLUSTER = 'skytpu-cluster'
_TAG_RANK = 'skytpu-rank'

# OCI lifecycle states -> provision API state words.
_STATE_MAP = {
    'PROVISIONING': 'pending',
    'STARTING': 'pending',
    'RUNNING': 'running',
    'STOPPING': 'stopping',
    'STOPPED': 'stopped',
    'TERMINATING': 'terminating',
    'TERMINATED': 'terminated',
}

# Cluster bookkeeping via the shared REST-cloud scaffolding.
_records = rest_cloud.ClusterRecords('oci_cluster')


def _compartment(client) -> str:
    import os
    env = os.environ.get('SKYTPU_OCI_COMPARTMENT')
    if env:
        return env
    from skypilot_tpu import config as config_lib
    cfg = config_lib.get_nested(('oci', 'compartment_ocid'), None)
    if cfg:
        return str(cfg)
    return client.tenancy  # root compartment fallback


def _subnet(client) -> str:
    import os
    env = os.environ.get('SKYTPU_OCI_SUBNET')
    if env:
        return env
    from skypilot_tpu import config as config_lib
    sub = config_lib.get_nested(('oci', 'subnet_ocid'), None)
    if not sub:
        raise exceptions.CloudError(
            'OCI needs an existing subnet: set oci.subnet_ocid in the '
            'skytpu config (or $SKYTPU_OCI_SUBNET). Create one with '
            '`oci network vcn create` + `oci network subnet create`, '
            'or reuse your tenancy default VCN subnet.')
    return str(sub)


def _nsg_name(name_on_cloud: str) -> str:
    return f'skytpu-{name_on_cloud}-nsg'


def _resolve_ad(client, compartment: str, region: str,
                zone: Optional[str]) -> str:
    """Map a catalog zone to a REAL availability-domain name.

    Real OCI AD names are tenancy-prefixed ('qIZq:US-ASHBURN-1-AD-2');
    the catalog's synthetic '{region}-AD-n' zones (and the old
    '{region}-AD-1' fallback) are NOT valid launch arguments. Resolve
    via the identity list-ADs call: a synthetic zone matches the AD
    whose name ends with its 'AD-n' suffix; no zone picks the first AD.
    A zone that is already tenancy-prefixed (contains ':') passes
    through. A synthetic suffix with no matching AD (e.g. AD-3 in a
    single-AD region) raises a capacity-class error so the provisioner
    fails over to the next zone instead of sending a 404-bound launch.

    Test fakes that don't implement the identity op keep the legacy
    synthetic behavior (their launch_instance accepts any name).
    """
    if zone and ':' in zone:
        return zone
    if not hasattr(client, 'list_availability_domains'):
        return zone or f'{region}-AD-1'
    ads = oci_api.call(client, 'list_availability_domains',
                       compartment_id=compartment)
    names = [a.get('name') for a in ads if a.get('name')]
    if not names:
        raise exceptions.CloudError(
            f'OCI identity returned no availability domains for '
            f'region {region} (compartment {compartment})')
    if zone is None:
        return names[0]
    # 'us-ashburn-1-AD-2' -> suffix 'AD-2'; exact-name zones also hit
    # this path and match themselves case-insensitively.
    z = zone.upper()
    suffix = 'AD-' + z.rsplit('AD-', 1)[-1] if 'AD-' in z else z
    for name in names:
        if name.upper().endswith(suffix):
            return name
    raise exceptions.InsufficientCapacityError(
        f'OCI availability domain for zone {zone!r} not found in '
        f'region {region} (tenancy has: {names})')


def _live_instances(client, compartment: str,
                    name: str) -> Dict[int, Dict[str, Any]]:
    """rank -> instance by freeform tags (compartment-scoped; tags are
    the authority, display names are not unique on OCI)."""
    out: Dict[int, Dict[str, Any]] = {}
    for inst in oci_api.call(client, 'list_instances',
                             compartment_id=compartment):
        tags = inst.get('freeformTags') or {}
        if tags.get(_TAG_CLUSTER) != name:
            continue
        if inst.get('lifecycleState') in ('TERMINATING', 'TERMINATED'):
            continue
        rank_tag = tags.get(_TAG_RANK)
        if rank_tag is None or not str(rank_tag).isdigit():
            continue
        out[int(rank_tag)] = inst
    return out


def _ensure_nsg(client, compartment: str, subnet_id: str,
                name: str) -> str:
    """Per-cluster NSG in the subnet's VCN with SSH open."""
    nsg_name = _nsg_name(name)
    for nsg in oci_api.call(client, 'list_nsgs',
                            compartment_id=compartment):
        if nsg.get('displayName') == nsg_name:
            return nsg['id']
    vcn_id = oci_api.call(client, 'get_subnet',
                          subnet_id=subnet_id).get('vcnId')
    created = oci_api.call(client, 'create_nsg',
                           compartment_id=compartment, vcn_id=vcn_id,
                           name=nsg_name)
    oci_api.call(client, 'add_nsg_rules', nsg_id=created['id'], rules=[{
        'direction': 'INGRESS', 'protocol': '6',  # tcp
        'source': '0.0.0.0/0', 'sourceType': 'CIDR_BLOCK',
        'tcpOptions': {'destinationPortRange': {'min': 22, 'max': 22}},
    }])
    return created['id']


# ---- provision API ---------------------------------------------------------
def run_instances(cluster_name: str, region: str, zone: Optional[str],
                  num_hosts: int, deploy_vars: Dict[str, Any]) -> None:
    name = deploy_vars['cluster_name_on_cloud']
    record = {'region': region, 'zone': zone, 'name_on_cloud': name,
              'num_hosts': num_hosts, 'deploy_vars': deploy_vars}
    _records.save(cluster_name, record)
    client = oci_api.get_client(region)
    compartment = _compartment(client)
    record['compartment'] = compartment
    _records.save(cluster_name, record)
    try:
        subnet_id = _subnet(client)
        nsg_id = _ensure_nsg(client, compartment, subnet_id, name)
        _, pub_path = authentication.get_or_generate_keys()
        with open(pub_path, encoding='utf-8') as f:
            pub_key = f.read().strip()
        # Resolve the catalog zone to the tenancy's real AD name via the
        # identity listing; synthetic '{region}-AD-n' strings are not
        # launchable on the real API.
        ad = _resolve_ad(client, compartment, region, zone)
        existing = _live_instances(client, compartment, name)
        for rank, inst in existing.items():
            if inst.get('lifecycleState') == 'STOPPED':
                oci_api.call(client, 'instance_action',
                             instance_id=inst['id'], action='START')
        shape = deploy_vars.get('instance_type', 'VM.Standard.E4.Flex')
        shape_config = deploy_vars.get('shape_config')
        for rank in range(num_hosts):
            if rank in existing:
                continue  # idempotent relaunch
            oci_api.call(
                client, 'launch_instance',
                compartment_id=compartment,
                name=f'{name}-r{rank}',
                shape=shape,
                shape_config=shape_config,
                availability_domain=ad,
                subnet_id=subnet_id,
                image_id=deploy_vars.get('image_id') or DEFAULT_IMAGE,
                ssh_public_key=pub_key,
                boot_volume_gb=int(deploy_vars.get('disk_size_gb')
                                   or 100),
                freeform_tags={_TAG_CLUSTER: name, _TAG_RANK: str(rank),
                               **{k: str(v) for k, v in
                                  (deploy_vars.get('labels')
                                   or {}).items()}},
                nsg_ids=[nsg_id],
                preemptible=bool(deploy_vars.get('use_spot')))
    except exceptions.InsufficientCapacityError:
        try:
            _terminate_all(client, compartment, name)
        except exceptions.CloudError:
            pass
        else:
            _records.delete(cluster_name)
        raise


def wait_instances(cluster_name: str, region: str, state: str = 'running',
                   timeout: float = 1800) -> None:
    rest_cloud.poll_for_state(
        cluster_name, lambda: query_instances(cluster_name, region),
        state, timeout)


def query_instances(cluster_name: str, region: str) -> Dict[str, str]:
    del region
    record = _records.load(cluster_name)
    if not record:
        return {}
    client = oci_api.get_client(record.get('region'))
    compartment = record.get('compartment') or _compartment(client)
    live = _live_instances(client, compartment, record['name_on_cloud'])
    if not live:
        return {}
    out: Dict[str, str] = {}
    for rank, inst in live.items():
        out[inst.get('displayName', f'r{rank}')] = _STATE_MAP.get(
            inst.get('lifecycleState', ''), 'unknown')
    for rank in range(int(record.get('num_hosts') or 0)):
        if rank not in live:
            # A preempted spot instance TERMINATEs and disappears: the
            # hole classifies as capacity via the shared poll loop.
            out[f'rank{rank}-missing'] = 'terminated'
    return out


def stop_instances(cluster_name: str, region: str) -> None:
    record = _records.require(cluster_name, 'OCI')
    client = oci_api.get_client(record.get('region'))
    compartment = record.get('compartment') or _compartment(client)
    for inst in _live_instances(client, compartment,
                                record['name_on_cloud']).values():
        if inst.get('lifecycleState') in ('PROVISIONING', 'STARTING',
                                          'RUNNING'):
            oci_api.call(client, 'instance_action',
                         instance_id=inst['id'], action='STOP')


def _terminate_all(client, compartment: str, name: str) -> None:
    for inst in _live_instances(client, compartment, name).values():
        oci_api.call(client, 'terminate_instance',
                     instance_id=inst['id'])


def terminate_instances(cluster_name: str, region: str) -> None:
    del region
    record = _records.load(cluster_name)
    if not record:
        return
    client = oci_api.get_client(record.get('region'))
    compartment = record.get('compartment') or _compartment(client)
    name = record['name_on_cloud']
    _terminate_all(client, compartment, name)
    # The per-cluster NSG is cluster-scoped: best-effort delete.
    for nsg in oci_api.call(client, 'list_nsgs',
                            compartment_id=compartment):
        if nsg.get('displayName') == _nsg_name(name):
            try:
                oci_api.call(client, 'delete_nsg', nsg_id=nsg['id'])
            except exceptions.CloudError:
                pass  # vnics may still reference it briefly
    _records.delete(cluster_name)


def get_cluster_info(cluster_name: str,
                     region: str) -> provision_lib.ClusterInfo:
    del region
    record = _records.require(cluster_name, 'OCI')
    client = oci_api.get_client(record.get('region'))
    compartment = record.get('compartment') or _compartment(client)
    live = _live_instances(client, compartment, record['name_on_cloud'])
    hosts: List[provision_lib.HostInfo] = []
    for rank in sorted(live):
        inst = live[rank]
        attachments = oci_api.call(client, 'list_vnic_attachments',
                                   compartment_id=compartment,
                                   instance_id=inst['id'])
        if not attachments:
            raise exceptions.ProvisionError(
                f'No VNIC on instance {inst.get("displayName")!r} yet.')
        vnic = oci_api.call(client, 'get_vnic',
                            vnic_id=attachments[0]['vnicId'])
        private = vnic.get('privateIp')
        if private is None:
            raise exceptions.ProvisionError(
                f'No private IP on {inst.get("displayName")!r} yet.')
        hosts.append(provision_lib.HostInfo(
            host_id=str(inst['id']), rank=rank,
            internal_ip=private,
            external_ip=vnic.get('publicIp'),
            extra={}))
    return provision_lib.ClusterInfo(
        cluster_name=cluster_name, cloud='oci',
        region=record['region'], zone=record.get('zone'), hosts=hosts,
        deploy_vars=record['deploy_vars'])


def open_ports(cluster_name: str, region: str, ports: List[str]) -> None:
    """Add tcp ingress rules to the per-cluster NSG (idempotent by
    existing-rule port ranges)."""
    if not ports:
        return
    record = _records.require(cluster_name, 'OCI')
    client = oci_api.get_client(record.get('region'))
    compartment = record.get('compartment') or _compartment(client)
    nsg_id = None
    for nsg in oci_api.call(client, 'list_nsgs',
                            compartment_id=compartment):
        if nsg.get('displayName') == _nsg_name(record['name_on_cloud']):
            nsg_id = nsg['id']
            break
    if nsg_id is None:
        raise exceptions.ClusterError(
            f'No NSG for cluster {cluster_name!r} (was it launched?)')
    have = set()
    for rule in oci_api.call(client, 'list_nsg_rules', nsg_id=nsg_id):
        rng = (rule.get('tcpOptions') or {}).get(
            'destinationPortRange') or {}
        if rng:
            # Key includes the SOURCE: a port open for one CIDR must
            # still gain rules for other configured CIDRs.
            have.add((rule.get('source'), rng.get('min'),
                      rng.get('max')))
    from skypilot_tpu import config as config_lib
    ranges = config_lib.get_nested(('oci', 'firewall_source_ranges'),
                                   ['0.0.0.0/0'])
    rules = []
    for port in sorted(ports, key=str):
        if '-' in str(port):
            lo, hi = (int(p) for p in str(port).split('-', 1))
        else:
            lo = hi = int(port)
        for cidr in ranges:
            if (cidr, lo, hi) in have:
                continue
            rules.append({
                'direction': 'INGRESS', 'protocol': '6',
                'source': cidr, 'sourceType': 'CIDR_BLOCK',
                'tcpOptions': {'destinationPortRange': {'min': lo,
                                                        'max': hi}},
            })
    if rules:
        oci_api.call(client, 'add_nsg_rules', nsg_id=nsg_id, rules=rules)


def get_command_runners(cluster_info: provision_lib.ClusterInfo,
                        ssh_credentials: Optional[Dict[str, str]] = None
                        ) -> List[runner_lib.CommandRunner]:
    return rest_cloud.ssh_runners(cluster_info, SSH_USER, ssh_credentials)
