"""GCP provisioner: TPU-VM slices (first-class) + GCE VMs (controllers).

TPU-native design vs reference (sky/provision/gcp/instance_utils.py:1191):
a *slice* provisions as ONE tpu.googleapis.com node (all hosts atomic — the
gang is the slice), via queued resources for v5e/v5p/v6e capacity; per-host
IPs come from the node's ``networkEndpoints`` in stable worker order, which
directly defines SKYTPU_HOST_RANK (no runtime discovery, contrast reference
``num_ips_per_node`` cloud_vm_ray_backend.py:2588-2596).

Cluster→(project, zone, node) bookkeeping lives in the client state kv
(the reference persists the same in cluster YAML files).
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import authentication
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import provision as provision_lib
from skypilot_tpu.provision import gcp_api
from skypilot_tpu.utils import command_runner as runner_lib

_LABEL = 'skytpu-cluster'


def _firewall_tag(name_on_cloud: str) -> str:
    """Network tag carried by every host of a cluster; firewall rules for
    ``open_ports`` target it."""
    return f'skytpu-{name_on_cloud}'


def _firewall_rule_name(name_on_cloud: str) -> str:
    return f'skytpu-{name_on_cloud}-ports'

_TPU_STATE_MAP = {
    'CREATING': 'pending', 'STARTING': 'pending', 'RESTARTING': 'pending',
    'REPAIRING': 'pending', 'READY': 'running', 'STOPPING': 'stopping',
    'STOPPED': 'stopped', 'DELETING': 'terminating', 'PREEMPTED': 'preempted',
    'TERMINATED': 'terminated',
}
_GCE_STATE_MAP = {
    'PROVISIONING': 'pending', 'STAGING': 'pending', 'RUNNING': 'running',
    'STOPPING': 'stopping', 'TERMINATED': 'stopped', 'SUSPENDED': 'stopped',
}


# ---- cluster record --------------------------------------------------------
def _record_key(cluster_name: str) -> str:
    return f'gcp_cluster/{cluster_name}'


def _save_record(cluster_name: str, record: Dict[str, Any]) -> None:
    global_user_state.set_kv(_record_key(cluster_name), json.dumps(record))


def _load_record(cluster_name: str) -> Optional[Dict[str, Any]]:
    raw = global_user_state.get_kv(_record_key(cluster_name))
    return json.loads(raw) if raw else None


def _delete_record(cluster_name: str) -> None:
    global_user_state.set_kv(_record_key(cluster_name), '')


# ---- provision API ---------------------------------------------------------
def _slice_names(name: str, num_slices: int) -> List[str]:
    """Per-slice TPU node names: bare name single-slice (back-compat),
    ``{name}-s{i}`` for a multi-slice gang."""
    if num_slices <= 1:
        return [name]
    return [f'{name}-s{i}' for i in range(num_slices)]


def run_instances(cluster_name: str, region: str, zone: Optional[str],
                  num_hosts: int, deploy_vars: Dict[str, Any]) -> None:
    assert zone is not None, 'GCP provisioning is zonal'
    project = deploy_vars['project_id']
    mode = deploy_vars.get('mode', 'tpu_vm')
    name = deploy_vars['cluster_name_on_cloud']
    num_slices = int(deploy_vars.get('num_slices') or 1) \
        if mode == 'tpu_vm' else 1
    record = {'project': project, 'zone': zone, 'mode': mode,
              'name_on_cloud': name, 'num_hosts': num_hosts,
              'num_slices': num_slices, 'deploy_vars': deploy_vars}
    # Record BEFORE the create calls: if creation partially succeeds and
    # then raises (operation timeout, second GCE insert failing), the
    # billing resources must remain reachable by terminate_instances.
    _save_record(cluster_name, record)
    try:
        if mode == 'tpu_vm':
            _run_tpu_slices(project, zone, name, num_slices, deploy_vars)
        else:
            _run_gce_instances(project, zone, name, num_hosts, deploy_vars)
    except exceptions.InsufficientCapacityError:
        # Clean failure: nothing was created; drop the record so failover
        # retries in another zone don't see a stale pointer.
        _delete_record(cluster_name)
        raise


def _tpu_node_body(name: str, deploy_vars: Dict[str, Any]) -> Dict[str, Any]:
    labels = dict(deploy_vars.get('labels') or {})
    labels[_LABEL] = name
    network = deploy_vars.get('network') or 'default'
    network_config: Dict[str, Any] = {'enableExternalIps': True,
                                      'network': network}
    if deploy_vars.get('subnetwork'):
        # Custom-mode VPCs reject creation without an explicit subnetwork.
        network_config['subnetwork'] = deploy_vars['subnetwork']
    body: Dict[str, Any] = {
        'acceleratorType': deploy_vars['accelerator_type'],
        'runtimeVersion': deploy_vars['runtime_version'],
        'networkConfig': network_config,
        # Network tag keyed on the CLUSTER (not the per-slice node name):
        # open_ports firewall rules target it (reference tags clusters the
        # same way, sky/provision/gcp/instance.py open_ports).
        'tags': [_firewall_tag(deploy_vars['cluster_name_on_cloud'])],
        'labels': labels,
        'metadata': {'ssh-keys': authentication.gcp_ssh_keys_metadata()},
        'schedulingConfig': {
            'preemptible': bool(deploy_vars.get('use_spot')),
            'reserved': bool(deploy_vars.get('reserved')),
        },
    }
    return body


def _run_tpu_slices(project: str, zone: str, name: str, num_slices: int,
                    deploy_vars: Dict[str, Any]) -> None:
    """Create the cluster's TPU slice node(s).

    Multi-slice (num_slices > 1) uses ONE queued resource carrying N
    nodeSpecs — the TPU API's atomic multi-slice grant: capacity for the
    whole gang is allocated together or not at all, so there is never a
    half-provisioned gang holding quota (the reference has no analog; its
    closest is per-VM ray-up retries, sky/provision/gcp/instance.py).
    """
    tpu = gcp_api.TpuClient(project)
    slice_names = _slice_names(name, num_slices)
    nodes = {n: tpu.get_node(zone, n) for n in slice_names}
    missing = [n for n, node in nodes.items() if node is None]
    pending_ops = []
    for n, node in nodes.items():
        if node is None:
            continue
        state = node.get('state')
        if state in ('READY', 'CREATING', 'STARTING', 'RESTARTING'):
            continue  # idempotent
        if state == 'STOPPED':
            pending_ops.append(tpu.start_node(zone, n))
            continue
        raise exceptions.CloudError(
            f'TPU node {n} in unexpected state {state}')
    for op in pending_ops:
        tpu.wait_operation(op)
    if not missing:
        return
    if deploy_vars.get('use_queued_resources'):
        # A stale QR under the cluster's id (nodes later preempted/deleted
        # while the grant object lived on) would 409 the re-request.
        qr = tpu.get_queued_resource(zone, name)
        if qr is not None:
            qr_state = (qr.get('state') or {}).get('state')
            if (len(missing) == len(slice_names)
                    or qr_state in ('FAILED', 'SUSPENDED')):
                # No healthy node outlives the grant (all missing, or the
                # API already marked its resources deleted): force-delete
                # is safe. Wait for the delete LRO — re-requesting the
                # same queuedResourceId mid-delete 409s.
                op = tpu.delete_queued_resource(zone, name)
                if op is not None:
                    tpu.wait_operation(op)
            else:
                # ACTIVE grant with healthy slices still running: deleting
                # it (force) would kill them, so recreate the missing
                # node(s) directly instead of via a queued resource.
                ops = [tpu.create_node(zone, n,
                                       _tpu_node_body(n, deploy_vars))
                       for n in missing]
                for op in ops:
                    tpu.wait_operation(op)
                return
        qr_body = {
            'tpu': {'nodeSpec': [{
                'parent': f'projects/{project}/locations/{zone}',
                'nodeId': n,
                'node': _tpu_node_body(n, deploy_vars),
            } for n in missing]},
        }
        if deploy_vars.get('use_spot'):
            qr_body['spot'] = {}
        elif not deploy_vars.get('reserved'):
            qr_body['guaranteed'] = {}
        tpu.create_queued_resource(zone, name, qr_body)
        _wait_queued_resource(tpu, zone, name)
    else:
        # Parallel inserts; wait all. A failure raises ProvisionError from
        # wait_operation and the backend tears the attempt down.
        ops = [tpu.create_node(zone, n, _tpu_node_body(n, deploy_vars))
               for n in missing]
        for op in ops:
            tpu.wait_operation(op)


def _wait_queued_resource(tpu: gcp_api.TpuClient, zone: str, qr_id: str,
                          timeout: float = 1800) -> None:
    """Queued resources either become ACTIVE (node exists) or fail; FAILED /
    long-SUSPENDED is classified as capacity so failover moves on."""
    deadline = time.time() + timeout
    while time.time() < deadline:
        qr = tpu.get_queued_resource(zone, qr_id)
        if qr is None:
            raise exceptions.CloudError(f'queued resource {qr_id} vanished')
        state = (qr.get('state') or {}).get('state', 'UNKNOWN')
        if state == 'ACTIVE':
            return
        if state in ('FAILED', 'SUSPENDED'):
            tpu.delete_queued_resource(zone, qr_id)
            raise exceptions.InsufficientCapacityError(
                f'queued resource {qr_id} {state.lower()} in {zone} '
                '(no TPU capacity)', reason='capacity')
        time.sleep(10)
    tpu.delete_queued_resource(zone, qr_id)
    raise exceptions.InsufficientCapacityError(
        f'queued resource {qr_id} not granted within {timeout}s',
        reason='capacity')


def _run_gce_instances(project: str, zone: str, name: str, num_hosts: int,
                       deploy_vars: Dict[str, Any]) -> None:
    gce = gcp_api.GceClient(project)
    existing = {i['name']: i for i in gce.list_instances(
        zone, label_filter=f'labels.{_LABEL}={name}')}
    machine = deploy_vars.get('instance_type', 'n2-standard-8')
    image = deploy_vars.get('image_family', 'ubuntu-2204-lts')
    # An explicit image_id (e.g. a clone-disk image URL) wins over the
    # public family default.
    source_image = (deploy_vars.get('image_id')
                    or f'projects/ubuntu-os-cloud/global/images/family/{image}')
    pending_ops = []
    for rank in range(num_hosts):
        iname = f'{name}-{rank}'
        inst = existing.get(iname)
        if inst is not None:
            if inst.get('status') == 'TERMINATED':
                pending_ops.append(gce.start(zone, iname))
            continue
        body = {
            'name': iname,
            'machineType': f'zones/{zone}/machineTypes/{machine}',
            'labels': {_LABEL: name, 'skytpu-rank': str(rank)},
            'disks': [{
                'boot': True,
                'initializeParams': {
                    'sourceImage': source_image,
                    'diskSizeGb': deploy_vars.get('disk_size_gb', 256),
                },
                'autoDelete': True,
            }],
            'tags': {'items': [_firewall_tag(name)]},
            'networkInterfaces': [dict(
                {'network': 'global/networks/'
                            f"{deploy_vars.get('network') or 'default'}",
                 'accessConfigs': [{'type': 'ONE_TO_ONE_NAT'}]},
                **({'subnetwork': f'regions/{deploy_vars["region"]}/'
                                  f'subnetworks/'
                                  f'{deploy_vars["subnetwork"]}'}
                   if deploy_vars.get('subnetwork') else {}))],
            'metadata': {'items': [{
                'key': 'ssh-keys',
                'value': authentication.gcp_ssh_keys_metadata(),
            }]},
            'scheduling': {
                'preemptible': bool(deploy_vars.get('use_spot'))},
        }
        pending_ops.append(gce.insert(zone, body))
    # Issue every insert first, then wait — N hosts provision in ~1x the
    # single-instance latency instead of Nx.
    for op in pending_ops:
        gce.wait_zone_operation(zone, op)


def _require_record(cluster_name: str) -> Dict[str, Any]:
    record = _load_record(cluster_name)
    if not record:
        raise exceptions.ClusterError(
            f'No GCP provisioning record for {cluster_name!r}')
    return record


def wait_instances(cluster_name: str, region: str, state: str = 'running',
                   timeout: float = 1800) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        states = set(query_instances(cluster_name, region).values())
        if states == {state}:
            return
        if 'preempted' in states or 'terminated' in states:
            raise exceptions.InsufficientCapacityError(
                f'{cluster_name}: host(s) preempted/terminated while '
                f'waiting for {state}', reason='capacity')
        time.sleep(10)
    raise exceptions.ProvisionError(
        f'{cluster_name} did not reach {state!r} within {timeout}s')


def query_instances(cluster_name: str, region: str) -> Dict[str, str]:
    record = _load_record(cluster_name)
    if not record:
        return {}
    project, zone = record['project'], record['zone']
    name = record['name_on_cloud']
    if record['mode'] == 'tpu_vm':
        tpu = gcp_api.TpuClient(project)
        num_slices = int(record.get('num_slices') or 1)
        hosts_per_slice = record['num_hosts'] // num_slices
        out: Dict[str, str] = {}
        any_alive = False
        for sname in _slice_names(name, num_slices):
            node = tpu.get_node(zone, sname)
            # A missing slice of a live gang must read as terminated hosts
            # (not be silently omitted): a half-dead multi-slice cluster
            # would otherwise report fully healthy and managed-job
            # preemption recovery would never fire.
            mapped = ('terminated' if node is None
                      else _TPU_STATE_MAP.get(node.get('state', ''),
                                              'unknown'))
            any_alive = any_alive or node is not None
            out.update({f'{sname}-w{r}': mapped
                        for r in range(hosts_per_slice)})
        # Whole cluster gone -> {} (the pre-existing "terminated cluster"
        # contract core.py relies on).
        return out if any_alive else {}
    gce = gcp_api.GceClient(project)
    out = {}
    for inst in gce.list_instances(zone,
                                   label_filter=f'labels.{_LABEL}={name}'):
        out[inst['name']] = _GCE_STATE_MAP.get(inst.get('status', ''),
                                               'unknown')
    return out


def stop_instances(cluster_name: str, region: str) -> None:
    record = _require_record(cluster_name)
    project, zone = record['project'], record['zone']
    name = record['name_on_cloud']
    if record['mode'] == 'tpu_vm':
        tpu = gcp_api.TpuClient(project)
        ops = [tpu.stop_node(zone, sname) for sname in
               _slice_names(name, int(record.get('num_slices') or 1))]
        for op in ops:
            tpu.wait_operation(op)
    else:
        gce = gcp_api.GceClient(project)
        ops = [gce.stop(zone, f'{name}-{rank}')
               for rank in range(record['num_hosts'])]
        for op in ops:
            gce.wait_zone_operation(zone, op)


def terminate_instances(cluster_name: str, region: str) -> None:
    record = _load_record(cluster_name)
    if not record:
        return
    project, zone = record['project'], record['zone']
    name = record['name_on_cloud']
    if record['mode'] == 'tpu_vm':
        tpu = gcp_api.TpuClient(project)
        if record['deploy_vars'].get('use_queued_resources'):
            tpu.delete_queued_resource(zone, name)
        ops = [tpu.delete_node(zone, sname) for sname in
               _slice_names(name, int(record.get('num_slices') or 1))]
        for op in ops:
            tpu.wait_operation(op)
    else:
        gce = gcp_api.GceClient(project)
        ops = [gce.delete(zone, f'{name}-{rank}')
               for rank in range(record['num_hosts'])]
        for op in ops:
            gce.wait_zone_operation(zone, op)
    # open_ports firewall rule is keyed on the cluster: it dies with it.
    try:
        gcp_api.GceClient(project).delete_firewall(_firewall_rule_name(name))
    except exceptions.CloudError:
        pass  # rule cleanup must never block teardown
    _delete_record(cluster_name)


def get_cluster_info(cluster_name: str, region: str
                     ) -> provision_lib.ClusterInfo:
    record = _require_record(cluster_name)
    project, zone = record['project'], record['zone']
    name = record['name_on_cloud']
    hosts: List[provision_lib.HostInfo] = []
    if record['mode'] == 'tpu_vm':
        tpu = gcp_api.TpuClient(project)
        num_slices = int(record.get('num_slices') or 1)
        # Ranks are slice-major: rank = slice_id * hosts_per_slice + worker
        # (networkEndpoints is in worker order within a slice).
        for slice_id, sname in enumerate(_slice_names(name, num_slices)):
            node = tpu.get_node(zone, sname)
            if node is None:
                raise exceptions.ClusterError(
                    f'TPU node {sname} not found')
            base = len(hosts)
            for worker, ep in enumerate(node.get('networkEndpoints', [])):
                hosts.append(provision_lib.HostInfo(
                    host_id=f'{sname}-w{worker}', rank=base + worker,
                    internal_ip=ep.get('ipAddress', ''),
                    external_ip=(ep.get('accessConfig') or {}).get(
                        'externalIp'),
                    extra={'node': sname, 'slice_id': slice_id}))
    else:
        insts = gcp_api.GceClient(project).list_instances(
            zone, label_filter=f'labels.{_LABEL}={name}')
        insts.sort(key=lambda i: int(
            (i.get('labels') or {}).get('skytpu-rank', 0)))
        for rank, inst in enumerate(insts):
            nic = (inst.get('networkInterfaces') or [{}])[0]
            access = (nic.get('accessConfigs') or [{}])[0]
            hosts.append(provision_lib.HostInfo(
                host_id=inst['name'], rank=rank,
                internal_ip=nic.get('networkIP', ''),
                external_ip=access.get('natIP'),
                extra={}))
    return provision_lib.ClusterInfo(
        cluster_name=cluster_name, cloud='gcp', region=region, zone=zone,
        hosts=hosts, deploy_vars=record['deploy_vars'])


def open_ports(cluster_name: str, region: str, ports: List[str]) -> None:
    """Expose ports: one firewall rule per cluster, targeting its network
    tag (reference sky/provision/gcp/instance.py open_ports +
    config.py firewall bootstrap). Idempotent: re-opening merges ports
    into the existing rule."""
    if not ports:
        return
    record = _require_record(cluster_name)
    project = record['project']
    name = record['name_on_cloud']
    network = (record['deploy_vars'].get('network') or 'default')
    gce = gcp_api.GceClient(project)
    rule_name = _firewall_rule_name(name)
    want = sorted({str(p) for p in ports})
    # Public by default (matches the reference's exposure for task/serve
    # ports); narrow with `gcp.firewall_source_ranges` in
    # ~/.skytpu/config.yaml for private deployments. Applied on create AND
    # patch so tightening the config takes effect on existing rules too.
    from skypilot_tpu import config as config_lib
    source_ranges = sorted(config_lib.get_nested(
        ('gcp', 'firewall_source_ranges'), ['0.0.0.0/0']))
    existing = gce.get_firewall(rule_name)
    if existing is not None:
        have = set()
        for allowed in existing.get('allowed', []):
            have.update(allowed.get('ports', []))
        merged = sorted(have | set(want))
        if (merged == sorted(have)
                and sorted(existing.get('sourceRanges', [])) ==
                source_ranges):
            return  # already open with the right exposure
        gce.wait_global_operation(gce.patch_firewall(rule_name, {
            'allowed': [{'IPProtocol': 'tcp', 'ports': merged}],
            'sourceRanges': source_ranges,
        }))
        return
    gce.wait_global_operation(gce.insert_firewall({
        'name': rule_name,
        'network': f'global/networks/{network}',
        'direction': 'INGRESS',
        'sourceRanges': source_ranges,
        'targetTags': [_firewall_tag(name)],
        'allowed': [{'IPProtocol': 'tcp', 'ports': want}],
    }))


def get_command_runners(cluster_info: provision_lib.ClusterInfo,
                        ssh_credentials: Optional[Dict[str, str]] = None
                        ) -> List[runner_lib.CommandRunner]:
    creds = ssh_credentials or {}
    key_path = creds.get('key_path')
    if key_path is None:
        key_path, _ = authentication.get_or_generate_keys()
    user = creds.get('user', authentication.SSH_USER)
    runners: List[runner_lib.CommandRunner] = []
    for h in cluster_info.hosts:
        ip = h.external_ip or h.internal_ip
        runners.append(runner_lib.SSHCommandRunner(ip, user, key_path))
    return runners


def create_image_from_cluster(cluster_name: str, region: str,
                              image_name: str) -> str:
    """Image the stopped cluster's head boot disk (reference
    ``--clone-disk-from``). GCE clusters only: TPU-VM boot disks are not
    imageable through the images API."""
    record = _require_record(cluster_name)
    if record.get('mode') != 'gce':
        raise exceptions.NotSupportedError(
            'clone-disk-from needs a GCE (CPU VM) cluster; TPU-VM boot '
            'disks cannot be imaged')
    project = record['project']
    zone = record['zone']
    head = f"{record['name_on_cloud']}-0"
    gce = gcp_api.GceClient(project)
    op = gce.create_image(image_name, zone, head)
    gce.wait_global_operation(op)
    return f'projects/{project}/global/images/{image_name}'
