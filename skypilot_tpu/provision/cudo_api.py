"""Thin Cudo Compute REST client with a test seam.

Counterpart of the reference's cudo SDK usage
(``sky/provision/cudo/cudo_wrapper.py`` over the cudo python SDK). The
real transport is a tiny urllib client over
``https://rest.compute.cudo.org/v1`` (bearer key + project id from the
cudo CLI's ``~/.config/cudo/cudo.yml``); tests install an in-process
fake via ``set_cudo_factory`` implementing the flat surface
(``create_vm``, ``list_vms``, ``start/stop/terminate_vm``), so the
project-scoped lifecycle runs with no cloud.

Error classification: stock wording ("no host available", "insufficient
capacity") -> failover; billing/quota wording -> quota.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import rest_cloud

API_ENDPOINT = 'https://rest.compute.cudo.org/v1'
CONFIG_PATH = '~/.config/cudo/cudo.yml'

_CAPACITY_MARKERS = (
    'no host available',
    'insufficient capacity',
    'out of stock',
    'no capacity',
)
_QUOTA_MARKERS = (
    'quota',
    'billing',
    'insufficient funds',
)


class CudoApiError(Exception):
    """Fake/real client error carrying an HTTP status + message."""

    def __init__(self, status: int, message: str = ''):
        super().__init__(message or str(status))
        self.status = status
        self.message = message or str(status)


classify_error = rest_cloud.marker_classifier(_CAPACITY_MARKERS,
                                              _QUOTA_MARKERS)


def read_credentials() -> Optional[Dict[str, str]]:
    """(api key, project id) from env or the cudo CLI config."""
    key = os.environ.get('CUDO_API_KEY')
    project = os.environ.get('CUDO_PROJECT_ID')
    if key and project:
        return {'key': key, 'project': project}
    path = os.path.expanduser(CONFIG_PATH)
    if os.path.exists(path):
        try:
            import yaml
            with open(path, encoding='utf-8') as f:
                cfg = yaml.safe_load(f) or {}
        except Exception:  # noqa: BLE001 — malformed config = no creds
            return None
        contexts = cfg.get('contexts') or {}
        ctx = contexts.get(cfg.get('current-context', 'default')) or {}
        key = key or ctx.get('key')
        project = project or ctx.get('project')
        if key and project:
            return {'key': str(key), 'project': str(project)}
    return None


def _parse_error(status: int, raw: bytes) -> Exception:
    try:
        err = json.loads(raw.decode())
        return CudoApiError(status, err.get('message', raw.decode()))
    except (ValueError, AttributeError):
        return CudoApiError(status,
                            raw.decode(errors='replace') or str(status))


class _RestClient:
    """Flat op surface over the shared retrying urllib transport."""

    def __init__(self):
        creds = read_credentials()
        if creds is None:
            raise exceptions.CloudError(
                'Cudo credentials not found: set $CUDO_API_KEY + '
                f'$CUDO_PROJECT_ID or run `cudo init` ({CONFIG_PATH}).')
        self.project = creds['project']
        self._headers = {'Authorization': f'Bearer {creds["key"]}',
                         'Content-Type': 'application/json'}

    def _request(self, method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        return rest_cloud.retrying_request(
            method, f'{API_ENDPOINT}{path}', self._headers, payload,
            _parse_error)

    # -- flat op surface (mirrored by test fakes) ---------------------------
    def create_vm(self, vm_id: str, data_center_id: str,
                  machine_type: str, vcpus: int, memory_gib: int,
                  boot_disk_gib: int, image_id: str, ssh_public_key: str,
                  metadata: Dict[str, str]) -> Dict[str, Any]:
        return dict(self._request(
            'POST', f'/projects/{self.project}/vm', {
                'vmId': vm_id, 'dataCenterId': data_center_id,
                'machineType': machine_type,
                'vcpus': vcpus, 'memoryGib': memory_gib,
                'bootDiskSizeGib': boot_disk_gib,
                'bootDiskImageId': image_id,
                'sshKeySource': 'SSH_KEY_SOURCE_NONE',
                'customSshKeys': [ssh_public_key],
                'metadata': dict(metadata),
            }))

    def list_vms(self) -> List[Dict[str, Any]]:
        return list(self._request(
            'GET', f'/projects/{self.project}/vms').get('VMs', []))

    def start_vm(self, vm_id: str) -> None:
        self._request('POST',
                      f'/projects/{self.project}/vms/{vm_id}/start', {})

    def stop_vm(self, vm_id: str) -> None:
        self._request('POST',
                      f'/projects/{self.project}/vms/{vm_id}/stop', {})

    def terminate_vm(self, vm_id: str) -> None:
        self._request(
            'POST',
            f'/projects/{self.project}/vms/{vm_id}/terminate', {})


# Test seam (``set_cudo_factory(lambda: fake)``), client construction
# and error-normalizing ``call`` via the shared ClientSeam.
_seam = rest_cloud.ClientSeam(_RestClient, CudoApiError, classify_error)
set_cudo_factory = _seam.set_factory
get_client = _seam.get_client
call = _seam.call
