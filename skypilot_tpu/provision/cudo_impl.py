"""Cudo Compute provisioner: project-scoped VMs in data centers.

Counterpart of reference ``sky/provision/cudo/instance.py`` +
``cudo_wrapper.py``. Twelfth VM cloud. Cudo-isms:

- VMs live in a PROJECT (the account's container, like an OCI
  compartment) and a DATA CENTER (the region unit, e.g.
  'gb-bournemouth'); no zones;
- the vmId is caller-chosen and unique per project: rank lives directly
  in the id (``{name}-r{rank}``) AND in metadata (belt and braces —
  metadata is the reference's tag mechanism, cudo_wrapper.py:78);
- stop/start supported; no spot market; no per-VM firewall API in
  scope (VMs get public IPs; the cloud class omits OPEN_PORTS);
- vcpus/memory ride the create call (Cudo machine types are
  host-family templates, sized per request) — derived from the catalog
  row like OCI's Flex shapeConfig.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import authentication
from skypilot_tpu import catalog
from skypilot_tpu import exceptions
from skypilot_tpu import provision as provision_lib
from skypilot_tpu.provision import cudo_api
from skypilot_tpu.provision import rest_cloud
from skypilot_tpu.utils import command_runner as runner_lib

SSH_USER = 'root'

DEFAULT_IMAGE = 'ubuntu-2204'

_STATE_MAP = {
    'PENDING': 'pending',
    'PROVISIONING': 'pending',
    'STARTING': 'pending',
    'ACTIVE': 'running',
    'RUNNING': 'running',
    'STOPPING': 'stopping',
    'STOPPED': 'stopped',
    'SUSPENDED': 'stopped',
    'DELETING': 'terminating',
    'FAILED': 'terminated',  # failed build -> rank hole -> failover
}

# Cluster bookkeeping + rank decoding via the shared REST-cloud
# scaffolding (rest_cloud.py).
_records = rest_cloud.ClusterRecords('cudo_cluster')


def _live_vms(client, name: str,
              region: Optional[str] = None) -> Dict[int, Dict[str, Any]]:
    """rank -> VM by vmId prefix, data-center filtered. The listing is
    project-scoped but spans data centers, and the SAME cluster name
    fails over across data centers — a cleanup survivor from the failed
    region must not be adopted into the new gang (the rest_cloud
    invariant; hyperstack guards identically)."""
    out: Dict[int, Dict[str, Any]] = {}
    for vm in cudo_api.call(client, 'list_vms'):
        vm_id = vm.get('id') or vm.get('vmId') or ''
        rank = rest_cloud.rank_of(vm_id, name)
        if rank is None:
            continue
        if vm.get('state') in ('DELETING', 'DELETED'):
            continue
        if region is not None and (vm.get('dataCenterId')
                                   or region) != region:
            continue
        out[rank] = vm
    return out


# ---- provision API ---------------------------------------------------------
def run_instances(cluster_name: str, region: str, zone: Optional[str],
                  num_hosts: int, deploy_vars: Dict[str, Any]) -> None:
    del zone  # data centers have no zones
    name = deploy_vars['cluster_name_on_cloud']
    record = {'region': region, 'zone': None, 'name_on_cloud': name,
              'num_hosts': num_hosts, 'deploy_vars': deploy_vars}
    _records.save(cluster_name, record)
    client = cudo_api.get_client()
    machine_type = deploy_vars.get('instance_type', 'epyc-milan')
    vcpus, mem = catalog.get_instance_info(machine_type, cloud='cudo')
    try:
        _, pub_path = authentication.get_or_generate_keys()
        with open(pub_path, encoding='utf-8') as f:
            pub_key = f.read().strip()
        existing = _live_vms(client, name, region)
        for rank, vm in existing.items():
            if _STATE_MAP.get(vm.get('state', '')) == 'stopped':
                cudo_api.call(client, 'start_vm',
                              vm_id=vm.get('id') or vm.get('vmId'))
        for rank in range(num_hosts):
            if rank in existing:
                continue  # idempotent relaunch
            cudo_api.call(
                client, 'create_vm',
                vm_id=f'{name}-r{rank}',
                data_center_id=region,
                machine_type=machine_type,
                vcpus=int(vcpus),
                memory_gib=int(mem),
                boot_disk_gib=int(deploy_vars.get('disk_size_gb')
                                  or 100),
                image_id=deploy_vars.get('image_id') or DEFAULT_IMAGE,
                ssh_public_key=pub_key,
                metadata={'skytpu-cluster': name,
                          'skytpu-rank': str(rank),
                          **{k: str(v) for k, v in
                             (deploy_vars.get('labels') or {}).items()}})
    except exceptions.InsufficientCapacityError:
        try:
            _terminate_all(client, name)
        except exceptions.CloudError:
            pass
        else:
            _records.delete(cluster_name)
        raise


def wait_instances(cluster_name: str, region: str, state: str = 'running',
                   timeout: float = 1800) -> None:
    rest_cloud.poll_for_state(
        cluster_name, lambda: query_instances(cluster_name, region),
        state, timeout)


def query_instances(cluster_name: str, region: str) -> Dict[str, str]:
    del region
    record = _records.load(cluster_name)
    if not record:
        return {}
    client = cudo_api.get_client()
    live = _live_vms(client, record['name_on_cloud'],
                     record.get('region'))
    if not live:
        return {}
    out: Dict[str, str] = {}
    for rank, vm in live.items():
        out[vm.get('id') or vm.get('vmId') or f'r{rank}'] = \
            _STATE_MAP.get(vm.get('state', ''), 'unknown')
    for rank in range(int(record.get('num_hosts') or 0)):
        if rank not in live:
            out[f'rank{rank}-missing'] = 'terminated'
    return out


def stop_instances(cluster_name: str, region: str) -> None:
    record = _records.require(cluster_name, 'Cudo')
    client = cudo_api.get_client()
    for vm in _live_vms(client, record['name_on_cloud']).values():
        if _STATE_MAP.get(vm.get('state', '')) in ('pending', 'running'):
            cudo_api.call(client, 'stop_vm',
                          vm_id=vm.get('id') or vm.get('vmId'))


def _terminate_all(client, name: str) -> None:
    for vm in _live_vms(client, name).values():
        cudo_api.call(client, 'terminate_vm',
                      vm_id=vm.get('id') or vm.get('vmId'))


def terminate_instances(cluster_name: str, region: str) -> None:
    del region
    record = _records.load(cluster_name)
    if not record:
        return
    client = cudo_api.get_client()
    _terminate_all(client, record['name_on_cloud'])
    _records.delete(cluster_name)


def get_cluster_info(cluster_name: str,
                     region: str) -> provision_lib.ClusterInfo:
    del region
    record = _records.require(cluster_name, 'Cudo')
    client = cudo_api.get_client()
    live = _live_vms(client, record['name_on_cloud'],
                     record.get('region'))
    hosts: List[provision_lib.HostInfo] = []
    for rank in sorted(live):
        vm = live[rank]
        nic = (vm.get('nics') or [{}])[0]
        public = (vm.get('publicIpAddress')
                  or nic.get('externalIpAddress'))
        private = (vm.get('privateIpAddress')
                   or nic.get('internalIpAddress') or public)
        if private is None:
            raise exceptions.ProvisionError(
                f'No IP on VM {vm.get("id")!r} yet.')
        hosts.append(provision_lib.HostInfo(
            host_id=str(vm.get('id') or vm.get('vmId')), rank=rank,
            internal_ip=private, external_ip=public,
            extra={}))
    return provision_lib.ClusterInfo(
        cluster_name=cluster_name, cloud='cudo',
        region=record['region'], zone=None, hosts=hosts,
        deploy_vars=record['deploy_vars'])


def get_command_runners(cluster_info: provision_lib.ClusterInfo,
                        ssh_credentials: Optional[Dict[str, str]] = None
                        ) -> List[runner_lib.CommandRunner]:
    return rest_cloud.ssh_runners(cluster_info, SSH_USER, ssh_credentials)
