"""DigitalOcean provisioner: droplet host groups (tag-scoped clusters).

Counterpart of reference ``sky/provision/do/instance.py`` (droplet ops
over pydo) — the fifth VM cloud. Same record/classification/failover
shape as GCP/AWS/Azure/Lambda so ``RetryingProvisioner`` drives all of
them identically.

DO-isms (mirrored from the reference's handling):
- droplets are found by a per-cluster TAG (DO tags are first-class API
  filters — cheaper and safer than name parsing on an account-global
  list); rank is encoded in the droplet name ``{name}-r{rank}``;
- stop is ``power_off`` — NOTE a powered-off droplet still bills on DO
  (like Azure's non-deallocated 'stopped'; DO has no deallocate, so
  stop support is billing-caveated, documented in docs/clouds.md);
- no spot market;
- ports are ONE per-cluster firewall object applied by tag, whose
  inbound rule list is replaced on update (PUT semantics).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import authentication
from skypilot_tpu import exceptions
from skypilot_tpu import provision as provision_lib
from skypilot_tpu.provision import do_api
from skypilot_tpu.provision import rest_cloud
from skypilot_tpu.utils import command_runner as runner_lib

SSH_USER = 'root'  # DO's stock images log in as root

DEFAULT_IMAGE = 'ubuntu-24-04-x64'

# Droplet statuses -> the provision API's state words. 'off' means
# powered off (still billing — DO has no deallocate).
_STATE_MAP = {
    'new': 'pending',
    'active': 'running',
    'off': 'stopped',
    'archive': 'terminated',
}


# Cluster bookkeeping + rank decoding via the shared REST-cloud
# scaffolding (rest_cloud.py).
_records = rest_cloud.ClusterRecords('do_cluster')


def _cluster_tag(name_on_cloud: str) -> str:
    return f'skytpu-{name_on_cloud}'


def _live_droplets(client, name: str,
                   region: Optional[str] = None
                   ) -> Dict[int, Dict[str, Any]]:
    """rank -> droplet for the cluster tag. Tags scope to the CLUSTER,
    but DO tags are account-global, not regional — so a region filter is
    still required wherever a cleanup-survivor from a failed-over region
    must not be adopted into the current gang (same hazard as Lambda)."""
    out: Dict[int, Dict[str, Any]] = {}
    for d in do_api.call(client, 'list_droplets', tag=_cluster_tag(name)):
        rank = rest_cloud.rank_of(d.get('name') or '', name)
        if rank is None:
            continue
        if d.get('status') == 'archive':
            continue
        if region is not None and (
                (d.get('region') or {}).get('slug') or region) != region:
            continue
        out[rank] = d
    return out


def _tagify(text: str) -> str:
    """DO tags allow only letters, digits, ':', '-', '_': anything else
    in a user label becomes '-' so create_droplet never 422s on a label
    like version:1.2."""
    return ''.join(c if (c.isalnum() or c in ':-_') else '-'
                   for c in text)


def _ensure_ssh_key(client) -> int:
    """Register the local public key if absent; returns the DO key id."""
    _, pub_path = authentication.get_or_generate_keys()
    with open(pub_path, encoding='utf-8') as f:
        pub_key = f.read().strip()
    for key in do_api.call(client, 'list_ssh_keys'):
        if (key.get('public_key') or '').strip() == pub_key:
            return int(key['id'])
    created = do_api.call(client, 'register_ssh_key', name='skytpu',
                          public_key=pub_key)
    return int(created['id'])


def _ips(droplet: Dict[str, Any]) -> Dict[str, Optional[str]]:
    v4 = (droplet.get('networks') or {}).get('v4') or []
    out: Dict[str, Optional[str]] = {'public': None, 'private': None}
    for net in v4:
        if net.get('type') in out and out[net['type']] is None:
            out[net['type']] = net.get('ip_address')
    return out


# ---- provision API ---------------------------------------------------------
def run_instances(cluster_name: str, region: str, zone: Optional[str],
                  num_hosts: int, deploy_vars: Dict[str, Any]) -> None:
    del zone  # DO has no zones
    name = deploy_vars['cluster_name_on_cloud']
    record = {'region': region, 'zone': None, 'name_on_cloud': name,
              'num_hosts': num_hosts, 'deploy_vars': deploy_vars}
    # Record BEFORE creating (partial-failure resources must stay
    # reachable by terminate_instances; same contract as provision/gcp.py).
    _records.save(cluster_name, record)
    client = do_api.get_client()
    try:
        key_id = _ensure_ssh_key(client)
        existing = _live_droplets(client, name, region)
        for rank, d in existing.items():
            if d.get('status') == 'off':
                do_api.call(client, 'droplet_action',
                            droplet_id=d['id'], action='power_on')
        for rank in range(num_hosts):
            if rank in existing:
                continue  # idempotent relaunch
            do_api.call(
                client, 'create_droplet',
                name=f'{name}-r{rank}',
                region=region,
                size=deploy_vars.get('instance_type', 's-2vcpu-4gb'),
                image=deploy_vars.get('image_id') or DEFAULT_IMAGE,
                ssh_key_ids=[key_id],
                tags=[_cluster_tag(name)] + [
                    _tagify(f'{k}:{v}') for k, v in
                    (deploy_vars.get('labels') or {}).items()])
    except exceptions.InsufficientCapacityError:
        # Clean up partial hosts, then drop the record so region
        # failover retries don't see a stale pointer. If cleanup itself
        # failed, KEEP the record so terminate_instances can retry.
        try:
            _terminate_all(client, name)
        except exceptions.CloudError:
            pass
        else:
            _records.delete(cluster_name)
        raise


def wait_instances(cluster_name: str, region: str, state: str = 'running',
                   timeout: float = 1800) -> None:
    # No spot on DO: no eviction heuristics, just converge-or-hole.
    rest_cloud.poll_for_state(
        cluster_name, lambda: query_instances(cluster_name, region),
        state, timeout)


def query_instances(cluster_name: str, region: str) -> Dict[str, str]:
    """Live host states. A PARTIALLY-dead cluster reports missing ranks
    as 'terminated'; a fully-dead cluster returns {} ("terminated
    cluster" contract in core.py)."""
    del region
    record = _records.load(cluster_name)
    if not record:
        return {}
    client = do_api.get_client()
    live = _live_droplets(client, record['name_on_cloud'],
                          record.get('region'))
    if not live:
        return {}
    out: Dict[str, str] = {}
    for rank, d in live.items():
        out[d.get('name', f'r{rank}')] = _STATE_MAP.get(
            d.get('status', ''), 'unknown')
    for rank in range(int(record.get('num_hosts') or 0)):
        if rank not in live:
            out[f'rank{rank}-missing'] = 'terminated'
    return out


def stop_instances(cluster_name: str, region: str) -> None:
    """power_off every droplet. NOTE: a powered-off droplet still bills
    on DO (no deallocate); `skytpu down` is the only way to stop the
    meter — documented in docs/clouds.md."""
    record = _records.require(cluster_name, 'DO')
    client = do_api.get_client()
    for d in _live_droplets(client, record['name_on_cloud']).values():
        if d.get('status') in ('new', 'active'):
            do_api.call(client, 'droplet_action', droplet_id=d['id'],
                        action='power_off')


def _terminate_all(client, name: str) -> None:
    for d in _live_droplets(client, name).values():
        do_api.call(client, 'delete_droplet', droplet_id=d['id'])


def terminate_instances(cluster_name: str, region: str) -> None:
    del region
    record = _records.load(cluster_name)
    if not record:
        return
    client = do_api.get_client()
    name = record['name_on_cloud']
    _terminate_all(client, name)
    # The per-cluster firewall object is cluster-scoped: delete it
    # (unlike Lambda's account-global rules).
    fw_name = _firewall_name(name)
    for fw in do_api.call(client, 'list_firewalls'):
        if fw.get('name') == fw_name:
            try:
                do_api.call(client, 'delete_firewall',
                            firewall_id=fw['id'])
            except exceptions.CloudError:
                pass  # best-effort; orphan firewalls hold no billing
    _records.delete(cluster_name)


def get_cluster_info(cluster_name: str,
                     region: str) -> provision_lib.ClusterInfo:
    del region
    record = _records.require(cluster_name, 'DO')
    client = do_api.get_client()
    live = _live_droplets(client, record['name_on_cloud'],
                          record.get('region'))
    hosts: List[provision_lib.HostInfo] = []
    for rank in sorted(live):
        d = live[rank]
        ips = _ips(d)
        internal = ips['private'] or ips['public']
        if internal is None:
            raise exceptions.ProvisionError(
                f'No IP on droplet {d.get("name")!r} yet.')
        hosts.append(provision_lib.HostInfo(
            host_id=str(d.get('id', f'r{rank}')), rank=rank,
            internal_ip=internal,
            external_ip=ips['public'],
            extra={}))
    return provision_lib.ClusterInfo(
        cluster_name=cluster_name, cloud='do',
        region=record['region'], zone=None, hosts=hosts,
        deploy_vars=record['deploy_vars'])


def _firewall_name(name_on_cloud: str) -> str:
    return f'skytpu-{name_on_cloud}-fw'


def open_ports(cluster_name: str, region: str, ports: List[str]) -> None:
    """One per-cluster firewall object applied by the cluster tag; its
    inbound rule list is REPLACED on update, so re-opening is idempotent
    and a tightened ``do.firewall_source_ranges`` re-applies."""
    if not ports:
        return
    record = _records.require(cluster_name, 'DO')
    client = do_api.get_client()
    name = record['name_on_cloud']
    from skypilot_tpu import config as config_lib
    ranges = config_lib.get_nested(('do', 'firewall_source_ranges'),
                                   ['0.0.0.0/0'])
    tag = _cluster_tag(name)
    fw_name = _firewall_name(name)
    existing = None
    for fw in do_api.call(client, 'list_firewalls'):
        if fw.get('name') == fw_name:
            existing = fw
            break
    wanted: Dict[str, Dict[str, Any]] = {}
    if existing is not None:
        for rule in existing.get('inbound_rules', []):
            # icmp rules legitimately omit 'ports' (DO only requires it
            # for tcp/udp): preserve them under a portless key.
            wanted[f"{rule['protocol']}:{rule.get('ports', '')}"] = \
                dict(rule)
    # SSH must stay reachable through the cluster firewall.
    wanted.setdefault('tcp:22', {
        'protocol': 'tcp', 'ports': '22',
        'sources': {'addresses': ['0.0.0.0/0', '::/0']}})
    for port in sorted(ports, key=str):
        spec = str(port)  # DO accepts '8080' and '9000-9010' verbatim
        wanted[f'tcp:{spec}'] = {
            'protocol': 'tcp', 'ports': spec,
            'sources': {'addresses': list(ranges)}}
    # .get: preserved ICMP rules have no 'ports' (they sort first).
    rules = sorted(wanted.values(), key=lambda r: r.get('ports', ''))
    if existing is None:
        do_api.call(client, 'create_firewall', name=fw_name,
                    inbound_rules=rules, tags=[tag])
    else:
        do_api.call(client, 'update_firewall',
                    firewall_id=existing['id'],
                    body={'name': fw_name, 'inbound_rules': rules,
                          'outbound_rules': existing.get(
                              'outbound_rules', []),
                          'tags': [tag]})


def get_command_runners(cluster_info: provision_lib.ClusterInfo,
                        ssh_credentials: Optional[Dict[str, str]] = None
                        ) -> List[runner_lib.CommandRunner]:
    return rest_cloud.ssh_runners(cluster_info, SSH_USER, ssh_credentials)
