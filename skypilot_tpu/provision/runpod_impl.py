"""RunPod provisioner: container pods with spot bids (terminate-only).

Counterpart of reference ``sky/provision/runpod/instance.py`` +
``utils.py`` (pod launch with ssh bootstrap via dockerArgs, spot pods
with bidPerGpu, no stop). RunPod-isms:

- pods are CONTAINERS: ssh is bootstrapped by the pod's docker command
  (sshd install + the local public key, reference utils.py:258-283) and
  lands on a host-mapped public port;
- instance types are ``{n}x_{GPU_ID}_{SECURE|COMMUNITY}`` plans
  (reference invents the same shape); regions are country codes;
- ``use_spot`` rents an interruptible pod with a per-GPU bid
  (catalog's spot price / gpu count); a preempted spot pod DISAPPEARS
  from the pod list (terminate semantics), which the shared rank-hole
  detection already classifies as capacity;
- ports CANNOT be opened after creation — the pod's port set is fixed
  at rent time, so ``open_ports`` only verifies the request against
  what run_instances already declared from deploy_vars.
"""
from __future__ import annotations

import shlex
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import provision as provision_lib
from skypilot_tpu.provision import rest_cloud
from skypilot_tpu.provision import runpod_api
from skypilot_tpu.utils import command_runner as runner_lib

SSH_USER = 'root'

DEFAULT_IMAGE = 'runpod/base:0.6.2-cpu'

_STATE_MAP = {
    'CREATED': 'pending',
    'RUNNING': 'running',
    'RESTARTING': 'pending',
    'EXITED': 'stopped',
    'TERMINATED': 'terminated',
}

# Cluster bookkeeping + rank decoding via the shared REST-cloud
# scaffolding (rest_cloud.py).
_records = rest_cloud.ClusterRecords('runpod_cluster')


def split_plan(instance_type: str) -> tuple:
    """'2x_NVIDIA_RTX_4090_SECURE' -> (2, 'NVIDIA RTX 4090', 'SECURE')."""
    parts = instance_type.split('_')
    count = int(parts[0].rstrip('x'))
    cloud_type = parts[-1]
    if cloud_type not in ('SECURE', 'COMMUNITY'):
        cloud_type = 'SECURE'
        gpu = ' '.join(parts[1:])
    else:
        gpu = ' '.join(parts[1:-1])
    return count, gpu, cloud_type


def _live_pods(client, name: str) -> Dict[int, Dict[str, Any]]:
    out: Dict[int, Dict[str, Any]] = {}
    for pod in runpod_api.call(client, 'list_pods'):
        rank = rest_cloud.rank_of(pod.get('name') or '', name)
        if rank is None:
            continue
        if pod.get('desiredStatus') == 'TERMINATED':
            continue
        out[rank] = pod
    return out


def _bootstrap_docker_args() -> str:
    """Pod entry command: install sshd + the local public key, then hold
    the container open (reference utils.py setup_cmd)."""
    from skypilot_tpu import authentication
    _, pub_path = authentication.get_or_generate_keys()
    with open(pub_path, encoding='utf-8') as f:
        pub_key = f.read().strip()
    script = (
        'apt-get update && '
        'DEBIAN_FRONTEND=noninteractive apt-get install -y '
        'openssh-server rsync && '
        'mkdir -p /var/run/sshd ~/.ssh && '
        f'echo {shlex.quote(pub_key)} >> ~/.ssh/authorized_keys && '
        'chmod 700 ~/.ssh && chmod 600 ~/.ssh/authorized_keys && '
        'service ssh restart && sleep infinity')
    return f'bash -c {shlex.quote(script)}'


def _ports_spec(deploy_vars: Dict[str, Any]) -> str:
    """The pod's FIXED port set: ssh + every task port, declared at rent
    time (RunPod cannot open ports later)."""
    ports = ['22/tcp']
    for p in deploy_vars.get('ports') or []:
        if '-' in str(p):
            lo, hi = (int(x) for x in str(p).split('-', 1))
            ports.extend(f'{q}/tcp' for q in range(lo, hi + 1))
        else:
            ports.append(f'{int(p)}/tcp')
    return ','.join(ports)


# ---- provision API ---------------------------------------------------------
def run_instances(cluster_name: str, region: str, zone: Optional[str],
                  num_hosts: int, deploy_vars: Dict[str, Any]) -> None:
    del zone  # country codes only
    name = deploy_vars['cluster_name_on_cloud']
    use_spot = bool(deploy_vars.get('use_spot'))
    record = {'region': region, 'zone': None, 'name_on_cloud': name,
              'num_hosts': num_hosts, 'deploy_vars': deploy_vars}
    _records.save(cluster_name, record)
    client = runpod_api.get_client()
    count, gpu, cloud_type = split_plan(
        deploy_vars.get('instance_type', '1x_NVIDIA_RTX_4090_SECURE'))
    bid = None
    if use_spot:
        from skypilot_tpu import catalog
        total = catalog.get_instance_hourly_cost(
            deploy_vars['instance_type'], use_spot=True, region=region,
            cloud='runpod')
        bid = round(total / count, 4)
    try:
        existing = _live_pods(client, name)
        for rank in range(num_hosts):
            if rank in existing:
                continue  # idempotent relaunch
            runpod_api.call(
                client, 'create_pod',
                name=f'{name}-r{rank}',
                image=deploy_vars.get('image_id') or DEFAULT_IMAGE,
                gpu_type_id=gpu,
                gpu_count=count,
                cloud_type=cloud_type,
                country_code=region,
                disk_gb=int(deploy_vars.get('disk_size_gb') or 50),
                ports=_ports_spec(deploy_vars),
                docker_args=_bootstrap_docker_args(),
                bid_per_gpu=bid)
    except exceptions.InsufficientCapacityError:
        try:
            _terminate_all(client, name)
        except exceptions.CloudError:
            pass
        else:
            _records.delete(cluster_name)
        raise


def wait_instances(cluster_name: str, region: str, state: str = 'running',
                   timeout: float = 1800) -> None:
    if state != 'running':
        raise exceptions.NotSupportedError(
            'RunPod cannot stop pods (terminate-only).')
    rest_cloud.poll_for_state(
        cluster_name, lambda: query_instances(cluster_name, region),
        state, timeout)


def query_instances(cluster_name: str, region: str) -> Dict[str, str]:
    del region
    record = _records.load(cluster_name)
    if not record:
        return {}
    client = runpod_api.get_client()
    live = _live_pods(client, record['name_on_cloud'])
    if not live:
        return {}
    out: Dict[str, str] = {}
    for rank, pod in live.items():
        out[pod.get('name', f'r{rank}')] = _STATE_MAP.get(
            pod.get('desiredStatus', ''), 'unknown')
    for rank in range(int(record.get('num_hosts') or 0)):
        if rank not in live:
            # A preempted spot pod disappears from the list: the hole
            # classifies as capacity via the shared poll loop.
            out[f'rank{rank}-missing'] = 'terminated'
    return out


def stop_instances(cluster_name: str, region: str) -> None:
    raise exceptions.NotSupportedError(
        'RunPod cannot stop pods (terminate-only); '
        'use `skytpu down` instead.')


def _terminate_all(client, name: str) -> None:
    for pod in _live_pods(client, name).values():
        runpod_api.call(client, 'terminate_pod', pod_id=pod['id'])


def terminate_instances(cluster_name: str, region: str) -> None:
    del region
    record = _records.load(cluster_name)
    if not record:
        return
    client = runpod_api.get_client()
    _terminate_all(client, record['name_on_cloud'])
    _records.delete(cluster_name)


def _ssh_endpoint(pod: Dict[str, Any]) -> tuple:
    """(ip, public_port) of the pod's mapped ssh port."""
    runtime = pod.get('runtime') or {}
    for port in runtime.get('ports') or []:
        if port.get('privatePort') == 22 and port.get('isIpPublic'):
            return port.get('ip'), int(port.get('publicPort') or 22)
    return None, 22


def get_cluster_info(cluster_name: str,
                     region: str) -> provision_lib.ClusterInfo:
    del region
    record = _records.require(cluster_name, 'RunPod')
    client = runpod_api.get_client()
    live = _live_pods(client, record['name_on_cloud'])
    hosts: List[provision_lib.HostInfo] = []
    for rank in sorted(live):
        pod = live[rank]
        ip, port = _ssh_endpoint(pod)
        if ip is None:
            raise exceptions.ProvisionError(
                f'Pod {pod.get("name")!r} has no public ssh port mapping '
                'yet.')
        hosts.append(provision_lib.HostInfo(
            host_id=str(pod['id']), rank=rank,
            internal_ip=ip, external_ip=ip, ssh_port=port,
            extra={}))
    return provision_lib.ClusterInfo(
        cluster_name=cluster_name, cloud='runpod',
        region=record['region'], zone=None, hosts=hosts,
        deploy_vars=record['deploy_vars'])


def open_ports(cluster_name: str, region: str, ports: List[str]) -> None:
    """RunPod port sets are FIXED at rent time: run_instances already
    declared deploy_vars['ports']; this verifies the request is covered
    and raises an actionable error otherwise (re-renting the pod is the
    only way to change its ports)."""
    record = _records.require(cluster_name, 'RunPod')
    declared = _ports_spec(record.get('deploy_vars') or {})
    have = set(declared.split(','))
    missing = []
    for p in ports:
        if '-' in str(p):
            lo, hi = (int(x) for x in str(p).split('-', 1))
            missing.extend(f'{q}/tcp' for q in range(lo, hi + 1)
                           if f'{q}/tcp' not in have)
        elif f'{int(p)}/tcp' not in have:
            missing.append(f'{int(p)}/tcp')
    if missing:
        raise exceptions.NotSupportedError(
            f'RunPod pods cannot open ports after creation; {missing} '
            'were not declared at launch. Add them to resources.ports '
            'and relaunch.')


def get_command_runners(cluster_info: provision_lib.ClusterInfo,
                        ssh_credentials: Optional[Dict[str, str]] = None
                        ) -> List[runner_lib.CommandRunner]:
    return rest_cloud.ssh_runners(cluster_info, SSH_USER, ssh_credentials)
