"""Cloud-agnostic provisioning API (functional, routed by cloud name).

Counterpart of reference ``sky/provision/__init__.py`` (_route_to_cloud_impl
:37, API surface :70-197). Each cloud module implements the same function
names; the router dispatches ``provision.<fn>(cloud, ...)`` to
``skypilot_tpu.provision.<cloud>.<fn>``.

The unit of provisioning is a *host group*: for TPU slices, hosts are the
slice's TPU-VM workers created atomically by one tpu.googleapis.com node
(the gang is the slice — no placement groups needed, unlike reference
RayCodeGen sky/backends/cloud_vm_ray_backend.py:389-545).
"""
from __future__ import annotations

import dataclasses
import functools
import importlib
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions

_CLOUD_MODULES = {
    'local': 'skypilot_tpu.provision.local_impl',
    'gcp': 'skypilot_tpu.provision.gcp',
    'aws': 'skypilot_tpu.provision.aws',
    'azure': 'skypilot_tpu.provision.azure',
    'kubernetes': 'skypilot_tpu.provision.kubernetes',
    'lambda': 'skypilot_tpu.provision.lambda_impl',
    'do': 'skypilot_tpu.provision.do_impl',
    'fluidstack': 'skypilot_tpu.provision.fluidstack_impl',
    'vast': 'skypilot_tpu.provision.vast_impl',
    'runpod': 'skypilot_tpu.provision.runpod_impl',
    'paperspace': 'skypilot_tpu.provision.paperspace_impl',
    'hyperstack': 'skypilot_tpu.provision.hyperstack_impl',
    'oci': 'skypilot_tpu.provision.oci_impl',
    'cudo': 'skypilot_tpu.provision.cudo_impl',
}


@dataclasses.dataclass
class HostInfo:
    """One reachable host (TPU-VM worker or VM)."""
    host_id: str
    rank: int
    internal_ip: str
    external_ip: Optional[str] = None
    ssh_port: int = 22
    # Cloud-specific bag (local: host_dir; gcp: instance metadata).
    extra: Dict[str, Any] = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class ClusterInfo:
    """Everything the backend/runtime needs to reach a provisioned cluster."""
    cluster_name: str
    cloud: str
    region: str
    zone: Optional[str]
    hosts: List[HostInfo]
    deploy_vars: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def head(self) -> HostInfo:
        return self.hosts[0]

    @property
    def num_hosts(self) -> int:
        return len(self.hosts)


def _route(fn_name: str, cloud: str):
    module_path = _CLOUD_MODULES.get(cloud)
    if module_path is None:
        raise exceptions.CloudError(f'No provisioner for cloud {cloud!r}')
    module = importlib.import_module(module_path)
    impl = getattr(module, fn_name, None)
    if impl is None:
        raise exceptions.CloudError(
            f'Provisioner for {cloud!r} does not implement {fn_name}')
    return impl


def _cloud_api(fn):
    @functools.wraps(fn)
    def wrapper(cloud: str, *args, **kwargs):
        return _route(fn.__name__, cloud)(*args, **kwargs)
    return wrapper


# ---- routed API (signatures shown by the no-op bodies) ---------------------
@_cloud_api
def run_instances(cluster_name: str, region: str, zone: Optional[str],
                  num_hosts: int, deploy_vars: Dict[str, Any]) -> None:
    """Create (or restart) the host group; idempotent."""


@_cloud_api
def wait_instances(cluster_name: str, region: str,
                   state: str = 'running', timeout: float = 1800) -> None:
    """Block until every host reaches `state` ('running'|'stopped')."""


@_cloud_api
def stop_instances(cluster_name: str, region: str) -> None:
    """Stop all hosts, keeping disks."""


@_cloud_api
def terminate_instances(cluster_name: str, region: str) -> None:
    """Delete the host group entirely."""


@_cloud_api
def query_instances(cluster_name: str, region: str) -> Dict[str, str]:
    """host_id -> raw cloud state ('running'/'stopped'/'terminated'/...)."""


@_cloud_api
def get_cluster_info(cluster_name: str, region: str) -> 'ClusterInfo':
    """Describe a provisioned cluster (hosts in stable rank order)."""


@_cloud_api
def open_ports(cluster_name: str, region: str, ports: List[str]) -> None:
    """Expose ports on the cluster's network."""


@_cloud_api
def get_command_runners(cluster_info: 'ClusterInfo',
                        ssh_credentials: Optional[Dict[str, str]] = None
                        ) -> List[Any]:
    """One CommandRunner per host, rank order (head first)."""


@_cloud_api
def create_image_from_cluster(cluster_name: str, region: str,
                              image_name: str) -> str:
    """Snapshot the (stopped) cluster's head boot disk into a reusable
    image; returns the image id a new launch can pass as ``image_id``
    (reference ``--clone-disk-from``, sky/execution.py:38-55)."""
