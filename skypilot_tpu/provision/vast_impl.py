"""Vast.ai provisioner: marketplace GPU instances with interruptible
bids.

Counterpart of reference ``sky/provision/vast/instance.py`` +
``utils.py`` (offer search -> create from offer; '-head'/'-worker'
labels; min_bid for preemptible). The seventh VM cloud, and the first
REST cloud with real SPOT semantics: ``use_spot`` becomes an
interruptible bid, and an instance the marketplace pauses (outbid /
host reclaim) is detected as a preemption — driving the same
managed-jobs recovery machinery as GCP/AWS spot.

Vast-isms:
- capacity is an EMPTY OFFER SEARCH, not an error code: the
  marketplace either has a matching machine right now or it doesn't
  (reference utils.py:101-103 raises on empty search);
- instance types are synthetic ``{n}x_{GPU_NAME}`` plans (the
  marketplace has no instance types; reference invents the same,
  utils.py:80-87); 'regions' are two-letter country codes snipped from
  host geolocations (utils.py:61-69);
- SSH lands on a host-mapped port (``ssh_host:ssh_port``), not 22 —
  the one cloud here exercising HostInfo.ssh_port;
- interruptible instances PAUSE when outbid (status 'stopped' without
  us stopping them): the wait loop's extra_check flags that as
  capacity so failover/recovery fires (same shape as Azure's
  spot-deallocate detection).

On-demand instances also support clean stop/start.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import provision as provision_lib
from skypilot_tpu.provision import rest_cloud
from skypilot_tpu.provision import vast_api
from skypilot_tpu.utils import command_runner as runner_lib

SSH_USER = 'root'  # Vast containers log in as root

DEFAULT_IMAGE = 'ubuntu:22.04'

# Bid margin over the offer's min_bid for interruptible rentals: high
# enough to not be instantly outbid, far below on-demand dph.
BID_MARGIN = 1.25

# Polls of persistent 'stopped' before an interruptible cluster that
# never reached running is declared preempted (~30s at the 5s poll
# interval): start_instance lands asynchronously on the real API, so a
# restart must not be misread as an outbid pause.
OUTBID_GRACE_POLLS = 6

# Vast actual_status -> provision API state words.
_STATE_MAP = {
    'created': 'pending',
    'loading': 'pending',
    'connecting': 'pending',
    'running': 'running',
    'stopping': 'stopping',
    'stopped': 'stopped',   # on-demand stop OR interruptible pause
    'exited': 'stopped',
    'offline': 'pending',
    'destroyed': 'terminated',
}

# Cluster bookkeeping + rank decoding via the shared REST-cloud
# scaffolding (rest_cloud.py).
_records = rest_cloud.ClusterRecords('vast_cluster')


def split_plan(instance_type: str) -> tuple:
    """'4x_RTX_4090' -> (4, 'RTX 4090')."""
    count, _, gpu = instance_type.partition('x_')
    return int(count or 1), gpu.replace('_', ' ')


def _live_instances(client, name: str) -> Dict[int, Dict[str, Any]]:
    """rank -> instance by label. Offers are machine-specific, so a
    region filter is unnecessary: ranks are only created from offers in
    the record's region, and labels are cluster-scoped."""
    out: Dict[int, Dict[str, Any]] = {}
    for inst in vast_api.call(client, 'list_instances'):
        rank = rest_cloud.rank_of(inst.get('label') or '', name)
        if rank is None:
            continue
        if inst.get('actual_status') in ('destroyed',):
            continue
        out[rank] = inst
    return out


def _onstart_cmd() -> str:
    """Container bootstrap: install the local public key for root ssh
    (Vast images start sshd; the key lands via the API's onstart)."""
    from skypilot_tpu import authentication
    _, pub_path = authentication.get_or_generate_keys()
    with open(pub_path, encoding='utf-8') as f:
        pub_key = f.read().strip()
    return ('mkdir -p ~/.ssh && '
            f'grep -qF "{pub_key}" ~/.ssh/authorized_keys 2>/dev/null || '
            f'echo "{pub_key}" >> ~/.ssh/authorized_keys')


# ---- provision API ---------------------------------------------------------
def run_instances(cluster_name: str, region: str, zone: Optional[str],
                  num_hosts: int, deploy_vars: Dict[str, Any]) -> None:
    del zone  # marketplace has no zones
    name = deploy_vars['cluster_name_on_cloud']
    use_spot = bool(deploy_vars.get('use_spot'))
    record = {'region': region, 'zone': None, 'name_on_cloud': name,
              'num_hosts': num_hosts, 'deploy_vars': deploy_vars,
              'interruptible': use_spot}
    _records.save(cluster_name, record)
    client = vast_api.get_client()
    num_gpus, gpu_name = split_plan(
        deploy_vars.get('instance_type', '1x_RTX_4090'))
    disk_gb = float(deploy_vars.get('disk_size_gb') or 100)
    try:
        existing = _live_instances(client, name)
        for rank, inst in existing.items():
            if _STATE_MAP.get(inst.get('actual_status', '')) == 'stopped':
                vast_api.call(client, 'start_instance',
                              instance_id=inst['id'])
        missing = [r for r in range(num_hosts) if r not in existing]
        if missing:
            offers = vast_api.call(
                client, 'search_offers', gpu_name=gpu_name,
                num_gpus=num_gpus, geolocation=region,
                min_disk_gb=disk_gb)
            if len(offers) < len(missing):
                # The marketplace has no matching machines right now:
                # that IS the capacity signal (reference utils.py:101).
                raise exceptions.InsufficientCapacityError(
                    f'Vast marketplace has {len(offers)} offer(s) for '
                    f'{num_gpus}x {gpu_name} in {region}, need '
                    f'{len(missing)}', reason='capacity')
            onstart = _onstart_cmd()
            for rank, offer in zip(missing, offers):
                bid = (round(float(offer.get('min_bid', 0.0))
                             * BID_MARGIN, 4) if use_spot else None)
                vast_api.call(
                    client, 'create_instance',
                    offer_id=offer['id'],
                    label=f'{name}-r{rank}',
                    image=deploy_vars.get('image_id') or DEFAULT_IMAGE,
                    disk_gb=disk_gb,
                    onstart_cmd=onstart,
                    bid_per_hour=bid)
    except exceptions.InsufficientCapacityError:
        try:
            _terminate_all(client, name)
        except exceptions.CloudError:
            pass
        else:
            _records.delete(cluster_name)
        raise


def wait_instances(cluster_name: str, region: str, state: str = 'running',
                   timeout: float = 1800) -> None:
    record = _records.load(cluster_name) or {}
    interruptible = bool(record.get('interruptible'))
    saw_running = [False]
    stopped_polls = [0]
    grace_polls = OUTBID_GRACE_POLLS

    def outbid_check(states: set) -> Optional[Exception]:
        # An interruptible instance PAUSES when outbid: persistent
        # 'stopped' while waiting for running means the bid lost —
        # classify as capacity so failover/recovery fires (same shape
        # as azure.py's spot-deallocation detection). On-demand
        # clusters only flag it after a seen running state, so a
        # stopped cluster being restarted is never misread.
        saw_running[0] = saw_running[0] or 'running' in states
        if state != 'running' or 'stopped' not in states:
            stopped_polls[0] = 0
            return None
        stopped_polls[0] += 1
        if saw_running[0] or (interruptible
                              and stopped_polls[0] > grace_polls):
            return exceptions.InsufficientCapacityError(
                f'{cluster_name}: instance paused while waiting for '
                'running (outbid / host reclaim?)', reason='capacity')
        return None

    rest_cloud.poll_for_state(
        cluster_name, lambda: query_instances(cluster_name, region),
        state, timeout, extra_check=outbid_check)


def query_instances(cluster_name: str, region: str) -> Dict[str, str]:
    del region
    record = _records.load(cluster_name)
    if not record:
        return {}
    client = vast_api.get_client()
    live = _live_instances(client, record['name_on_cloud'])
    if not live:
        return {}
    out: Dict[str, str] = {}
    for rank, inst in live.items():
        out[inst.get('label', f'r{rank}')] = _STATE_MAP.get(
            inst.get('actual_status', ''), 'unknown')
    for rank in range(int(record.get('num_hosts') or 0)):
        if rank not in live:
            out[f'rank{rank}-missing'] = 'terminated'
    return out


def stop_instances(cluster_name: str, region: str) -> None:
    record = _records.require(cluster_name, 'Vast')
    client = vast_api.get_client()
    for inst in _live_instances(client, record['name_on_cloud']).values():
        if _STATE_MAP.get(inst.get('actual_status', '')) in ('pending',
                                                             'running'):
            vast_api.call(client, 'stop_instance',
                          instance_id=inst['id'])


def _terminate_all(client, name: str) -> None:
    for inst in _live_instances(client, name).values():
        vast_api.call(client, 'destroy_instance', instance_id=inst['id'])


def terminate_instances(cluster_name: str, region: str) -> None:
    del region
    record = _records.load(cluster_name)
    if not record:
        return
    client = vast_api.get_client()
    _terminate_all(client, record['name_on_cloud'])
    _records.delete(cluster_name)


def get_cluster_info(cluster_name: str,
                     region: str) -> provision_lib.ClusterInfo:
    del region
    record = _records.require(cluster_name, 'Vast')
    client = vast_api.get_client()
    live = _live_instances(client, record['name_on_cloud'])
    hosts: List[provision_lib.HostInfo] = []
    for rank in sorted(live):
        inst = live[rank]
        ssh_host = inst.get('ssh_host') or inst.get('public_ipaddr')
        if not ssh_host:
            raise exceptions.ProvisionError(
                f'No ssh host on instance {inst.get("label")!r} yet.')
        hosts.append(provision_lib.HostInfo(
            host_id=str(inst['id']), rank=rank,
            # Rendezvous inside Vast's overlay uses the instance's own
            # address; control-plane ssh goes through the host-mapped
            # port below.
            internal_ip=inst.get('local_ipaddr') or ssh_host,
            external_ip=ssh_host,
            ssh_port=int(inst.get('ssh_port') or 22),
            extra={}))
    return provision_lib.ClusterInfo(
        cluster_name=cluster_name, cloud='vast',
        region=record['region'], zone=None, hosts=hosts,
        deploy_vars=record['deploy_vars'])


# No open_ports: Vast exposes host-mapped ports chosen by the host, not
# arbitrary firewall rules; the cloud class omits OPEN_PORTS.


def get_command_runners(cluster_info: provision_lib.ClusterInfo,
                        ssh_credentials: Optional[Dict[str, str]] = None
                        ) -> List[runner_lib.CommandRunner]:
    # ssh_runners honors each HostInfo's host-mapped ssh_port.
    return rest_cloud.ssh_runners(cluster_info, SSH_USER, ssh_credentials)
