"""Thin Paperspace REST client with a test seam.

Counterpart of the reference's ``sky/provision/paperspace/utils.py``
(PaperspaceCloudClient over ``https://api.paperspace.com/v1``,
bearer-token auth from ``~/.paperspace/config.json``). The real
transport is a tiny urllib client; tests install an in-process fake via
``set_paperspace_factory`` implementing the same flat surface
(``create_machine``, ``list_machines``, ``start/stop/delete_machine``),
so the full stop-capable lifecycle runs with no cloud.

Error classification: capacity wording ("out of capacity",
"no available machines") -> failover; team-limit wording -> quota.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import rest_cloud

API_ENDPOINT = 'https://api.paperspace.com/v1'
CREDENTIALS_PATH = '~/.paperspace/config.json'

_CAPACITY_MARKERS = (
    'out of capacity',
    'no available machines',
    'not currently available',
)
_QUOTA_MARKERS = (
    'machine limit',
    'team limit',
    'quota',
)


class PaperspaceApiError(Exception):
    """Fake/real client error carrying an HTTP status + message."""

    def __init__(self, status: int, message: str = ''):
        super().__init__(message or str(status))
        self.status = status
        self.message = message or str(status)


classify_error = rest_cloud.marker_classifier(_CAPACITY_MARKERS,
                                              _QUOTA_MARKERS)


def read_api_key() -> Optional[str]:
    env = os.environ.get('PAPERSPACE_API_KEY')
    if env:
        return env
    path = os.path.expanduser(CREDENTIALS_PATH)
    if os.path.exists(path):
        try:
            with open(path, encoding='utf-8') as f:
                cfg = json.load(f)
            return cfg.get('apiKey') or None
        except (ValueError, OSError):
            return None
    return None


def _parse_error(status: int, raw: bytes) -> Exception:
    try:
        err = json.loads(raw.decode())
        msg = (err.get('message')
               or (err.get('error') or {}).get('message')
               or raw.decode())
        return PaperspaceApiError(status, str(msg))
    except (ValueError, AttributeError):
        return PaperspaceApiError(
            status, raw.decode(errors='replace') or str(status))


class _RestClient:
    """Flat op surface over the shared retrying urllib transport."""

    def __init__(self):
        api_key = read_api_key()
        if api_key is None:
            raise exceptions.CloudError(
                'Paperspace credentials not found: set '
                f'$PAPERSPACE_API_KEY or log in ({CREDENTIALS_PATH}).')
        self._headers = {'Authorization': f'Bearer {api_key}',
                         'Content-Type': 'application/json'}

    def _request(self, method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        return rest_cloud.retrying_request(
            method, f'{API_ENDPOINT}{path}', self._headers, payload,
            _parse_error)

    # -- flat op surface (mirrored by test fakes) ---------------------------
    def list_startup_scripts(self) -> List[Dict[str, Any]]:
        body = self._request('GET', '/startup-scripts?limit=200')
        items = body.get('items')
        if items is None:
            items = body.get('data') or []
        return list(items)

    def create_startup_script(self, name: str,
                              script: str) -> Dict[str, Any]:
        body = self._request('POST', '/startup-scripts', {
            'name': name, 'script': script, 'isRunOnce': False,
            'isEnabled': True,
        })
        return dict(body.get('data') or body)

    def create_machine(self, name: str, machine_type: str, region: str,
                       disk_gb: int, startup_script_id: str,
                       template_id: str = 'tkni3aa4'  # Ubuntu 22.04
                       ) -> Dict[str, Any]:
        # The v1 API only takes a PERSISTED startup script by id
        # (startupScriptId) — an inline script field is silently ignored
        # and the machine would boot keyless (reference
        # sky/provision/paperspace/utils.py set_sky_key_script persists
        # the object for the same reason).
        body = self._request('POST', '/machines', {
            'name': name, 'machineType': machine_type, 'region': region,
            'diskSize': disk_gb, 'templateId': template_id,
            'publicIpType': 'dynamic',
            'startupScriptId': startup_script_id,
        })
        return dict(body.get('data') or body)

    def list_machines(self) -> List[Dict[str, Any]]:
        body = self._request('GET', '/machines?limit=200')
        items = body.get('items')
        if items is None:
            items = body.get('data') or []
        return list(items)

    def start_machine(self, machine_id: str) -> None:
        self._request('PATCH', f'/machines/{machine_id}/start')

    def stop_machine(self, machine_id: str) -> None:
        self._request('PATCH', f'/machines/{machine_id}/stop')

    def delete_machine(self, machine_id: str) -> None:
        self._request('DELETE', f'/machines/{machine_id}')


# Test seam (``set_paperspace_factory(lambda: fake)``), client
# construction and error-normalizing ``call`` via the shared ClientSeam.
_seam = rest_cloud.ClientSeam(_RestClient, PaperspaceApiError,
                              classify_error)
set_paperspace_factory = _seam.set_factory
get_client = _seam.get_client
call = _seam.call
