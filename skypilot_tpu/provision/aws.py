"""AWS provisioner: EC2 VM host groups (controllers, CPU tasks, storage).

Counterpart of reference ``sky/provision/aws/instance.py`` (956 LoC of EC2
ops) + ``config.py`` (security-group bootstrap). Differences in this
TPU-native stack: no TPU accelerators on AWS — EC2 covers the multi-cloud
half of the story (controllers, CPU tasks, egress-optimized placement,
inter-cloud storage), with the same record/classification/failover shape
as the GCP provisioner so ``RetryingProvisioner`` drives both identically.

Cluster bookkeeping (region, AZ, name-on-cloud) lives in the client state
kv, mirroring ``provision/gcp.py``.
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import authentication
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import provision as provision_lib
from skypilot_tpu.provision import aws_api
from skypilot_tpu.utils import command_runner as runner_lib

_TAG_CLUSTER = 'skytpu-cluster'
_TAG_RANK = 'skytpu-rank'

_EC2_STATE_MAP = {
    'pending': 'pending', 'running': 'running', 'stopping': 'stopping',
    'stopped': 'stopped', 'shutting-down': 'terminating',
    'terminated': 'terminated',
}

SSH_USER = 'ubuntu'  # canonical Ubuntu AMI login


# ---- cluster record --------------------------------------------------------
def _record_key(cluster_name: str) -> str:
    return f'aws_cluster/{cluster_name}'


def _save_record(cluster_name: str, record: Dict[str, Any]) -> None:
    global_user_state.set_kv(_record_key(cluster_name), json.dumps(record))


def _load_record(cluster_name: str) -> Optional[Dict[str, Any]]:
    raw = global_user_state.get_kv(_record_key(cluster_name))
    return json.loads(raw) if raw else None


def _delete_record(cluster_name: str) -> None:
    global_user_state.set_kv(_record_key(cluster_name), '')


def _require_record(cluster_name: str) -> Dict[str, Any]:
    record = _load_record(cluster_name)
    if not record:
        raise exceptions.ClusterError(
            f'No AWS provisioning record for {cluster_name!r}')
    return record


def _sg_name(name_on_cloud: str) -> str:
    return f'skytpu-{name_on_cloud}-sg'


def _key_name() -> str:
    return 'skytpu-key'


def _live_instances(ec2, name: str,
                    states: Optional[List[str]] = None
                    ) -> List[Dict[str, Any]]:
    filters = [{'Name': f'tag:{_TAG_CLUSTER}', 'Values': [name]}]
    if states is None:
        states = ['pending', 'running', 'stopping', 'stopped']
    filters.append({'Name': 'instance-state-name', 'Values': states})
    resp = aws_api.call(ec2, 'describe_instances', Filters=filters)
    return aws_api.instances_from_describe(resp)


def _ensure_key_pair(ec2) -> str:
    """Import the skytpu ed25519 public key as an EC2 key pair
    (idempotent; reference uses per-cluster keys via cluster YAML)."""
    name = _key_name()
    resp = aws_api.call(ec2, 'describe_key_pairs')
    if any(kp.get('KeyName') == name for kp in resp.get('KeyPairs', [])):
        return name
    _, pub_path = authentication.get_or_generate_keys()
    with open(pub_path) as f:
        pub = f.read().strip()
    aws_api.call(ec2, 'import_key_pair', KeyName=name,
                 PublicKeyMaterial=pub.encode())
    return name


def _ensure_security_group(ec2, name: str) -> str:
    """Per-cluster SG with SSH open; serve/task ports added by
    open_ports (reference sky/provision/aws/config.py SG bootstrap)."""
    sg_name = _sg_name(name)
    resp = aws_api.call(ec2, 'describe_security_groups', Filters=[
        {'Name': 'group-name', 'Values': [sg_name]}])
    groups = resp.get('SecurityGroups', [])
    if groups:
        return groups[0]['GroupId']
    created = aws_api.call(ec2, 'create_security_group',
                           GroupName=sg_name,
                           Description=f'skytpu cluster {name}')
    sg_id = created['GroupId']
    aws_api.call(ec2, 'authorize_security_group_ingress',
                 GroupId=sg_id,
                 IpPermissions=[{'IpProtocol': 'tcp', 'FromPort': 22,
                                 'ToPort': 22,
                                 'IpRanges': [{'CidrIp': '0.0.0.0/0'}]}])
    return sg_id


# ---- provision API ---------------------------------------------------------
def run_instances(cluster_name: str, region: str, zone: Optional[str],
                  num_hosts: int, deploy_vars: Dict[str, Any]) -> None:
    name = deploy_vars['cluster_name_on_cloud']
    record = {'region': region, 'zone': zone, 'name_on_cloud': name,
              'num_hosts': num_hosts, 'deploy_vars': deploy_vars}
    # Record BEFORE creating (partial-failure resources must stay
    # reachable by terminate_instances; same contract as provision/gcp.py).
    _save_record(cluster_name, record)
    ec2 = aws_api.get_ec2(region)
    try:
        key_name = _ensure_key_pair(ec2)
        sg_id = _ensure_security_group(ec2, name)
        existing = {aws_api.tag_value(i, _TAG_RANK): i
                    for i in _live_instances(ec2, name)}
        to_start = []
        missing_ranks = []
        for rank in range(num_hosts):
            inst = existing.get(str(rank))
            if inst is None:
                missing_ranks.append(rank)
            elif inst['State']['Name'] == 'stopped':
                to_start.append(inst['InstanceId'])
        if to_start:
            aws_api.call(ec2, 'start_instances', InstanceIds=to_start)
        image_id = deploy_vars.get('image_id')
        if image_id is None and missing_ranks:
            image_id = aws_api.resolve_default_ami(region)
        for rank in missing_ranks:
            placement: Dict[str, Any] = {}
            if zone:
                placement['AvailabilityZone'] = zone
            market = ({'MarketType': 'spot', 'SpotOptions': {
                'InstanceInterruptionBehavior': 'terminate'}}
                if deploy_vars.get('use_spot') else None)
            kwargs: Dict[str, Any] = dict(
                ImageId=image_id,
                InstanceType=deploy_vars.get('instance_type', 'm6i.large'),
                MinCount=1, MaxCount=1,
                KeyName=key_name,
                SecurityGroupIds=[sg_id],
                Placement=placement,
                BlockDeviceMappings=[{
                    'DeviceName': '/dev/sda1',
                    'Ebs': {'VolumeSize':
                            deploy_vars.get('disk_size_gb', 256),
                            'DeleteOnTermination': True},
                }],
                TagSpecifications=[{
                    'ResourceType': 'instance',
                    'Tags': [
                        {'Key': _TAG_CLUSTER, 'Value': name},
                        {'Key': _TAG_RANK, 'Value': str(rank)},
                        {'Key': 'Name', 'Value': f'{name}-{rank}'},
                    ] + [{'Key': k, 'Value': str(v)} for k, v in
                         (deploy_vars.get('labels') or {}).items()],
                }],
            )
            if market:
                kwargs['InstanceMarketOptions'] = market
            aws_api.call(ec2, 'run_instances', **kwargs)
    except exceptions.InsufficientCapacityError:
        # Clean up any partial hosts, then drop the record so zone
        # failover retries don't see a stale pointer.
        try:
            _terminate_all(ec2, name)
        except exceptions.CloudError:
            pass
        _delete_record(cluster_name)
        raise


def wait_instances(cluster_name: str, region: str, state: str = 'running',
                   timeout: float = 1800) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        states = set(query_instances(cluster_name, region).values())
        if states == {state}:
            return
        if not states or 'terminated' in states or 'terminating' in states:
            # Empty set = every host gone (EC2 spot reclaim deletes, it
            # doesn't stop) — same capacity classification as a partial
            # loss so failover fires immediately instead of timing out.
            raise exceptions.InsufficientCapacityError(
                f'{cluster_name}: instance(s) terminated while waiting '
                f'for {state} (spot reclaim?)', reason='capacity')
        time.sleep(5)
    raise exceptions.ProvisionError(
        f'{cluster_name} did not reach {state!r} within {timeout}s')


def query_instances(cluster_name: str, region: str) -> Dict[str, str]:
    """Live host states. A PARTIALLY-dead cluster reports its missing
    ranks as 'terminated' (managed-job recovery must see the hole —
    same contract as the GCP multi-slice path); a fully-dead cluster
    returns {} ("terminated cluster" contract in core.py). Terminated
    EC2 instances linger in describe_instances for ~an hour, so absence
    is judged per-rank against the record's num_hosts, not by reading
    terminated rows (which would outlive relaunches)."""
    record = _load_record(cluster_name)
    if not record:
        return {}
    ec2 = aws_api.get_ec2(record['region'])
    out: Dict[str, str] = {}
    live_ranks = set()
    for inst in _live_instances(ec2, record['name_on_cloud']):
        raw = inst['State']['Name']
        out[inst['InstanceId']] = _EC2_STATE_MAP.get(raw, 'unknown')
        live_ranks.add(aws_api.tag_value(inst, _TAG_RANK))
    if not out:
        return {}
    for rank in range(int(record.get('num_hosts') or 0)):
        if str(rank) not in live_ranks:
            out[f'rank{rank}-missing'] = 'terminated'
    return out


def stop_instances(cluster_name: str, region: str) -> None:
    record = _require_record(cluster_name)
    ec2 = aws_api.get_ec2(record['region'])
    ids = [i['InstanceId'] for i in _live_instances(
        ec2, record['name_on_cloud'], states=['pending', 'running'])]
    if ids:
        aws_api.call(ec2, 'stop_instances', InstanceIds=ids)


def _terminate_all(ec2, name: str) -> None:
    ids = [i['InstanceId'] for i in _live_instances(ec2, name)]
    if ids:
        aws_api.call(ec2, 'terminate_instances', InstanceIds=ids)


def terminate_instances(cluster_name: str, region: str) -> None:
    record = _load_record(cluster_name)
    if not record:
        return
    ec2 = aws_api.get_ec2(record['region'])
    name = record['name_on_cloud']
    _terminate_all(ec2, name)
    # Best-effort SG cleanup (fails with DependencyViolation while
    # instances are shutting down — retried briefly, then left; the SG is
    # free and reused on relaunch).
    for _ in range(6):
        try:
            resp = aws_api.call(ec2, 'describe_security_groups', Filters=[
                {'Name': 'group-name', 'Values': [_sg_name(name)]}])
            groups = resp.get('SecurityGroups', [])
            if not groups:
                break
            aws_api.call(ec2, 'delete_security_group',
                         GroupId=groups[0]['GroupId'])
            break
        except exceptions.CloudError:
            time.sleep(2)
    _delete_record(cluster_name)


def get_cluster_info(cluster_name: str,
                     region: str) -> provision_lib.ClusterInfo:
    record = _require_record(cluster_name)
    ec2 = aws_api.get_ec2(record['region'])
    hosts: List[provision_lib.HostInfo] = []
    insts = _live_instances(ec2, record['name_on_cloud'])
    insts.sort(key=lambda i: int(aws_api.tag_value(i, _TAG_RANK) or 0))
    for inst in insts:
        rank = int(aws_api.tag_value(inst, _TAG_RANK) or 0)
        hosts.append(provision_lib.HostInfo(
            host_id=inst['InstanceId'], rank=rank,
            internal_ip=inst.get('PrivateIpAddress', ''),
            external_ip=inst.get('PublicIpAddress'),
            extra={}))
    return provision_lib.ClusterInfo(
        cluster_name=cluster_name, cloud='aws', region=record['region'],
        zone=record.get('zone'), hosts=hosts,
        deploy_vars=record['deploy_vars'])


def open_ports(cluster_name: str, region: str, ports: List[str]) -> None:
    """Authorize task/serve ports on the cluster's security group
    (reference sky/provision/aws/instance.py open_ports). Source ranges
    configurable via ``aws.firewall_source_ranges`` like GCP's."""
    if not ports:
        return
    record = _require_record(cluster_name)
    ec2 = aws_api.get_ec2(record['region'])
    name = record['name_on_cloud']
    resp = aws_api.call(ec2, 'describe_security_groups', Filters=[
        {'Name': 'group-name', 'Values': [_sg_name(name)]}])
    groups = resp.get('SecurityGroups', [])
    if not groups:
        raise exceptions.ClusterError(
            f'security group {_sg_name(name)} missing for {cluster_name}')
    sg = groups[0]
    # Keyed by (lo, hi) → currently-authorized IPv4 source ranges (tcp
    # only; IPv6/udp rules added out of band are left untouched), so a
    # tightened aws.firewall_source_ranges re-applies to already-open
    # ports, matching gcp.open_ports' patch behavior. New CIDRs are
    # authorized BEFORE stale ones are revoked: a failure mid-way must
    # never leave a previously-open serving port fully closed.
    have: Dict[Any, set] = {}
    for p in sg.get('IpPermissions', []):
        if p.get('IpProtocol') != 'tcp':
            continue
        key = (p.get('FromPort'), p.get('ToPort'))
        have.setdefault(key, set()).update(
            r.get('CidrIp') for r in p.get('IpRanges', []))
    from skypilot_tpu import config as config_lib
    ranges = config_lib.get_nested(('aws', 'firewall_source_ranges'),
                                   ['0.0.0.0/0'])
    perms = []
    revoke = []
    for port in ports:
        # Port specs are ints or 'lo-hi' ranges (resources._parse_ports).
        if '-' in str(port):
            lo, hi = (int(p) for p in str(port).split('-', 1))
        else:
            lo = hi = int(port)
        existing = have.get((lo, hi), set())
        to_add = [r for r in ranges if r not in existing]
        to_remove = sorted(existing - set(ranges) - {None})
        if to_add:
            perms.append({'IpProtocol': 'tcp', 'FromPort': lo,
                          'ToPort': hi,
                          'IpRanges': [{'CidrIp': r} for r in to_add]})
        if to_remove:
            revoke.append({'IpProtocol': 'tcp', 'FromPort': lo,
                           'ToPort': hi,
                           'IpRanges': [{'CidrIp': r} for r in to_remove]})
    if perms:
        aws_api.call(ec2, 'authorize_security_group_ingress',
                     GroupId=sg['GroupId'], IpPermissions=perms)
    if revoke:
        aws_api.call(ec2, 'revoke_security_group_ingress',
                     GroupId=sg['GroupId'], IpPermissions=revoke)


def get_command_runners(cluster_info: provision_lib.ClusterInfo,
                        ssh_credentials: Optional[Dict[str, str]] = None
                        ) -> List[runner_lib.CommandRunner]:
    creds = ssh_credentials or {}
    key_path = creds.get('key_path')
    if key_path is None:
        key_path, _ = authentication.get_or_generate_keys()
    user = creds.get('user', SSH_USER)
    runners: List[runner_lib.CommandRunner] = []
    for h in cluster_info.hosts:
        ip = h.external_ip or h.internal_ip
        runners.append(runner_lib.SSHCommandRunner(ip, user, key_path))
    return runners


def create_image_from_cluster(cluster_name: str, region: str,
                              image_name: str) -> str:
    """AMI from the stopped cluster's head instance (reference
    ``--clone-disk-from``; EC2 CreateImage on a stopped instance is a
    consistent snapshot)."""
    record = _require_record(cluster_name)
    ec2 = aws_api.get_ec2(record['region'])
    insts = _live_instances(ec2, record['name_on_cloud'])
    head = next((i for i in insts
                 if aws_api.tag_value(i, _TAG_RANK) == '0'), None)
    if head is None:
        raise exceptions.ClusterError(
            f'{cluster_name}: no rank-0 instance to image')
    resp = aws_api.call(ec2, 'create_image',
                        InstanceId=head['InstanceId'], Name=image_name)
    image_id = resp['ImageId']
    deadline = time.time() + 900
    while time.time() < deadline:
        desc = aws_api.call(ec2, 'describe_images', ImageIds=[image_id])
        images = desc.get('Images', [])
        state = images[0].get('State') if images else None
        if state == 'available':
            return image_id
        if state in ('failed', 'error'):
            raise exceptions.CloudError(
                f'AMI {image_id} creation failed')
        time.sleep(5)
    raise exceptions.ProvisionError(f'AMI {image_id} not available in time')
