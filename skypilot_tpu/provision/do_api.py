"""Thin DigitalOcean REST client with a test seam.

Counterpart of the reference's ``sky/provision/do/utils.py`` (pydo
client wrapper: droplet CRUD, ssh keys, per-error classification). The
real transport is a tiny urllib client over the public v2 REST API —
no pydo SDK needed; tests install an in-process fake via
``set_do_factory`` implementing the same flat surface
(``create_droplet``, ``list_droplets``, ``droplet_action``,
``delete_droplet``, ssh keys, firewalls), so lifecycle + failover logic
runs for real with no cloud.

Auth (reference utils.py:23-94): ``$DIGITALOCEAN_ACCESS_TOKEN`` first,
then doctl config files (``access-token`` / ``auth-contexts``).

Error classification: 422 capacity wording ("currently unavailable",
"not enough available capacity") -> zone/region failover;
droplet-limit wording -> quota; everything else -> plain CloudError.
"""
from __future__ import annotations

import json
import os
import urllib.parse
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import rest_cloud

API_ENDPOINT = 'https://api.digitalocean.com/v2'

DOCTL_CONFIG_PATHS = [
    '~/Library/Application Support/doctl/config.yaml',  # macOS
    os.path.join(os.environ.get('XDG_CONFIG_HOME', '~/.config'),
                 'doctl/config.yaml'),                  # Linux
]

_CAPACITY_MARKERS = (
    'currently unavailable',
    'not enough available capacity',
    'is not available in',
    'out of capacity',
)
_QUOTA_MARKERS = (
    'droplet limit',
    'will exceed your',
    'limit exceeded',
)


class DoApiError(Exception):
    """Fake/real client error carrying an HTTP status + message."""

    def __init__(self, status: int, message: str = ''):
        super().__init__(message or str(status))
        self.status = status
        self.message = message or str(status)


classify_error = rest_cloud.marker_classifier(_CAPACITY_MARKERS,
                                              _QUOTA_MARKERS)


def read_api_token() -> Optional[str]:
    env = os.environ.get('DIGITALOCEAN_ACCESS_TOKEN')
    if env:
        return env
    for p in DOCTL_CONFIG_PATHS:
        path = os.path.expanduser(p)
        if not os.path.exists(path):
            continue
        try:
            import yaml
            with open(path, encoding='utf-8') as f:
                cfg = yaml.safe_load(f) or {}
        except Exception:  # noqa: BLE001 — malformed config = no creds
            continue
        token = cfg.get('access-token')
        if token:
            return str(token)
        contexts = cfg.get('auth-contexts') or {}
        for tok in contexts.values():
            if tok:
                return str(tok)
    return None


def _parse_error(status: int, raw: bytes) -> Exception:
    """DO's error envelope: {'id': ..., 'message': ...}."""
    try:
        err = json.loads(raw.decode())
        return DoApiError(status, err.get('message', raw.decode()))
    except (ValueError, AttributeError):
        return DoApiError(status,
                          raw.decode(errors='replace') or str(status))


class _RestClient:
    """Flat op surface over the shared retrying urllib transport."""

    def __init__(self):
        token = read_api_token()
        if token is None:
            raise exceptions.CloudError(
                'DigitalOcean credentials not found: set '
                '$DIGITALOCEAN_ACCESS_TOKEN or run `doctl auth init`.')
        self._headers = {'Authorization': f'Bearer {token}',
                         'Content-Type': 'application/json'}

    def _request(self, method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        return rest_cloud.retrying_request(
            method, f'{API_ENDPOINT}{path}', self._headers, payload,
            _parse_error)

    # -- flat op surface (mirrored by test fakes) ---------------------------
    def create_droplet(self, name: str, region: str, size: str, image: str,
                       ssh_key_ids: List[int], tags: List[str],
                       user_data: Optional[str] = None) -> Dict[str, Any]:
        body: Dict[str, Any] = {
            'name': name, 'region': region, 'size': size, 'image': image,
            'ssh_keys': ssh_key_ids, 'tags': tags,
        }
        if user_data:
            body['user_data'] = user_data
        return dict(self._request('POST', '/droplets', body)
                    .get('droplet', {}))

    def list_droplets(self, tag: Optional[str] = None
                      ) -> List[Dict[str, Any]]:
        path = '/droplets'
        if tag:
            path += f'?tag_name={urllib.parse.quote(tag)}'
        return self._paginate(path, 'droplets')

    def droplet_action(self, droplet_id: int, action: str) -> None:
        # 'power_off' / 'power_on' (droplet actions API).
        self._request('POST', f'/droplets/{droplet_id}/actions',
                      {'type': action})

    def delete_droplet(self, droplet_id: int) -> None:
        self._request('DELETE', f'/droplets/{droplet_id}')

    def _paginate(self, path: str, key: str) -> List[Dict[str, Any]]:
        out: List[Dict[str, Any]] = []
        page = 1
        sep = '&' if '?' in path else '?'
        while True:
            resp = self._request('GET',
                                 f'{path}{sep}per_page=200&page={page}')
            out.extend(resp.get(key, []))
            links = (resp.get('links') or {}).get('pages') or {}
            if 'next' not in links:
                return out
            page += 1

    def list_ssh_keys(self) -> List[Dict[str, Any]]:
        return self._paginate('/account/keys', 'ssh_keys')

    def register_ssh_key(self, name: str, public_key: str
                         ) -> Dict[str, Any]:
        return dict(self._request('POST', '/account/keys',
                                  {'name': name, 'public_key': public_key})
                    .get('ssh_key', {}))

    def list_firewalls(self) -> List[Dict[str, Any]]:
        return self._paginate('/firewalls', 'firewalls')

    def create_firewall(self, name: str, inbound_rules: List[Dict[str, Any]],
                        tags: List[str]) -> Dict[str, Any]:
        body = {
            'name': name,
            'inbound_rules': inbound_rules,
            # Allow all outbound (provisioning needs package installs).
            'outbound_rules': [
                {'protocol': p, 'ports': '0',
                 'destinations': {'addresses': ['0.0.0.0/0', '::/0']}}
                for p in ('tcp', 'udp', 'icmp')
            ],
            'tags': tags,
        }
        return dict(self._request('POST', '/firewalls', body)
                    .get('firewall', {}))

    def update_firewall(self, firewall_id: str,
                        body: Dict[str, Any]) -> None:
        self._request('PUT', f'/firewalls/{firewall_id}', body)

    def delete_firewall(self, firewall_id: str) -> None:
        self._request('DELETE', f'/firewalls/{firewall_id}')


# Test seam (``set_do_factory(lambda: fake)``), client construction and
# error-normalizing ``call`` via the shared ClientSeam.
_seam = rest_cloud.ClientSeam(_RestClient, DoApiError, classify_error)
set_do_factory = _seam.set_factory
get_client = _seam.get_client
call = _seam.call
