"""Local provisioner: emulated hosts as directories + real subprocesses.

The permanent test backend (clouds/local.py docstring). Cluster state lives
under ``$SKYTPU_STATE_DIR/local_clusters/<name>/``:

    metadata.json     {status, num_hosts, deploy_vars}
    host0/ host1/...  per-host working directories ("filesystems")

Jobs later run as real subprocesses with cwd=hostN/, driven through the same
agent/job-queue path used on TPU hosts — so provisioning, setup, exec, logs,
autostop, and recovery are all testable hermetically (a deliberate upgrade
over the reference, whose multi-node paths need real clouds or kind —
SURVEY.md §4).
"""
from __future__ import annotations

import json
import os
import shutil
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import provision as provision_lib
from skypilot_tpu.utils import command_runner as runner_lib


def _clusters_root() -> str:
    root = os.path.join(global_user_state.get_state_dir(), 'local_clusters')
    os.makedirs(root, exist_ok=True)
    return root


def _cluster_dir(cluster_name: str) -> str:
    return os.path.join(_clusters_root(), cluster_name)


def _metadata_path(cluster_name: str) -> str:
    return os.path.join(_cluster_dir(cluster_name), 'metadata.json')


def _read_metadata(cluster_name: str) -> Optional[Dict[str, Any]]:
    try:
        with open(_metadata_path(cluster_name)) as f:
            return json.load(f)
    except (FileNotFoundError, json.JSONDecodeError):
        return None


def _write_metadata(cluster_name: str, meta: Dict[str, Any]) -> None:
    os.makedirs(_cluster_dir(cluster_name), exist_ok=True)
    tmp = _metadata_path(cluster_name) + '.tmp'
    with open(tmp, 'w') as f:
        json.dump(meta, f, indent=2)
    os.replace(tmp, _metadata_path(cluster_name))


# ---- provision API ---------------------------------------------------------
def run_instances(cluster_name: str, region: str, zone: Optional[str],
                  num_hosts: int, deploy_vars: Dict[str, Any]) -> None:
    meta = _read_metadata(cluster_name)
    if meta is not None and meta['num_hosts'] != num_hosts:
        raise exceptions.ClusterError(
            f'Cluster {cluster_name!r} exists with {meta["num_hosts"]} hosts;'
            f' requested {num_hosts}. Tear it down first.')
    # Simulated capacity errors for failover tests: deploy_vars may carry
    # zones to reject (set via resources labels in tests).
    fail_zones = (deploy_vars or {}).get('fail_in_zones', [])
    if zone in fail_zones:
        raise exceptions.InsufficientCapacityError(
            f'local: no more capacity in zone {zone!r}')
    image_id = (deploy_vars or {}).get('image_id')
    for rank in range(num_hosts):
        host_dir = os.path.join(_cluster_dir(cluster_name), f'host{rank}')
        fresh = not os.path.isdir(host_dir)
        os.makedirs(host_dir, exist_ok=True)
        if fresh and image_id and str(image_id).startswith('local-image://'):
            # Cloned-disk launch: the emulated host 'disk' is its dir —
            # materialize the image contents into it (clone-disk parity
            # for the hermetic cloud; see create_image_from_cluster).
            src = _image_dir(image_id[len('local-image://'):])
            if not os.path.isdir(src):
                raise exceptions.ClusterError(
                    f'local image {image_id!r} does not exist')
            shutil.copytree(src, host_dir, dirs_exist_ok=True)
    _write_metadata(cluster_name, {
        'status': 'running',
        'num_hosts': num_hosts,
        'region': region,
        'zone': zone,
        'deploy_vars': deploy_vars or {},
        'launched_at': int(time.time()),
    })


def wait_instances(cluster_name: str, region: str, state: str = 'running',
                   timeout: float = 1800) -> None:
    meta = _read_metadata(cluster_name)
    if meta is None or meta['status'] != state:
        raise exceptions.ClusterError(
            f'Local cluster {cluster_name!r} not in state {state!r} '
            f'(meta={meta})')


def _kill_host_processes(cluster_name: str) -> None:
    """"Power off" the emulated hosts: kill the head agent and every
    job's process group.

    Real clouds get this for free when the instance dies; locally these
    are plain processes that outlive their cluster unless killed.
    """
    import glob as glob_lib
    import signal

    from skypilot_tpu.runtime import constants as rt_constants
    root = _cluster_dir(cluster_name)
    pid_path = os.path.join(root, 'host0', rt_constants.RUNTIME_DIR,
                            rt_constants.AGENT_PID_FILE)
    try:
        with open(pid_path) as f:
            pid = int(f.read().strip())
        # A crashed agent leaves a stale pid file and the OS may reuse
        # the PID: only kill a process that really is our agent. Without
        # /proc (macOS) the identity check is unavailable — kill anyway:
        # the pid came from our own fresh pid file.
        try:
            with open(f'/proc/{pid}/cmdline', 'rb') as f:
                verified = b'skypilot_tpu.runtime.agent' in f.read()
        except FileNotFoundError:
            verified = not os.path.isdir('/proc')
        if verified:
            os.kill(pid, signal.SIGTERM)
            # Wait for the death: an immediate restart's is-agent-alive
            # check must not race a still-dying process (it would skip
            # spawning a fresh agent and the bring-up barrier then waits
            # on a heartbeat nobody writes).
            deadline = time.time() + 5
            while time.time() < deadline:
                try:
                    os.kill(pid, 0)
                except ProcessLookupError:
                    break
                time.sleep(0.05)
            else:
                os.kill(pid, signal.SIGKILL)
    except (FileNotFoundError, ValueError, ProcessLookupError,
            PermissionError):
        pass
    # The "host" is off: no agent pid is valid anymore.
    try:
        os.remove(pid_path)
    except FileNotFoundError:
        pass
    # Job leaders run setsid'd (their pgid == the pid in the file).
    # SIGTERM first for a clean exit, but follow up with SIGKILL: python
    # only delivers signals between bytecodes, so a job wedged inside a
    # blocking C call (e.g. a hung device-tunnel RPC) would otherwise
    # survive teardown and keep holding the chip.
    job_pgids = []
    for job_pid_file in glob_lib.glob(
            os.path.join(root, 'host*', '.skytpu_job_*.pid')):
        try:
            with open(job_pid_file) as f:
                pgid = int(f.read().strip())
            os.killpg(pgid, signal.SIGTERM)
            job_pgids.append(pgid)
        except (FileNotFoundError, ValueError, ProcessLookupError,
                PermissionError):
            pass
    if job_pgids:
        deadline = time.time() + 3
        while time.time() < deadline:
            live = []
            for pgid in job_pgids:
                try:
                    os.killpg(pgid, 0)
                    live.append(pgid)
                except (ProcessLookupError, PermissionError):
                    pass
            job_pgids = live
            if not job_pgids:
                break
            time.sleep(0.05)
        for pgid in job_pgids:
            try:
                os.killpg(pgid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass


def stop_instances(cluster_name: str, region: str) -> None:
    meta = _read_metadata(cluster_name)
    if meta is None:
        return
    # Publish the state transition BEFORE killing: real clouds report
    # 'stopping'/'shutting-down' the moment the API call lands, and
    # observers (the serve replica prober's preemption discriminator)
    # depend on cloud-truth changing before the host processes finish
    # dying — the kill waits below can take seconds.
    meta['status'] = 'stopping'
    _write_metadata(cluster_name, meta)
    _kill_host_processes(cluster_name)
    meta['status'] = 'stopped'
    _write_metadata(cluster_name, meta)


def terminate_instances(cluster_name: str, region: str) -> None:
    meta = _read_metadata(cluster_name)
    if meta is not None:
        meta['status'] = 'terminating'  # visible before the kill waits
        _write_metadata(cluster_name, meta)
    _kill_host_processes(cluster_name)
    shutil.rmtree(_cluster_dir(cluster_name), ignore_errors=True)


def query_instances(cluster_name: str, region: str) -> Dict[str, str]:
    meta = _read_metadata(cluster_name)
    if meta is None:
        return {}
    return {f'host{r}': meta['status'] for r in range(meta['num_hosts'])}


def get_cluster_info(cluster_name: str, region: str
                     ) -> provision_lib.ClusterInfo:
    meta = _read_metadata(cluster_name)
    if meta is None:
        raise exceptions.ClusterError(
            f'Local cluster {cluster_name!r} does not exist')
    hosts = [
        provision_lib.HostInfo(
            host_id=f'host{r}', rank=r, internal_ip='127.0.0.1',
            external_ip='127.0.0.1',
            extra={'host_dir': os.path.join(_cluster_dir(cluster_name),
                                            f'host{r}')})
        for r in range(meta['num_hosts'])
    ]
    return provision_lib.ClusterInfo(
        cluster_name=cluster_name, cloud='local',
        region=meta.get('region', 'local'), zone=meta.get('zone'),
        hosts=hosts, deploy_vars=meta.get('deploy_vars', {}))


def open_ports(cluster_name: str, region: str, ports: List[str]) -> None:
    pass  # localhost: nothing to open


def get_command_runners(cluster_info: provision_lib.ClusterInfo,
                        ssh_credentials: Optional[Dict[str, str]] = None
                        ) -> List[runner_lib.CommandRunner]:
    return [
        runner_lib.LocalProcessRunner(h.extra['host_dir'])
        for h in cluster_info.hosts
    ]


def _image_dir(image_name: str) -> str:
    from skypilot_tpu import global_user_state
    return os.path.join(global_user_state.get_state_dir(), 'local_images',
                        image_name)


def create_image_from_cluster(cluster_name: str, region: str,
                              image_name: str) -> str:
    """Snapshot the head host dir (the emulated boot disk) into a
    reusable local image; new clusters launched with the returned
    ``local-image://`` id start from a copy of its contents."""
    head = os.path.join(_cluster_dir(cluster_name), 'host0')
    if not os.path.isdir(head):
        raise exceptions.ClusterError(
            f'local cluster {cluster_name!r} has no host dir to image')
    dst = _image_dir(image_name)
    shutil.rmtree(dst, ignore_errors=True)
    # The runtime dir (agent pidfiles, job queue) is the "OS" half of the
    # emulated disk — a clone must not import the source's live job
    # state, exactly like a real boot-disk image excludes instance
    # identity.
    from skypilot_tpu.runtime import constants as rt_constants
    shutil.copytree(head, dst, dirs_exist_ok=True,
                    ignore=shutil.ignore_patterns(
                        rt_constants.RUNTIME_DIR, '.skytpu_job_*'))
    return f'local-image://{image_name}'
