"""Thin Vast.ai REST client with a test seam.

Counterpart of the reference's ``sky/provision/vast/utils.py`` (vast
SDK wrapper: search_offers / create_instance / show_instances). The
real transport is a tiny urllib client over the public v0 REST API
(``https://console.vast.ai/api/v0``, ``Authorization: Bearer`` with the
account API key); tests install an in-process fake via
``set_vast_factory`` implementing the same flat surface
(``search_offers``, ``create_instance``, ``list_instances``,
``start_instance``, ``stop_instance``, ``destroy_instance``), so the
marketplace-offer lifecycle and bid/preemption logic run for real with
no cloud.

Vast is a MARKETPLACE: capacity is "no matching offer right now", not a
cloud error code — the provisioner classifies an empty offer search as
InsufficientCapacityError itself. API errors here are plumbing
(auth/rate limits), classified as plain CloudError except quota
wording.
"""
from __future__ import annotations

import json
import os
import urllib.parse
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import rest_cloud

API_ENDPOINT = 'https://console.vast.ai/api/v0'
API_KEY_PATH = '~/.vast_api_key'

_QUOTA_MARKERS = ('quota', 'credit', 'balance too low')


class VastApiError(Exception):
    """Fake/real client error carrying an HTTP status + message."""

    def __init__(self, status: int, message: str = ''):
        super().__init__(message or str(status))
        self.status = status
        self.message = message or str(status)


classify_error = rest_cloud.marker_classifier(
    quota_markers=_QUOTA_MARKERS)


def read_api_key() -> Optional[str]:
    env = os.environ.get('VAST_API_KEY')
    if env:
        return env
    path = os.path.expanduser(API_KEY_PATH)
    if os.path.exists(path):
        with open(path, encoding='utf-8') as f:
            key = f.read().strip()
        return key or None
    return None


def _parse_error(status: int, raw: bytes) -> Exception:
    """Vast's error envelope: {'error': ..., 'msg': ...}."""
    try:
        err = json.loads(raw.decode())
        msg = err.get('msg') or err.get('error') or raw.decode()
        return VastApiError(status, str(msg))
    except (ValueError, AttributeError):
        return VastApiError(status,
                            raw.decode(errors='replace') or str(status))


class _RestClient:
    """Flat op surface over the shared retrying urllib transport."""

    def __init__(self):
        api_key = read_api_key()
        if api_key is None:
            raise exceptions.CloudError(
                'Vast.ai credentials not found: set $VAST_API_KEY or '
                f'write the key to {API_KEY_PATH}.')
        self._headers = {'Authorization': f'Bearer {api_key}',
                         'Content-Type': 'application/json'}

    def _request(self, method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        return rest_cloud.retrying_request(
            method, f'{API_ENDPOINT}{path}', self._headers, payload,
            _parse_error)

    # -- flat op surface (mirrored by test fakes) ---------------------------
    def search_offers(self, gpu_name: str, num_gpus: int, geolocation: str,
                      min_disk_gb: float) -> List[Dict[str, Any]]:
        """Rentable offers matching the spec, as the marketplace sees
        them right now. Each offer carries id, dph_total ($/h on-demand),
        min_bid ($/h floor for interruptible), cpu_cores, cpu_ram."""
        query = {
            'verified': {'eq': True},
            'rentable': {'eq': True},
            'gpu_name': {'eq': gpu_name},
            'num_gpus': {'eq': num_gpus},
            'geolocation': {'in': [geolocation]},
            'disk_space': {'gte': min_disk_gb},
            'order': [['dph_total', 'asc']],
            'type': 'on-demand',
        }
        # The query JSON carries spaces ('RTX 4090') and braces: it MUST
        # be percent-encoded or urllib refuses the URL outright.
        encoded = urllib.parse.quote(
            json.dumps(query, separators=(',', ':')))
        body = self._request('GET', f'/bundles?q={encoded}')
        return list(body.get('offers', []))

    def create_instance(self, offer_id: int, label: str, image: str,
                        disk_gb: float, onstart_cmd: str,
                        bid_per_hour: Optional[float] = None
                        ) -> Dict[str, Any]:
        payload: Dict[str, Any] = {
            'client_id': 'me', 'image': image, 'disk': disk_gb,
            'label': label, 'onstart': onstart_cmd,
            'runtype': 'ssh', 'direct': True,
        }
        if bid_per_hour is not None:
            payload['price'] = bid_per_hour  # interruptible bid
        return dict(self._request('PUT', f'/asks/{offer_id}/', payload))

    def list_instances(self) -> List[Dict[str, Any]]:
        return list(self._request('GET', '/instances')
                    .get('instances', []))

    def start_instance(self, instance_id: int) -> None:
        self._request('PUT', f'/instances/{instance_id}/',
                      {'state': 'running'})

    def stop_instance(self, instance_id: int) -> None:
        self._request('PUT', f'/instances/{instance_id}/',
                      {'state': 'stopped'})

    def destroy_instance(self, instance_id: int) -> None:
        self._request('DELETE', f'/instances/{instance_id}/')


# Test seam (``set_vast_factory(lambda: fake)``), client construction
# and error-normalizing ``call`` via the shared ClientSeam.
_seam = rest_cloud.ClientSeam(_RestClient, VastApiError, classify_error)
set_vast_factory = _seam.set_factory
get_client = _seam.get_client
call = _seam.call
