"""Thin FluidStack REST client with a test seam.

Counterpart of the reference's
``sky/provision/fluidstack/fluidstack_utils.py`` (FluidstackClient over
``https://platform.fluidstack.io`` with an ``api-key`` header). The real
transport is a tiny urllib client; tests install an in-process fake via
``set_fluidstack_factory`` implementing the same flat surface
(``create_instance``, ``list_instances``, ``delete_instance``,
``list_plans``, ssh keys), so lifecycle + failover logic runs for real
with no cloud.

Error classification: out-of-stock wording ("out of stock", reference
fluidstack_utils.py:98-99) -> capacity failover; quota wording ->
quota; everything else -> plain CloudError.
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import rest_cloud

API_ENDPOINT = 'https://platform.fluidstack.io'
API_KEY_PATH = '~/.fluidstack/api_key'

_CAPACITY_MARKERS = (
    'out of stock',
    'no capacity',
    'not available in',
)
_QUOTA_MARKERS = (
    'quota',
    'limit reached',
)


class FluidstackApiError(Exception):
    """Fake/real client error carrying an HTTP status + message."""

    def __init__(self, status: int, message: str = ''):
        super().__init__(message or str(status))
        self.status = status
        self.message = message or str(status)


classify_error = rest_cloud.marker_classifier(_CAPACITY_MARKERS,
                                              _QUOTA_MARKERS)


def read_api_key() -> Optional[str]:
    env = os.environ.get('FLUIDSTACK_API_KEY')
    if env:
        return env
    path = os.path.expanduser(API_KEY_PATH)
    if os.path.exists(path):
        with open(path, encoding='utf-8') as f:
            key = f.read().strip()
        return key or None
    return None


def _parse_error(status: int, raw: bytes) -> Exception:
    """FluidStack's error envelope: {'message': ...} or {'error': ...}."""
    try:
        err = json.loads(raw.decode())
        msg = err.get('message') or err.get('error') or raw.decode()
        return FluidstackApiError(status, str(msg))
    except (ValueError, AttributeError):
        return FluidstackApiError(
            status, raw.decode(errors='replace') or str(status))


class _RestClient:
    """Flat op surface over the shared retrying urllib transport."""

    def __init__(self):
        api_key = read_api_key()
        if api_key is None:
            raise exceptions.CloudError(
                'FluidStack credentials not found: set '
                f'$FLUIDSTACK_API_KEY or write the key to {API_KEY_PATH}.')
        self._headers = {'api-key': api_key,
                         'Content-Type': 'application/json'}

    def _request(self, method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None) -> Any:
        return rest_cloud.retrying_request(
            method, f'{API_ENDPOINT}{path}', self._headers, payload,
            _parse_error)

    # -- flat op surface (mirrored by test fakes) ---------------------------
    def create_instance(self, gpu_type: str, gpu_count: int, region: str,
                        name: str, ssh_key_name: str) -> str:
        body = self._request('POST', '/instances', {
            'gpu_type': gpu_type, 'gpu_count': gpu_count,
            'region': region, 'name': name,
            'operating_system_label': 'ubuntu_22_04_lts_nvidia',
            'ssh_key': ssh_key_name,
        })
        return str(body.get('id'))

    def list_instances(self) -> List[Dict[str, Any]]:
        return list(self._request('GET', '/instances') or [])

    def delete_instance(self, instance_id: str) -> None:
        self._request('DELETE', f'/instances/{instance_id}')

    def list_plans(self) -> List[Dict[str, Any]]:
        return list(self._request(
            'GET', '/list_available_configurations') or [])

    def list_ssh_keys(self) -> List[Dict[str, str]]:
        return list(self._request('GET', '/ssh_keys') or [])

    def register_ssh_key(self, name: str, public_key: str) -> None:
        self._request('POST', '/ssh_keys',
                      {'name': name, 'public_key': public_key})


# Test seam (``set_fluidstack_factory(lambda: fake)``), client
# construction and error-normalizing ``call`` via the shared ClientSeam.
_seam = rest_cloud.ClientSeam(_RestClient, FluidstackApiError,
                              classify_error)
set_fluidstack_factory = _seam.set_factory
get_client = _seam.get_client
call = _seam.call
