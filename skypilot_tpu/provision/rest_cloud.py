"""Shared scaffolding for the REST-API clouds (Lambda, DO, FluidStack…).

These providers share a shape the hyperscaler clouds don't: a flat
account-global JSON REST API (no SDK), name- or tag-encoded cluster
membership, and a client-side kv record as the only durable pointer.
The pieces every such provisioner needs live here ONCE so an invariant
fixed in one cloud (e.g. "never adopt an instance from a failed-over
region") cannot silently be lost in the next copy:

- :class:`ClusterRecords` — the kv bookkeeping contract (save BEFORE
  create; keep the record when cleanup fails so terminate can retry);
- :func:`rank_of` — stateless ``{name}-r{rank}`` rank decoding;
- :func:`poll_for_state` — the wait loop with rank-hole-as-capacity
  semantics (a dead gang must fail over, not wait out the timeout);
- :func:`ssh_runners` — per-host SSHCommandRunner construction;
- :func:`retrying_request` — urllib transport with 429 backoff.

Per-cloud error *classification* stays in each ``<cloud>_api`` module:
the marker strings and status shapes genuinely differ per provider.
"""
from __future__ import annotations

import json
import socket
import time
import urllib.error
import urllib.request
from typing import Any, Callable, Dict, List, Optional, Union

from skypilot_tpu import authentication
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu.utils import command_runner as runner_lib


class ClusterRecords:
    """Client-side kv record per cluster (region, name-on-cloud,
    num_hosts, deploy_vars). The record is written BEFORE any create
    call so partially-created resources stay reachable by
    terminate_instances (contract shared with provision/gcp.py)."""

    def __init__(self, prefix: str):
        self._prefix = prefix

    def _key(self, cluster_name: str) -> str:
        return f'{self._prefix}/{cluster_name}'

    def save(self, cluster_name: str, record: Dict[str, Any]) -> None:
        global_user_state.set_kv(self._key(cluster_name),
                                 json.dumps(record))

    def load(self, cluster_name: str) -> Optional[Dict[str, Any]]:
        raw = global_user_state.get_kv(self._key(cluster_name))
        return json.loads(raw) if raw else None

    def delete(self, cluster_name: str) -> None:
        global_user_state.set_kv(self._key(cluster_name), '')

    def require(self, cluster_name: str,
                cloud_repr: str) -> Dict[str, Any]:
        record = self.load(cluster_name)
        if not record:
            raise exceptions.ClusterError(
                f'No {cloud_repr} provisioning record for '
                f'{cluster_name!r}')
        return record


def rank_of(instance_name: str, name_on_cloud: str) -> Optional[int]:
    """Rank from ``{name_on_cloud}-r{rank}``; None if foreign."""
    prefix = f'{name_on_cloud}-r'
    if not instance_name.startswith(prefix):
        return None
    suffix = instance_name[len(prefix):]
    return int(suffix) if suffix.isdigit() else None


def poll_for_state(cluster_name: str,
                   query: Callable[[], Dict[str, str]],
                   state: str,
                   timeout: float,
                   interval: float = 5.0,
                   extra_check: Optional[
                       Callable[[set], Optional[Exception]]] = None
                   ) -> None:
    """Poll ``query()`` until every host reports ``state``.

    A rank hole ('terminated' in the states, reported by the shared
    query contract for missing ranks) or a fully-vanished cluster
    raises InsufficientCapacityError so the provisioner fails over
    instead of waiting out the timeout. ``extra_check(states)`` lets a
    cloud add its own mid-wait hazard (e.g. Azure's spot-deallocation
    detection) by returning an exception to raise.
    """
    deadline = time.time() + timeout
    while time.time() < deadline:
        states = set(query().values())
        if states == {state}:
            return
        if (not states or 'terminating' in states
                or 'terminated' in states):
            raise exceptions.InsufficientCapacityError(
                f'{cluster_name}: host(s) disappeared while waiting for '
                f'{state}', reason='capacity')
        if extra_check is not None:
            exc = extra_check(states)
            if exc is not None:
                raise exc
        time.sleep(interval)
    raise exceptions.ProvisionError(
        f'{cluster_name} did not reach {state!r} within {timeout}s')


def ssh_runners(cluster_info, default_user: str,
                ssh_credentials: Optional[Dict[str, str]] = None
                ) -> List[runner_lib.CommandRunner]:
    """One SSHCommandRunner per host, rank order (head first). Honors
    HostInfo.ssh_port (Vast maps ssh onto host-chosen ports; everyone
    else leaves the default 22)."""
    creds = ssh_credentials or {}
    key_path = creds.get('key_path')
    if key_path is None:
        key_path, _ = authentication.get_or_generate_keys()
    user = creds.get('user', default_user)
    runners: List[runner_lib.CommandRunner] = []
    for h in cluster_info.hosts:
        ip = h.external_ip or h.internal_ip
        runners.append(runner_lib.SSHCommandRunner(ip, user, key_path,
                                                   port=h.ssh_port))
    return runners


def marker_classifier(capacity_markers=(), quota_markers=()):
    """Build a classify_error(exc) from provider-specific marker
    strings: capacity wording -> InsufficientCapacityError (failover
    fires), quota wording -> CloudError(reason='quota') (blocklist, no
    retry), everything else -> plain CloudError. Matches against the
    error's code attribute AND message so both code-carrying (Lambda)
    and wording-only (DO) providers work."""
    def classify(exc: Exception) -> exceptions.CloudError:
        blob = f'{getattr(exc, "code", "")} {exc}'.lower()
        if any(m in blob for m in capacity_markers):
            return exceptions.InsufficientCapacityError(str(exc),
                                                        reason='capacity')
        if any(m in blob for m in quota_markers):
            return exceptions.CloudError(str(exc), reason='quota')
        return exceptions.CloudError(str(exc))
    return classify


class ClientSeam:
    """Per-cloud client construction with the in-process-fake test seam
    and error-normalizing call() — identical mechanics for every REST
    cloud, so a hardening fix lands once.

    ``ClientSeam(real_factory, api_error_types, classify)`` exposes
    ``set_factory`` (tests install a fake), ``get_client`` and
    ``call`` — bind them to the api module's public names.
    """

    def __init__(self, real_factory: Callable[[], Any],
                 api_error_types, classify):
        self._factory: Optional[Callable[[], Any]] = None
        self._real_factory = real_factory
        self._api_error_types = api_error_types
        self._classify = classify

    def set_factory(self, factory: Optional[Callable[[], Any]]) -> None:
        self._factory = factory

    def get_client(self) -> Any:
        if self._factory is not None:
            return self._factory()
        return self._real_factory()

    def call(self, client: Any, op: str, **kwargs) -> Any:
        try:
            return getattr(client, op)(**kwargs)
        except self._api_error_types as e:
            raise self._classify(e) from e


def retrying_request(method: str, url: str,
                     headers: Union[Dict[str, str],
                                    Callable[[], Dict[str, str]]],
                     payload: Optional[Dict[str, Any]],
                     parse_error: Callable[[int, bytes], Exception],
                     max_attempts: int = 6,
                     timeout: float = 60.0,
                     return_headers: bool = False) -> Any:
    """One urllib call with 429/transport backoff. ``parse_error(status,
    body)`` builds the cloud's typed API error from a failure response
    (each provider has its own error envelope). ``headers`` may be a
    CALLABLE rebuilt per attempt — required by providers whose headers
    are time-sensitive (OCI signs the date header; with full backoff the
    sleeps drift a once-signed date into the clock-skew rejection
    window). ``return_headers=True`` returns ``(body,
    response_headers)`` — needed by providers that paginate via response
    headers (OCI's ``opc-next-page``)."""
    data = json.dumps(payload).encode() if payload is not None else None
    backoff = 5.0
    for attempt in range(max_attempts):
        hdrs = headers() if callable(headers) else headers
        req = urllib.request.Request(url, data=data, method=method,
                                     headers=hdrs)
        try:
            with urllib.request.urlopen(req, timeout=timeout) as resp:
                body = resp.read().decode()
                parsed = json.loads(body) if body else {}
                if return_headers:
                    return parsed, dict(resp.headers)
                return parsed
        except urllib.error.HTTPError as e:
            if e.code == 429 and attempt < max_attempts - 1:
                time.sleep(backoff)  # rate limited: retry with backoff
                backoff = min(backoff * 2, 60)
                continue
            try:
                raw = e.read()
            except Exception:  # noqa: BLE001 — body read is best-effort
                raw = b''
            raise parse_error(e.code, raw) from e
        except (urllib.error.URLError, socket.timeout, OSError) as e:
            # Transport-level failure: no HTTP status to classify, so
            # parse_error can't apply — wrap as CloudError so the
            # failover/retry machinery above understands it instead of
            # seeing a raw socket exception. Resend ONLY when nothing
            # can have reached the server (connect refused, DNS) or the
            # method is idempotent (GET/HEAD, and PUT/DELETE by HTTP
            # semantics — terminate/firewall-update resend safely): a
            # read timeout on a POST may mean the cloud already accepted
            # the mutation (an instance launch billed twice is worse
            # than one failed-over error).
            reason = getattr(e, 'reason', e)
            resend_safe = (
                method.upper() in ('GET', 'HEAD', 'PUT', 'DELETE')
                or isinstance(reason, (ConnectionRefusedError,
                                       socket.gaierror))
                or isinstance(e, ConnectionRefusedError))
            if resend_safe and attempt < max_attempts - 1:
                time.sleep(backoff)
                backoff = min(backoff * 2, 60)
                continue
            raise exceptions.CloudError(
                f'{method} {url} transport failure '
                f'(attempt {attempt + 1}/{max_attempts}): {e}') from e
    raise parse_error(429, b'rate limited after retries')
