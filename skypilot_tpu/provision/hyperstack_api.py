"""Thin Hyperstack REST client with a test seam.

Counterpart of the reference's
``sky/provision/hyperstack/hyperstack_utils.py`` (HyperstackClient over
``https://infrahub-api.nexgencloud.com/v1`` with an ``api_key``
header). The real transport is a tiny urllib client; tests install an
in-process fake via ``set_hyperstack_factory`` implementing the same
flat surface (``create_vm``, ``list_vms``, ``start/stop/delete_vm``,
``add_security_rule``, environments, ssh keys), so the stop-capable
lifecycle and the per-instance port rules run with no cloud.

Error classification: stock wording ("not enough capacity",
"insufficient resources") -> failover; credit/quota wording -> quota.
"""
from __future__ import annotations

import json
import os
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import rest_cloud

API_ENDPOINT = 'https://infrahub-api.nexgencloud.com/v1'
API_KEY_PATH = '~/.hyperstack/api_key'

_CAPACITY_MARKERS = (
    'not enough capacity',
    'insufficient resources',
    'no hosts available',
    'out of stock',
)
_QUOTA_MARKERS = (
    'quota',
    'credit',
    'exceeded your limit',
)


class HyperstackApiError(Exception):
    """Fake/real client error carrying an HTTP status + message."""

    def __init__(self, status: int, message: str = ''):
        super().__init__(message or str(status))
        self.status = status
        self.message = message or str(status)


classify_error = rest_cloud.marker_classifier(_CAPACITY_MARKERS,
                                              _QUOTA_MARKERS)


def read_api_key() -> Optional[str]:
    env = os.environ.get('HYPERSTACK_API_KEY')
    if env:
        return env
    path = os.path.expanduser(API_KEY_PATH)
    if os.path.exists(path):
        with open(path, encoding='utf-8') as f:
            key = f.read().strip()
        return key or None
    return None


def _parse_error(status: int, raw: bytes) -> Exception:
    try:
        err = json.loads(raw.decode())
        msg = err.get('message') or err.get('error') or raw.decode()
        return HyperstackApiError(status, str(msg))
    except (ValueError, AttributeError):
        return HyperstackApiError(
            status, raw.decode(errors='replace') or str(status))


class _RestClient:
    """Flat op surface over the shared retrying urllib transport."""

    def __init__(self):
        api_key = read_api_key()
        if api_key is None:
            raise exceptions.CloudError(
                'Hyperstack credentials not found: set '
                f'$HYPERSTACK_API_KEY or write the key to {API_KEY_PATH}.')
        self._headers = {'api_key': api_key,
                         'Content-Type': 'application/json'}

    def _request(self, method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        return rest_cloud.retrying_request(
            method, f'{API_ENDPOINT}{path}', self._headers, payload,
            _parse_error)

    # -- flat op surface (mirrored by test fakes) ---------------------------
    def list_environments(self) -> List[Dict[str, Any]]:
        return list(self._request('GET', '/core/environments')
                    .get('environments', []))

    def create_environment(self, name: str, region: str) -> Dict[str, Any]:
        return dict(self._request('POST', '/core/environments',
                                  {'name': name, 'region': region})
                    .get('environment', {}))

    def list_ssh_keys(self) -> List[Dict[str, Any]]:
        return list(self._request('GET', '/core/keypairs')
                    .get('keypairs', []))

    def register_ssh_key(self, name: str, environment: str,
                         public_key: str) -> Dict[str, Any]:
        return dict(self._request('POST', '/core/keypairs', {
            'name': name, 'environment_name': environment,
            'public_key': public_key,
        }).get('keypair', {}))

    def create_vm(self, name: str, environment: str, flavor: str,
                  key_name: str, image: str,
                  security_rules: List[Dict[str, Any]]) -> Dict[str, Any]:
        body = self._request('POST', '/core/virtual-machines', {
            'name': name, 'environment_name': environment,
            'flavor_name': flavor, 'key_name': key_name,
            'image_name': image, 'count': 1,
            'assign_floating_ip': True,
            'security_rules': security_rules,
        })
        instances = body.get('instances') or []
        return dict(instances[0]) if instances else dict(body)

    def list_vms(self) -> List[Dict[str, Any]]:
        return list(self._request('GET', '/core/virtual-machines')
                    .get('instances', []))

    def start_vm(self, vm_id: int) -> None:
        self._request('GET', f'/core/virtual-machines/{vm_id}/start')

    def stop_vm(self, vm_id: int) -> None:
        self._request('GET', f'/core/virtual-machines/{vm_id}/stop')

    def delete_vm(self, vm_id: int) -> None:
        self._request('DELETE', f'/core/virtual-machines/{vm_id}')

    def add_security_rule(self, vm_id: int,
                          rule: Dict[str, Any]) -> None:
        self._request('POST',
                      f'/core/virtual-machines/{vm_id}/sg-rules', rule)


# Test seam (``set_hyperstack_factory(lambda: fake)``), client
# construction and error-normalizing ``call`` via the shared ClientSeam.
_seam = rest_cloud.ClientSeam(_RestClient, HyperstackApiError,
                              classify_error)
set_hyperstack_factory = _seam.set_factory
get_client = _seam.get_client
call = _seam.call
