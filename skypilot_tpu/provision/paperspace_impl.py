"""Paperspace provisioner: CORE machines (full stop/start lifecycle).

Counterpart of reference ``sky/provision/paperspace/instance.py`` +
``utils.py``. Ninth VM cloud: a REST cloud with the FULL lifecycle —
stop/start work and don't bill compute while off — making it the first
REST cloud the optimizer can autostop without `--down`. No spot, no
zones, no firewall API (machines get dynamic public IPs with open
inbound on the account's default network; the cloud class omits
OPEN_PORTS to stay conservative).

Rank discovery is stateless via machine names ``{name}-r{rank}``; the
machine list is account-global, so the shared region filter applies
(same adoption hazard as Lambda/FluidStack).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import authentication
from skypilot_tpu import exceptions
from skypilot_tpu import provision as provision_lib
from skypilot_tpu.provision import paperspace_api
from skypilot_tpu.provision import rest_cloud
from skypilot_tpu.utils import command_runner as runner_lib

SSH_USER = 'paperspace'

# Paperspace machine states -> provision API state words.
_STATE_MAP = {
    'provisioning': 'pending',
    'starting': 'pending',
    'restarting': 'pending',
    'ready': 'running',
    'stopping': 'stopping',
    'off': 'stopped',
    'upgrading': 'pending',
    'serviceready': 'pending',
}

# Cluster bookkeeping + rank decoding via the shared REST-cloud
# scaffolding (rest_cloud.py).
_records = rest_cloud.ClusterRecords('paperspace_cluster')


def _ensure_startup_script(client) -> str:
    """Persist (or reuse) the key-install startup script; returns its id
    (the v1 API only takes scripts by id — reference
    sky/provision/paperspace/utils.py get/set_sky_key_script)."""
    _, pub_path = authentication.get_or_generate_keys()
    with open(pub_path, encoding='utf-8') as f:
        pub_key = f.read().strip()
    script = ('#!/bin/bash\nmkdir -p /home/paperspace/.ssh\n'
              f'grep -qF "{pub_key}" /home/paperspace/.ssh/authorized_keys '
              f'2>/dev/null || echo "{pub_key}" >> '
              '/home/paperspace/.ssh/authorized_keys\n'
              'chown -R paperspace:paperspace /home/paperspace/.ssh\n')
    for s in paperspace_api.call(client, 'list_startup_scripts'):
        if s.get('name') == 'skytpu-key' and pub_key in (
                s.get('script') or ''):
            return s['id']
    created = paperspace_api.call(client, 'create_startup_script',
                                  name='skytpu-key', script=script)
    return created['id']


def _live_machines(client, name: str,
                   region: Optional[str] = None
                   ) -> Dict[int, Dict[str, Any]]:
    out: Dict[int, Dict[str, Any]] = {}
    for m in paperspace_api.call(client, 'list_machines'):
        rank = rest_cloud.rank_of(m.get('name') or '', name)
        if rank is None:
            continue
        if m.get('state') in ('deleted', 'deleting'):
            continue
        if region is not None and (m.get('region') or region) != region:
            continue
        out[rank] = m
    return out


# ---- provision API ---------------------------------------------------------
def run_instances(cluster_name: str, region: str, zone: Optional[str],
                  num_hosts: int, deploy_vars: Dict[str, Any]) -> None:
    del zone  # no zones
    name = deploy_vars['cluster_name_on_cloud']
    record = {'region': region, 'zone': None, 'name_on_cloud': name,
              'num_hosts': num_hosts, 'deploy_vars': deploy_vars}
    _records.save(cluster_name, record)
    client = paperspace_api.get_client()
    try:
        script_id = _ensure_startup_script(client)
        existing = _live_machines(client, name, region)
        for rank, m in existing.items():
            if m.get('state') == 'off':
                paperspace_api.call(client, 'start_machine',
                                    machine_id=m['id'])
        for rank in range(num_hosts):
            if rank in existing:
                continue  # idempotent relaunch
            paperspace_api.call(
                client, 'create_machine',
                name=f'{name}-r{rank}',
                machine_type=deploy_vars.get('instance_type', 'C5'),
                region=region,
                disk_gb=int(deploy_vars.get('disk_size_gb') or 100),
                startup_script_id=script_id)
    except exceptions.InsufficientCapacityError:
        try:
            _terminate_all(client, name)
        except exceptions.CloudError:
            pass
        else:
            _records.delete(cluster_name)
        raise


def wait_instances(cluster_name: str, region: str, state: str = 'running',
                   timeout: float = 1800) -> None:
    rest_cloud.poll_for_state(
        cluster_name, lambda: query_instances(cluster_name, region),
        state, timeout)


def query_instances(cluster_name: str, region: str) -> Dict[str, str]:
    del region
    record = _records.load(cluster_name)
    if not record:
        return {}
    client = paperspace_api.get_client()
    live = _live_machines(client, record['name_on_cloud'],
                          record.get('region'))
    if not live:
        return {}
    out: Dict[str, str] = {}
    for rank, m in live.items():
        out[m.get('name', f'r{rank}')] = _STATE_MAP.get(
            m.get('state', ''), 'unknown')
    for rank in range(int(record.get('num_hosts') or 0)):
        if rank not in live:
            out[f'rank{rank}-missing'] = 'terminated'
    return out


def stop_instances(cluster_name: str, region: str) -> None:
    """Stop (machines off don't bill compute on Paperspace — unlike DO,
    a clean stop story)."""
    record = _records.require(cluster_name, 'Paperspace')
    client = paperspace_api.get_client()
    for m in _live_machines(client, record['name_on_cloud']).values():
        if m.get('state') in ('provisioning', 'starting', 'restarting',
                              'ready', 'serviceready', 'upgrading'):
            paperspace_api.call(client, 'stop_machine',
                                machine_id=m['id'])


def _terminate_all(client, name: str) -> None:
    for m in _live_machines(client, name).values():
        paperspace_api.call(client, 'delete_machine', machine_id=m['id'])


def terminate_instances(cluster_name: str, region: str) -> None:
    del region
    record = _records.load(cluster_name)
    if not record:
        return
    client = paperspace_api.get_client()
    _terminate_all(client, record['name_on_cloud'])
    _records.delete(cluster_name)


def get_cluster_info(cluster_name: str,
                     region: str) -> provision_lib.ClusterInfo:
    del region
    record = _records.require(cluster_name, 'Paperspace')
    client = paperspace_api.get_client()
    live = _live_machines(client, record['name_on_cloud'],
                          record.get('region'))
    hosts: List[provision_lib.HostInfo] = []
    for rank in sorted(live):
        m = live[rank]
        public = m.get('publicIp')
        private = m.get('privateIp') or public
        if private is None:
            raise exceptions.ProvisionError(
                f'No IP on machine {m.get("name")!r} yet.')
        hosts.append(provision_lib.HostInfo(
            host_id=str(m['id']), rank=rank,
            internal_ip=private, external_ip=public,
            extra={}))
    return provision_lib.ClusterInfo(
        cluster_name=cluster_name, cloud='paperspace',
        region=record['region'], zone=None, hosts=hosts,
        deploy_vars=record['deploy_vars'])


def get_command_runners(cluster_info: provision_lib.ClusterInfo,
                        ssh_credentials: Optional[Dict[str, str]] = None
                        ) -> List[runner_lib.CommandRunner]:
    return rest_cloud.ssh_runners(cluster_info, SSH_USER, ssh_credentials)
