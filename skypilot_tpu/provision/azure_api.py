"""Thin Azure compute client with a test seam.

Counterpart of the reference's azure-mgmt usage in
``sky/provision/azure/instance.py`` (VM CRUD, NSG bootstrap,
per-error-code failover classification in azure.py). The real transport
is the azure SDK (gated: this build may not ship it); tests install an
in-process fake via ``set_azure_factory`` that implements the same flat
client surface (``create_vm``, ``list_vms``, ...), so lifecycle +
failover logic runs for real with no cloud and no SDK.

The client surface is deliberately FLAT (one method per operation, dict
payloads) rather than the SDK's poller/model-class shape: the
provisioner's logic — tag-based rank discovery, capacity classification,
partial-failure teardown — is what we test; SDK plumbing belongs in the
one real adapter.

Error classification mirrors the reference Azure handler
(sky/clouds/azure.py stockout handling): allocation/SKU-capacity errors
-> zone/region failover; quota errors -> region/cloud blocklist.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, Optional

from skypilot_tpu import exceptions

# Azure error codes -> failover classification.
_CAPACITY_CODES = {
    'AllocationFailed',
    'ZonalAllocationFailed',
    'OverconstrainedAllocationRequest',
    'OverconstrainedZonalAllocationRequest',
    'SkuNotAvailable',
    'NotAvailableForSubscription',
    'SpotKeepDeallocated',  # spot capacity reclaimed
}
_QUOTA_CODES = {
    'QuotaExceeded',
    'OperationNotAllowed',  # the SDK's quota-exceeded umbrella
}


class AzureApiError(Exception):
    """Fake/real client error carrying an Azure error code."""

    def __init__(self, code: str, message: str = ''):
        super().__init__(message or code)
        self.code = code
        self.message = message or code


def classify_error(exc: Exception) -> exceptions.CloudError:
    code = getattr(exc, 'code', None)
    if code is None:  # azure.core HttpResponseError shape
        err = getattr(exc, 'error', None)
        code = getattr(err, 'code', '') if err is not None else ''
    msg = str(exc)
    if code in _CAPACITY_CODES:
        return exceptions.InsufficientCapacityError(msg, reason='capacity')
    if code in _QUOTA_CODES:
        return exceptions.CloudError(msg, reason='quota')
    return exceptions.CloudError(msg)


_azure_factory: Optional[Callable[[str], Any]] = None


def set_azure_factory(factory: Optional[Callable[[str], Any]]) -> None:
    """Test seam: ``factory(region) -> fake Azure client``."""
    global _azure_factory
    _azure_factory = factory


def get_client(region: str) -> Any:
    if _azure_factory is not None:
        return _azure_factory(region)
    raise exceptions.CloudError(
        'Real Azure provisioning needs the azure-mgmt-compute SDK, which '
        'is not installed (pip install azure-mgmt-compute '
        'azure-mgmt-network azure-identity).')


def call(client: Any, op: str, **kwargs) -> Dict[str, Any]:
    """Invoke a client op, normalizing errors to CloudError subclasses."""
    try:
        return getattr(client, op)(**kwargs)
    except AzureApiError as e:
        raise classify_error(e) from e
    except Exception as e:  # azure.core.exceptions.HttpResponseError
        # (duck-typed: the SDK may be absent, so the except can't name it)
        if getattr(e, 'error', None) is not None or hasattr(e, 'code'):
            raise classify_error(e) from e
        raise


def tag_value(vm: Dict[str, Any], key: str) -> Optional[str]:
    return (vm.get('tags') or {}).get(key)
