"""Thin GCP REST clients: TPU API (tpu.googleapis.com v2) + GCE.

The reference drives these through google-api-python-client discovery
(reference sky/provision/gcp/instance_utils.py:1203-1209); here a direct
``requests`` transport keeps the dependency surface tiny and — more
importantly — gives tests a single seam (``set_transport``) to fake the
whole cloud, including TPU state machines and capacity errors
(reference's tests mock at the boto3/discovery level instead, SURVEY.md §4).

Error classification (→ failover behavior) mirrors the reference's GCP
handler (sky/backends/cloud_vm_ray_backend.py:950-1105):
  - "no more capacity" / RESOURCE_EXHAUSTED / stockout → blocklist zone
  - quota exceeded / permission → blocklist region/cloud
"""
from __future__ import annotations

import time
from typing import Any, Dict, Iterator, List, Optional

from skypilot_tpu import exceptions

TPU_BASE = 'https://tpu.googleapis.com/v2'
GCE_BASE = 'https://compute.googleapis.com/compute/v1'

_CAPACITY_MARKERS = (
    'no more capacity',                 # TPU stockout (reference :1019)
    'out of capacity',
    'resource_exhausted',
    'stockout',
    'does not have enough resources',
    'zonal_resource_pool_exhausted',
    'insufficient capacity',
)
_QUOTA_MARKERS = ('quota', 'rate limit')


class HttpTransport:
    """Real transport: requests + google-auth token.

    Transient failures (connection errors, 5xx, throttling 429 without a
    capacity marker) are retried with exponential backoff — the TPU API
    throttles routinely, and a single 503 must not abort a provision
    (reference wraps discovery calls in per-call retries).
    """

    MAX_ATTEMPTS = 5
    BACKOFF_S = 1.0
    _RETRY_STATUSES = (429, 500, 502, 503, 504)

    def __init__(self):
        self._session = None
        self._creds = None

    def _ensure(self):
        import google.auth
        import google.auth.transport.requests
        import requests
        if self._session is None:
            self._session = requests.Session()
            self._creds, _ = google.auth.default(
                scopes=['https://www.googleapis.com/auth/cloud-platform'])
        if not self._creds.valid:
            self._creds.refresh(
                google.auth.transport.requests.Request(self._session))

    def request(self, method: str, url: str,
                json_body: Optional[Dict[str, Any]] = None,
                params: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        import requests
        last_exc: Optional[Exception] = None
        for attempt in range(self.MAX_ATTEMPTS):
            if attempt:
                time.sleep(min(self.BACKOFF_S * 2**(attempt - 1), 30))
            self._ensure()
            try:
                resp = self._session.request(
                    method, url, json=json_body, params=params,
                    headers={'Authorization': f'Bearer {self._creds.token}'},
                    timeout=60)
            except (requests.ConnectionError, requests.Timeout) as e:
                last_exc = e
                continue
            if resp.status_code < 400:
                return resp.json() if resp.content else {}
            try:
                payload = resp.json().get('error', {})
                message = payload.get('message', resp.text)
            except Exception:
                message = resp.text
            err = classify_error(resp.status_code, message)
            # Genuine capacity stockouts must surface immediately (they
            # drive zone failover); plain throttling/5xx is retried.
            is_stockout = any(m in (message or '').lower()
                              for m in _CAPACITY_MARKERS)
            if resp.status_code in self._RETRY_STATUSES and not is_stockout:
                last_exc = err
                continue
            raise err
        assert last_exc is not None
        raise (last_exc if isinstance(last_exc, exceptions.CloudError)
               else exceptions.CloudError(
                   f'transport failure after {self.MAX_ATTEMPTS} attempts: '
                   f'{last_exc!r}'))


_transport: Any = None


def get_transport() -> Any:
    global _transport
    if _transport is None:
        _transport = HttpTransport()
    return _transport


def set_transport(transport: Any) -> None:
    """Test seam: install a fake cloud."""
    global _transport
    _transport = transport


def classify_error(code: int, message: str) -> exceptions.CloudError:
    low = (message or '').lower()
    if any(m in low for m in _CAPACITY_MARKERS) or code == 429:
        return exceptions.InsufficientCapacityError(message, code=code,
                                                    reason='capacity')
    if any(m in low for m in _QUOTA_MARKERS) or code == 403:
        return exceptions.CloudError(message, code=code, reason='quota')
    return exceptions.CloudError(message, code=code)


class TpuClient:
    """projects.locations.nodes + queuedResources of tpu.googleapis.com."""

    def __init__(self, project: str):
        self.project = project

    def _loc(self, zone: str) -> str:
        return f'{TPU_BASE}/projects/{self.project}/locations/{zone}'

    # -- nodes ---------------------------------------------------------------
    def create_node(self, zone: str, node_id: str,
                    body: Dict[str, Any]) -> Dict[str, Any]:
        return get_transport().request(
            'POST', f'{self._loc(zone)}/nodes', json_body=body,
            params={'nodeId': node_id})

    def get_node(self, zone: str, node_id: str) -> Optional[Dict[str, Any]]:
        try:
            return get_transport().request(
                'GET', f'{self._loc(zone)}/nodes/{node_id}')
        except exceptions.CloudError as e:
            if e.code == 404:
                return None
            raise

    def list_nodes(self, zone: str) -> List[Dict[str, Any]]:
        out = get_transport().request('GET', f'{self._loc(zone)}/nodes')
        return out.get('nodes', [])

    def delete_node(self, zone: str, node_id: str) -> Dict[str, Any]:
        try:
            return get_transport().request(
                'DELETE', f'{self._loc(zone)}/nodes/{node_id}')
        except exceptions.CloudError as e:
            if e.code == 404:
                return {}
            raise

    def stop_node(self, zone: str, node_id: str) -> Dict[str, Any]:
        try:
            return get_transport().request(
                'POST', f'{self._loc(zone)}/nodes/{node_id}:stop')
        except exceptions.CloudError as e:
            if e.code == 404:
                # Node already gone (preempted/reaped slice of a gang):
                # stopping the rest must proceed, same as delete_node.
                return {}
            raise

    def start_node(self, zone: str, node_id: str) -> Dict[str, Any]:
        return get_transport().request(
            'POST', f'{self._loc(zone)}/nodes/{node_id}:start')

    # -- queued resources (v5p/v6e capacity; reference uses these for
    # gang-atomic multi-host slices) -----------------------------------------
    def create_queued_resource(self, zone: str, qr_id: str,
                               body: Dict[str, Any]) -> Dict[str, Any]:
        return get_transport().request(
            'POST', f'{self._loc(zone)}/queuedResources', json_body=body,
            params={'queuedResourceId': qr_id})

    def get_queued_resource(self, zone: str,
                            qr_id: str) -> Optional[Dict[str, Any]]:
        try:
            return get_transport().request(
                'GET', f'{self._loc(zone)}/queuedResources/{qr_id}')
        except exceptions.CloudError as e:
            if e.code == 404:
                return None
            raise

    def delete_queued_resource(self, zone: str,
                               qr_id: str) -> Optional[Dict[str, Any]]:
        """Returns the delete LRO (None if the QR was already gone) so
        callers re-using the same queuedResourceId can wait_operation it —
        creating before the delete completes would 409 ALREADY_EXISTS."""
        try:
            return get_transport().request(
                'DELETE', f'{self._loc(zone)}/queuedResources/{qr_id}',
                params={'force': 'true'})
        except exceptions.CloudError as e:
            if e.code != 404:
                raise
            return None

    # -- operations ----------------------------------------------------------
    def wait_operation(self, op: Dict[str, Any],
                       timeout: float = 1800) -> Dict[str, Any]:
        if not op or op.get('done') or 'name' not in op:
            return op
        deadline = time.time() + timeout
        while time.time() < deadline:
            cur = get_transport().request('GET', f'{TPU_BASE}/{op["name"]}')
            if cur.get('done'):
                err = cur.get('error')
                if err:
                    raise classify_error(err.get('code', 500),
                                         err.get('message', str(err)))
                return cur
            time.sleep(5)
        raise exceptions.ProvisionError(
            f'GCP operation {op.get("name")} timed out after {timeout}s')


class GceClient:
    """Minimal GCE instances API (controller/CPU VMs)."""

    def __init__(self, project: str):
        self.project = project

    def _zone_url(self, zone: str) -> str:
        return f'{GCE_BASE}/projects/{self.project}/zones/{zone}'

    def insert(self, zone: str, body: Dict[str, Any]) -> Dict[str, Any]:
        return get_transport().request(
            'POST', f'{self._zone_url(zone)}/instances', json_body=body)

    def list_instances(self, zone: str,
                       label_filter: Optional[str] = None
                       ) -> List[Dict[str, Any]]:
        params = {}
        if label_filter:
            params['filter'] = label_filter
        out = get_transport().request(
            'GET', f'{self._zone_url(zone)}/instances', params=params)
        return out.get('items', [])

    def delete(self, zone: str, name: str) -> Dict[str, Any]:
        try:
            return get_transport().request(
                'DELETE', f'{self._zone_url(zone)}/instances/{name}')
        except exceptions.CloudError as e:
            if e.code == 404:
                return {}
            raise

    def stop(self, zone: str, name: str) -> Dict[str, Any]:
        return get_transport().request(
            'POST', f'{self._zone_url(zone)}/instances/{name}/stop')

    def start(self, zone: str, name: str) -> Dict[str, Any]:
        return get_transport().request(
            'POST', f'{self._zone_url(zone)}/instances/{name}/start')

    @staticmethod
    def _check_op_error(op: Dict[str, Any]) -> None:
        """A DONE GCE operation may still carry an error payload
        (synchronous failures) — it must raise, not pass silently."""
        if op.get('error'):
            errs = op['error'].get('errors', [])
            msg = '; '.join(e.get('message', '') for e in errs) \
                or str(op['error'])
            raise classify_error(500, msg)

    def wait_zone_operation(self, zone: str, op: Dict[str, Any],
                            timeout: float = 600) -> None:
        if not op or 'name' not in op or op.get('status') == 'DONE':
            if op:
                self._check_op_error(op)
            return
        deadline = time.time() + timeout
        while time.time() < deadline:
            cur = get_transport().request(
                'GET', f'{self._zone_url(zone)}/operations/{op["name"]}')
            if cur.get('status') == 'DONE':
                self._check_op_error(cur)
                return
            time.sleep(2)
        raise exceptions.ProvisionError('GCE operation timed out')

    # -- images (clone-disk support) -----------------------------------------
    def create_image(self, image_name: str, zone: str,
                     source_disk: str) -> Dict[str, Any]:
        """Create a global image from a zonal disk (the boot disk of an
        auto-created instance shares the instance's name)."""
        body = {'name': image_name,
                'sourceDisk': (f'projects/{self.project}/zones/{zone}/'
                               f'disks/{source_disk}')}
        return get_transport().request(
            'POST', f'{self._global_url()}/images', json_body=body)

    # -- firewalls (global resources; serving-port exposure) -----------------
    def _global_url(self) -> str:
        return f'{GCE_BASE}/projects/{self.project}/global'

    def get_firewall(self, name: str) -> Optional[Dict[str, Any]]:
        try:
            return get_transport().request(
                'GET', f'{self._global_url()}/firewalls/{name}')
        except exceptions.CloudError as e:
            if e.code == 404:
                return None
            raise

    def insert_firewall(self, body: Dict[str, Any]) -> Dict[str, Any]:
        return get_transport().request(
            'POST', f'{self._global_url()}/firewalls', json_body=body)

    def patch_firewall(self, name: str,
                       body: Dict[str, Any]) -> Dict[str, Any]:
        return get_transport().request(
            'PATCH', f'{self._global_url()}/firewalls/{name}',
            json_body=body)

    def delete_firewall(self, name: str) -> Dict[str, Any]:
        try:
            return get_transport().request(
                'DELETE', f'{self._global_url()}/firewalls/{name}')
        except exceptions.CloudError as e:
            if e.code == 404:
                return {}
            raise

    def wait_global_operation(self, op: Dict[str, Any],
                              timeout: float = 600) -> None:
        if not op or 'name' not in op or op.get('status') == 'DONE':
            if op:
                self._check_op_error(op)
            return
        deadline = time.time() + timeout
        while time.time() < deadline:
            cur = get_transport().request(
                'GET', f'{self._global_url()}/operations/{op["name"]}')
            if cur.get('status') == 'DONE':
                self._check_op_error(cur)
                return
            time.sleep(2)
        raise exceptions.ProvisionError('GCE global operation timed out')
