"""Kubernetes provisioner: pods as hosts, GKE TPU slices as node selectors.

Counterpart of reference ``sky/provision/kubernetes/instance.py`` (+ the
GKE-TPU label logic in ``utils.py`` — is_tpu_on_gke, TPU accelerator/
topology selectors). TPU-native shape: a multi-host TPU slice maps to one
pod per TPU-VM worker, all carrying the generation's GKE podslice node
selector + topology, so GKE's TPU webhook injects the right device
plumbing; ranks are stable via a ``skytpu/rank`` label.

Pods are the whole lifecycle: no STOP (pods don't stop — the cloud ABC
excludes the feature), terminate deletes by label selector.
"""
from __future__ import annotations

import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu import provision as provision_lib
from skypilot_tpu.provision import k8s_api
from skypilot_tpu.utils import command_runner as runner_lib

_CLUSTER_LABEL = 'skytpu/cluster'
_RANK_LABEL = 'skytpu/rank'

# GKE TPU podslice accelerator labels per generation (reference
# sky/provision/kubernetes/utils.py GKELabelFormatter).
GKE_TPU_ACCELERATOR = {
    'v4': 'tpu-v4-podslice',
    'v5e': 'tpu-v5-lite-podslice',
    'v5p': 'tpu-v5p-slice',
    'v6e': 'tpu-v6e-slice',
}

_DEFAULT_IMAGE = 'python:3.11-slim'


def _namespace(deploy_vars: Dict[str, Any]) -> str:
    return deploy_vars.get('namespace') or 'default'


def _client(deploy_vars: Dict[str, Any]) -> k8s_api.PodClient:
    return k8s_api.PodClient(namespace=_namespace(deploy_vars))


def _pod_name(cluster_name: str, rank: int) -> str:
    return f'{cluster_name}-{rank}'


def _pod_body(cluster_name: str, rank: int,
              deploy_vars: Dict[str, Any]) -> Dict[str, Any]:
    tpu_gen = deploy_vars.get('tpu_generation')
    chips_per_host = int(deploy_vars.get('chips_per_host') or 0)
    container: Dict[str, Any] = {
        'name': 'skytpu',
        'image': deploy_vars.get('image') or _DEFAULT_IMAGE,
        # The runtime drives pods through exec; the container just stays up.
        'command': ['/bin/sh', '-c', 'sleep infinity'],
        'resources': {'requests': {}, 'limits': {}},
    }
    if deploy_vars.get('cpus'):
        container['resources']['requests']['cpu'] = str(
            deploy_vars['cpus'])
    if deploy_vars.get('memory_gb'):
        container['resources']['requests']['memory'] = (
            f"{deploy_vars['memory_gb']}Gi")
    spec: Dict[str, Any] = {
        'restartPolicy': 'Never',
        'containers': [container],
    }
    if tpu_gen:
        accelerator = GKE_TPU_ACCELERATOR.get(tpu_gen)
        if accelerator is None:
            raise exceptions.InvalidResourcesError(
                f'TPU generation {tpu_gen!r} has no GKE podslice mapping')
        spec['nodeSelector'] = {
            'cloud.google.com/gke-tpu-accelerator': accelerator,
            'cloud.google.com/gke-tpu-topology':
                deploy_vars.get('tpu_topology', ''),
        }
        # google.com/tpu counts CHIPS visible to this pod (one worker's).
        container['resources']['requests']['google.com/tpu'] = \
            str(chips_per_host)
        container['resources']['limits']['google.com/tpu'] = \
            str(chips_per_host)
    return {
        'apiVersion': 'v1',
        'kind': 'Pod',
        'metadata': {
            'name': _pod_name(cluster_name, rank),
            'labels': {
                _CLUSTER_LABEL: cluster_name,
                _RANK_LABEL: str(rank),
            },
        },
        'spec': spec,
    }


# ---- provision API ---------------------------------------------------------
def run_instances(cluster_name: str, region: str, zone: Optional[str],
                  num_hosts: int, deploy_vars: Dict[str, Any]) -> None:
    import json
    client = _client(deploy_vars)
    # Kubernetes object names/labels must be DNS-1123: use the sanitized
    # on-cloud name (display names may carry e.g. underscores).
    name = deploy_vars.get('cluster_name_on_cloud') or cluster_name
    # Persist BEFORE creating pods: a mid-loop create failure must leave
    # terminate_instances able to find the partial set by its real label.
    from skypilot_tpu import global_user_state
    global_user_state.set_kv(
        f'k8s_deploy:{cluster_name}',
        json.dumps({'namespace': _namespace(deploy_vars),
                    'name_on_cloud': name, 'num_hosts': num_hosts}))
    existing = {p['metadata']['name']
                for p in client.list_pods(f'{_CLUSTER_LABEL}={name}')}
    for rank in range(num_hosts):
        if _pod_name(name, rank) in existing:
            continue  # idempotent re-run
        client.create_pod(_pod_body(name, rank, deploy_vars))


def _stored(cluster_name: str) -> Dict[str, Any]:
    import json
    from skypilot_tpu import global_user_state
    raw = global_user_state.get_kv(f'k8s_deploy:{cluster_name}')
    if not raw:
        return {'namespace': 'default', 'name_on_cloud': cluster_name,
                'num_hosts': 0}
    return json.loads(raw)


def wait_instances(cluster_name: str, region: str, state: str = 'running',
                   timeout: float = 1800) -> None:
    if state != 'running':
        raise exceptions.NotSupportedError(
            'kubernetes pods only wait for running')
    stored = _stored(cluster_name)
    name = stored['name_on_cloud']
    want = stored['num_hosts']
    client = k8s_api.PodClient(namespace=stored['namespace'])
    deadline = time.time() + timeout
    poll = 2.0
    while True:
        pods = client.list_pods(f'{_CLUSTER_LABEL}={name}')
        phases = {p['metadata']['name']: p.get('status', {}).get('phase')
                  for p in pods}
        if (pods and (not want or len(pods) == want)
                and all(ph == 'Running' for ph in phases.values())):
            return
        # Terminal pod phases never heal (restartPolicy=Never): waiting
        # out the timeout would only delay failover.
        dead = [n for n, ph in phases.items()
                if ph in ('Failed', 'Succeeded')]
        if dead:
            raise exceptions.ProvisionError(
                f'kubernetes pods for {cluster_name!r} terminated during '
                f'bring-up: {dead}')
        # Surface scheduling stockouts immediately: they drive failover.
        for p in pods:
            if p.get('status', {}).get('phase') != 'Pending':
                continue
            for evt in client.pod_events(p['metadata']['name']):
                if evt.get('reason') == 'FailedScheduling':
                    err = k8s_api.classify_scheduling_error(
                        evt.get('message', ''))
                    if err is not None:
                        raise err
        if time.time() > deadline:
            raise exceptions.ProvisionError(
                f'kubernetes pods for {cluster_name!r} not Running within '
                f'{timeout}s: {phases}')
        time.sleep(poll)


def stop_instances(cluster_name: str, region: str) -> None:
    raise exceptions.NotSupportedError('kubernetes pods cannot be stopped')


def terminate_instances(cluster_name: str, region: str) -> None:
    stored = _stored(cluster_name)
    client = k8s_api.PodClient(namespace=stored['namespace'])
    for pod in client.list_pods(
            f'{_CLUSTER_LABEL}={stored["name_on_cloud"]}'):
        client.delete_pod(pod['metadata']['name'])
    client.delete_service(f'{stored["name_on_cloud"]}-ports')
    from skypilot_tpu import global_user_state
    global_user_state.set_kv(f'k8s_deploy:{cluster_name}', None)


_PHASE_MAP = {'Pending': 'starting', 'Running': 'running',
              'Succeeded': 'terminated', 'Failed': 'terminated',
              'Unknown': 'unknown'}


def query_instances(cluster_name: str, region: str) -> Dict[str, str]:
    stored = _stored(cluster_name)
    client = k8s_api.PodClient(namespace=stored['namespace'])
    out = {}
    for pod in client.list_pods(
            f'{_CLUSTER_LABEL}={stored["name_on_cloud"]}'):
        phase = pod.get('status', {}).get('phase', 'Unknown')
        out[pod['metadata']['name']] = _PHASE_MAP.get(phase, 'unknown')
    return out


def get_cluster_info(cluster_name: str, region: str
                     ) -> provision_lib.ClusterInfo:
    stored = _stored(cluster_name)
    namespace = stored['namespace']
    client = k8s_api.PodClient(namespace=namespace)
    pods = client.list_pods(f'{_CLUSTER_LABEL}={stored["name_on_cloud"]}')
    if not pods:
        raise exceptions.ClusterError(
            f'kubernetes cluster {cluster_name!r} has no pods')
    pods.sort(key=lambda p: int(p['metadata']['labels'].get(_RANK_LABEL,
                                                            '0')))
    hosts = []
    for pod in pods:
        rank = int(pod['metadata']['labels'].get(_RANK_LABEL, '0'))
        hosts.append(provision_lib.HostInfo(
            host_id=pod['metadata']['name'],
            rank=rank,
            internal_ip=pod.get('status', {}).get('podIP', ''),
            external_ip=None,
            extra={'namespace': namespace,
                   'pod_name': pod['metadata']['name']}))
    return provision_lib.ClusterInfo(
        cluster_name=cluster_name, cloud='kubernetes', region=region,
        zone=None, hosts=hosts,
        deploy_vars={'namespace': namespace})


def open_ports(cluster_name: str, region: str, ports: List[str]) -> None:
    """NodePort service targeting the head pod (rank 0)."""
    stored = _stored(cluster_name)
    name = stored['name_on_cloud']
    client = k8s_api.PodClient(namespace=stored['namespace'])
    client.create_service({
        'apiVersion': 'v1',
        'kind': 'Service',
        'metadata': {'name': f'{name}-ports',
                     'labels': {_CLUSTER_LABEL: name}},
        'spec': {
            'type': 'NodePort',
            'selector': {_CLUSTER_LABEL: name, _RANK_LABEL: '0'},
            'ports': [{'name': f'p{p}', 'port': int(p),
                       'targetPort': int(p)} for p in ports],
        },
    })


def get_command_runners(cluster_info: provision_lib.ClusterInfo,
                        ssh_credentials: Optional[Dict[str, str]] = None
                        ) -> List[runner_lib.CommandRunner]:
    return [
        runner_lib.KubernetesCommandRunner(
            namespace=h.extra['namespace'], pod_name=h.extra['pod_name'])
        for h in cluster_info.hosts
    ]
