"""Thin RunPod GraphQL client with a test seam.

Counterpart of the reference's ``sky/provision/runpod/utils.py`` (runpod
SDK wrapper: create_pod / create_spot_pod / get_pods / terminate). The
real transport POSTs GraphQL to ``https://api.runpod.io/graphql``
(``api_key`` query param, the SDK's auth shape); tests install an
in-process fake via ``set_runpod_factory`` implementing the same flat
surface (``create_pod``, ``list_pods``, ``terminate_pod``), so pod
lifecycle + bid/failover logic runs for real with no cloud.

Error classification: "no longer any instances available" / "no gpu
found" wording (the API's stockout phrasing) -> capacity failover;
balance/spend-limit wording -> quota.
"""
from __future__ import annotations

import json
from typing import Any, Dict, List, Optional

import os

from skypilot_tpu import exceptions
from skypilot_tpu.provision import rest_cloud

API_ENDPOINT = 'https://api.runpod.io/graphql'
CREDENTIALS_PATH = '~/.runpod/config.toml'

_CAPACITY_MARKERS = (
    'no longer any instances available',
    'no gpu found',
    'not enough',
    'unavailable',
)
_QUOTA_MARKERS = (
    'spend limit',
    'insufficient balance',
    'zero balance',
)


class RunpodApiError(Exception):
    """Fake/real client error carrying a GraphQL error message."""

    def __init__(self, message: str):
        super().__init__(message)
        self.message = message


classify_error = rest_cloud.marker_classifier(_CAPACITY_MARKERS,
                                              _QUOTA_MARKERS)


def read_api_key() -> Optional[str]:
    env = os.environ.get('RUNPOD_API_KEY')
    if env:
        return env
    path = os.path.expanduser(CREDENTIALS_PATH)
    if os.path.exists(path):
        # Minimal TOML: the SDK writes `api_key = "<key>"`.
        with open(path, encoding='utf-8') as f:
            for line in f:
                key, _, value = line.partition('=')
                if key.strip() == 'api_key':
                    return value.strip().strip('"\'') or None
    return None


def _parse_error(status: int, raw: bytes) -> Exception:
    try:
        body = json.loads(raw.decode())
        errs = body.get('errors') or []
        if errs:
            return RunpodApiError(errs[0].get('message', raw.decode()))
        return RunpodApiError(raw.decode())
    except (ValueError, AttributeError):
        return RunpodApiError(raw.decode(errors='replace') or str(status))


class _RestClient:
    """Flat op surface over GraphQL mutations/queries."""

    def __init__(self):
        api_key = read_api_key()
        if api_key is None:
            raise exceptions.CloudError(
                'RunPod credentials not found: set $RUNPOD_API_KEY or '
                f'run `runpod config` ({CREDENTIALS_PATH}).')
        self._url = f'{API_ENDPOINT}?api_key={api_key}'
        self._headers = {'Content-Type': 'application/json'}

    def _gql(self, query: str,
             variables: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        body = rest_cloud.retrying_request(
            'POST', self._url, self._headers,
            {'query': query, 'variables': variables or {}}, _parse_error)
        errs = body.get('errors') or []
        if errs:  # GraphQL errors ride a 200 response
            raise RunpodApiError(errs[0].get('message', str(errs[0])))
        return body.get('data') or {}

    # -- flat op surface (mirrored by test fakes) ---------------------------
    def create_pod(self, name: str, image: str, gpu_type_id: str,
                   gpu_count: int, cloud_type: str, country_code: str,
                   disk_gb: int, ports: str, docker_args: str,
                   bid_per_gpu: Optional[float] = None) -> Dict[str, Any]:
        mutation = ('podRentInterruptable' if bid_per_gpu is not None
                    else 'podFindAndDeployOnDemand')
        inp: Dict[str, Any] = {
            'name': name, 'imageName': image, 'gpuTypeId': gpu_type_id,
            'gpuCount': gpu_count, 'cloudType': cloud_type,
            'countryCode': country_code, 'containerDiskInGb': disk_gb,
            'ports': ports, 'dockerArgs': docker_args,
            'supportPublicIp': True,
        }
        if bid_per_gpu is not None:
            inp['bidPerGpu'] = bid_per_gpu
        data = self._gql(
            f'mutation($input: PodRentInput!) {{ {mutation}(input: $input)'
            ' { id desiredStatus } }', {'input': inp})
        return dict(data.get(mutation) or {})

    def list_pods(self) -> List[Dict[str, Any]]:
        data = self._gql(
            'query { myself { pods { id name desiredStatus costPerHr '
            'runtime { ports { ip isIpPublic privatePort publicPort } } '
            '} } }')
        return list(((data.get('myself') or {}).get('pods')) or [])

    def terminate_pod(self, pod_id: str) -> None:
        self._gql('mutation($id: String!) { podTerminate(podId: $id) }',
                  {'id': pod_id})


# Test seam (``set_runpod_factory(lambda: fake)``), client construction
# and error-normalizing ``call`` via the shared ClientSeam.
_seam = rest_cloud.ClientSeam(_RestClient, RunpodApiError, classify_error)
set_runpod_factory = _seam.set_factory
get_client = _seam.get_client
call = _seam.call
