"""Azure provisioner: VM host groups (controllers, CPU tasks, storage).

Counterpart of reference ``sky/provision/azure/instance.py`` (VM ops,
NSG bootstrap in config.py) — the third VM cloud proving the functional
provision API generalizes. Same record/classification/failover shape as
the GCP/AWS provisioners so ``RetryingProvisioner`` drives all three
identically: tag-based rank discovery, capacity-vs-quota error
classification, partial-failure teardown.

Azure-isms vs EC2 (mirrored from the reference's handling):
- stop is ``deallocate`` (billing stops; 'stopped' alone still bills);
- spot is ``priority='Spot'`` + an eviction policy, and reclaim
  DEALLOCATES the VM rather than deleting it — a spot VM found
  deallocated that we did not stop counts as preempted;
- ports are NSG rules with priorities, not SG permissions.

Cluster bookkeeping (region, zone, name-on-cloud) lives in the client
state kv, mirroring ``provision/gcp.py``.
"""
from __future__ import annotations

import json
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import authentication
from skypilot_tpu import exceptions
from skypilot_tpu import global_user_state
from skypilot_tpu import provision as provision_lib
from skypilot_tpu.provision import azure_api
from skypilot_tpu.utils import command_runner as runner_lib

_TAG_CLUSTER = 'skytpu-cluster'
_TAG_RANK = 'skytpu-rank'

# Azure power/provisioning states -> the provision API's state words.
_STATE_MAP = {
    'creating': 'pending', 'starting': 'pending', 'running': 'running',
    'stopping': 'stopping', 'stopped': 'stopping',  # stopped still bills
    'deallocating': 'stopping', 'deallocated': 'stopped',
    'deleting': 'terminating',
}

SSH_USER = 'azureuser'  # canonical Azure Linux login

# NSG rule priorities: 100-4096, lower wins; SSH at 1000, task ports from
# 2000 upward (one rule per port spec, priority derived from the port so
# re-opening is idempotent).
_SSH_PRIORITY = 1000
_PORT_PRIORITY_BASE = 2000


# ---- cluster record --------------------------------------------------------
def _record_key(cluster_name: str) -> str:
    return f'azure_cluster/{cluster_name}'


def _save_record(cluster_name: str, record: Dict[str, Any]) -> None:
    global_user_state.set_kv(_record_key(cluster_name), json.dumps(record))


def _load_record(cluster_name: str) -> Optional[Dict[str, Any]]:
    raw = global_user_state.get_kv(_record_key(cluster_name))
    return json.loads(raw) if raw else None


def _delete_record(cluster_name: str) -> None:
    global_user_state.set_kv(_record_key(cluster_name), '')


def _require_record(cluster_name: str) -> Dict[str, Any]:
    record = _load_record(cluster_name)
    if not record:
        raise exceptions.ClusterError(
            f'No Azure provisioning record for {cluster_name!r}')
    return record


def _nsg_name(name_on_cloud: str) -> str:
    return f'skytpu-{name_on_cloud}-nsg'


def _live_vms(client, name: str,
              include_deleting: bool = False) -> List[Dict[str, Any]]:
    vms = azure_api.call(client, 'list_vms').get('vms', [])
    out = []
    for vm in vms:
        if azure_api.tag_value(vm, _TAG_CLUSTER) != name:
            continue
        if not include_deleting and vm.get('state') == 'deleting':
            continue
        if vm.get('state') == 'deleted':
            continue
        out.append(vm)
    return out


def _ensure_nsg(client, name: str) -> str:
    """Per-cluster network security group with SSH open; task/serve ports
    added by open_ports (reference sky/provision/azure/config.py)."""
    nsg = _nsg_name(name)
    existing = azure_api.call(client, 'list_nsgs').get('nsgs', [])
    if nsg not in existing:
        azure_api.call(client, 'create_nsg', name=nsg)
        azure_api.call(client, 'upsert_nsg_rule', nsg=nsg,
                       rule_name='skytpu-ssh', priority=_SSH_PRIORITY,
                       port_range='22', source_ranges=['0.0.0.0/0'])
    return nsg


# ---- provision API ---------------------------------------------------------
def run_instances(cluster_name: str, region: str, zone: Optional[str],
                  num_hosts: int, deploy_vars: Dict[str, Any]) -> None:
    name = deploy_vars['cluster_name_on_cloud']
    record = {'region': region, 'zone': zone, 'name_on_cloud': name,
              'num_hosts': num_hosts, 'deploy_vars': deploy_vars}
    # Record BEFORE creating (partial-failure resources must stay
    # reachable by terminate_instances; same contract as provision/gcp.py).
    _save_record(cluster_name, record)
    client = azure_api.get_client(region)
    try:
        nsg = _ensure_nsg(client, name)
        _, pub_path = authentication.get_or_generate_keys()
        with open(pub_path) as f:
            ssh_pub = f.read().strip()
        existing = {azure_api.tag_value(vm, _TAG_RANK): vm
                    for vm in _live_vms(client, name)}
        to_start = []
        missing_ranks = []
        for rank in range(num_hosts):
            vm = existing.get(str(rank))
            if vm is None:
                missing_ranks.append(rank)
            elif vm['state'] == 'deallocated':
                to_start.append(vm['name'])
        if to_start:
            azure_api.call(client, 'start_vms', names=to_start)
        for rank in missing_ranks:
            azure_api.call(
                client, 'create_vm',
                name=f'{name}-{rank}',
                vm_size=deploy_vars.get('instance_type',
                                        'Standard_D2s_v5'),
                image=(deploy_vars.get('image_id')
                       or 'Canonical:ubuntu-24_04-lts:server:latest'),
                zone=zone,
                nsg=nsg,
                os_disk_gb=deploy_vars.get('disk_size_gb', 256),
                ssh_user=SSH_USER,
                ssh_public_key=ssh_pub,
                priority=('Spot' if deploy_vars.get('use_spot')
                          else 'Regular'),
                eviction_policy=('Deallocate'
                                 if deploy_vars.get('use_spot') else None),
                tags={
                    _TAG_CLUSTER: name,
                    _TAG_RANK: str(rank),
                    **{k: str(v) for k, v in
                       (deploy_vars.get('labels') or {}).items()},
                })
    except exceptions.InsufficientCapacityError:
        # Clean up any partial hosts, then drop the record so zone
        # failover retries don't see a stale pointer.
        try:
            _terminate_all(client, name)
        except exceptions.CloudError:
            pass
        _delete_record(cluster_name)
        raise


def wait_instances(cluster_name: str, region: str, state: str = 'running',
                   timeout: float = 1800) -> None:
    record = _load_record(cluster_name) or {}
    use_spot = bool((record.get('deploy_vars') or {}).get('use_spot'))
    deadline = time.time() + timeout
    saw_running = False
    while time.time() < deadline:
        states = set(query_instances(cluster_name, region).values())
        if states == {state}:
            return
        saw_running = saw_running or 'running' in states
        if (not states or 'terminating' in states
                or 'terminated' in states):
            # 'terminated' appears as a rank{N}-missing hole from
            # query_instances: a partially-dead cluster must fail over,
            # not wait out the timeout (parity with aws.py/gcp.py).
            raise exceptions.InsufficientCapacityError(
                f'{cluster_name}: VM(s) disappeared while waiting for '
                f'{state}', reason='capacity')
        if (state == 'running' and 'stopped' in states
                and (use_spot or saw_running)):
            # Azure spot reclaim DEALLOCATES rather than deletes: a VM
            # going (back) to deallocated mid-wait was evicted — capacity,
            # so failover fires. Gated on spot / a previously-seen running
            # state: a non-spot restart of a deallocated cluster passes
            # through 'stopped' legitimately while its async start lands.
            raise exceptions.InsufficientCapacityError(
                f'{cluster_name}: VM deallocated while waiting for '
                'running (spot eviction?)', reason='capacity')
        time.sleep(5)
    raise exceptions.ProvisionError(
        f'{cluster_name} did not reach {state!r} within {timeout}s')


def query_instances(cluster_name: str, region: str) -> Dict[str, str]:
    """Live host states. A PARTIALLY-dead cluster reports its missing
    ranks as 'terminated' (managed-job recovery must see the hole); a
    fully-dead cluster returns {} ("terminated cluster" contract in
    core.py)."""
    record = _load_record(cluster_name)
    if not record:
        return {}
    client = azure_api.get_client(record['region'])
    out: Dict[str, str] = {}
    live_ranks = set()
    for vm in _live_vms(client, record['name_on_cloud']):
        out[vm['name']] = _STATE_MAP.get(vm['state'], 'unknown')
        live_ranks.add(azure_api.tag_value(vm, _TAG_RANK))
    if not out:
        return {}
    for rank in range(int(record.get('num_hosts') or 0)):
        if str(rank) not in live_ranks:
            out[f'rank{rank}-missing'] = 'terminated'
    return out


def stop_instances(cluster_name: str, region: str) -> None:
    """Deallocate (NOT power-off: a merely 'stopped' Azure VM still
    bills compute; only 'deallocated' releases it)."""
    record = _require_record(cluster_name)
    client = azure_api.get_client(record['region'])
    # 'stopped' (OS powered off) still bills compute — deallocate it too;
    # only 'deallocated'/'deallocating' are already done.
    names = [vm['name'] for vm in _live_vms(client, record['name_on_cloud'])
             if vm['state'] in ('creating', 'starting', 'running',
                                'stopping', 'stopped')]
    if names:
        azure_api.call(client, 'deallocate_vms', names=names)


def _terminate_all(client, name: str) -> None:
    names = [vm['name'] for vm in _live_vms(client, name)]
    if names:
        azure_api.call(client, 'delete_vms', names=names)


def terminate_instances(cluster_name: str, region: str) -> None:
    record = _load_record(cluster_name)
    if not record:
        return
    client = azure_api.get_client(record['region'])
    name = record['name_on_cloud']
    _terminate_all(client, name)
    try:
        azure_api.call(client, 'delete_nsg', name=_nsg_name(name))
    except exceptions.CloudError:
        pass  # best-effort; reused on relaunch otherwise
    _delete_record(cluster_name)


def get_cluster_info(cluster_name: str,
                     region: str) -> provision_lib.ClusterInfo:
    record = _require_record(cluster_name)
    client = azure_api.get_client(record['region'])
    hosts: List[provision_lib.HostInfo] = []
    vms = _live_vms(client, record['name_on_cloud'])
    vms.sort(key=lambda vm: int(azure_api.tag_value(vm, _TAG_RANK) or 0))
    for vm in vms:
        rank = int(azure_api.tag_value(vm, _TAG_RANK) or 0)
        hosts.append(provision_lib.HostInfo(
            host_id=vm['name'], rank=rank,
            internal_ip=vm.get('private_ip', ''),
            external_ip=vm.get('public_ip'),
            extra={}))
    return provision_lib.ClusterInfo(
        cluster_name=cluster_name, cloud='azure', region=record['region'],
        zone=record.get('zone'), hosts=hosts,
        deploy_vars=record['deploy_vars'])


def open_ports(cluster_name: str, region: str, ports: List[str]) -> None:
    """Upsert one NSG rule per port spec (reference
    sky/provision/azure open_ports). A rule keeps its name across calls,
    so re-opening is idempotent and a tightened
    ``azure.firewall_source_ranges`` re-applies on the next call.
    Priorities must be UNIQUE per NSG direction on real Azure: an
    existing rule reuses its priority, a new rule takes the lowest free
    slot at/above the task-port base."""
    if not ports:
        return
    record = _require_record(cluster_name)
    client = azure_api.get_client(record['region'])
    nsg = _nsg_name(record['name_on_cloud'])
    from skypilot_tpu import config as config_lib
    ranges = config_lib.get_nested(('azure', 'firewall_source_ranges'),
                                   ['0.0.0.0/0'])
    existing = azure_api.call(client, 'list_nsg_rules',
                              nsg=nsg).get('rules', {})
    used = {r['priority'] for r in existing.values()}

    def next_free_priority() -> int:
        p = _PORT_PRIORITY_BASE
        while p in used:
            p += 1
        used.add(p)
        return p

    for port in sorted(ports, key=str):
        if '-' in str(port):
            lo, hi = (int(p) for p in str(port).split('-', 1))
        else:
            lo = hi = int(port)
        rule_name = f'skytpu-port-{lo}-{hi}'
        priority = (existing[rule_name]['priority']
                    if rule_name in existing else next_free_priority())
        azure_api.call(
            client, 'upsert_nsg_rule', nsg=nsg,
            rule_name=rule_name, priority=priority,
            port_range=(f'{lo}' if lo == hi else f'{lo}-{hi}'),
            source_ranges=list(ranges))


def get_command_runners(cluster_info: provision_lib.ClusterInfo,
                        ssh_credentials: Optional[Dict[str, str]] = None
                        ) -> List[runner_lib.CommandRunner]:
    creds = ssh_credentials or {}
    key_path = creds.get('key_path')
    if key_path is None:
        key_path, _ = authentication.get_or_generate_keys()
    user = creds.get('user', SSH_USER)
    runners: List[runner_lib.CommandRunner] = []
    for h in cluster_info.hosts:
        ip = h.external_ip or h.internal_ip
        runners.append(runner_lib.SSHCommandRunner(ip, user, key_path))
    return runners
