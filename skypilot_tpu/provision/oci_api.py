"""Thin OCI Core Services client with REAL request signing + test seam.

Counterpart of the reference's oci SDK usage
(``sky/provision/oci/query_utils.py`` over the oci python SDK). Unlike
the other REST clouds, OCI authenticates every request with an RSA
HTTP signature (draft-cavage), so this module carries a real signing
transport built on ``cryptography`` — no oci SDK needed:

- ``~/.oci/config`` (ini: user/fingerprint/key_file/tenancy/region) is
  the credential source, exactly what the oci CLI writes;
- requests are signed over ``(request-target) host date`` (+
  ``x-content-sha256 content-type content-length`` for bodies) with
  ``keyId = tenancy/user/fingerprint``;
- tests install an in-process fake via ``set_oci_factory`` implementing
  the flat surface (``launch_instance``, ``list_instances``,
  ``instance_action``, ``terminate_instance``, vnic/NSG ops), so
  lifecycle + failover logic runs with no cloud and no keys.

Error classification: the canonical "Out of host capacity." (OCI's
infamous stockout) -> failover; LimitExceeded/QuotaExceeded -> quota.
"""
from __future__ import annotations

import base64
import configparser
import datetime
import email.utils
import hashlib
import json
import os
import urllib.parse
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import rest_cloud

CONFIG_PATH = '~/.oci/config'
API_VERSION = '20160918'

_CAPACITY_MARKERS = (
    'out of host capacity',
    'out of capacity',
    'internalerror',  # OCI's launch-time capacity umbrella
)
_QUOTA_MARKERS = (
    'limitexceeded',
    'quotaexceeded',
    'service limit',
)


class OciApiError(Exception):
    """Fake/real client error carrying an OCI error code + message."""

    def __init__(self, status: int, code: str = '', message: str = ''):
        super().__init__(message or code or str(status))
        self.status = status
        self.code = code
        self.message = message or code or str(status)


classify_error = rest_cloud.marker_classifier(_CAPACITY_MARKERS,
                                              _QUOTA_MARKERS)


def read_config(profile: str = 'DEFAULT') -> Optional[Dict[str, str]]:
    """Parse ~/.oci/config; None when absent/incomplete."""
    path = os.path.expanduser(os.environ.get('OCI_CLI_CONFIG_FILE')
                              or CONFIG_PATH)
    if not os.path.exists(path):
        return None
    parser = configparser.ConfigParser()
    try:
        parser.read(path)
    except configparser.Error:
        return None
    if profile not in parser:
        return None
    section = parser[profile]
    cfg = {k: section.get(k, '') for k in
           ('user', 'fingerprint', 'key_file', 'tenancy', 'region')}
    if not all(cfg.values()):
        return None
    return cfg


def _parse_error(status: int, raw: bytes) -> Exception:
    """OCI's error envelope: {'code': ..., 'message': ...}."""
    try:
        err = json.loads(raw.decode())
        return OciApiError(status, err.get('code', ''),
                           err.get('message', raw.decode()))
    except (ValueError, AttributeError):
        return OciApiError(status, '',
                           raw.decode(errors='replace') or str(status))


class _Signer:
    """draft-cavage HTTP signature with the API key from ~/.oci/config."""

    def __init__(self, cfg: Dict[str, str]):
        from cryptography.hazmat.primitives import serialization
        self.key_id = (f'{cfg["tenancy"]}/{cfg["user"]}/'
                       f'{cfg["fingerprint"]}')
        with open(os.path.expanduser(cfg['key_file']), 'rb') as f:
            self._key = serialization.load_pem_private_key(f.read(),
                                                           password=None)

    def sign(self, method: str, url: str,
             body: Optional[bytes]) -> Dict[str, str]:
        from cryptography.hazmat.primitives import hashes
        from cryptography.hazmat.primitives.asymmetric import padding
        parsed = urllib.parse.urlsplit(url)
        target = parsed.path + (f'?{parsed.query}' if parsed.query else '')
        date = email.utils.format_datetime(
            datetime.datetime.now(datetime.timezone.utc), usegmt=True)
        headers = {'date': date, 'host': parsed.netloc}
        names = ['(request-target)', 'host', 'date']
        lines = [f'(request-target): {method.lower()} {target}',
                 f'host: {parsed.netloc}', f'date: {date}']
        if body is not None:
            sha = base64.b64encode(
                hashlib.sha256(body).digest()).decode()
            headers.update({'x-content-sha256': sha,
                            'content-type': 'application/json',
                            'content-length': str(len(body))})
            names += ['x-content-sha256', 'content-type',
                      'content-length']
            lines += [f'x-content-sha256: {sha}',
                      'content-type: application/json',
                      f'content-length: {len(body)}']
        signature = base64.b64encode(self._key.sign(
            '\n'.join(lines).encode(), padding.PKCS1v15(),
            hashes.SHA256())).decode()
        headers['Authorization'] = (
            'Signature version="1",'
            f'keyId="{self.key_id}",algorithm="rsa-sha256",'
            f'headers="{" ".join(names)}",signature="{signature}"')
        return headers


class _RestClient:
    """Flat op surface over the signed transport (Core Services API).

    ``region`` overrides the home region from ~/.oci/config: the
    endpoint is per-region (iaas.<region>.oraclecloud.com), so
    provisioning a failed-over region MUST NOT talk to the home
    region's endpoint (an AD of another region is rejected there).
    """

    def __init__(self, region: Optional[str] = None):
        cfg = read_config()
        if cfg is None:
            raise exceptions.CloudError(
                'OCI credentials not found: run `oci setup config` '
                f'({CONFIG_PATH} needs user/fingerprint/key_file/'
                'tenancy/region).')
        self._cfg = cfg
        self._signer = _Signer(cfg)
        self._region = region or cfg['region']
        self._base = (f'https://iaas.{self._region}'
                      f'.oraclecloud.com/{API_VERSION}')
        # Identity service (availability-domain listing) lives on its
        # own per-region endpoint, same signing transport.
        self._identity_base = (f'https://identity.{self._region}'
                               f'.oraclecloud.com/{API_VERSION}')

    @property
    def tenancy(self) -> str:
        return self._cfg['tenancy']

    def _request(self, method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None,
                 return_headers: bool = False,
                 base: Optional[str] = None) -> Any:
        url = f'{base or self._base}{path}'
        body = (json.dumps(payload).encode()
                if payload is not None else None)
        # Header FACTORY, not a dict: each retry attempt re-signs, so a
        # 429 backoff (up to ~135s of sleeps) can't drift the signed
        # date header into OCI's clock-skew rejection window.
        return rest_cloud.retrying_request(
            method, url, lambda: self._signer.sign(method, url, body),
            payload, _parse_error, return_headers=return_headers)

    # -- flat op surface (mirrored by test fakes) ---------------------------
    def launch_instance(self, compartment_id: str, name: str, shape: str,
                        shape_config: Optional[Dict[str, Any]],
                        availability_domain: str, subnet_id: str,
                        image_id: str, ssh_public_key: str,
                        freeform_tags: Dict[str, str],
                        nsg_ids: List[str],
                        boot_volume_gb: int = 100,
                        preemptible: bool = False) -> Dict[str, Any]:
        body: Dict[str, Any] = {
            'compartmentId': compartment_id,
            'displayName': name,
            'shape': shape,
            'availabilityDomain': availability_domain,
            'createVnicDetails': {'subnetId': subnet_id,
                                  'assignPublicIp': True,
                                  'nsgIds': nsg_ids},
            'sourceDetails': {'sourceType': 'image',
                              'imageId': image_id,
                              'bootVolumeSizeInGBs': boot_volume_gb},
            'metadata': {'ssh_authorized_keys': ssh_public_key},
            'freeformTags': dict(freeform_tags),
        }
        if shape_config:
            body['shapeConfig'] = shape_config
        if preemptible:
            body['preemptibleInstanceConfig'] = {
                'preemptionAction': {'type': 'TERMINATE',
                                     'preserveBootVolume': False}}
        return dict(self._request('POST', '/instances/', body))

    def list_instances(self, compartment_id: str) -> List[Dict[str, Any]]:
        # ONE unfiltered listing, paginated via the opc-next-page
        # response header (OCI's pagination contract); terminal states
        # are filtered client-side. Per-state queries would be 5
        # requests per poll tick and each would still need pagination.
        out: List[Dict[str, Any]] = []
        page: Optional[str] = None
        while True:
            params = {'compartmentId': compartment_id, 'limit': 1000}
            if page:
                params['page'] = page
            q = urllib.parse.urlencode(params)
            body, headers = self._request('GET', f'/instances/?{q}',
                                          return_headers=True)
            out.extend(body or [])
            page = {k.lower(): v for k, v in headers.items()}.get(
                'opc-next-page')
            if not page:
                break
        return [i for i in out
                if i.get('lifecycleState') not in ('TERMINATED',)]

    def instance_action(self, instance_id: str, action: str) -> None:
        # action in ('START', 'STOP', 'SOFTSTOP')
        self._request('POST',
                      f'/instances/{instance_id}?action={action}', {})

    def terminate_instance(self, instance_id: str) -> None:
        self._request(
            'DELETE',
            f'/instances/{instance_id}?preserveBootVolume=false')

    def list_vnic_attachments(self, compartment_id: str,
                              instance_id: str) -> List[Dict[str, Any]]:
        q = urllib.parse.urlencode({'compartmentId': compartment_id,
                                    'instanceId': instance_id})
        return list(self._request('GET', f'/vnicAttachments/?{q}') or [])

    def get_vnic(self, vnic_id: str) -> Dict[str, Any]:
        return dict(self._request('GET', f'/vnics/{vnic_id}') or {})

    def create_nsg(self, compartment_id: str, vcn_id: str,
                   name: str) -> Dict[str, Any]:
        return dict(self._request('POST', '/networkSecurityGroups/', {
            'compartmentId': compartment_id, 'vcnId': vcn_id,
            'displayName': name}))

    def list_nsgs(self, compartment_id: str) -> List[Dict[str, Any]]:
        q = urllib.parse.urlencode({'compartmentId': compartment_id})
        return list(self._request(
            'GET', f'/networkSecurityGroups/?{q}') or [])

    def add_nsg_rules(self, nsg_id: str,
                      rules: List[Dict[str, Any]]) -> None:
        self._request(
            'POST',
            f'/networkSecurityGroups/{nsg_id}/actions/addSecurityRules',
            {'securityRules': rules})

    def list_nsg_rules(self, nsg_id: str) -> List[Dict[str, Any]]:
        return list(self._request(
            'GET',
            f'/networkSecurityGroups/{nsg_id}/securityRules') or [])

    def delete_nsg(self, nsg_id: str) -> None:
        self._request('DELETE', f'/networkSecurityGroups/{nsg_id}')

    def get_subnet(self, subnet_id: str) -> Dict[str, Any]:
        return dict(self._request('GET', f'/subnets/{subnet_id}') or {})

    def list_availability_domains(
            self, compartment_id: str) -> List[Dict[str, Any]]:
        """Identity API: the tenancy's REAL AD names for this region
        (tenancy-prefixed, e.g. 'qIZq:US-ASHBURN-1-AD-2'). The catalog's
        synthetic '{region}-AD-n' zones must be resolved through this
        listing before launch — the Compute API rejects names that are
        not exactly what identity returns."""
        q = urllib.parse.urlencode({'compartmentId': compartment_id})
        return list(self._request('GET', f'/availabilityDomains/?{q}',
                                  base=self._identity_base) or [])


# Test seam (``set_oci_factory(lambda: fake)``), client construction and
# error-normalizing ``call`` via the shared ClientSeam. get_client takes
# the REGION being provisioned (fakes ignore it; the real client must
# target that region's endpoint).
_seam = rest_cloud.ClientSeam(_RestClient, OciApiError, classify_error)
set_oci_factory = _seam.set_factory
call = _seam.call


def get_client(region: Optional[str] = None) -> Any:
    if _seam._factory is not None:  # pylint: disable=protected-access
        return _seam._factory()  # pylint: disable=protected-access
    return _RestClient(region)
