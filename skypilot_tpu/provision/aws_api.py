"""Thin EC2 client with a test seam.

Counterpart of the reference's boto3 usage in
``sky/provision/aws/instance.py`` (EC2 run/describe/terminate ops :1, SG +
VPC bootstrap in ``config.py``) and its error handling in
``sky/clouds/aws.py``. The real transport is boto3 (gated: this build may
not ship it); tests install an in-process fake EC2 via ``set_ec2_factory``
that implements the same snake_case boto3 client surface
(``run_instances``, ``describe_instances``, ...), so lifecycle + failover
logic runs for real with no cloud and no boto3.

Error classification mirrors the reference AWS handler: capacity errors
(InsufficientInstanceCapacity, SpotMaxPriceTooLow, ...) → zone failover;
limit/quota errors → region/cloud blocklist.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import exceptions

# EC2 error codes → failover classification (reference
# sky/provision/aws/instance.py stockout handling).
_CAPACITY_CODES = {
    'InsufficientInstanceCapacity',
    'InsufficientHostCapacity',
    'InsufficientCapacityOnOutpost',
    'SpotMaxPriceTooLow',
    'InsufficientFreeAddressesInSubnet',
    'Unsupported',  # AZ does not offer the instance type
}
_QUOTA_CODES = {
    'InstanceLimitExceeded',
    'VcpuLimitExceeded',
    'MaxSpotInstanceCountExceeded',
    'RequestLimitExceeded',
}


class AwsApiError(Exception):
    """Fake/real client error carrying an EC2 error code."""

    def __init__(self, code: str, message: str = ''):
        super().__init__(message or code)
        self.code = code
        self.message = message or code


def classify_error(exc: Exception) -> exceptions.CloudError:
    code = getattr(exc, 'code', None)
    if code is None:  # botocore ClientError shape
        resp = getattr(exc, 'response', None) or {}
        code = (resp.get('Error') or {}).get('Code', '')
    msg = str(exc)
    if code in _CAPACITY_CODES:
        return exceptions.InsufficientCapacityError(msg, reason='capacity')
    if code in _QUOTA_CODES:
        return exceptions.CloudError(msg, reason='quota')
    return exceptions.CloudError(msg)


_ec2_factory: Optional[Callable[[str], Any]] = None


def set_ec2_factory(factory: Optional[Callable[[str], Any]]) -> None:
    """Test seam: ``factory(region) -> fake EC2 client``."""
    global _ec2_factory
    _ec2_factory = factory


def get_ec2(region: str) -> Any:
    if _ec2_factory is not None:
        return _ec2_factory(region)
    try:
        import boto3  # type: ignore
    except ImportError as e:
        raise exceptions.CloudError(
            'boto3 is required for real AWS provisioning and is not '
            'installed (pip install boto3).') from e
    return boto3.client('ec2', region_name=region)


# Canonical's public SSM parameter for the latest Ubuntu 22.04 LTS AMI —
# per-region, maintained by Canonical (the reference resolves AMIs via its
# catalog's per-region image column, fetched the same way).
_UBUNTU_SSM_PARAM = ('/aws/service/canonical/ubuntu/server/22.04/stable/'
                     'current/amd64/hvm/ebs-gp3/ami-id')
_FAKE_AMI = 'ami-ubuntu-2204'  # accepted by the in-process fake EC2 only
_ami_cache: Dict[str, str] = {}  # region -> AMI id (real mode only)


def resolve_default_ami(region: str) -> str:
    """Default Ubuntu 22.04 AMI for ``region`` when no image_id is given.

    AMI IDs are per-region, so there is no single valid default literal.
    In fake mode (test seam installed) the placeholder is fine; against
    real EC2 we resolve Canonical's SSM public parameter, and fail fast
    with an actionable error rather than letting run_instances die with
    InvalidAMIID.Malformed (which would mis-classify as a generic cloud
    error and burn failover retries)."""
    if _ec2_factory is not None:
        return _FAKE_AMI
    cached = _ami_cache.get(region)
    if cached is not None:
        return cached
    try:
        import boto3  # type: ignore
        ssm = boto3.client('ssm', region_name=region)
        ami = ssm.get_parameter(Name=_UBUNTU_SSM_PARAM)['Parameter']['Value']
    except Exception as e:  # noqa: BLE001 — any failure → actionable error
        raise exceptions.CloudError(
            f'Could not resolve a default Ubuntu AMI for region {region} '
            f'via SSM ({e!r}). Set an explicit image_id in the task '
            'resources (e.g. image_id: ami-0123456789abcdef0).') from e
    _ami_cache[region] = ami
    return ami


_zones_cache: Dict[str, tuple] = {}  # region -> AZ names (real mode only)


def available_zones(region: str) -> List[str]:
    """Availability-zone names for ``region``, best-effort.

    Real mode asks EC2 (describe_availability_zones, cached per region) so
    3-AZ regions never get probed with a nonexistent '<region>d' — that
    fails with InvalidParameterValue, which is NOT a capacity error and
    would abort the whole region mid-failover. Fake clients that don't
    implement the op (and real-mode API failures) fall back to a-f: the
    fake raises per-AZ capacity errors, so extra suffixes only extend the
    walk."""
    if _ec2_factory is None and region in _zones_cache:
        return list(_zones_cache[region])
    fallback = [f'{region}{s}' for s in 'abcdef']
    try:
        ec2 = get_ec2(region)
        resp = ec2.describe_availability_zones(
            Filters=[{'Name': 'state', 'Values': ['available']}])
    except Exception:  # noqa: BLE001 — fall back to suffix probing
        return fallback
    zones = sorted(
        z['ZoneName'] for z in resp.get('AvailabilityZones', [])
        if z.get('ZoneType', 'availability-zone') == 'availability-zone')
    if not zones:
        return fallback
    if _ec2_factory is None:
        _zones_cache[region] = tuple(zones)
    return zones


def call(ec2: Any, op: str, **kwargs) -> Dict[str, Any]:
    """Invoke a client op, normalizing errors to CloudError subclasses."""
    try:
        return getattr(ec2, op)(**kwargs)
    except AwsApiError as e:
        raise classify_error(e) from e
    except Exception as e:  # botocore.exceptions.ClientError (duck-typed:
        # boto3 may be absent, so the except clause can't name it)
        if getattr(e, 'response', None) is not None:
            raise classify_error(e) from e
        raise


def instances_from_describe(resp: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [inst for r in resp.get('Reservations', [])
            for inst in r.get('Instances', [])]


def tag_value(inst: Dict[str, Any], key: str) -> Optional[str]:
    for tag in inst.get('Tags', []):
        if tag.get('Key') == key:
            return tag.get('Value')
    return None
