"""Thin EC2 client with a test seam.

Counterpart of the reference's boto3 usage in
``sky/provision/aws/instance.py`` (EC2 run/describe/terminate ops :1, SG +
VPC bootstrap in ``config.py``) and its error handling in
``sky/clouds/aws.py``. The real transport is boto3 (gated: this build may
not ship it); tests install an in-process fake EC2 via ``set_ec2_factory``
that implements the same snake_case boto3 client surface
(``run_instances``, ``describe_instances``, ...), so lifecycle + failover
logic runs for real with no cloud and no boto3.

Error classification mirrors the reference AWS handler: capacity errors
(InsufficientInstanceCapacity, SpotMaxPriceTooLow, ...) → zone failover;
limit/quota errors → region/cloud blocklist.
"""
from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import exceptions

# EC2 error codes → failover classification (reference
# sky/provision/aws/instance.py stockout handling).
_CAPACITY_CODES = {
    'InsufficientInstanceCapacity',
    'InsufficientHostCapacity',
    'InsufficientCapacityOnOutpost',
    'SpotMaxPriceTooLow',
    'InsufficientFreeAddressesInSubnet',
    'Unsupported',  # AZ does not offer the instance type
}
_QUOTA_CODES = {
    'InstanceLimitExceeded',
    'VcpuLimitExceeded',
    'MaxSpotInstanceCountExceeded',
    'RequestLimitExceeded',
}


class AwsApiError(Exception):
    """Fake/real client error carrying an EC2 error code."""

    def __init__(self, code: str, message: str = ''):
        super().__init__(message or code)
        self.code = code
        self.message = message or code


def classify_error(exc: Exception) -> exceptions.CloudError:
    code = getattr(exc, 'code', None)
    if code is None:  # botocore ClientError shape
        resp = getattr(exc, 'response', None) or {}
        code = (resp.get('Error') or {}).get('Code', '')
    msg = str(exc)
    if code in _CAPACITY_CODES:
        return exceptions.InsufficientCapacityError(msg, reason='capacity')
    if code in _QUOTA_CODES:
        return exceptions.CloudError(msg, reason='quota')
    return exceptions.CloudError(msg)


_ec2_factory: Optional[Callable[[str], Any]] = None


def set_ec2_factory(factory: Optional[Callable[[str], Any]]) -> None:
    """Test seam: ``factory(region) -> fake EC2 client``."""
    global _ec2_factory
    _ec2_factory = factory


def get_ec2(region: str) -> Any:
    if _ec2_factory is not None:
        return _ec2_factory(region)
    try:
        import boto3  # type: ignore
    except ImportError as e:
        raise exceptions.CloudError(
            'boto3 is required for real AWS provisioning and is not '
            'installed (pip install boto3).') from e
    return boto3.client('ec2', region_name=region)


def call(ec2: Any, op: str, **kwargs) -> Dict[str, Any]:
    """Invoke a client op, normalizing errors to CloudError subclasses."""
    try:
        return getattr(ec2, op)(**kwargs)
    except AwsApiError as e:
        raise classify_error(e) from e
    except Exception as e:  # botocore.exceptions.ClientError (duck-typed:
        # boto3 may be absent, so the except clause can't name it)
        if getattr(e, 'response', None) is not None:
            raise classify_error(e) from e
        raise


def instances_from_describe(resp: Dict[str, Any]) -> List[Dict[str, Any]]:
    return [inst for r in resp.get('Reservations', [])
            for inst in r.get('Instances', [])]


def tag_value(inst: Dict[str, Any], key: str) -> Optional[str]:
    for tag in inst.get('Tags', []):
        if tag.get('Key') == key:
            return tag.get('Value')
    return None
