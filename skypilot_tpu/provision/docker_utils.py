"""Docker image runtime for VM hosts: ``image_id: docker:<image>`` tasks.

Counterpart of reference ``sky/provision/docker_utils.py:1-447``
(DockerInitializer: install docker, login, pull, run). Architectural
difference: the reference keeps ONE long-lived container per host and
``docker exec``s every command into it; here the host runs the runtime
(agent/job queue) and each JOB RANK runs as its own ``docker run``
container. That keeps the existing process-group lifecycle intact —
``docker run`` stays attached with --sig-proxy, so the agent's
setsid/kill -TERM cancellation and exit-code propagation work unchanged,
and a finished job leaves no container behind (--rm).

The home directory mounts into the container at the same path, so the
shipped workdir, runtime dir (logs, compile cache), and checkpoints are
shared between host and container.
"""
from __future__ import annotations

import shlex
from typing import Dict

DOCKER_PREFIX = 'docker:'


def is_docker_image(image_id) -> bool:
    return bool(image_id) and str(image_id).startswith(DOCKER_PREFIX)


def image_name(image_id: str) -> str:
    assert is_docker_image(image_id), image_id
    return image_id[len(DOCKER_PREFIX):]


def bootstrap_command(image_id: str) -> str:
    """Idempotent per-host bring-up: install docker (Ubuntu), enable the
    daemon, grant the login user access, pre-pull the image so the first
    job doesn't pay the pull (reference DockerInitializer.initialize)."""
    img = shlex.quote(image_name(image_id))
    # $SUDO resolves empty when running as root / sudo-less images.
    return (
        'SUDO=$(command -v sudo || true); '
        'command -v docker >/dev/null || { '
        '$SUDO apt-get update -qq && '
        '$SUDO apt-get install -y -qq docker.io && '
        '$SUDO systemctl enable --now docker; }; '
        '$SUDO usermod -aG docker "$(id -un)" 2>/dev/null || true; '
        f'$SUDO docker pull -q {img}')


def run_in_container_command(image_id: str, container_name: str,
                             script: str, env: Dict[str, str],
                             workdir: str) -> str:
    """One rank's job as an attached ``docker run``.

    - ``--network host``: the SKYTPU_* rank contract (coordinator ports,
      MEGASCALE) must resolve exactly as on the host.
    - ``$HOME`` bind-mount at the same path + ``-w`` into the shipped
      workdir: container sees the same filesystem contract as a host job.
    - attached + default sig-proxy: the agent's kill -TERM on the process
      group reaches the container's PID 1; --rm reaps it.
    - TPU-VM hosts pass the accelerator through with --privileged (the
      reference's docker runs do the same for GPUs via nvidia runtime).
    """
    img = shlex.quote(image_name(image_id))
    env_flags = ' '.join(
        f'-e {shlex.quote(f"{k}={v}")}' for k, v in env.items())
    # Plain `docker` (not sudo): bootstrap added the login user to the
    # docker group, and each runner command is a fresh shell session.
    # --user: container writes into the bind-mounted $HOME as the login
    # user, not root — root-owned droppings would break the next
    # launch's rsync --delete workdir sync.
    return (
        f'docker rm -f {shlex.quote(container_name)} '
        '>/dev/null 2>&1 || true; '
        f'exec docker run --rm --name {shlex.quote(container_name)} '
        '--network host --privileged --user "$(id -u):$(id -g)" '
        '-v "$HOME:$HOME" -e HOME="$HOME" '
        f'-w "$HOME/{workdir}" {env_flags} {img} '
        f'bash -c {shlex.quote(script)}')
