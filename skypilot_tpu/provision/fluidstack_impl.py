"""FluidStack provisioner: GPU instance host groups (terminate-only).

Counterpart of reference ``sky/provision/fluidstack/instance.py`` —
same reduced lifecycle class as Lambda (no stop, no spot, no zones) but
with FluidStack-isms:

- instance types are ``{gpu_type}::{gpu_count}`` plans (reference
  fluidstack_utils.py:90-99); availability is checked against the
  plans list BEFORE launching, so a sold-out plan classifies as
  capacity without burning a launch call;
- there is NO ports API: the cloud class simply omits the OPEN_PORTS
  feature and serve/port tasks are refused up front;
- rank discovery is stateless via instance names ``{name}-r{rank}``
  (same as Lambda; FluidStack has no tags either).

Cluster bookkeeping lives in the client state kv, mirroring the other
REST clouds.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import authentication
from skypilot_tpu import exceptions
from skypilot_tpu import provision as provision_lib
from skypilot_tpu.provision import fluidstack_api
from skypilot_tpu.provision import rest_cloud
from skypilot_tpu.utils import command_runner as runner_lib

SSH_USER = 'ubuntu'

# FluidStack statuses -> provision API state words (reference
# instance.py:84 pending set + :100 running filter).
_STATE_MAP = {
    'pending': 'pending',
    'provisioning': 'pending',
    'running': 'running',
    'unhealthy': 'pending',
    'terminating': 'terminating',
    'terminated': 'terminated',
}


def split_plan(instance_type: str) -> tuple:
    """'A100_80G::8' -> ('A100_80G', 8)."""
    gpu_type, _, count = instance_type.partition('::')
    return gpu_type, int(count or 1)


# Cluster bookkeeping + rank decoding via the shared REST-cloud
# scaffolding (rest_cloud.py).
_records = rest_cloud.ClusterRecords('fluidstack_cluster')


def _live_instances(client, name: str,
                    region: Optional[str] = None
                    ) -> Dict[int, Dict[str, Any]]:
    """rank -> instance. Region-filtered: the API is account-global, so
    a leaked instance from a failed-over region must not be adopted
    (same hazard as Lambda)."""
    out: Dict[int, Dict[str, Any]] = {}
    for inst in fluidstack_api.call(client, 'list_instances'):
        rank = rest_cloud.rank_of(inst.get('name') or '', name)
        if rank is None:
            continue
        if inst.get('status') in ('terminated', 'terminating'):
            continue
        if region is not None and (inst.get('region') or region) != region:
            continue
        out[rank] = inst
    return out


def _ensure_ssh_key(client) -> str:
    _, pub_path = authentication.get_or_generate_keys()
    with open(pub_path, encoding='utf-8') as f:
        pub_key = f.read().strip()
    keys = fluidstack_api.call(client, 'list_ssh_keys')
    for key in keys:
        if (key.get('public_key') or '').strip() == pub_key:
            return key['name']
    taken = {key.get('name') for key in keys}
    key_name = 'skytpu'
    idx = 0
    while key_name in taken:
        idx += 1
        key_name = f'skytpu-{idx}'
    fluidstack_api.call(client, 'register_ssh_key', name=key_name,
                        public_key=pub_key)
    return key_name


def _check_stock(client, instance_type: str, region: str) -> None:
    """Sold-out plans classify as capacity BEFORE a launch call
    (reference fluidstack_utils.py:90-99)."""
    gpu_type, gpu_count = split_plan(instance_type)
    for plan in fluidstack_api.call(client, 'list_plans'):
        if (plan.get('gpu_type') == gpu_type
                and gpu_count in (plan.get('gpu_counts') or [])
                and region in (plan.get('regions') or [])):
            return
    raise exceptions.InsufficientCapacityError(
        f'Plan {instance_type} out of stock in region {region}',
        reason='capacity')


# ---- provision API ---------------------------------------------------------
def run_instances(cluster_name: str, region: str, zone: Optional[str],
                  num_hosts: int, deploy_vars: Dict[str, Any]) -> None:
    del zone  # FluidStack has no zones
    name = deploy_vars['cluster_name_on_cloud']
    record = {'region': region, 'zone': None, 'name_on_cloud': name,
              'num_hosts': num_hosts, 'deploy_vars': deploy_vars}
    _records.save(cluster_name, record)
    client = fluidstack_api.get_client()
    instance_type = deploy_vars.get('instance_type', 'A100_80G::1')
    try:
        _check_stock(client, instance_type, region)
        key_name = _ensure_ssh_key(client)
        gpu_type, gpu_count = split_plan(instance_type)
        existing = _live_instances(client, name, region)
        for rank in range(num_hosts):
            if rank in existing:
                continue  # idempotent relaunch
            fluidstack_api.call(
                client, 'create_instance',
                gpu_type=gpu_type, gpu_count=gpu_count, region=region,
                name=f'{name}-r{rank}', ssh_key_name=key_name)
    except exceptions.InsufficientCapacityError:
        try:
            _terminate_all(client, name)
        except exceptions.CloudError:
            pass
        else:
            _records.delete(cluster_name)
        raise


def wait_instances(cluster_name: str, region: str, state: str = 'running',
                   timeout: float = 1800) -> None:
    if state != 'running':
        raise exceptions.NotSupportedError(
            'FluidStack cannot stop instances (terminate-only).')
    rest_cloud.poll_for_state(
        cluster_name, lambda: query_instances(cluster_name, region),
        state, timeout)


def query_instances(cluster_name: str, region: str) -> Dict[str, str]:
    del region
    record = _records.load(cluster_name)
    if not record:
        return {}
    client = fluidstack_api.get_client()
    live = _live_instances(client, record['name_on_cloud'],
                           record.get('region'))
    if not live:
        return {}
    out: Dict[str, str] = {}
    for rank, inst in live.items():
        out[inst.get('name', f'r{rank}')] = _STATE_MAP.get(
            inst.get('status', ''), 'unknown')
    for rank in range(int(record.get('num_hosts') or 0)):
        if rank not in live:
            out[f'rank{rank}-missing'] = 'terminated'
    return out


def stop_instances(cluster_name: str, region: str) -> None:
    raise exceptions.NotSupportedError(
        'FluidStack cannot stop instances (terminate-only); '
        'use `skytpu down` instead.')


def _terminate_all(client, name: str) -> None:
    for inst in _live_instances(client, name).values():
        if inst.get('id'):
            fluidstack_api.call(client, 'delete_instance',
                                instance_id=inst['id'])


def terminate_instances(cluster_name: str, region: str) -> None:
    del region
    record = _records.load(cluster_name)
    if not record:
        return
    client = fluidstack_api.get_client()
    _terminate_all(client, record['name_on_cloud'])
    _records.delete(cluster_name)


def get_cluster_info(cluster_name: str,
                     region: str) -> provision_lib.ClusterInfo:
    del region
    record = _records.require(cluster_name, 'FluidStack')
    client = fluidstack_api.get_client()
    live = _live_instances(client, record['name_on_cloud'],
                           record.get('region'))
    hosts: List[provision_lib.HostInfo] = []
    single = int(record.get('num_hosts') or 0) == 1
    for rank in sorted(live):
        inst = live[rank]
        internal = inst.get('private_ip')
        if internal is None:
            if not single:
                raise exceptions.ProvisionError(
                    f'No private IP for {inst.get("name")!r} — multi-host '
                    'rendezvous needs one.')
            internal = '127.0.0.1'
        hosts.append(provision_lib.HostInfo(
            host_id=str(inst.get('id', f'r{rank}')), rank=rank,
            internal_ip=internal,
            external_ip=inst.get('ip_address'),
            extra={}))
    return provision_lib.ClusterInfo(
        cluster_name=cluster_name, cloud='fluidstack',
        region=record['region'], zone=None, hosts=hosts,
        deploy_vars=record['deploy_vars'])


# No open_ports: FluidStack has no firewall API; the cloud class omits
# the OPEN_PORTS feature so port-requiring tasks are refused up front.


def get_command_runners(cluster_info: provision_lib.ClusterInfo,
                        ssh_credentials: Optional[Dict[str, str]] = None
                        ) -> List[runner_lib.CommandRunner]:
    return rest_cloud.ssh_runners(cluster_info, SSH_USER, ssh_credentials)
