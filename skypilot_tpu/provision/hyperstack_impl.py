"""Hyperstack provisioner: GPU VMs in per-region environments.

Counterpart of reference ``sky/provision/hyperstack/instance.py`` +
``hyperstack_utils.py``. Tenth VM cloud. Hyperstack-isms:

- VMs live inside an ENVIRONMENT (a per-region project container);
  the provisioner creates/reuses ``skytpu-{region}`` per region and
  keypairs are registered per environment (reference
  hyperstack_utils.py:139-170);
- ports are PER-INSTANCE security rules: SSH is opened in the create
  payload, task/serve ports are added to each VM post-creation
  (reference _security_rule/open_ports) — a fourth ports flavor after
  per-cluster SGs (AWS/DO), account-global rules (Lambda), and
  fixed-at-rent sets (RunPod);
- stop/start are supported ('SHUTOFF' doesn't bill compute);
- no spot market, no zones.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional

from skypilot_tpu import authentication
from skypilot_tpu import exceptions
from skypilot_tpu import provision as provision_lib
from skypilot_tpu.provision import hyperstack_api
from skypilot_tpu.provision import rest_cloud
from skypilot_tpu.utils import command_runner as runner_lib

SSH_USER = 'ubuntu'

DEFAULT_IMAGE = 'Ubuntu Server 22.04 LTS R535 CUDA 12.2'

# Hyperstack VM statuses -> provision API state words.
_STATE_MAP = {
    'CREATING': 'pending',
    'BUILD': 'pending',
    'STARTING': 'pending',
    'ACTIVE': 'running',
    'STOPPING': 'stopping',
    'SHUTOFF': 'stopped',
    'DELETING': 'terminating',
    'ERROR': 'terminated',  # failed build: treat as a hole -> failover
}

# Cluster bookkeeping + rank decoding via the shared REST-cloud
# scaffolding (rest_cloud.py).
_records = rest_cloud.ClusterRecords('hyperstack_cluster')


def _environment_name(region: str) -> str:
    return f'skytpu-{region}'


def _ensure_environment(client, region: str) -> str:
    name = _environment_name(region)
    for env in hyperstack_api.call(client, 'list_environments'):
        if env.get('name') == name:
            return name
    hyperstack_api.call(client, 'create_environment', name=name,
                        region=region)
    return name


def _ensure_ssh_key(client, environment: str) -> str:
    """Keypairs are scoped to an environment (reference
    hyperstack_utils.py:139-170); one 'skytpu' key per environment."""
    _, pub_path = authentication.get_or_generate_keys()
    with open(pub_path, encoding='utf-8') as f:
        pub_key = f.read().strip()
    key_name = f'skytpu-{environment}'
    for key in hyperstack_api.call(client, 'list_ssh_keys'):
        if (key.get('name') == key_name
                and (key.get('environment') or {}).get(
                    'name', key.get('environment_name')) == environment):
            return key_name
    hyperstack_api.call(client, 'register_ssh_key', name=key_name,
                        environment=environment, public_key=pub_key)
    return key_name


def _ssh_rule(port: int) -> Dict[str, Any]:
    return {'direction': 'ingress', 'protocol': 'tcp',
            'ethertype': 'IPv4', 'remote_ip_prefix': '0.0.0.0/0',
            'port_range_min': port, 'port_range_max': port}


def _live_vms(client, name: str,
              region: Optional[str] = None) -> Dict[int, Dict[str, Any]]:
    """rank -> VM, scoped to our environment when region is known (the
    VM list is account-global: same adoption hazard as Lambda)."""
    env = _environment_name(region) if region else None
    out: Dict[int, Dict[str, Any]] = {}
    for vm in hyperstack_api.call(client, 'list_vms'):
        rank = rest_cloud.rank_of(vm.get('name') or '', name)
        if rank is None:
            continue
        if vm.get('status') in ('DELETING', 'DELETED'):
            continue
        vm_env = (vm.get('environment') or {}).get(
            'name', vm.get('environment_name'))
        if env is not None and (vm_env or env) != env:
            continue
        out[rank] = vm
    return out


# ---- provision API ---------------------------------------------------------
def run_instances(cluster_name: str, region: str, zone: Optional[str],
                  num_hosts: int, deploy_vars: Dict[str, Any]) -> None:
    del zone  # no zones
    name = deploy_vars['cluster_name_on_cloud']
    record = {'region': region, 'zone': None, 'name_on_cloud': name,
              'num_hosts': num_hosts, 'deploy_vars': deploy_vars}
    _records.save(cluster_name, record)
    client = hyperstack_api.get_client()
    try:
        environment = _ensure_environment(client, region)
        key_name = _ensure_ssh_key(client, environment)
        existing = _live_vms(client, name, region)
        for rank, vm in existing.items():
            if vm.get('status') == 'SHUTOFF':
                hyperstack_api.call(client, 'start_vm', vm_id=vm['id'])
        for rank in range(num_hosts):
            if rank in existing:
                continue  # idempotent relaunch
            hyperstack_api.call(
                client, 'create_vm',
                name=f'{name}-r{rank}',
                environment=environment,
                flavor=deploy_vars.get('instance_type',
                                       'n3-RTX-A6000x1'),
                key_name=key_name,
                image=deploy_vars.get('image_id') or DEFAULT_IMAGE,
                security_rules=[_ssh_rule(22)])
    except exceptions.InsufficientCapacityError:
        try:
            _terminate_all(client, name)
        except exceptions.CloudError:
            pass
        else:
            _records.delete(cluster_name)
        raise


def wait_instances(cluster_name: str, region: str, state: str = 'running',
                   timeout: float = 1800) -> None:
    rest_cloud.poll_for_state(
        cluster_name, lambda: query_instances(cluster_name, region),
        state, timeout)


def query_instances(cluster_name: str, region: str) -> Dict[str, str]:
    del region
    record = _records.load(cluster_name)
    if not record:
        return {}
    client = hyperstack_api.get_client()
    live = _live_vms(client, record['name_on_cloud'],
                     record.get('region'))
    if not live:
        return {}
    out: Dict[str, str] = {}
    for rank, vm in live.items():
        out[vm.get('name', f'r{rank}')] = _STATE_MAP.get(
            vm.get('status', ''), 'unknown')
    for rank in range(int(record.get('num_hosts') or 0)):
        if rank not in live:
            out[f'rank{rank}-missing'] = 'terminated'
    return out


def stop_instances(cluster_name: str, region: str) -> None:
    record = _records.require(cluster_name, 'Hyperstack')
    client = hyperstack_api.get_client()
    for vm in _live_vms(client, record['name_on_cloud']).values():
        if vm.get('status') in ('CREATING', 'BUILD', 'STARTING',
                                'ACTIVE'):
            hyperstack_api.call(client, 'stop_vm', vm_id=vm['id'])


def _terminate_all(client, name: str) -> None:
    for vm in _live_vms(client, name).values():
        hyperstack_api.call(client, 'delete_vm', vm_id=vm['id'])


def terminate_instances(cluster_name: str, region: str) -> None:
    del region
    record = _records.load(cluster_name)
    if not record:
        return
    client = hyperstack_api.get_client()
    _terminate_all(client, record['name_on_cloud'])
    # The per-region environment is shared by other skytpu clusters:
    # left in place deliberately.
    _records.delete(cluster_name)


def get_cluster_info(cluster_name: str,
                     region: str) -> provision_lib.ClusterInfo:
    del region
    record = _records.require(cluster_name, 'Hyperstack')
    client = hyperstack_api.get_client()
    live = _live_vms(client, record['name_on_cloud'],
                     record.get('region'))
    hosts: List[provision_lib.HostInfo] = []
    for rank in sorted(live):
        vm = live[rank]
        public = vm.get('floating_ip')
        private = vm.get('fixed_ip') or public
        if private is None:
            raise exceptions.ProvisionError(
                f'No IP on VM {vm.get("name")!r} yet.')
        hosts.append(provision_lib.HostInfo(
            host_id=str(vm['id']), rank=rank,
            internal_ip=private, external_ip=public,
            extra={}))
    return provision_lib.ClusterInfo(
        cluster_name=cluster_name, cloud='hyperstack',
        region=record['region'], zone=None, hosts=hosts,
        deploy_vars=record['deploy_vars'])


def open_ports(cluster_name: str, region: str, ports: List[str]) -> None:
    """Per-INSTANCE security rules added post-creation (reference
    hyperstack_utils.py open_ports): one tcp rule per port per VM.
    Idempotent via the VM's existing rule list."""
    if not ports:
        return
    record = _records.require(cluster_name, 'Hyperstack')
    client = hyperstack_api.get_client()
    for vm in _live_vms(client, record['name_on_cloud'],
                        record.get('region')).values():
        have = {(r.get('port_range_min'), r.get('port_range_max'))
                for r in vm.get('security_rules') or []}
        for port in ports:
            if '-' in str(port):
                lo, hi = (int(p) for p in str(port).split('-', 1))
            else:
                lo = hi = int(port)
            if (lo, hi) in have:
                continue
            hyperstack_api.call(
                client, 'add_security_rule', vm_id=vm['id'],
                rule={'direction': 'ingress', 'protocol': 'tcp',
                      'ethertype': 'IPv4',
                      'remote_ip_prefix': '0.0.0.0/0',
                      'port_range_min': lo, 'port_range_max': hi})


def get_command_runners(cluster_info: provision_lib.ClusterInfo,
                        ssh_credentials: Optional[Dict[str, str]] = None
                        ) -> List[runner_lib.CommandRunner]:
    return rest_cloud.ssh_runners(cluster_info, SSH_USER, ssh_credentials)
