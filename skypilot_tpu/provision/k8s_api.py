"""Thin Kubernetes REST client (pods/services/events) with a test seam.

Counterpart of the reference's kubernetes adaptor + utils
(sky/adaptors/kubernetes.py, sky/provision/kubernetes/utils.py) — but a
direct REST transport instead of the official client, mirroring
``provision/gcp_api.py``: one ``set_transport`` seam lets tests fake the
whole API server (pod state machines, FailedScheduling stockouts) with no
cluster.

Auth resolution order (real transport):
1. In-cluster service account (/var/run/secrets/kubernetes.io/...).
2. ``$KUBECONFIG`` / ``~/.kube/config``: current-context's server +
   bearer token or client cert (the two most common GKE shapes).
"""
from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional

from skypilot_tpu import exceptions

_SA_DIR = '/var/run/secrets/kubernetes.io/serviceaccount'

# FailedScheduling markers that mean "no capacity for this shape now" →
# zone-level failover (analog of gcp_api's capacity classification).
_CAPACITY_MARKERS = (
    'insufficient google.com/tpu',
    'insufficient nvidia.com/gpu',
    'insufficient cpu',
    'insufficient memory',
    'no nodes available',
    "didn't match pod's node affinity",
)


def classify_scheduling_error(message: str) -> Optional[Exception]:
    low = (message or '').lower()
    for marker in _CAPACITY_MARKERS:
        if marker in low:
            return exceptions.InsufficientCapacityError(
                f'kubernetes: {message}')
    return None


class KubeConfigError(exceptions.CloudError):
    pass


class HttpTransport:
    """requests-based transport with kubeconfig/in-cluster auth."""

    MAX_ATTEMPTS = 4
    _RETRY_STATUSES = (429, 500, 502, 503, 504)

    def __init__(self):
        self._server: Optional[str] = None
        self._headers: Dict[str, str] = {}
        self._verify: Any = True
        self._cert: Any = None
        self._session = None

    # -- auth ---------------------------------------------------------------
    def _load_in_cluster(self) -> bool:
        token_path = os.path.join(_SA_DIR, 'token')
        if not os.path.exists(token_path):
            return False
        host = os.environ.get('KUBERNETES_SERVICE_HOST')
        port = os.environ.get('KUBERNETES_SERVICE_PORT', '443')
        if not host:
            return False
        with open(token_path) as f:
            self._headers = {'Authorization': f'Bearer {f.read().strip()}'}
        ca = os.path.join(_SA_DIR, 'ca.crt')
        self._verify = ca if os.path.exists(ca) else True
        self._server = f'https://{host}:{port}'
        return True

    def _load_kubeconfig(self) -> bool:
        import base64
        import tempfile

        import yaml
        path = os.environ.get('KUBECONFIG',
                              os.path.expanduser('~/.kube/config'))
        if not os.path.exists(path):
            return False
        with open(path) as f:
            cfg = yaml.safe_load(f) or {}
        ctx_name = cfg.get('current-context')
        contexts = {c['name']: c['context']
                    for c in cfg.get('contexts', [])}
        clusters = {c['name']: c['cluster']
                    for c in cfg.get('clusters', [])}
        users = {u['name']: u.get('user', {}) for u in cfg.get('users', [])}
        if ctx_name not in contexts:
            raise KubeConfigError(
                f'kubeconfig {path}: current-context {ctx_name!r} missing')
        ctx = contexts[ctx_name]
        cluster = clusters.get(ctx.get('cluster'))
        user = users.get(ctx.get('user'), {})
        if cluster is None:
            raise KubeConfigError(f'kubeconfig {path}: cluster not found')
        self._server = cluster['server'].rstrip('/')

        def _materialize(data_key: str, file_key: str,
                         src: Dict[str, Any]) -> Optional[str]:
            if src.get(file_key):
                return src[file_key]
            if src.get(data_key):
                tmp = tempfile.NamedTemporaryFile(delete=False)
                tmp.write(base64.b64decode(src[data_key]))
                tmp.close()
                return tmp.name
            return None

        ca = _materialize('certificate-authority-data',
                          'certificate-authority', cluster)
        self._verify = ca if ca else not cluster.get(
            'insecure-skip-tls-verify', False)
        token = user.get('token')
        if token:
            self._headers = {'Authorization': f'Bearer {token}'}
        cert = _materialize('client-certificate-data', 'client-certificate',
                            user)
        key = _materialize('client-key-data', 'client-key', user)
        if cert and key:
            self._cert = (cert, key)
        return True

    def _ensure(self):
        import requests
        if self._session is None:
            self._session = requests.Session()
            if not (self._load_in_cluster() or self._load_kubeconfig()):
                raise KubeConfigError(
                    'No Kubernetes credentials: not in-cluster and no '
                    'kubeconfig found')

    # -- request ------------------------------------------------------------
    def request(self, method: str, path: str,
                json_body: Optional[Dict[str, Any]] = None,
                params: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        import requests
        self._ensure()
        url = f'{self._server}{path}'
        last: Optional[Exception] = None
        for attempt in range(self.MAX_ATTEMPTS):
            if attempt:
                time.sleep(min(1.0 * 2**(attempt - 1), 15))
            try:
                resp = self._session.request(
                    method, url, json=json_body, params=params,
                    headers=self._headers, verify=self._verify,
                    cert=self._cert, timeout=60)
            except (requests.ConnectionError, requests.Timeout) as e:
                last = e
                continue
            if resp.status_code < 400:
                return resp.json() if resp.content else {}
            if resp.status_code in self._RETRY_STATUSES:
                last = exceptions.CloudError(
                    f'kubernetes {resp.status_code}: {resp.text[:200]}')
                continue
            if resp.status_code == 404:
                raise KeyError(path)
            raise exceptions.CloudError(
                f'kubernetes {method} {path}: {resp.status_code} '
                f'{resp.text[:300]}')
        raise (last if isinstance(last, exceptions.CloudError)
               else exceptions.CloudError(f'kubernetes transport: {last!r}'))


_transport: Any = None


def get_transport() -> Any:
    global _transport
    if _transport is None:
        _transport = HttpTransport()
    return _transport


def set_transport(transport: Any) -> None:
    """Test seam: install a fake API server."""
    global _transport
    _transport = transport


class PodClient:
    """Namespaced pod/service/event operations."""

    def __init__(self, namespace: str = 'default',
                 transport: Optional[Any] = None):
        self.namespace = namespace
        self._t = transport or get_transport()

    def _ns(self, kind: str, name: str = '') -> str:
        suffix = f'/{name}' if name else ''
        return f'/api/v1/namespaces/{self.namespace}/{kind}{suffix}'

    def create_pod(self, body: Dict[str, Any]) -> Dict[str, Any]:
        return self._t.request('POST', self._ns('pods'), json_body=body)

    def get_pod(self, name: str) -> Optional[Dict[str, Any]]:
        try:
            return self._t.request('GET', self._ns('pods', name))
        except KeyError:
            return None

    def list_pods(self, label_selector: str) -> List[Dict[str, Any]]:
        try:
            resp = self._t.request('GET', self._ns('pods'),
                                   params={'labelSelector': label_selector})
        except KeyError:  # namespace gone: nothing listed, not a crash
            return []
        return resp.get('items', [])

    def delete_pod(self, name: str) -> None:
        try:
            self._t.request('DELETE', self._ns('pods', name),
                            params={'gracePeriodSeconds': '5'})
        except KeyError:
            pass

    def pod_events(self, name: str) -> List[Dict[str, Any]]:
        try:
            resp = self._t.request(
                'GET', self._ns('events'),
                params={'fieldSelector': f'involvedObject.name={name}'})
        except KeyError:
            return []
        return resp.get('items', [])

    def create_service(self, body: Dict[str, Any]) -> Dict[str, Any]:
        return self._t.request('POST', self._ns('services'),
                               json_body=body)

    def delete_service(self, name: str) -> None:
        try:
            self._t.request('DELETE', self._ns('services', name))
        except KeyError:
            pass

    def version(self) -> Dict[str, Any]:
        return self._t.request('GET', '/version')
