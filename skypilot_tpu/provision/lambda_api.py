"""Thin Lambda Cloud REST client with a test seam.

Counterpart of the reference's ``sky/provision/lambda_cloud/lambda_utils.py``
(LambdaCloudClient: launch/terminate/list over
``https://cloud.lambdalabs.com/api/v1``, bearer-token auth from
``~/.lambda_cloud/lambda_keys``). The real transport is a tiny
urllib-based client (Lambda's API is plain JSON REST — no SDK needed);
tests install an in-process fake via ``set_lambda_factory`` implementing
the same flat surface (``launch``, ``list_instances``, ``terminate``,
``list_ssh_keys``, ``register_ssh_key``, ``list_firewall_rules``,
``put_firewall_rules``), so lifecycle + failover logic runs for real
with no cloud.

Error classification mirrors the reference's error-code strings
(lambda_utils.py raise_lambda_error): the API returns
``error.code`` values like ``instance-operations/launch/
insufficient-capacity`` -> capacity failover;
``global/quota-exceeded`` -> quota; 429 rate-limit -> retried by the
transport, surfaced as a plain CloudError if persistent.
"""
from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, List, Optional

from skypilot_tpu import exceptions
from skypilot_tpu.provision import rest_cloud

API_ENDPOINT = 'https://cloud.lambdalabs.com/api/v1'
CREDENTIALS_PATH = '~/.lambda_cloud/lambda_keys'

_CAPACITY_MARKERS = (
    'insufficient-capacity',
    'not-enough-capacity',
)
_QUOTA_MARKERS = (
    'quota-exceeded',
    'instance-quota',
)


class LambdaApiError(Exception):
    """Fake/real client error carrying a Lambda API error code string."""

    def __init__(self, code: str, message: str = ''):
        super().__init__(message or code)
        self.code = code
        self.message = message or code


classify_error = rest_cloud.marker_classifier(_CAPACITY_MARKERS,
                                              _QUOTA_MARKERS)


# ---- real transport --------------------------------------------------------
def read_api_key() -> Optional[str]:
    """API key from $LAMBDA_API_KEY or ~/.lambda_cloud/lambda_keys
    (``api_key = <key>`` lines, the reference's credential format)."""
    env = os.environ.get('LAMBDA_API_KEY')
    if env:
        return env
    path = os.path.expanduser(CREDENTIALS_PATH)
    if not os.path.exists(path):
        return None
    with open(path, encoding='utf-8') as f:
        for line in f:
            if ' = ' in line:
                key, _, value = line.strip().partition(' = ')
                if key == 'api_key':
                    return value
    return None


def _parse_error(status: int, raw: bytes) -> Exception:
    """Lambda's error envelope: {'error': {'code', 'message'}}."""
    try:
        body = json.loads(raw.decode())
        err = body.get('error', {})
        return LambdaApiError(err.get('code', str(status)),
                              err.get('message', raw.decode()))
    except (ValueError, AttributeError):
        return LambdaApiError(str(status),
                              raw.decode(errors='replace') or str(status))


class _RestClient:
    """Flat op surface over the shared retrying urllib transport."""

    def __init__(self):
        api_key = read_api_key()
        if api_key is None:
            raise exceptions.CloudError(
                'Lambda Cloud credentials not found: set $LAMBDA_API_KEY or '
                f'write api_key to {CREDENTIALS_PATH}.')
        self._headers = {'Authorization': f'Bearer {api_key}',
                         'Content-Type': 'application/json'}

    def _request(self, method: str, path: str,
                 payload: Optional[Dict[str, Any]] = None) -> Dict[str, Any]:
        return rest_cloud.retrying_request(
            method, f'{API_ENDPOINT}{path}', self._headers, payload,
            _parse_error)

    # -- flat op surface (mirrored by test fakes) ---------------------------
    def launch(self, region: str, instance_type: str, name: str,
               ssh_key_names: List[str], quantity: int = 1) -> List[str]:
        body = self._request('POST', '/instance-operations/launch', {
            'region_name': region,
            'instance_type_name': instance_type,
            'ssh_key_names': ssh_key_names,
            'name': name,
            'quantity': quantity,
        })
        return list(body.get('data', {}).get('instance_ids', []))

    def list_instances(self) -> List[Dict[str, Any]]:
        return list(self._request('GET', '/instances').get('data', []))

    def terminate(self, instance_ids: List[str]) -> None:
        self._request('POST', '/instance-operations/terminate',
                      {'instance_ids': instance_ids})

    def list_ssh_keys(self) -> List[Dict[str, str]]:
        return list(self._request('GET', '/ssh-keys').get('data', []))

    def register_ssh_key(self, name: str, public_key: str) -> None:
        self._request('POST', '/ssh-keys',
                      {'name': name, 'public_key': public_key})

    def list_firewall_rules(self) -> List[Dict[str, Any]]:
        return list(self._request('GET', '/firewall-rules').get('data', []))

    def put_firewall_rules(self, rules: List[Dict[str, Any]]) -> None:
        # PUT replaces the account's full rule set (API semantics).
        self._request('PUT', '/firewall-rules', {'data': rules})

    def instance_types(self) -> Dict[str, Any]:
        return dict(self._request('GET', '/instance-types').get('data', {}))


# Test seam (``set_lambda_factory(lambda: fake)``), client construction
# and error-normalizing ``call`` via the shared ClientSeam.
_seam = rest_cloud.ClientSeam(_RestClient, LambdaApiError, classify_error)
set_lambda_factory = _seam.set_factory
get_client = _seam.get_client
call = _seam.call
