"""Mixtral-family sparse-MoE decoder, TPU-first.

Reuses the Llama block (GQA + RoPE + RMSNorm, scan-over-layers, pipeline
support) and swaps the SwiGLU feed-forward for a top-k routed
mixture-of-experts (ops/moe.py): expert-stacked weights with the leading
``expert`` dim sharded over the ``ep`` mesh axis, capacity-based dense
dispatch so the whole layer is MXU einsums + one all-to-all.

The reference has no MoE implementation (BASELINE.md workload #5 runs
Mixtral via a user container; reference sky/examples only set rank env
vars — SURVEY.md §2.8). Architecture constants follow the public
Mixtral-8x7B config (32 layers, 8 experts, top-2, 14336 ffn dim).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Tuple

import jax
import jax.numpy as jnp

from skypilot_tpu.models.llama import (LlamaConfig, LlamaModel, Params,
                                       logical_axes as llama_logical_axes)
from skypilot_tpu.ops.layers import rms_norm
from skypilot_tpu.ops.moe import moe_ffn


@dataclasses.dataclass(frozen=True)
class MixtralConfig(LlamaConfig):
    num_experts: int = 8
    top_k: int = 2
    capacity_factor: float = 1.25
    router_aux_weight: float = 0.02

    @property
    def num_params(self) -> int:
        e, l, v = self.embed_dim, self.num_layers, self.vocab_size
        qkv = e * self.head_dim * (self.num_heads + 2 * self.num_kv_heads)
        o = self.num_heads * self.head_dim * e
        moe = self.num_experts * 3 * e * self.mlp_dim + e * self.num_experts
        per_layer = qkv + o + moe + 2 * e
        head = 0 if self.tie_embeddings else e * v
        return v * e + l * per_layer + e + head

    @property
    def flops_params(self) -> int:
        return self.active_params

    @property
    def active_params(self) -> int:
        """Params touched per token (top-k experts) — the FLOPs-relevant
        count for MFU/throughput accounting."""
        e, l, v = self.embed_dim, self.num_layers, self.vocab_size
        qkv = e * self.head_dim * (self.num_heads + 2 * self.num_kv_heads)
        o = self.num_heads * self.head_dim * e
        moe = self.top_k * 3 * e * self.mlp_dim + e * self.num_experts
        per_layer = qkv + o + moe + 2 * e
        head = 0 if self.tie_embeddings else e * v
        return v * e + l * per_layer + e + head


PRESETS: Dict[str, MixtralConfig] = {
    'test-tiny-moe': MixtralConfig(vocab_size=256, embed_dim=64, num_layers=2,
                                   num_heads=4, num_kv_heads=2, head_dim=16,
                                   mlp_dim=128, max_seq_len=512,
                                   dtype=jnp.float32, remat=False,
                                   num_experts=4, top_k=2,
                                   capacity_factor=4.0),
    # BASELINE workload #5 anchor (Mixtral 8x7B on preemptible v5e).
    'mixtral-8x7b': MixtralConfig(vocab_size=32000, embed_dim=4096,
                                  num_layers=32, num_heads=32, num_kv_heads=8,
                                  head_dim=128, mlp_dim=14336,
                                  max_seq_len=32768, rope_theta=1e6,
                                  num_experts=8, top_k=2),
}


def logical_axes(config: MixtralConfig) -> Params:
    axes = llama_logical_axes(config)
    axes['layers'].pop('w_gate')
    axes['layers'].pop('w_up')
    axes['layers'].pop('w_down')
    axes['layers'].update({
        'router': ('layers', 'embed', None),
        'we_gate': ('layers', 'expert', 'embed', 'mlp'),
        'we_up': ('layers', 'expert', 'embed', 'mlp'),
        'we_down': ('layers', 'expert', 'mlp', 'embed'),
    })
    return axes


class MixtralModel(LlamaModel):
    """Llama block stack with a routed-MoE feed-forward."""

    config: MixtralConfig

    @property
    def aux_loss_weight(self) -> float:
        return self.config.router_aux_weight

    def logical_axes(self) -> Params:
        return logical_axes(self.config)

    def init(self, rng: jax.Array) -> Params:
        c = self.config
        params = super().init(rng)
        lp = params['layers']
        for name in ('w_gate', 'w_up', 'w_down'):
            lp.pop(name)
        l, e, m, ne = c.num_layers, c.embed_dim, c.mlp_dim, c.num_experts
        keys = jax.random.split(jax.random.fold_in(rng, 17), 4)

        def dense(key, shape, fan_in):
            return (jax.random.normal(key, shape, jnp.float32)
                    * fan_in**-0.5).astype(c.dtype)

        lp['router'] = (jax.random.normal(keys[0], (l, e, ne), jnp.float32)
                        * e**-0.5)  # f32: routing decisions stay stable
        lp['we_gate'] = dense(keys[1], (l, ne, e, m), e)
        lp['we_up'] = dense(keys[2], (l, ne, e, m), e)
        lp['we_down'] = dense(keys[3], (l, ne, m, e), m)
        return params

    def _mlp_delta(self, lp: Params, x: jax.Array,
                   constrain: bool = True) -> Tuple[jax.Array, jax.Array]:
        c = self.config
        h = rms_norm(x, lp['mlp_norm'], c.norm_eps)
        y, aux = moe_ffn(h, lp['router'], lp['we_gate'], lp['we_up'],
                         lp['we_down'], top_k=c.top_k,
                         capacity_factor=c.capacity_factor)
        return y, aux['aux_loss']
