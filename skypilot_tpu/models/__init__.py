"""Flagship model family (TPU-native JAX).

The reference ships models only as example YAMLs that invoke external
frameworks (reference examples/tpu/v6e/train-llama3-8b.yaml, llm/llama-3);
here the model layer is in-tree so benchmarks, serving, and parallelism are
owned end-to-end by the framework.
"""
from skypilot_tpu.models.llama import (LlamaConfig, LlamaModel, PRESETS)
from skypilot_tpu.models.mixtral import (MixtralConfig, MixtralModel,
                                         PRESETS as MOE_PRESETS)

__all__ = ['LlamaConfig', 'LlamaModel', 'PRESETS', 'MixtralConfig',
           'MixtralModel', 'MOE_PRESETS']
