"""Llama-family decoder-only transformer, TPU-first.

Design (vs the reference's torch-XLA example, reference
examples/tpu/v6e/train-llama3-8b.yaml:43-50, which wraps HF Transformers):
  - pure-JAX pytree params (dict-of-arrays), stacked per-layer weights with
    a leading ``layers`` dim so the block stack is one ``lax.scan`` — O(1)
    HLO size in depth, fast compiles, natural remat boundary;
  - logical-axis shardings (parallel/sharding.py) so one model definition
    serves DP, FSDP, FSDP×TP, and FSDP×TP×SP meshes unchanged;
  - attention via ops.attention (Pallas flash on TPU) or
    parallel.ring_attention under shard_map when the mesh has sp > 1;
  - bf16 params/activations, f32 norms/softmax/logits.

GQA, RoPE (configurable theta), SwiGLU, RMSNorm — the Llama-2/3
architecture family; presets cover the baseline workloads in BASELINE.md
(Llama-2-7B serving, Llama-3-8B training).
"""
from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from skypilot_tpu.ops import attention as attention_ops
from skypilot_tpu.ops.layers import apply_rotary, precompute_rotary, rms_norm
from skypilot_tpu.parallel.ring_attention import ring_attention
from skypilot_tpu.parallel.sharding import (DEFAULT_RULES, LogicalRules,
                                            shard_map)

Params = Dict[str, Any]


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab_size: int = 128256
    embed_dim: int = 4096
    num_layers: int = 32
    num_heads: int = 32
    num_kv_heads: int = 8
    head_dim: int = 128
    mlp_dim: int = 14336
    max_seq_len: int = 8192
    rope_theta: float = 500000.0
    norm_eps: float = 1e-5
    dtype: Any = jnp.bfloat16
    tie_embeddings: bool = False
    remat: bool = True
    # Rematerialization policy: 'full' recomputes the whole layer in the
    # backward (min memory, ~+2NP FLOPs); 'dots' saves matmul outputs with
    # no batch dims + flash residuals; 'names' saves only the fattest
    # per-layer activations (attention context, SwiGLU product, flash
    # residuals); 'names_qkv' additionally saves post-rotary Q/K/V
    # (measured fastest on v5e @1B seq8192: +3.2% over 'names');
    # 'names_offload' moves the fat activations to pinned host memory
    # (fits bigger models, ~33% slower). Selective remat is the VERDICT
    # r1 MFU lever: full-layer remat costs ~25% of step FLOPs.
    remat_policy: str = 'full'
    # Pipeline parallelism: microbatch count when the mesh has pp > 1
    # (None -> one microbatch per stage, the minimum busy schedule).
    pp_microbatches: Optional[int] = None

    @property
    def num_params(self) -> int:
        e, l, v = self.embed_dim, self.num_layers, self.vocab_size
        qkv = e * self.head_dim * (self.num_heads + 2 * self.num_kv_heads)
        o = self.num_heads * self.head_dim * e
        mlp = 3 * e * self.mlp_dim
        per_layer = qkv + o + mlp + 2 * e
        head = 0 if self.tie_embeddings else e * v
        return v * e + l * per_layer + e + head

    @property
    def flops_params(self) -> int:
        """Param count for FLOPs accounting (MoE subclasses override with
        the per-token ACTIVE count)."""
        return self.num_params

    def train_flops_per_token(self, seq_len: int,
                              causal: bool = True) -> float:
        """Training FLOPs per token: 6N plus the self-attention term.

        The standard MFU accounting (PaLM appendix B): matmul FLOPs/token
        = 6N + 12·L·H·Q·T, where the second term is the QK^T and PV
        matmuls (fwd+bwd). Causal attention executes only half the score
        matrix (flash kernels skip masked tiles), so the term halves.
        At long sequence this is NOT a correction term — e.g. a 0.89B
        model at seq 8192 spends ~23% of its matmul FLOPs in attention —
        omitting it understates utilization of work the MXU really runs.
        """
        attn = 12 * self.num_layers * self.num_heads * self.head_dim \
            * seq_len
        if causal:
            attn //= 2
        return 6 * self.flops_params + attn


PRESETS: Dict[str, LlamaConfig] = {
    # Tiny config for unit tests / dryruns (dims stay multiples of 2 so tp/sp
    # axes divide them).
    'test-tiny': LlamaConfig(vocab_size=256, embed_dim=64, num_layers=2,
                             num_heads=4, num_kv_heads=2, head_dim=16,
                             mlp_dim=128, max_seq_len=512, dtype=jnp.float32,
                             remat=False),
    # ~1.3B with head_dim 128 (flash-kernel friendly); single-chip bench size.
    'llama-1b': LlamaConfig(vocab_size=32000, embed_dim=2048, num_layers=16,
                            num_heads=16, num_kv_heads=8, head_dim=128,
                            mlp_dim=5632, max_seq_len=8192,
                            rope_theta=10000.0),
    'llama2-7b': LlamaConfig(vocab_size=32000, embed_dim=4096, num_layers=32,
                             num_heads=32, num_kv_heads=32, head_dim=128,
                             mlp_dim=11008, max_seq_len=4096,
                             rope_theta=10000.0),
    'llama3-8b': LlamaConfig(),  # defaults are Llama-3-8B
    'llama3-70b': LlamaConfig(embed_dim=8192, num_layers=80, num_heads=64,
                              num_kv_heads=8, mlp_dim=28672),
}


def logical_axes(config: LlamaConfig) -> Params:
    """Pytree of logical-axis tuples matching ``init`` output."""
    axes = {
        'embed': ('vocab', 'embed'),
        'final_norm': (None,),
        'layers': {
            'wq': ('layers', 'embed', 'heads', None),
            'wk': ('layers', 'embed', 'kv_heads', None),
            'wv': ('layers', 'embed', 'kv_heads', None),
            'wo': ('layers', 'heads', None, 'embed'),
            'w_gate': ('layers', 'embed', 'mlp'),
            'w_up': ('layers', 'embed', 'mlp'),
            'w_down': ('layers', 'mlp', 'embed'),
            'attn_norm': ('layers', None),
            'mlp_norm': ('layers', None),
        },
    }
    if not config.tie_embeddings:
        axes['lm_head'] = ('embed', 'vocab')
    return axes


class LlamaModel:
    """Stateless module: ``init`` makes params, ``apply`` runs the forward."""

    def __init__(self, config: LlamaConfig,
                 mesh: Optional[Mesh] = None,
                 rules: LogicalRules = DEFAULT_RULES):
        self.config = config
        self.mesh = mesh
        if mesh is not None and mesh.shape.get('pp', 1) > 1:
            # Stage-major layer stacking: shard the layer dim over pp so each
            # stage's weights live on its own devices (parallel/pipeline.py).
            rules = rules.with_overrides(layers='pp')
        self.rules = rules

    @property
    def aux_loss_weight(self) -> float:
        return 0.0

    def logical_axes(self) -> Params:
        return logical_axes(self.config)

    # -- init ---------------------------------------------------------------
    def init(self, rng: jax.Array) -> Params:
        c = self.config
        dt = c.dtype
        k_embed, k_layers, k_head = jax.random.split(rng, 3)

        def dense(key, shape, fan_in):
            return (jax.random.normal(key, shape, jnp.float32)
                    * fan_in**-0.5).astype(dt)

        lk = jax.random.split(k_layers, 7)
        l, e, h, kvh, d, m = (c.num_layers, c.embed_dim, c.num_heads,
                              c.num_kv_heads, c.head_dim, c.mlp_dim)
        params: Params = {
            'embed': dense(k_embed, (c.vocab_size, e), 1.0),
            'final_norm': jnp.ones((e,), dt),
            'layers': {
                'wq': dense(lk[0], (l, e, h, d), e),
                'wk': dense(lk[1], (l, e, kvh, d), e),
                'wv': dense(lk[2], (l, e, kvh, d), e),
                'wo': dense(lk[3], (l, h, d, e), h * d),
                'w_gate': dense(lk[4], (l, e, m), e),
                'w_up': dense(lk[5], (l, e, m), e),
                'w_down': dense(lk[6], (l, m, e), m),
                'attn_norm': jnp.ones((l, e), dt),
                'mlp_norm': jnp.ones((l, e), dt),
            },
        }
        if not c.tie_embeddings:
            params['lm_head'] = dense(k_head, (e, c.vocab_size), e)
        return params

    def param_shardings(self, mesh: Optional[Mesh] = None):
        from skypilot_tpu.parallel.sharding import tree_shardings
        mesh = mesh or self.mesh
        assert mesh is not None
        return tree_shardings(mesh, self.rules, self.logical_axes())

    # -- helpers ------------------------------------------------------------
    def _constrain(self, x, *axes):
        if self.mesh is None:
            return x
        return lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(self.mesh, self.rules.spec(*axes)))

    def _sp_size(self) -> int:
        if self.mesh is None:
            return 1
        return self.mesh.shape.get('sp', 1)

    def _attend(self, q, k, v):
        """Dispatch: ring attention under shard_map when sp > 1."""
        if self._sp_size() > 1:
            k, v = attention_ops._maybe_repeat_kv(q, k, v)
            rules = self.rules
            qkv_spec = rules.spec('batch', 'seq', 'act_heads', None)
            fn = shard_map(
                functools.partial(ring_attention,
                                  axis_name='sp', causal=True),
                mesh=self.mesh,
                in_specs=(qkv_spec, qkv_spec, qkv_spec),
                out_specs=qkv_spec)
            return fn(q, k, v)
        return attention_ops.attention(q, k, v, causal=True)

    # -- transformer blocks (overridable; Mixtral swaps the MLP for MoE) ----
    def _qkv(self, lp: Params, x: jax.Array, cos, sin, positions,
             constrain: bool = True
             ) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Pre-attention norm + QKV projections + rotary (shared with the
        decode engine, models/decode.py, so the block math lives once)."""
        c = self.config
        con = self._constrain if constrain else (lambda a, *axes: a)
        h = rms_norm(x, lp['attn_norm'], c.norm_eps)
        q = jnp.einsum('bse,ehd->bshd', h, lp['wq'])
        k = jnp.einsum('bse,ehd->bshd', h, lp['wk'])
        v = jnp.einsum('bse,ehd->bshd', h, lp['wv'])
        q = apply_rotary(q, cos, sin, positions)
        k = apply_rotary(k, cos, sin, positions)
        q = con(q, 'batch', 'seq', 'act_heads', None)
        k = con(k, 'batch', 'seq', 'act_kv_heads', None)
        v = con(v, 'batch', 'seq', 'act_kv_heads', None)
        from jax.ad_checkpoint import checkpoint_name
        # Named so 'names_qkv' can keep post-rotary Q/K/V: the flash
        # BACKWARD needs them, and recomputing costs 3 projections +
        # rotary per layer (~6% of step FLOPs at seq 8192).
        return (checkpoint_name(q, 'q_rot'), checkpoint_name(k, 'k_rot'),
                checkpoint_name(v, 'v_proj'))

    def _attn_delta(self, lp: Params, x: jax.Array, cos, sin, positions,
                    constrain: bool = True) -> jax.Array:
        from jax.ad_checkpoint import checkpoint_name
        q, k, v = self._qkv(lp, x, cos, sin, positions, constrain)
        attn = checkpoint_name(self._attend(q, k, v), 'attn_out')
        return jnp.einsum('bshd,hde->bse', attn, lp['wo'])

    def _mlp_delta(self, lp: Params, x: jax.Array,
                   constrain: bool = True) -> Tuple[jax.Array, jax.Array]:
        """Post-attention feed-forward. Returns (delta, aux_loss_scalar)."""
        from jax.ad_checkpoint import checkpoint_name
        c = self.config
        con = self._constrain if constrain else (lambda a, *axes: a)
        h = rms_norm(x, lp['mlp_norm'], c.norm_eps)
        gate = jnp.einsum('bse,em->bsm', h, lp['w_gate'])
        up = jnp.einsum('bse,em->bsm', h, lp['w_up'])
        gated = con(jax.nn.silu(gate) * up, 'batch', 'seq', 'act_mlp')
        gated = checkpoint_name(gated, 'mlp_gated')
        return (jnp.einsum('bsm,me->bse', gated, lp['w_down']),
                jnp.zeros((), jnp.float32))

    def _layer_step(self, lp: Params, x: jax.Array, cos, sin, positions,
                    constrain: bool = True) -> Tuple[jax.Array, jax.Array]:
        con = self._constrain if constrain else (lambda a, *axes: a)
        x = x + self._attn_delta(lp, x, cos, sin, positions, constrain)
        x = con(x, 'batch', 'seq', 'act_embed')
        delta, aux = self._mlp_delta(lp, x, constrain)
        x = con(x + delta, 'batch', 'seq', 'act_embed')
        return x, aux

    # -- forward ------------------------------------------------------------
    def apply(self, params: Params, tokens: jax.Array,
              positions: Optional[jax.Array] = None) -> jax.Array:
        """tokens [B, S] int32 -> logits [B, S, V] (f32)."""
        return self.apply_with_aux(params, tokens, positions)[0]

    def apply_with_aux(self, params: Params, tokens: jax.Array,
                       positions: Optional[jax.Array] = None
                       ) -> Tuple[jax.Array, jax.Array]:
        """Forward returning (logits, mean per-layer aux loss).

        aux is 0 for dense models; MoE models return the router
        load-balancing loss (weighted into the train loss by the Trainer via
        ``aux_loss_weight``).
        """
        c = self.config
        if positions is None:
            positions = jnp.arange(tokens.shape[1])
        cos, sin = precompute_rotary(c.head_dim, c.max_seq_len, c.rope_theta)

        from skypilot_tpu.ops.embedding import embed_lookup
        x = embed_lookup(params['embed'], tokens, self.mesh,
                         self.rules).astype(c.dtype)
        x = self._constrain(x, 'batch', 'seq', 'act_embed')

        pp = self.mesh.shape.get('pp', 1) if self.mesh is not None else 1
        if pp > 1:
            x, aux = self._apply_pipelined(params['layers'], x, cos, sin,
                                           positions, pp)
        else:
            def layer(x, lp):
                return self._layer_step(lp, x, cos, sin, positions)

            layer = _maybe_remat(layer, c)
            x, auxs = lax.scan(layer, x, params['layers'])
            aux = jnp.mean(auxs)

        x = rms_norm(x, params['final_norm'], c.norm_eps)
        head = (params['embed'].T if c.tie_embeddings else params['lm_head'])
        # bf16 operands + f32 accumulation: runs at bf16 MXU rate (an f32
        # matmul on TPU is ~4x slower) with f32-accurate logits.
        logits = jnp.einsum('bse,ev->bsv', x, head,
                            preferred_element_type=jnp.float32)
        return self._constrain(logits, 'batch', 'seq', 'act_vocab'), aux

    def _apply_pipelined(self, layers: Params, x: jax.Array, cos, sin,
                         positions, pp: int) -> Tuple[jax.Array, jax.Array]:
        """Run the block stack as ``pp`` pipeline stages (parallel/pipeline).

        Inside the manual-pp shard_map body, sharding constraints cannot
        reference the pp axis, so the per-layer constraints are skipped —
        dp/fsdp/tp shardings propagate from the inputs (GSPMD-auto axes).
        Ring attention (sp > 1) composes with pp via the same manual-axis
        mechanism but is not yet supported together — asserted here.
        """
        from skypilot_tpu.parallel.pipeline import pipeline, split_stages
        if self._sp_size() > 1:
            raise NotImplementedError('pp > 1 with sp > 1 is not supported '
                                      'yet; use ring attention without '
                                      'pipeline stages or vice versa')
        c = self.config

        def stage_fn(local_layers, h, cos, sin, positions):
            def one(h, lp):
                return self._layer_step(lp, h, cos, sin, positions,
                                        constrain=False)

            one = _maybe_remat(one, c)
            h, auxs = lax.scan(one, h, local_layers)
            return h, jnp.mean(auxs)

        out, aux = pipeline(stage_fn, split_stages(layers, pp), x,
                            cos, sin, positions,
                            mesh=self.mesh,
                            num_microbatches=c.pp_microbatches,
                            with_aux=True)
        # stage_fn's aux is a mean over its layers; pipeline sums the stage
        # means over pp, so divide to get the global per-layer mean.
        return out, aux / pp

    # -- decode (serving) ---------------------------------------------------
    def init_cache(self, batch: int, max_len: int) -> Params:
        c = self.config
        shape = (c.num_layers, batch, max_len, c.num_kv_heads, c.head_dim)
        return {
            'k': jnp.zeros(shape, c.dtype),
            'v': jnp.zeros(shape, c.dtype),
            'length': jnp.zeros((), jnp.int32),
        }

    def decode_step(self, params: Params, cache: Params,
                    tokens: jax.Array) -> Tuple[jax.Array, Params]:
        """Append ``tokens`` [B, T] at cache.length, return last-token logits.

        Covers both prefill (T = prompt length) and autoregressive decode
        (T = 1) with one code path; static T per compiled variant.
        """
        c = self.config
        start = cache['length']
        positions = start + jnp.arange(tokens.shape[1])
        cos, sin = precompute_rotary(c.head_dim, c.max_seq_len, c.rope_theta)
        x = params['embed'][tokens].astype(c.dtype)
        max_len = cache['k'].shape[2]

        new_k, new_v = [], []
        for i in range(c.num_layers):
            lp = jax.tree.map(lambda p: p[i], params['layers'])
            q, k, v = self._qkv(lp, x, cos, sin, positions, constrain=False)
            k_cache = lax.dynamic_update_slice(
                cache['k'][i], k, (0, start, 0, 0))
            v_cache = lax.dynamic_update_slice(
                cache['v'][i], v, (0, start, 0, 0))
            new_k.append(k_cache)
            new_v.append(v_cache)
            # Mask beyond current length via position comparison.
            kv_pos = jnp.arange(max_len)
            valid = kv_pos[None, :] <= positions[:, None]  # [T, max_len]
            attn = _cached_attention(q, k_cache, v_cache, valid)
            x = x + jnp.einsum('bshd,hde->bse', attn, lp['wo'])
            x = x + self._mlp_delta(lp, x, constrain=False)[0]

        x = rms_norm(x, params['final_norm'], c.norm_eps)
        head = (params['embed'].T if c.tie_embeddings else params['lm_head'])
        logits = jnp.einsum('be,ev->bv', x[:, -1].astype(jnp.float32),
                            head.astype(jnp.float32))
        new_cache = {
            'k': jnp.stack(new_k),
            'v': jnp.stack(new_v),
            'length': start + tokens.shape[1],
        }
        return logits, new_cache


def _maybe_remat(layer_fn, config: LlamaConfig):
    """Apply the configured rematerialization policy to a scan body."""
    if not config.remat:
        return layer_fn
    cp = jax.checkpoint_policies
    if config.remat_policy == 'dots':
        # Save matmul outputs AND the flash-attention residuals (softmax
        # stats + context): without the latter, backward re-runs the whole
        # flash forward per layer.
        return jax.checkpoint(
            layer_fn,
            policy=cp.save_from_both_policies(
                cp.dots_with_no_batch_dims_saveable,
                cp.save_only_these_names('flash_out', 'flash_lse')))
    if config.remat_policy == 'names':
        # Selective: keep only the fattest per-layer activations
        # (attention context + stats, SwiGLU product); backward recomputes
        # norms/projections/rotary from the saved layer input.
        return jax.checkpoint(
            layer_fn,
            policy=cp.save_only_these_names(
                'attn_out', 'mlp_gated', 'flash_out', 'flash_lse'))
    if config.remat_policy == 'names_qkv':
        # 'names' + post-rotary Q/K/V: trades ~1.5 GB more activation
        # memory (b1 s8192 @1B) for skipping the QKV-projection+rotary
        # recompute in backward.
        return jax.checkpoint(
            layer_fn,
            policy=cp.save_only_these_names(
                'attn_out', 'mlp_gated', 'flash_out', 'flash_lse',
                'q_rot', 'k_rot', 'v_proj'))
    if config.remat_policy == 'names_offload':
        # Fat activations offload to host memory; only the flash
        # residuals stay in HBM. Frees ~2 GB for batch at the cost of
        # host<->device traffic each step (measured 33% slower than
        # 'names' on v5e at 1B/seq8192 — an option for models that
        # otherwise don't fit, not a throughput win).
        return jax.checkpoint(
            layer_fn,
            policy=cp.save_and_offload_only_these_names(
                names_which_can_be_saved=['flash_out', 'flash_lse'],
                names_which_can_be_offloaded=['attn_out', 'mlp_gated'],
                offload_src='device', offload_dst='pinned_host'))
    if config.remat_policy != 'full':
        raise ValueError(
            f'unknown remat_policy {config.remat_policy!r}; expected one '
            "of 'full', 'dots', 'names', 'names_qkv', 'names_offload'")
    return jax.checkpoint(layer_fn)


def _cached_attention(q, k, v, valid):
    """Attention against a (padded) cache with an explicit validity mask."""
    return attention_ops.mha_reference(q, k, v, mask=valid)
