"""TPU-native autoregressive decode engine (slot-based continuous batching).

This is the serving engine the BASELINE anchors require (reference anchor:
JetStream serving numbers in /root/reference/examples/tpu/v6e/README.md:94-130;
the reference itself ships no engine — it orchestrates JetStream/vLLM).

Design (vs the correctness-oracle ``LlamaModel.decode_step``):
  - one **stacked KV cache** ``[L, B, M, kvh, d]`` held in a donated
    ``DecodeState``; every jitted op updates it via dynamic-slice /
    scatter so XLA aliases buffers in place — no per-step ``jnp.stack``.
  - ``lax.scan`` over layers (O(1) HLO in depth, fast compiles).
  - **slots**: a fixed decode batch of B independent sequences with
    per-row ``lengths``; requests are prefilled one at a time (padded to a
    static bucket), inserted into a free slot, and decoded together —
    continuous batching, the TPU-friendly JetStream architecture.
  - sampling (greedy / temperature / top-k) runs inside the step jit, so
    the only per-step host traffic is B sampled token ids.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from skypilot_tpu.models.llama import LlamaConfig, LlamaModel, Params
from skypilot_tpu.ops import attention as attention_ops
from skypilot_tpu.ops.layers import precompute_rotary, rms_norm


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DecodeState:
    """Batched decode state: stacked KV cache + per-slot bookkeeping."""
    k: jax.Array            # [L, B, M, kvh, d]
    v: jax.Array            # [L, B, M, kvh, d]
    lengths: jax.Array      # [B] int32: tokens currently in each slot's cache
    last_tokens: jax.Array  # [B] int32: next token to feed per slot
    active: jax.Array       # [B] bool: slot occupied


class DecodeEngine:
    """Jitted prefill / insert / step over a fixed slot batch.

    ``batch_slots`` and ``max_len`` are static (one compiled program);
    prompts are padded to power-of-two buckets so prefill compiles a small
    number of variants.
    """

    def __init__(self, config: LlamaConfig, batch_slots: int = 8,
                 max_len: Optional[int] = None,
                 model: Optional[LlamaModel] = None):
        self.config = config
        # Engine reuses the model's block methods (_qkv/_mlp_delta) so the
        # transformer math lives once; pass a MixtralModel to serve MoE.
        self.model = model or LlamaModel(config)
        self.batch_slots = batch_slots
        self.max_len = max_len or config.max_seq_len
        self._prefill = jax.jit(self._prefill_impl)
        self._insert = jax.jit(self._insert_impl, donate_argnums=(0,))
        # temperature/top_k are *traced* [B] args — any per-request sampling
        # settings reuse the one compiled step (no recompile DoS).
        self._step = jax.jit(self._step_impl, donate_argnums=(1,))

    # -- state --------------------------------------------------------------
    def init_state(self) -> DecodeState:
        c = self.config
        shape = (c.num_layers, self.batch_slots, self.max_len,
                 c.num_kv_heads, c.head_dim)
        b = self.batch_slots
        return DecodeState(
            k=jnp.zeros(shape, c.dtype),
            v=jnp.zeros(shape, c.dtype),
            lengths=jnp.zeros((b,), jnp.int32),
            last_tokens=jnp.zeros((b,), jnp.int32),
            active=jnp.zeros((b,), bool),
        )

    # -- prefill ------------------------------------------------------------
    def prefill(self, params: Params, tokens: jax.Array,
                true_len: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Run a single prompt [T_padded] through the model.

        Returns (k [L, T_padded, kvh, d], v, last_logits [V]). End-padding is
        benign under causal attention; the garbage keys past ``true_len``
        are masked out at decode time by the slot length. The caller samples
        the FIRST generated token from ``last_logits`` (that token is the
        TTFT token) and feeds it to ``insert`` as ``last_token``.
        """
        return self._prefill(params, tokens,
                             jnp.asarray(true_len, jnp.int32))

    def _prefill_impl(self, params, tokens, true_len):
        c = self.config
        t = tokens.shape[0]
        positions = jnp.arange(t)
        cos, sin = precompute_rotary(c.head_dim, c.max_seq_len, c.rope_theta)
        x = params['embed'][tokens][None].astype(c.dtype)  # [1, T, e]
        model = self.model

        def layer(x, lp):
            q, k, v = model._qkv(lp, x, cos, sin, positions, constrain=False)
            attn = attention_ops.attention(q, k, v, causal=True)
            x = x + jnp.einsum('bshd,hde->bse', attn, lp['wo'])
            x = x + model._mlp_delta(lp, x, constrain=False)[0]
            return x, (k[0], v[0])

        x, (ks, vs) = lax.scan(layer, x, params['layers'])
        x = rms_norm(x, params['final_norm'], c.norm_eps)
        head = (params['embed'].T if c.tie_embeddings else params['lm_head'])
        # Logits only for the last real token — avoids the [T, V] matmul.
        last = x[0, true_len - 1].astype(jnp.float32)
        logits = last @ head.astype(jnp.float32)
        return ks, vs, logits

    # -- insert -------------------------------------------------------------
    def insert(self, state: DecodeState, k: jax.Array, v: jax.Array,
               true_len: jax.Array, last_token: jax.Array,
               slot: jax.Array) -> DecodeState:
        """Write a prefilled prompt's KV into ``slot`` and mark it active."""
        return self._insert(state, k, v, jnp.asarray(true_len, jnp.int32),
                            jnp.asarray(last_token, jnp.int32),
                            jnp.asarray(slot, jnp.int32))

    def _insert_impl(self, state, k, v, true_len, last_token, slot):
        t = k.shape[1]
        pad_m = self.max_len - t
        if pad_m < 0:
            raise ValueError(f'prefill length {t} exceeds max_len '
                             f'{self.max_len}')
        # [L, T, kvh, d] -> [L, 1, M, kvh, d] zero-extended, then one
        # dynamic_update_slice into the stacked cache (in-place: donated).
        kf = jnp.pad(k, ((0, 0), (0, pad_m), (0, 0), (0, 0)))[:, None]
        vf = jnp.pad(v, ((0, 0), (0, pad_m), (0, 0), (0, 0)))[:, None]
        new_k = lax.dynamic_update_slice(state.k, kf.astype(state.k.dtype),
                                         (0, slot, 0, 0, 0))
        new_v = lax.dynamic_update_slice(state.v, vf.astype(state.v.dtype),
                                         (0, slot, 0, 0, 0))
        return DecodeState(
            k=new_k, v=new_v,
            lengths=state.lengths.at[slot].set(true_len),
            last_tokens=state.last_tokens.at[slot].set(last_token),
            active=state.active.at[slot].set(True),
        )

    def release(self, state: DecodeState, slot: int) -> DecodeState:
        """Mark a slot free (cache contents are dead; lengths gate reads)."""
        return DecodeState(k=state.k, v=state.v,
                           lengths=state.lengths.at[slot].set(0),
                           last_tokens=state.last_tokens,
                           active=state.active.at[slot].set(False))

    # -- decode step --------------------------------------------------------
    def step(self, params: Params, state: DecodeState, rng: jax.Array,
             temperature=0.0, top_k=0) -> Tuple[DecodeState, jax.Array]:
        """One token for every active slot. Returns (state, sampled [B]).

        ``temperature``/``top_k`` may be scalars or per-slot [B] arrays;
        they are traced (not static), so heterogeneous sampling settings
        never trigger recompilation.
        """
        b = self.batch_slots
        temperature = jnp.broadcast_to(
            jnp.asarray(temperature, jnp.float32), (b,))
        top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (b,))
        return self._step(params, state, rng, temperature, top_k)

    def _step_impl(self, params, state, rng, temperature, top_k):
        c = self.config
        b = self.batch_slots
        grp = c.num_heads // c.num_kv_heads
        cos, sin = precompute_rotary(c.head_dim, c.max_seq_len, c.rope_theta)
        positions = state.lengths[:, None]  # [B, 1]: new token's position
        x = params['embed'][state.last_tokens][:, None].astype(c.dtype)
        rows = jnp.arange(b)
        kv_pos = jnp.arange(self.max_len)
        # New key written at index ``lengths`` -> valid keys are <= lengths.
        valid = kv_pos[None] <= state.lengths[:, None]  # [B, M]

        model = self.model

        def layer(carry, inputs):
            x, cache_k, cache_v = carry
            lp, i = inputs
            q, k, v = model._qkv(lp, x, cos, sin, positions, constrain=False)
            # Scatter the new K/V row into layer i at each slot's length
            # (in-place on the donated carry).
            cache_k = cache_k.at[i, rows, state.lengths].set(
                k[:, 0].astype(cache_k.dtype))
            cache_v = cache_v.at[i, rows, state.lengths].set(
                v[:, 0].astype(cache_v.dtype))
            k_layer = cache_k[i]  # [B, M, kvh, d]
            v_layer = cache_v[i]
            # Grouped-query attention without repeating KV ([B,kvh,grp,d]).
            qg = q[:, 0].reshape(b, c.num_kv_heads, grp, c.head_dim)
            s = jnp.einsum('bkgd,bmkd->bkgm', qg.astype(jnp.float32),
                           k_layer.astype(jnp.float32))
            s = s * (c.head_dim**-0.5)
            s = jnp.where(valid[:, None, None, :], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            attn = jnp.einsum('bkgm,bmkd->bkgd', p,
                              v_layer.astype(jnp.float32))
            attn = attn.reshape(b, 1, c.num_heads, c.head_dim).astype(c.dtype)
            x = x + jnp.einsum('bshd,hde->bse', attn, lp['wo'])
            x = x + model._mlp_delta(lp, x, constrain=False)[0]
            return (x, cache_k, cache_v), None

        n_layers = c.num_layers
        (x, new_k, new_v), _ = lax.scan(
            layer, (x, state.k, state.v),
            (params['layers'], jnp.arange(n_layers)))

        x = rms_norm(x, params['final_norm'], c.norm_eps)
        head = (params['embed'].T if c.tie_embeddings else params['lm_head'])
        logits = jnp.einsum('be,ev->bv', x[:, 0].astype(jnp.float32),
                            head.astype(jnp.float32))
        sampled = _sample(logits, rng, temperature, top_k)
        active_i = state.active.astype(jnp.int32)
        return DecodeState(
            k=new_k, v=new_v,
            lengths=state.lengths + active_i,
            last_tokens=jnp.where(state.active, sampled, state.last_tokens),
            active=state.active,
        ), sampled


def _sample(logits: jax.Array, rng: jax.Array, temperature,
            top_k) -> jax.Array:
    """Greedy (temperature 0) / temperature / top-k sampling, inside jit.

    ``temperature`` [B] f32 and ``top_k`` [B] int32 are traced per-row
    values (scalars broadcast); out-of-range top_k is clamped to the vocab,
    so malformed requests cannot crash the compiled step.
    """
    v = logits.shape[-1]
    temperature = jnp.broadcast_to(
        jnp.asarray(temperature, jnp.float32), logits.shape[:1])
    top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), logits.shape[:1])
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    srt = jnp.sort(scaled, axis=-1)  # ascending
    kth_idx = jnp.clip(v - top_k, 0, v - 1)
    kth = jnp.take_along_axis(srt, kth_idx[:, None], axis=-1)
    kth = jnp.where((top_k > 0)[:, None], kth, -jnp.inf)
    filtered = jnp.where(scaled >= kth, scaled, -jnp.inf)
    sampled = jax.random.categorical(rng, filtered, axis=-1).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)


def prefill_bucket(length: int, max_len: int, floor: int = 16) -> int:
    """Smallest power-of-two bucket >= length (bounded by max_len)."""
    b = floor
    while b < length:
        b *= 2
    return min(b, max_len)
