"""TPU-native autoregressive decode engine (slot-based continuous batching).

This is the serving engine the BASELINE anchors require (reference anchor:
JetStream serving numbers in /root/reference/examples/tpu/v6e/README.md:94-130;
the reference itself ships no engine — it orchestrates JetStream/vLLM).

Design (vs the correctness-oracle ``LlamaModel.decode_step``):
  - one **stacked KV cache** ``[L, B, M, kvh, d]`` held in a donated
    ``DecodeState``; every jitted op updates it via dynamic-slice /
    scatter so XLA aliases buffers in place — no per-step ``jnp.stack``.
  - ``lax.scan`` over layers (O(1) HLO in depth, fast compiles).
  - **slots**: a fixed decode batch of B independent sequences with
    per-row ``lengths``; requests are prefilled one at a time (padded to a
    static bucket), inserted into a free slot, and decoded together —
    continuous batching, the TPU-friendly JetStream architecture.
  - sampling (greedy / temperature / top-k) runs inside the step jit, so
    the only per-step host traffic is B sampled token ids.
  - **paged KV** (default, ``SKYTPU_KV_BLOCK=64``; 0 = contiguous): KV
    lives in a global pool of fixed-size blocks addressed through
    per-slot block tables (PagedAttention, Kwon et al. SOSP '23), so a
    slot consumes only the blocks its sequence fills and full prefix
    blocks are shared across slots via the host-side refcounting +
    hash-chain prefix cache in ``models/paged_kv.py`` (RadixAttention-
    style reuse). All three admission paths and the decode-step scatter
    are block-indexed; the contiguous layout remains as the
    equivalence oracle and for A/B microbenches.
  - **int8 KV** (``SKYTPU_KV_DTYPE=int8``, paged mode only): the pool
    stores symmetric-absmax-quantized int8 rows plus f32 per-(layer,
    block, kv-head, row) scales; every write path quantizes and the
    attention gather dequantizes (int8 -> f32 x scale) before QK^T with
    f32 score accumulation. Scales travel with blocks, so prefix
    sharing, tail reclaim and spec-decode rollback-by-length-masking
    need no extra invalidation. Halves KV bytes/token -> double the
    block capacity under one HBM budget. ``bf16`` (default) traces the
    exact pre-quantization program: bit-identical streams, zero new
    compiles.
"""
from __future__ import annotations

import dataclasses
import time
from collections import OrderedDict, deque
from typing import Any, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from skypilot_tpu import env_vars
from skypilot_tpu.models import paged_kv
from skypilot_tpu.models.llama import LlamaConfig, LlamaModel, Params
from skypilot_tpu.ops import attention as attention_ops
from skypilot_tpu.ops.layers import precompute_rotary, rms_norm
from skypilot_tpu.utils import metrics as metrics_lib


class StepProfiler:
    """Engine-side metrics: step timing, token mix, compile variants.

    Registered on the process default registry so the generation
    server's ``/metrics`` exposes engine series next to scheduler ones.
    The engine holds ``profiler = None`` when metrics are disabled, so
    every instrumentation site is a single ``is not None`` branch.

    The recompile counter counts FIRST-SEEN jit variants host-side
    (kind, shape) — prefill buckets, chunk_spans final-bucket variants,
    the step itself. After warmup it equals the compiled-variant count;
    any mid-traffic increase is a compile stall landing inside a
    request's latency (the multi-second XLA pauses admission control
    cannot see coming).
    """

    def __init__(self):
        self.step_ms = metrics_lib.histogram(
            'skytpu_engine_step_ms',
            'decode step dispatch wall time',
            buckets=(0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250,
                     1000, 10000, 60000))
        self.steps = metrics_lib.counter(
            'skytpu_engine_steps_total', 'decode steps dispatched')
        self.recompiles = metrics_lib.counter(
            'skytpu_engine_recompiles_total',
            'first-seen jit variants (compile-cache misses)')
        self.prefill_tokens = metrics_lib.counter(
            'skytpu_engine_prefill_tokens_total',
            'prefill tokens dispatched (padded buckets)')
        self.decode_tokens = metrics_lib.counter(
            'skytpu_engine_decode_tokens_total',
            'decode tokens dispatched for active slots')
        self.occupancy = metrics_lib.gauge(
            'skytpu_engine_occupancy_ratio',
            'active slots / batch slots at the last decode step')
        # Host-side gap between consecutive step dispatches: the time
        # the dispatch queue is NOT being fed. With >= 2 steps in
        # flight the device rides out these gaps; the histogram is the
        # signal that says whether it has to. Sub-ms buckets: on local
        # hardware the healthy gap is tens of microseconds.
        self.step_gap_ms = metrics_lib.histogram(
            'skytpu_engine_step_gap_ms',
            'host gap between consecutive decode step dispatches',
            buckets=(0.01, 0.025, 0.05, 0.1, 0.25, 0.5, 1, 2.5, 5, 10,
                     25, 50, 100, 1000))
        self.inflight_steps = metrics_lib.gauge(
            'skytpu_engine_inflight_steps_count',
            'decode steps dispatched but not yet fetched by the emitter')
        # Speculative-decode series. The accept histogram observes
        # tokens EMITTED per verify step (accept + 1: the accepted
        # draft prefix plus the corrected token), so its mean
        # (sum/count) is accepted_tokens_per_step directly — the
        # dashboard's "accept/step" column and the ROADMAP target
        # (> 1.8 on repetitive traffic) read straight off it.
        self.spec_accept = metrics_lib.histogram(
            'skytpu_engine_spec_accept_tokens',
            'tokens emitted per verify step (accepted prefix + 1)',
            buckets=(1, 2, 3, 4, 5, 6, 7, 8, 9, 16))
        self.spec_draft_hits = metrics_lib.counter(
            'skytpu_engine_spec_draft_hits_total',
            'draft tokens accepted by verification')
        self.spec_verify_ms = metrics_lib.histogram(
            'skytpu_engine_spec_verify_ms',
            'verify step dispatch wall time',
            buckets=(0.1, 0.25, 0.5, 1, 2.5, 5, 10, 25, 50, 100, 250,
                     1000, 10000, 60000))
        # Quantized-KV series. kv_bytes_per_token is what int8 storage
        # halves; the scale histogram is the accuracy canary — scales
        # drifting toward the top buckets mean coarser quantization
        # steps (absmax/127), which is where greedy agreement degrades
        # first. Log-spaced buckets: bf16 activations put typical
        # per-row absmax around 1e-2..1, so the edges bracket that by
        # two decades each way.
        self.kv_bytes_per_token = metrics_lib.gauge(
            'skytpu_engine_kv_bytes_per_token',
            'KV cache bytes stored per token across all layers/heads')
        self.kv_quant_scale = metrics_lib.histogram(
            'skytpu_engine_kv_quant_scale_ratio',
            'per-row absmax quantization scales sampled at scrape time',
            buckets=(1e-4, 3e-4, 1e-3, 3e-3, 0.01, 0.03, 0.1, 0.3,
                     1.0, 3.0, 10.0))
        self._seen_variants: set = set()
        # Roofline attribution state: static (FLOPs, bytes) per compiled
        # step variant (note_roofline, filled at warmup) joined with a
        # measured per-variant dispatch-time EWMA (note_step attributes
        # each step to the variant the preceding note_variant named).
        self._variant_labels: Dict[tuple, str] = {}
        self._variant_costs: Dict[str, Tuple[float, float]] = {}
        self._variant_step_s: Dict[str, float] = {}
        self._current_variant: Optional[str] = None
        # Last-N raw gap samples, per-PROFILER (one profiler per
        # engine): the registry histogram above is process-global, so a
        # same-process A/B (depth-1 vs depth-2 engines in one test or
        # bench run) needs a per-engine distribution to compare.
        self.gap_samples: 'deque[float]' = deque(
            maxlen=self.GAP_SAMPLES_MAX)

    GAP_SAMPLES_MAX = 4096
    # EWMA weight for the per-variant step-time attribution: slow
    # enough to ride out one compile stall, fast enough that the MFU
    # gauge tracks a real regime change within ~10 steps.
    STEP_EWMA_ALPHA = 0.2

    @staticmethod
    def variant_label(kind: str, *shape) -> str:
        """Stable label for one jit variant: ``step:8``,
        ``step_verify:8x4``, ``prefill_chunk_final:64`` — the key the
        roofline gauge family and the cost table share. Shape entries
        may themselves be dim tuples (``admit_many`` passes the whole
        array shape); they flatten into the same ``x``-joined form."""
        dims = []
        for s in shape:
            if isinstance(s, (tuple, list)):
                dims.extend(int(d) for d in s)
            else:
                dims.append(int(s))
        if not dims:
            return kind
        return kind + ':' + 'x'.join(str(d) for d in dims)

    def note_variant(self, kind: str, *shape) -> None:
        key = (kind, *shape)
        label = self._variant_labels.get(key)
        if label is None:
            label = self.variant_label(kind, *shape)
            self._variant_labels[key] = label
            self._seen_variants.add(key)
            self.recompiles.inc()
        self._current_variant = label

    def note_step(self, wall_s: float) -> None:
        self.steps.inc()
        self.step_ms.observe(wall_s * 1e3)
        variant = self._current_variant
        if variant is not None:
            prev = self._variant_step_s.get(variant)
            self._variant_step_s[variant] = (
                wall_s if prev is None
                else prev + self.STEP_EWMA_ALPHA * (wall_s - prev))

    def note_gap(self, gap_s: float) -> None:
        ms = gap_s * 1e3
        self.step_gap_ms.observe(ms)
        self.gap_samples.append(ms)

    def note_inflight(self, depth: int) -> None:
        self.inflight_steps.set(depth)

    def note_occupancy(self, active: int, total: int) -> None:
        self.occupancy.set(active / total if total else 0.0)
        self.decode_tokens.inc(active)

    def note_spec_accept(self, accept: int, k: int) -> None:
        """One slot's verify outcome: ``accept`` of ``k`` draft tokens
        survived, so accept + 1 tokens were emitted this step."""
        self.spec_accept.observe(accept + 1)
        if accept:
            self.spec_draft_hits.inc(accept)

    def note_kv_config(self, kv_dtype: str, bytes_per_token: int) -> None:
        """Engine-construction facts: the storage dtype as a Prometheus
        info gauge (constant 1, dtype label) and the per-token KV
        footprint the dtype implies."""
        metrics_lib.gauge(
            'skytpu_engine_kv_dtype_info',
            'KV cache storage dtype (constant 1; dtype label)',
            labels={'dtype': kv_dtype}).set(1)
        self.kv_bytes_per_token.set(bytes_per_token)

    def note_hbm(self, ledger: Dict[str, float],
                 block_stats: Optional[Dict[str, float]] = None) -> None:
        """Publish the HBM ledger (component -> bytes) as the labeled
        ``skytpu_engine_hbm_bytes`` gauge family, plus the block-pool
        utilization/fragmentation ratios when ``hbm_block_stats()``
        output is passed (scrape-time refresh; registration is
        idempotent, so repeat scrapes just .set())."""
        def comp(component: str, nbytes: float) -> None:
            metrics_lib.gauge(
                'skytpu_engine_hbm_bytes',
                'device-memory accounting by component',
                labels={'component': component}).set(nbytes)

        for component, nbytes in ledger.items():
            comp(component, nbytes)
        if block_stats:
            comp('kv_used', block_stats.get('kv_used_bytes', 0))
            comp('kv_free', block_stats.get('kv_free_bytes', 0))
            metrics_lib.gauge(
                'skytpu_engine_hbm_kv_utilization_ratio',
                'used fraction of the KV block pool').set(
                    block_stats.get('kv_block_utilization', 0.0))
            metrics_lib.gauge(
                'skytpu_engine_hbm_fragmentation_ratio',
                'share of pool bytes in free-but-resident blocks').set(
                    block_stats.get('kv_fragmentation_ratio', 0.0))

    def note_roofline(self,
                      costs: Dict[str, Tuple[float, float]]) -> None:
        """Record the compiled-cost table (variant -> (FLOPs, bytes
        accessed) per dispatch, from ``DecodeEngine.roofline_costs`` at
        warmup) and publish the static halves as labeled gauges. The
        dynamic halves — MFU and arithmetic intensity joined with the
        measured step-time EWMA — refresh at scrape time via
        :meth:`roofline_snapshot` (``note_hbm`` cadence)."""
        self._variant_costs.update(costs)
        for variant, (flops, nbytes) in costs.items():
            metrics_lib.gauge(
                'skytpu_engine_step_flops',
                'FLOPs one dispatch of this jit step variant executes',
                labels={'variant': variant}).set(flops)
            metrics_lib.gauge(
                'skytpu_engine_step_bytes',
                'HBM bytes one dispatch of this jit step variant moves',
                labels={'variant': variant}).set(nbytes)

    def roofline_snapshot(self, peak_flops: float = 0.0
                          ) -> Dict[str, Dict[str, float]]:
        """variant -> {flops, bytes, ai, step_ms, mfu}; refreshes the
        ``skytpu_engine_step_ai_ratio`` / ``_mfu_ratio`` gauges.

        AI = FLOPs / bytes places the variant on the roofline's x-axis
        (below the chip's FLOPs:bandwidth ratio = bandwidth-bound);
        MFU = FLOPs / (step_time * peak) is how much of the machine the
        variant actually uses. ``peak_flops`` <= 0 (SKYTPU_PEAK_TFLOPS
        unset) reports MFU 0 — AI and the static gauges still export.
        """
        out: Dict[str, Dict[str, float]] = {}
        for variant, (flops, nbytes) in sorted(
                self._variant_costs.items()):
            ai = flops / nbytes if nbytes > 0 else 0.0
            step_s = self._variant_step_s.get(variant)
            mfu = 0.0
            if step_s and peak_flops > 0:
                mfu = flops / step_s / peak_flops
            metrics_lib.gauge(
                'skytpu_engine_step_ai_ratio',
                'arithmetic intensity (FLOPs per HBM byte) of this '
                'step variant', labels={'variant': variant}).set(ai)
            metrics_lib.gauge(
                'skytpu_engine_step_mfu_ratio',
                'serving MFU of this step variant: FLOPs / (measured '
                'dispatch EWMA x SKYTPU_PEAK_TFLOPS)',
                labels={'variant': variant}).set(mfu)
            out[variant] = {
                'flops': flops, 'bytes': nbytes, 'ai': ai,
                'step_ms': (step_s or 0.0) * 1e3, 'mfu': mfu,
            }
        return out


def peak_flops() -> float:
    """$SKYTPU_PEAK_TFLOPS in FLOP/s — the MFU denominator. 0.0 when
    unset: the roofline MFU gauges report 0 but AI/FLOPs/bytes still
    export (they need no hardware constant)."""
    return float(env_vars.get('SKYTPU_PEAK_TFLOPS') or 0.0) * 1e12


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class DecodeState:
    """Batched decode state: stacked KV cache + per-slot bookkeeping.

    Contiguous mode (``kv_block=0``): k/v are [L, B, kvh, M, d]
    (head-major, sequence next-to-minor — decode attention for each
    (slot, kv-head) pair streams a contiguous [M, d] block from HBM;
    the naive [L, B, M, kvh, d] layout strides those reads and measured
    ~3.4x slower per step at M=4096 on v5e) and ``block_tables`` is an
    empty [B, 0] placeholder.

    Paged mode (``kv_block>0``, the default): k/v are ONE global pool of
    fixed-size blocks [L, num_blocks, kvh, block, d] and
    ``block_tables[b]`` lists the physical block ids holding slot b's
    rows in order (vLLM-style PagedAttention). Row ``p`` of slot ``b``
    lives at pool row ``(block_tables[b, p // block], p % block)``;
    unassigned table entries point at the reserved null block 0, whose
    rows are never read unmasked. Slots sharing a prompt prefix map the
    SAME physical blocks (refcounted on the host), which is what makes
    the shared-system-prompt workload prefill its prefix once.

    Int8 mode (``kv_dtype='int8'``, paged only): k/v hold int8
    quantized rows and ``k_scale``/``v_scale`` hold the f32 absmax
    scales per (layer, block, kv-head, row) — [L, NB, kvh, BS], the
    pool layout minus the head_dim axis. A scale row is written by
    exactly the scatter that writes its KV row, so block sharing and
    rollback semantics are inherited unchanged. In bf16 mode the scale
    fields are zero-size placeholders (the ``block_tables`` [B, 0]
    pattern): they cost nothing and never enter traced math.
    """
    k: jax.Array            # [L, B, kvh, M, d] or [L, NB, kvh, BS, d]
    v: jax.Array            # same layout as k
    lengths: jax.Array      # [B] int32: tokens currently in each slot's cache
    last_tokens: jax.Array  # [B] int32: next token to feed per slot
    active: jax.Array       # [B] bool: slot occupied
    block_tables: jax.Array  # [B, max_blocks] int32 (paged), [B, 0] else
    k_scale: jax.Array      # [L, NB, kvh, BS] f32 (int8 mode), [0] else
    v_scale: jax.Array      # same layout as k_scale


KV_DTYPES = ('bf16', 'int8')


def quantize_kv_rows(x: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """Symmetric absmax int8 quantization over the trailing head_dim.

    ``x`` [..., d] float -> (int8 codes [..., d], f32 scales [...]).
    Scale = absmax / 127 per row; an all-zero row gets scale 0 and
    codes 0, so zero rows round-trip exactly (the null block stays
    null). Round-to-nearest keeps the worst-case row error at
    scale / 2 per element.
    """
    xf = x.astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)        # [...]
    scale = amax / 127.0
    safe = jnp.where(scale > 0.0, scale, 1.0)
    q = jnp.clip(jnp.round(xf / safe[..., None]), -127.0, 127.0)
    return q.astype(jnp.int8), scale


def dequantize_kv_rows(q: jax.Array, scale: jax.Array) -> jax.Array:
    """Inverse of :func:`quantize_kv_rows`: int8 codes [..., d] x f32
    scales [...] -> f32 rows (the attention-gather dequant — scores
    then accumulate in f32 via preferred_element_type)."""
    return q.astype(jnp.float32) * scale[..., None]


class DecodeEngine:
    """Jitted prefill / insert / step over a fixed slot batch.

    ``batch_slots`` and ``max_len`` are static (one compiled program);
    prompts are padded to power-of-two buckets so prefill compiles a small
    number of variants.
    """

    def __init__(self, config: LlamaConfig, batch_slots: int = 8,
                 max_len: Optional[int] = None,
                 model: Optional[LlamaModel] = None,
                 kv_block: Optional[int] = None,
                 kv_blocks: Optional[int] = None,
                 spec_tokens: Optional[int] = None,
                 kv_dtype: Optional[str] = None):
        """``kv_block`` ($SKYTPU_KV_BLOCK, default 64; 0 = contiguous):
        rows per KV block. Paged mode replaces the per-slot contiguous
        [max_len] KV region with a global pool of ``kv_blocks`` blocks
        ($SKYTPU_KV_BLOCKS, default batch_slots * ceil(max_len/block)
        + null block — the contiguous layout's HBM budget) addressed
        through per-slot block tables, so a slot only consumes the
        blocks its sequence actually fills and full prefix blocks can
        be shared across slots. The contiguous path stays selectable as
        the equivalence oracle and for microbench A/Bs.

        ``spec_tokens`` ($SKYTPU_SPEC_TOKENS, default 4; 0 = plain
        one-token steps): max draft tokens per ``step_verify`` dispatch.
        It only gates the CALLER (the scheduler reads it to decide
        whether to draft); ``step_verify`` itself accepts any [B, K]
        draft, one compiled variant per K.

        ``kv_dtype`` ($SKYTPU_KV_DTYPE, default 'bf16'): KV storage
        dtype. 'int8' stores absmax-quantized rows + f32 per-row
        scales — half the KV bytes per token, so the same HBM budget
        holds twice the blocks. Requires paged mode: the contiguous
        layout is the bit-identity oracle and is rejected with int8.
        """
        self.config = config
        # Engine reuses the model's block methods (_qkv/_mlp_delta) so the
        # transformer math lives once; pass a MixtralModel to serve MoE.
        self.model = model or LlamaModel(config)
        self.batch_slots = batch_slots
        self.max_len = max_len or config.max_seq_len
        if kv_block is None:
            kv_block = env_vars.get_int('SKYTPU_KV_BLOCK')
        self.kv_block = max(0, int(kv_block))
        self.paged = self.kv_block > 0
        if kv_dtype is None:
            kv_dtype = env_vars.get('SKYTPU_KV_DTYPE') or 'bf16'
        if kv_dtype not in KV_DTYPES:
            raise ValueError(f'SKYTPU_KV_DTYPE must be one of '
                             f'{KV_DTYPES}, got {kv_dtype!r}')
        if kv_dtype == 'int8' and not self.paged:
            raise ValueError(
                'SKYTPU_KV_DTYPE=int8 requires the paged KV layout '
                '(SKYTPU_KV_BLOCK > 0): the contiguous kv_block=0 path '
                'is the bit-identity equivalence oracle and stays '
                'bf16')
        self.kv_dtype = kv_dtype
        # Host-side Python flag: bf16 mode traces EXACTLY the
        # pre-quantization program (bit-identical streams, no new
        # compiles); int8 mode swaps in quantized writes + dequantizing
        # gathers at trace time.
        self.quantized = kv_dtype == 'int8'
        if self.paged:
            self.max_blocks = -(-self.max_len // self.kv_block)
            # Gathered per-slot view length; >= max_len when max_len is
            # not a block multiple (the overhang is always masked).
            self.m_pad = self.max_blocks * self.kv_block
            if kv_blocks is None:
                kv_blocks = env_vars.get_int('SKYTPU_KV_BLOCKS') or None
            if kv_blocks is None:
                kv_blocks = batch_slots * self.max_blocks + 1
            self.kv_blocks = max(int(kv_blocks), 2)
            self.allocator = paged_kv.BlockAllocator(
                self.kv_blocks, self.kv_block, reserved=1)
            # Legacy-API convenience: slots driven without an explicit
            # table (tests, bench microloops) get a full-capacity
            # assignment on first touch, cached so the same slot always
            # maps the same ids (deterministic across engines).
            self._auto_tables: Dict[int, jax.Array] = {}
        else:
            self.max_blocks = 0
            self.m_pad = self.max_len
            self.kv_blocks = 0
            self.allocator = None
            self._auto_tables = {}
        self._prefill = jax.jit(self._prefill_impl)
        self._prefill_chunk = jax.jit(self._prefill_chunk_impl,
                                      donate_argnums=(0,))
        self._prefill_chunk_final = jax.jit(self._prefill_chunk_final_impl,
                                            donate_argnums=(0,))
        self._insert = jax.jit(self._insert_impl, donate_argnums=(0,))
        self._admit = jax.jit(self._admit_impl, donate_argnums=(0,))
        self._admit_many = jax.jit(self._admit_many_impl,
                                   donate_argnums=(0,))
        if spec_tokens is None:
            spec_tokens = env_vars.get_int('SKYTPU_SPEC_TOKENS')
        self.spec_tokens = max(0, int(spec_tokens))
        # temperature/top_k are *traced* [B] args — any per-request sampling
        # settings reuse the one compiled step (no recompile DoS).
        self._step = jax.jit(self._step_impl, donate_argnums=(1,))
        self._step_verify = jax.jit(self._step_verify_impl,
                                    donate_argnums=(1,))
        self._release = jax.jit(self._release_impl, donate_argnums=(0,))
        self._sample_one = jax.jit(self._sample_one_impl)
        # Scalar sampling settings -> cached device [B] arrays. Building
        # them per step() call runs eager asarray+broadcast_to ops — on a
        # high-latency tunneled link those are extra device dispatches
        # per decoded token, which quietly multiplied step latency ~4x in
        # the round-4 standalone decode bench. Callers passing scalars
        # must hit this cache; only genuinely per-slot arrays trace new.
        # LRU-bounded: the settings are CLIENT-supplied, so an unbounded
        # dict is a slow device-memory leak under adversarial traffic.
        self._scalar_sampling_cache: 'OrderedDict' = OrderedDict()
        # Step profiling (skytpu_engine_* series). None when metrics are
        # disabled: every instrumentation site below is ONE branch.
        self.profiler = (StepProfiler()
                         if metrics_lib.enabled() else None)
        if self.profiler is not None:
            self.profiler.note_kv_config(self.kv_dtype,
                                         self.kv_bytes_per_token())
        # End timestamp of the last step dispatch — the step-gap
        # histogram's anchor. None across idle periods (see
        # note_dispatch_break) so the first step after a lull measures
        # host overhead, not the lull.
        self._last_dispatch_end: Optional[float] = None

    def note_dispatch_break(self) -> None:
        """Caller (the scheduler) is about to wait for work: break the
        step-gap chain so the idle wait is not recorded as a gap."""
        self._last_dispatch_end = None

    # -- state --------------------------------------------------------------
    def init_state(self) -> DecodeState:
        c = self.config
        b = self.batch_slots
        if self.paged:
            shape = (c.num_layers, self.kv_blocks, c.num_kv_heads,
                     self.kv_block, c.head_dim)
            tables = jnp.zeros((b, self.max_blocks), jnp.int32)
        else:
            shape = (c.num_layers, b, c.num_kv_heads, self.max_len,
                     c.head_dim)
            tables = jnp.zeros((b, 0), jnp.int32)
        if self.quantized:
            pool_dtype = jnp.int8
            # One f32 scale per (layer, block, kv-head, row): the pool
            # layout minus head_dim.
            scale_shape = (c.num_layers, self.kv_blocks,
                           c.num_kv_heads, self.kv_block)
        else:
            pool_dtype = c.dtype
            scale_shape = (0,)  # placeholder, never read (cf. [B, 0])
        return DecodeState(
            k=jnp.zeros(shape, pool_dtype),
            v=jnp.zeros(shape, pool_dtype),
            lengths=jnp.zeros((b,), jnp.int32),
            last_tokens=jnp.zeros((b,), jnp.int32),
            active=jnp.zeros((b,), bool),
            block_tables=tables,
            k_scale=jnp.zeros(scale_shape, jnp.float32),
            v_scale=jnp.zeros(scale_shape, jnp.float32),
        )

    def kv_bytes_per_token(self) -> int:
        """HBM bytes one cached token row costs across all layers (K
        and V, scales included) — the capacity denominator: pool bytes
        / this = token capacity, and the dashboard's "KV bytes/tok"."""
        c = self.config
        if self.quantized:
            per_head = c.head_dim * 1 + 4  # int8 codes + one f32 scale
        else:
            per_head = c.head_dim * jnp.dtype(c.dtype).itemsize
        return 2 * c.num_layers * c.num_kv_heads * per_head

    def hbm_ledger(self, state: DecodeState,
                   params: Optional[Params] = None) -> Dict[str, int]:
        """Device-memory accounting table (component -> bytes).

        Every entry is computed from shape metadata (``.nbytes`` reads
        the aval, never device buffers), so the ledger is safe to build
        while the async runtime holds donated state in flight. The KV
        entries are exact by construction — ``kv_code_pool +
        kv_scale_pool == kv_bytes_per_token() * kv_block * kv_blocks``
        in paged mode for both bf16 and int8 (tier-1 pinned) — and
        ``weights`` sums the param tree when the caller holds one.
        ``spec_buffers`` is the per-dispatch draft+verify token I/O
        ([B, 1+K] int32 in and out) — the only persistent spec-path
        device footprint beyond the KV rows already in the pool.
        """
        ledger: Dict[str, int] = {}
        if params is not None:
            ledger['weights'] = sum(
                leaf.nbytes for leaf in jax.tree_util.tree_leaves(params))
        ledger['kv_code_pool'] = state.k.nbytes + state.v.nbytes
        ledger['kv_scale_pool'] = (state.k_scale.nbytes
                                   + state.v_scale.nbytes)
        ledger['spec_buffers'] = (
            2 * self.batch_slots * (1 + self.spec_tokens) * 4
            if self.spec_tokens else 0)
        return ledger

    def hbm_block_stats(self) -> Dict[str, float]:
        """Block-pool utilization/fragmentation companion to the byte
        ledger (paged mode; empty dict otherwise). Fragmentation here
        is the share of pool bytes parked in free-but-resident blocks
        (incl. LRU-cached prefix blocks awaiting reuse or eviction)."""
        if not self.paged:
            return {}
        stats = self.allocator.stats()
        total = max(1, stats['kv_blocks_total'])
        block_bytes = self.kv_bytes_per_token() * self.kv_block
        return {
            'kv_block_bytes': block_bytes,
            'kv_used_bytes': stats['kv_blocks_used'] * block_bytes,
            'kv_free_bytes': stats['kv_blocks_free'] * block_bytes,
            'kv_block_utilization': stats['kv_block_utilization'],
            'kv_fragmentation_ratio': stats['kv_blocks_free'] / total,
        }

    def observe_kv_scales(self, state: DecodeState, cap: int = 512) -> None:
        """Sample current k-scales into the quant-scale histogram
        (scrape-time, int8 mode only). Layer 0 only and capped: this is
        an accuracy canary, not an exhaustive dump. Best-effort — the
        async runtime may have donated ``state``'s buffers to an
        in-flight step, in which case reading them raises and the
        scrape simply skips this sample."""
        if not self.quantized or self.profiler is None:
            return
        try:
            scales = jax.device_get(state.k_scale[0])
        except (RuntimeError, ValueError):
            return
        flat = scales.reshape(-1)
        nz = flat[flat > 0.0][:cap]
        for s in nz:
            self.profiler.kv_quant_scale.observe(float(s))

    # -- roofline attribution ------------------------------------------------
    # Variant kinds with a cost model: the forward-pass dispatches whose
    # FLOPs/bytes place serving on the roofline. Admission/insert/release
    # scatters are bookkeeping, not modeled.
    ROOFLINE_KINDS = ('prefill', 'prefill_chunk', 'prefill_chunk_final',
                      'step', 'step_verify')

    def estimate_step_cost(self, kind: str, *shape) -> Tuple[float, float]:
        """Analytic (FLOPs, HBM bytes) for ONE dispatch of a jit step
        variant — the ``cost_analysis`` fallback, from config dims only.

        FLOPs (matmul MACs x 2, the standard accounting):
          - layer matmuls: ``2 * P_layers * T`` (qkv + o + SwiGLU mlp
            weights, T = token rows computed, PADDED — what the
            compiled program runs, active or not);
          - lm head: ``2 * E * V * T_logits`` (every row in
            decode/verify; only the last row in prefill kinds);
          - attention: ``4 * L * Hq * d * T * M`` (QK^T and AV, each
            ``2 * M * d`` MACs per query row, over the padded context
            M — decode attends through the full gathered table).
        Bytes: the whole weight tree once per dispatch, plus KV rows
        gathered (S sequences x M padded rows) and the T rows written,
        at the pool's per-token footprint (int8 halves it). Activations
        are ignored: orders of magnitude below weights+KV at serving
        shapes.
        """
        c = self.config
        b = self.batch_slots
        if kind == 'step':
            t, t_logits, seqs, m = b, b, b, self.m_pad
        elif kind == 'step_verify':
            k = int(shape[1]) if len(shape) > 1 else self.spec_tokens
            t, t_logits, seqs, m = b * (1 + k), b * (1 + k), b, self.m_pad
        elif kind in ('prefill_chunk', 'prefill_chunk_final'):
            t, t_logits, seqs, m = int(shape[0]), 1, 1, self.m_pad
        elif kind == 'prefill':
            t, t_logits, seqs, m = int(shape[0]), 1, 1, int(shape[0])
        else:
            raise ValueError(f'no cost model for variant kind {kind!r}')
        qkv = c.embed_dim * c.head_dim * (c.num_heads
                                          + 2 * c.num_kv_heads)
        proj = c.num_heads * c.head_dim * c.embed_dim
        mlp = 3 * c.embed_dim * c.mlp_dim
        p_layers = c.num_layers * (qkv + proj + mlp)
        flops = (2.0 * p_layers * t
                 + 2.0 * c.embed_dim * c.vocab_size * t_logits
                 + 4.0 * c.num_layers * c.num_heads * c.head_dim * t * m)
        param_bytes = c.num_params * jnp.dtype(c.dtype).itemsize
        kv_bytes = self.kv_bytes_per_token() * (seqs * m + t)
        return flops, float(param_bytes + kv_bytes)

    @staticmethod
    def _xla_cost(lowered) -> Optional[Tuple[float, float]]:
        """(flops, bytes accessed) from XLA's own cost model, or None
        when the backend doesn't expose one. The compiled analysis is
        preferred (it has the real buffer assignment); the pre-compile
        HLO analysis is the second chance. Both APIs vary by backend
        and JAX version — dict or [dict] — hence the broad guards."""
        for get in (lambda: lowered.compile().cost_analysis(),
                    lambda: lowered.cost_analysis()):
            try:
                analysis = get()
            except Exception:  # noqa: BLE001 — backend-dependent API
                continue
            if isinstance(analysis, (list, tuple)):
                analysis = analysis[0] if analysis else None
            if not isinstance(analysis, dict):
                continue
            flops = float(analysis.get('flops') or 0.0)
            nbytes = float(analysis.get('bytes accessed') or 0.0)
            if flops > 0.0:
                return flops, nbytes
        return None

    def roofline_costs(self, params: Params, state: DecodeState,
                       rng: Optional[jax.Array] = None
                       ) -> Dict[str, Tuple[float, float]]:
        """(FLOPs, bytes) per compiled step variant, keyed by
        :meth:`StepProfiler.variant_label` — XLA's cost model when the
        backend exposes one, the analytic estimator otherwise (bytes
        fall back independently: some backends report flops but zero
        bytes). Covers exactly the variants warmup compiled (the
        profiler's first-seen set); re-lowering them is warmup-time
        work, never on the step path."""
        if rng is None:
            rng = jax.random.PRNGKey(0)
        b = self.batch_slots
        temp = jnp.zeros((b,), jnp.float32)
        topk = jnp.zeros((b,), jnp.int32)
        table = jnp.zeros((self.max_blocks,), jnp.int32)
        zero = jnp.int32(0)
        variants = []
        if self.profiler is not None:
            variants = [key for key in self.profiler._seen_variants
                        if key[0] in self.ROOFLINE_KINDS]
        if not variants:
            # Engine costed before any traffic/warmup: the decode core.
            variants = [('step', b)]
            if self.spec_tokens > 0:
                variants.append(('step_verify', b, self.spec_tokens))
        costs: Dict[str, Tuple[float, float]] = {}
        for key in sorted(variants, key=str):
            kind, shape = key[0], key[1:]
            try:
                if kind == 'step':
                    lowered = self._step.lower(params, state, rng, temp,
                                               topk)
                elif kind == 'step_verify':
                    draft = jnp.zeros((b, int(shape[1])), jnp.int32)
                    lowered = self._step_verify.lower(
                        params, state, rng, temp, topk, draft)
                elif kind == 'prefill':
                    lowered = self._prefill.lower(
                        params, jnp.zeros((int(shape[0]),), jnp.int32),
                        jnp.int32(1))
                elif kind == 'prefill_chunk':
                    lowered = self._prefill_chunk.lower(
                        state, params,
                        jnp.zeros((int(shape[0]),), jnp.int32),
                        zero, zero, table)
                else:  # prefill_chunk_final
                    lowered = self._prefill_chunk_final.lower(
                        state, params,
                        jnp.zeros((int(shape[0]),), jnp.int32),
                        zero, zero, jnp.int32(1), rng,
                        jnp.float32(0.0), jnp.int32(0), table)
                xla = self._xla_cost(lowered)
            except Exception:  # noqa: BLE001 — lowering is best-effort
                xla = None
            flops, nbytes = self.estimate_step_cost(kind, *shape)
            if xla is not None:
                flops = xla[0]
                if xla[1] > 0.0:
                    nbytes = xla[1]
            costs[StepProfiler.variant_label(kind, *shape)] = (flops,
                                                               nbytes)
        return costs

    # -- paged-KV host-side helpers -----------------------------------------
    def _table_arg(self, slot: Optional[int],
                   table_row) -> jax.Array:
        """Device table row for an admission-path dispatch: the caller's
        explicit assignment (scheduler), the slot's cached auto
        assignment (legacy API), or an empty placeholder (contiguous)."""
        if not self.paged:
            return jnp.zeros((0,), jnp.int32)
        if table_row is not None:
            row = jnp.asarray(table_row, jnp.int32)
            if row.shape != (self.max_blocks,):
                raise ValueError(f'table row must be [{self.max_blocks}]'
                                 f', got {row.shape}')
            return row
        return self._auto_table(slot)

    def _auto_table(self, slot: int) -> jax.Array:
        """Full-capacity block assignment for ``slot``, allocated once
        and reused (so repeated admissions into one slot — and the same
        admission order on two engines — map identical physical ids)."""
        row = self._auto_tables.get(slot)
        if row is None:
            ids = self.allocator.alloc(self.max_blocks)
            if ids is None:
                raise RuntimeError(
                    f'KV pool exhausted auto-assigning slot {slot}: '
                    f'{self.allocator.available()} of '
                    f'{self.allocator.capacity} blocks free')
            row = jnp.asarray(ids, jnp.int32)
            self._auto_tables[slot] = row
        return row

    def free_auto_tables(self) -> None:
        """Release every auto-assigned slot's blocks back to the pool
        (a scheduler that takes over explicit block management calls
        this after warmup so auto assignments don't pin the pool)."""
        if not self.paged:
            return
        for row in self._auto_tables.values():
            self.allocator.deref([int(b) for b in row])
        self._auto_tables.clear()

    def reset_kv(self) -> None:
        """Forget all host-side block state (crash recovery, paired with
        a fresh ``init_state``)."""
        if self.paged:
            self.allocator.reset()
            self._auto_tables.clear()

    def _gather_slot(self, pool_layer: jax.Array,
                     table_row: jax.Array,
                     scale_layer: Optional[jax.Array] = None
                     ) -> jax.Array:
        """[NB, kvh, BS, d] pool gathered through [nb] -> [kvh, M, d].

        With ``scale_layer`` ([NB, kvh, BS] f32, int8 mode) the rows
        dequantize in the gather: int8 -> f32 x per-row scale, so
        attention sees f32 values and accumulates scores in f32."""
        g = pool_layer[table_row]           # [nb, kvh, BS, d]
        if scale_layer is not None:
            s = scale_layer[table_row]      # [nb, kvh, BS]
            g = dequantize_kv_rows(g, s)
        g = g.transpose(1, 0, 2, 3)         # [kvh, nb, BS, d]
        return g.reshape(g.shape[0], -1, g.shape[3])

    def _gather_batch(self, pool_layer: jax.Array,
                      tables: jax.Array,
                      scale_layer: Optional[jax.Array] = None
                      ) -> jax.Array:
        """[NB, kvh, BS, d] pool gathered through [B, nb] ->
        [B, kvh, M, d] — the paged decode read: per (slot, kv-head) the
        rows land in table order, so downstream attention is identical
        to the contiguous layout's. ``scale_layer`` dequantizes as in
        :meth:`_gather_slot`."""
        g = pool_layer[tables]              # [B, nb, kvh, BS, d]
        if scale_layer is not None:
            s = scale_layer[tables]         # [B, nb, kvh, BS]
            g = dequantize_kv_rows(g, s)
        g = g.transpose(0, 2, 1, 3, 4)      # [B, kvh, nb, BS, d]
        return g.reshape(g.shape[0], g.shape[1], -1, g.shape[4])

    # -- prefill ------------------------------------------------------------
    def prefill(self, params: Params, tokens: jax.Array,
                true_len: int) -> Tuple[jax.Array, jax.Array, jax.Array]:
        """Run a single prompt [T_padded] through the model.

        Returns (k [L, kvh, T_padded, d], v, last_logits [V]) — KV already
        in the cache's head-major layout. End-padding is
        benign under causal attention; the garbage keys past ``true_len``
        are masked out at decode time by the slot length. The caller samples
        the FIRST generated token from ``last_logits`` (that token is the
        TTFT token) and feeds it to ``insert`` as ``last_token``.
        """
        if self.profiler is not None:
            self.profiler.note_variant('prefill', tokens.shape[0])
            self.profiler.prefill_tokens.inc(tokens.shape[0])
        return self._prefill(params, tokens,
                             jnp.asarray(true_len, jnp.int32))

    def _prefill_impl(self, params, tokens, true_len):
        c = self.config
        t = tokens.shape[0]
        positions = jnp.arange(t)
        cos, sin = precompute_rotary(c.head_dim, c.max_seq_len, c.rope_theta)
        x = params['embed'][tokens][None].astype(c.dtype)  # [1, T, e]
        model = self.model

        def layer(x, lp):
            q, k, v = model._qkv(lp, x, cos, sin, positions, constrain=False)
            attn = attention_ops.attention(q, k, v, causal=True)
            x = x + jnp.einsum('bshd,hde->bse', attn, lp['wo'])
            x = x + model._mlp_delta(lp, x, constrain=False)[0]
            # [T, kvh, d] -> [kvh, T, d]: the cache's head-major layout.
            return x, (k[0].transpose(1, 0, 2), v[0].transpose(1, 0, 2))

        x, (ks, vs) = lax.scan(layer, x, params['layers'])
        x = rms_norm(x, params['final_norm'], c.norm_eps)
        head = (params['embed'].T if c.tie_embeddings else params['lm_head'])
        # Logits only for the last real token — avoids the [T, V] matmul.
        last = x[0, true_len - 1].astype(jnp.float32)
        logits = last @ head.astype(jnp.float32)
        return ks, vs, logits

    # -- chunked prefill ----------------------------------------------------
    def prefill_chunk(self, params: Params, state: DecodeState,
                      tokens: jax.Array, offset, slot,
                      table_row=None) -> DecodeState:
        """Run ONE prompt chunk [C] at cache ``offset`` of ``slot``,
        writing its KV rows in place (donated state, one dispatch).

        Unlike monolithic ``prefill`` — which stalls every occupied decode
        slot for the full prompt length — a chunk dispatch is short, so
        the scheduler can interleave ``step`` dispatches between chunks
        (Sarathi-style piggybacked prefill). The chunk's queries attend
        to the slot's already-written prefix rows [0, offset) plus the
        chunk itself under a causal mask; rows past the chunk are masked,
        so stale cache contents cannot leak in. The slot stays INACTIVE
        (lengths 0) until the final chunk commits it, so concurrent
        decode steps skip it.

        Paged mode: rows are written through ``table_row`` (explicit
        scheduler assignment, or the slot's auto assignment). A nonzero
        ``offset`` whose leading blocks came from the prefix cache skips
        their prefill entirely — the chunk's queries attend to the
        SHARED blocks through the table."""
        if self.profiler is not None:
            self.profiler.note_variant('prefill_chunk', tokens.shape[0])
            self.profiler.prefill_tokens.inc(tokens.shape[0])
        return self._prefill_chunk(state, params, tokens,
                                   jnp.asarray(offset, jnp.int32),
                                   jnp.asarray(slot, jnp.int32),
                                   self._table_arg(slot, table_row))

    def prefill_chunk_final(self, params: Params, state: DecodeState,
                            tokens: jax.Array, offset, slot, true_len,
                            rng: jax.Array, temperature: float = 0.0,
                            top_k: int = 0, table_row=None
                            ) -> Tuple[DecodeState, jax.Array, jax.Array]:
        """Final chunk: forward + first-token sample + slot activation in
        ONE dispatch (the chunked counterpart of fused ``admit``).
        Returns (state, first_token, next_rng). ``true_len`` is the FULL
        prompt length; the chunk's padding past ``true_len - offset`` is
        benign (garbage rows are masked by the slot length, exactly like
        monolithic end-padding)."""
        if self.profiler is not None:
            self.profiler.note_variant('prefill_chunk_final',
                                       tokens.shape[0])
            self.profiler.prefill_tokens.inc(tokens.shape[0])
        return self._prefill_chunk_final(
            state, params, tokens, jnp.asarray(offset, jnp.int32),
            jnp.asarray(slot, jnp.int32),
            jnp.asarray(true_len, jnp.int32), rng,
            jnp.float32(temperature), jnp.int32(top_k),
            self._table_arg(slot, table_row))

    def _chunk_forward(self, state, params, tokens, offset, slot, table):
        """Shared traced body: chunk forward over prefix KV + in-place
        cache writes. Returns (x [1, C, e] final hidden, new_k, new_v)."""
        c = self.config
        t = tokens.shape[0]
        grp = c.num_heads // c.num_kv_heads
        positions = offset + jnp.arange(t)  # [C] absolute positions
        cos, sin = precompute_rotary(c.head_dim, c.max_seq_len, c.rope_theta)
        x = params['embed'][tokens][None].astype(c.dtype)  # [1, C, e]
        kv_pos = jnp.arange(self.m_pad)
        # [C, M]: a chunk query at absolute position p sees kv rows <= p —
        # the prompt's own prefix chunks plus the causal part of this one.
        valid = kv_pos[None, :] <= positions[:, None]
        model = self.model
        if self.paged:
            # Per-row physical addresses for the chunk's writes.
            blk = table[positions // self.kv_block]   # [C]
            row = positions % self.kv_block           # [C]
            kv_heads = jnp.arange(c.num_kv_heads)

        def layer(carry, inputs):
            if self.quantized:
                x, cache_k, cache_v, scale_k, scale_v = carry
            else:
                x, cache_k, cache_v = carry
                scale_k = scale_v = None
            lp, i = inputs
            q, k, v = model._qkv(lp, x, cos, sin, positions, constrain=False)
            if self.quantized:
                # Quantize the chunk's [C, kvh, d] rows and scatter the
                # int8 codes + [C, kvh] scales through the SAME block-
                # table addresses (in-place on the donated carry).
                qk, sk = quantize_kv_rows(k[0])
                qv, sv = quantize_kv_rows(v[0])
                cache_k = cache_k.at[i, blk[:, None], kv_heads[None, :],
                                     row[:, None]].set(qk)
                cache_v = cache_v.at[i, blk[:, None], kv_heads[None, :],
                                     row[:, None]].set(qv)
                scale_k = scale_k.at[i, blk[:, None], kv_heads[None, :],
                                     row[:, None]].set(sk)
                scale_v = scale_v.at[i, blk[:, None], kv_heads[None, :],
                                     row[:, None]].set(sv)
                k_slot = self._gather_slot(cache_k[i], table,
                                           scale_k[i])  # [kvh, M, d] f32
                v_slot = self._gather_slot(cache_v[i], table,
                                           scale_v[i])
            elif self.paged:
                # Scatter the chunk's [C, kvh, d] rows through the block
                # table (in-place on the donated carry).
                cache_k = cache_k.at[i, blk[:, None], kv_heads[None, :],
                                     row[:, None]].set(
                    k[0].astype(cache_k.dtype))
                cache_v = cache_v.at[i, blk[:, None], kv_heads[None, :],
                                     row[:, None]].set(
                    v[0].astype(cache_v.dtype))
                k_slot = self._gather_slot(cache_k[i], table)  # [kvh,M,d]
                v_slot = self._gather_slot(cache_v[i], table)
            else:
                # [1, C, kvh, d] -> [1, 1, kvh, C, d]: one contiguous
                # write at (layer i, slot, :, offset) head-major.
                kf = k[0].transpose(1, 0, 2)[None, None]
                vf = v[0].transpose(1, 0, 2)[None, None]
                cache_k = lax.dynamic_update_slice(
                    cache_k, kf.astype(cache_k.dtype),
                    (i, slot, 0, offset, 0))
                cache_v = lax.dynamic_update_slice(
                    cache_v, vf.astype(cache_v.dtype),
                    (i, slot, 0, offset, 0))
                k_slot = cache_k[i, slot]  # [kvh, M, d]
                v_slot = cache_v[i, slot]
            # Grouped-query attention over the slot's cache rows, same
            # contiguous-[M, d] streaming pattern as the decode step.
            qg = q[0].reshape(t, c.num_kv_heads, grp, c.head_dim)
            s = jnp.einsum('ckgd,kmd->ckgm', qg, k_slot,
                           preferred_element_type=jnp.float32)
            s = s * (c.head_dim**-0.5)
            s = jnp.where(valid[:, None, None, :], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            attn = jnp.einsum('ckgm,kmd->ckgd', p.astype(c.dtype), v_slot,
                              preferred_element_type=jnp.float32)
            attn = attn.reshape(1, t, c.num_heads,
                                c.head_dim).astype(c.dtype)
            x = x + jnp.einsum('bshd,hde->bse', attn, lp['wo'])
            x = x + model._mlp_delta(lp, x, constrain=False)[0]
            if self.quantized:
                return (x, cache_k, cache_v, scale_k, scale_v), None
            return (x, cache_k, cache_v), None

        if self.quantized:
            (x, new_k, new_v, new_sk, new_sv), _ = lax.scan(
                layer, (x, state.k, state.v, state.k_scale,
                        state.v_scale),
                (params['layers'], jnp.arange(c.num_layers)))
        else:
            (x, new_k, new_v), _ = lax.scan(
                layer, (x, state.k, state.v),
                (params['layers'], jnp.arange(c.num_layers)))
            new_sk, new_sv = state.k_scale, state.v_scale
        return x, new_k, new_v, new_sk, new_sv

    def _tables_with(self, state, slot, table) -> jax.Array:
        """state.block_tables with ``slot``'s row set (paged only)."""
        if not self.paged:
            return state.block_tables
        return state.block_tables.at[slot].set(table)

    def _prefill_chunk_impl(self, state, params, tokens, offset, slot,
                            table):
        _, new_k, new_v, new_sk, new_sv = self._chunk_forward(
            state, params, tokens, offset, slot, table)
        return DecodeState(k=new_k, v=new_v, lengths=state.lengths,
                           last_tokens=state.last_tokens,
                           active=state.active,
                           block_tables=self._tables_with(state, slot,
                                                          table),
                           k_scale=new_sk, v_scale=new_sv)

    def _prefill_chunk_final_impl(self, state, params, tokens, offset,
                                  slot, true_len, rng, temperature, top_k,
                                  table):
        c = self.config
        x, new_k, new_v, new_sk, new_sv = self._chunk_forward(
            state, params, tokens, offset, slot, table)
        x = rms_norm(x, params['final_norm'], c.norm_eps)
        head = (params['embed'].T if c.tie_embeddings else params['lm_head'])
        # Logits only for the prompt's last REAL token (chunk-relative).
        last = x[0, true_len - 1 - offset].astype(jnp.float32)
        logits = last @ head.astype(jnp.float32)
        rng, sub = jax.random.split(rng)
        first = _sample(logits[None], sub, temperature, top_k)[0]
        return DecodeState(
            k=new_k, v=new_v,
            lengths=state.lengths.at[slot].set(true_len),
            last_tokens=state.last_tokens.at[slot].set(first),
            active=state.active.at[slot].set(True),
            block_tables=self._tables_with(state, slot, table),
            k_scale=new_sk, v_scale=new_sv,
        ), first, rng

    # -- insert -------------------------------------------------------------
    def insert(self, state: DecodeState, k: jax.Array, v: jax.Array,
               true_len: jax.Array, last_token: jax.Array,
               slot: jax.Array, table_row=None) -> DecodeState:
        """Write a prefilled prompt's KV into ``slot`` and mark it active."""
        return self._insert(state, k, v, jnp.asarray(true_len, jnp.int32),
                            jnp.asarray(last_token, jnp.int32),
                            jnp.asarray(slot, jnp.int32),
                            self._table_arg(slot, table_row))

    def _insert_impl(self, state, k, v, true_len, last_token, slot,
                     table):
        t = k.shape[2]
        pad_m = self.max_len - t
        if pad_m < 0:
            raise ValueError(f'prefill length {t} exceeds max_len '
                             f'{self.max_len}')
        new_sk, new_sv = state.k_scale, state.v_scale
        if self.paged:
            # Scatter the T rows through the block table. Rows past the
            # table's assignment hit the null block (index 0) — garbage
            # there is never read unmasked.
            pos = jnp.arange(t)
            blk = table[pos // self.kv_block]
            row = pos % self.kv_block
            kv_heads = jnp.arange(self.config.num_kv_heads)
            vals_k = k.transpose(0, 2, 1, 3)  # [L, T, kvh, d]
            vals_v = v.transpose(0, 2, 1, 3)
            if self.quantized:
                # Codes + [L, T, kvh] scales land through the same
                # addresses the row scatter uses.
                vals_k, sk = quantize_kv_rows(vals_k)
                vals_v, sv = quantize_kv_rows(vals_v)
                new_sk = state.k_scale.at[:, blk[:, None],
                                          kv_heads[None, :],
                                          row[:, None]].set(sk)
                new_sv = state.v_scale.at[:, blk[:, None],
                                          kv_heads[None, :],
                                          row[:, None]].set(sv)
            new_k = state.k.at[:, blk[:, None], kv_heads[None, :],
                               row[:, None]].set(
                vals_k.astype(state.k.dtype))
            new_v = state.v.at[:, blk[:, None], kv_heads[None, :],
                               row[:, None]].set(
                vals_v.astype(state.v.dtype))
        else:
            # [L, kvh, T, d] -> [L, 1, kvh, M, d] zero-extended, then one
            # dynamic_update_slice into the stacked cache (in-place:
            # donated).
            kf = jnp.pad(k, ((0, 0), (0, 0), (0, pad_m), (0, 0)))[:, None]
            vf = jnp.pad(v, ((0, 0), (0, 0), (0, pad_m), (0, 0)))[:, None]
            new_k = lax.dynamic_update_slice(
                state.k, kf.astype(state.k.dtype), (0, slot, 0, 0, 0))
            new_v = lax.dynamic_update_slice(
                state.v, vf.astype(state.v.dtype), (0, slot, 0, 0, 0))
        return DecodeState(
            k=new_k, v=new_v,
            lengths=state.lengths.at[slot].set(true_len),
            last_tokens=state.last_tokens.at[slot].set(last_token),
            active=state.active.at[slot].set(True),
            block_tables=self._tables_with(state, slot, table),
            k_scale=new_sk, v_scale=new_sv,
        )

    def admit(self, params: Params, state: DecodeState, tokens: jax.Array,
              true_len: int, slot: int, rng: jax.Array,
              temperature: float = 0.0, top_k: int = 0, table_row=None
              ) -> Tuple[DecodeState, jax.Array, jax.Array]:
        """Fused prefill + first-token sample + insert: ONE device
        dispatch per admission. Returns (state, first_token, next_rng).

        The unfused path (prefill -> sample_first -> insert) materializes
        the [L, kvh, T, d] prefill KV in HBM and costs 3-4 dispatches;
        under serving load admission competes with decode steps for the
        chip, so admission overhead directly gates req/s.
        """
        if self.profiler is not None:
            self.profiler.note_variant('admit', tokens.shape[0])
            self.profiler.prefill_tokens.inc(tokens.shape[0])
        return self._admit(state, params, tokens,
                           jnp.asarray(true_len, jnp.int32),
                           jnp.asarray(slot, jnp.int32), rng,
                           jnp.float32(temperature), jnp.int32(top_k),
                           self._table_arg(slot, table_row))

    def _admit_impl(self, state, params, tokens, true_len, slot, rng,
                    temperature, top_k, table):
        ks, vs, logits = self._prefill_impl(params, tokens, true_len)
        rng, sub = jax.random.split(rng)
        first = _sample(logits[None], sub, temperature, top_k)[0]
        new_state = self._insert_impl(state, ks, vs, true_len, first, slot,
                                      table)
        return new_state, first, rng

    def admit_many(self, params: Params, state: DecodeState,
                   tokens: jax.Array, true_lens, slots, rng: jax.Array,
                   temperatures, top_ks, table_rows=None
                   ) -> Tuple[DecodeState, jax.Array, jax.Array]:
        """Fused BATCHED prefill + first-token sample + insert for N
        same-bucket prompts: ONE device dispatch admits all of them.
        Returns (state, first_tokens [N], next_rng).

        Why this exists beyond ``admit``: a thundering herd of arrivals
        (closed-loop serving waves) admits back-to-back, and each admit
        is a full dispatch round-trip; batching divides those RTTs by N
        and streams each layer's weights once for N prompts instead of
        N times. Compile variants are (N, bucket) pairs — the scheduler
        caps N (ADMIT_BATCH_MAX) and groups same-bucket prompts only.
        """
        if self.profiler is not None:
            self.profiler.note_variant('admit_many', tokens.shape)
            self.profiler.prefill_tokens.inc(
                tokens.shape[0] * tokens.shape[1])
        if not self.paged:
            tables = jnp.zeros((tokens.shape[0], 0), jnp.int32)
        elif table_rows is not None:
            tables = jnp.asarray(table_rows, jnp.int32)
        else:
            tables = jnp.stack([self._table_arg(int(s), None)
                                for s in slots])
        return self._admit_many(
            state, params, tokens,
            jnp.asarray(true_lens, jnp.int32),
            jnp.asarray(slots, jnp.int32), rng,
            jnp.asarray(temperatures, jnp.float32),
            jnp.asarray(top_ks, jnp.int32), tables)

    def _admit_many_impl(self, state, params, tokens, true_lens, slots,
                         rng, temperatures, top_ks, tables):
        c = self.config
        n, t = tokens.shape
        positions = jnp.arange(t)
        cos, sin = precompute_rotary(c.head_dim, c.max_seq_len, c.rope_theta)
        x = params['embed'][tokens].astype(c.dtype)  # [N, T, e]
        model = self.model

        def layer(x, lp):
            q, k, v = model._qkv(lp, x, cos, sin, positions, constrain=False)
            attn = attention_ops.attention(q, k, v, causal=True)
            x = x + jnp.einsum('bshd,hde->bse', attn, lp['wo'])
            x = x + model._mlp_delta(lp, x, constrain=False)[0]
            # [N, T, kvh, d] -> [N, kvh, T, d]: the cache's head-major
            # layout, batch leading for the scatter below.
            return x, (k.transpose(0, 2, 1, 3), v.transpose(0, 2, 1, 3))

        x, (ks, vs) = lax.scan(layer, x, params['layers'])
        # ks: [L, N, kvh, T, d]
        x = rms_norm(x, params['final_norm'], c.norm_eps)
        head = (params['embed'].T if c.tie_embeddings else params['lm_head'])
        rows = jnp.arange(n)
        last = x[rows, true_lens - 1].astype(jnp.float32)   # [N, e]
        logits = last @ head.astype(jnp.float32)            # [N, V]
        rng, sub = jax.random.split(rng)
        firsts = _sample(logits, sub, temperatures, top_ks)  # [N]
        new_sk, new_sv = state.k_scale, state.v_scale
        if self.paged:
            # Scatter all N prompts' [T] rows through their tables in
            # one update per cache half (in-place: donated state).
            pos = jnp.arange(t)
            blk = jnp.take(tables, pos // self.kv_block, axis=1)  # [N,T]
            row = pos % self.kv_block                             # [T]
            kv_heads = jnp.arange(c.num_kv_heads)
            vals_k = ks.transpose(0, 1, 3, 2, 4)  # [L, N, T, kvh, d]
            vals_v = vs.transpose(0, 1, 3, 2, 4)
            if self.quantized:
                vals_k, sk = quantize_kv_rows(vals_k)  # [L, N, T, kvh]
                vals_v, sv = quantize_kv_rows(vals_v)
                new_sk = state.k_scale.at[:, blk[:, :, None],
                                          kv_heads[None, None, :],
                                          row[None, :, None]].set(sk)
                new_sv = state.v_scale.at[:, blk[:, :, None],
                                          kv_heads[None, None, :],
                                          row[None, :, None]].set(sv)
            new_k = state.k.at[:, blk[:, :, None],
                               kv_heads[None, None, :],
                               row[None, :, None]].set(
                vals_k.astype(state.k.dtype))
            new_v = state.v.at[:, blk[:, :, None],
                               kv_heads[None, None, :],
                               row[None, :, None]].set(
                vals_v.astype(state.v.dtype))
            new_tables = state.block_tables.at[slots].set(tables)
        else:
            pad_m = self.max_len - t
            kf = jnp.pad(ks, ((0, 0), (0, 0), (0, 0), (0, pad_m), (0, 0)))
            vf = jnp.pad(vs, ((0, 0), (0, 0), (0, 0), (0, pad_m), (0, 0)))
            # One scatter per cache half writes all N slots'
            # [L, kvh, M, d] blocks (in-place: donated state).
            new_k = state.k.at[:, slots].set(kf.astype(state.k.dtype))
            new_v = state.v.at[:, slots].set(vf.astype(state.v.dtype))
            new_tables = state.block_tables
        return DecodeState(
            k=new_k, v=new_v,
            lengths=state.lengths.at[slots].set(true_lens),
            last_tokens=state.last_tokens.at[slots].set(firsts),
            active=state.active.at[slots].set(True),
            block_tables=new_tables,
            k_scale=new_sk, v_scale=new_sv,
        ), firsts, rng

    def release(self, state: DecodeState, slot: int) -> DecodeState:
        """Mark a slot free (cache contents are dead; lengths gate reads).

        Jitted with a traced slot + donated state: one device dispatch,
        which matters on high-latency links where per-dispatch overhead
        is the serving bottleneck."""
        return self._release(state, jnp.asarray(slot, jnp.int32))

    def _release_impl(self, state, slot):
        # Paged: clear the slot's table to the null block. Its old
        # blocks may be freed and reassigned to another slot's NEXT
        # admission; a stale table would let this (now inactive) slot's
        # parked decode write land inside the new owner's live block.
        tables = (state.block_tables.at[slot].set(0) if self.paged
                  else state.block_tables)
        return DecodeState(k=state.k, v=state.v,
                           lengths=state.lengths.at[slot].set(0),
                           last_tokens=state.last_tokens,
                           active=state.active.at[slot].set(False),
                           block_tables=tables,
                           k_scale=state.k_scale,
                           v_scale=state.v_scale)

    def sample_first(self, logits: jax.Array, rng: jax.Array,
                     temperature: float, top_k: int
                     ) -> Tuple[jax.Array, jax.Array]:
        """Sample the TTFT token from prefill logits [V] on device — one
        dispatch, no host sync. Returns (token scalar, next rng)."""
        return self._sample_one(logits, rng,
                                jnp.float32(temperature),
                                jnp.int32(top_k))

    @staticmethod
    def _sample_one_impl(logits, rng, temperature, top_k):
        rng, sub = jax.random.split(rng)
        return _sample(logits[None], sub, temperature, top_k)[0], rng

    # -- decode step --------------------------------------------------------
    # skylint: hot-path
    def step(self, params: Params, state: DecodeState, rng: jax.Array,
             temperature=0.0, top_k=0
             ) -> Tuple[DecodeState, jax.Array, jax.Array]:
        """One token for every active slot.

        Returns (state, sampled [B], next_rng): the rng is split INSIDE
        the jit so a decode step is a single device dispatch (a separate
        host-side split doubles per-step dispatch overhead, which is the
        bottleneck on tunneled/high-latency device links).

        ``temperature``/``top_k`` may be scalars or per-slot [B] arrays;
        they are traced (not static), so heterogeneous sampling settings
        never trigger recompilation. Device arrays already shaped [B]
        pass through without a re-broadcast dispatch.
        """
        b = self.batch_slots
        if not (isinstance(temperature, jax.Array)
                and temperature.shape == (b,)
                and temperature.dtype == jnp.float32):
            if isinstance(temperature, (int, float)):
                temperature = self._scalar_sampling(float(temperature),
                                                    jnp.float32)
            else:  # per-slot list/ndarray: genuinely new data
                temperature = jnp.broadcast_to(
                    jnp.asarray(temperature, jnp.float32), (b,))
        if not (isinstance(top_k, jax.Array) and top_k.shape == (b,)
                and top_k.dtype == jnp.int32):
            if isinstance(top_k, int):
                top_k = self._scalar_sampling(top_k, jnp.int32)
            else:
                top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32),
                                         (b,))
        if self.profiler is None:
            return self._step(params, state, rng, temperature, top_k)
        # Dispatch wall time, not device time: steps are pipelined (no
        # host sync), so steady-state this tracks per-step cadence and a
        # spike marks a compile or a backed-up dispatch queue. The gap
        # (end of previous dispatch -> start of this one) is the host
        # time the dispatch queue went unfed — the quantity the async
        # runtime exists to overlap with device work.
        self.profiler.note_variant('step', b)
        t0 = time.perf_counter()
        if self._last_dispatch_end is not None:
            self.profiler.note_gap(t0 - self._last_dispatch_end)
        out = self._step(params, state, rng, temperature, top_k)
        end = time.perf_counter()
        self.profiler.note_step(end - t0)
        self._last_dispatch_end = end
        return out

    # Distinct scalar (temperature, top_k) settings are CLIENT-supplied;
    # bound the cache so adversarial traffic (every request a new float)
    # cannot grow device arrays without limit. 32 entries covers any
    # realistic settings mix; past it, least-recently-used settings
    # rebuild their [B] array on next use (one extra dispatch).
    SCALAR_SAMPLING_CACHE_MAX = 32

    def _scalar_sampling(self, value, dtype) -> jax.Array:
        """Device-resident [B] broadcast of a scalar sampling setting,
        LRU-cached so repeated step() calls with scalar defaults dispatch
        exactly ONE device computation (the step itself)."""
        key = (value, dtype.__name__)
        cached = self._scalar_sampling_cache.get(key)
        if cached is None:
            cached = jnp.broadcast_to(jnp.asarray(value, dtype),
                                      (self.batch_slots,))
            # Materialize now: broadcast_to may return a lazy/committed
            # view; block so later steps pay zero transfer.
            cached.block_until_ready()
            self._scalar_sampling_cache[key] = cached
            while (len(self._scalar_sampling_cache)
                   > self.SCALAR_SAMPLING_CACHE_MAX):
                self._scalar_sampling_cache.popitem(last=False)
        else:
            self._scalar_sampling_cache.move_to_end(key)
        return cached

    def _step_impl(self, params, state, rng, temperature, top_k):
        rng, sample_rng = jax.random.split(rng)
        c = self.config
        b = self.batch_slots
        grp = c.num_heads // c.num_kv_heads
        cos, sin = precompute_rotary(c.head_dim, c.max_seq_len, c.rope_theta)
        positions = state.lengths[:, None]  # [B, 1]: new token's position
        x = params['embed'][state.last_tokens][:, None].astype(c.dtype)
        rows = jnp.arange(b)
        kv_pos = jnp.arange(self.m_pad)
        # New key written at index ``lengths`` -> valid keys are <= lengths.
        valid = kv_pos[None] <= state.lengths[:, None]  # [B, M]
        # INACTIVE slots park their (garbage) step-write at the LAST row
        # instead of row ``lengths`` (= 0). A slot mid-chunked-prefill is
        # inactive but already holds real KV rows from offset 0 up — the
        # old unconditional write-at-lengths clobbered its row 0 on every
        # interleaved decode step. The last row is never read before
        # being rewritten: readers mask by kv_pos <= lengths, and a slot
        # AT capacity rewrites that row itself before attending. (Paged:
        # a released slot's table is cleared to the null block, so a
        # vacated slot's parked write can never land in a reassigned
        # block; mid-prefill slots park inside their own assignment or
        # the null block.)
        write_pos = jnp.where(state.active, state.lengths,
                              self.max_len - 1)  # [B]
        if self.paged:
            # Physical address of each slot's write through its table.
            phys_blk = jnp.take_along_axis(
                state.block_tables,
                (write_pos // self.kv_block)[:, None], axis=1)[:, 0]
            phys_row = (write_pos % self.kv_block)[:, None]  # [B, 1]
        write_pos = write_pos[:, None]  # [B, 1]

        model = self.model

        kv_heads = jnp.arange(c.num_kv_heads)

        def layer(carry, inputs):
            if self.quantized:
                x, cache_k, cache_v, scale_k, scale_v = carry
            else:
                x, cache_k, cache_v = carry
                scale_k = scale_v = None
            lp, i = inputs
            q, k, v = model._qkv(lp, x, cos, sin, positions, constrain=False)
            if self.quantized:
                # Quantized row scatter: int8 codes + [B, kvh] scales
                # through the same table-resolved addresses; the gather
                # dequantizes to f32 before QK^T.
                qk, sk = quantize_kv_rows(k[:, 0])
                qv, sv = quantize_kv_rows(v[:, 0])
                cache_k = cache_k.at[i, phys_blk[:, None],
                                     kv_heads[None, :], phys_row].set(qk)
                cache_v = cache_v.at[i, phys_blk[:, None],
                                     kv_heads[None, :], phys_row].set(qv)
                scale_k = scale_k.at[i, phys_blk[:, None],
                                     kv_heads[None, :], phys_row].set(sk)
                scale_v = scale_v.at[i, phys_blk[:, None],
                                     kv_heads[None, :], phys_row].set(sv)
                k_layer = self._gather_batch(cache_k[i],
                                             state.block_tables,
                                             scale_k[i])
                v_layer = self._gather_batch(cache_v[i],
                                             state.block_tables,
                                             scale_v[i])
            elif self.paged:
                # Block-indexed row scatter + gather of each slot's view
                # through its table (indices broadcast to [B, kvh]).
                cache_k = cache_k.at[i, phys_blk[:, None],
                                     kv_heads[None, :], phys_row].set(
                    k[:, 0].astype(cache_k.dtype))
                cache_v = cache_v.at[i, phys_blk[:, None],
                                     kv_heads[None, :], phys_row].set(
                    v[:, 0].astype(cache_v.dtype))
                k_layer = self._gather_batch(cache_k[i],
                                             state.block_tables)
                v_layer = self._gather_batch(cache_v[i],
                                             state.block_tables)
            else:
                # Scatter the new K/V row into layer i at each slot's
                # length (in-place on the donated carry). Cache is
                # [L,B,kvh,M,d]; indices broadcast to [B, kvh] -> writes
                # [B, kvh, d] rows.
                cache_k = cache_k.at[i, rows[:, None], kv_heads[None, :],
                                     write_pos].set(
                    k[:, 0].astype(cache_k.dtype))
                cache_v = cache_v.at[i, rows[:, None], kv_heads[None, :],
                                     write_pos].set(
                    v[:, 0].astype(cache_v.dtype))
                k_layer = cache_k[i]  # [B, kvh, M, d]
                v_layer = cache_v[i]
            # Grouped-query attention without repeating KV ([B,kvh,grp,d]);
            # per (b, kvh) the [M, d] operand is contiguous in HBM, and the
            # MXU accumulates bf16 x bf16 in f32 (preferred_element_type)
            # with no f32 materialization of the cache.
            qg = q[:, 0].reshape(b, c.num_kv_heads, grp, c.head_dim)
            s = jnp.einsum('bkgd,bkmd->bkgm', qg, k_layer,
                           preferred_element_type=jnp.float32)
            s = s * (c.head_dim**-0.5)
            s = jnp.where(valid[:, None, None, :], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            attn = jnp.einsum('bkgm,bkmd->bkgd', p.astype(c.dtype), v_layer,
                              preferred_element_type=jnp.float32)
            attn = attn.reshape(b, 1, c.num_heads, c.head_dim).astype(c.dtype)
            x = x + jnp.einsum('bshd,hde->bse', attn, lp['wo'])
            x = x + model._mlp_delta(lp, x, constrain=False)[0]
            if self.quantized:
                return (x, cache_k, cache_v, scale_k, scale_v), None
            return (x, cache_k, cache_v), None

        n_layers = c.num_layers
        if self.quantized:
            (x, new_k, new_v, new_sk, new_sv), _ = lax.scan(
                layer, (x, state.k, state.v, state.k_scale,
                        state.v_scale),
                (params['layers'], jnp.arange(n_layers)))
        else:
            (x, new_k, new_v), _ = lax.scan(
                layer, (x, state.k, state.v),
                (params['layers'], jnp.arange(n_layers)))
            new_sk, new_sv = state.k_scale, state.v_scale

        x = rms_norm(x, params['final_norm'], c.norm_eps)
        head = (params['embed'].T if c.tie_embeddings else params['lm_head'])
        logits = jnp.einsum('be,ev->bv', x[:, 0].astype(jnp.float32),
                            head.astype(jnp.float32))
        sampled = _sample(logits, sample_rng, temperature, top_k)
        active_i = state.active.astype(jnp.int32)
        # Clamp: a slot at capacity rewrites its last cache row instead of
        # scattering out of bounds. The serving scheduler's emission lags
        # dispatch (pipelined D2H), so a few steps can land after a slot is
        # logically full; their tokens are discarded at emission.
        return DecodeState(
            k=new_k, v=new_v,
            lengths=jnp.minimum(state.lengths + active_i,
                                self.max_len - 1),
            last_tokens=jnp.where(state.active, sampled, state.last_tokens),
            active=state.active,
            block_tables=state.block_tables,
            k_scale=new_sk, v_scale=new_sv,
        ), sampled, rng


    # -- speculative decode step --------------------------------------------
    # skylint: hot-path
    def step_verify(self, params: Params, state: DecodeState,
                    rng: jax.Array, draft, temperature=0.0, top_k=0
                    ) -> Tuple[DecodeState, jax.Array, jax.Array,
                               jax.Array]:
        """One VERIFY step: score ``draft`` [B, K] host-proposed tokens
        plus each slot's pending last token in a single [B, 1+K]
        batched forward, accept the longest greedy prefix, and emit the
        corrected token after it — Leviathan-style speculative decoding
        with a model-free drafter (``draft_tokens``).

        Returns (state, out [B, 1+K], accept [B], next_rng): slot b
        emits ``out[b, 0 .. accept[b]]`` (1..K+1 tokens), exactly the
        tokens ``accept[b] + 1`` successive plain ``step`` calls would
        have produced — greedy output is provably unchanged, only the
        number of forwards per token changes. Rejected draft rows are
        rolled back by LENGTH MASKING: ``lengths`` advances only past
        accepted rows, so rejected KV writes sit beyond every reader's
        validity mask and are overwritten by the next step — they are
        never committed to block accounting.

        ``K = draft.shape[1]`` is a traced-shape bucket: one compiled
        variant per K (the scheduler uses a single fixed K, so steady
        state is recompile-free, pinned by the recompile counter).
        Sampling slots (temperature > 0) accept zero draft tokens and
        emit only ``out[:, 0]``, which reproduces the plain step's
        categorical draw bit-for-bit — speculation accelerates greedy
        rows in a mixed batch without perturbing sampled ones.
        """
        b = self.batch_slots
        draft = jnp.asarray(draft, jnp.int32)
        if not (isinstance(temperature, jax.Array)
                and temperature.shape == (b,)
                and temperature.dtype == jnp.float32):
            if isinstance(temperature, (int, float)):
                temperature = self._scalar_sampling(float(temperature),
                                                    jnp.float32)
            else:
                temperature = jnp.broadcast_to(
                    jnp.asarray(temperature, jnp.float32), (b,))
        if not (isinstance(top_k, jax.Array) and top_k.shape == (b,)
                and top_k.dtype == jnp.int32):
            if isinstance(top_k, int):
                top_k = self._scalar_sampling(top_k, jnp.int32)
            else:
                top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32),
                                         (b,))
        if self.profiler is None:
            return self._step_verify(params, state, rng, temperature,
                                     top_k, draft)
        self.profiler.note_variant('step_verify', b, draft.shape[1])
        t0 = time.perf_counter()
        if self._last_dispatch_end is not None:
            self.profiler.note_gap(t0 - self._last_dispatch_end)
        out = self._step_verify(params, state, rng, temperature, top_k,
                                draft)
        end = time.perf_counter()
        self.profiler.note_step(end - t0)
        self.profiler.spec_verify_ms.observe((end - t0) * 1e3)
        self._last_dispatch_end = end
        return out

    # shapecheck: draft = i32[8, 4]
    def _step_verify_impl(self, params, state, rng, temperature, top_k,
                          draft):
        rng, sample_rng = jax.random.split(rng)
        c = self.config
        b = self.batch_slots
        k_spec = draft.shape[1]
        t = 1 + k_spec
        grp = c.num_heads // c.num_kv_heads
        cos, sin = precompute_rotary(c.head_dim, c.max_seq_len, c.rope_theta)
        # Position t's input: the pending last token, then the draft.
        inputs = jnp.concatenate([state.last_tokens[:, None], draft],
                                 axis=1)                       # [B, T]
        positions = state.lengths[:, None] + jnp.arange(t)[None]  # [B, T]
        x = params['embed'][inputs].astype(c.dtype)            # [B, T, e]
        kv_pos = jnp.arange(self.m_pad)
        # Query at position p sees kv rows <= p: its own write plus the
        # draft rows before it (which ARE the greedy path up to the
        # first mismatch — past it, everything is masked off by the
        # final lengths and rewritten).
        valid = kv_pos[None, None, :] <= positions[:, :, None]  # [B,T,M]
        # Rows past capacity (and every row of an inactive slot) must
        # not land anywhere real: give them an out-of-range row index
        # and let the scatter's mode='drop' discard them. Clamping
        # instead would collapse several draft rows onto one physical
        # row — duplicate scatter indices with differing values are
        # nondeterministic, which would break bit-identity at the
        # capacity edge.
        ok = state.active[:, None] & (positions <= self.max_len - 1)
        wp = jnp.minimum(positions, self.max_len - 1)  # in-bounds lookup
        kv_heads = jnp.arange(c.num_kv_heads)
        if self.paged:
            blk = jnp.take_along_axis(state.block_tables,
                                      wp // self.kv_block, axis=1)  # [B,T]
            row = jnp.where(ok, wp % self.kv_block, self.kv_block)
        else:
            rows_b = jnp.arange(b)
            row_idx = jnp.where(ok, wp, self.m_pad)

        model = self.model

        def layer(carry, inputs_l):
            if self.quantized:
                x, cache_k, cache_v, scale_k, scale_v = carry
            else:
                x, cache_k, cache_v = carry
                scale_k = scale_v = None
            lp, i = inputs_l
            q, k, v = model._qkv(lp, x, cos, sin, positions, constrain=False)
            if self.quantized:
                # Quantized [B, T, kvh, d] append: codes + [B, T, kvh]
                # scales through the same addresses, with the SAME
                # out-of-range row sentinel dropping both — a rejected
                # draft row leaves code and scale untouched together.
                qk, sk = quantize_kv_rows(k)
                qv, sv = quantize_kv_rows(v)
                cache_k = cache_k.at[i, blk[:, :, None],
                                     kv_heads[None, None, :],
                                     row[:, :, None]].set(
                    qk, mode='drop')
                cache_v = cache_v.at[i, blk[:, :, None],
                                     kv_heads[None, None, :],
                                     row[:, :, None]].set(
                    qv, mode='drop')
                scale_k = scale_k.at[i, blk[:, :, None],
                                     kv_heads[None, None, :],
                                     row[:, :, None]].set(
                    sk, mode='drop')
                scale_v = scale_v.at[i, blk[:, :, None],
                                     kv_heads[None, None, :],
                                     row[:, :, None]].set(
                    sv, mode='drop')
                k_layer = self._gather_batch(cache_k[i],
                                             state.block_tables,
                                             scale_k[i])
                v_layer = self._gather_batch(cache_v[i],
                                             state.block_tables,
                                             scale_v[i])
            elif self.paged:
                # [B, T, kvh, d] rows scattered through the tables;
                # out-of-range row sentinels drop.
                cache_k = cache_k.at[i, blk[:, :, None],
                                     kv_heads[None, None, :],
                                     row[:, :, None]].set(
                    k.astype(cache_k.dtype), mode='drop')
                cache_v = cache_v.at[i, blk[:, :, None],
                                     kv_heads[None, None, :],
                                     row[:, :, None]].set(
                    v.astype(cache_v.dtype), mode='drop')
                k_layer = self._gather_batch(cache_k[i],
                                             state.block_tables)
                v_layer = self._gather_batch(cache_v[i],
                                             state.block_tables)
            else:
                cache_k = cache_k.at[i, rows_b[:, None, None],
                                     kv_heads[None, None, :],
                                     row_idx[:, :, None]].set(
                    k.astype(cache_k.dtype), mode='drop')
                cache_v = cache_v.at[i, rows_b[:, None, None],
                                     kv_heads[None, None, :],
                                     row_idx[:, :, None]].set(
                    v.astype(cache_v.dtype), mode='drop')
                k_layer = cache_k[i]  # [B, kvh, M, d]
                v_layer = cache_v[i]
            # Grouped-query attention, T queries per slot over the
            # slot's cache rows (same layout as the 1-query step).
            qg = q.reshape(b, t, c.num_kv_heads, grp, c.head_dim)
            s = jnp.einsum('btkgd,bkmd->btkgm', qg, k_layer,
                           preferred_element_type=jnp.float32)
            s = s * (c.head_dim**-0.5)
            s = jnp.where(valid[:, :, None, None, :], s, -1e30)
            p = jax.nn.softmax(s, axis=-1)
            attn = jnp.einsum('btkgm,bkmd->btkgd', p.astype(c.dtype),
                              v_layer,
                              preferred_element_type=jnp.float32)
            attn = attn.reshape(b, t, c.num_heads,
                                c.head_dim).astype(c.dtype)
            x = x + jnp.einsum('bshd,hde->bse', attn, lp['wo'])
            x = x + model._mlp_delta(lp, x, constrain=False)[0]
            if self.quantized:
                return (x, cache_k, cache_v, scale_k, scale_v), None
            return (x, cache_k, cache_v), None

        if self.quantized:
            (x, new_k, new_v, new_sk, new_sv), _ = lax.scan(
                layer, (x, state.k, state.v, state.k_scale, state.v_scale),
                (params['layers'], jnp.arange(c.num_layers)))
        else:
            (x, new_k, new_v), _ = lax.scan(
                layer, (x, state.k, state.v),
                (params['layers'], jnp.arange(c.num_layers)))
            new_sk, new_sv = state.k_scale, state.v_scale

        x = rms_norm(x, params['final_norm'], c.norm_eps)
        head = (params['embed'].T if c.tie_embeddings else params['lm_head'])
        logits = jnp.einsum('bte,ev->btv', x.astype(jnp.float32),
                            head.astype(jnp.float32))  # [B, 1+K, V]
        # Row 0 through the full sampler: for temperature 0 it is the
        # greedy argmax; for sampling slots it reproduces the plain
        # step's categorical draw (same split discipline, same rng).
        out0 = _sample(logits[:, 0], sample_rng, temperature, top_k)
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)  # [B, T]
        # accept = longest prefix of the draft matching the greedy
        # continuation; sampling slots accept nothing (their draft
        # rows were scored under greedy context, not their draws).
        match = (draft == greedy[:, :k_spec]).astype(jnp.int32)
        accept = jnp.cumprod(match, axis=1).sum(axis=1)         # [B]
        accept = jnp.where(temperature > 0.0, 0, accept)
        out = jnp.concatenate([out0[:, None], greedy[:, 1:]], axis=1)
        # The corrected token out[accept] becomes the slot's pending
        # input; lengths advance by accept + 1 (the emitted count).
        new_last = jnp.take_along_axis(out, accept[:, None],
                                       axis=1)[:, 0]
        active_i = state.active.astype(jnp.int32)
        return DecodeState(
            k=new_k, v=new_v,
            k_scale=new_sk, v_scale=new_sv,
            lengths=jnp.minimum(state.lengths + (accept + 1) * active_i,
                                self.max_len - 1),
            last_tokens=jnp.where(state.active, new_last,
                                  state.last_tokens),
            active=state.active,
            block_tables=state.block_tables,
        ), out, accept, rng


def draft_tokens(history: List[int], k: int, ngram: int = 3) -> List[int]:
    """Model-free prompt-lookup draft: ``k`` proposed continuation
    tokens from ``history`` (the request's prompt + emitted tokens).

    Longest-match-first n-gram backoff (Prompt Lookup Decoding): find
    the most recent EARLIER occurrence of the trailing ``n``-gram for
    n = ngram .. 1 and propose the ``k`` tokens that followed it.
    Correctness never depends on this — ``step_verify`` accepts only
    the exact greedy continuation — so a cold or stale drafter merely
    lowers the accept rate. Short proposals pad by repeating the last
    proposed (or last history) token; an empty history drafts zeros.
    """
    if k <= 0:
        return []
    h = history
    n_h = len(h)
    out: List[int] = []
    for n in range(min(ngram, n_h - 1), 0, -1):
        tail = h[n_h - n:]
        # Most recent earlier occurrence: scan right-to-left over
        # window starts whose match leaves at least one follower.
        for start in range(n_h - n - 1, -1, -1):
            if h[start:start + n] == tail:
                follow = h[start + n:start + n + k]
                out = list(follow)
                break
        if out:
            break
    pad = out[-1] if out else (h[-1] if h else 0)
    while len(out) < k:
        out.append(pad)
    return out[:k]


def _sample(logits: jax.Array, rng: jax.Array, temperature,
            top_k) -> jax.Array:
    """Greedy (temperature 0) / temperature / top-k sampling, inside jit.

    ``temperature`` [B] f32 and ``top_k`` [B] int32 are traced per-row
    values (scalars broadcast); out-of-range top_k is clamped to the vocab,
    so malformed requests cannot crash the compiled step.
    """
    v = logits.shape[-1]
    temperature = jnp.broadcast_to(
        jnp.asarray(temperature, jnp.float32), logits.shape[:1])
    top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), logits.shape[:1])
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temperature, 1e-6)[:, None]
    srt = jnp.sort(scaled, axis=-1)  # ascending
    kth_idx = jnp.clip(v - top_k, 0, v - 1)
    kth = jnp.take_along_axis(srt, kth_idx[:, None], axis=-1)
    kth = jnp.where((top_k > 0)[:, None], kth, -jnp.inf)
    filtered = jnp.where(scaled >= kth, scaled, -jnp.inf)
    sampled = jax.random.categorical(rng, filtered, axis=-1).astype(jnp.int32)
    return jnp.where(temperature <= 0.0, greedy, sampled)


def chunk_spans(prompt_len: int, chunk: int,
                max_len: int) -> List[Tuple[int, int, bool]]:
    """Split a prompt into prefill-chunk spans ``(offset, bucket, final)``.

    Every mid span is exactly ``chunk`` tokens (ONE compiled variant per
    configured chunk size); the final span pads its remainder up to a
    ``prefill_bucket`` capped at ``chunk``, so final-chunk variants stay a
    small pow2 family instead of one compile per prompt length. The final
    bucket is additionally capped at ``max_len - offset`` so the cache
    write can never run past the slot.
    """
    if chunk <= 0:
        raise ValueError(f'chunk must be positive, got {chunk}')
    spans: List[Tuple[int, int, bool]] = []
    off = 0
    while prompt_len - off > chunk:
        spans.append((off, chunk, False))
        off += chunk
    rem = prompt_len - off
    bucket = min(prefill_bucket(rem, min(chunk, max_len)), max_len - off)
    spans.append((off, bucket, True))
    return spans


def prefill_bucket(length: int, max_len: int, floor: int = 16) -> int:
    """Smallest bucket >= length (bounded by max_len).

    Power-of-two up to 512, then multiples of 512: prefill cost is linear
    in the bucket, so pow2-only padding wastes up to ~2x compute on long
    prompts (2500 -> 4096) for the sake of fewer compile variants; 512
    granularity caps the waste at ~20% for a handful more compiles.
    """
    b = floor
    while b < length and b < 512:
        b *= 2
    if length > b:
        b = (length + 511) // 512 * 512
    return min(b, max_len)
