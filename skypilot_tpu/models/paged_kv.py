"""Host-side bookkeeping for the paged KV cache: block allocator +
hash-chain prefix cache.

The device side (``models/decode.py``) holds KV as a global pool of
fixed-size blocks ``[L, num_blocks, kvh, block, d]`` addressed through a
per-slot block table; THIS module decides which physical block ids a
sequence maps, entirely on the host — no device traffic. Two layers:

- **BlockAllocator**: refcounted alloc/free over block ids. Ids are
  handed out lowest-first (deterministic: two engines fed the same
  admission order build identical tables, which the paged-vs-contiguous
  oracle tests rely on). Block 0 is reserved as the *null block*: every
  unassigned table entry points at it, inactive slots park their decode
  write in its last row, and nothing ever reads it unmasked.
- **Prefix cache** (inside the allocator, vLLM/RadixAttention style): a
  map from a *hash chain* over full token blocks to the block id that
  holds that prefix's KV. A new request whose leading blocks hit the
  chain maps them into its table (refcount++, no copy — blocks are only
  shared FULL, and writes land strictly past a sequence's shared
  prefix, so copy-on-write never actually copies) and prefills only its
  suffix. Released blocks that are cached stay resident with ref 0 on
  an LRU; allocation evicts them only when the free list is dry, and
  never evicts a referenced block.

The chain hash of block i commits to every token of blocks 0..i (one
running sha256 over the token stream), so a hash hit implies the whole
prefix matches — no per-block token comparison on lookup.
"""
from __future__ import annotations

import bisect
import hashlib
import threading
from collections import OrderedDict
from typing import Dict, List, Optional, Sequence, Tuple

from skypilot_tpu.utils import metrics as metrics_lib


def hash_token_blocks(tokens: Sequence[int], block_size: int,
                      n_blocks: Optional[int] = None) -> List[bytes]:
    """Chain hashes for the first ``n_blocks`` FULL blocks of ``tokens``
    (default: every full block). ``hash[i]`` commits to tokens
    ``[0, (i+1)*block_size)`` — a running digest, so matching hash[i]
    implies hashes 0..i-1 matched too."""
    if n_blocks is None:
        n_blocks = len(tokens) // block_size
    h = hashlib.sha256()
    out: List[bytes] = []
    for i in range(n_blocks):
        block = tokens[i * block_size:(i + 1) * block_size]
        h.update(b''.join(int(t).to_bytes(8, 'little', signed=True)
                          for t in block))
        out.append(h.digest())
    return out


def blocks_for(rows: int, block_size: int) -> int:
    """Blocks needed to hold ``rows`` KV rows."""
    return -(-max(0, rows) // block_size)


class _KvMetrics:
    """skytpu_engine_kv_* family on the process default registry."""

    def __init__(self):
        self.pool_blocks = metrics_lib.gauge(
            'skytpu_engine_kv_pool_blocks_count',
            'allocatable KV blocks in the pool')
        self.used_blocks = metrics_lib.gauge(
            'skytpu_engine_kv_used_blocks_count',
            'KV blocks currently referenced by a slot')
        self.utilization = metrics_lib.gauge(
            'skytpu_engine_kv_block_utilization_ratio',
            'referenced blocks / pool blocks')
        self.prefix_lookups = metrics_lib.counter(
            'skytpu_engine_kv_prefix_lookups_total',
            'prefix-cache lookups at admission')
        self.prefix_hits = metrics_lib.counter(
            'skytpu_engine_kv_prefix_hits_total',
            'admissions that reused >= 1 cached block')
        self.lookup_tokens = metrics_lib.counter(
            'skytpu_engine_kv_prefix_lookup_tokens_total',
            'prompt tokens submitted to prefix lookup')
        self.hit_tokens = metrics_lib.counter(
            'skytpu_engine_kv_prefix_hit_tokens_total',
            'prompt tokens served from cached blocks '
            '(prefill work skipped)')
        self.evictions = metrics_lib.counter(
            'skytpu_engine_kv_evictions_total',
            'cached unreferenced blocks evicted to satisfy an '
            'allocation')
        self.reclaimed = metrics_lib.counter(
            'skytpu_engine_kv_blocks_reclaimed_total',
            'never-written tail blocks returned to the pool at release '
            '(early EOS before the reserved budget was consumed)')


class BlockAllocator:
    """Refcounted block ids + prefix cache with LRU eviction.

    Thread-safe: the serving scheduler mutates from its own thread while
    HTTP handler threads peek (``match``) for admission estimates.
    """

    def __init__(self, num_blocks: int, block_size: int,
                 reserved: int = 1):
        if num_blocks <= reserved:
            raise ValueError(f'pool needs > {reserved} blocks, got '
                             f'{num_blocks}')
        self.num_blocks = num_blocks
        self.block_size = block_size
        self.reserved = reserved
        self._lock = threading.Lock()
        self._m = _KvMetrics() if metrics_lib.enabled() else None
        self._init_tables_locked()
        if self._m is not None:
            self._m.pool_blocks.set(self.capacity)

    def _init_tables_locked(self) -> None:
        """Caller holds ``_lock`` (``reset``) or the allocator is not
        yet shared (``__init__``)."""
        self._free: List[int] = list(range(self.reserved,
                                           self.num_blocks))
        self._ref: Dict[int, int] = {}
        self._hash_to_block: Dict[bytes, int] = {}
        self._block_hash: Dict[int, bytes] = {}
        # ref-0 blocks still registered in the prefix cache, oldest
        # (least recently touched) first — the eviction order.
        self._lru: 'OrderedDict[int, None]' = OrderedDict()
        self.counters = {'lookups': 0, 'hits': 0, 'lookup_tokens': 0,
                         'hit_tokens': 0, 'evictions': 0, 'reclaimed': 0}

    # -- capacity -----------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.num_blocks - self.reserved

    def available(self) -> int:
        """Blocks allocatable right now (free + evictable)."""
        with self._lock:
            return len(self._free) + len(self._lru)

    def used(self) -> int:
        with self._lock:
            return len(self._ref)

    # -- alloc / free -------------------------------------------------------
    def _alloc_locked(self, n: int) -> List[int]:
        """``n`` blocks with ref 1 each; caller holds the lock and has
        already checked availability."""
        out: List[int] = []
        for _ in range(n):
            if self._free:
                blk = self._free.pop(0)
            else:
                blk, _ = self._lru.popitem(last=False)  # LRU evict
                h = self._block_hash.pop(blk)
                del self._hash_to_block[h]
                self.counters['evictions'] += 1
                if self._m is not None:
                    self._m.evictions.inc()
            self._ref[blk] = 1
            out.append(blk)
        return out

    def alloc(self, n: int) -> Optional[List[int]]:
        """``n`` fresh blocks with ref 1 each, or None if the pool (free
        + evictable) cannot satisfy the request — nothing is taken on
        failure, so callers can retry after a release."""
        if n <= 0:
            return []
        with self._lock:
            if len(self._free) + len(self._lru) < n:
                return None
            out = self._alloc_locked(n)
            self._update_gauges_locked()
            return out

    def reserve(self, hashes: Sequence[bytes], total_blocks: int
                ) -> Optional[Tuple[List[int], List[int]]]:
        """One admission's whole reservation, atomically: longest cached
        chain prefix ref'd + fresh blocks for the rest, or None with
        NOTHING taken (and nothing recorded) when the pool can't satisfy
        it. Metrics/counters record only on success, so a pool-dry
        request retried every scheduler tick counts ONE lookup when it
        finally admits — not one per retry — and its failed attempts
        don't churn cached blocks to the LRU tail. Returns
        (cached_ids, new_ids)."""
        with self._lock:
            cached = self._match_locked(hashes)
            need = total_blocks - len(cached)
            # Ref'ing a cached ref-0 block removes it from the LRU, so
            # it cannot also back a fresh allocation.
            evictable = len(self._lru) - sum(1 for b in cached
                                             if b in self._lru)
            if len(self._free) + evictable < need:
                return None
            for blk in cached:
                cur = self._ref.get(blk, 0)
                if cur == 0:
                    self._lru.pop(blk, None)
                self._ref[blk] = cur + 1
            new = self._alloc_locked(need)
            self._record_lookup_locked(len(hashes), len(cached))
            self._update_gauges_locked()
            return cached, new

    def ref_blocks(self, blocks: Sequence[int]) -> None:
        """Take an additional reference on already-live or cached
        blocks (prefix sharing)."""
        with self._lock:
            for blk in blocks:
                cur = self._ref.get(blk, 0)
                if cur == 0:
                    # Leaving the LRU: referenced again.
                    self._lru.pop(blk, None)
                self._ref[blk] = cur + 1
            self._update_gauges_locked()

    def deref(self, blocks: Sequence[int]) -> None:
        """Drop one reference per block; ref-0 cached blocks become
        evictable (LRU tail = most recently released), uncached ones
        return to the free list."""
        with self._lock:
            for blk in blocks:
                cur = self._ref.get(blk)
                if cur is None:
                    raise ValueError(f'deref of unreferenced block {blk}')
                if cur > 1:
                    self._ref[blk] = cur - 1
                    continue
                del self._ref[blk]
                if blk in self._block_hash:
                    self._lru[blk] = None
                else:
                    bisect.insort(self._free, blk)
            self._update_gauges_locked()

    def reclaim_tail(self, blocks: Sequence[int]) -> int:
        """Return never-written tail blocks straight to the free list.

        A request reserves ``ceil((prompt+max_tokens)/block)`` blocks at
        admission; on early EOS the rows past its actual length were
        never written, so the blocks backing them carry no cacheable
        KV. They are exclusively owned (ref == 1) and never committed
        to the prefix cache — both enforced here, because reclaiming a
        shared or cached block would corrupt another sequence. Returns
        the number reclaimed (mirrors the
        skytpu_engine_kv_blocks_reclaimed_total counter)."""
        if not blocks:
            return 0
        with self._lock:
            for blk in blocks:
                if self._ref.get(blk) != 1:
                    raise ValueError(
                        f'reclaim of shared/unreferenced block {blk}')
                if blk in self._block_hash:
                    raise ValueError(f'reclaim of cached block {blk}')
            for blk in blocks:
                del self._ref[blk]
                bisect.insort(self._free, blk)
            self.counters['reclaimed'] += len(blocks)
            if self._m is not None:
                self._m.reclaimed.inc(len(blocks))
            self._update_gauges_locked()
        return len(blocks)

    # -- prefix cache -------------------------------------------------------
    def _match_locked(self, hashes: Sequence[bytes]) -> List[int]:
        out: List[int] = []
        for h in hashes:
            blk = self._hash_to_block.get(h)
            if blk is None:
                break
            out.append(blk)
        return out

    def _record_lookup_locked(self, n_hashes: int, n_matched: int
                              ) -> None:
        """One admission's lookup in the hit-rate series — counted at
        reservation time only (estimator peeks via ``match`` stay
        silent, and a pool-dry retry loop records nothing until it
        finally admits)."""
        self.counters['lookups'] += 1
        self.counters['lookup_tokens'] += n_hashes * self.block_size
        if self._m is not None:
            self._m.prefix_lookups.inc()
            self._m.lookup_tokens.inc(n_hashes * self.block_size)
        if n_matched:
            self.counters['hits'] += 1
            self.counters['hit_tokens'] += n_matched * self.block_size
            if self._m is not None:
                self._m.prefix_hits.inc()
                self._m.hit_tokens.inc(n_matched * self.block_size)

    def match(self, hashes: Sequence[bytes]) -> List[int]:
        """Longest cached chain prefix -> block ids. Read-only: no refs
        taken, nothing recorded — the estimator's peek."""
        with self._lock:
            return self._match_locked(hashes)

    def match_and_ref(self, hashes: Sequence[bytes]) -> List[int]:
        """match() + take a reference on every matched block, atomically
        (a concurrent eviction between match and ref would hand the
        caller a block about to be reused). Records the lookup."""
        with self._lock:
            out = self._match_locked(hashes)
            for blk in out:
                cur = self._ref.get(blk, 0)
                if cur == 0:
                    self._lru.pop(blk, None)
                self._ref[blk] = cur + 1
            self._record_lookup_locked(len(hashes), len(out))
            self._update_gauges_locked()
            return out

    def commit(self, hashes: Sequence[bytes],
               blocks: Sequence[int]) -> None:
        """Register (hash, block) pairs after their KV has been
        dispatched. First writer wins: a hash already cached keeps its
        existing block (the duplicate's copy stays private and frees
        normally). Only referenced blocks may be committed — the caller
        still holds the admitting sequence's ref."""
        with self._lock:
            for h, blk in zip(hashes, blocks):
                if h in self._hash_to_block:
                    continue
                if self._ref.get(blk, 0) <= 0:
                    raise ValueError(
                        f'commit of unreferenced block {blk}')
                if blk in self._block_hash:
                    continue  # already caches a different chain position
                self._hash_to_block[h] = blk
                self._block_hash[blk] = h

    # -- maintenance --------------------------------------------------------
    def reset(self) -> None:
        """Forget everything (crash recovery alongside a fresh
        ``init_state``)."""
        with self._lock:
            self._init_tables_locked()
            self._update_gauges_locked()

    def stats(self) -> Dict[str, float]:
        with self._lock:
            used = len(self._ref)
            cap = self.capacity
            lk = self.counters['lookup_tokens']
            return {
                'kv_block': self.block_size,
                'kv_blocks_total': cap,
                'kv_blocks_free': len(self._free) + len(self._lru),
                'kv_blocks_used': used,
                'kv_block_utilization': round(used / cap, 4) if cap
                else 0.0,
                'prefix_cache_blocks': len(self._hash_to_block),
                'prefix_lookups': self.counters['lookups'],
                'prefix_hits': self.counters['hits'],
                'prefix_hit_tokens': self.counters['hit_tokens'],
                'prefix_lookup_tokens': lk,
                'prefix_hit_rate': (round(
                    self.counters['hit_tokens'] / lk, 4) if lk else 0.0),
                'prefix_evictions': self.counters['evictions'],
                'kv_blocks_reclaimed': self.counters['reclaimed'],
            }

    def _update_gauges_locked(self) -> None:
        if self._m is None:
            return
        used = len(self._ref)
        self._m.used_blocks.set(used)
        if self.capacity:
            self._m.utilization.set(used / self.capacity)
