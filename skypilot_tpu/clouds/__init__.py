"""Cloud abstraction: Cloud ABC + registry.

Counterpart of reference ``sky/clouds/cloud.py`` (Cloud ABC with capability
enum, feasibility, pricing, deploy vars, credentials;
sky/clouds/cloud.py:131-887). GCP/TPU-first but the same functional shape so
more providers can be added.
"""
from skypilot_tpu.clouds.cloud import (Cloud, CloudFeature, CLOUD_REGISTRY,
                                       FeasibleResources)
from skypilot_tpu.clouds import aws as _aws  # registers
from skypilot_tpu.clouds import azure as _azure  # registers
from skypilot_tpu.clouds import cudo as _cudo  # registers
from skypilot_tpu.clouds import do as _do  # registers
from skypilot_tpu.clouds import fluidstack as _fluidstack  # registers
from skypilot_tpu.clouds import gcp as _gcp  # registers
from skypilot_tpu.clouds import hyperstack as _hyperstack  # registers
from skypilot_tpu.clouds import kubernetes as _kubernetes  # registers
from skypilot_tpu.clouds import lambda_cloud as _lambda  # registers
from skypilot_tpu.clouds import local as _local  # registers
from skypilot_tpu.clouds import oci as _oci  # registers
from skypilot_tpu.clouds import paperspace as _paperspace  # registers
from skypilot_tpu.clouds import runpod as _runpod  # registers
from skypilot_tpu.clouds import vast as _vast  # registers

__all__ = ['Cloud', 'CloudFeature', 'CLOUD_REGISTRY', 'FeasibleResources',
           'get_cloud']


def get_cloud(name: str) -> Cloud:
    cls = CLOUD_REGISTRY.from_str(name)
    assert cls is not None
    return cls()
