"""Cudo Compute: project-scoped VMs in data centers (stop/start, no
spot).

Counterpart of reference ``sky/clouds/cudo.py``. Twelfth VM cloud;
data centers play the region role, sizing rides the create call
(catalog rows carry the priced vcpus/memory point per machine family).
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib


@cloud_lib.CLOUD_REGISTRY.register(name='cudo')
class Cudo(cloud_lib.Cloud):
    NAME = 'cudo'
    _FEATURES = frozenset({
        cloud_lib.CloudFeature.STOP,
        cloud_lib.CloudFeature.AUTOSTOP,
        cloud_lib.CloudFeature.MULTI_HOST,
        cloud_lib.CloudFeature.STORAGE_MOUNTS,
    })

    # ---- credentials ------------------------------------------------------
    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        if os.environ.get('SKYTPU_FAKE_CUDO_CREDENTIALS'):
            return True, None
        from skypilot_tpu.provision import cudo_api
        if cudo_api.read_credentials() is not None:
            return True, None
        return False, ('Cudo credentials not found. Set $CUDO_API_KEY + '
                       '$CUDO_PROJECT_ID or run `cudo init`.')

    @classmethod
    def get_active_user_identity(cls) -> Optional[List[str]]:
        if os.environ.get('SKYTPU_FAKE_CUDO_CREDENTIALS'):
            return ['fake-identity@cudo.test']
        from skypilot_tpu.provision import cudo_api
        creds = cudo_api.read_credentials()
        return [f'cudo-project-{creds["project"]}'] if creds else None

    # ---- topology ---------------------------------------------------------
    def regions_for(self, resources) -> List[str]:
        if resources.tpu is not None:
            return []  # no TPUs on Cudo
        if resources.use_spot:
            return []  # no spot market
        itype = resources.instance_type or 'epyc-milan'
        regions = catalog.get_vm_regions(itype, cloud=self.NAME)
        if resources.region is not None:
            regions = [r for r in regions if r == resources.region]
        return regions

    def zones_for(self, resources, region: str) -> List[Optional[str]]:
        if resources.zone is not None:
            return []  # data centers have no zones
        return [None]

    # ---- pricing ----------------------------------------------------------
    def hourly_cost(self, resources, region=None, zone=None) -> float:
        region = region or resources.region
        assert resources.instance_type is not None, resources
        return catalog.get_instance_hourly_cost(
            resources.instance_type, resources.use_spot, region=region,
            cloud=self.NAME)

    def egress_cost_per_gb(self, dst_cloud: str, dst_region: str,
                           src_region: Optional[str]) -> float:
        return 0.0  # Cudo does not bill egress

    # ---- feasibility ------------------------------------------------------
    def get_feasible_resources(self,
                               resources) -> cloud_lib.FeasibleResources:
        if resources.tpu is not None:
            return cloud_lib.FeasibleResources(
                [], hint='Cudo has no TPU accelerators; use cloud: gcp.')
        if resources.use_spot:
            return cloud_lib.FeasibleResources(
                [], hint='Cudo has no spot market.')
        if resources.ports:
            return cloud_lib.FeasibleResources(
                [], hint='Cudo port management is not wired up; tasks '
                         'needing open ports cannot run there.')
        if resources.instance_type is not None:
            if not catalog.get_vm_regions(resources.instance_type,
                                          cloud=self.NAME):
                return cloud_lib.FeasibleResources(
                    [], hint=(f'{resources.instance_type} is not a Cudo '
                              'machine family in the catalog.'))
            return cloud_lib.FeasibleResources(
                [resources.copy(cloud=self.NAME)])
        itype = catalog.get_default_instance_type(
            cpus=resources._cpus, cpus_plus=resources._cpus_plus,  # pylint: disable=protected-access
            memory=resources._memory, memory_plus=resources._memory_plus,  # pylint: disable=protected-access
            region=resources.region, cloud=self.NAME)
        if itype is None:
            return cloud_lib.FeasibleResources(
                [], hint=(f'No Cudo machine with cpus={resources.cpus}, '
                          f'memory={resources.memory}'))
        return cloud_lib.FeasibleResources(
            [resources.copy(cloud=self.NAME, instance_type=itype)])

    # ---- deployment -------------------------------------------------------
    def make_deploy_variables(self, resources, cluster_name_on_cloud: str,
                              region: str,
                              zone: Optional[str]) -> Dict[str, Any]:
        return {
            'cloud': self.NAME,
            'mode': 'cudo_vm',
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region,
            'zone': None,
            'use_spot': False,
            'disk_size_gb': resources.disk_size,
            'labels': dict(resources.labels or {}),
            'ports': [],
            'instance_type': resources.instance_type,
            'image_id': None,  # stock ubuntu-2204 image
        }
