"""Vast.ai: marketplace GPU instances (first REST cloud with SPOT).

Counterpart of reference ``sky/clouds/vast.py``. ``use_spot`` maps to
an interruptible bid on the marketplace; preemption (outbid / host
reclaim) pauses the instance and is detected by the provisioner, so
managed-jobs recovery works exactly as on GCP/AWS spot. Regions are
two-letter country codes (the marketplace's only stable geography).
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib


@cloud_lib.CLOUD_REGISTRY.register(name='vast')
class Vast(cloud_lib.Cloud):
    NAME = 'vast'
    _FEATURES = frozenset({
        cloud_lib.CloudFeature.STOP,
        cloud_lib.CloudFeature.AUTOSTOP,
        cloud_lib.CloudFeature.SPOT,       # interruptible bids
        cloud_lib.CloudFeature.MULTI_HOST,
        cloud_lib.CloudFeature.STORAGE_MOUNTS,
        cloud_lib.CloudFeature.CUSTOM_IMAGES,  # any docker image
    })

    # ---- credentials ------------------------------------------------------
    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        if os.environ.get('SKYTPU_FAKE_VAST_CREDENTIALS'):
            return True, None
        from skypilot_tpu.provision import vast_api
        if vast_api.read_api_key() is not None:
            return True, None
        return False, ('Vast.ai credentials not found. Set $VAST_API_KEY '
                       'or write the key to ~/.vast_api_key.')

    @classmethod
    def get_active_user_identity(cls) -> Optional[List[str]]:
        if os.environ.get('SKYTPU_FAKE_VAST_CREDENTIALS'):
            return ['fake-identity@vast.test']
        from skypilot_tpu.provision import vast_api
        key = vast_api.read_api_key()
        return [f'vast-key-{key[:8]}'] if key else None

    # ---- topology ---------------------------------------------------------
    def regions_for(self, resources) -> List[str]:
        if resources.tpu is not None:
            return []  # no TPUs on the marketplace
        itype = resources.instance_type or '1x_RTX_4090'
        regions = catalog.get_vm_regions(itype, cloud=self.NAME)
        if resources.region is not None:
            regions = [r for r in regions if r == resources.region]
        return regions

    def zones_for(self, resources, region: str) -> List[Optional[str]]:
        if resources.zone is not None:
            return []  # no zones
        return [None]

    # ---- pricing ----------------------------------------------------------
    def hourly_cost(self, resources, region=None, zone=None) -> float:
        # Catalog prices are MEDIAN marketplace rates (the live offer
        # price is only known at provision time); spot_price is the
        # typical winning interruptible bid.
        region = region or resources.region
        assert resources.instance_type is not None, resources
        return catalog.get_instance_hourly_cost(
            resources.instance_type, resources.use_spot, region=region,
            cloud=self.NAME)

    def egress_cost_per_gb(self, dst_cloud: str, dst_region: str,
                           src_region: Optional[str]) -> float:
        return 0.0  # hosts set their own (usually zero) transfer rates

    # ---- feasibility ------------------------------------------------------
    def get_feasible_resources(self,
                               resources) -> cloud_lib.FeasibleResources:
        if resources.tpu is not None:
            return cloud_lib.FeasibleResources(
                [], hint='Vast.ai has no TPU accelerators; use '
                         'cloud: gcp.')
        if resources.ports:
            return cloud_lib.FeasibleResources(
                [], hint='Vast.ai exposes only host-mapped ports; tasks '
                         'needing arbitrary open ports cannot run there.')
        if resources.instance_type is not None:
            if not catalog.get_vm_regions(resources.instance_type,
                                          cloud=self.NAME):
                return cloud_lib.FeasibleResources(
                    [], hint=(f'{resources.instance_type} is not a Vast '
                              'plan in the catalog (format: '
                              '{n}x_{GPU_NAME}).'))
            return cloud_lib.FeasibleResources(
                [resources.copy(cloud=self.NAME)])
        itype = catalog.get_default_instance_type(
            cpus=resources._cpus, cpus_plus=resources._cpus_plus,  # pylint: disable=protected-access
            memory=resources._memory, memory_plus=resources._memory_plus,  # pylint: disable=protected-access
            region=resources.region, cloud=self.NAME)
        if itype is None:
            return cloud_lib.FeasibleResources(
                [], hint=(f'No Vast plan with cpus={resources.cpus}, '
                          f'memory={resources.memory}'))
        return cloud_lib.FeasibleResources(
            [resources.copy(cloud=self.NAME, instance_type=itype)])

    # ---- deployment -------------------------------------------------------
    def make_deploy_variables(self, resources, cluster_name_on_cloud: str,
                              region: str,
                              zone: Optional[str]) -> Dict[str, Any]:
        from skypilot_tpu.provision import docker_utils
        image_id = resources.image_id
        if docker_utils.is_docker_image(image_id):
            # Vast instances ARE containers: the task image becomes the
            # instance image directly (like kubernetes, not docker-in-VM).
            image_id = docker_utils.image_name(image_id)
        return {
            'cloud': self.NAME,
            'mode': 'vast_marketplace',
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region,
            'zone': None,
            'use_spot': resources.use_spot,
            'disk_size_gb': resources.disk_size,
            'labels': dict(resources.labels or {}),
            'ports': [],
            'instance_type': resources.instance_type,
            'image_id': image_id,
        }
