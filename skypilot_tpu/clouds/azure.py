"""Azure: VMs (controllers, CPU tasks, blob storage egress).

Counterpart of reference ``sky/clouds/azure.py`` (feasibility, pricing,
deploy vars, credential checks). This TPU-native stack has no Azure
accelerators — Azure is the third VM cloud: it hardens the multi-cloud
abstraction (optimizer cross-cloud choice, GCP<->AWS<->Azure failover)
and adds blob-side storage placement.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib


@cloud_lib.CLOUD_REGISTRY.register(name='azure')
class Azure(cloud_lib.Cloud):
    NAME = 'azure'
    _FEATURES = frozenset({
        cloud_lib.CloudFeature.STOP,
        cloud_lib.CloudFeature.AUTOSTOP,
        cloud_lib.CloudFeature.SPOT,
        cloud_lib.CloudFeature.MULTI_HOST,
        cloud_lib.CloudFeature.STORAGE_MOUNTS,
        cloud_lib.CloudFeature.OPEN_PORTS,
        cloud_lib.CloudFeature.CUSTOM_IMAGES,
    })

    # ---- credentials ------------------------------------------------------
    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        if os.environ.get('SKYTPU_FAKE_AZURE_CREDENTIALS'):
            return True, None
        # Gate on the SDK too: provisioning needs azure-mgmt-compute, so
        # reporting Azure usable without it would let the optimizer place
        # clusters that every provision call then fails (the AWS AMI
        # lesson: check-time honesty beats launch-time surprises).
        try:
            import azure.mgmt.compute  # type: ignore # noqa: F401
        except ImportError:
            return False, ('azure-mgmt-compute SDK not installed '
                           '(pip install azure-mgmt-compute '
                           'azure-mgmt-network azure-identity).')
        if os.environ.get('AZURE_SUBSCRIPTION_ID'):
            return True, None
        if os.path.exists(os.path.expanduser('~/.azure/azureProfile.json')):
            return True, None
        return False, ('Azure credentials not found. Run `az login` or '
                       'set AZURE_SUBSCRIPTION_ID (+ service principal '
                       'env).')

    @classmethod
    def get_active_user_identity(cls) -> Optional[List[str]]:
        if os.environ.get('SKYTPU_FAKE_AZURE_CREDENTIALS'):
            return ['fake-identity@azure.test']
        sub = os.environ.get('AZURE_SUBSCRIPTION_ID')
        return [sub] if sub else None

    # ---- topology ---------------------------------------------------------
    def regions_for(self, resources) -> List[str]:
        if resources.tpu is not None:
            return []  # no TPUs on Azure
        itype = resources.instance_type or 'Standard_D2s_v5'
        regions = catalog.get_vm_regions(itype, cloud=self.NAME)
        if resources.region is not None:
            regions = [r for r in regions if r == resources.region]
        return regions

    def zones_for(self, resources, region: str) -> List[Optional[str]]:
        # Azure availability zones are optional placement ('1'/'2'/'3');
        # the default deployment is regional (zone=None) and a zonal
        # allocation failure fails over to explicit zones, mirroring the
        # reference's regional-first Azure behavior.
        if resources.zone is not None:
            return [resources.zone]
        return [None, '1', '2', '3']

    # ---- pricing ----------------------------------------------------------
    def hourly_cost(self, resources, region=None, zone=None) -> float:
        region = region or resources.region
        assert resources.instance_type is not None, resources
        return catalog.get_instance_hourly_cost(
            resources.instance_type, resources.use_spot, region=region,
            cloud=self.NAME)

    def egress_cost_per_gb(self, dst_cloud: str, dst_region: str,
                           src_region: Optional[str]) -> float:
        if src_region is None or dst_cloud != self.NAME:
            return 0.087  # internet egress (public Azure pricing, first tier)
        if src_region == dst_region:
            return 0.0
        return 0.02  # inter-region within Azure

    # ---- feasibility ------------------------------------------------------
    def get_feasible_resources(self,
                               resources) -> cloud_lib.FeasibleResources:
        if resources.tpu is not None:
            return cloud_lib.FeasibleResources(
                [], hint='Azure has no TPU accelerators; use cloud: gcp.')
        if resources.instance_type is not None:
            if not catalog.get_vm_regions(resources.instance_type,
                                          cloud=self.NAME):
                return cloud_lib.FeasibleResources(
                    [], hint=(f'{resources.instance_type} is not an Azure '
                              'VM size in the catalog.'))
            return cloud_lib.FeasibleResources(
                [resources.copy(cloud=self.NAME)])
        itype = catalog.get_default_instance_type(
            cpus=resources._cpus, cpus_plus=resources._cpus_plus,  # pylint: disable=protected-access
            memory=resources._memory, memory_plus=resources._memory_plus,  # pylint: disable=protected-access
            region=resources.region, cloud=self.NAME)
        if itype is None:
            return cloud_lib.FeasibleResources(
                [], hint=(f'No Azure VM size with cpus={resources.cpus}, '
                          f'memory={resources.memory}'))
        return cloud_lib.FeasibleResources(
            [resources.copy(cloud=self.NAME, instance_type=itype)])

    # ---- deployment -------------------------------------------------------
    def make_deploy_variables(self, resources, cluster_name_on_cloud: str,
                              region: str,
                              zone: Optional[str]) -> Dict[str, Any]:
        from skypilot_tpu.provision import docker_utils
        image_id = resources.image_id
        if docker_utils.is_docker_image(image_id):
            image_id = None  # stock image; ranks run in the container
        return {
            'cloud': self.NAME,
            'mode': 'azure_vm',
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region,
            'zone': zone,
            'use_spot': resources.use_spot,
            'disk_size_gb': resources.disk_size,
            'labels': dict(resources.labels or {}),
            'ports': list(resources.ports or ()),
            'instance_type': resources.instance_type,
            'image_id': image_id,
        }
