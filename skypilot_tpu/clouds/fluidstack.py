"""FluidStack: GPU instances (terminate-only, no ports API).

Counterpart of reference ``sky/clouds/fluidstack.py`` (feasibility,
pricing, deploy vars; unsupported-feature table at :40-53). Sixth VM
cloud; like Lambda it is terminate-only with no spot, and additionally
has NO firewall API — the first cloud omitting OPEN_PORTS, so
serve/port-requiring tasks are refused up front by the feature gate.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib


@cloud_lib.CLOUD_REGISTRY.register(name='fluidstack')
class Fluidstack(cloud_lib.Cloud):
    NAME = 'fluidstack'
    _FEATURES = frozenset({
        cloud_lib.CloudFeature.AUTOSTOP,  # autodown only (no STOP)
        cloud_lib.CloudFeature.MULTI_HOST,
        cloud_lib.CloudFeature.STORAGE_MOUNTS,
    })

    # ---- credentials ------------------------------------------------------
    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        if os.environ.get('SKYTPU_FAKE_FLUIDSTACK_CREDENTIALS'):
            return True, None
        from skypilot_tpu.provision import fluidstack_api
        if fluidstack_api.read_api_key() is not None:
            return True, None
        return False, ('FluidStack credentials not found. Set '
                       '$FLUIDSTACK_API_KEY or write the key to '
                       '~/.fluidstack/api_key.')

    @classmethod
    def get_active_user_identity(cls) -> Optional[List[str]]:
        if os.environ.get('SKYTPU_FAKE_FLUIDSTACK_CREDENTIALS'):
            return ['fake-identity@fluidstack.test']
        from skypilot_tpu.provision import fluidstack_api
        key = fluidstack_api.read_api_key()
        return [f'fluidstack-key-{key[:8]}'] if key else None

    # ---- topology ---------------------------------------------------------
    def regions_for(self, resources) -> List[str]:
        if resources.tpu is not None:
            return []  # no TPUs on FluidStack
        if resources.use_spot:
            return []  # no spot market
        itype = resources.instance_type or 'A100_80G::1'
        regions = catalog.get_vm_regions(itype, cloud=self.NAME)
        if resources.region is not None:
            regions = [r for r in regions if r == resources.region]
        return regions

    def zones_for(self, resources, region: str) -> List[Optional[str]]:
        if resources.zone is not None:
            return []  # no zones
        return [None]

    # ---- pricing ----------------------------------------------------------
    def hourly_cost(self, resources, region=None, zone=None) -> float:
        region = region or resources.region
        assert resources.instance_type is not None, resources
        return catalog.get_instance_hourly_cost(
            resources.instance_type, resources.use_spot, region=region,
            cloud=self.NAME)

    def egress_cost_per_gb(self, dst_cloud: str, dst_region: str,
                           src_region: Optional[str]) -> float:
        return 0.0  # FluidStack does not bill egress

    # ---- feasibility ------------------------------------------------------
    def get_feasible_resources(self,
                               resources) -> cloud_lib.FeasibleResources:
        if resources.tpu is not None:
            return cloud_lib.FeasibleResources(
                [], hint='FluidStack has no TPU accelerators; use '
                         'cloud: gcp.')
        if resources.use_spot:
            return cloud_lib.FeasibleResources(
                [], hint='FluidStack has no spot market.')
        if resources.ports:
            return cloud_lib.FeasibleResources(
                [], hint='FluidStack has no firewall API; tasks needing '
                         'open ports cannot run there.')
        if resources.instance_type is not None:
            if not catalog.get_vm_regions(resources.instance_type,
                                          cloud=self.NAME):
                return cloud_lib.FeasibleResources(
                    [], hint=(f'{resources.instance_type} is not a '
                              'FluidStack plan in the catalog '
                              '(format: GPU_TYPE::count).'))
            return cloud_lib.FeasibleResources(
                [resources.copy(cloud=self.NAME)])
        itype = catalog.get_default_instance_type(
            cpus=resources._cpus, cpus_plus=resources._cpus_plus,  # pylint: disable=protected-access
            memory=resources._memory, memory_plus=resources._memory_plus,  # pylint: disable=protected-access
            region=resources.region, cloud=self.NAME)
        if itype is None:
            return cloud_lib.FeasibleResources(
                [], hint=(f'No FluidStack plan with cpus={resources.cpus},'
                          f' memory={resources.memory}'))
        return cloud_lib.FeasibleResources(
            [resources.copy(cloud=self.NAME, instance_type=itype)])

    # ---- deployment -------------------------------------------------------
    def make_deploy_variables(self, resources, cluster_name_on_cloud: str,
                              region: str,
                              zone: Optional[str]) -> Dict[str, Any]:
        return {
            'cloud': self.NAME,
            'mode': 'fluidstack_vm',
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region,
            'zone': None,
            'use_spot': False,
            'disk_size_gb': resources.disk_size,
            'labels': dict(resources.labels or {}),
            'ports': [],
            'instance_type': resources.instance_type,
            'image_id': None,  # stock ubuntu_22_04_lts_nvidia
        }
