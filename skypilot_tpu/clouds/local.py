"""Local "cloud": subprocess-backed hosts on this machine.

The permanent unit-test backend (SURVEY.md §7 phase 1): every orchestration
path — provision, setup, exec, job queue, logs, autostop, recovery — runs for
real against local processes, no cloud credentials needed. The reference has
no equivalent (its tests mock boto3 objects or need real clouds;
tests/common_test_fixtures.py:356); this is a deliberate testability upgrade.

A "cluster" is a directory under the state dir; "hosts" are entries that the
local provisioner materializes; jobs run as real subprocesses through the
same agent/job-queue code path used on TPU hosts. Multi-host slices are
emulated with N worker entries on one machine (rank env vars still exported),
which is exactly what `jax.distributed` + virtual CPU devices need for tests.
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu.clouds import cloud as cloud_lib

LOCAL_REGION = 'local'
LOCAL_ZONE = 'local-a'
# Two zones so zone-level behaviors (capacity failover, spot-placer
# preemption avoidance) are testable hermetically.
LOCAL_ZONES = ['local-a', 'local-b']


@cloud_lib.CLOUD_REGISTRY.register(name='local')
class Local(cloud_lib.Cloud):
    NAME = 'local'
    _FEATURES = frozenset({
        cloud_lib.CloudFeature.AUTOSTOP,
        cloud_lib.CloudFeature.STOP,
        cloud_lib.CloudFeature.MULTI_HOST,
        cloud_lib.CloudFeature.OPEN_PORTS,
        # SPOT accepted so spot-serving paths run hermetically; actual
        # preemption is still injected by tests (nothing preempts here).
        cloud_lib.CloudFeature.SPOT,
        # docker: image tasks run hermetically with a stub docker binary.
        cloud_lib.CloudFeature.CUSTOM_IMAGES,
    })

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        return True, None

    @classmethod
    def get_active_user_identity(cls) -> Optional[List[str]]:
        return ['local']

    def regions_for(self, resources) -> List[str]:
        if resources.region not in (None, LOCAL_REGION):
            return []
        return [LOCAL_REGION]

    def zones_for(self, resources, region: str) -> List[Optional[str]]:
        if resources.zone is not None:
            return ([resources.zone] if resources.zone in LOCAL_ZONES
                    else [])
        return list(LOCAL_ZONES)

    def hourly_cost(self, resources, region=None, zone=None) -> float:
        return 0.0

    def get_feasible_resources(self, resources) -> cloud_lib.FeasibleResources:
        # Accept anything; a TPU resource is emulated with N host slots.
        return cloud_lib.FeasibleResources(
            [resources.copy(cloud=self.NAME)])

    def make_deploy_variables(self, resources, cluster_name_on_cloud: str,
                              region: str,
                              zone: Optional[str]) -> Dict[str, Any]:
        return {
            'cloud': self.NAME,
            'mode': 'local',
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region,
            'zone': zone,
            'num_hosts': resources.num_hosts,
            'tpu_slice': resources.tpu.name if resources.tpu else None,
            # clone-disk images (local-image://...) materialize into the
            # emulated host dirs on first provision.
            'image_id': resources.image_id,
        }
