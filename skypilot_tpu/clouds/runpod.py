"""RunPod: container pods (spot bids, terminate-only, fixed port sets).

Counterpart of reference ``sky/clouds/runpod.py`` (STOP unsupported at
:27; spot pods via bidPerGpu). Eighth VM cloud: spot WITHOUT stop — a
feature combination none of the previous clouds exercise — and ports
fixed at rent time (declared from resources.ports at launch; open_ports
verifies instead of mutating).
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib


@cloud_lib.CLOUD_REGISTRY.register(name='runpod')
class RunPod(cloud_lib.Cloud):
    NAME = 'runpod'
    _FEATURES = frozenset({
        cloud_lib.CloudFeature.AUTOSTOP,   # autodown only (no STOP)
        cloud_lib.CloudFeature.SPOT,       # interruptible pods w/ bids
        cloud_lib.CloudFeature.MULTI_HOST,
        cloud_lib.CloudFeature.STORAGE_MOUNTS,
        cloud_lib.CloudFeature.OPEN_PORTS,  # declared at rent time
        cloud_lib.CloudFeature.CUSTOM_IMAGES,  # any docker image
    })

    # ---- credentials ------------------------------------------------------
    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        if os.environ.get('SKYTPU_FAKE_RUNPOD_CREDENTIALS'):
            return True, None
        from skypilot_tpu.provision import runpod_api
        if runpod_api.read_api_key() is not None:
            return True, None
        return False, ('RunPod credentials not found. Set '
                       '$RUNPOD_API_KEY or run `runpod config`.')

    @classmethod
    def get_active_user_identity(cls) -> Optional[List[str]]:
        if os.environ.get('SKYTPU_FAKE_RUNPOD_CREDENTIALS'):
            return ['fake-identity@runpod.test']
        from skypilot_tpu.provision import runpod_api
        key = runpod_api.read_api_key()
        return [f'runpod-key-{key[:8]}'] if key else None

    # ---- topology ---------------------------------------------------------
    def regions_for(self, resources) -> List[str]:
        if resources.tpu is not None:
            return []  # no TPUs on RunPod
        itype = resources.instance_type or '1x_NVIDIA_RTX_4090_SECURE'
        regions = catalog.get_vm_regions(itype, cloud=self.NAME)
        if resources.region is not None:
            regions = [r for r in regions if r == resources.region]
        return regions

    def zones_for(self, resources, region: str) -> List[Optional[str]]:
        if resources.zone is not None:
            return []  # no zones
        return [None]

    # ---- pricing ----------------------------------------------------------
    def hourly_cost(self, resources, region=None, zone=None) -> float:
        region = region or resources.region
        assert resources.instance_type is not None, resources
        return catalog.get_instance_hourly_cost(
            resources.instance_type, resources.use_spot, region=region,
            cloud=self.NAME)

    def egress_cost_per_gb(self, dst_cloud: str, dst_region: str,
                           src_region: Optional[str]) -> float:
        return 0.0  # RunPod does not bill egress

    # ---- feasibility ------------------------------------------------------
    def get_feasible_resources(self,
                               resources) -> cloud_lib.FeasibleResources:
        if resources.tpu is not None:
            return cloud_lib.FeasibleResources(
                [], hint='RunPod has no TPU accelerators; use cloud: gcp.')
        if resources.instance_type is not None:
            if not catalog.get_vm_regions(resources.instance_type,
                                          cloud=self.NAME):
                return cloud_lib.FeasibleResources(
                    [], hint=(f'{resources.instance_type} is not a RunPod '
                              'plan in the catalog (format: '
                              '{n}x_{GPU_ID}_{SECURE|COMMUNITY}).'))
            return cloud_lib.FeasibleResources(
                [resources.copy(cloud=self.NAME)])
        itype = catalog.get_default_instance_type(
            cpus=resources._cpus, cpus_plus=resources._cpus_plus,  # pylint: disable=protected-access
            memory=resources._memory, memory_plus=resources._memory_plus,  # pylint: disable=protected-access
            region=resources.region, cloud=self.NAME)
        if itype is None:
            return cloud_lib.FeasibleResources(
                [], hint=(f'No RunPod plan with cpus={resources.cpus}, '
                          f'memory={resources.memory}'))
        return cloud_lib.FeasibleResources(
            [resources.copy(cloud=self.NAME, instance_type=itype)])

    # ---- deployment -------------------------------------------------------
    def make_deploy_variables(self, resources, cluster_name_on_cloud: str,
                              region: str,
                              zone: Optional[str]) -> Dict[str, Any]:
        from skypilot_tpu.provision import docker_utils
        image_id = resources.image_id
        if docker_utils.is_docker_image(image_id):
            # Pods ARE containers: the task image is the pod image.
            image_id = docker_utils.image_name(image_id)
        return {
            'cloud': self.NAME,
            'mode': 'runpod_pod',
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region,
            'zone': None,
            'use_spot': resources.use_spot,
            'disk_size_gb': resources.disk_size,
            'labels': dict(resources.labels or {}),
            # Ports ride the pod spec (fixed at rent time).
            'ports': list(resources.ports or ()),
            'instance_type': resources.instance_type,
            'image_id': image_id,
        }
