"""Cloud ABC: feasibility, pricing, deploy variables, credentials."""
from __future__ import annotations

import dataclasses
import enum
from typing import Any, Dict, List, Optional, TYPE_CHECKING, Tuple

from skypilot_tpu.utils import registry

if TYPE_CHECKING:
    from skypilot_tpu import resources as resources_lib

CLOUD_REGISTRY: registry.Registry = registry.Registry('cloud')


class CloudFeature(enum.Enum):
    """Capabilities a task/operation may require of a cloud.

    Same role as reference CloudImplementationFeatures (sky/clouds/cloud.py:31).
    """
    STOP = 'stop'
    AUTOSTOP = 'autostop'
    SPOT = 'spot'
    MULTI_HOST = 'multi_host'
    STORAGE_MOUNTS = 'storage_mounts'
    OPEN_PORTS = 'open_ports'
    CUSTOM_IMAGES = 'custom_images'


@dataclasses.dataclass
class FeasibleResources:
    """Result of a feasibility query: concrete candidates + rejection notes."""
    resources: List['resources_lib.Resources']
    fuzzy_candidates: List[str] = dataclasses.field(default_factory=list)
    hint: Optional[str] = None


class Cloud:
    """Abstract cloud provider."""

    NAME = 'abstract'
    _FEATURES: frozenset = frozenset()

    # ---- identity / credentials ------------------------------------------
    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        """(ok, reason-if-not)."""
        raise NotImplementedError

    @classmethod
    def get_active_user_identity(cls) -> Optional[List[str]]:
        return None

    # ---- capabilities -----------------------------------------------------
    @classmethod
    def supports(cls, feature: CloudFeature) -> bool:
        return feature in cls._FEATURES

    @classmethod
    def check_features_are_supported(
            cls, features: set) -> None:
        unsupported = {f for f in features if not cls.supports(f)}
        if unsupported:
            from skypilot_tpu import exceptions
            raise exceptions.NotSupportedError(
                f'{cls.NAME} does not support: '
                f'{sorted(f.value for f in unsupported)}')

    # ---- topology ---------------------------------------------------------
    def regions_for(
            self, resources: 'resources_lib.Resources') -> List[str]:
        raise NotImplementedError

    def zones_for(self, resources: 'resources_lib.Resources',
                  region: str) -> List[Optional[str]]:
        """Zones to iterate for failover within a region (None = regional)."""
        raise NotImplementedError

    # ---- pricing ----------------------------------------------------------
    def hourly_cost(self, resources: 'resources_lib.Resources',
                    region: Optional[str] = None,
                    zone: Optional[str] = None) -> float:
        raise NotImplementedError

    def egress_cost_per_gb(self, dst_cloud: str, dst_region: str,
                           src_region: Optional[str]) -> float:
        return 0.0

    # ---- feasibility ------------------------------------------------------
    def get_feasible_resources(
            self, resources: 'resources_lib.Resources'
    ) -> FeasibleResources:
        """Turn a (possibly partial) filter into launchable candidates."""
        raise NotImplementedError

    # ---- deployment -------------------------------------------------------
    def make_deploy_variables(
            self, resources: 'resources_lib.Resources',
            cluster_name_on_cloud: str, region: str,
            zone: Optional[str]) -> Dict[str, Any]:
        """Variables consumed by the provisioner for this cloud."""
        raise NotImplementedError

    def __repr__(self) -> str:
        return self.NAME

    def __eq__(self, other: Any) -> bool:
        return isinstance(other, Cloud) and self.NAME == other.NAME

    def __hash__(self) -> int:
        return hash(self.NAME)
