"""OCI: Core compute instances (4th enterprise cloud; preemptible spot).

Counterpart of reference ``sky/clouds/oci.py``. Availability domains
play the zone role (``{region}-AD-{n}``); ``use_spot`` maps to
preemptible instances (TERMINATE on reclaim). Requires an existing
subnet (``oci.subnet_ocid`` config) — see docs/clouds.md.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib


@cloud_lib.CLOUD_REGISTRY.register(name='oci')
class OCI(cloud_lib.Cloud):
    NAME = 'oci'
    _FEATURES = frozenset({
        cloud_lib.CloudFeature.STOP,      # standard shapes don't bill
        cloud_lib.CloudFeature.AUTOSTOP,  # compute while stopped
        cloud_lib.CloudFeature.SPOT,      # preemptible instances
        cloud_lib.CloudFeature.MULTI_HOST,
        cloud_lib.CloudFeature.STORAGE_MOUNTS,
        cloud_lib.CloudFeature.OPEN_PORTS,   # per-cluster NSG
        cloud_lib.CloudFeature.CUSTOM_IMAGES,
    })

    # ---- credentials ------------------------------------------------------
    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        if os.environ.get('SKYTPU_FAKE_OCI_CREDENTIALS'):
            return True, None
        from skypilot_tpu.provision import oci_api
        if oci_api.read_config() is not None:
            return True, None
        return False, ('OCI credentials not found. Run '
                       '`oci setup config` (needs user, fingerprint, '
                       'key_file, tenancy, region in ~/.oci/config).')

    @classmethod
    def get_active_user_identity(cls) -> Optional[List[str]]:
        if os.environ.get('SKYTPU_FAKE_OCI_CREDENTIALS'):
            return ['fake-identity@oci.test']
        from skypilot_tpu.provision import oci_api
        cfg = oci_api.read_config()
        return [cfg['user']] if cfg else None

    # ---- topology ---------------------------------------------------------
    def regions_for(self, resources) -> List[str]:
        if resources.tpu is not None:
            return []  # no TPUs on OCI
        itype = resources.instance_type or 'VM.Standard.E4.Flex'
        regions = catalog.get_vm_regions(itype, cloud=self.NAME)
        if resources.region is not None:
            regions = [r for r in regions if r == resources.region]
        return regions

    def zones_for(self, resources, region: str) -> List[Optional[str]]:
        # Availability domains: AD-1..AD-3 (single-AD regions fail over
        # to the next region when AD-2/3 don't exist — the capacity
        # classification handles the NotFound).
        if resources.zone is not None:
            return ([resources.zone]
                    if resources.zone.startswith(region) else [])
        return [f'{region}-AD-{i}' for i in (1, 2, 3)]

    # ---- pricing ----------------------------------------------------------
    def hourly_cost(self, resources, region=None, zone=None) -> float:
        region = region or resources.region
        assert resources.instance_type is not None, resources
        return catalog.get_instance_hourly_cost(
            resources.instance_type, resources.use_spot, region=region,
            cloud=self.NAME)

    def egress_cost_per_gb(self, dst_cloud: str, dst_region: str,
                           src_region: Optional[str]) -> float:
        # First 10 TB/month free; the overage rate as the conservative
        # planning number.
        if src_region is not None and dst_cloud == self.NAME \
                and src_region == dst_region:
            return 0.0
        return 0.0085

    # ---- feasibility ------------------------------------------------------
    def get_feasible_resources(self,
                               resources) -> cloud_lib.FeasibleResources:
        if resources.tpu is not None:
            return cloud_lib.FeasibleResources(
                [], hint='OCI has no TPU accelerators; use cloud: gcp.')
        if resources.instance_type is not None:
            if not catalog.get_vm_regions(resources.instance_type,
                                          cloud=self.NAME):
                return cloud_lib.FeasibleResources(
                    [], hint=(f'{resources.instance_type} is not an OCI '
                              'shape in the catalog.'))
            return cloud_lib.FeasibleResources(
                [resources.copy(cloud=self.NAME)])
        itype = catalog.get_default_instance_type(
            cpus=resources._cpus, cpus_plus=resources._cpus_plus,  # pylint: disable=protected-access
            memory=resources._memory, memory_plus=resources._memory_plus,  # pylint: disable=protected-access
            region=resources.region, cloud=self.NAME)
        if itype is None:
            return cloud_lib.FeasibleResources(
                [], hint=(f'No OCI shape with cpus={resources.cpus}, '
                          f'memory={resources.memory}'))
        return cloud_lib.FeasibleResources(
            [resources.copy(cloud=self.NAME, instance_type=itype)])

    # ---- deployment -------------------------------------------------------
    def make_deploy_variables(self, resources, cluster_name_on_cloud: str,
                              region: str,
                              zone: Optional[str]) -> Dict[str, Any]:
        from skypilot_tpu.provision import docker_utils
        image_id = resources.image_id
        if docker_utils.is_docker_image(image_id):
            image_id = None  # stock image; ranks run in the container
        shape = resources.instance_type
        shape_config = None
        if shape and '.Flex' in shape:
            # Catalog sizing variants ('VM.Standard.E4.Flex.8') are
            # pricing points of the REAL Flex shape: strip the numeric
            # suffix for the launch and derive shapeConfig from the
            # variant's catalog row so the launch matches what was
            # priced. Arm A1 shapes are 1 OCPU = 1 vCPU; x86 SMT shapes
            # are 1 OCPU = 2 vCPUs.
            import re
            vcpus, mem = catalog.get_instance_info(shape, cloud=self.NAME)
            per_ocpu = 1 if '.A1.' in shape else 2
            shape = re.sub(r'(\.Flex)\.\d+$', r'\1', shape)
            shape_config = {'ocpus': max(1, vcpus // per_ocpu),
                            'memoryInGBs': mem}
        return {
            'cloud': self.NAME,
            'mode': 'oci_instance',
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region,
            'zone': zone,
            'use_spot': resources.use_spot,
            'disk_size_gb': resources.disk_size,
            'labels': dict(resources.labels or {}),
            'ports': list(resources.ports or ()),
            'instance_type': shape,
            'shape_config': shape_config,
            'image_id': image_id,
        }
