"""Lambda Cloud: GPU VMs (terminate-only lifecycle, no zones, no spot).

Counterpart of reference ``sky/clouds/lambda_cloud.py`` (feasibility,
pricing, deploy vars, credential checks; unsupported-feature table at
:39-47). In this TPU-native stack Lambda is the fourth VM cloud and the
first with a REDUCED capability surface — no STOP/AUTOSTOP-to-stop, no
SPOT, no custom images — which exercises the feature-gating path
(``check_features_are_supported``) that the full-featured clouds never
hit.
"""
from __future__ import annotations

import os
from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import catalog
from skypilot_tpu.clouds import cloud as cloud_lib


@cloud_lib.CLOUD_REGISTRY.register(name='lambda')
class Lambda(cloud_lib.Cloud):
    NAME = 'lambda'
    # Terminate-only: autostop is supported as autodown (the agent's
    # autostop hook always terminates where STOP is absent).
    _FEATURES = frozenset({
        cloud_lib.CloudFeature.AUTOSTOP,
        cloud_lib.CloudFeature.MULTI_HOST,
        cloud_lib.CloudFeature.STORAGE_MOUNTS,
        cloud_lib.CloudFeature.OPEN_PORTS,
    })

    # Lambda caps instance names at 64 chars; '-r{rank}' needs headroom
    # (reference _MAX_CLUSTER_NAME_LEN_LIMIT = 57).
    MAX_CLUSTER_NAME_LENGTH = 57

    # ---- credentials ------------------------------------------------------
    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        if os.environ.get('SKYTPU_FAKE_LAMBDA_CREDENTIALS'):
            return True, None
        from skypilot_tpu.provision import lambda_api
        if lambda_api.read_api_key() is not None:
            return True, None
        return False, ('Lambda Cloud credentials not found. Set '
                       '$LAMBDA_API_KEY or write `api_key = <key>` to '
                       '~/.lambda_cloud/lambda_keys.')

    @classmethod
    def get_active_user_identity(cls) -> Optional[List[str]]:
        if os.environ.get('SKYTPU_FAKE_LAMBDA_CREDENTIALS'):
            return ['fake-identity@lambda.test']
        from skypilot_tpu.provision import lambda_api
        key = lambda_api.read_api_key()
        # The API has no whoami endpoint; the key prefix is the stable
        # per-account identity component.
        return [f'lambda-key-{key[:8]}'] if key else None

    # ---- topology ---------------------------------------------------------
    def regions_for(self, resources) -> List[str]:
        if resources.tpu is not None:
            return []  # no TPUs on Lambda
        if resources.use_spot:
            return []  # no spot market
        itype = resources.instance_type or 'gpu_1x_a10'
        regions = catalog.get_vm_regions(itype, cloud=self.NAME)
        if resources.region is not None:
            regions = [r for r in regions if r == resources.region]
        return regions

    def zones_for(self, resources, region: str) -> List[Optional[str]]:
        if resources.zone is not None:
            return []  # Lambda has no zones; a pinned zone can't match
        return [None]

    # ---- pricing ----------------------------------------------------------
    def hourly_cost(self, resources, region=None, zone=None) -> float:
        region = region or resources.region
        assert resources.instance_type is not None, resources
        return catalog.get_instance_hourly_cost(
            resources.instance_type, resources.use_spot, region=region,
            cloud=self.NAME)

    def egress_cost_per_gb(self, dst_cloud: str, dst_region: str,
                           src_region: Optional[str]) -> float:
        return 0.0  # Lambda does not bill egress

    # ---- feasibility ------------------------------------------------------
    def get_feasible_resources(self,
                               resources) -> cloud_lib.FeasibleResources:
        if resources.tpu is not None:
            return cloud_lib.FeasibleResources(
                [], hint='Lambda Cloud has no TPU accelerators; use '
                         'cloud: gcp.')
        if resources.use_spot:
            return cloud_lib.FeasibleResources(
                [], hint='Lambda Cloud has no spot market.')
        if resources.instance_type is not None:
            if not catalog.get_vm_regions(resources.instance_type,
                                          cloud=self.NAME):
                return cloud_lib.FeasibleResources(
                    [], hint=(f'{resources.instance_type} is not a Lambda '
                              'instance type in the catalog.'))
            return cloud_lib.FeasibleResources(
                [resources.copy(cloud=self.NAME)])
        itype = catalog.get_default_instance_type(
            cpus=resources._cpus, cpus_plus=resources._cpus_plus,  # pylint: disable=protected-access
            memory=resources._memory, memory_plus=resources._memory_plus,  # pylint: disable=protected-access
            region=resources.region, cloud=self.NAME)
        if itype is None:
            return cloud_lib.FeasibleResources(
                [], hint=(f'No Lambda instance with cpus={resources.cpus}, '
                          f'memory={resources.memory}'))
        return cloud_lib.FeasibleResources(
            [resources.copy(cloud=self.NAME, instance_type=itype)])

    # ---- deployment -------------------------------------------------------
    def make_deploy_variables(self, resources, cluster_name_on_cloud: str,
                              region: str,
                              zone: Optional[str]) -> Dict[str, Any]:
        return {
            'cloud': self.NAME,
            'mode': 'lambda_vm',
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'region': region,
            'zone': None,
            'use_spot': False,
            'disk_size_gb': resources.disk_size,
            'labels': dict(resources.labels or {}),
            'ports': list(resources.ports or ()),
            'instance_type': resources.instance_type,
            'image_id': None,  # Lambda launches its stock Ubuntu image
        }
