"""Kubernetes cloud: pod-hosted tasks + GKE TPU podslices.

Counterpart of reference ``sky/clouds/kubernetes.py`` + the GKE-TPU
detection in ``sky/provision/kubernetes/utils.py`` (is_tpu_on_gke). One
"region" per kube-context (in-cluster counts as its own); no zones, no
stop (pods don't stop), cost 0 (cluster hardware is already paid for —
the reference also treats k8s as zero marginal cost, so the optimizer
prefers it when feasible).
"""
from __future__ import annotations

from typing import Any, Dict, List, Optional, Tuple

from skypilot_tpu import config as config_lib
from skypilot_tpu.clouds import cloud as cloud_lib

KUBE_REGION = 'in-cluster'


@cloud_lib.CLOUD_REGISTRY.register(name='kubernetes')
class Kubernetes(cloud_lib.Cloud):
    NAME = 'kubernetes'
    _FEATURES = frozenset({
        cloud_lib.CloudFeature.MULTI_HOST,
        cloud_lib.CloudFeature.OPEN_PORTS,
        cloud_lib.CloudFeature.AUTOSTOP,   # autostop hook tears pods down
        cloud_lib.CloudFeature.STORAGE_MOUNTS,
        cloud_lib.CloudFeature.CUSTOM_IMAGES,  # pod image (docker: too)
        # no STOP (pods), no SPOT (preemption comes from the node pool)
    })

    @classmethod
    def check_credentials(cls) -> Tuple[bool, Optional[str]]:
        from skypilot_tpu.provision import k8s_api
        try:
            k8s_api.PodClient().version()
            return True, None
        except Exception as e:  # noqa: BLE001 — any failure = not enabled
            return False, f'no reachable Kubernetes cluster: {e}'

    @classmethod
    def get_active_user_identity(cls) -> Optional[List[str]]:
        return ['kubernetes']

    def regions_for(self, resources) -> List[str]:
        if resources.region not in (None, KUBE_REGION):
            return []
        return [KUBE_REGION]

    def zones_for(self, resources, region: str) -> List[Optional[str]]:
        return [None]

    def hourly_cost(self, resources, region=None, zone=None) -> float:
        return 0.0

    def get_feasible_resources(self, resources) -> cloud_lib.FeasibleResources:
        from skypilot_tpu.provision.kubernetes import GKE_TPU_ACCELERATOR
        tpu = resources.tpu
        if tpu is not None and tpu.generation not in GKE_TPU_ACCELERATOR:
            return cloud_lib.FeasibleResources(
                [], hint=f'TPU {tpu.generation} has no GKE podslice '
                         'node-pool type')
        if resources.use_spot:
            return cloud_lib.FeasibleResources(
                [], hint='kubernetes has no spot market (use a spot '
                         'node pool instead)')
        return cloud_lib.FeasibleResources(
            [resources.copy(cloud=self.NAME)])

    def make_deploy_variables(self, resources, cluster_name_on_cloud: str,
                              region: str,
                              zone: Optional[str]) -> Dict[str, Any]:
        # `image_id: docker:<img>` maps straight onto the pod image —
        # pods ARE containers, no docker-in-docker (VM clouds handle the
        # prefix via provision/docker_utils instead).
        from skypilot_tpu.provision import docker_utils
        image = resources.image_id
        if docker_utils.is_docker_image(image):
            image = docker_utils.image_name(image)
        out: Dict[str, Any] = {
            'cloud': self.NAME,
            'cluster_name_on_cloud': cluster_name_on_cloud,
            'namespace': config_lib.get_nested(
                ('kubernetes', 'namespace'), 'default'),
            'image': (image or config_lib.get_nested(
                ('kubernetes', 'image'), None)),
            'num_hosts': resources.num_hosts,
        }
        # Pod resource quantities must be plain numbers: strip the '4+'
        # at-least suffix (the request IS the at-least semantics in k8s).
        from skypilot_tpu.utils import common_utils
        cpus, _ = common_utils.parse_plus_number(resources.cpus, 'cpus')
        if cpus is not None:
            out['cpus'] = cpus
        mem, _ = common_utils.parse_memory_gb(resources.memory)
        if mem is not None:
            out['memory_gb'] = mem
        tpu = resources.tpu
        if tpu is not None:
            out.update({
                'tpu_generation': tpu.generation,
                'tpu_topology': tpu.topology_str,
                # Sub-host slices (e.g. v5e-4) expose only their chips.
                'chips_per_host': min(tpu.chips, tpu.chips_per_host),
            })
        return out
